"""The reference's external-LZ-module hook, honored for real.

The reference probes sys.path for ``lambda_local_LZ_from_profile``,
``extended_LZ_lambda``, ``transport_from_profile`` (in that order) before
giving up on a profile CSV (`first_principles_yields.py:170-187`).
VERDICT r3 missing #1: a user with one of those modules on path must get
identical behavior from this framework's CLI — these tests pin the hook's
probe order, both entry-point shapes, the clamp, the swallow-all
contract, and the documented divergence (explicit estimator flags request
the in-repo kernel and skip the hook).
"""
from __future__ import annotations

import sys
import textwrap

import pytest

from bdlz_tpu.cli import resolve_P, try_external_P_from_profile
from bdlz_tpu.config import config_from_dict

MODNAMES = (
    "lambda_local_LZ_from_profile",
    "extended_LZ_lambda",
    "transport_from_profile",
)


@pytest.fixture
def modpath(tmp_path, monkeypatch):
    """A temp dir on sys.path; drops any fake hook modules afterwards."""
    monkeypatch.syspath_prepend(str(tmp_path))
    yield tmp_path
    for name in MODNAMES:
        sys.modules.pop(name, None)


def _write_module(dirpath, name, body):
    (dirpath / f"{name}.py").write_text(textwrap.dedent(body))


def _cfg(**over):
    return config_from_dict({"P_chi_to_B": 0.149, **over})


class TestHookUnit:
    def test_prob_entry_point(self, modpath):
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                assert csv == "prof.csv"
                return 0.25 + v_w
        """)
        P, mod = try_external_P_from_profile("prof.csv", 0.3)
        assert P == pytest.approx(0.55)
        assert mod == "transport_from_profile"

    def test_prob_clamped_to_unit_interval(self, modpath):
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                return 7.5
        """)
        P, _ = try_external_P_from_profile("prof.csv", 0.3)
        assert P == 1.0

    def test_lambda_entry_point_maps_through_exponential(self, modpath):
        # P = 1 - e^(-2*pi*lambda), lambda floored at 0 (reference :183)
        import math

        _write_module(modpath, "extended_LZ_lambda", """
            def compute_lambda_eff_from_profile(csv):
                return 0.05
        """)
        P, mod = try_external_P_from_profile("prof.csv", 0.3)
        assert P == pytest.approx(1.0 - math.exp(-2.0 * math.pi * 0.05))
        assert mod == "extended_LZ_lambda"

        _write_module(modpath, "extended_LZ_lambda", """
            def compute_lambda_eff_from_profile(csv):
                return -3.0
        """)
        sys.modules.pop("extended_LZ_lambda")
        P, _ = try_external_P_from_profile("prof.csv", 0.3)
        assert P == 0.0  # floored lambda -> e^0

    def test_probe_order_first_module_wins(self, modpath):
        _write_module(modpath, "lambda_local_LZ_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                return 0.111
        """)
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                return 0.999
        """)
        P, mod = try_external_P_from_profile("prof.csv", 0.3)
        assert P == pytest.approx(0.111)
        assert mod == "lambda_local_LZ_from_profile"

    def test_module_without_entry_points_is_skipped(self, modpath):
        _write_module(modpath, "lambda_local_LZ_from_profile", """
            SOMETHING_ELSE = 1
        """)
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                return 0.42
        """)
        P, mod = try_external_P_from_profile("prof.csv", 0.3)
        assert P == pytest.approx(0.42)
        assert mod == "transport_from_profile"

    def test_raising_module_swallowed_to_none(self, modpath):
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                raise RuntimeError("boom")
        """)
        assert try_external_P_from_profile("prof.csv", 0.3) == (None, None)

    def test_absent_modules_give_none(self):
        assert try_external_P_from_profile("prof.csv", 0.3) == (None, None)


class TestResolvePIntegration:
    def test_hook_wins_on_reference_shaped_invocation(self, modpath, capsys):
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                return 0.321
        """)
        P = resolve_P(_cfg(), "prof.csv")
        captured = capsys.readouterr()
        assert P == pytest.approx(0.321)
        # stdout carries EXACTLY the reference's single maybe_P line
        # (byte parity, ADVICE r4); the module attribution goes to stderr
        assert captured.out == "[info] Using P_chi_to_B from profile: 0.321\n"
        assert "transport_from_profile" in captured.err

    def test_explicit_estimator_skips_hook(self, modpath, tmp_path, capsys):
        # Documented divergence: --lz-method selects the in-repo kernel.
        # The fake returns a sentinel rather than raising — a raise would
        # be swallowed by the hook's swallow-all contract and the test
        # could not detect a regression of the skip logic.
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                return 0.777
        """)
        import numpy as np

        xi = np.linspace(-30.0, 30.0, 2001)
        m1 = np.full_like(xi, 1.0)
        m2 = 1.0 + 0.08 * np.tanh(xi / 4.0)
        m12 = np.full_like(xi, 0.02)
        csv = tmp_path / "prof.csv"
        np.savetxt(csv, np.c_[xi, m1, m2, m12], delimiter=",",
                   header="xi,m11,m22,m12", comments="")
        for kwargs in (
            {"lz_method": "dephased", "lz_gamma_phi": 0.1},
            # explicitly passing the DEFAULT estimator opts out too
            {"lz_method": "coherent"},
        ):
            P = resolve_P(_cfg(), str(csv), **kwargs)
            assert 0.0 <= P <= 1.0
            assert P != pytest.approx(0.777), kwargs
            # and the in-repo kernel (not the config value) provided it
            assert "Using P_chi_to_B from profile" in capsys.readouterr().out

    def test_hook_failure_falls_through_to_kernel_then_config(
        self, modpath, capsys
    ):
        _write_module(modpath, "transport_from_profile", """
            def compute_prob_from_profile(csv, v_w):
                raise RuntimeError("boom")
        """)
        # nonexistent CSV: hook swallows, in-repo kernel fails, config wins
        P = resolve_P(_cfg(), "does_not_exist.csv")
        out = capsys.readouterr().out
        assert P == pytest.approx(0.149)
        assert "falling back to config" in out
