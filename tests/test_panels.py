"""Spectral panel quadrature (solvers/panels.py) + its audit-gated wiring.

The satellite battery the PR promises: GL-vs-trapezoid agreement over the
adversarial gate population (including zero-reference and seam-straddling
points), the spectral-decay audit, tri-state knob resolution through
run_sweep / the CLIs, and chunk double-buffer bit-parity with the serial
loop."""
import numpy as np
import pytest

from bdlz_tpu.config import (
    config_from_dict,
    point_params_from_config,
    static_choices_from_config,
)
from bdlz_tpu.ops.kjma_table import make_f_table
from bdlz_tpu.parallel import build_grid, make_mesh, run_sweep
from bdlz_tpu.solvers.panels import (
    N_PANELS_DEFAULT,
    NODES_PER_PANEL_DEFAULT,
    integrate_YB_panel_gl,
    make_panel_scheme,
    panel_edges,
    y_branch_seam,
    y_washout_turn_on,
)
from bdlz_tpu.solvers.quadrature import (
    integrate_YB_quadrature_tabulated,
    quadrature_bounds,
)
from bdlz_tpu.validation import (
    build_audit_population,
    panel_gl_population_audit,
    relative_errors,
)

BENCH_OVER = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


@pytest.fixture(scope="module")
def base_cfg():
    return config_from_dict(dict(BENCH_OVER))


@pytest.fixture(scope="module")
def table_np(base_cfg):
    return make_f_table(base_cfg.I_p, np)


@pytest.fixture(scope="module")
def mesh8():
    import jax

    assert len(jax.devices()) == 8
    return make_mesh(shape=(4, 2))


def _point(grid, i):
    return type(grid)(*(float(np.asarray(f)[i]) for f in grid))


class TestPanelScheme:
    def test_edges_snap_breakpoints(self, base_cfg):
        """Every in-window analytic breakpoint lands EXACTLY on a panel
        edge, and edges stay sorted within the clipped support."""
        cfg = config_from_dict(dict(BENCH_OVER))
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        # a seam-in-window point: m = 3·T_p·1.05 puts T=m/3 mid-window
        pp = pp._replace(m_chi_GeV=3.0 * pp.T_p_GeV * 1.05)
        y_lo, y_hi = quadrature_bounds(pp, np)
        edges = np.asarray(panel_edges(pp, y_lo, y_hi, N_PANELS_DEFAULT, np))
        assert edges.shape == (N_PANELS_DEFAULT + 1,)
        assert np.all(np.diff(edges) >= 0)
        assert edges[0] == y_lo and edges[-1] == y_hi
        seam = float(y_branch_seam(pp, np))
        wash = float(y_washout_turn_on(pp.I_p, np))
        assert y_lo < seam < y_hi
        assert seam in edges               # the jump is a panel edge
        assert wash in edges               # the washout turn-on too

    def test_out_of_window_breakpoints_do_not_distort(self, base_cfg):
        """Breakpoints outside [y_lo, y_hi] (the common case — the bench
        grid's seam sits at y ~ 4000) leave the uniform edges untouched."""
        pp = point_params_from_config(base_cfg, base_cfg.P_chi_to_B)
        y_lo, y_hi = quadrature_bounds(pp, np)
        assert float(y_branch_seam(pp, np)) > y_hi  # seam outside
        edges = np.asarray(panel_edges(pp, y_lo, y_hi, 16, np))
        wash = float(y_washout_turn_on(pp.I_p, np))
        uniform = y_lo + (y_hi - y_lo) / 16 * np.arange(17)
        moved = np.flatnonzero(edges != uniform)
        # only the washout snap (in-window) may move an edge
        assert len(moved) <= 1
        assert wash in edges

    def test_scheme_shape_validation(self):
        with pytest.raises(ValueError, match="n_panels"):
            make_panel_scheme(np, n_panels=0)
        s = make_panel_scheme(np, n_panels=4, n_nodes=8)
        assert s.n_quad_nodes == 32
        # Gauss-Legendre exactness sanity: degree-15 polynomial, 8 nodes
        assert float(np.sum(s.weights * s.nodes**14)) == pytest.approx(
            2.0 / 15.0, rel=1e-12
        )

    def test_empty_window_returns_exact_zero(self, base_cfg, table_np):
        """T-windows mapping to an empty clipped y-interval must return
        EXACTLY 0.0 — the zero-reference gate points compare bitwise."""
        pp = point_params_from_config(base_cfg, base_cfg.P_chi_to_B)
        # whole T-window above T_p at large beta: y(T_lo) < Y_NEG_CUT
        # while y_lo clips AT the cut -> y_hi < y_lo (empty interval)
        pp = pp._replace(
            beta_over_H=400.0, T_min_over_Tp=10.0, T_max_over_Tp=12.0
        )
        y_lo, y_hi = quadrature_bounds(pp, np)
        assert y_hi < y_lo  # genuinely empty after support clipping
        gl = float(integrate_YB_panel_gl(pp, "fermion", table_np, np))
        tr = float(integrate_YB_quadrature_tabulated(pp, "fermion", table_np, np))
        assert gl == 0.0 == tr


class TestAgreement:
    def test_gl_matches_trapezoid_on_bench_grid(self, base_cfg, table_np):
        """<=1e-9 vs the 8000-node reference trapezoid over a bench-grid
        slice (the acceptance claim, measured at ~1e-11 in practice)."""
        grid = build_grid(base_cfg, {
            "m_chi_GeV": np.geomspace(0.1, 10.0, 5),
            "T_p_GeV": np.geomspace(30.0, 300.0, 5),
            "v_w": [0.05, 0.9],
        })
        n = len(np.asarray(grid.m_chi_GeV))
        gl = np.empty(n)
        tr = np.empty(n)
        for i in range(n):
            pp = _point(grid, i)
            gl[i] = integrate_YB_panel_gl(pp, "fermion", table_np, np)
            tr[i] = integrate_YB_quadrature_tabulated(
                pp, "fermion", table_np, np, n_y=8000
            )
        assert float(np.max(relative_errors(gl, tr))) <= 1e-9

    def test_adversarial_population_seam_and_zero_points(self, base_cfg,
                                                         table_np):
        """Over the audit population: non-seam points agree with the
        trapezoid; seam-straddling points CONVERGE (self-consistent under
        node refinement) even where the trapezoid carries O(h) jump error;
        zero-reference (empty-window) points are exactly 0 on both."""
        pop = build_audit_population(base_cfg, 64, seed=1)
        grid = pop.grid
        n = len(np.asarray(grid.m_chi_GeV))
        grid_np = type(grid)(*(np.asarray(f, dtype=np.float64) for f in grid))
        y_lo, y_hi = quadrature_bounds(grid_np, np)
        seam = np.asarray(y_branch_seam(grid_np, np))
        seam_in = (seam > y_lo) & (seam < y_hi)
        assert seam_in.any()  # the population does straddle the seam
        dense = make_panel_scheme(np, n_panels=2 * N_PANELS_DEFAULT,
                                  n_nodes=NODES_PER_PANEL_DEFAULT)
        for i in range(0, n, 3):
            pp = _point(grid, i)
            gl = float(integrate_YB_panel_gl(pp, "fermion", table_np, np))
            tr = float(integrate_YB_quadrature_tabulated(
                pp, "fermion", table_np, np, n_y=8000
            ))
            if tr == 0.0:
                assert gl == 0.0  # zero-reference: bitwise agreement
                continue
            if seam_in[i]:
                # the trapezoid is O(h)-wrong at a jump; the panel rule
                # must instead be stable under its own refinement
                gl2 = float(integrate_YB_panel_gl(
                    pp, "fermion", table_np, np, scheme=dense
                ))
                assert gl == pytest.approx(gl2, rel=5e-7)
            else:
                assert gl == pytest.approx(tr, rel=5e-7), i


class TestAudit:
    def test_smooth_population_passes(self, base_cfg, table_np):
        grid = build_grid(base_cfg, {
            "m_chi_GeV": np.geomspace(0.1, 10.0, 6),
            "T_p_GeV": np.geomspace(30.0, 300.0, 6),
        })
        a = panel_gl_population_audit(grid, "fermion", n_y=8000,
                                      table=table_np)
        assert a.ok, a.reason
        assert a.max_rel_vs_trap <= 1e-9
        # spectral decay: halving the nodes collapses the error by far
        # more than the 0.25 admission ratio
        assert a.max_err_half <= 0.25 * a.max_err_quarter
        assert a.n_quad_nodes == N_PANELS_DEFAULT * NODES_PER_PANEL_DEFAULT

    def test_seam_population_fails_loudly(self, base_cfg, table_np):
        pop = build_audit_population(base_cfg, 64, seed=1)
        a = panel_gl_population_audit(pop.grid, "fermion", n_y=8000,
                                      table=table_np)
        assert not a.ok
        assert "seam" in a.reason
        assert a.n_seam_inside > 0

    def test_swept_I_p_refused(self, base_cfg, table_np):
        grid = build_grid(base_cfg, {"I_p": [0.3, 0.4]})
        a = panel_gl_population_audit(grid, "fermion", table=table_np)
        assert not a.ok and "I_p" in a.reason


class TestKnobResolution:
    AXES = {"m_chi_GeV": np.geomspace(0.1, 2.0, 12).tolist()}

    def test_auto_resolves_on_for_smooth_grid(self, base_cfg, mesh8):
        static = static_choices_from_config(base_cfg)
        assert static.quad_panel_gl is None  # config default: tri-state
        res = run_sweep(base_cfg, self.AXES, static, mesh=mesh8, chunk_size=8)
        assert res.quad_impl == "panel_gl"
        assert res.n_quad_nodes == N_PANELS_DEFAULT * NODES_PER_PANEL_DEFAULT

    def test_auto_falls_back_on_seam_grid(self, base_cfg, mesh8, capsys):
        """A sweep whose grid crosses the T=m/3 seam must stay on the
        trapezoid, loudly."""
        static = static_choices_from_config(base_cfg)
        axes = {"m_chi_GeV": [250.0, 300.0]}  # m ~ 3*T_p: seam in-window
        res = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8)
        assert res.quad_impl == "trap"
        assert "audit fallback" in capsys.readouterr().err

    def test_explicit_off_pins_trapezoid(self, base_cfg, mesh8):
        static = static_choices_from_config(base_cfg)
        res = run_sweep(
            base_cfg, self.AXES, static._replace(quad_panel_gl=False),
            mesh=mesh8, chunk_size=8,
        )
        assert res.quad_impl == "trap"
        assert res.n_quad_nodes == 8000

    def test_explicit_on_skips_audit_even_on_seam_grid(self, base_cfg, mesh8):
        static = static_choices_from_config(base_cfg)
        axes = {"m_chi_GeV": [250.0, 300.0]}
        res = run_sweep(
            base_cfg, axes, static._replace(quad_panel_gl=True),
            mesh=mesh8, chunk_size=8,
        )
        assert res.quad_impl == "panel_gl"
        assert res.n_failed == 0

    def test_stiff_impl_ignores_quad(self, base_cfg, mesh8, capsys):
        import dataclasses

        cfg = dataclasses.replace(base_cfg, T_min_over_Tp=0.2)
        static = static_choices_from_config(cfg)
        res = run_sweep(
            cfg, {"Gamma_wash_over_H": [0.01, 0.1]},
            static._replace(quad_panel_gl=True), mesh=mesh8, chunk_size=8,
        )
        assert res.quad_impl is None and res.n_quad_nodes is None
        assert "requires the tabulated engine" in capsys.readouterr().err

    def test_gl_sweep_matches_trap_sweep(self, base_cfg, mesh8):
        static = static_choices_from_config(base_cfg)
        r_gl = run_sweep(base_cfg, self.AXES, static, mesh=mesh8, chunk_size=8)
        r_tr = run_sweep(
            base_cfg, self.AXES, static._replace(quad_panel_gl=False),
            mesh=mesh8, chunk_size=8,
        )
        assert r_gl.quad_impl == "panel_gl" and r_tr.quad_impl == "trap"
        np.testing.assert_allclose(
            r_gl.outputs["DM_over_B"], r_tr.outputs["DM_over_B"], rtol=1e-9
        )

    def test_resume_invalidated_by_quad_change(self, base_cfg, mesh8,
                                               tmp_path):
        """Panel-GL and trapezoid chunks must never be spliced: the
        resolved scheme joins the manifest hash."""
        static = static_choices_from_config(base_cfg)
        out = str(tmp_path / "sweep")
        r1 = run_sweep(base_cfg, self.AXES, static, mesh=mesh8,
                       chunk_size=16, out_dir=out)
        assert r1.quad_impl == "panel_gl"
        # same resolution resumes
        r2 = run_sweep(base_cfg, self.AXES, static, mesh=mesh8,
                       chunk_size=16, out_dir=out)
        assert r2.resumed_chunks == r2.chunks
        # pinned trapezoid recomputes from scratch
        r3 = run_sweep(
            base_cfg, self.AXES, static._replace(quad_panel_gl=False),
            mesh=mesh8, chunk_size=16, out_dir=out,
        )
        assert r3.resumed_chunks == 0


class TestDoubleBuffer:
    def test_overlap_bit_parity_with_serial_loop(self, base_cfg, mesh8):
        """The double-buffered chunk loop runs the same programs on the
        same inputs — outputs must be BIT-identical to the serial loop."""
        static = static_choices_from_config(base_cfg)
        axes = {"m_chi_GeV": np.geomspace(0.1, 2.0, 24).tolist()}
        r_ov = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8)
        r_ser = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8,
                          overlap_chunks=False)
        for f in r_ov.outputs:
            np.testing.assert_array_equal(
                r_ov.outputs[f], r_ser.outputs[f], err_msg=f
            )

    def test_overlap_parity_with_resume_and_failures(self, base_cfg, mesh8,
                                                     tmp_path):
        """Overlap + chunk files + failed points + partial resume all
        reproduce the serial loop's bookkeeping exactly."""
        import os

        static = static_choices_from_config(base_cfg)
        axes = {"incident_flux_scale": [1.07e-9, np.inf] * 6}
        out = str(tmp_path / "ov")
        r1 = run_sweep(base_cfg, dict(axes), static, mesh=mesh8,
                       chunk_size=4, out_dir=out)
        assert r1.n_failed == 6
        os.remove(f"{out}/chunk_00001.npz")  # force one recompute
        r2 = run_sweep(base_cfg, dict(axes), static, mesh=mesh8,
                       chunk_size=4, out_dir=out)
        r3 = run_sweep(base_cfg, dict(axes), static, mesh=mesh8,
                       chunk_size=4, out_dir=str(tmp_path / "ser"),
                       overlap_chunks=False)
        assert r2.n_failed == r3.n_failed == 6
        np.testing.assert_array_equal(r2.failed_mask, r3.failed_mask)
        np.testing.assert_array_equal(
            r2.outputs["DM_over_B"], r3.outputs["DM_over_B"]
        )


class TestJitVmapParity:
    def test_jit_vmap_matches_numpy_scalar_loop(self, base_cfg, table_np):
        import jax
        import jax.numpy as jnp

        table_j = make_f_table(base_cfg.I_p, jnp)
        grid = build_grid(base_cfg, {"m_chi_GeV": np.geomspace(0.1, 10, 8)})
        fn = jax.jit(jax.vmap(
            lambda p: integrate_YB_panel_gl(p, "fermion", table_j, jnp),
            in_axes=(0,),
        ))
        got = np.asarray(fn(jax.tree.map(jnp.asarray, grid)))
        ref = np.array([
            float(integrate_YB_panel_gl(_point(grid, i), "fermion",
                                        table_np, np))
            for i in range(8)
        ])
        np.testing.assert_allclose(got, ref, rtol=1e-13)


def test_cli_quad_flag_per_point(base_cfg, tmp_path, capsys, monkeypatch):
    """--quad on routes the per-point CLI through the panel rule; the
    default invocation stays byte-identical (bit-pinned trapezoid)."""
    import dataclasses
    import json

    from bdlz_tpu.cli import main as cli_main

    monkeypatch.chdir(tmp_path)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dataclasses.asdict(base_cfg)))

    def ratio():
        capsys.readouterr()  # drop the printed block; read the artifact
        return float(json.loads(
            (tmp_path / "yields_out.json").read_text()
        )["final"]["DM_over_B"])

    cli_main(["--config", str(cfg_path)])
    r_default = ratio()
    cli_main(["--config", str(cfg_path), "--quad", "on"])
    r_gl = ratio()
    # the default stays on the bit-pinned trapezoid (the archived golden
    # ratio); --quad on agrees to the panel rule's convergence level
    assert r_default == pytest.approx(5.6889263349, rel=1e-9)
    assert r_gl == pytest.approx(r_default, rel=1e-9)
    assert r_gl != r_default  # a different scheme, not a no-op


def test_sweep_cli_quad_flag(base_cfg, tmp_path, capsys):
    import dataclasses
    import json

    from bdlz_tpu.sweep_cli import main as sweep_main

    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps(dataclasses.asdict(base_cfg)))
    for flag, want in (("auto", "panel_gl"), ("off", "trap"),
                       ("on", "panel_gl")):
        sweep_main([
            "--config", str(cfg),
            "--axis", "m_chi_GeV=geom:0.1:2:8",
            "--chunk", "8", "--quad", flag,
        ])
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]
        )
        assert summary["quad_impl"] == want, flag
        assert summary["n_quad_nodes"] == (
            N_PANELS_DEFAULT * NODES_PER_PANEL_DEFAULT
            if want == "panel_gl" else 8000
        )
