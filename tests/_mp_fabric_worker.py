"""Worker for the real 2-process whole-host failover test.

Launched by ``tests/test_fabric.py::TestFabricMP`` as
``python _mp_fabric_worker.py <role> <store_root> <content_hash>``
with both roles sharing one trusted store root (the membership plane
AND the artifact registry):

* the **victim** registers fabric seat 0 on a SHORT (2 s) wall-clock
  lease, cold-admits the tenant, answers a fixed request trace, prints
  the values, and exits WITHOUT standing down — the real host-death
  shape: its lease dangles until TTL expiry;
* the **survivor** registers seat 1, heartbeats until the router's
  live set no longer contains the victim (pure TTL arithmetic — no
  channel to the corpse), then serves the SAME trace: the router must
  pick the survivor, cold admission must be a validated fetch-by-hash
  through its pull-through cache (one miss, zero rebuilds beyond the
  fetch), and every value must be bitwise-equal to the victim's.

Exit 0 with a JSON result line on stdout; any contract violation is a
loud traceback + nonzero exit the parent test surfaces.
"""
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

FABRIC = "mpfab"
SCENARIO = "coherent"


def _base():
    from bdlz_tpu.config import config_from_dict

    # the tiny_emulator fixture's base, verbatim
    return config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })


def _thetas():
    import numpy as np

    rng = np.random.default_rng(5)
    return np.column_stack([
        rng.uniform(0.92, 1.08, 4),    # m_chi_GeV
        rng.uniform(92.0, 108.0, 4),   # T_p_GeV
        rng.uniform(0.26, 0.34, 4),    # v_w
    ])


def _host(store, content_hash, role, index, ttl_s, cache_root=None):
    from bdlz_tpu.serve import FabricHost

    return FabricHost(
        _base(), fabric=FABRIC, host_id=role, host_index=index,
        store=store, tenant_map={SCENARIO: content_hash},
        ttl_s=ttl_s, cache_root=cache_root, max_batch_size=4,
    )


def _serve_trace(host):
    futs = [host.submit(t, scenario=SCENARIO) for t in _thetas()]
    host.drain()
    out = [f.result(timeout=0) for f in futs]
    assert all(r.host_id == host.host_id for r in out), "host_id stamp"
    assert all(not r.degraded for r in out), "clean serve degraded?"
    return [r.value for r in out]


def victim(store, content_hash):
    host = _host(store, content_hash, "victim", 0, ttl_s=2.0)
    host.register()
    values = _serve_trace(host)
    print(json.dumps({"values": values}))
    sys.stdout.flush()
    # host death: NO close(), NO lease release — the seat dangles
    os._exit(0)


def survivor(store, content_hash, cache_root):
    from bdlz_tpu.serve import GlobalRouter

    host = _host(
        store, content_hash, "survivor", 1, ttl_s=30.0,
        cache_root=cache_root,
    )
    router = GlobalRouter(store, FABRIC, 2)
    host.register()
    deadline = time.time() + 60.0
    waited_out_victim = False
    while time.time() < deadline:
        host.heartbeat()
        live = {r["host_id"] for r in router.live()}
        if "victim" not in live:
            waited_out_victim = True
            break
        time.sleep(0.1)
    assert waited_out_victim, "victim's lease never expired"
    routed = router.route(scenario=SCENARIO)
    assert routed["host_id"] == "survivor", routed
    values = _serve_trace(host)
    print(json.dumps({
        "values": values,
        "admissions": len(host.service.admission_events),
        "cache": host.artifact_cache.counters(),
    }))
    sys.stdout.flush()
    host.close()


def main():
    from bdlz_tpu.provenance import Store

    role, store_root, content_hash = sys.argv[1:4]
    store = Store(store_root)
    if role == "victim":
        victim(store, content_hash)
    elif role == "survivor":
        survivor(store, content_hash, sys.argv[4])
    else:
        raise SystemExit(f"unknown role {role!r}")


if __name__ == "__main__":
    main()
