"""Unit tests for the y<->T maps and the batched KJMA kernel (SURVEY §4.2)."""
import math

import numpy as np
import pytest

from bdlz_tpu.physics.percolation import (
    KJMAGrid,
    T_of_y,
    area_over_volume,
    make_kjma_grid,
    y_of_T,
)
from bdlz_tpu.physics.thermo import hubble_rate

BENCH = dict(I_p=0.34, beta_over_H=100.0, T_p=100.0, v_w=0.30, g_star=106.75)


def aov(y, grid, **kw):
    p = {**BENCH, **kw}
    return area_over_volume(
        y, p["I_p"], p["beta_over_H"], p["T_p"], p["v_w"], p["g_star"], grid, np
    )


def test_y_of_T_closed_form():
    # y = B/2 [(T_p/T)^2 - 1]: zero at T_p, positive below, negative above.
    assert y_of_T(100.0, 100.0, 100.0, np) == 0.0
    assert y_of_T(50.0, 100.0, 100.0, np) == pytest.approx(150.0)
    assert y_of_T(200.0, 100.0, 100.0, np) == pytest.approx(-37.5)


def test_y_T_roundtrip():
    Ts = np.geomspace(0.1, 500.0, 64)
    ys = y_of_T(Ts, 100.0, 100.0, np)
    back = T_of_y(ys, 100.0, 100.0, np)
    np.testing.assert_allclose(back, Ts, rtol=1e-12)


def test_T_of_y_out_of_range_guard():
    # denom <= 1e-12 -> T_p * 1e6 (reference :133-134).
    assert T_of_y(-50.0001, 100.0, 100.0, np) == 100.0 * 1e6


def test_grid_matches_reference_spec():
    grid = make_kjma_grid(np)
    assert grid.z.shape == (1200,)
    assert grid.z[0] == 0.0 and grid.z[-1] == 30.0
    # gamma4(0) = 0, gamma4(inf) = 6 = Gamma(4)
    assert grid.gamma4[0] == pytest.approx(0.0, abs=1e-12)
    # gamma4(30) = 6 − e⁻³⁰·29886 ≈ 6 − 2.8e-9
    assert grid.gamma4[-1] == pytest.approx(6.0, abs=1e-8)


def test_aov_hard_zero_above_y50():
    grid = make_kjma_grid(np)
    assert aov(50.0001, grid) == 0.0
    assert aov(np.array([60.0, 1e3]), grid).tolist() == [0.0, 0.0]


def test_aov_batched_matches_scalar_loop():
    """The tensorized kernel must equal per-scalar evaluation bitwise —
    this is the hot-loop replacement (reference :261)."""
    grid = make_kjma_grid(np)
    ys = np.linspace(-80.0, 49.0, 777)
    batched = aov(ys, grid)
    scalars = np.array([aov(float(y), grid) for y in ys])
    np.testing.assert_array_equal(batched, scalars)


def test_aov_against_independent_quadrature():
    """Check the KJMA integral against scipy adaptive quadrature on the
    *continuum* integrand (not the fixed grid): the 1200-point trapezoid on
    [0, 30] should agree to its own discretisation error (~1e-7 rel)."""
    from scipy.integrate import quad

    grid = make_kjma_grid(np)
    p = BENCH
    H_p = hubble_rate(p["T_p"], p["g_star"], np)
    beta = p["beta_over_H"] * H_p
    for y in (-5.0, 0.0, 2.0):
        expy = math.exp(y)

        def integrand(z):
            g4 = 6.0 - math.exp(-z) * (z**3 + 3 * z**2 + 6 * z + 6)
            return z**2 * math.exp(-z) * math.exp(-(p["I_p"] / 6.0) * expy * g4)

        F, _ = quad(integrand, 0.0, 30.0, epsabs=1e-14, epsrel=1e-12)
        expected = (p["I_p"] / 2.0) * (beta / p["v_w"]) * expy * F
        assert aov(y, grid) == pytest.approx(expected, rel=5e-7)


def test_aov_exp_clamp_continuity():
    """e^y is clamped at y=±50 (reference :161): below −50 the prefactor
    saturates, so A/V(-60) == A/V(-50)."""
    grid = make_kjma_grid(np)
    assert aov(-60.0, grid) == aov(-50.0, grid)


def test_aov_wall_velocity_floor():
    grid = make_kjma_grid(np)
    assert np.isfinite(aov(0.0, grid, v_w=0.0))
    assert aov(0.0, grid, v_w=0.0) == aov(0.0, grid, v_w=1e-12)
