"""Worker for the divergent-kernel-knob fleet test.

Launched twice by ``tests/test_multihost.py::test_divergent_kernel_knob_
raises_fleetwide`` as ``python _mp_knob_worker.py <port> <process_id>``
with DIFFERENT ``BDLZ_PALLAS_COL_BLOCK`` values per process.  Both
processes must raise the fleet-uniformity RuntimeError from the sweep's
startup agreement — one host raising while the other proceeds into a
chunk collective would deadlock (which the parent's timeout converts
into a failure).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from _mp_common import force_local_device_count, pin_worker_platform

# must run before the first `import jax` (overrides the parent pytest
# process's 8-device flag)
force_local_device_count(2)


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])

    import jax

    pin_worker_platform(jax, 2)

    from bdlz_tpu.parallel.multihost import init_multihost

    assert init_multihost(f"localhost:{port}", 2, pid) is True

    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.parallel import make_mesh, run_sweep

    base = config_from_dict({
        "regime": "nonthermal", "P_chi_to_B": 0.149,
        "Y_chi_init": 4.90e-10,
    })
    static = static_choices_from_config(base)
    axes = {"m_chi_GeV": np.geomspace(0.5, 2.0, 4).tolist()}
    try:
        run_sweep(
            base, axes, static, mesh=make_mesh(shape=(4, 1)),
            chunk_size=4, n_y=2000, impl="pallas", interpret=True,
        )
    except RuntimeError as exc:
        assert "BDLZ_PALLAS_COL_BLOCK differs across hosts" in str(exc), exc
        print(f"worker {pid} KNOB-MISMATCH-RAISED")
        return
    raise AssertionError("divergent knob did not raise")


if __name__ == "__main__":
    main()
