"""Design-scale LZ stress tests (VERDICT r4 ask #7).

Real bounce-solver profiles run to millions of ξ-samples (paper
§6.1/§10).  These tests prove the profile→P path — native CSV ingestion,
the coherent transfer-matrix kernel, and the P(v_w) table build — stays
correct and memory-bounded at ≥1e6 segments.  The tree product pads to a
power of two (lz/kernel.py `_ordered_tree_product`), so 1e6+1 points is
deliberately just past the 2^20 doubling boundary.

`scripts/lz_scale_bench.py` is the companion that records throughput
numbers (docs/perf_notes.md "LZ at design scale").
"""
import os

import numpy as np
import pytest

from bdlz_tpu.lz.profile import BounceProfile
from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_table, probabilities_for_points

N_ROWS = 1_000_001


@pytest.fixture(scope="module")
def big_profile():
    xi = np.linspace(-300.0, 300.0, N_ROWS)
    return BounceProfile(
        xi=xi,
        delta=-0.08 * np.tanh(xi / 4.0),
        mix=np.full(N_ROWS, 0.02),
    )


def test_coherent_kernel_at_1e6_segments(big_profile, monkeypatch):
    """The coherent kernel completes over ~1e6 segments with a small
    speed-chunk budget (forces multi-chunk execution) and produces
    finite, physical probabilities."""
    # ~34 MB/speed of tree leaves at 2^20 padded segments -> budget of
    # 2^28 bytes = 8 speeds per chunk -> 2 chunks for 9 speeds
    monkeypatch.setenv("BDLZ_LZ_SPEED_CHUNK_BYTES", str(1 << 28))
    v = np.linspace(0.05, 0.9, 9)
    P = probabilities_for_points(big_profile, v, method="coherent")
    assert P.shape == (9,)
    assert np.isfinite(P).all()
    assert ((P >= 0.0) & (P <= 1.0)).all()
    # single crossing at xi=0: the local composition bounds the physics —
    # the coherent P oscillates around it but stays well off 0 and 1 at
    # these adiabaticities
    assert P.max() > 0.1


def test_speed_chunking_matches_single_shot():
    """Chunked evaluation (with its last-chunk padding) is bitwise the
    un-chunked program on a short profile."""
    xi = np.linspace(-30.0, 30.0, 2001)
    prof = BounceProfile(
        xi=xi, delta=-0.08 * np.tanh(xi / 4.0), mix=np.full(2001, 0.02)
    )
    v = np.linspace(0.05, 0.9, 7)
    env = dict(os.environ)
    try:
        os.environ["BDLZ_LZ_SPEED_CHUNK_BYTES"] = str(1 << 40)
        P_one = probabilities_for_points(prof, v, method="coherent")
        # 2000 segments -> padded 2048 -> 2048*8*4 B/speed; 3 chunks of 3
        os.environ["BDLZ_LZ_SPEED_CHUNK_BYTES"] = str(2048 * 8 * 4 * 3)
        P_chunked = probabilities_for_points(prof, v, method="coherent")
        os.environ["BDLZ_LZ_SPEED_CHUNK_BYTES"] = str(2048 * 8 * 9 * 2)
        P_deph = probabilities_for_points(
            prof, v, method="dephased", gamma_phi=0.03
        )
        os.environ["BDLZ_LZ_SPEED_CHUNK_BYTES"] = str(1 << 40)
        P_deph_one = probabilities_for_points(
            prof, v, method="dephased", gamma_phi=0.03
        )
    finally:
        os.environ.clear()
        os.environ.update(env)
    np.testing.assert_array_equal(P_chunked, P_one)
    np.testing.assert_array_equal(P_deph, P_deph_one)


def test_table2d_speed_chunk_budget_matches_default():
    """The 2-D P(v_w, Γ_φ) build caps its speed chunk by the same leaf
    budget as the 1-D path; a budget forcing per-speed chunks reproduces
    the default build bitwise."""
    from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_gamma_table

    xi = np.linspace(-30.0, 30.0, 2001)
    prof = BounceProfile(
        xi=xi, delta=-0.08 * np.tanh(xi / 4.0), mix=np.full(2001, 0.02)
    )
    env = dict(os.environ)
    try:
        os.environ["BDLZ_LZ_SPEED_CHUNK_BYTES"] = str(1 << 40)
        t_big = make_P_of_vw_gamma_table(
            prof, 0.1, 0.9, 0.0, 0.2, n_v=8, n_g=8
        )
        # 2000 segments -> padded 2048; 2048*8*9 B/speed -> budget of
        # exactly 3 speeds per chunk
        os.environ["BDLZ_LZ_SPEED_CHUNK_BYTES"] = str(2048 * 8 * 9 * 3)
        t_small = make_P_of_vw_gamma_table(
            prof, 0.1, 0.9, 0.0, 0.2, n_v=8, n_g=8
        )
    finally:
        os.environ.clear()
        os.environ.update(env)
    np.testing.assert_array_equal(
        np.asarray(t_small.values), np.asarray(t_big.values)
    )


def test_table2d_ragged_tail_compiles_once():
    """speed_chunk not dividing n_v must NOT cost a second compilation:
    the tail chunk is padded to the common chunk shape (mirroring
    probabilities_for_points) — a second trace of the jitted P_chunk
    would re-pay ~the whole first chunk's compile on long profiles.
    Also pins that the padded tail produces the same values as an
    evenly-divided build."""
    from bdlz_tpu.lz.sweep_bridge import TRACE_COUNTS, make_P_of_vw_gamma_table

    xi = np.linspace(-30.0, 30.0, 2001)
    prof = BounceProfile(
        xi=xi, delta=-0.08 * np.tanh(xi / 4.0), mix=np.full(2001, 0.02)
    )
    before = TRACE_COUNTS["P_chunk_2d"]
    # n_v=10 with speed_chunk=4 -> chunks of 4, 4, and a ragged 2
    t_ragged = make_P_of_vw_gamma_table(
        prof, 0.1, 0.9, 0.0, 0.2, n_v=10, n_g=8, speed_chunk=4
    )
    assert TRACE_COUNTS["P_chunk_2d"] - before == 1
    # dividing chunk, same nodes: values bitwise equal (vmap lanes are
    # independent, so tail padding cannot perturb the real nodes)
    t_even = make_P_of_vw_gamma_table(
        prof, 0.1, 0.9, 0.0, 0.2, n_v=10, n_g=8, speed_chunk=5
    )
    np.testing.assert_array_equal(
        np.asarray(t_ragged.values), np.asarray(t_even.values)
    )


def test_ptable_build_at_1e6_segments(big_profile, monkeypatch):
    """The MCMC's P(v_w) table build runs the chunked path end to end at
    design scale (small node count keeps the test fast; the table-node
    axis IS the speed axis being chunked)."""
    monkeypatch.setenv("BDLZ_LZ_SPEED_CHUNK_BYTES", str(1 << 28))
    table = make_P_of_vw_table(big_profile, "coherent", 0.1, 0.9, n=16)
    vals = np.asarray(table.values)
    assert vals.shape == (16,)
    assert np.isfinite(vals).all()
    assert ((vals >= 0.0) & (vals <= 1.0)).all()


def test_native_parser_at_1e6_rows(tmp_path):
    """The native C++ CSV parser ingests a million-row profile correctly
    (header mapping, first/last row values)."""
    from bdlz_tpu.native import native_available, read_csv_native

    if not native_available():
        pytest.skip("native toolchain unavailable")
    n = N_ROWS
    xi = np.linspace(-300.0, 300.0, n)
    delta = -0.08 * np.tanh(xi / 4.0)
    mix = np.full(n, 0.02)
    path = tmp_path / "big.csv"
    with open(path, "w") as f:
        f.write("xi,delta,m_mix\n")
        np.savetxt(f, np.column_stack([xi, delta, mix]), delimiter=",")
    names, table = read_csv_native(str(path))
    assert names == ["xi", "delta", "m_mix"]
    assert table.shape == (n, 3)
    np.testing.assert_allclose(table[0], [xi[0], delta[0], mix[0]], rtol=1e-15)
    np.testing.assert_allclose(
        table[-1], [xi[-1], delta[-1], mix[-1]], rtol=1e-15
    )
    np.testing.assert_allclose(table[:, 0], xi, rtol=1e-15)
