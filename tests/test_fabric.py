"""Cross-host serving fabric (bdlz_tpu/serve/fabric.py + the host-lease
hooks in parallel/multihost.py).

Pins the ISSUE-20 acceptance contract on a fake clock, single-process:
TTL'd host-lease membership (exclusive create, heartbeat extend,
expired-seat steal with a generation bump, LIVE-seat identity collision
refused, torn record reads as fenced and heals), lease-fenced routing
(``heartbeat_loss`` — a live-but-silent host is fenced by TTL
arithmetic alone), whole-host failover (a crashed host's in-flight and
queued requests fail with typed ``ServiceUnavailable`` — never silent
loss — the submit ladder re-routes to a survivor, and the survivor
cold-admits the dead host's tenant from the registry by content hash
through its pull-through cache: a validated fetch, never a rebuild,
with answers bitwise-equal to the pre-crash host), partition-tolerant
serving (``store_partition`` → bounded retry → loud degraded-exact
answers reason ``"store_partition"`` → automatic rejoin when the store
heals), idle-host elastic chunk stealing (results bitwise-equal to a
serial ``run_sweep``; admission pressure stops the stealing within one
tick), and the zero-overhead default-OFF pins for the three new fault
sites.

The real 2-process host-kill twin lives in ``tests/_mp_fabric_worker.py``
under ``@pytest.mark.slow`` (tier-2, ``scripts/slow_suite.sh``).
"""
import dataclasses

import numpy as np
import pytest

from bdlz_tpu.config import (
    config_from_dict,
    static_choices_from_config,
    validate,
)
from bdlz_tpu.faults import VALID_SITES, FaultPlan
from bdlz_tpu.parallel.multihost import (
    host_lease_job,
    publish_host_lease,
    read_host_lease,
)
from bdlz_tpu.serve import (
    REASON_STORE_PARTITION,
    FabricError,
    FabricHost,
    GlobalRouter,
    ServiceUnavailable,
    ServingFabric,
)
from bdlz_tpu.utils.retry import RetryPolicy

PHYS = {
    "regime": "nonthermal",
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


def _cfg(**kw):
    return validate(config_from_dict({**PHYS, **kw}), backend="tpu")


class _Tick:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def assert_bitwise(a, b, label):
    a, b = np.asarray(a), np.asarray(b)
    same = (a == b) | (np.isnan(a) & np.isnan(b))
    assert same.all(), f"{label}: bit drift at {np.argwhere(~same)[:4]}"


@pytest.fixture(scope="module")
def fabric_plane(tmp_path_factory, jit_warmup):
    """Two tiny published two-channel artifacts (distinct physics →
    distinct hashes) in one shared store — the minimal two-tenant world
    every fabric here routes over."""
    from bdlz_tpu.emulator import AxisSpec, build_emulator
    from bdlz_tpu.provenance import Store, publish_artifact

    base = _cfg(P_chi_to_B=0.1)
    base_b = _cfg(P_chi_to_B=0.2)
    spec = {
        "m_chi_GeV": AxisSpec(0.9, 1.1, 2, "log"),
        "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
    }
    kw = dict(rtol=1e-2, n_probe=4, n_holdout=8, max_rounds=1, n_y=400,
              chunk_size=64, require_converged=False)
    root = tmp_path_factory.mktemp("fabric")
    art_a, _ = build_emulator(base, spec, out_dir=str(root / "a"), **kw)
    art_b, _ = build_emulator(base_b, spec, out_dir=str(root / "b"), **kw)
    store = Store(str(root / "store"))
    h_a = publish_artifact(store, art_a)
    h_b = publish_artifact(store, art_b)
    return {
        "base": base,
        "store": store,
        "tenant_map": {"coherent": h_a, "heavy": h_b},
        "h_a": h_a,
        "h_b": h_b,
        "root": root,
    }


def _host(plane, clock, idx, *, fabric="fab", ttl_s=30.0, **kw):
    kw.setdefault("max_batch_size", 4)
    return FabricHost(
        plane["base"], fabric=fabric, host_id=f"h{idx}", host_index=idx,
        store=plane["store"], tenant_map=plane["tenant_map"],
        clock=clock, ttl_s=ttl_s, **kw,
    )


def _fabric(plane, clock, n=2, *, fabric="fab", host_kw=None):
    hosts = [
        _host(plane, clock, i, fabric=fabric,
              **(host_kw or {}).get(i, {}))
        for i in range(n)
    ]
    router = GlobalRouter(plane["store"], fabric, n, clock=clock)
    fab = ServingFabric(hosts, router)
    fab.register_all()
    return fab


def _thetas(n, seed=5):
    rng = np.random.default_rng(seed)
    return np.column_stack([
        rng.uniform(0.92, 1.08, n), rng.uniform(0.26, 0.34, n)
    ])


# ---------------------------------------------------------------------------
# host-lease membership
# ---------------------------------------------------------------------------

class TestHostLeaseMembership:
    def test_register_creates_ttl_lease(self, fabric_plane):
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=1, fabric="memb")
        try:
            rec = read_host_lease(fabric_plane["store"], "memb", 0)
            assert rec["host_id"] == "h0" and rec["fabric"] == "memb"
            assert rec["expires_at"] == pytest.approx(30.0)
            assert rec["pools"] == {}  # nothing admitted yet
        finally:
            fab.close()

    def test_heartbeat_extends_and_advertises_pools(self, fabric_plane):
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=1, fabric="adv")
        try:
            fut = fab.submit(_thetas(1)[0], scenario="coherent")
            fab.drain()
            assert fut.result(timeout=0).artifact_hash == fabric_plane["h_a"]
            clock.t = 10.0
            fab.tick()  # heartbeat refreshes expiry AND the pool ad
            rec = read_host_lease(fabric_plane["store"], "adv", 0)
            assert rec["expires_at"] == pytest.approx(40.0)
            assert rec["pools"] == {"coherent": fabric_plane["h_a"]}
            assert rec["capacity"]["n_pools"] == 1
        finally:
            fab.close()

    def test_live_seat_collision_is_typed_refusal(self, fabric_plane):
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=1, fabric="coll")
        imposter = _host(fabric_plane, clock, 0, fabric="coll")
        imposter.host_id = "imposter"  # same seat, different identity
        try:
            with pytest.raises(FabricError, match="collision"):
                imposter.register()
        finally:
            fab.close()
            imposter.close()

    def test_expired_seat_stolen_with_generation_bump(self, fabric_plane):
        clock = _Tick()
        store = fabric_plane["store"]
        old = {"schema": 1, "host_id": "dead", "host_index": 0,
               "generation": 4, "expires_at": 5.0}
        assert publish_host_lease(store, "steal", 0, old, clock=clock)
        clock.t = 6.0  # past the old holder's TTL
        new = {"schema": 1, "host_id": "fresh", "host_index": 0,
               "generation": 0, "expires_at": 36.0}
        assert publish_host_lease(store, "steal", 0, new, clock=clock)
        rec = read_host_lease(store, "steal", 0)
        # the replacement is visible to routers that cached the corpse
        assert rec["host_id"] == "fresh" and rec["generation"] == 5

    def test_torn_host_lease_fences_then_heals(self, fabric_plane):
        from bdlz_tpu.provenance import lease_entry_name

        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=1, fabric="torn")
        try:
            store = fabric_plane["store"]
            path = store.path_for(
                lease_entry_name(host_lease_job("torn"), 0)
            )
            with open(path, "w", encoding="utf-8") as f:
                f.write('{"host_id": "h')  # torn mid-write
            # a torn record reads as a FENCED seat...
            assert read_host_lease(store, "torn", 0) is None
            assert fab.router.live() == []
            # ...and the next successful heartbeat rewrites it whole
            assert fab.hosts[0].heartbeat()
            rec = read_host_lease(store, "torn", 0)
            assert rec["host_id"] == "h0"
            assert [r["host_id"] for r in fab.router.live()] == ["h0"]
        finally:
            fab.close()


# ---------------------------------------------------------------------------
# routing + fencing
# ---------------------------------------------------------------------------

class TestRouterFencing:
    def test_route_prefers_scenario_advertiser(self, fabric_plane):
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=2, fabric="pref")
        try:
            # warm "coherent" onto host1 by hand, then advertise it
            fab.hosts[1].submit(_thetas(1)[0], scenario="coherent")
            fab.hosts[1].drain()
            fab.tick()
            # host0 is less loaded (0 pools), but host1 ADVERTISES the
            # scenario — affinity beats load
            rec = fab.router.route(scenario="coherent")
            assert rec["host_id"] == "h1"
            # hash-tagged routing sees the same advertisement
            rec = fab.router.route(artifact_hash=fabric_plane["h_a"])
            assert rec["host_id"] == "h1"
            # an unadvertised scenario falls back to least-loaded
            assert fab.router.route(scenario="heavy")["host_id"] == "h0"
        finally:
            fab.close()

    def test_heartbeat_loss_fences_live_but_silent_host(self, fabric_plane):
        plan = FaultPlan.from_obj([
            {"site": "heartbeat_loss", "kind": "raise", "chunk": 0},
        ])
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=2, fabric="hbl",
                      host_kw={0: {"fault_plan": plan}})
        try:
            clock.t = 31.0  # past both registration TTLs
            fab.tick()      # host1 extends; host0's heartbeat is eaten
            sick = fab.hosts[0]
            assert sick.alive and sick.heartbeats_lost == 1
            assert not sick.partitioned  # silent loss, NOT a partition
            # the host still answers — but the router must fence it on
            # TTL arithmetic alone
            assert [r["host_id"] for r in fab.router.live()] == ["h1"]
            assert fab.router.route(scenario="coherent")["host_id"] == "h1"
        finally:
            fab.close()

    def test_no_live_host_is_typed_refusal(self, fabric_plane):
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=2, fabric="dead")
        try:
            clock.t = 100.0  # everyone's lease is ancient history
            with pytest.raises(ServiceUnavailable, match="no live host"):
                fab.router.route(scenario="coherent")
        finally:
            fab.close()


# ---------------------------------------------------------------------------
# whole-host failover
# ---------------------------------------------------------------------------

class TestFailover:
    def test_crash_failover_readmit_roundtrip(self, fabric_plane,
                                              tmp_path):
        """THE acceptance pin: kill one of two hosts with queued work —
        every queued future fails TYPED, the submit ladder re-routes to
        the survivor while the corpse's lease is still unexpired, the
        survivor cold-admits the tenant from the registry through its
        pull-through cache (fetch-by-hash, never a rebuild), and its
        answers are bitwise-equal to the dead host's."""
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=2, fabric="fo", host_kw={
            1: {"cache_root": str(tmp_path / "h1cache")},
        })
        try:
            thetas = _thetas(4)
            # ladder start: both hosts are empty, seat 0 wins the tie
            futs = [fab.submit(t, scenario="coherent") for t in thetas]
            fab.drain()
            v0 = [f.result(timeout=0) for f in futs]
            assert {r.host_id for r in v0} == {"h0"}
            fab.tick()  # advertise host0's pool

            # queued work dies TYPED at crash — never silent loss
            doomed = [fab.submit(t, scenario="coherent") for t in thetas]
            failed = fab.hosts[0].crash()
            assert failed == len(doomed)
            for f in doomed:
                with pytest.raises(ServiceUnavailable):
                    f.result(timeout=0)

            # the corpse's lease has NOT expired: routing still points
            # at it, and the ladder walks to the survivor
            assert fab.router.route(
                scenario="coherent")["host_id"] == "h0"
            refuts = [fab.submit(t, scenario="coherent") for t in thetas]
            assert fab.failovers >= 1
            fab.drain()
            v1 = [f.result(timeout=0) for f in refuts]
            assert {r.host_id for r in v1} == {"h1"}
            assert all(not r.degraded for r in v1)

            # bitwise-identical answers on the survivor
            assert_bitwise([r.value for r in v1],
                           [r.value for r in v0], "failover values")

            # readmission was a validated FETCH, not a rebuild: one
            # admission event, one cache miss (pull-through fill)
            ev = fab.hosts[1].service.admission_events
            assert len(ev) == 1 and not ev[0]["readmit"]
            assert fab.hosts[1].artifact_cache.counters() == {
                "hits": 0, "misses": 1, "corrupt_evictions": 0,
            }
            pool = fab.hosts[1].service.pool("coherent")
            assert pool.stats.extras["artifact_cache"]["misses"] == 1
            assert pool.stats.as_rows()[-1]["host_id"] == "h1"

            # after TTL expiry the corpse is fenced outright
            clock.t = 62.0
            fab.tick()
            assert [r["host_id"] for r in fab.router.live()] == ["h1"]
        finally:
            fab.close()

    def test_injected_host_crash_site(self, fabric_plane):
        plan = FaultPlan.from_obj([
            {"site": "host_crash", "kind": "raise", "chunk": 0},
        ])
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=2, fabric="hc",
                      host_kw={0: {"fault_plan": plan}})
        try:
            fut = fab.hosts[0].submit(_thetas(1)[0], scenario="coherent")
            fab.tick()  # host0 dies AT the tick; host1 unaffected
            assert not fab.hosts[0].alive and fab.hosts[1].alive
            with pytest.raises(ServiceUnavailable):
                fut.result(timeout=0)
            # dead host refuses synchronously (the ladder's signal)
            with pytest.raises(ServiceUnavailable, match="dead"):
                fab.hosts[0].submit(_thetas(1)[0], scenario="coherent")
        finally:
            fab.close()


# ---------------------------------------------------------------------------
# store partition → degraded-exact → rejoin
# ---------------------------------------------------------------------------

class TestStorePartition:
    def test_partition_degrades_exact_then_rejoins(self, fabric_plane):
        # register() is store call 0; the first heartbeat's bounded
        # retry burns calls 1,2,3 — all partitioned — then the store
        # heals and call 4 lands
        plan = FaultPlan.from_obj([
            {"site": "store_partition", "kind": "raise", "chunk": k}
            for k in (1, 2, 3)
        ])
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=1, fabric="part",
                      host_kw={0: {"fault_plan": plan,
                                   "partition_retries": 3}})
        host = fab.hosts[0]
        try:
            futs = [fab.submit(t, scenario="coherent")
                    for t in _thetas(4)]
            host.drain()
            clean = [f.result(timeout=0) for f in futs]
            assert all(not r.degraded for r in clean)

            assert not host.heartbeat()  # retries exhausted
            assert host.partitioned

            # admitted tenant: LOUD degraded-exact, not stale-routed
            f = host.submit(_thetas(1)[0], scenario="coherent")
            r = f.result(timeout=0)
            assert r.degraded and r.replica == -1
            assert r.fallback_reason == REASON_STORE_PARTITION
            assert r.host_id == "h0" and np.isfinite(r.value)
            row = host.service.pool("coherent").stats.as_rows()[-1]
            assert row["replica"] == -1 and row["host_id"] == "h0"

            # un-admitted tenant needs the unreachable registry: typed
            with pytest.raises(ServiceUnavailable, match="partitioned"):
                host.submit(_thetas(1)[0], scenario="heavy").result(
                    timeout=0)

            # rejoin is automatic: the next heartbeat lands and serving
            # returns to the fast path
            assert host.heartbeat() and not host.partitioned
            f = fab.submit(_thetas(1)[0], scenario="coherent")
            host.drain()
            assert not f.result(timeout=0).degraded
            assert host.degraded_partition_answers == 1
        finally:
            fab.close()


# ---------------------------------------------------------------------------
# idle-host elastic chunk stealing
# ---------------------------------------------------------------------------

SWEEP_AXES = {"m_chi_GeV": [0.5, 1.0, 2.0], "T_p_GeV": [80.0, 150.0]}
SWEEP_CHUNK = 2
SWEEP_N_Y = 200


def _retry():
    return RetryPolicy(max_attempts=2, backoff_s=0.0, sleep=lambda s: None)


class TestChunkStealing:
    def test_idle_host_drains_queue_bitwise(self, fabric_plane):
        """An idle host steals the whole elastic queue; a later elastic
        fold is 100% warm and bitwise-equal to serial run_sweep — and
        admission pressure stops the stealing within one tick."""
        from bdlz_tpu.parallel.scheduler import (
            LeasePlane,
            ensure_job_record,
            plan_elastic_sweep,
            run_sweep_elastic,
        )
        from bdlz_tpu.parallel.sweep import run_sweep

        base = fabric_plane["base"]
        static = static_choices_from_config(base)
        serial = run_sweep(
            base, SWEEP_AXES, static, mesh=None, chunk_size=SWEEP_CHUNK,
            n_y=SWEEP_N_Y, retry=_retry(),
        )

        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=1, fabric="chunks")
        host = fab.hosts[0]
        try:
            plan = plan_elastic_sweep(
                base, SWEEP_AXES, static, chunk_size=SWEEP_CHUNK,
                n_y=SWEEP_N_Y, retry=_retry(),
            )
            store = fabric_plane["store"]
            ensure_job_record(store, plan)
            leases = LeasePlane(
                store, plan.job, plan.n_chunks, ttl_s=60.0, clock=clock,
            )
            host.attach_sweep(plan, leases)

            # one idle tick = one stolen chunk (steal_chunks_per_tick=1)
            fab.tick()
            assert host.chunks_stolen == 1

            # admission pressure RELEASES the queue: a queued request
            # makes the host non-idle, and the stealing pass yields
            fut = fab.submit(_thetas(1)[0], scenario="coherent")
            assert not host.serving_idle()
            assert host._maybe_steal_chunks() == 0
            host.drain()
            assert fut.result(timeout=0).artifact_hash == (
                fabric_plane["h_a"]
            )

            # idle again: the remaining chunks drain through the ticks
            for _ in range(plan.n_chunks):
                fab.tick()
            assert host.chunks_stolen == plan.n_chunks
            assert all(
                leases.state(ci) == "done" for ci in range(plan.n_chunks)
            )
            assert fab.summary()["hosts"][0]["chunks_stolen"] == (
                plan.n_chunks
            )

            # the committed chunks ARE the sweep: a coordinator folds
            # them 100% warm, bitwise-equal to serial
            res = run_sweep_elastic(
                base, SWEEP_AXES, static, store=store,
                chunk_size=SWEEP_CHUNK, n_y=SWEEP_N_Y, retry=_retry(),
            )
            assert res.cache_hits == plan.n_chunks
            assert res.cache_misses == 0
            for f in serial.outputs:
                assert_bitwise(res.outputs[f], serial.outputs[f], f)
        finally:
            fab.close()


# ---------------------------------------------------------------------------
# real 2-process host-kill failover (tier-2: scripts/slow_suite.sh)
# ---------------------------------------------------------------------------

class TestFabricMP:
    """Whole-host failover across REAL OS processes: a victim host on a
    short wall-clock lease serves a trace and dies without standing
    down; a survivor waits out the dangling lease by TTL arithmetic
    alone, wins the routing, cold-admits the tenant by content hash
    (one pull-through cache miss — a fetch, never a rebuild), and
    answers the same trace bitwise-identically."""

    @pytest.mark.slow
    def test_host_kill_failover_across_processes(self, tmp_path,
                                                 tiny_emulator):
        import json
        import os
        import subprocess
        import sys

        from bdlz_tpu.provenance import Store, publish_artifact

        _, _, art, _ = tiny_emulator
        shared = str(tmp_path / "shared")
        h = publish_artifact(Store(shared), art)
        worker = os.path.join(os.path.dirname(__file__),
                              "_mp_fabric_worker.py")

        def _run(args):
            p = subprocess.run(
                [sys.executable, worker, *args],
                capture_output=True, text=True, timeout=300,
            )
            assert p.returncode == 0, (
                f"{args[0]} violated the fabric contract:\n"
                f"{p.stdout}\n{p.stderr}"
            )
            return json.loads(p.stdout.strip().splitlines()[-1])

        v = _run(["victim", shared, h])
        s = _run(["survivor", shared, h, str(tmp_path / "cache")])
        assert_bitwise(s["values"], v["values"], "survivor values")
        assert s["admissions"] == 1
        assert s["cache"] == {
            "hits": 0, "misses": 1, "corrupt_evictions": 0,
        }


# ---------------------------------------------------------------------------
# fault-site + schema pins (zero-overhead, default OFF)
# ---------------------------------------------------------------------------

class TestFabricPins:
    def test_new_sites_registered(self):
        assert VALID_SITES[-3:] == (
            "host_crash", "heartbeat_loss", "store_partition",
        )

    def test_sites_default_off_zero_overhead(self, fabric_plane):
        # no plan armed → the hooks are never consulted and the served
        # surface is byte-identical to the pre-fabric plane
        clock = _Tick()
        fab = _fabric(fabric_plane, clock, n=1, fabric="off")
        host = fab.hosts[0]
        try:
            assert host._faults is None
            fut = fab.submit(_thetas(1)[0], scenario="coherent")
            host.drain()
            r = fut.result(timeout=0)
            assert not r.degraded and r.fallback_reason is None
            assert r.host_id == "h0"
            s = host.summary()
            assert s["alive"] and not s["partitioned"]
            assert s["heartbeats_lost"] == 0
            assert s["degraded_partition_answers"] == 0
            assert s["service"]["host_id"] == "h0"
        finally:
            fab.close()

    def test_artifact_cache_pull_through(self, fabric_plane, tmp_path,
                                         capsys):
        """The satellite contract: second fetch of the same hash is a
        validated LOCAL hit; a corrupt local entry evicts loudly and
        pull-through refills it."""
        import os

        from bdlz_tpu.provenance import ArtifactCache

        cache = ArtifactCache(str(tmp_path / "pull"))
        store, h = fabric_plane["store"], fabric_plane["h_a"]
        art = cache.fetch(store, h)
        assert art.content_hash == h
        assert cache.counters() == {
            "hits": 0, "misses": 1, "corrupt_evictions": 0,
        }
        assert cache.fetch(store, h).content_hash == h
        assert cache.counters()["hits"] == 1  # local, validated

        npz = os.path.join(
            cache.store.root, "emulator_artifact", h, "artifact.npz"
        )
        with open(npz, "wb") as f:
            f.write(b"bitrot")
        art = cache.fetch(store, h)  # evict loudly, refetch, refill
        assert art.content_hash == h
        assert "corrupt" in capsys.readouterr().err
        assert cache.counters() == {
            "hits": 1, "misses": 2, "corrupt_evictions": 1,
        }
        assert cache.fetch(store, h).content_hash == h
        assert cache.counters()["hits"] == 2
