"""bench.py smoke test: the driver-facing artifact generator must keep
its contract (ONE final JSON line with the metric schema) — regressions
here would silently void a round's benchmark evidence."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def jax_compile_cache(tmp_path_factory):
    """One persistent XLA compilation-cache dir shared by the bench
    SUBPROCESS tests: the leg-cache test's cold round compiles most of
    the suite's programs, and the smoke test's identical-shape programs
    then load from disk instead of recompiling (measured −20 s+ on
    XLA-CPU; verified: only timing fields change — every value field,
    including the NUTS ESS ratio, is bit-identical with and without the
    cache, because the cached artifact IS the compiled program)."""
    d = tmp_path_factory.mktemp("jax_compile_cache")
    return {
        "JAX_COMPILATION_CACHE_DIR": str(d),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.2",
    }


def test_relay_wait_resolution(monkeypatch):
    """The relay wait is configurable and CPU-pinned processes default to
    60 s instead of stalling 600 s for a TPU they never asked for
    (BENCH_r05 relay_waited_s=600.0): flag > BDLZ_RELAY_WAIT_S > legacy
    BDLZ_BENCH_RELAY_WAIT_S > platform-aware default."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_module", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    for env in ("BDLZ_RELAY_WAIT_S", "BDLZ_BENCH_RELAY_WAIT_S"):
        monkeypatch.delenv(env, raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert bench._relay_wait_default() == 60.0
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    assert bench._relay_wait_default() == 600.0
    monkeypatch.setenv("BDLZ_BENCH_RELAY_WAIT_S", "120")  # legacy env
    assert bench._relay_wait_default() == 120.0
    monkeypatch.setenv("BDLZ_RELAY_WAIT_S", "45")  # new env wins
    assert bench._relay_wait_default() == 45.0


def test_relay_probe_cached_once_per_process(monkeypatch):
    """Satellite pin (BENCH_r05: relay_waited_s=600.0 and later legs
    waited AGAIN): the relay verdict is resolved at most once per
    process — a completed wait caches its outcome, and every later
    probe/wait (other bench legs, the backend's ensure_live_backend)
    reuses it without touching the socket."""
    from bdlz_tpu.utils import platform as plat

    probes = []

    def fake_probe(timeout):
        probes.append(timeout)
        return False

    monkeypatch.setattr(plat, "_probe_relay", fake_probe)
    plat.reset_relay_cache()
    try:
        assert plat.wait_for_relay(max_wait_s=0.0) is False
        assert len(probes) == 1
        # later legs: no re-probe, no re-wait — cached verdict
        assert plat.wait_for_relay(max_wait_s=600.0) is False
        assert plat.axon_relay_alive() is False
        assert len(probes) == 1
        # reset re-admits a recovered relay
        plat.reset_relay_cache()
        monkeypatch.setattr(plat, "_probe_relay", lambda t: True)
        assert plat.axon_relay_alive() is True
        assert plat.wait_for_relay(max_wait_s=0.0) is True
    finally:
        plat.reset_relay_cache()


@pytest.mark.slow
def test_bench_leg_cache_replays_cpu_round(tmp_path, jax_compile_cache):
    """Opportunistic-bench satellite (docs/provenance.md): a degraded
    round's CPU legs are keyed by provenance identity and replayed on
    the next degraded round with ``"cached": true`` on every reused
    metric line — r03–r05 re-paid the full CPU suite after every relay
    death.  Forced on here via the test-only BDLZ_BENCH_LEG_CACHE=force
    (production arms it only when tpu_unavailable)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BDLZ_BENCH_") and k != "BDLZ_FAULT_PLAN"}
    env.update(
        BDLZ_BENCH_PLATFORM="cpu",
        BDLZ_BENCH_POINTS="256", BDLZ_BENCH_CHUNK="256",
        BDLZ_BENCH_NY="2000", BDLZ_BENCH_GATE_POINTS="12",
        BDLZ_BENCH_ODE_POINTS="16", BDLZ_BENCH_LZ_POINTS="256",
        BDLZ_BENCH_LZ_TABLE_N="256", BDLZ_BENCH_EMU_QUERIES="2048",
        BDLZ_BENCH_EMU_EXACT_POINTS="32", BDLZ_BENCH_CHAOS_POINTS="16",
        BDLZ_BENCH_SERVE_QUERIES="1024", BDLZ_BENCH_SERVE_BATCH="256",
        BDLZ_BENCH_SERVE_LAT_QUERIES="256",
        BDLZ_BENCH_CHAOS_SERVE_QUERIES="384",
        BDLZ_BENCH_CHAOS_SERVE_BATCH="16",
        # tiny multi-tenant leg: three pools (coherent/chain/thermal)
        # with the evict→degrade→readmit trace still run end to end
        BDLZ_BENCH_MT_BATCH="8", BDLZ_BENCH_MT_TICKS="8",
        BDLZ_BENCH_MT_NY="200", BDLZ_BENCH_MT_GRID="2",
        # tiny cross-host leg: the 2-host kill→failover→readmit trace
        # still runs end to end
        BDLZ_BENCH_XH_BATCH="8", BDLZ_BENCH_XH_TICKS="8",
        # tiny seam leg: the split/build/serve machinery still runs,
        # but no acceptance numbers are asserted on THIS test (replay
        # equality is)
        BDLZ_BENCH_SEAM_NY="200", BDLZ_BENCH_SEAM_ROUNDS="2",
        BDLZ_BENCH_SEAM_RTOL="1e-3", BDLZ_BENCH_SEAM_QUERIES="64",
        BDLZ_BENCH_SEAM_EXACT="16",
        # tiny gradient/NUTS legs: the machinery runs, replay equality
        # is what THIS test asserts (the >=5x ESS acceptance is pinned
        # in the smoke test at the leg's real sizes)
        BDLZ_BENCH_GRAD_POINTS="256", BDLZ_BENCH_GRAD_CHUNK="256",
        BDLZ_BENCH_NUTS_WALKERS="8", BDLZ_BENCH_NUTS_STRETCH_STEPS="64",
        BDLZ_BENCH_NUTS_CHAINS="2", BDLZ_BENCH_NUTS_STEPS="32",
        BDLZ_BENCH_NUTS_WARMUP="16",
        # tiny bounce leg: the gate audit + a 2-spec batch/scalar A/B
        # still run; replay equality is what THIS test asserts
        BDLZ_BENCH_BOUNCE_POINTS="2",
        # tiny self_improve leg: the full closed loop (drift → elastic
        # traffic-steered rebuild → auto-publish) still runs
        BDLZ_BENCH_SI_QUERIES="64", BDLZ_BENCH_SI_BATCH="8",
        BDLZ_BENCH_SI_NY="200",
        BDLZ_BENCH_LEG_CACHE="force",
        BDLZ_CACHE_ROOT=str(tmp_path / "store"),
        PYTHONPATH=REPO,
        **jax_compile_cache,
    )

    def bench_round():
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, env=env, timeout=600,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return [json.loads(ln) for ln in out.stdout.strip().splitlines()]

    first = bench_round()
    assert all("cached" not in d for d in first)   # cold round: measured
    second = bench_round()
    # every line of the second round is a replay, main line included,
    # with values identical to the measured round's
    assert all(d.get("cached") is True for d in second)
    by_metric = {d["metric"]: d for d in first}
    for d in second:
        ref = by_metric[d["metric"]]
        assert {k: v for k, v in d.items() if k != "cached"} == ref, d["metric"]


# slow (with the leg-cache replay test above): the two dominate the
# tier-1 wall — 109 s + 105 s of a 977 s run on the 2026-08 durations
# table — and they gate the bench HARNESS, not the product; every
# product behavior they drive end to end (serving, seam split, NUTS,
# bounce, closed-loop refinement) has its own fast tier-1 pins.
# `pytest -m slow tests/test_bench.py` runs them.
@pytest.mark.slow
def test_bench_cpu_smoke(jax_compile_cache):
    # drop any inherited bench knobs so a developer's exported overrides
    # (BDLZ_BENCH_IMPL etc.) cannot change what this test asserts
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BDLZ_BENCH_") and k != "BDLZ_FAULT_PLAN"}
    env.update(
        BDLZ_BENCH_PLATFORM="cpu",
        BDLZ_BENCH_POINTS="256",
        BDLZ_BENCH_CHUNK="256",
        BDLZ_BENCH_NY="2000",
        # small audit-style gate population: the smoke test exercises the
        # population gate's machinery, not its full 128-point cost
        BDLZ_BENCH_GATE_POINTS="24",
        # tiny secondary legs (they now run on EVERY platform)
        BDLZ_BENCH_ODE_POINTS="16",
        BDLZ_BENCH_LZ_POINTS="256",
        BDLZ_BENCH_LZ_TABLE_N="256",
        # small emulator leg: the box still exercises real refinement
        # (sigma_y), but queries/exact-sample sizes stay smoke-sized
        BDLZ_BENCH_EMU_QUERIES="2048",
        BDLZ_BENCH_EMU_EXACT_POINTS="64",
        # tiny chaos leg: the fault plan + healing machinery still runs
        BDLZ_BENCH_CHAOS_POINTS="16",
        # small serve_bench leg: the fleet/routing/overload machinery
        # still runs (1-replica + 4-replica streams, latency pump,
        # canned overload trace) at smoke size
        BDLZ_BENCH_SERVE_QUERIES="2048",
        BDLZ_BENCH_SERVE_BATCH="256",
        BDLZ_BENCH_SERVE_LAT_QUERIES="512",
        # small chaos_serve leg: 24 fake-clock batches — enough trace
        # for the full breaker trip → failed probes → heal → re-close
        # choreography the acceptance asserts below pin
        BDLZ_BENCH_CHAOS_SERVE_QUERIES="384",
        BDLZ_BENCH_CHAOS_SERVE_BATCH="16",
        # small serve_multitenant leg: three scenario pools, chain-pool
        # replica faults + one forced eviction — the availability /
        # bit-parity / eviction-choreography acceptance asserts below
        # pin this exact line
        BDLZ_BENCH_MT_BATCH="8",
        BDLZ_BENCH_MT_TICKS="8",
        BDLZ_BENCH_MT_NY="200",
        BDLZ_BENCH_MT_GRID="2",
        # small serve_crosshost leg: a 2-host fabric with host 0
        # killed mid-trace — the availability / typed-loss / failover
        # / fetch-not-rebuild readmission acceptance asserts below pin
        # this exact line
        BDLZ_BENCH_XH_BATCH="8",
        BDLZ_BENCH_XH_TICKS="8",
        # the seam_split leg at its ACCEPTANCE settings (rtol 1e-4,
        # full round budget): the >=10x fallback ratio and the <=1e-3
        # gated-agreement are asserted below on this exact line
        BDLZ_BENCH_SEAM_NY="200",
        BDLZ_BENCH_SEAM_QUERIES="512",
        BDLZ_BENCH_SEAM_EXACT="128",
        # the grad_sweep leg at smoke point count (FD parity is pinned
        # below regardless of size); the NUTS leg at smoke-trimmed but
        # ACCEPTANCE-valid sizes — the >=5x ESS-per-eval criterion is
        # asserted on this exact line (measured 6.2x at these settings)
        BDLZ_BENCH_GRAD_POINTS="256",
        BDLZ_BENCH_GRAD_CHUNK="256",
        BDLZ_BENCH_NUTS_STEPS="256",
        BDLZ_BENCH_NUTS_WARMUP="120",
        BDLZ_BENCH_NUTS_STRETCH_STEPS="320",
        # small bounce_sweep leg: the validation gate + the batched
        # vs scalar-loop A/B still run on a 2-spec eps scan — the
        # gate residuals and parity are asserted below regardless of
        # batch size (the gate itself shoots the reference potential)
        BDLZ_BENCH_BOUNCE_POINTS="2",
        # the self_improve leg at smoke size: one autonomous cycle of
        # the closed loop (8 fake-clock batches per hour) — the hour-2
        # < hour-1 gated-fallback drop and the unaffected-region
        # bitwise pin are asserted below on this exact line
        BDLZ_BENCH_SI_QUERIES="64",
        BDLZ_BENCH_SI_BATCH="8",
        BDLZ_BENCH_SI_NY="200",
        PYTHONPATH=REPO,
        **jax_compile_cache,
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    # the driver parses the FINAL stdout line as the metric
    last = out.stdout.strip().splitlines()[-1]
    d = json.loads(last)
    assert d["metric"] == "sweep_points_per_sec_per_chip"
    assert d["value"] > 0
    assert {"unit", "vs_baseline", "n_points", "impl", "platform",
            "rel_err_vs_reference", "pallas_preflight"} <= set(d)
    assert d["platform"] == "cpu"
    assert d["impl"] == "tabulated"  # pallas is TPU-only by default
    assert d["rel_err_vs_reference"] <= 1e-6
    assert d["gate_points"] == 24  # the audit-style population ran
    # the y-quadrature resolution: the bench grid is smooth (no T=m/3
    # seam in-window), so the audit must admit the panel-GL fast path,
    # and every sweep metric line names the scheme it ran
    assert d["quad_impl"] == "panel_gl"
    from bdlz_tpu.solvers.panels import (
        N_PANELS_DEFAULT,
        NODES_PER_PANEL_DEFAULT,
    )

    assert d["n_quad_nodes"] == N_PANELS_DEFAULT * NODES_PER_PANEL_DEFAULT
    # the quad_gl A/B summary round-trips between the main JSON and the
    # sub-metric line, and carries the acceptance numbers: a measured
    # speedup over the trapezoid at <=1e-9 agreement with it on this
    # (smooth) grid, with the panel path's own gate error on the line
    assert d["quad_gl"] is not None
    # full engine coverage even on CPU (VERDICT r4 weak #4): all three
    # secondary legs must carry numbers, flagged with their platform
    assert d["lz_sweep_points_per_sec_per_chip"] > 0
    assert d["lz_coherent_sweep_points_per_sec_per_chip"] > 0
    # the LZ scenario-plane legs (docs/scenarios.md) carry numbers too
    assert d["lz_chain_sweep_points_per_sec_per_chip"] > 0
    assert d["lz_thermal_sweep_points_per_sec_per_chip"] > 0
    assert d["esdirk_points_per_sec_per_chip"] > 0
    secondary = [json.loads(ln) for ln in out.stdout.strip().splitlines()[:-1]]
    names = {s["metric"] for s in secondary}
    assert {"esdirk_sweep_points_per_sec_per_chip",
            "lz_sweep_points_per_sec_per_chip",
            "lz_coherent_sweep_points_per_sec_per_chip",
            "lz_chain_sweep_points_per_sec_per_chip",
            "lz_thermal_sweep_points_per_sec_per_chip",
            "emulator_query_points_per_sec",
            "quad_gl_sweep_points_per_sec_per_chip",
            "chaos_sweep_points_per_sec_per_chip",
            "sweep_churn_points_per_sec",
            "sweep_cache_warm_vs_cold",
            "seam_split_fallback_ratio",
            "serve_bench_queries_per_sec_per_chip",
            "chaos_serve_availability",
            "serve_multitenant_availability",
            "serve_crosshost_availability",
            "grad_sweep_points_per_sec_per_chip",
            "bounce_profiles_per_sec_per_chip",
            "self_improve_gated_rate",
            "nuts_ess_per_eval"} <= names
    # robustness schema: every sweep metric line carries the failure
    # counters (nulls where the leg has no healing path), main line
    # included
    assert {"n_failed", "n_quarantined", "n_retries"} <= set(d)
    for s in secondary:
        if s["metric"] in ("emulator_query_points_per_sec",
                           "serve_bench_queries_per_sec_per_chip",
                           "seam_split_fallback_ratio",
                           "chaos_serve_availability",
                           "serve_multitenant_availability",
                           "serve_crosshost_availability",
                           "nuts_ess_per_eval"):
            continue  # query/serving/sampler metrics, not sweep lines
        assert {"n_failed", "n_quarantined", "n_retries"} <= set(s), s["metric"]
    # the scenario-plane legs (docs/scenarios.md): mode, gate residuals
    # and the vs-two-channel throughput ratio ride each line; the chain
    # gate pins the N=2 reduction at the acceptance tolerance and the
    # thermal gate's cold limit is bitwise by construction
    ch = next(s for s in secondary
              if s["metric"] == "lz_chain_sweep_points_per_sec_per_chip")
    assert ch["lz_mode"] == "chain" and ch["lz_n_levels"] >= 2
    assert ch["gate_n2_vs_coherent"] <= 1e-12
    assert ch["gate_analytic_flat_band"] <= 1e-10
    assert "vs_two_channel" in ch
    th = next(s for s in secondary
              if s["metric"] == "lz_thermal_sweep_points_per_sec_per_chip")
    assert th["lz_mode"] == "thermal"
    assert th["gate_cold_limit_bitwise"] is True
    assert th["gate_monotonicity_defect"] <= 0.0
    assert "vs_two_channel" in th
    # the chaos line: healed sweep under the canned fault plan — the
    # injected poison point is quarantined, the NaN point masked, the
    # transient chunk retried, and every unaffected point bit-identical
    # to the clean run
    chaos = next(s for s in secondary
                 if s["metric"] == "chaos_sweep_points_per_sec_per_chip")
    assert chaos["value"] > 0
    assert chaos["n_quarantined"] == 1
    assert chaos["n_failed"] == 2          # poison (quarantined) + NaN point
    assert chaos["n_retries"] >= 1
    assert chaos["bitwise_equal_unaffected"] is True
    assert chaos["clean_points_per_sec_per_chip"] > 0
    assert {"site", "kind"} <= set(chaos["fault_plan"][0])
    assert d["chaos"] == {
        "value": chaos["value"],
        "vs_clean": chaos["vs_clean"],
        "n_failed": chaos["n_failed"],
        "n_quarantined": chaos["n_quarantined"],
        "n_retries": chaos["n_retries"],
        "bitwise_equal_unaffected": chaos["bitwise_equal_unaffected"],
    }
    # the sweep_churn line: the elastic work-stealing fleet under churn
    # (worker crash + flaky lease + torn store read + scripted
    # kill/spawn) heals everything — nothing failed, nothing
    # quarantined — and the folded result is BITWISE-equal to the
    # serial single-host engine, the contract the scheduler exists for
    churn = next(s for s in secondary
                 if s["metric"] == "sweep_churn_points_per_sec")
    assert churn["value"] > 0
    assert churn["bitwise_equal"] is True
    assert churn["n_failed"] == 0
    assert churn["n_quarantined"] == 0
    assert churn["serial_points_per_sec"] > 0
    assert churn["vs_serial"] > 0
    assert churn["n_workers"] == 2
    assert {"site", "kind"} <= set(churn["churn_plan"][0])
    assert d["sweep_churn"] == {
        "value": churn["value"],
        "vs_serial": churn["vs_serial"],
        "n_failed": churn["n_failed"],
        "n_quarantined": churn["n_quarantined"],
        "n_retries": churn["n_retries"],
        "bitwise_equal": churn["bitwise_equal"],
    }
    # the sweep_cache line (docs/provenance.md): a warm rebuild of the
    # same emulator box through the content-addressed chunk cache must
    # beat the cold build by the acceptance margin with EVERY chunk
    # served from the store and a BIT-identical surface — caching that
    # changes a single bit is corruption, not caching
    sc = next(s for s in secondary
              if s["metric"] == "sweep_cache_warm_vs_cold")
    assert sc["bitwise_equal"] is True
    assert sc["hit_rate"] == 1.0 and sc["cache_misses"] == 0
    assert sc["cache_hits"] > 0
    assert sc["value"] >= 20          # the acceptance-criterion speedup
    assert sc["cold_seconds"] > sc["warm_seconds"]
    assert d["sweep_cache"] == {
        "value": sc["value"],
        "cold_seconds": sc["cold_seconds"],
        "warm_seconds": sc["warm_seconds"],
        "cache_hits": sc["cache_hits"],
        "cache_misses": sc["cache_misses"],
        "hit_rate": sc["hit_rate"],
        "bitwise_equal": sc["bitwise_equal"],
    }
    # provenance schema: cache counters ride every sweep metric line
    # (nulls where the leg bypasses the chunk cache), main line included
    assert {"cache_hits", "cache_misses"} <= set(d)
    for s in secondary:
        if s["metric"] in ("emulator_query_points_per_sec",
                           "serve_bench_queries_per_sec_per_chip",
                           "seam_split_fallback_ratio",
                           "chaos_serve_availability",
                           "serve_multitenant_availability",
                           "serve_crosshost_availability",
                           "nuts_ess_per_eval"):
            continue
        assert {"cache_hits", "cache_misses"} <= set(s), s["metric"]
    # a plain (relay-up / forced-cpu) round never reuses cached legs
    assert "cached" not in d
    assert all("cached" not in s for s in secondary)
    quad = next(s for s in secondary
                if s["metric"] == "quad_gl_sweep_points_per_sec_per_chip")
    assert {"value", "vs_trapezoid", "trapezoid_points_per_sec_per_chip",
            "rel_err_vs_reference", "scheme_vs_trapezoid_rel_err",
            "resolved_on", "audit", "quad_impl", "n_quad_nodes",
            "platform"} <= set(quad)
    assert quad["quad_impl"] == "panel_gl"
    assert quad["resolved_on"] is True
    assert quad["audit"]["ok"] is True
    # the panel rule must beat the trapezoid it replaces even at this
    # smoke n_y=2000 (at the production n_y=8000 the node cut is ~14x)
    assert quad["vs_trapezoid"] >= 1.5
    # ... while agreeing with it to the acceptance tolerance on the
    # smooth bench grid, and passing its own equal-scheme gate
    assert quad["scheme_vs_trapezoid_rel_err"] <= 1e-9
    assert quad["rel_err_vs_reference"] <= 1e-9
    assert d["quad_gl"] == {
        "value": quad["value"],
        "vs_trapezoid": quad["vs_trapezoid"],
        "rel_err_vs_reference": quad["rel_err_vs_reference"],
        "scheme_vs_trapezoid_rel_err": quad["scheme_vs_trapezoid_rel_err"],
        "resolved_on": quad["resolved_on"],
    }
    # every sweep metric line records its quadrature (nulls on the stiff
    # line, where no y-quadrature exists)
    for s in secondary:
        if s["metric"].startswith("lz_"):
            assert s["quad_impl"] == d["quad_impl"]
            assert s["n_quad_nodes"] == d["n_quad_nodes"]
        if s["metric"].startswith("esdirk_"):
            assert s["quad_impl"] is None and s["n_quad_nodes"] is None
    # the emulator metric schema round-trips: secondary line fields and
    # the main JSON's "emulator" summary must agree, the build must hit
    # its default tolerance on the held-out set, and batched queries
    # must beat the exact per-point path by >= 100x (the serving claim)
    emu = next(s for s in secondary
               if s["metric"] == "emulator_query_points_per_sec")
    assert {"value", "build_seconds", "refinement_rounds", "n_exact_evals",
            "grid_points", "rtol_target", "max_rel_err", "spot_rel_err",
            "converged", "exact_points_per_sec", "vs_exact",
            "platform"} <= set(emu)
    assert emu["converged"] is True
    assert emu["max_rel_err"] <= emu["rtol_target"] == 1e-4
    assert emu["spot_rel_err"] <= 1e-4      # independent of the build's gate
    assert emu["refinement_rounds"] >= 2    # the adaptive loop actually ran
    assert emu["vs_exact"] >= 100
    assert d["emulator"] == {
        "build_seconds": emu["build_seconds"],
        "refinement_rounds": emu["refinement_rounds"],
        "max_rel_err": emu["max_rel_err"],
        "converged": emu["converged"],
        "vs_exact": emu["vs_exact"],
        "query_points_per_sec": emu["value"],
    }
    # the serve_bench line (docs/serving.md schema): fleet throughput +
    # replica scaling measured on the SAME request stream with
    # bit-identical responses, request-plane latency percentiles, and
    # the deterministic shed rate of the canned overload trace — with
    # the main JSON's "serve" summary round-tripping the headline fields
    srv = next(s for s in secondary
               if s["metric"] == "serve_bench_queries_per_sec_per_chip")
    assert {"value", "qps", "single_replica_qps", "replica_scaling",
            "bit_identical_across_replicas", "n_replicas",
            "n_replica_devices", "host_cores", "warmup_seconds",
            "routing", "artifact_hash", "p50_latency_s", "p99_latency_s",
            "mean_occupancy", "shed_rate", "admission_rejects",
            "deadline_kills", "overload_offered", "platform",
            "tpu_unavailable"} <= set(srv)
    assert srv["value"] > 0 and srv["qps"] > 0
    assert srv["n_replicas"] == 4          # min(4, the 8-device mesh)
    assert srv["n_replica_devices"] == 4
    # the acceptance bit-parity contract: 4 replicas, same stream, same
    # bits (wall-clock scaling is a hardware property — bounded by
    # host_cores on the CPU fallback — so it is recorded, not pinned)
    assert srv["bit_identical_across_replicas"] is True
    assert srv["replica_scaling"] > 0
    assert srv["warmup_seconds"] > 0
    assert srv["p50_latency_s"] is not None
    assert srv["p99_latency_s"] is not None
    assert srv["p99_latency_s"] >= srv["p50_latency_s"]
    # the canned overload trace MUST shed (it offers 8 full queue
    # bounds against one dispatch per burst) but never everything
    assert 0.0 < srv["shed_rate"] < 1.0
    assert srv["admission_rejects"] > 0
    assert len(srv["artifact_hash"]) == 16
    assert d["serve"] == {
        "value": srv["value"],
        "qps": srv["qps"],
        "replica_scaling": srv["replica_scaling"],
        "p50_latency_s": srv["p50_latency_s"],
        "p99_latency_s": srv["p99_latency_s"],
        "shed_rate": srv["shed_rate"],
        "bit_identical_across_replicas": srv[
            "bit_identical_across_replicas"
        ],
    }
    # the chaos_serve line (docs/robustness.md "Replica health plane"):
    # the canned single-replica fault trace on a 2-replica fleet — the
    # acceptance criteria checked on the line itself: availability
    # >= 0.99 over the trace, every answer bit-identical to the clean
    # run (healed batches re-run the same fused kernel), and the
    # breaker re-closed after its half-open probe, with the recovery
    # span recorded in fake-clock seconds
    cs = next(s for s in secondary
              if s["metric"] == "chaos_serve_availability")
    assert {"value", "n_requests", "n_replicas", "host_cores",
            "p50_latency_s", "p99_latency_s", "breaker_opens",
            "breaker_reclosed", "recovery_s", "healed_batches",
            "degraded_batches", "bitwise_equal_unaffected",
            "wall_seconds", "fault_plan", "artifact_hash", "platform",
            "tpu_unavailable"} <= set(cs)
    assert cs["value"] >= 0.99
    assert cs["bitwise_equal_unaffected"] is True
    assert cs["breaker_reclosed"] is True
    assert cs["breaker_opens"] >= 1
    assert cs["recovery_s"] > 0
    assert cs["healed_batches"] >= 1
    assert cs["degraded_batches"] == 0     # one healthy replica remained
    assert cs["n_replicas"] == 2
    assert cs["p99_latency_s"] is not None
    assert {"site", "kind"} <= set(cs["fault_plan"][0])
    assert d["chaos_serve"] == {
        "value": cs["value"],
        "p99_latency_s": cs["p99_latency_s"],
        "recovery_s": cs["recovery_s"],
        "breaker_opens": cs["breaker_opens"],
        "breaker_reclosed": cs["breaker_reclosed"],
        "healed_batches": cs["healed_batches"],
        "bitwise_equal_unaffected": cs["bitwise_equal_unaffected"],
    }
    # the serve_multitenant line (docs/serving.md "Multi-tenant plane"):
    # three scenario-routed artifact pools through the canned chaos
    # trace — chain-pool replica faults healed in place, the coherent
    # pool force-evicted mid-trace (its answers degrade LOUDLY to the
    # exact path, never silently), then readmitted by hash — with
    # every per-pool answer bit-identical to a single-tenant fleet
    mt = next(s for s in secondary
              if s["metric"] == "serve_multitenant_availability")
    assert {"value", "n_requests", "n_pools", "scenarios", "qps_per_chip",
            "per_pool", "shed_rate", "cold_admission_s", "readmit_s",
            "degraded_answers", "evictions", "forced_evictions",
            "admissions", "readmissions", "autoscale_passes", "resizes",
            "replica_budget", "tenant_routing",
            "bitwise_equal_unaffected", "fault_plan", "build_seconds",
            "wall_seconds", "platform", "tpu_unavailable"} <= set(mt)
    assert mt["value"] >= 0.99
    assert mt["bitwise_equal_unaffected"] is True
    assert mt["n_pools"] == 3
    assert set(mt["scenarios"]) == {"coherent", "chain", "thermal"}
    # the eviction choreography: exactly one forced eviction (the
    # armed pool_evict fault), answered through the degraded exact
    # path, then one cold readmission by content hash
    assert mt["forced_evictions"] == 1
    assert mt["evictions"] == 1
    assert mt["degraded_answers"] > 0
    assert mt["readmissions"] == 1
    assert mt["readmit_s"] is not None
    assert mt["admissions"] == 3           # one cold admission per pool
    assert set(mt["cold_admission_s"]) == {"coherent", "chain", "thermal"}
    assert all(v > 0 for v in mt["cold_admission_s"].values())
    assert mt["autoscale_passes"] >= 1
    assert mt["qps_per_chip"] > 0
    assert {"site", "kind"} <= set(mt["fault_plan"][0])
    for scn, p in mt["per_pool"].items():
        assert len(p["artifact_hash"]) == 16
        assert p["n_replicas"] >= 1
        assert p["p50_latency_s"] is not None, scn
        assert p["p99_latency_s"] is not None, scn
        assert p["p99_latency_s"] >= p["p50_latency_s"], scn
    assert mt["per_pool"]["chain"]["lz_mode"] == "chain"
    assert mt["per_pool"]["thermal"]["lz_mode"] == "thermal"
    # only the evicted pool served degraded answers; it was readmitted
    # before the trace ended, so it is resident again at summary time
    assert mt["per_pool"]["coherent"]["evicted"] is False
    assert d["serve_multitenant"] == {
        "value": mt["value"],
        "qps_per_chip": mt["qps_per_chip"],
        "shed_rate": mt["shed_rate"],
        "cold_admission_s": mt["cold_admission_s"],
        "readmit_s": mt["readmit_s"],
        "degraded_answers": mt["degraded_answers"],
        "forced_evictions": mt["forced_evictions"],
        "autoscale_passes": mt["autoscale_passes"],
        "bitwise_equal_unaffected": mt["bitwise_equal_unaffected"],
    }
    # the serve_crosshost line (docs/serving.md "Cross-host fabric"):
    # host 0 of a 2-host fabric killed mid-trace — queued work fails
    # TYPED and client retries re-answer through the submit ladder on
    # the survivor, which cold-admits the tenant from the registry by
    # content hash (one pull-through cache miss, never a rebuild), with
    # every answer bitwise-equal to a clean single-host fleet
    xh = next(s for s in secondary
              if s["metric"] == "serve_crosshost_availability")
    assert {"value", "n_requests", "n_hosts", "kill_tick",
            "host_lease_ttl_s", "typed_losses", "untyped_losses",
            "failovers", "failover_latency_s", "answered_by",
            "survivor_admissions", "survivor_cache", "readmit_was_fetch",
            "bitwise_equal_unaffected", "fault_plan", "wall_seconds",
            "platform", "tpu_unavailable"} <= set(xh)
    assert xh["value"] >= 0.99
    assert xh["n_hosts"] == 2
    assert xh["untyped_losses"] == 0       # loss is TYPED or nothing
    assert xh["typed_losses"] > 0          # the kill actually bit
    assert xh["failovers"] >= 1            # the ladder actually walked
    assert xh["failover_latency_s"] is not None
    assert xh["answered_by"]["h0"] > 0 and xh["answered_by"]["h1"] > 0
    assert xh["survivor_admissions"] == 1  # one cold admission, by hash
    assert xh["readmit_was_fetch"] is True
    assert xh["survivor_cache"]["misses"] == 1
    assert xh["bitwise_equal_unaffected"] is True
    assert {"site", "kind"} <= set(xh["fault_plan"][0])
    assert d["serve_crosshost"] == {
        "value": xh["value"],
        "typed_losses": xh["typed_losses"],
        "untyped_losses": xh["untyped_losses"],
        "failovers": xh["failovers"],
        "failover_latency_s": xh["failover_latency_s"],
        "survivor_admissions": xh["survivor_admissions"],
        "readmit_was_fetch": xh["readmit_was_fetch"],
        "bitwise_equal_unaffected": xh["bitwise_equal_unaffected"],
    }
    # the self_improve line (ROADMAP item 4's acceptance, checked on the
    # line itself): after ONE autonomous traffic-steered rebuild+rollout
    # cycle the hour-2 gated-fallback rate of the replayed drifted trace
    # drops below hour 1 (>=2x at these smoke sizes), the daemon
    # promoted its candidate, and the far-out-of-domain probe answered
    # bit-identically before and after the rollout
    si = next(s for s in secondary
              if s["metric"] == "self_improve_gated_rate")
    assert {"value", "n_requests", "gated_fallback_hour1",
            "gated_fallback_hour2", "gated_rate_hour1", "gated_rate_hour2",
            "cycles", "daemon_state", "drift_gated_rate", "rebuild_budget",
            "snapshot", "train_snapshot", "decision", "seed_hash",
            "serving_hash", "elastic", "bitwise_equal_unaffected",
            "wall_seconds", "platform", "tpu_unavailable"} <= set(si)
    assert si["cycles"] == 1
    assert si["gated_fallback_hour1"] > 0.2      # the seed box was wrong
    assert si["gated_fallback_hour2"] < si["gated_fallback_hour1"] / 2
    assert si["value"] == si["gated_fallback_hour2"]
    assert si["decision"]["outcome"] == "promoted"
    assert si["decision"]["candidate_score"] < si["decision"][
        "serving_score"]
    assert si["serving_hash"] != si["seed_hash"]  # the rollout landed
    assert len(si["snapshot"]) == 16 and len(si["train_snapshot"]) == 16
    assert si["bitwise_equal_unaffected"] is True
    assert d["self_improve"] == {
        "value": si["value"],
        "gated_fallback_hour1": si["gated_fallback_hour1"],
        "gated_fallback_hour2": si["gated_fallback_hour2"],
        "cycles": si["cycles"],
        "daemon_state": si["daemon_state"],
        "bitwise_equal_unaffected": si["bitwise_equal_unaffected"],
    }
    # the seam_split line (the PR's acceptance criteria, checked on the
    # line itself): on a deterministic seam-crossing trace the
    # split+gated bundle's exact-fallback rate is >=10x below the
    # single-domain artifact's at equal tolerance, the answers the
    # gated service serves agree with the exact engine to <=1e-3, and
    # the build A/B shows the split reaching <=1e-4 held-out with FEWER
    # exact sweep points than the (unconverged) single-domain build
    seam = next(s for s in secondary
                if s["metric"] == "seam_split_fallback_ratio")
    assert {"seam_band", "n_trace", "fallback_rate_split_gated",
            "fallback_rate_split_ungated", "fallback_rate_single_gated",
            "fallback_rate_single_ungated", "qps_split_gated",
            "qps_single_gated", "gated_vs_exact_max_rel_err",
            "ungated_single_vs_exact_max_rel_err", "split_n_exact_evals",
            "single_n_exact_evals", "split_held_out_max_rel_err",
            "single_held_out_max_rel_err", "split_converged",
            "bundle_hash", "n_domains"} <= set(seam)
    assert seam["value"] >= 10
    assert seam["fallback_rate_single_gated"] >= (
        10 * seam["fallback_rate_split_gated"]
    )
    assert seam["gated_vs_exact_max_rel_err"] <= 1e-3
    assert seam["split_converged"] is True
    assert seam["split_held_out_max_rel_err"] <= 1e-4
    assert seam["single_converged"] is False
    assert seam["split_n_exact_evals"] < seam["single_n_exact_evals"]
    assert seam["n_domains"] == 2
    assert seam["seam_band"]["axis"] == "m_chi_GeV"
    # the split artifact still pays SOME fallback (the seam band itself)
    assert seam["fallback_rate_split_gated"] > 0
    # ... and the ungated single-domain surface would serve seam
    # queries WRONG — the number the gate exists to prevent
    assert seam["ungated_single_vs_exact_max_rel_err"] > 1e-3
    assert d["seam_split"] == {
        "value": seam["value"],
        "fallback_rate_split_gated": seam["fallback_rate_split_gated"],
        "fallback_rate_single_gated": seam["fallback_rate_single_gated"],
        "gated_vs_exact_max_rel_err": seam["gated_vs_exact_max_rel_err"],
        "split_n_exact_evals": seam["split_n_exact_evals"],
        "single_n_exact_evals": seam["single_n_exact_evals"],
        "split_held_out_max_rel_err": seam["split_held_out_max_rel_err"],
        "single_held_out_max_rel_err": seam[
            "single_held_out_max_rel_err"
        ],
        "split_converged": seam["split_converged"],
    }
    for s in secondary:
        assert s["platform"] == "cpu"
        assert "tpu_unavailable" in s
    # the stiff metric carries the lockstep A/B: repacked throughput,
    # the speedup ratio, the engines' mutual drift, AND both engines'
    # Radau spot accuracy ("3x at equal rel_err" needs all four fields)
    ode = next(s for s in secondary
               if s["metric"] == "esdirk_sweep_points_per_sec_per_chip")
    # stiff drift satellite: the line names its engine + grid size (and
    # the grid default is pinned at 1024 — overridden to 16 here via the
    # legacy BDLZ_BENCH_ODE_POINTS env, which must keep working)
    assert ode["engine"] == "esdirk"
    assert ode["lockstep_engine"] == "esdirk_lockstep"
    assert ode["n_points"] == 16
    assert ode["value"] > 0 and ode["lockstep_points_per_sec_per_chip"] > 0
    assert ode["vs_lockstep"] == pytest.approx(
        ode["value"] / ode["lockstep_points_per_sec_per_chip"], rel=0.05
    )
    # null is bench's documented "not measured" sentinel (Radau spot
    # failure / all-NaN lanes); on the CPU smoke grid every spot must
    # actually measure, so fail with the real signal, not a TypeError
    for key in ("rel_err_vs_lockstep", "rel_err_vs_reference",
                "lockstep_rel_err_vs_reference"):
        assert ode[key] is not None, f"{key} unmeasured (null) on smoke grid"
        assert ode[key] <= 1e-6, (key, ode[key])
    assert ode["compaction"]["rounds"] >= 1
    assert ode["compaction"]["lanes_retired"] >= ode["n_points"]
    # the grad_sweep line (the differentiable pipeline): reverse-mode
    # d(Omega_DM/Omega_b)/dtheta throughput with the FD parity of the
    # Planck log-posterior gradient measured ON the line — the
    # tentpole's <= 1e-5 acceptance, checked every round
    gs = next(s for s in secondary
              if s["metric"] == "grad_sweep_points_per_sec_per_chip")
    assert {"value", "n_points", "n_params", "seconds",
            "forward_points_per_sec_per_chip", "vs_forward",
            "fd_max_rel_err", "impl", "quad_impl", "n_quad_nodes",
            "platform", "tpu_unavailable"} <= set(gs)
    assert gs["value"] > 0
    assert gs["n_params"] == 4
    assert gs["fd_max_rel_err"] <= 1e-5
    assert gs["quad_impl"] == d["quad_impl"]
    assert d["grad_sweep"] == {
        "value": gs["value"],
        "vs_forward": gs["vs_forward"],
        "fd_max_rel_err": gs["fd_max_rel_err"],
    }
    # the nuts_ess_per_eval line (gradient-based inference): NUTS vs
    # stretch bulk-ESS per logp evaluation on the round's
    # emulator-backed Planck posterior — the >=5x acceptance criterion
    # is asserted on the line itself, warmup bill included in the
    # NUTS denominator
    nuts = next(s for s in secondary if s["metric"] == "nuts_ess_per_eval")
    assert {"value", "params", "nuts_ess", "nuts_evals",
            "nuts_ess_per_eval", "nuts_step_size", "nuts_divergent",
            "nuts_mean_tree_depth", "mass_matrix", "n_chains", "n_steps",
            "n_warmup", "stretch_ess", "stretch_evals",
            "stretch_ess_per_eval", "stretch_acceptance", "n_walkers",
            "stretch_steps", "artifact_hash", "platform",
            "tpu_unavailable"} <= set(nuts)
    assert nuts["value"] >= 5
    assert nuts["nuts_ess_per_eval"] >= 5 * nuts["stretch_ess_per_eval"]
    assert nuts["nuts_divergent"] == 0
    assert nuts["nuts_evals"] > 0 and nuts["stretch_evals"] > 0
    assert len(nuts["artifact_hash"]) == 16
    assert d["nuts_ess_per_eval"] == {
        "value": nuts["value"],
        "nuts_ess_per_eval": nuts["nuts_ess_per_eval"],
        "stretch_ess_per_eval": nuts["stretch_ess_per_eval"],
        "mass_matrix": nuts["mass_matrix"],
        "nuts_divergent": nuts["nuts_divergent"],
    }
    # the bounce_sweep line (the in-framework O(4) bounce solver,
    # bdlz_tpu/bounce): gate-first — the validation gate (archived-P
    # reproduction, bitwise on the reference potential, + thin-wall
    # action sanity) passed before any throughput was reported, and the
    # batched vs host-scalar-loop A/B ran on the bench's own eps scan
    # (bitwise parity is enforced INSIDE the leg; a breach would have
    # made the metric unavailable, failing the names assertion above)
    bn = next(s for s in secondary
              if s["metric"] == "bounce_profiles_per_sec_per_chip")
    assert {"value", "unit", "n_points", "n_failed", "seconds",
            "scalar_loop_seconds", "vs_scalar_loop", "gate_P_vs_archived",
            "gate_action_vs_thin_wall", "platform",
            "tpu_unavailable"} <= set(bn)
    assert bn["value"] > 0
    assert bn["n_points"] == 2 and bn["n_failed"] == 0
    assert bn["vs_scalar_loop"] > 0
    # the P gate is an exact-reproduction contract, not a tolerance
    assert bn["gate_P_vs_archived"] == 0.0
    # thin-wall closed form is an estimate; the shot action must land
    # within the documented ~6% of it on the reference potential
    assert bn["gate_action_vs_thin_wall"] <= 0.1
    assert d["bounce_sweep"] == {
        "value": bn["value"],
        "vs_scalar_loop": bn["vs_scalar_loop"],
        "gate_P_vs_archived": bn["gate_P_vs_archived"],
        "gate_action_vs_thin_wall": bn["gate_action_vs_thin_wall"],
    }
    assert np.isfinite(d["value"])
