"""In-framework O(4) bounce solver (docs/scenarios.md "Potential-space
axes"): potential → profile → P → yields, end-to-end.

Pins the subsystem's acceptance contract: the shot action lands within
the documented margin of the closed-form thin-wall S₄ on the reference
potential, the batched vmapped program is BITWISE-identical per lane to
the host scalar loop (the fixed-lane-width parity contract), the shot
reference profile reproduces the archived ``P_chi_to_B`` EXACTLY
through the local LZ composition (``validation.bounce_audit``), the
derived profile round-trips through both ``write_profile_csv`` schemas
bit-identically, and the potential fingerprint joins every downstream
identity — sweep manifest hashes, emulator artifact identities and
serve admission — with cross-potential skew rejected loudly.
"""
import numpy as np
import pytest

from bdlz_tpu.bounce import (
    BounceSolution,
    BounceSolveError,
    PotentialError,
    PotentialSpec,
    as_potential_spec,
    load_potential_json,
    potential_fingerprint,
    reference_potential,
    solve_bounce,
    solve_bounce_batch,
    solve_bounce_scalar_loop,
    thin_wall_action,
    thin_wall_radius,
    vacua,
    validate_potential,
    wall_tension,
    wall_width_mu,
    write_potential_json,
)
from bdlz_tpu.bounce.potential import (
    REFERENCE_P_CHI_TO_B,
    REFERENCE_V_WALL,
)
from bdlz_tpu.bounce.shooting import bounce_profile
from bdlz_tpu.config import (
    config_from_dict,
    static_choices_from_config,
    validate,
)
from bdlz_tpu.lz.profile import find_crossings, load_profile_csv, write_profile_csv
from bdlz_tpu.lz.sweep_bridge import (
    probabilities_for_points,
    profile_fingerprint,
)

#: The tiny_emulator-style physics base (same as test_scenarios.py).
PHYS = {
    "regime": "nonthermal",
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


def _cfg(**kw):
    return validate(config_from_dict({**PHYS, **kw}), backend="tpu")


@pytest.fixture(scope="module")
def ref_solution(jit_warmup):
    """ONE reference-potential shoot, shared by the whole module (the
    compiled lane-width-8 program is lru-cached, so later solves at the
    same knobs reuse it)."""
    spec = reference_potential()
    return spec, solve_bounce(spec)


@pytest.fixture(scope="module")
def ref_profile(ref_solution):
    spec, sol = ref_solution
    return bounce_profile(spec, solution=sol)


# ---------------------------------------------------------------------------
# potential spec: validation, closed forms, identity, IO
# ---------------------------------------------------------------------------

class TestPotentialSpec:
    def test_bad_knobs_rejected(self):
        ref = reference_potential()
        for field, bad, msg in (
            ("lam4", 0.0, "lam4"),
            ("lam4", -1.0, "lam4"),
            ("vev", 0.0, "vev"),
            ("eps", 0.0, "degenerate vacua"),
            ("eps", -0.01, "degenerate vacua"),
            ("g_delta", 0.0, "g_delta"),
            ("m_mix0", -1e-6, "m_mix0"),
            ("vev", float("nan"), "finite"),
        ):
            with pytest.raises(PotentialError, match=msg):
                validate_potential(ref._replace(**{field: bad}))

    def test_spinodal_rejected_at_validation_not_as_failed_shoot(self):
        # eps past λ₄v⁴/(3√3) ≈ 0.0962: the well has no barrier, so the
        # spec must fail loudly at validation time
        ref = reference_potential()
        with pytest.raises(PotentialError, match="spinodal"):
            validate_potential(ref._replace(eps=0.2))

    def test_vacua_ordering_and_tilt(self):
        spec = reference_potential()
        phi_false, phi_top, phi_true = vacua(spec)
        assert phi_false < phi_top < phi_true
        # the tilt pushes the true vacuum past +v and the barrier top
        # off φ = 0 toward the false side
        assert phi_true > spec.vev
        assert phi_top < 0.0 < phi_true

    def test_thin_wall_closed_forms(self):
        spec = reference_potential()
        sigma = wall_tension(spec)
        assert sigma == pytest.approx(
            (2.0 / 3.0) * np.sqrt(spec.lam4) * spec.vev**3
        )
        assert thin_wall_radius(spec) == pytest.approx(3.0 * sigma / spec.eps)
        assert thin_wall_action(spec) == pytest.approx(
            27.0 * np.pi**2 * sigma**4 / (2.0 * spec.eps**3)
        )
        assert wall_width_mu(spec) == pytest.approx(
            0.5 * spec.vev * np.sqrt(spec.lam4)
        )

    def test_fingerprint_is_pinned_and_knob_sensitive(self):
        spec = reference_potential()
        # the identity every artifact built from the reference potential
        # records — changing this breaks stored-identity compatibility
        assert potential_fingerprint(spec) == "528b931f88909962"
        assert potential_fingerprint(dict(spec._asdict())) == (
            potential_fingerprint(spec)
        )
        assert potential_fingerprint(spec._replace(eps=spec.eps * (1 + 1e-15))) != (
            potential_fingerprint(spec)
        )

    def test_json_round_trip_exact(self, tmp_path):
        spec = reference_potential()
        path = str(tmp_path / "pot.json")
        write_potential_json(path, spec)
        loaded = load_potential_json(path)
        assert loaded == spec                    # bitwise: floats via repr
        assert as_potential_spec(path) == spec   # the --bounce CLI path
        assert potential_fingerprint(path) == potential_fingerprint(spec)

    def test_mapping_keys_must_be_exact(self):
        spec = reference_potential()
        d = dict(spec._asdict())
        with pytest.raises(PotentialError, match="missing"):
            as_potential_spec({k: v for k, v in d.items() if k != "eps"})
        with pytest.raises(PotentialError, match="unknown"):
            as_potential_spec({**d, "epsilon": 0.05})
        with pytest.raises(PotentialError, match="cannot interpret"):
            as_potential_spec(42)


# ---------------------------------------------------------------------------
# shooting: thin-wall limit + batch/scalar bitwise parity
# ---------------------------------------------------------------------------

class TestShooting:
    def test_reference_shoot_lands_in_thin_wall_limit(self, ref_solution):
        # the analytic-limit satellite: at μR = 10 the shot bounce must
        # agree with Coleman's closed forms — the wall radius to a few
        # percent, the action to the documented ~6% margin
        spec, sol = ref_solution
        assert bool(sol.converged)
        phi_false, phi_top, phi_true = vacua(spec)
        # the release point is exponentially close to (but short of)
        # the true vacuum — the thin-wall signature
        assert phi_top < float(sol.phi0) < phi_true
        assert abs(float(sol.phi0) - phi_true) < 0.1 * (phi_true - phi_top)
        assert abs(float(sol.r_wall) / thin_wall_radius(spec) - 1.0) <= 0.05
        assert abs(float(sol.action) / thin_wall_action(spec) - 1.0) <= 0.10
        # the dense trajectory interpolates false vacuum at the far end
        assert float(sol.phi[-1]) == pytest.approx(phi_false, abs=1e-3)

    def test_batch_matches_scalar_loop_bitwise(self, ref_solution):
        # THE parity contract the fixed-lane-width design exists for:
        # a partial lane (3 specs, padded to width 8) through the ONE
        # vmapped program vs the same program driven one spec at a time
        # — every field of every lane bitwise equal, and invariant
        # under batch permutation (lanes are value-independent)
        spec, sol = ref_solution
        specs = [
            spec._replace(eps=spec.eps * 0.9),
            spec,
            spec._replace(eps=spec.eps * 1.1),
        ]
        batch = solve_bounce_batch(specs)
        loop = solve_bounce_scalar_loop(specs)
        assert bool(np.all(batch.converged))
        for field in BounceSolution._fields:
            a = np.asarray(getattr(batch, field))
            b = np.asarray(getattr(loop, field))
            assert np.array_equal(a, b), field
        rev = solve_bounce_batch(specs[::-1])
        for field in BounceSolution._fields:
            a = np.asarray(getattr(batch, field))
            r = np.asarray(getattr(rev, field))
            assert np.array_equal(a, r[::-1]), field
        # the reference lane inside the batch == the solo solve
        for field in ("phi0", "r_wall", "action"):
            assert np.asarray(getattr(batch, field))[1] == np.asarray(
                getattr(sol, field)
            ), field

    def test_empty_batch_rejected(self):
        with pytest.raises(BounceSolveError, match="at least one"):
            solve_bounce_batch([])


# ---------------------------------------------------------------------------
# profile extraction + archived-P gate
# ---------------------------------------------------------------------------

class TestProfile:
    def test_single_crossing_wall_window(self, ref_solution, ref_profile):
        spec, _ = ref_solution
        prof = ref_profile
        assert prof.xi.shape == (801,)
        assert np.all(np.diff(prof.xi) > 0)
        assert np.all(prof.mix == spec.m_mix0)
        # Δ > 0 inside the bubble (φ ≈ φ_true), < 0 outside — exactly
        # one level crossing at the wall
        assert prof.delta[0] > 0 > prof.delta[-1]
        assert find_crossings(prof).xi_star.shape == (1,)

    def test_reference_profile_reproduces_archived_P_exactly(self, ref_profile):
        # the PR gate: not a tolerance — the shot profile's local LZ
        # composition at v_w = 0.3 IS the archived number, bitwise
        P = probabilities_for_points(
            ref_profile, np.asarray([REFERENCE_V_WALL]), method="local"
        )
        assert float(P[0]) == REFERENCE_P_CHI_TO_B

    def test_bounce_audit_gate_passes(self, ref_solution):
        from bdlz_tpu.validation import bounce_audit

        audit = bounce_audit()
        assert audit.ok, audit.reason
        assert audit.P_vs_archived == 0.0
        assert audit.n_crossings == 1
        assert audit.action_vs_thin_wall <= 0.10

    def test_csv_round_trip_bitwise_both_schemas(self, tmp_path, ref_profile):
        # the write-side satellite: a solver-derived profile archived
        # through either schema re-ingests bit-identically
        for schema in ("delta", "matrix"):
            path = str(tmp_path / f"prof_{schema}.csv")
            write_profile_csv(path, ref_profile, schema=schema)
            back = load_profile_csv(path)
            np.testing.assert_array_equal(back.xi, ref_profile.xi)
            np.testing.assert_array_equal(back.delta, ref_profile.delta)
            np.testing.assert_array_equal(back.mix, ref_profile.mix)

    def test_profile_rejects_bad_solutions(self, ref_solution):
        spec, sol = ref_solution
        batched = BounceSolution(*(np.stack([f, f]) for f in sol))
        with pytest.raises(BounceSolveError, match="batched"):
            bounce_profile(spec, solution=batched)
        failed = sol._replace(converged=np.asarray(False))
        with pytest.raises(BounceSolveError, match="did not converge"):
            bounce_profile(spec, solution=failed)
        with pytest.raises(BounceSolveError, match="n_xi"):
            bounce_profile(spec, solution=sol, n_xi=1)
        with pytest.raises(BounceSolveError, match="escapes"):
            bounce_profile(spec, solution=sol, xi_halfwidth_walls=1e4)


# ---------------------------------------------------------------------------
# end-to-end: sweep manifest / emulator identity / serve admission
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def bounce_emulator(tmp_path_factory, ref_solution):
    """A tiny chain-mode emulator box built FROM the potential spec."""
    from bdlz_tpu.emulator import AxisSpec, build_emulator

    spec, _ = ref_solution
    base = _cfg(lz_mode="chain", lz_n_levels=3, P_chi_to_B=0.1)
    axes = {
        "m_chi_GeV": AxisSpec(0.9, 1.1, 2, "log"),
        "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
    }
    out = str(tmp_path_factory.mktemp("bounce_emu") / "artifact")
    artifact, report = build_emulator(
        base, axes, rtol=1e-2, n_probe=4, n_holdout=8, max_rounds=1,
        n_y=400, chunk_size=64, out_dir=out, require_converged=False,
        bounce=spec,
    )
    return base, axes, artifact, report


class TestEndToEnd:
    def test_sweep_manifest_carries_potential_fingerprint(
        self, tmp_path, ref_solution, ref_profile
    ):
        # same physics through both doors: a bounce sweep and a sweep
        # of the pre-derived profile are BITWISE-equal in outputs, but
        # their manifest hashes must DIFFER — the potential fingerprint
        # joins the identity alongside the derived profile's own
        import json

        from bdlz_tpu.parallel import run_sweep

        spec, _ = ref_solution
        cfg = _cfg()
        static = static_choices_from_config(cfg)
        axes = {"v_w": np.linspace(0.2, 0.6, 6)}
        out_b = str(tmp_path / "by_bounce")
        out_p = str(tmp_path / "by_profile")
        res_b = run_sweep(cfg, dict(axes), static, mesh=None, chunk_size=8,
                          n_y=400, out_dir=out_b, keep_outputs=True,
                          bounce=spec)
        res_p = run_sweep(cfg, dict(axes), static, mesh=None, chunk_size=8,
                          n_y=400, out_dir=out_p, keep_outputs=True,
                          lz_profile=ref_profile)
        assert res_b.n_failed == 0 and res_p.n_failed == 0
        np.testing.assert_array_equal(
            res_b.outputs["DM_over_B"], res_p.outputs["DM_over_B"]
        )
        with open(f"{out_b}/manifest.json") as f:
            h_b = json.load(f)["hash"]
        with open(f"{out_p}/manifest.json") as f:
            h_p = json.load(f)["hash"]
        assert h_b != h_p

    def test_sweep_rejects_both_doors_at_once(self, ref_solution, ref_profile):
        from bdlz_tpu.parallel import run_sweep

        spec, _ = ref_solution
        cfg = _cfg()
        with pytest.raises(ValueError, match="not both"):
            run_sweep(cfg, {"v_w": np.linspace(0.2, 0.6, 3)},
                      static_choices_from_config(cfg), bounce=spec,
                      lz_profile=ref_profile)

    def test_build_guards(self, ref_solution, ref_profile):
        from bdlz_tpu.emulator import AxisSpec, EmulatorBuildError, build_emulator

        spec, _ = ref_solution
        axes = {"v_w": AxisSpec(0.25, 0.35, 2, "lin")}
        with pytest.raises(EmulatorBuildError, match="scenario lz_mode"):
            build_emulator(_cfg(P_chi_to_B=0.1), axes, bounce=spec)
        base = _cfg(lz_mode="chain", lz_n_levels=3, P_chi_to_B=0.1)
        with pytest.raises(EmulatorBuildError, match="not both"):
            build_emulator(base, axes, bounce=spec, lz_profile=ref_profile)
        with pytest.raises(EmulatorBuildError, match="elastic"):
            build_emulator(base, axes, bounce=spec,
                           elastic={"m_chi_GeV": (0.9, 1.1)})

    def test_artifact_identity_carries_both_fingerprints(
        self, bounce_emulator, ref_solution, ref_profile
    ):
        spec, _ = ref_solution
        _, _, artifact, _ = bounce_emulator
        ident = dict(artifact.identity)
        assert ident["bounce"] == potential_fingerprint(spec)
        # the derived profile's array-level fingerprint rides alongside,
        # so solver-knob drift changes the identity even at a fixed
        # potential
        assert ident["lz_profile"] == profile_fingerprint(ref_profile)

    def test_serve_admission_checks_potential_fingerprint(
        self, bounce_emulator, ref_solution, ref_profile
    ):
        from bdlz_tpu.serve.service import YieldService

        spec, _ = ref_solution
        base, _, artifact, _ = bounce_emulator
        # matching potential: admitted (the spec is re-shot and the
        # derived profile then passes the lz_profile fingerprint check)
        YieldService(artifact, base, warm=False, bounce=spec)
        # the pre-derived profile is an equally valid admission ticket
        YieldService(artifact, base, warm=False, lz_profile=ref_profile)
        # cross-potential skew: rejected loudly BEFORE any shoot
        with pytest.raises(ValueError, match="does not match the potential"):
            YieldService(artifact, base, warm=False,
                         bounce=spec._replace(eps=0.049))
        with pytest.raises(ValueError, match="not both"):
            YieldService(artifact, base, warm=False, bounce=spec,
                         lz_profile=ref_profile)

    def test_serve_rejects_bounce_without_potential_on_record(
        self, tmp_path, ref_solution, ref_profile
    ):
        # an artifact built from a CSV profile records NO potential —
        # claiming one at admission time must fail, not silently pass
        from bdlz_tpu.emulator import AxisSpec, build_emulator
        from bdlz_tpu.serve.service import YieldService

        spec, _ = ref_solution
        base = _cfg(lz_mode="chain", lz_n_levels=3, P_chi_to_B=0.1)
        axes = {
            "m_chi_GeV": AxisSpec(0.9, 1.1, 2, "log"),
            "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
        }
        artifact, _ = build_emulator(
            base, axes, rtol=1e-2, n_probe=4, n_holdout=8, max_rounds=1,
            n_y=400, chunk_size=64, require_converged=False,
            lz_profile=ref_profile,
        )
        assert "bounce" not in dict(artifact.identity)
        with pytest.raises(ValueError, match="does not match the potential"):
            YieldService(artifact, base, warm=False, bounce=spec)
