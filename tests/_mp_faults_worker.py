"""Worker for the 2-process fault-injection sweep test.

Launched twice by ``tests/test_multihost.py::test_two_process_fault_healing``
as ``python _mp_faults_worker.py <port> <process_id> <out_dir>``.  Both
processes run the SAME deterministic fault plan (a transient error on
chunk 0 plus one poison point) through the mesh-sharded sweep: the
attempt-outcome agreement (allreduce_min) and the deterministic plan must
keep the retry/bisect decisions in lockstep — divergence deadlocks, which
the parent's timeout converts into a failure — and both processes must
end with the identical quarantine mask and outputs.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from _mp_common import force_local_device_count, pin_worker_platform

# must run before the first `import jax` (overrides the parent pytest
# process's 8-device flag)
force_local_device_count(2)


def main() -> None:
    port, pid, out_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax

    pin_worker_platform(jax, 2)

    from bdlz_tpu.parallel.multihost import init_multihost

    assert init_multihost(f"localhost:{port}", 2, pid) is True
    assert jax.process_count() == 2, jax.process_count()

    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.faults import FaultPlan
    from bdlz_tpu.parallel import make_mesh, run_sweep
    from bdlz_tpu.utils.retry import RetryPolicy

    cfg = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    static = static_choices_from_config(cfg)
    axes = {"m_chi_GeV": np.geomspace(0.3, 3.0, 8).tolist()}
    mesh = make_mesh(shape=(4, 1))  # all 4 global devices on dp

    plan = FaultPlan.from_obj([
        {"site": "step", "kind": "transient", "key": 0, "times": 1},
        {"site": "step", "kind": "poison", "point": 5},
    ])
    retry = RetryPolicy(max_attempts=2, backoff_s=0.0, sleep=lambda s: None)
    res = run_sweep(
        cfg, axes, static, mesh=mesh, chunk_size=4, n_y=2000,
        out_dir=f"{out_dir}/sweep", fault_plan=plan, retry=retry,
    )
    assert res.n_quarantined == 1, res.n_quarantined
    assert res.n_failed == 1, res.n_failed
    assert res.n_retries >= 1, res.n_retries
    expected = np.zeros(8, dtype=bool)
    expected[5] = True
    np.testing.assert_array_equal(res.quarantined_mask, expected)
    np.testing.assert_array_equal(res.failed_mask, expected)

    # resume under the SAME armed plan (chaos directories carry their own
    # identity; resumed chunks never dispatch, so no fault fires):
    # counters and masks must round-trip identically on both processes
    # (chunk files + manifest live on shared tmp storage)
    plan2 = FaultPlan.from_obj([
        {"site": "step", "kind": "transient", "key": 0, "times": 1},
        {"site": "step", "kind": "poison", "point": 5},
    ])
    res2 = run_sweep(
        cfg, axes, static, mesh=mesh, chunk_size=4, n_y=2000,
        out_dir=f"{out_dir}/sweep", fault_plan=plan2, retry=retry,
    )
    assert res2.resumed_chunks == res.chunks, res2.resumed_chunks
    assert res2.n_quarantined == 1 and res2.n_retries == 0
    np.testing.assert_array_equal(res2.quarantined_mask, expected)
    np.testing.assert_array_equal(
        res.outputs["DM_over_B"], res2.outputs["DM_over_B"]
    )

    np.savez(
        f"{out_dir}/faults_p{pid}.npz",
        DM_over_B=res.outputs["DM_over_B"],
        quarantined=res.quarantined_mask,
        failed=res.failed_mask,
    )
    print(f"worker {pid} OK")


if __name__ == "__main__":
    main()
