"""Worker for the real 2-process jax.distributed chunk-cache test.

Launched twice by ``tests/test_multihost.py::test_two_process_chunk_cache``
as ``python _mp_cache_worker.py <port> <process_id> <out_dir>``.  Both
processes join one distributed runtime over a SHARED store root: the
cold sweep's entries are written by the coordinator only, then the warm
sweep's broadcast hit-plan makes every process — including process 1,
which never wrote a byte — read the chunks the other host's coordinator
stored and reproduce the cold outputs bitwise.  That is the fleet
contract: no host recomputes a chunk any host has already paid for.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from _mp_common import force_local_device_count, pin_worker_platform

# must run before the first `import jax` (overrides the parent pytest
# process's 8-device flag)
force_local_device_count(2)


def main() -> None:
    port, pid, out_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax

    pin_worker_platform(jax, 2)

    from bdlz_tpu.parallel.multihost import init_multihost

    assert init_multihost(f"localhost:{port}", 2, pid) is True
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.parallel import make_mesh, run_sweep

    cfg = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    # explicit quadrature: skip the (identical, but slow) per-process audit
    static = static_choices_from_config(cfg)._replace(quad_panel_gl=False)
    axes = {"m_chi_GeV": np.geomspace(0.3, 3.0, 8).tolist()}
    mesh = make_mesh(shape=(4, 1))  # all 4 global devices on dp
    store_root = f"{out_dir}/store"

    cold = run_sweep(
        cfg, axes, static, mesh=mesh, chunk_size=4, n_y=2000,
        cache=store_root,
    )
    assert cold.n_failed == 0
    assert cold.cache_hits == 0 and cold.cache_misses == cold.chunks == 2

    # warm pass: the broadcast hit-plan must serve every chunk from the
    # shared store on BOTH processes identically (divergence would
    # deadlock, which the parent's timeout converts into a failure);
    # process 1 reads chunks it never wrote — the cross-host reuse pin
    warm = run_sweep(
        cfg, axes, static, mesh=mesh, chunk_size=4, n_y=2000,
        cache=store_root,
    )
    assert warm.cache_hits == 2 and warm.cache_misses == 0, (
        warm.cache_hits, warm.cache_misses,
    )
    np.testing.assert_array_equal(
        cold.outputs["DM_over_B"], warm.outputs["DM_over_B"]
    )

    np.savez(f"{out_dir}/result_p{pid}.npz", **warm.outputs)
    print(f"worker {pid} OK")


if __name__ == "__main__":
    main()
