"""Shared plumbing for the 2-process ``jax.distributed`` tests.

One home for the JAX-version quirks the multihost workers and their
parent tests all hit, so the next quirk is fixed once:

* ``force_local_device_count`` — the XLA_FLAGS override that must run
  BEFORE the worker's first ``import jax`` (old JAX has no
  ``jax_num_cpu_devices`` config option, and the flag inherited from the
  parent pytest process pins 8 devices, not the worker's 2);
* ``pin_worker_platform`` — the in-process config pin (host CPU, x64,
  and the device count again on JAX versions that support it);
* ``assert_worker_ok`` — the parent-side result check, including the
  capability skip for JAX builds whose CPU backend has no multiprocess
  collectives (the 2-process path cannot run there at all).
"""
import os
import re


def force_local_device_count(n: int) -> None:
    """Pin XLA's virtual host-CPU device count; call before ``import jax``."""
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()


def pin_worker_platform(jax, n_devices: int) -> None:
    """In-process config (not env vars) is the reliable pin in this
    container; must happen before any backend touch."""
    jax.config.update("jax_platforms", "cpu")
    # only newer JAX has the config option; older releases got the count
    # from force_local_device_count() before jax was imported
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", n_devices)
    jax.config.update("jax_enable_x64", True)


def assert_worker_ok(rc: int, out: str, err: str) -> None:
    import pytest

    if rc != 0 and "Multiprocess computations aren't implemented" in (
        out + err
    ):
        # this JAX build's CPU backend has no multiprocess collectives:
        # the 2-process path cannot run here at all
        pytest.skip(
            "JAX CPU backend lacks multiprocess collectives in this "
            "environment"
        )
    assert rc == 0, f"worker failed (rc={rc}):\n{out}\n{err}"
