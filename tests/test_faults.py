"""The chaos suite: deterministic fault injection + self-healing sweep.

Tier-1 pins for the robustness layer (ISSUE 5): the FaultPlan/RetryPolicy
primitives, then a tiny sweep under each fault class — transient step
error (retried), poison point (bisected and quarantined), NaN poison
(failure-masked), torn chunk file (resume detects-and-recomputes) — each
asserting results BIT-identical to a clean run on every unaffected
point.  All tests are sleep-free: retry policies carry an injected no-op
sleep, and torn storage is injected post-write, never raced.
"""
import json

import numpy as np
import pytest

from bdlz_tpu.config import (
    ConfigError,
    config_from_dict,
    static_choices_from_config,
    validate,
)
from bdlz_tpu.faults import (
    FaultError,
    FaultPlan,
    FaultPlanError,
    TransientFaultError,
)
from bdlz_tpu.parallel import make_mesh, run_sweep
from bdlz_tpu.utils.retry import (
    RetryPolicy,
    backoff_delay,
    call_with_retry,
    deterministic_jitter,
    resolve_retry_policy,
)

BENCH_OVER = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


@pytest.fixture(scope="module")
def base_cfg():
    return config_from_dict(dict(BENCH_OVER))


@pytest.fixture(scope="module")
def mesh8():
    import jax

    assert len(jax.devices()) == 8
    return make_mesh(shape=(4, 2))


def _noop_sleep_policy(max_attempts=2, calls=None):
    """A retry policy whose sleep is recorded, never slept."""
    sink = calls if calls is not None else []
    return RetryPolicy(
        max_attempts=max_attempts, backoff_s=0.01, sleep=sink.append
    ), sink


class TestFaultPlan:
    def test_parse_and_describe(self):
        plan = FaultPlan.from_obj({"faults": [
            {"site": "step", "kind": "transient", "chunk": 0, "times": 2},
            {"site": "step", "kind": "poison", "point": 5},
            {"site": "serve_exact", "kind": "raise", "call": 1},
            {"site": "clock", "kind": "slow", "delay_s": 0.25},
        ]})
        assert plan.describe() == [
            {"site": "step", "kind": "transient", "key": 0, "times": 2},
            {"site": "step", "kind": "poison", "point": 5},
            {"site": "serve_exact", "kind": "raise", "key": 1},
            {"site": "clock", "kind": "slow", "delay_s": 0.25},
        ]
        assert plan.delay_s("clock", 0) == 0.25
        assert plan.delay_s("clock", 7) == 0.25  # key=None matches all

    def test_json_text_and_file(self, tmp_path):
        payload = {"faults": [{"site": "step", "kind": "raise", "key": 3}]}
        from_text = FaultPlan.from_json(json.dumps(payload))
        p = tmp_path / "plan.json"
        p.write_text(json.dumps(payload))
        from_file = FaultPlan.from_json(str(p))
        assert from_text.describe() == from_file.describe()

    def test_malformed_plans_rejected(self):
        with pytest.raises(FaultPlanError, match="site"):
            FaultPlan.from_obj([{"site": "bogus", "kind": "raise"}])
        with pytest.raises(FaultPlanError, match="kind"):
            FaultPlan.from_obj([{"site": "step", "kind": "explode"}])
        with pytest.raises(FaultPlanError, match="point"):
            FaultPlan.from_obj([{"site": "step", "kind": "poison"}])
        with pytest.raises(FaultPlanError, match="times"):
            FaultPlan.from_obj([{"site": "step", "kind": "transient"}])
        with pytest.raises(FaultPlanError, match="unknown fault-spec"):
            FaultPlan.from_obj([{"site": "step", "kind": "raise", "bog": 1}])
        with pytest.raises(FaultPlanError, match="not valid JSON"):
            FaultPlan.from_json("{broken")

    def test_transient_counting_then_recovery(self):
        plan = FaultPlan.from_obj([
            {"site": "step", "kind": "transient", "key": 2, "times": 2},
        ])
        plan.fire("step", 0)  # other chunk: silent
        for _ in range(2):
            with pytest.raises(TransientFaultError):
                plan.fire("step", 2)
        plan.fire("step", 2)  # budget spent: recovered

    def test_poison_range_and_nan_points(self):
        plan = FaultPlan.from_obj([
            {"site": "step", "kind": "poison", "point": 10},
            {"site": "step", "kind": "nan", "point": 4},
        ])
        plan.check_range("step", 0, 10)   # poison point excluded: silent
        with pytest.raises(FaultError, match="poison point 10"):
            plan.check_range("step", 8, 16)
        assert plan.nan_points("step", 0, 8) == [4]
        assert plan.nan_points("step", 8, 16) == []

    def test_corrupt_file_truncates_once(self, tmp_path):
        plan = FaultPlan.from_obj([
            {"site": "chunk_write", "kind": "torn", "key": 0},
        ])
        f = tmp_path / "chunk.npz"
        f.write_bytes(b"x" * 100)
        assert plan.corrupt_file("chunk_write", 0, str(f)) is True
        assert f.stat().st_size == 50
        # fires once: the re-written file stays healthy
        f.write_bytes(b"y" * 100)
        assert plan.corrupt_file("chunk_write", 0, str(f)) is False
        assert f.stat().st_size == 100

    def test_resolve_default_off_and_env(self, base_cfg, monkeypatch):
        monkeypatch.delenv("BDLZ_FAULT_PLAN", raising=False)
        assert FaultPlan.resolve(None, base_cfg) is None
        monkeypatch.setenv(
            "BDLZ_FAULT_PLAN",
            '{"faults": [{"site": "step", "kind": "raise", "key": 0}]}',
        )
        plan = FaultPlan.resolve(None, base_cfg)
        assert plan is not None and len(plan.specs) == 1
        # explicit False gate wins over the env
        import dataclasses

        off = dataclasses.replace(base_cfg, fault_injection=False)
        assert FaultPlan.resolve(None, off) is None
        # explicit True without any plan is a configuration error
        monkeypatch.delenv("BDLZ_FAULT_PLAN", raising=False)
        on = dataclasses.replace(base_cfg, fault_injection=True)
        with pytest.raises(FaultPlanError, match="no fault plan"):
            FaultPlan.resolve(None, on)

    def test_robustness_knobs_never_enter_identities(self, base_cfg):
        """Arming a fault plan or tuning retry knobs is orchestration —
        it must not stale a single resume manifest, emulator artifact,
        or refcache entry (config AND static identity sides)."""
        import dataclasses

        from bdlz_tpu.config import (
            config_identity_dict,
            static_choices_from_config,
        )
        from bdlz_tpu.emulator.artifact import build_identity
        from bdlz_tpu.parallel.sweep import grid_hash

        tuned = dataclasses.replace(
            base_cfg,
            fault_injection=False,
            fault_plan='{"faults": []}',
            retry_enabled=True,
            retry_max_attempts=9,
            retry_backoff_s=1.5,
        )
        assert config_identity_dict(tuned) == config_identity_dict(base_cfg)
        axes = {"m_chi_GeV": [0.5, 1.0]}
        assert (
            grid_hash(tuned, axes, 2000) == grid_hash(base_cfg, axes, 2000)
        )
        assert build_identity(
            tuned, static_choices_from_config(tuned), 2000, "tabulated"
        ) == build_identity(
            base_cfg, static_choices_from_config(base_cfg), 2000, "tabulated"
        )

    def test_config_knob_validation(self):
        with pytest.raises(ConfigError, match="retry_max_attempts"):
            validate(config_from_dict({"retry_max_attempts": 0}))
        with pytest.raises(ConfigError, match="retry_backoff_s"):
            validate(config_from_dict({"retry_backoff_s": -1.0}))
        with pytest.raises(ConfigError, match="fault_injection"):
            validate(config_from_dict({"fault_injection": "yes"}))
        with pytest.raises(ConfigError, match="retry_enabled"):
            validate(config_from_dict({"retry_enabled": 1}))


class TestRetryPolicy:
    def test_deterministic_jitter_reproducible(self):
        a = deterministic_jitter(0, "chunk3", 1)
        assert a == deterministic_jitter(0, "chunk3", 1)
        assert 0.0 <= a < 1.0
        assert a != deterministic_jitter(0, "chunk3", 2)
        assert a != deterministic_jitter(1, "chunk3", 1)

    def test_backoff_doubles_and_caps(self):
        pol = RetryPolicy(max_attempts=5, backoff_s=0.1, max_backoff_s=0.3)
        d0 = backoff_delay(pol, "x", 0)
        d5 = backoff_delay(pol, "x", 5)
        assert 0.05 <= d0 <= 0.1      # 0.1 * [0.5, 1.0) jitter band
        assert d5 == 0.3              # capped
        assert backoff_delay(pol, "x", 0) == d0  # deterministic

    def test_call_with_retry_recovers_and_exhausts(self):
        pol, sleeps = _noop_sleep_policy(max_attempts=3)
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 3:
                raise RuntimeError("boom")
            return "ok"

        retried = []
        assert call_with_retry(
            flaky, pol, label="t",
            on_retry=lambda a, e: retried.append(a),
        ) == "ok"
        assert retried == [0, 1]
        assert sleeps == [backoff_delay(pol, "t", 0), backoff_delay(pol, "t", 1)]

        def dead():
            raise RuntimeError("still dead")

        with pytest.raises(RuntimeError, match="still dead"):
            call_with_retry(dead, pol, label="t2")

    def test_resolution_tristate(self, base_cfg):
        import dataclasses

        # None -> engine default
        assert resolve_retry_policy(base_cfg, engine_default=True) is not None
        assert resolve_retry_policy(base_cfg, engine_default=False) is None
        # explicit False wins
        off = dataclasses.replace(base_cfg, retry_enabled=False)
        assert resolve_retry_policy(off, engine_default=True) is None
        # knobs flow through
        tuned = dataclasses.replace(
            base_cfg, retry_enabled=True, retry_max_attempts=7,
            retry_backoff_s=0.5,
        )
        pol = resolve_retry_policy(tuned)
        assert pol.max_attempts == 7 and pol.backoff_s == 0.5


class TestSweepChaos:
    """Tiny sweeps under each injected fault class (tier-1, sleep-free)."""

    AXES = {"m_chi_GeV": np.geomspace(0.1, 2.0, 16).tolist()}

    @pytest.fixture(scope="class")
    def clean(self, base_cfg, mesh8):
        static = static_choices_from_config(base_cfg)
        return run_sweep(
            base_cfg, self.AXES, static, mesh=mesh8, chunk_size=8, n_y=2000,
        )

    def _chaos(self, base_cfg, mesh8, plan, max_attempts=2, **kw):
        static = static_choices_from_config(base_cfg)
        policy, sleeps = _noop_sleep_policy(max_attempts=max_attempts)
        res = run_sweep(
            base_cfg, self.AXES, static, mesh=mesh8, chunk_size=8, n_y=2000,
            fault_plan=FaultPlan.from_obj(plan), retry=policy, **kw,
        )
        return res, sleeps

    def test_disabled_faults_bit_identical(self, base_cfg, mesh8, clean):
        """With no fault plan the healed engine is byte-identical to the
        pre-robustness engine's output (the acceptance pin)."""
        static = static_choices_from_config(base_cfg)
        res = run_sweep(
            base_cfg, self.AXES, static, mesh=mesh8, chunk_size=8,
            n_y=2000, fault_plan=None,
        )
        np.testing.assert_array_equal(
            res.outputs["DM_over_B"], clean.outputs["DM_over_B"]
        )
        assert res.n_quarantined == 0 and res.n_retries == 0
        assert not res.quarantined_mask.any()

    def test_transient_step_fault_retried(self, base_cfg, mesh8, clean,
                                          tmp_path):
        """A chunk that fails transiently costs retries (with the
        injected, never-slept backoff), not points — results stay
        bit-identical to the clean run."""
        from bdlz_tpu.utils.logging import EventLog

        events_path = tmp_path / "events.jsonl"
        res, sleeps = self._chaos(
            base_cfg, mesh8,
            [{"site": "step", "kind": "transient", "key": 1, "times": 1}],
            event_log=EventLog(path=str(events_path)),
        )
        assert res.n_failed == 0 and res.n_quarantined == 0
        assert res.n_retries == 1
        assert len(sleeps) == 1  # injected sleep, recorded not slept
        np.testing.assert_array_equal(
            res.outputs["DM_over_B"], clean.outputs["DM_over_B"]
        )
        events = [json.loads(ln) for ln in
                  events_path.read_text().splitlines()]
        retries = [e for e in events if e["event"] == "chunk_retry"]
        assert len(retries) == 1 and retries[0]["chunk"] == 1
        assert not [e for e in events if e["event"] == "chunk_quarantine"]

    def test_poison_point_bisected_to_quarantine(self, base_cfg, mesh8,
                                                 clean, tmp_path):
        """A persistently failing point is isolated by bisection: ONLY it
        is quarantined, every survivor of its chunk is kept bit-identical
        to the clean run."""
        from bdlz_tpu.utils.logging import EventLog

        events_path = tmp_path / "events.jsonl"
        res, _ = self._chaos(
            base_cfg, mesh8,
            [{"site": "step", "kind": "poison", "point": 5}],
            event_log=EventLog(path=str(events_path)),
        )
        assert res.n_quarantined == 1 and res.n_failed == 1
        assert res.n_retries >= 1
        expected = np.zeros(16, dtype=bool)
        expected[5] = True
        np.testing.assert_array_equal(res.quarantined_mask, expected)
        np.testing.assert_array_equal(res.failed_mask, expected)
        assert np.isnan(res.outputs["DM_over_B"][5])
        np.testing.assert_array_equal(
            res.outputs["DM_over_B"][~expected],
            clean.outputs["DM_over_B"][~expected],
        )
        events = [json.loads(ln) for ln in
                  events_path.read_text().splitlines()]
        quarantines = [e for e in events if e["event"] == "chunk_quarantine"]
        assert len(quarantines) == 1
        assert (quarantines[0]["lo"], quarantines[0]["hi"]) == (5, 6)

    def test_nan_fault_joins_failure_mask(self, base_cfg, mesh8, clean):
        """A NaN-poisoned output is an ordinary masked failure (physics
        path), not a quarantine."""
        res, _ = self._chaos(
            base_cfg, mesh8,
            [{"site": "step", "kind": "nan", "point": 3}],
        )
        assert res.n_failed == 1 and res.n_quarantined == 0
        assert res.failed_mask[3] and not res.quarantined_mask.any()
        keep = ~res.failed_mask
        np.testing.assert_array_equal(
            res.outputs["DM_over_B"][keep],
            clean.outputs["DM_over_B"][keep],
        )

    def test_torn_chunk_file_recomputed_on_resume(self, base_cfg, mesh8,
                                                  clean, tmp_path, capsys):
        """Torn storage: the chunk .npz is truncated after its (atomic)
        write; the resume pass must detect the corrupt file, recompute
        that chunk only, and reproduce the clean results."""
        out = str(tmp_path / "sweep")
        res1, _ = self._chaos(
            base_cfg, mesh8,
            [{"site": "chunk_write", "kind": "torn", "key": 0}],
            out_dir=out,
        )
        assert res1.n_failed == 0  # the RUN was healthy; storage was not
        with pytest.raises(Exception):
            np.load(f"{out}/chunk_00000.npz")["DM_over_B"]
        # resume under the SAME armed plan (chaos directories have their
        # own identity — a clean run would recompute from scratch)
        res2, _ = self._chaos(
            base_cfg, mesh8,
            [{"site": "chunk_write", "kind": "torn", "key": 0}],
            out_dir=out,
        )
        assert res2.resumed_chunks == res2.chunks - 1
        assert "recomputing" in capsys.readouterr().err
        np.testing.assert_array_equal(
            res2.outputs["DM_over_B"], clean.outputs["DM_over_B"]
        )

    def test_resume_after_quarantine_manifest_roundtrip(self, base_cfg,
                                                        mesh8, tmp_path):
        """Quarantine is durable: the manifest records it, and a resume
        under the same plan restores the counters and masks without
        recomputing (resumed chunks never dispatch, so no fault fires)."""
        out = str(tmp_path / "sweep")
        plan = [{"site": "step", "kind": "poison", "point": 5}]
        res1, _ = self._chaos(base_cfg, mesh8, plan, out_dir=out)
        assert res1.n_quarantined == 1
        manifest = json.loads((tmp_path / "sweep" / "manifest.json").read_text())
        rec = manifest["chunks"]["0"]
        assert rec["n_quarantined"] == 1 and rec["quarantined"] == [5]
        assert manifest["chunks"]["1"].get("n_quarantined", 0) == 0
        res2, _ = self._chaos(base_cfg, mesh8, plan, out_dir=out)
        assert res2.resumed_chunks == res2.chunks
        assert res2.n_quarantined == 1 and res2.n_retries == 0
        np.testing.assert_array_equal(
            res2.quarantined_mask, res1.quarantined_mask
        )
        np.testing.assert_array_equal(
            res2.outputs["DM_over_B"], res1.outputs["DM_over_B"]
        )

    def test_clean_run_never_resumes_a_chaos_directory(self, base_cfg,
                                                       mesh8, tmp_path,
                                                       clean):
        """An armed fault plan joins the sweep identity: a clean run in
        the same directory recomputes from scratch instead of silently
        adopting injected NaN/quarantined chunks as physics."""
        out = str(tmp_path / "sweep")
        res1, _ = self._chaos(
            base_cfg, mesh8,
            [{"site": "step", "kind": "nan", "point": 3}],
            out_dir=out,
        )
        assert res1.n_failed == 1
        static = static_choices_from_config(base_cfg)
        res2 = run_sweep(
            base_cfg, self.AXES, static, mesh=mesh8, chunk_size=8,
            n_y=2000, out_dir=out,
        )
        assert res2.resumed_chunks == 0
        assert res2.n_failed == 0
        np.testing.assert_array_equal(
            res2.outputs["DM_over_B"], clean.outputs["DM_over_B"]
        )

    def test_whole_chunk_persistent_failure_bounded(self, base_cfg, mesh8,
                                                    clean):
        """A chunk where EVERY attempt fails (persistent raise keyed to
        the chunk) wholesale-quarantines under the heal budget — O(log
        chunk) probes, never O(chunk) full re-executions — and the other
        chunk survives bit-identical."""
        res, sleeps = self._chaos(
            base_cfg, mesh8,
            [{"site": "step", "kind": "raise", "key": 0}],
            max_attempts=3,
        )
        assert res.n_quarantined == 8          # all of chunk 0
        assert res.quarantined_mask[:8].all()
        assert not res.quarantined_mask[8:].any()
        # budget bound: max_attempts * 4 * (1 + ceil(log2(8))) = 48
        assert res.n_retries <= 48
        assert len(sleeps) <= res.n_retries    # sleeps injected, bounded
        np.testing.assert_array_equal(
            res.outputs["DM_over_B"][8:], clean.outputs["DM_over_B"][8:]
        )

    def test_retry_disabled_raises_through(self, base_cfg, mesh8):
        """retry_enabled=False restores the old crash semantics — the
        debugging escape hatch, and the pin that healing is really the
        only thing standing between a fault and the sweep."""
        import dataclasses

        cfg = dataclasses.replace(base_cfg, retry_enabled=False)
        static = static_choices_from_config(cfg)
        with pytest.raises(FaultError):
            run_sweep(
                cfg, self.AXES, static, mesh=mesh8, chunk_size=8, n_y=2000,
                fault_plan=FaultPlan.from_obj(
                    [{"site": "step", "kind": "raise", "key": 0}]
                ),
            )


class TestEmulatorBuildChaos:
    def test_build_tolerates_quarantined_probes(self, base_cfg):
        """A probe chunk whose exact evaluation stays dead after the
        retry budget is dropped (never pooled), recorded in the report
        AND the artifact manifest, and the build still converges."""
        from bdlz_tpu.emulator import AxisSpec, build_emulator, load_artifact

        plan = FaultPlan.from_obj([
            # first TWO probe-evaluator calls fail: attempt + its one
            # retry, so the first probe chunk is quarantined, then the
            # injected fault recovers for every later round
            {"site": "probe", "kind": "transient", "times": 2},
        ])
        policy, _ = _noop_sleep_policy(max_attempts=2)
        spec = {
            "m_chi_GeV": AxisSpec(0.9, 1.1, 3, "log"),
            "T_p_GeV": AxisSpec(90.0, 110.0, 3, "log"),
        }
        artifact, report = build_emulator(
            base_cfg, spec, rtol=1e-4, n_probe=8, n_holdout=16,
            max_rounds=4, n_y=400, chunk_size=64, seed=0,
            fault_plan=plan, retry=policy,
        )
        assert report.quarantined_probes == 8  # round 0's whole draw
        assert artifact.manifest["quarantined_probes"] == 8
        assert report.converged

    def test_transient_probe_fault_healed_by_retry(self, base_cfg):
        """One transient failure inside the retry budget costs nothing:
        no quarantined probes, bit-identical surface to a clean build."""
        from bdlz_tpu.emulator import AxisSpec, build_emulator

        spec = {
            "m_chi_GeV": AxisSpec(0.9, 1.1, 3, "log"),
            "T_p_GeV": AxisSpec(90.0, 110.0, 3, "log"),
        }
        kw = dict(rtol=1e-4, n_probe=8, n_holdout=16, max_rounds=4,
                  n_y=400, chunk_size=64, seed=0)
        clean_art, _ = build_emulator(base_cfg, spec, **kw)
        policy, sleeps = _noop_sleep_policy(max_attempts=2)
        art, report = build_emulator(
            base_cfg, spec, **kw,
            fault_plan=FaultPlan.from_obj(
                [{"site": "probe", "kind": "transient", "times": 1}]
            ),
            retry=policy,
        )
        assert report.quarantined_probes == 0
        assert len(sleeps) == 1
        for f in clean_art.values:
            np.testing.assert_array_equal(art.values[f], clean_art.values[f])
