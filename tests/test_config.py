"""Config-system tests: strict schema, reference defaults, template
round-trip, and the regime:"auto" latent-bug regression (SURVEY §4.5)."""
import json

import pytest

from bdlz_tpu.config import (
    REFERENCE_KEYS,
    Config,
    ConfigError,
    config_from_dict,
    default_config,
    load_config,
    resolve_Y_chi_init,
    validate,
    write_template,
)

# The reference's 20 defaults (`first_principles_yields.py:291-301`).
REFERENCE_DEFAULTS = {
    "m_chi_GeV": 0.95, "g_chi": 2, "chi_stats": "fermion", "regime": "nonthermal",
    "sigma_v_chi_GeV_m2": 0.0,
    "T_p_GeV": 100.0, "beta_over_H": 100.0, "v_w": 0.30, "I_p": 0.34,
    "g_star": 106.75, "g_star_s": 106.75,
    "P_chi_to_B": None, "source_shape_sigma_y": 15.0, "Gamma_wash_over_H": 0.0,
    "incident_flux_scale": 1.0, "deplete_DM_from_source": False,
    "T_max_over_Tp": 5.0, "T_min_over_Tp": 1.0e-3,
    "Y_chi_init": 4.90e-10, "n_chi_at_Tp_GeV3": None,
}


def test_defaults_match_reference():
    d = default_config()
    for k, v in REFERENCE_DEFAULTS.items():
        assert d[k] == v, k
    assert tuple(list(d)[: len(REFERENCE_KEYS)]) == REFERENCE_KEYS


def test_unknown_key_rejected():
    with pytest.raises(ConfigError, match="Unknown config key"):
        config_from_dict({"m_chi_GEV": 1.0})  # typo'd case


def test_merge_over_defaults():
    cfg = config_from_dict({"m_chi_GeV": 2.0})
    assert cfg.m_chi_GeV == 2.0
    assert cfg.beta_over_H == 100.0


def test_template_roundtrip(tmp_path):
    path = tmp_path / "template.json"
    write_template(str(path))
    cfg = load_config(str(path))
    assert cfg == Config()


def test_template_default_is_reference_keys_only(tmp_path):
    """VERDICT r3 missing #2: the default template artifact is exactly
    the reference's 20-key dict, in its declaration order."""
    path = tmp_path / "template.json"
    write_template(str(path))
    raw = json.loads(path.read_text())
    assert list(raw) == list(REFERENCE_KEYS)
    assert raw == REFERENCE_DEFAULTS


def test_template_extensions_opt_in(tmp_path):
    path = tmp_path / "template.json"
    write_template(str(path), include_extensions=True)
    raw = json.loads(path.read_text())
    assert set(raw) == set(default_config())
    assert "backend" in raw and "ode_method" in raw
    assert load_config(str(path)) == Config()


def test_template_byte_parity_with_reference_script(tmp_path):
    """--write-template must produce the byte-identical file and stdout
    (reference :309-312, :356-357) — compared against the pinned fixture
    by default (tests/fixtures/reference_parity/), so the default suite
    never executes the untrusted snapshot (ADVICE r4); set
    BDLZ_RUN_REFERENCE_SUBPROCESS=1 to also run the live reference and
    re-certify the fixture."""
    import os
    import pathlib
    import subprocess
    import sys as _sys

    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    repo_root = pathlib.Path(__file__).resolve().parents[1]
    fix_dir = pathlib.Path(__file__).resolve().parent / "fixtures" / "reference_parity"
    expected = ((fix_dir / "template.stdout.txt").read_text(),
                (fix_dir / "template.json").read_bytes())

    scripts = [("ours", str(repo_root / "first_principles_yields.py"))]
    if os.environ.get("BDLZ_RUN_REFERENCE_SUBPROCESS") == "1":
        assert pathlib.Path("/root/reference").exists(), (
            "BDLZ_RUN_REFERENCE_SUBPROCESS=1 but /root/reference is not "
            "mounted — live re-certification cannot run"
        )
        scripts.append(("ref", "/root/reference/first_principles_yields.py"))
    for tag, script in scripts:
        d = tmp_path / tag
        d.mkdir()
        r = subprocess.run(
            [_sys.executable, script, "--write-template",
             "--config", "t.json"],
            cwd=d, capture_output=True, text=True, env=env, timeout=300,
        )
        assert r.returncode == 0, r.stderr
        assert (r.stdout, (d / "t.json").read_bytes()) == expected, tag


def test_regime_auto_rejected_on_quadrature_path():
    """The reference documents regime:"auto" but crashes its quadrature
    path on it (UnboundLocalError at :376-384); this framework errors
    up-front there."""
    cfg = config_from_dict({"regime": "auto"})
    with pytest.raises(ConfigError, match="regime"):
        validate(cfg)


def test_regime_auto_rejected_on_jax_backend():
    """The TPU path is strict on every route: auto is always rejected."""
    cfg = config_from_dict({"regime": "auto", "Gamma_wash_over_H": 0.01})
    with pytest.raises(ConfigError, match="regime"):
        validate(cfg, backend="tpu")


def test_regime_auto_accepted_on_reference_ode_path():
    """The reference's ODE path *works* with auto (else-branch thermal
    default, :399-400) — the numpy backend must reproduce, not reject."""
    cfg = config_from_dict({"regime": "auto", "Gamma_wash_over_H": 0.01})
    assert validate(cfg, backend="numpy") is cfg


def test_regime_auto_ode_path_uses_thermal_default():
    """On the reference backend + ODE path, auto must produce exactly the
    thermal run (the reference's else-branch default, :399-400)."""
    from bdlz_tpu.cli import run_point

    over = {
        "Gamma_wash_over_H": 0.05,
        "T_min_over_Tp": 0.05,
        "ode_reference_step_cap": False,  # keep the Radau run fast
        "P_chi_to_B": 0.14925839040304145,
        "incident_flux_scale": 1.07e-9,
    }
    res_auto = run_point(
        validate(config_from_dict({"regime": "auto", **over}), backend="numpy"),
        0.14925839040304145, "numpy",
    )
    res_thermal = run_point(
        config_from_dict({"regime": "thermal", **over}),
        0.14925839040304145, "numpy",
    )
    assert float(res_auto.Y_B) == float(res_thermal.Y_B)
    assert float(res_auto.Y_chi) == float(res_thermal.Y_chi)


def test_backend_key_accepted():
    cfg = config_from_dict({"backend": "tpu"})
    assert cfg.backend == "tpu"


def test_Y_chi_init_resolution_order():
    assert resolve_Y_chi_init(config_from_dict({"Y_chi_init": 3e-10})) == 3e-10
    # n_chi_at_Tp fallback: n/s at T_p
    cfg = config_from_dict({"Y_chi_init": None, "n_chi_at_Tp_GeV3": 1.0})
    import numpy as np
    from bdlz_tpu.physics.thermo import entropy_density

    expected = 1.0 / entropy_density(cfg.T_p_GeV, cfg.g_star_s, np)
    assert resolve_Y_chi_init(cfg) == pytest.approx(expected, rel=1e-15)
    # final fallback
    cfg = config_from_dict({"Y_chi_init": None})
    assert resolve_Y_chi_init(cfg) == 1.0e-12


def test_benchmark_config_loads(benchmark_config_path):
    cfg = validate(load_config(benchmark_config_path))
    assert cfg.P_chi_to_B == 0.14925839040304145
    assert cfg.source_shape_sigma_y == 9.0
    assert cfg.incident_flux_scale == 1.07e-9
    assert cfg.backend == "numpy"


def test_config_json_rejects_unknown(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"not_a_key": 1}))
    with pytest.raises(ConfigError):
        load_config(str(path))


class TestOdeMethodKey:
    def test_valid_methods_match_solver_tableaus(self):
        """config.VALID_ODE_METHODS must stay in sync with the solver's
        tableau registry (no import cycle allows a direct reference)."""
        from bdlz_tpu.config import VALID_ODE_METHODS
        from bdlz_tpu.solvers.sdirk import _TABLEAUS

        assert set(VALID_ODE_METHODS) == set(_TABLEAUS)

    def test_unknown_method_rejected(self):
        from bdlz_tpu.config import ConfigError, config_from_dict, validate

        with pytest.raises(ConfigError, match="ode_method"):
            validate(config_from_dict({"ode_method": "radau99"}))

    def test_config_key_selects_tableau(self):
        """static.ode_method flows into solve_boltzmann_esdirk: the config
        key must reproduce the explicitly-selected tableau bitwise."""
        import numpy as np

        from bdlz_tpu.config import (
            config_from_dict,
            point_params_from_config,
            static_choices_from_config,
        )
        from bdlz_tpu.physics.percolation import make_kjma_grid
        from bdlz_tpu.solvers.sdirk import solve_boltzmann_esdirk

        raw = {
            "regime": "nonthermal", "P_chi_to_B": 0.149,
            "source_shape_sigma_y": 9.0, "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.9e-10, "Gamma_wash_over_H": 0.02,
            "T_min_over_Tp": 0.2,
        }
        grid = make_kjma_grid(np)
        results = {}
        for m in ("kvaerno3", "sdirk4"):
            cfg = config_from_dict(dict(raw, ode_method=m))
            static = static_choices_from_config(cfg)
            pp = point_params_from_config(cfg, cfg.P_chi_to_B)
            sol = solve_boltzmann_esdirk(
                pp, static, grid, (4.9e-10, 0.0),
                0.2 * cfg.T_p_GeV, 5.0 * cfg.T_p_GeV,
            )
            explicit = solve_boltzmann_esdirk(
                pp, static, grid, (4.9e-10, 0.0),
                0.2 * cfg.T_p_GeV, 5.0 * cfg.T_p_GeV, method=m,
            )
            assert float(sol.y[1]) == float(explicit.y[1])
            results[m] = (int(sol.n_steps), float(sol.y[1]))
        # the tableaus genuinely differ (different step counts)
        assert results["kvaerno3"][0] != results["sdirk4"][0]

    def test_ode_method_absent_from_default_yields_out(self):
        """A default config's yields_out inputs must not grow the new key
        (byte-parity with the reference artifact)."""
        from bdlz_tpu.config import config_from_dict
        from bdlz_tpu.models.yields_pipeline import YieldsResult
        from bdlz_tpu.utils.io import yields_out_payload

        cfg = config_from_dict({"P_chi_to_B": 0.149})
        res = YieldsResult(1e-11, 5e-10, 1e-28, 1e-27, 5.0)
        payload = yields_out_payload(cfg, 0.149, res)
        assert "ode_method" not in payload["inputs"]
        payload2 = yields_out_payload(
            config_from_dict({"P_chi_to_B": 0.149, "ode_method": "kvaerno3"}),
            0.149, res,
        )
        assert payload2["inputs"]["ode_method"] == "kvaerno3"

    def test_identity_dict_contract(self):
        """Resume identities: passive extension keys omitted at their
        defaults (adding a framework field must not invalidate every
        pre-existing checkpoint), but result-affecting knobs pinned at
        their RESOLVED values (a future change to their defaults must
        invalidate — otherwise chunks computed at two settings would be
        silently spliced)."""
        from bdlz_tpu.config import (
            RESULT_AFFECTING_EXTENSIONS,
            config_from_dict,
            config_identity_dict,
        )
        from bdlz_tpu.parallel.sweep import grid_hash

        base = {"P_chi_to_B": 0.149}
        cfg = config_from_dict(base)
        ident = config_identity_dict(cfg)
        for k in ("backend", "m_B_GeV", "n_y", "ode_reference_step_cap"):
            assert k not in ident  # passive keys: omitted at default
        for k in RESULT_AFFECTING_EXTENSIONS:
            assert k in ident      # engine knobs: pinned resolved
        assert ident["ode_method"] == "sdirk4"
        # explicitly writing the default produces the same identity/hash
        cfg2 = config_from_dict(dict(base, ode_method="sdirk4"))
        axes = {"m_chi_GeV": [0.5, 1.0]}
        assert grid_hash(cfg, axes, 2000) == grid_hash(cfg2, axes, 2000)
        # a NON-default engine knob changes the identity
        cfg3 = config_from_dict(dict(base, ode_method="kvaerno3"))
        assert config_identity_dict(cfg3)["ode_method"] == "kvaerno3"
        assert grid_hash(cfg, axes, 2000) != grid_hash(cfg3, axes, 2000)

    def test_ode_tolerances_from_config(self):
        """ode_rtol/ode_atol config keys flow through StaticChoices into
        the stiff engine; an invalid value is rejected at validation."""
        import numpy as np

        from bdlz_tpu.config import (
            ConfigError,
            config_from_dict,
            point_params_from_config,
            static_choices_from_config,
            validate,
        )
        from bdlz_tpu.physics.percolation import make_kjma_grid
        from bdlz_tpu.solvers.sdirk import solve_boltzmann_esdirk

        with pytest.raises(ConfigError, match="positive"):
            validate(config_from_dict({"ode_atol": 0.0}))

        raw = {
            "regime": "nonthermal", "P_chi_to_B": 0.149,
            "source_shape_sigma_y": 9.0, "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.9e-10, "Gamma_wash_over_H": 0.02,
            "T_min_over_Tp": 0.2,
        }
        grid = make_kjma_grid(np)
        cfg = config_from_dict(dict(raw, ode_atol=1e-20))
        static = static_choices_from_config(cfg)
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        from_cfg = solve_boltzmann_esdirk(
            pp, static, grid, (4.9e-10, 0.0),
            0.2 * cfg.T_p_GeV, 5.0 * cfg.T_p_GeV,
        )
        explicit = solve_boltzmann_esdirk(
            pp, static, grid, (4.9e-10, 0.0),
            0.2 * cfg.T_p_GeV, 5.0 * cfg.T_p_GeV, atol=1e-20,
        )
        assert float(from_cfg.y[1]) == float(explicit.y[1])
        assert int(from_cfg.n_steps) == int(explicit.n_steps)
        # a tighter atol genuinely changes the run (more steps)
        default_run = solve_boltzmann_esdirk(
            pp, static_choices_from_config(config_from_dict(raw)), grid,
            (4.9e-10, 0.0), 0.2 * cfg.T_p_GeV, 5.0 * cfg.T_p_GeV,
        )
        assert int(from_cfg.n_steps) > int(default_run.n_steps)


class TestHealthPlaneKnobs:
    """The replica health plane / auto-rollback knobs (serve/health.py):
    validated bounds + the SERVE_CONFIG_FIELDS exclusion — breakers
    pick WHICH replica answers, never what a kernel computes, so tuning
    them stales nothing."""

    def test_validation(self):
        from bdlz_tpu.config import ConfigError, config_from_dict, validate

        validate(config_from_dict({
            "health_enabled": True, "breaker_window": 3,
            "breaker_threshold": 0.25, "breaker_cooldown_s": 2.0,
            "breaker_latency_slo_s": 0.5, "rollback_budget": 0.01,
        }))
        validate(config_from_dict({"health_enabled": False}))
        with pytest.raises(ConfigError, match="health_enabled"):
            validate(config_from_dict({"health_enabled": "on"}))
        with pytest.raises(ConfigError, match="breaker_window"):
            validate(config_from_dict({"breaker_window": 0}))
        with pytest.raises(ConfigError, match="breaker_threshold"):
            validate(config_from_dict({"breaker_threshold": 0.0}))
        with pytest.raises(ConfigError, match="breaker_threshold"):
            validate(config_from_dict({"breaker_threshold": 1.5}))
        with pytest.raises(ConfigError, match="breaker_cooldown_s"):
            validate(config_from_dict({"breaker_cooldown_s": 0.0}))
        with pytest.raises(ConfigError, match="breaker_latency_slo_s"):
            validate(config_from_dict({"breaker_latency_slo_s": -0.1}))
        with pytest.raises(ConfigError, match="rollback_budget"):
            validate(config_from_dict({"rollback_budget": 0.0}))
        with pytest.raises(ConfigError, match="rollback_budget"):
            validate(config_from_dict({"rollback_budget": 2.0}))

    def test_excluded_from_every_identity(self):
        from bdlz_tpu.config import (
            SERVE_CONFIG_FIELDS,
            config_from_dict,
            config_identity_dict,
        )
        from bdlz_tpu.parallel.sweep import grid_hash

        for k in ("health_enabled", "breaker_window", "breaker_threshold",
                  "breaker_cooldown_s", "breaker_latency_slo_s",
                  "rollback_budget"):
            assert k in SERVE_CONFIG_FIELDS
        base = {"P_chi_to_B": 0.149}
        cfg = config_from_dict(base)
        tuned = config_from_dict(dict(
            base, health_enabled=True, breaker_window=2,
            breaker_threshold=0.9, breaker_cooldown_s=9.0,
            breaker_latency_slo_s=0.3, rollback_budget=0.5,
        ))
        assert config_identity_dict(tuned) == config_identity_dict(cfg)
        axes = {"m_chi_GeV": [0.5, 1.0]}
        assert grid_hash(cfg, axes, 2000) == grid_hash(tuned, axes, 2000)


class TestTenancyKnobs:
    """The multi-tenant serving-plane knobs (serve/tenancy.py):
    validated bounds + the SERVE_CONFIG_FIELDS exclusion — routing,
    memory budgets and autoscale cadence pick WHICH pool/replica
    answers and WHEN tables are resident, never what a kernel
    computes, so tuning them stales nothing."""

    def test_validation(self):
        from bdlz_tpu.config import ConfigError, config_from_dict, validate

        validate(config_from_dict({
            "tenant_routing": "scenario", "memory_budget_bytes": 1 << 20,
            "autoscale_interval_s": 0.5, "pool_min_replicas": 2,
        }))
        validate(config_from_dict({"tenant_routing": "hash"}))
        validate(config_from_dict({}))  # null routing = engine decides
        with pytest.raises(ConfigError, match="tenant_routing"):
            validate(config_from_dict({"tenant_routing": "round_robin"}))
        with pytest.raises(ConfigError, match="memory_budget_bytes"):
            validate(config_from_dict({"memory_budget_bytes": 0}))
        with pytest.raises(ConfigError, match="autoscale_interval_s"):
            validate(config_from_dict({"autoscale_interval_s": 0.0}))
        with pytest.raises(ConfigError, match="pool_min_replicas"):
            validate(config_from_dict({"pool_min_replicas": 0}))

    def test_excluded_from_every_identity(self):
        from bdlz_tpu.config import (
            SERVE_CONFIG_FIELDS,
            config_from_dict,
            config_identity_dict,
        )
        from bdlz_tpu.parallel.sweep import grid_hash

        for k in ("tenant_routing", "memory_budget_bytes",
                  "autoscale_interval_s", "pool_min_replicas"):
            assert k in SERVE_CONFIG_FIELDS
        base = {"P_chi_to_B": 0.149}
        cfg = config_from_dict(base)
        tuned = config_from_dict(dict(
            base, tenant_routing="hash", memory_budget_bytes=1 << 24,
            autoscale_interval_s=0.25, pool_min_replicas=3,
        ))
        assert config_identity_dict(tuned) == config_identity_dict(cfg)
        axes = {"m_chi_GeV": [0.5, 1.0]}
        assert grid_hash(cfg, axes, 2000) == grid_hash(tuned, axes, 2000)


class TestEmulatorSeamKnobs:
    """The seam-split/error-gate/posterior-weight knobs: validated
    tri-states with DELIBERATE identity treatment — seam_split and
    error_gate_tol never touch any identity (build structure and serve
    policy), posterior_weight's single identity home is the emulator
    artifact's own key (build_identity), never the shared config
    payload."""

    def test_validation(self):
        from bdlz_tpu.config import ConfigError, config_from_dict, validate

        validate(config_from_dict({"seam_split": True}))
        validate(config_from_dict({"seam_split": False}))
        validate(config_from_dict({"error_gate_tol": 1e-4}))
        validate(config_from_dict({"error_gate_tol": False}))
        validate(config_from_dict({"posterior_weight": "planck"}))
        with pytest.raises(ConfigError, match="seam_split"):
            validate(config_from_dict({"seam_split": "yes"}))
        with pytest.raises(ConfigError, match="ambiguous"):
            validate(config_from_dict({"error_gate_tol": True}))
        with pytest.raises(ConfigError, match="error_gate_tol"):
            validate(config_from_dict({"error_gate_tol": -1e-3}))
        with pytest.raises(ConfigError, match="posterior_weight"):
            validate(config_from_dict({"posterior_weight": "flat"}))

    def test_excluded_from_config_identity(self):
        from bdlz_tpu.config import (
            EMULATOR_CONFIG_FIELDS,
            config_from_dict,
            config_identity_dict,
        )
        from bdlz_tpu.parallel.sweep import grid_hash

        base = {"P_chi_to_B": 0.149}
        cfg = config_from_dict(base)
        cfg_knobs = config_from_dict(dict(
            base, seam_split=True, error_gate_tol=1e-3,
            posterior_weight="planck",
        ))
        ident = config_identity_dict(cfg_knobs)
        for k in EMULATOR_CONFIG_FIELDS:
            assert k not in ident
        # tuning the knobs stales NO sweep manifest
        axes = {"m_chi_GeV": [0.5, 1.0]}
        assert grid_hash(cfg, axes, 2000) == grid_hash(cfg_knobs, axes, 2000)

    def test_posterior_weight_home_is_artifact_identity(self):
        from bdlz_tpu.config import (
            config_from_dict,
            static_choices_from_config,
        )
        from bdlz_tpu.emulator import build_identity

        cfg = config_from_dict({"posterior_weight": "planck"})
        static = static_choices_from_config(cfg)
        ident = build_identity(cfg, static, 2000, "tabulated")
        assert ident["posterior_weight"] == "planck"
        assert "posterior_weight" not in ident["base"]
        # unweighted: no key at all (omit-at-absent — pre-existing
        # artifacts keep verifying)
        plain = config_from_dict({})
        ident0 = build_identity(
            plain, static_choices_from_config(plain), 2000, "tabulated"
        )
        assert "posterior_weight" not in ident0
        # explicit argument overrides the config knob
        ident2 = build_identity(
            plain, static_choices_from_config(plain), 2000, "tabulated",
            posterior_weight="planck",
        )
        assert ident2["posterior_weight"] == "planck"


class TestSamplerKnobs:
    """The MCMC sampler knobs (sampler/mass_matrix/target_accept) and
    the emulator refine_signal knob: validated, with the PR's identity
    contract — the sampler cannot stale sweep manifests or emulator
    artifacts (SAMPLER_CONFIG_FIELDS exclusion), its single identity
    home is the MCMC checkpoint identity; refine_signal's single home
    is the artifact's own key, like posterior_weight."""

    def test_validation(self):
        from bdlz_tpu.config import ConfigError, config_from_dict, validate

        validate(config_from_dict({"sampler": "nuts"}))
        validate(config_from_dict({"mass_matrix": "dense"}))
        validate(config_from_dict({"target_accept": 0.9}))
        validate(config_from_dict({"refine_signal": "fisher"}))
        with pytest.raises(ConfigError, match="sampler"):
            validate(config_from_dict({"sampler": "hmc"}))
        with pytest.raises(ConfigError, match="mass_matrix"):
            validate(config_from_dict({"mass_matrix": "full"}))
        with pytest.raises(ConfigError, match="target_accept"):
            validate(config_from_dict({"target_accept": 1.5}))
        with pytest.raises(ConfigError, match="target_accept"):
            validate(config_from_dict({"target_accept": 0.0}))
        with pytest.raises(ConfigError, match="refine_signal"):
            validate(config_from_dict({"refine_signal": "hessian"}))

    def test_sampler_excluded_from_config_and_artifact_identity(self):
        from bdlz_tpu.config import (
            SAMPLER_CONFIG_FIELDS,
            config_from_dict,
            config_identity_dict,
            static_choices_from_config,
        )
        from bdlz_tpu.emulator import build_identity
        from bdlz_tpu.parallel.sweep import grid_hash

        base = {"P_chi_to_B": 0.149}
        cfg = config_from_dict(base)
        cfg_knobs = config_from_dict(dict(
            base, sampler="nuts", mass_matrix="dense", target_accept=0.9,
        ))
        ident = config_identity_dict(cfg_knobs)
        for k in SAMPLER_CONFIG_FIELDS:
            assert k not in ident
        # the headline pin: choosing NUTS stales no sweep manifest and
        # no emulator artifact
        axes = {"m_chi_GeV": [0.5, 1.0]}
        assert grid_hash(cfg, axes, 2000) == grid_hash(cfg_knobs, axes, 2000)
        st = static_choices_from_config(cfg)
        st_k = static_choices_from_config(cfg_knobs)
        assert build_identity(cfg, st, 2000, "tabulated") == build_identity(
            cfg_knobs, st_k, 2000, "tabulated"
        )

    def test_sampler_home_is_checkpoint_identity(self):
        """Omit-at-default: a None sampler payload leaves every existing
        chain digest byte-stable; a NUTS payload (or any knob change
        inside it) splits the digest — the loud-resume-invalidation
        contract."""
        import numpy as np

        from bdlz_tpu.provenance import mcmc_segment_identity

        init = np.zeros((4, 2))
        legacy = mcmc_segment_identity(init, 0, 10, 5, 2.0, 1, {"c": 1})
        stretch = mcmc_segment_identity(
            init, 0, 10, 5, 2.0, 1, {"c": 1}, sampler=None
        )
        assert legacy.digest(16) == stretch.digest(16)
        nuts = mcmc_segment_identity(
            init, 0, 10, 5, 2.0, 1, {"c": 1},
            sampler={"name": "nuts", "mass_matrix": "diag",
                     "target_accept": 0.8, "max_tree_depth": 8,
                     "n_warmup": 300},
        )
        assert nuts.digest(16) != legacy.digest(16)
        nuts2 = mcmc_segment_identity(
            init, 0, 10, 5, 2.0, 1, {"c": 1},
            sampler={"name": "nuts", "mass_matrix": "dense",
                     "target_accept": 0.8, "max_tree_depth": 8,
                     "n_warmup": 300},
        )
        assert nuts2.digest(16) != nuts.digest(16)

    def test_refine_signal_home_is_artifact_identity(self):
        from bdlz_tpu.config import (
            config_from_dict,
            static_choices_from_config,
        )
        from bdlz_tpu.emulator import build_identity

        cfg = config_from_dict({"refine_signal": "fisher"})
        static = static_choices_from_config(cfg)
        ident = build_identity(cfg, static, 2000, "tabulated")
        assert ident["refine_signal"] == "fisher"
        assert "refine_signal" not in ident["base"]
        plain = config_from_dict({})
        ident0 = build_identity(
            plain, static_choices_from_config(plain), 2000, "tabulated"
        )
        assert "refine_signal" not in ident0
        ident2 = build_identity(
            plain, static_choices_from_config(plain), 2000, "tabulated",
            refine_signal="fisher",
        )
        assert ident2["refine_signal"] == "fisher"
