"""Gradient-layer tests (sampling/grad.py): the tentpole's acceptance
harness — jax.grad vs central finite differences on the exact AND
emulator-backed Planck log-posteriors (rel err ≤ 1e-5 strictly inside
the prior bounds), the chain/thermal lz_mode table paths, the Fisher
information fields, and the audit's loud refusals."""
import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config

BENCH_OVER = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}

#: The tentpole's acceptance tolerance (ISSUE: jax.grad vs central FD).
PARITY_TOL = 1e-5


def _table(n=4096):
    import jax.numpy as jnp

    from bdlz_tpu.ops.kjma_table import make_f_table

    base = config_from_dict(dict(BENCH_OVER))
    return base, static_choices_from_config(base), make_f_table(
        base.I_p, jnp, n=n
    )


def _profile():
    from bdlz_tpu.lz.profile import BounceProfile

    xi = np.linspace(-2.0, 2.0, 201)
    return BounceProfile(xi=xi, delta=2.0 * xi, mix=np.full_like(xi, 0.3))


class TestGradientParity:
    """Central finite differences vs jax.grad at deterministic points
    strictly inside the prior bounds — the satellite harness."""

    def test_exact_logp_parity(self):
        from bdlz_tpu.sampling import gradient_parity, make_pipeline_logprob

        base, static, table = _table()
        logp = make_pipeline_logprob(
            base, static, table,
            param_keys=("m_chi_GeV", "P_chi_to_B"),
            bounds={"m_chi_GeV": (0.05, 20.0), "P_chi_to_B": (1e-4, 1.0)},
            n_y=2000,
        )
        rep = gradient_parity(logp, np.array([0.97, 0.15]))
        assert np.isfinite(rep["value"])
        assert rep["max_rel_err"] <= PARITY_TOL, rep

    def test_exact_logp_parity_log_params_and_more_axes(self):
        from bdlz_tpu.sampling import gradient_parity, make_pipeline_logprob

        base, static, table = _table()
        logp = make_pipeline_logprob(
            base, static, table,
            param_keys=("m_chi_GeV", "v_w", "source_shape_sigma_y"),
            bounds={"m_chi_GeV": (np.log10(0.5), np.log10(2.0))},
            log_params=("m_chi_GeV",),
            n_y=2000,
        )
        rep = gradient_parity(logp, np.array([np.log10(0.97), 0.31, 8.7]))
        assert rep["max_rel_err"] <= PARITY_TOL, rep

    def test_panel_gl_scheme_parity(self):
        """The snapped-panel Gauss-Legendre y-quadrature (the sweep fast
        path) is differentiable too — node positions AND weights carry
        gradients."""
        from bdlz_tpu.sampling import gradient_parity, make_pipeline_logprob

        base, static, table = _table()
        logp = make_pipeline_logprob(
            base, static._replace(quad_panel_gl=True), table,
            param_keys=("m_chi_GeV", "P_chi_to_B"),
            bounds={"m_chi_GeV": (0.05, 20.0), "P_chi_to_B": (1e-4, 1.0)},
            n_y=2000,
        )
        rep = gradient_parity(logp, np.array([0.97, 0.15]))
        assert rep["max_rel_err"] <= PARITY_TOL, rep

    def test_emulator_logp_parity(self, tiny_emulator):
        """The emulator fast mode: log-space interp is piecewise-smooth;
        parity holds away from cell boundaries (FD's own discretization
        straddling a knot is an FD artifact, so the probe point is
        chosen inside a cell — the audit documents the boundary)."""
        from bdlz_tpu.sampling import gradient_parity, make_pipeline_logprob

        base, _out_dir, artifact, _report = tiny_emulator
        static = static_choices_from_config(base)
        _b, _s, table = _table()
        logp = make_pipeline_logprob(
            base, static, table,
            param_keys=("m_chi_GeV", "v_w"),
            bounds={"m_chi_GeV": (0.92, 1.08), "v_w": (0.26, 0.34)},
            emulator=artifact,
        )
        rep = gradient_parity(logp, np.array([0.97, 0.31]), rel_step=1e-7)
        assert rep["max_rel_err"] <= PARITY_TOL, rep

    def test_chain_mode_table_parity(self):
        """The N-level chain scenario's sampled-v_w path: P(v_w) from
        the band-traversing PTableN column, interpolated in-jit — the
        mcmc_cli lz_mode='chain' seam."""
        from bdlz_tpu.lz.sweep_bridge import PTable, make_P_table_n
        from bdlz_tpu.sampling import gradient_parity, make_pipeline_logprob

        import jax.numpy as jnp

        base, static, table = _table()
        tn = make_P_table_n(_profile(), 3, 0.1, 0.6, n=256, xp=jnp)
        pt = PTable(u0=tn.u0, inv_du=tn.inv_du, values=tn.values[:, -1],
                    v_lo=tn.v_lo, v_hi=tn.v_hi, method="chain")
        logp = make_pipeline_logprob(
            base, static, table, param_keys=("v_w",),
            bounds={"v_w": (0.12, 0.58)}, lz_P_table=pt, n_y=2000,
        )
        rep = gradient_parity(logp, np.array([0.31]), rel_step=1e-7)
        assert rep["max_rel_err"] <= PARITY_TOL, rep

    def test_thermal_mode_table_parity(self):
        """The finite-T bath scenario's sampled-v_w path: Γ_φ derived at
        the pinned T_p, then the dephased P(v_w) table — the mcmc_cli
        lz_mode='thermal' seam."""
        from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_table
        from bdlz_tpu.lz.thermal import thermal_gamma_phi, thermal_method_for
        from bdlz_tpu.sampling import gradient_parity, make_pipeline_logprob

        import jax.numpy as jnp

        base, static, table = _table()
        method, gam = thermal_method_for(
            thermal_gamma_phi(base.T_p_GeV, 0.05, 1.0)
        )
        pt = make_P_of_vw_table(
            _profile(), method, 0.1, 0.6, n=256, gamma_phi=gam, xp=jnp,
        )
        logp = make_pipeline_logprob(
            base, static, table, param_keys=("v_w",),
            bounds={"v_w": (0.12, 0.58)}, lz_P_table=pt, n_y=2000,
        )
        rep = gradient_parity(logp, np.array([0.31]), rel_step=1e-7)
        assert rep["max_rel_err"] <= PARITY_TOL, rep

    def test_lz_lambda1_parity(self):
        from bdlz_tpu.sampling import gradient_parity, make_pipeline_logprob

        base, static, table = _table()
        logp = make_pipeline_logprob(
            base, static, table, param_keys=("v_w",),
            bounds={"v_w": (0.05, 0.9)}, lz_lambda1=0.004, n_y=2000,
        )
        rep = gradient_parity(logp, np.array([0.31]))
        assert rep["max_rel_err"] <= PARITY_TOL, rep


class TestFisherFields:
    def test_observable_jacobian_and_fisher(self):
        """J = ∂(Ω_b, Ω_DM)/∂θ via one reverse pass per field; the
        Planck Fisher F = JᵀΣ⁻¹J is symmetric PSD and matches the
        hand-contraction."""
        import jax.numpy as jnp

        from bdlz_tpu.constants import (
            PLANCK_OMEGA_B_H2_SIGMA,
            PLANCK_OMEGA_DM_H2_SIGMA,
        )
        from bdlz_tpu.sampling import (
            make_observable_jacobian,
            make_pipeline_observables,
            planck_fisher_information,
        )

        base, static, table = _table()
        obs = make_pipeline_observables(
            base, static, table, param_keys=("m_chi_GeV", "v_w"),
            n_y=2000,
        )
        thetas = jnp.asarray([[0.97, 0.31], [1.5, 0.4]])
        omegas, jac = make_observable_jacobian(obs)(thetas)
        assert omegas.shape == (2, 2) and jac.shape == (2, 2, 2)
        assert np.all(np.isfinite(np.asarray(jac)))
        F = np.asarray(planck_fisher_information(jac))
        assert F.shape == (2, 2, 2)
        s = np.array([PLANCK_OMEGA_B_H2_SIGMA, PLANCK_OMEGA_DM_H2_SIGMA])
        J = np.asarray(jac[0])
        want = J.T @ np.diag(1.0 / s**2) @ J
        assert np.allclose(F[0], want, rtol=1e-12)
        assert np.allclose(F[0], F[0].T)
        assert np.all(np.linalg.eigvalsh(F[0]) >= -1e-6 * F[0].max())

    def test_ratio_and_grad_matches_fd(self):
        import jax.numpy as jnp

        from bdlz_tpu.sampling import (
            central_fd_grad,
            make_pipeline_observables,
            make_ratio_and_grad,
        )

        base, static, table = _table()
        obs = make_pipeline_observables(
            base, static, table, param_keys=("m_chi_GeV", "v_w"), n_y=2000,
        )
        fn = make_ratio_and_grad(obs)
        theta = np.array([0.97, 0.31])
        vals, grads = fn(jnp.asarray(theta)[None, :])

        def ratio(t):
            ob, od = obs(t)
            return od / ob

        fd = central_fd_grad(ratio, theta)
        rel = np.abs(np.asarray(grads[0]) - fd) / np.maximum(np.abs(fd), 1e-300)
        assert rel.max() <= PARITY_TOL

    def test_field_log10_jacobian_matches_fd_in_axis_coords(self):
        import jax.numpy as jnp

        from bdlz_tpu.sampling.grad import make_field_log10_jacobian

        base, static, table = _table()
        fj = make_field_log10_jacobian(
            base, static, table, ("m_chi_GeV", "v_w"), ("log", "lin"),
            n_y=2000,
        )
        x = np.array([0.97, 0.31])
        jac = np.asarray(fj(jnp.asarray(x)[None, :]))[0]   # (2 fields, 2)

        from bdlz_tpu.models.yields_pipeline import point_yields_fast
        from bdlz_tpu.config import point_params_from_config

        def log_fields(xv):
            pp = point_params_from_config(base, base.P_chi_to_B)
            pp = pp._replace(m_chi_GeV=xv[0], v_w=xv[1])
            import jax.numpy as jnp2

            pp = type(pp)(*(jnp2.asarray(f) for f in pp))
            res = point_yields_fast(pp, static, table, jnp2, n_y=2000)
            return np.array([
                np.log10(float(res.rho_B_kg_m3)),
                np.log10(float(res.rho_DM_kg_m3)),
            ])

        eps = 1e-6
        for k, scale in enumerate(("log", "lin")):
            up = x.copy()
            dn = x.copy()
            h = eps * abs(x[k])
            up[k] += h
            dn[k] -= h
            fd = (log_fields(up) - log_fields(dn)) / (2 * h)
            # chain rule into the axis coordinate (log10 x for log axes)
            du = x[k] * np.log(10.0) if scale == "log" else 1.0
            fd = fd * du
            rel = np.abs(jac[:, k] - fd) / np.maximum(np.abs(fd), 1e-12)
            assert rel.max() <= 1e-4, (k, jac[:, k], fd)


class TestAuditRefusals:
    """The no-silent-zero-gradient contract: every genuinely
    non-differentiable seam refuses loudly at construction."""

    def test_I_p_refused_on_observables(self):
        from bdlz_tpu.sampling import make_pipeline_observables

        base, static, table = _table()
        with pytest.raises(ValueError, match="I_p"):
            make_pipeline_observables(base, static, table, param_keys=("I_p",))

    def test_field_jacobian_refuses_scenario_modes(self):
        from bdlz_tpu.sampling.grad import make_field_log10_jacobian

        base, static, table = _table()
        chain_static = static._replace(lz_mode="chain", lz_n_levels=3)
        with pytest.raises(ValueError, match="host-side"):
            make_field_log10_jacobian(
                base, chain_static, table, ("v_w",), ("lin",)
            )

    def test_field_jacobian_refuses_I_p_axis(self):
        from bdlz_tpu.sampling.grad import make_field_log10_jacobian

        base, static, table = _table()
        with pytest.raises(ValueError, match="I_p"):
            make_field_log10_jacobian(
                base, static, table, ("I_p",), ("lin",)
            )


class TestBoundsVectorization:
    """The per-coordinate Python bounds loop became ONE jnp.where over
    the bounds arrays — pinned bitwise against a reference loop
    implementation, inside and outside the box."""

    def _loop_reference(self, base, static, table, param_keys, bounds,
                        log_params, n_y):
        """The pre-vectorization semantics, re-derived independently."""
        import jax.numpy as jnp

        from bdlz_tpu.config import point_params_from_config
        from bdlz_tpu.models.yields_pipeline import point_yields_fast
        from bdlz_tpu.parallel.sweep import AXIS_MAP
        from bdlz_tpu.sampling import omegas_from_result, planck_gaussian_logp

        pp0 = point_params_from_config(base, base.P_chi_to_B or 0.0)

        def logp(theta):
            values = {}
            lp = jnp.zeros(())
            for i, k in enumerate(param_keys):
                v = theta[i]
                if k in log_params:
                    v = 10.0 ** v
                if k in bounds:
                    lo, hi = bounds[k]
                    inside = jnp.logical_and(theta[i] >= lo, theta[i] <= hi)
                    lp = jnp.where(inside, lp, -jnp.inf)
                values[AXIS_MAP[k]] = v
            pp = pp0._replace(**values)
            pp = type(pp)(*(jnp.asarray(f) for f in pp))
            res = point_yields_fast(pp, static, table, jnp, n_y=n_y)
            ob, od = omegas_from_result(res)
            lp = lp + planck_gaussian_logp(ob, od)
            return jnp.where(jnp.isfinite(lp), lp, -jnp.inf)

        return logp

    def test_bitwise_parity_with_loop(self):
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.sampling import make_pipeline_logprob

        base, static, table = _table()
        keys = ("m_chi_GeV", "P_chi_to_B", "v_w")
        bounds = {"m_chi_GeV": (0.5, 2.0), "P_chi_to_B": (0.01, 0.9)}
        new = make_pipeline_logprob(
            base, static, table, param_keys=keys, bounds=bounds, n_y=2000,
        )
        ref = self._loop_reference(
            base, static, table, keys, bounds, (), 2000,
        )
        thetas = np.array([
            [0.97, 0.15, 0.3],     # inside
            [0.4, 0.15, 0.3],      # m below lo
            [0.97, 0.95, 0.3],     # P above hi
            [0.5, 0.9, 0.3],       # exactly on both bounds (inclusive)
            [2.1, 0.001, 0.3],     # both outside
        ])
        got = np.asarray(jax.vmap(new)(jnp.asarray(thetas)))
        want = np.asarray(jax.vmap(ref)(jnp.asarray(thetas)))
        assert np.array_equal(got, want), (got, want)
        assert np.isfinite(got[0]) and np.isfinite(got[3])
        assert got[1] == -np.inf and got[2] == -np.inf and got[4] == -np.inf

    def test_emulator_bitwise_parity_with_loop(self, tiny_emulator):
        """Same pin for the emulator fast mode's copy of the loop."""
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.sampling import make_pipeline_logprob

        base, _out, artifact, _rep = tiny_emulator
        static = static_choices_from_config(base)
        _b, _s, table = _table()
        bounds = {"m_chi_GeV": (0.92, 1.08), "v_w": (0.26, 0.34)}
        logp = make_pipeline_logprob(
            base, static, table, param_keys=("m_chi_GeV", "v_w"),
            bounds=bounds, emulator=artifact,
        )
        thetas = np.array([
            [0.97, 0.31],    # inside
            [0.90, 0.31],    # below m bound but inside the artifact box
            [0.97, 0.36],    # v_w above bound AND outside the box
            [1.08, 0.26],    # exactly on bounds (inclusive)
        ])
        got = np.asarray(jax.vmap(logp)(jnp.asarray(thetas)))
        assert np.isfinite(got[0]) and np.isfinite(got[3])
        assert got[1] == -np.inf and got[2] == -np.inf
