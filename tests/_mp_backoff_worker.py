"""Worker for the cross-process backoff-determinism test.

Launched (at least) twice by ``tests/test_elastic.py::
test_backoff_schedule_identical_across_processes`` as
``python _mp_backoff_worker.py``.  Prints the full ``backoff_delay``
schedule for a fixed grid of ``(seed, label, attempt)`` triples, one
``repr(float)`` per line.  The elastic scheduler's claim/steal fairness
(and the event-log replayability of a healed sweep) rests on every
process deriving the IDENTICAL schedule from the same policy inputs —
the parent asserts the two processes' stdout is byte-identical.

Deliberately jax-free: the schedule is pure host arithmetic
(SHA256-jittered exponential backoff, utils/retry.py) and must not
depend on any backend state.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    from bdlz_tpu.utils.retry import RetryPolicy, backoff_delay

    for seed in (0, 1, 12345):
        for label in ("chunk0:0", "chunk3:96", "probe:7", "weird label:\t"):
            policy = RetryPolicy(
                max_attempts=5, backoff_s=0.05, max_backoff_s=2.0, seed=seed,
            )
            for attempt in range(5):
                print(repr(backoff_delay(policy, label, attempt)))


if __name__ == "__main__":
    main()
