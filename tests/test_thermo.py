"""Unit tests for the thermo/cosmo library (SURVEY §4.2), including both
sides of the T = m/3 branch seam the archived numbers depend on."""
import math

import numpy as np
import pytest

from bdlz_tpu.constants import MPL_GEV, PI, ZETA3
from bdlz_tpu.physics.thermo import (
    entropy_density,
    hubble_rate,
    mean_speed_chi,
    n_chi_equilibrium,
    wall_flux,
)


def test_hubble_rate_formula():
    T, g = 100.0, 106.75
    assert hubble_rate(T, g, np) == pytest.approx(
        1.66 * math.sqrt(g) * T * T / MPL_GEV, rel=1e-15
    )


def test_entropy_density_formula():
    T, g = 3.7, 106.75
    assert entropy_density(T, g, np) == pytest.approx(
        (2 * PI**2 / 45) * g * T**3, rel=1e-15
    )


class TestEquilibriumDensity:
    m, g = 0.95, 2

    def test_relativistic_fermion(self):
        T = self.m  # T > m/3
        expected = self.g * (3 * ZETA3 / (4 * PI**2)) * T**3
        assert n_chi_equilibrium(T, self.m, self.g, "fermion", np) == expected

    def test_relativistic_boson(self):
        T = self.m
        expected = self.g * (ZETA3 / PI**2) * T**3
        assert n_chi_equilibrium(T, self.m, self.g, "boson", np) == expected

    def test_boltzmann_branch(self):
        T = self.m / 10.0
        expected = (
            self.g * (self.m / (2 * PI)) ** 1.5 * T**1.5 * math.exp(-self.m / T)
        )
        assert n_chi_equilibrium(T, self.m, self.g, "fermion", np) == pytest.approx(
            expected, rel=1e-15
        )

    def test_branch_seam_is_at_m_over_3_exclusive(self):
        """The predicate is strictly T > m/3 (reference :95): at exactly m/3
        the Maxwell-Boltzmann branch applies."""
        T_seam = self.m / 3.0
        mb = self.g * (self.m / (2 * PI)) ** 1.5 * T_seam**1.5 * math.exp(-3.0)
        assert n_chi_equilibrium(T_seam, self.m, self.g, "fermion", np) == pytest.approx(
            mb, rel=1e-14
        )
        just_above = np.nextafter(T_seam, np.inf)
        rel = self.g * (3 * ZETA3 / (4 * PI**2)) * just_above**3
        assert n_chi_equilibrium(just_above, self.m, self.g, "fermion", np) == rel

    def test_seam_discontinuity_magnitude(self):
        """The jump at the seam is ~x5.6 for the benchmark mass (SURVEY §2.1)."""
        T = self.m / 3.0
        below = n_chi_equilibrium(T, self.m, self.g, "fermion", np)
        above = n_chi_equilibrium(np.nextafter(T, np.inf), self.m, self.g, "fermion", np)
        assert 5.0 < above / below < 6.0

    def test_tiny_T_floor_no_warning(self):
        with np.errstate(over="raise", invalid="raise", divide="raise"):
            out = n_chi_equilibrium(np.array([0.0, 1e-40]), self.m, self.g, "fermion", np)
        assert np.all(out == 0.0)

    def test_vectorized_matches_scalar(self):
        Ts = np.geomspace(1e-3, 10.0, 101) * self.m
        vec = n_chi_equilibrium(Ts, self.m, self.g, "fermion", np)
        scl = np.array(
            [n_chi_equilibrium(float(T), self.m, self.g, "fermion", np) for T in Ts]
        )
        np.testing.assert_array_equal(vec, scl)


class TestMeanSpeed:
    def test_relativistic(self):
        assert mean_speed_chi(1.0, 0.95, np) == 1.0

    def test_nonrelativistic(self):
        T, m = 0.01, 0.95
        assert mean_speed_chi(T, m, np) == pytest.approx(
            math.sqrt(8 * T / (PI * m)), rel=1e-15
        )

    def test_mass_floor(self):
        # m floored at 1e-20 (reference :117); T <= m/3 needs tiny T too.
        T = 1e-30
        v = mean_speed_chi(T, 1e-25, np)
        assert v == pytest.approx(math.sqrt(8 * T / (PI * 1e-20)), rel=1e-15)


def test_wall_flux_composition():
    T, m, g = 0.1, 0.95, 2
    J = wall_flux(T, m, g, "fermion", np)
    assert J == pytest.approx(
        0.25
        * n_chi_equilibrium(T, m, g, "fermion", np)
        * mean_speed_chi(T, m, np),
        rel=1e-15,
    )
