"""Golden-output tests (SURVEY §4.1): the NumPy backend must reproduce the
archived benchmark numbers and match the reference script's stdout and
yields_out.json byte-for-byte.

The reference outputs are pinned as checked-in fixtures under
``tests/fixtures/reference_parity/`` (captured once from the snapshot), so
the default suite never EXECUTES the untrusted ``/root/reference`` script
(ADVICE r4).  Set ``BDLZ_RUN_REFERENCE_SUBPROCESS=1`` to additionally run
the live reference and re-verify the fixtures against it.
"""
import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from bdlz_tpu.config import (
    load_config,
    point_params_from_config,
    static_choices_from_config,
)
from bdlz_tpu.models.yields_pipeline import point_yields
from bdlz_tpu.physics.percolation import make_kjma_grid

# Archived golden values (reference PDF §6.3 Eqs. 19-21; BASELINE.md).
GOLDEN_Y_B = 8.7208853627e-11
GOLDEN_Y_CHI = 4.9e-10
GOLDEN_RATIO = 5.6889263349

REFERENCE_DIR = pathlib.Path("/root/reference")
REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
FIXTURE_DIR = pathlib.Path(__file__).resolve().parent / "fixtures" / "reference_parity"

#: Opt-in for executing the untrusted reference snapshot as a subprocess
#: (off by default — the pinned fixtures carry the parity contract).
RUN_REFERENCE = os.environ.get("BDLZ_RUN_REFERENCE_SUBPROCESS") == "1"
if RUN_REFERENCE and not REFERENCE_DIR.exists():
    # fail loudly rather than silently degrading to fixture-only: the
    # operator asked for live re-certification
    raise RuntimeError(
        "BDLZ_RUN_REFERENCE_SUBPROCESS=1 but /root/reference is not "
        "mounted — live re-certification cannot run"
    )


def _run_pipeline(script, config_path, cwd):
    """Run a yields pipeline script with --diagnostics; return (stdout, out_dict)."""
    env = {k: v for k, v in os.environ.items() if not k.startswith(("JAX_", "XLA_"))}
    r = subprocess.run(
        [sys.executable, str(script), "--config", str(config_path),
         "--diagnostics"],
        cwd=cwd, capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, (script, r.stderr)
    out = json.loads((pathlib.Path(cwd) / "yields_out.json").read_text())
    return r.stdout, out


def test_numpy_backend_reproduces_archived_numbers(benchmark_config_path):
    cfg = load_config(benchmark_config_path)
    pp = point_params_from_config(cfg, cfg.P_chi_to_B)
    static = static_choices_from_config(cfg)
    result = point_yields(pp, static, make_kjma_grid(np), np)

    assert float(result.Y_B) == pytest.approx(GOLDEN_Y_B, rel=2e-11)
    assert float(result.Y_chi) == GOLDEN_Y_CHI
    assert float(result.DM_over_B) == pytest.approx(GOLDEN_RATIO, rel=2e-11)
    # Densities are exact functions of the yields (reference :413-417).
    assert float(result.rho_B_kg_m3) == pytest.approx(4.217e-28, rel=1e-3)
    assert float(result.rho_DM_kg_m3) == pytest.approx(2.399e-27, rel=1e-3)


def test_bit_parity_with_reference_script(benchmark_config_path, tmp_path):
    """Our CLI's stdout and yields_out.json must match the reference
    byte-for-byte on the NumPy backend — compared against the pinned
    fixture by default; against the live reference script too under
    BDLZ_RUN_REFERENCE_SUBPROCESS=1."""
    ours_dir = tmp_path / "ours"
    ours_dir.mkdir()
    our_stdout, our_out = _run_pipeline(
        REPO_ROOT / "first_principles_yields.py", benchmark_config_path,
        ours_dir,
    )

    fix_stdout = (FIXTURE_DIR / "benchmark.stdout.txt").read_text()
    fix_out = json.loads((FIXTURE_DIR / "benchmark.yields_out.json").read_text())
    assert our_stdout == fix_stdout
    assert our_out["final"] == fix_out["final"]
    assert our_out["inputs"] == fix_out["inputs"]

    if RUN_REFERENCE:
        ref_dir = tmp_path / "ref"
        ref_dir.mkdir()
        ref_stdout, ref_out = _run_pipeline(
            REFERENCE_DIR / "first_principles_yields.py",
            benchmark_config_path, ref_dir,
        )
        # live reference also re-certifies the fixture isn't stale
        assert ref_stdout == fix_stdout
        assert ref_out["final"] == fix_out["final"]
        assert ref_out["inputs"] == fix_out["inputs"]


#: Non-default parameter points for the broadened parity sweep: each
#: exercises a different branch of the scalar pipeline (thermal regime,
#: boson statistics, clip-edge windows, non-default shape/dof values).
PARITY_VARIANTS = {
    "thermal-light": {
        "regime": "thermal", "m_chi_GeV": 0.4, "P_chi_to_B": 0.3,
        "source_shape_sigma_y": 6.0, "incident_flux_scale": 2e-9,
    },
    "boson-heavy": {
        "chi_stats": "boson", "g_chi": 1, "m_chi_GeV": 140.0,
        "T_p_GeV": 40.0, "P_chi_to_B": 0.08, "Y_chi_init": 1.1e-9,
        "incident_flux_scale": 5e-10,
    },
    "clip-edges": {
        "P_chi_to_B": 0.5, "beta_over_H": 300.0, "v_w": 0.08,
        "T_max_over_Tp": 8.0, "T_min_over_Tp": 1e-4,
        "source_shape_sigma_y": 25.0, "Y_chi_init": 4.9e-10,
    },
    "nonstandard-dof": {
        "g_star": 75.75, "g_star_s": 61.75, "I_p": 0.5,
        "P_chi_to_B": 0.149, "Y_chi_init": 4.9e-10,
    },
}


@pytest.mark.parametrize("name", sorted(PARITY_VARIANTS))
def test_bit_parity_across_config_variants(name, tmp_path):
    """Byte parity with the reference must hold across the pipeline's
    branches, not just at the archived benchmark point — fixtures by
    default, live reference under BDLZ_RUN_REFERENCE_SUBPROCESS=1."""
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps({"regime": "nonthermal",
                                    **PARITY_VARIANTS[name]}))

    ours_dir = tmp_path / "ours"
    ours_dir.mkdir()
    our_stdout, our_out = _run_pipeline(
        REPO_ROOT / "first_principles_yields.py", cfg_path, ours_dir,
    )

    fix_stdout = (FIXTURE_DIR / f"{name}.stdout.txt").read_text()
    fix_out = json.loads((FIXTURE_DIR / f"{name}.yields_out.json").read_text())
    assert our_stdout == fix_stdout
    assert our_out == fix_out

    if RUN_REFERENCE:
        ref_dir = tmp_path / "ref"
        ref_dir.mkdir()
        ref_stdout, ref_out = _run_pipeline(
            REFERENCE_DIR / "first_principles_yields.py", cfg_path, ref_dir,
        )
        assert ref_stdout == fix_stdout
        assert ref_out == fix_out
