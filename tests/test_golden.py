"""Golden-output tests (SURVEY §4.1): the NumPy backend must reproduce the
archived benchmark numbers, and — when the reference snapshot is mounted —
match the actual reference script's stdout and yields_out.json byte-for-byte.
"""
import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from bdlz_tpu.config import (
    load_config,
    point_params_from_config,
    static_choices_from_config,
)
from bdlz_tpu.models.yields_pipeline import point_yields
from bdlz_tpu.physics.percolation import make_kjma_grid

# Archived golden values (reference PDF §6.3 Eqs. 19-21; BASELINE.md).
GOLDEN_Y_B = 8.7208853627e-11
GOLDEN_Y_CHI = 4.9e-10
GOLDEN_RATIO = 5.6889263349

REFERENCE_DIR = pathlib.Path("/root/reference")
REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_numpy_backend_reproduces_archived_numbers(benchmark_config_path):
    cfg = load_config(benchmark_config_path)
    pp = point_params_from_config(cfg, cfg.P_chi_to_B)
    static = static_choices_from_config(cfg)
    result = point_yields(pp, static, make_kjma_grid(np), np)

    assert float(result.Y_B) == pytest.approx(GOLDEN_Y_B, rel=2e-11)
    assert float(result.Y_chi) == GOLDEN_Y_CHI
    assert float(result.DM_over_B) == pytest.approx(GOLDEN_RATIO, rel=2e-11)
    # Densities are exact functions of the yields (reference :413-417).
    assert float(result.rho_B_kg_m3) == pytest.approx(4.217e-28, rel=1e-3)
    assert float(result.rho_DM_kg_m3) == pytest.approx(2.399e-27, rel=1e-3)


@pytest.mark.skipif(not REFERENCE_DIR.exists(), reason="reference snapshot not mounted")
def test_bit_parity_with_reference_script(benchmark_config_path, tmp_path):
    """Run the actual reference pipeline and our CLI side by side; stdout
    and yields_out.json must match byte-for-byte on the NumPy backend."""
    env = {k: v for k, v in os.environ.items() if not k.startswith(("JAX_", "XLA_"))}

    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    ref = subprocess.run(
        [sys.executable, str(REFERENCE_DIR / "first_principles_yields.py"),
         "--config", benchmark_config_path, "--diagnostics"],
        cwd=ref_dir, capture_output=True, text=True, env=env, timeout=300,
    )
    assert ref.returncode == 0, ref.stderr

    ours_dir = tmp_path / "ours"
    ours_dir.mkdir()
    ours = subprocess.run(
        [sys.executable, str(REPO_ROOT / "first_principles_yields.py"),
         "--config", benchmark_config_path, "--diagnostics"],
        cwd=ours_dir, capture_output=True, text=True, env=env, timeout=300,
    )
    assert ours.returncode == 0, ours.stderr

    assert ours.stdout == ref.stdout

    ref_out = json.loads((ref_dir / "yields_out.json").read_text())
    our_out = json.loads((ours_dir / "yields_out.json").read_text())
    assert our_out["final"] == ref_out["final"]
    assert our_out["inputs"] == ref_out["inputs"]


#: Non-default parameter points for the broadened parity sweep: each
#: exercises a different branch of the scalar pipeline (thermal regime,
#: boson statistics, clip-edge windows, non-default shape/dof values).
PARITY_VARIANTS = {
    "thermal-light": {
        "regime": "thermal", "m_chi_GeV": 0.4, "P_chi_to_B": 0.3,
        "source_shape_sigma_y": 6.0, "incident_flux_scale": 2e-9,
    },
    "boson-heavy": {
        "chi_stats": "boson", "g_chi": 1, "m_chi_GeV": 140.0,
        "T_p_GeV": 40.0, "P_chi_to_B": 0.08, "Y_chi_init": 1.1e-9,
        "incident_flux_scale": 5e-10,
    },
    "clip-edges": {
        "P_chi_to_B": 0.5, "beta_over_H": 300.0, "v_w": 0.08,
        "T_max_over_Tp": 8.0, "T_min_over_Tp": 1e-4,
        "source_shape_sigma_y": 25.0, "Y_chi_init": 4.9e-10,
    },
    "nonstandard-dof": {
        "g_star": 75.75, "g_star_s": 61.75, "I_p": 0.5,
        "P_chi_to_B": 0.149, "Y_chi_init": 4.9e-10,
    },
}


@pytest.mark.skipif(not REFERENCE_DIR.exists(), reason="reference snapshot not mounted")
@pytest.mark.parametrize("name", sorted(PARITY_VARIANTS))
def test_bit_parity_across_config_variants(name, tmp_path):
    """Byte parity with the actual reference script must hold across the
    pipeline's branches, not just at the archived benchmark point."""
    env = {k: v for k, v in os.environ.items() if not k.startswith(("JAX_", "XLA_"))}
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps({"regime": "nonthermal",
                                    **PARITY_VARIANTS[name]}))

    dirs = {}
    for label, script in (
        ("ref", REFERENCE_DIR / "first_principles_yields.py"),
        ("ours", REPO_ROOT / "first_principles_yields.py"),
    ):
        d = tmp_path / label
        d.mkdir()
        r = subprocess.run(
            [sys.executable, str(script), "--config", str(cfg_path),
             "--diagnostics"],
            cwd=d, capture_output=True, text=True, env=env, timeout=300,
        )
        assert r.returncode == 0, (label, r.stderr)
        dirs[label] = (d, r.stdout)

    assert dirs["ours"][1] == dirs["ref"][1]
    ref_out = json.loads((dirs["ref"][0] / "yields_out.json").read_text())
    our_out = json.loads((dirs["ours"][0] / "yields_out.json").read_text())
    assert our_out == ref_out
