"""Two-channel LZ kernel tests (SURVEY §7.6): profile ingestion, crossing
finding, the analytic single-crossing limit, the batched-expm cross-check,
and the maybe_P seam contract."""
import numpy as np
import pytest

from bdlz_tpu.lz import (
    BounceProfile,
    ProfileError,
    find_crossings,
    lambda_eff_from_profile,
    local_lambdas,
    probability_from_lambda,
    probability_from_profile,
    transfer_matrix_propagation,
    load_profile_csv,
)


def linear_profile(alpha=1.0, kappa=0.1, L=200.0, N=40000):
    xi = np.linspace(-L, L, N)
    return BounceProfile(xi=xi, delta=alpha * xi, mix=np.full_like(xi, kappa))


class TestProfileIO:
    def test_delta_mix_schema(self, tmp_path):
        p = tmp_path / "p.csv"
        p.write_text("xi,delta,m_mix\n-1.0,-2.0,0.1\n0.0,0.0,0.1\n1.0,2.0,0.1\n")
        prof = load_profile_csv(str(p))
        assert prof.xi.tolist() == [-1.0, 0.0, 1.0]
        assert prof.delta.tolist() == [-2.0, 0.0, 2.0]

    def test_mass_matrix_schema(self, tmp_path):
        p = tmp_path / "m.csv"
        p.write_text("xi,m11,m22,m12\n0.0,1.0,2.0,0.3\n1.0,3.0,1.0,0.4\n")
        prof = load_profile_csv(str(p))
        np.testing.assert_allclose(prof.delta, [-1.0, 2.0])
        np.testing.assert_allclose(prof.mix, [0.3, 0.4])

    def test_missing_columns_raise(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("xi,foo\n0,1\n1,2\n")
        with pytest.raises(ProfileError, match="columns"):
            load_profile_csv(str(p))

    def test_too_few_rows_raise(self, tmp_path):
        p = tmp_path / "short.csv"
        p.write_text("xi,delta,m_mix\n0,1,0.1\n")
        with pytest.raises(ProfileError, match="at least 2"):
            load_profile_csv(str(p))

    def test_unsorted_xi_raises_with_row_index(self, tmp_path):
        # Silent argsort used to reorder (Δ, m_mix) against the caller's
        # file; the contract is now strictly-increasing-or-loud.
        p = tmp_path / "u.csv"
        p.write_text("xi,delta,m_mix\n1.0,2.0,0.2\n-1.0,-2.0,0.1\n")
        with pytest.raises(ProfileError, match="data row 2"):
            load_profile_csv(str(p))

    def test_duplicate_xi_raises_with_row_index(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text(
            "xi,delta,m_mix\n0.0,-1.0,0.1\n1.0,0.0,0.1\n1.0,1.0,0.1\n2.0,2.0,0.1\n"
        )
        with pytest.raises(ProfileError, match="data row 3"):
            load_profile_csv(str(p))

    def test_single_row_names_offending_row(self, tmp_path):
        p = tmp_path / "one.csv"
        p.write_text("xi,delta,m_mix\n0.5,1.0,0.1\n")
        with pytest.raises(ProfileError, match="data row 1"):
            load_profile_csv(str(p))


class TestCrossingFinder:
    def test_single_linear_crossing(self):
        prof = linear_profile(alpha=2.0, kappa=0.3, L=10.0, N=1001)
        c = find_crossings(prof)
        assert c.xi_star.size == 1
        assert c.xi_star[0] == pytest.approx(0.0, abs=1e-12)
        assert c.slope[0] == pytest.approx(2.0, rel=1e-12)
        assert c.mix[0] == pytest.approx(0.3, rel=1e-12)

    def test_multi_crossing(self):
        xi = np.linspace(0.0, 4 * np.pi, 4001)
        prof = BounceProfile(xi=xi, delta=np.sin(xi), mix=np.full_like(xi, 0.1))
        c = find_crossings(prof)
        # sin is exactly zero at the xi=0 boundary sample and changes sign
        # at pi, 2pi, 3pi
        assert c.xi_star.size == 4
        np.testing.assert_allclose(
            c.xi_star, [0.0, np.pi, 2 * np.pi, 3 * np.pi], atol=1e-4
        )

    def test_lambda_locals(self):
        prof = linear_profile(alpha=2.0, kappa=0.3, L=10.0, N=1001)
        lams = local_lambdas(find_crossings(prof), v_w=0.5)
        assert lams[0] == pytest.approx(0.3**2 / (0.5 * 2.0), rel=1e-9)


class TestProbabilityMaps:
    def test_lambda_to_P(self):
        assert probability_from_lambda(0.0) == 0.0
        assert probability_from_lambda(-1.0) == 0.0  # clamped (reference :183)
        assert probability_from_lambda(1e9) == 1.0
        lam = 0.25
        assert probability_from_lambda(lam) == pytest.approx(
            1.0 - np.exp(-2 * np.pi * lam), rel=1e-15
        )

    def test_lambda_eff_sums_crossings(self):
        xi = np.linspace(0.5, 3.5 * np.pi, 40001)  # avoid boundary zeros
        prof = BounceProfile(xi=xi, delta=np.sin(xi), mix=np.full_like(xi, 0.1))
        lam = lambda_eff_from_profile(prof, v_w=1.0)
        # three crossings (pi, 2pi, 3pi), each |slope|=1, mix=0.1 -> 0.01 each
        assert lam == pytest.approx(0.03, rel=1e-3)


class TestCoherentPropagation:
    def test_single_crossing_matches_analytic_LZ(self):
        """The distributed kernel must reduce to P = 1 − e^(−2πλ) in the
        single-crossing limit (paper Eq. 9) — the only analytic anchor."""
        alpha, kappa, v_w = 1.0, 0.1, 1.0
        prof = linear_profile(alpha=alpha, kappa=kappa)
        _, P = transfer_matrix_propagation(prof, v_w)
        lam = kappa**2 / (v_w * alpha)
        assert P == pytest.approx(probability_from_lambda(lam), rel=1e-3)

    def test_wall_velocity_dependence(self):
        """Slower wall => more adiabatic => larger conversion."""
        prof = linear_profile()
        _, P_slow = transfer_matrix_propagation(prof, 0.5)
        _, P_fast = transfer_matrix_propagation(prof, 2.0)
        assert P_slow > P_fast

    def test_su2_equals_generic_expm(self):
        """Real-quaternion path == vmapped jax.scipy.linalg.expm path."""
        xi = np.linspace(-5.0, 5.0, 301)
        prof = BounceProfile(xi=xi, delta=xi.copy(), mix=np.full_like(xi, 0.3))
        U1, P1 = transfer_matrix_propagation(prof, 0.5)
        U2, P2 = transfer_matrix_propagation(prof, 0.5, use_generic_expm=True)
        np.testing.assert_allclose(U1, U2, atol=1e-13)
        assert P1 == pytest.approx(P2, abs=1e-13)

    def test_unitarity(self):
        U, P = transfer_matrix_propagation(linear_profile(N=5001), 0.7)
        np.testing.assert_allclose(U @ U.conj().T, np.eye(2), atol=1e-12)
        assert 0.0 <= P <= 1.0

    def test_zero_mixing_no_conversion(self):
        xi = np.linspace(-10, 10, 1001)
        prof = BounceProfile(xi=xi, delta=xi.copy(), mix=np.zeros_like(xi))
        _, P = transfer_matrix_propagation(prof, 0.5)
        assert P == 0.0

    def test_adiabatic_limit_full_conversion(self):
        """Huge mixing / slow wall: adiabatic following, P -> 1."""
        prof = linear_profile(alpha=1.0, kappa=2.0, L=50.0, N=20000)
        _, P = transfer_matrix_propagation(prof, 0.1)
        assert P > 0.99


class TestSeamContract:
    """(profile_csv, v_w) -> P in [0,1] — the reference maybe_P plug-in
    contract (`first_principles_yields.py:317-328`)."""

    def _write_profile(self, tmp_path, prof):
        p = tmp_path / "profile.csv"
        rows = "\n".join(
            f"{x},{d},{m}" for x, d, m in zip(prof.xi, prof.delta, prof.mix)
        )
        p.write_text("xi,delta,m_mix\n" + rows + "\n")
        return str(p)

    def test_coherent_and_local_agree_single_crossing(self, tmp_path):
        prof = linear_profile()
        path = self._write_profile(tmp_path, prof)
        P_coh = probability_from_profile(path, 1.0)
        P_loc = probability_from_profile(path, 1.0, method="local")
        assert 0.0 <= P_coh <= 1.0 and 0.0 <= P_loc <= 1.0
        assert P_coh == pytest.approx(P_loc, rel=2e-3)

    def test_cli_seam(self, tmp_path, benchmark_config_path, capsys):
        """CLI --maybe-compute-P-from-profile actually uses the kernel."""
        from bdlz_tpu.cli import resolve_P
        from bdlz_tpu.config import load_config

        prof = linear_profile(N=2001)
        path = self._write_profile(tmp_path, prof)
        cfg = load_config(benchmark_config_path)
        P = resolve_P(cfg, path)
        out = capsys.readouterr().out
        assert "[info] Using P_chi_to_B from profile:" in out
        assert 0.0 < P < 1.0
        assert P != cfg.P_chi_to_B

    def test_bad_method_raises(self, tmp_path):
        path = self._write_profile(tmp_path, linear_profile(N=101))
        with pytest.raises(ValueError, match="method"):
            probability_from_profile(path, 1.0, method="bogus")

    def test_cli_seam_method_selection(self, tmp_path, benchmark_config_path,
                                       capsys):
        """The main CLI's estimator flags reach the kernel: dephased at
        Γ = 0 equals the coherent default, and the flag pairing is
        validated like the sweep/MCMC CLIs."""
        from bdlz_tpu.cli import main as cli_main, resolve_P
        from bdlz_tpu.config import load_config

        prof = linear_profile(N=2001)
        path = self._write_profile(tmp_path, prof)
        cfg = load_config(benchmark_config_path)
        P_coh = resolve_P(cfg, path)
        P_dep0 = resolve_P(cfg, path, lz_method="dephased", lz_gamma_phi=0.0)
        out = capsys.readouterr().out
        # both resolutions must have come FROM THE PROFILE — a silent
        # fall-back to cfg.P_chi_to_B would make the parity check vacuous
        assert out.count("[info] Using P_chi_to_B from profile:") == 2
        assert P_dep0 == pytest.approx(P_coh, rel=1e-9)
        assert P_coh != cfg.P_chi_to_B
        # caller-contract errors raise instead of warn-and-fall-back
        with pytest.raises(ValueError, match="no effect"):
            resolve_P(cfg, path, lz_method="coherent", lz_gamma_phi=0.5)
        with pytest.raises(SystemExit):
            cli_main(["--config", benchmark_config_path,
                      "--lz-method", "dephased"])  # no profile
        with pytest.raises(SystemExit):
            cli_main(["--config", benchmark_config_path,
                      "--maybe-compute-P-from-profile", path,
                      "--lz-gamma-phi", "0.5"])  # gamma without dephased


class TestMomentumAveraging:
    """Paper §10's F(k) layer: flux-weighted thermal average of the coherent
    kernel over incident χ momenta."""

    def test_cold_limit_recovers_wall_speed(self):
        """T → 0 with m > 0: every χ is at rest in the plasma frame, so the
        wall-frame traversal speed is v_w for all nodes and <P> = P(v_w)
        exactly (F_k = 1)."""
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        prof = linear_profile(alpha=1.0, kappa=0.05, N=4000)
        v_w = 0.3
        P_avg, F_k = momentum_averaged_probability(
            prof, v_w, T_GeV=1e-16, m_GeV=1.0
        )
        _, P_wall = transfer_matrix_propagation(prof, v_w)
        assert P_avg == pytest.approx(P_wall, rel=1e-6)
        assert F_k == pytest.approx(1.0, rel=1e-6)

    def test_average_is_a_convex_combination(self):
        """<P> must lie within the range of P over the sampled speeds, and
        inside [0, 1]."""
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        prof = linear_profile(alpha=1.0, kappa=0.05, N=4000)
        P_avg, F_k = momentum_averaged_probability(
            prof, v_w=0.3, T_GeV=0.5, m_GeV=0.95
        )
        assert 0.0 <= P_avg <= 1.0
        assert np.isfinite(F_k) and F_k > 0.0

    def test_quadrature_converged_local(self):
        """The smooth analytic (local) average with the segmented
        quadrature: doubling both orders moves <P> by <2e-6 rel
        (measured ~3e-7 at the 128x24 defaults)."""
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        prof = linear_profile(alpha=1.0, kappa=0.05, N=4000)
        P1, _ = momentum_averaged_probability(
            prof, v_w=0.3, T_GeV=1.0, m_GeV=0.95, method="local"
        )
        P2, _ = momentum_averaged_probability(
            prof, v_w=0.3, T_GeV=1.0, m_GeV=0.95, n_k=256, n_mu=48, method="local"
        )
        assert P1 == pytest.approx(P2, rel=2e-6)

    def test_quadrature_coherent_phase_jitter_bounded(self):
        """The coherent average carries Stuckelberg-phase sampling jitter
        (the observable oscillates in 1/v_n); doubling the orders must stay
        within the documented ~1e-3 relative band and near the smooth
        local-composition average."""
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        prof = linear_profile(alpha=1.0, kappa=0.05, N=4000)
        P1, _ = momentum_averaged_probability(
            prof, v_w=0.3, T_GeV=1.0, m_GeV=0.95, n_k=64, n_mu=16
        )
        P2, _ = momentum_averaged_probability(
            prof, v_w=0.3, T_GeV=1.0, m_GeV=0.95, n_k=128, n_mu=24
        )
        assert P1 == pytest.approx(P2, rel=2e-2)
        P_loc, _ = momentum_averaged_probability(
            prof, v_w=0.3, T_GeV=1.0, m_GeV=0.95, method="local"
        )
        assert P1 == pytest.approx(P_loc, rel=5e-2)

    def test_hot_limit_averages_over_speeds(self):
        """Relativistic bath (T >> m): incident speeds spread toward 1, so
        the average must differ from the single-speed estimate for a
        velocity-sensitive crossing (F_k != 1)."""
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        prof = linear_profile(alpha=1.0, kappa=0.05, N=4000)
        P_avg, F_k = momentum_averaged_probability(
            prof, v_w=0.1, T_GeV=100.0, m_GeV=0.95
        )
        assert abs(F_k - 1.0) > 1e-3


def test_cli_momentum_average_flag(tmp_path, capsys, monkeypatch):
    """--lz-momentum-average routes P through the momentum-averaged kernel
    and reports F_k; the result block format is unchanged."""
    import json

    from bdlz_tpu.cli import main

    prof = tmp_path / "prof.csv"
    xi = np.linspace(-200, 200, 2000)
    rows = "\n".join(f"{x},{x},{0.05}" for x in xi)
    prof.write_text("xi,delta,m_mix\n" + rows + "\n")

    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "regime": "nonthermal", "P_chi_to_B": 0.5, "Y_chi_init": 4.9e-10,
        "incident_flux_scale": 1.07e-9, "source_shape_sigma_y": 9.0,
    }))
    monkeypatch.chdir(tmp_path)
    main(["--config", str(cfg), "--maybe-compute-P-from-profile", str(prof),
          "--lz-momentum-average"])
    out = capsys.readouterr().out
    assert "momentum-averaged LZ kernel: F_k =" in out
    assert "[info] Using P_chi_to_B from profile:" in out
    assert "DM/B ratio=" in out


class TestSweepBridge:
    """Per-sweep-point LZ probabilities (the seam resolved inside scans)."""

    def test_local_matches_single_point_kernel(self):
        from bdlz_tpu.lz import probabilities_for_points

        prof = linear_profile(alpha=1.0, kappa=0.05)
        v_ws = np.array([0.1, 0.3, 0.3, 0.7])
        P = probabilities_for_points(prof, v_ws, method="local")
        lam1 = float(np.sum(local_lambdas(find_crossings(prof), v_w=1.0)))
        np.testing.assert_allclose(P, 1.0 - np.exp(-2 * np.pi * lam1 / v_ws), rtol=1e-14)
        # repeated v_w values get identical P
        assert P[1] == P[2]

    def test_coherent_dedup_matches_per_point(self):
        from bdlz_tpu.lz import probabilities_for_points

        prof = linear_profile(alpha=1.0, kappa=0.05)
        v_ws = np.array([0.2, 0.5, 0.2])
        P = probabilities_for_points(prof, v_ws, method="coherent")
        for i, vw in enumerate(v_ws):
            _, P_ref = transfer_matrix_propagation(prof, float(vw))
            assert P[i] == pytest.approx(float(P_ref), rel=1e-10)

    def test_momentum_method_requires_thermo_inputs(self):
        from bdlz_tpu.lz import probabilities_for_points

        prof = linear_profile()
        with pytest.raises(ValueError, match="local-momentum"):
            probabilities_for_points(prof, [0.3], method="local-momentum")

    def test_fingerprint_distinguishes_profiles(self):
        from bdlz_tpu.lz import profile_fingerprint

        a = profile_fingerprint(linear_profile(alpha=1.0))
        b = profile_fingerprint(linear_profile(alpha=1.1))
        assert a != b
        assert a == profile_fingerprint(linear_profile(alpha=1.0))


class TestDephased:
    """Density-matrix (Bloch) distributed-LZ transport with diabatic-basis
    dephasing: Γ = 0 must reproduce the coherent SU(2) kernel exactly, and
    Γ → ∞ must kill Stückelberg interference, approaching the classical
    composition of per-crossing flips."""

    def _two_crossing_profile(self, alpha=0.1, kappa=0.34, x0=20.0, N=40001):
        # Δ = α(ξ² − x0²): zeros at ±x0 with slope 2αx0; LZ zones of width
        # ~κ/(2αx0) around each, far narrower than the 2·x0 separation, so
        # between-crossing coherence and within-crossing dynamics have
        # cleanly separated timescales for the dephasing to discriminate.
        L = 2.0 * x0
        xi = np.linspace(-L, L, N)
        return BounceProfile(
            xi=xi, delta=alpha * (xi * xi - x0 * x0), mix=np.full_like(xi, kappa)
        )

    def test_gamma_zero_matches_coherent(self):
        import jax.numpy as jnp

        from bdlz_tpu.lz.kernel import (
            _segment_hamiltonians,
            propagate_bloch,
            propagate_quaternion,
        )

        prof = self._two_crossing_profile()
        a, b, dxi = _segment_hamiltonians(prof, jnp)
        for v in (0.3, 0.62, 0.95):
            q = np.asarray(propagate_quaternion(a, b, dxi, jnp.asarray(v), jnp))
            P_coh = q[1] ** 2 + q[2] ** 2
            r = np.asarray(propagate_bloch(
                a, b, dxi, jnp.asarray(v), jnp.asarray(0.0), jnp
            ))
            assert np.abs(np.linalg.norm(r) - 1.0) < 1e-10  # pure state stays pure
            P_bloch = 0.5 * (1.0 - r[2])
            assert P_bloch == pytest.approx(P_coh, rel=1e-9, abs=1e-12), v

    def test_large_gamma_approaches_incoherent_composition(self):
        from bdlz_tpu.lz.kernel import dephased_probability, local_lambdas

        prof = self._two_crossing_profile()
        v = 0.5
        lams = local_lambdas(find_crossings(prof), v)
        assert lams.size == 2
        p1, p2 = (1.0 - np.exp(-2.0 * np.pi * lams))
        P_incoh = p1 * (1.0 - p2) + (1.0 - p1) * p2
        # Γ chosen so Γ·τ_sep ≈ 40 (inter-crossing coherence dead) while
        # Γ·τ_zone ≈ 0.1 (single-crossing dynamics barely touched)
        P_deph = dephased_probability(prof, v, gamma_phi=0.5)
        assert P_deph == pytest.approx(P_incoh, rel=0.1)

    def test_dephasing_damps_stueckelberg_oscillations(self):
        from bdlz_tpu.lz.sweep_bridge import probabilities_for_points

        prof = self._two_crossing_profile()
        vs = np.linspace(0.4, 0.6, 41)
        P_coh = probabilities_for_points(prof, vs, method="coherent")
        P_mid = probabilities_for_points(
            prof, vs, method="dephased", gamma_phi=0.05
        )
        P_dead = probabilities_for_points(
            prof, vs, method="dephased", gamma_phi=1.0
        )
        swing = lambda P: P.max() - P.min()  # noqa: E731
        assert swing(P_coh) > 0.1  # the interference structure is there
        assert swing(P_mid) < swing(P_coh)
        assert swing(P_dead) < 0.2 * swing(P_coh)
        assert np.all((P_dead >= 0.0) & (P_dead <= 1.0))

    def test_dephased_table_matches_host_kernel(self):
        import jax.numpy as jnp

        from bdlz_tpu.lz.kernel import dephased_probability
        from bdlz_tpu.lz.sweep_bridge import eval_P_table, make_P_of_vw_table

        prof = self._two_crossing_profile(N=2001)
        tab = make_P_of_vw_table(
            prof, "dephased", 0.3, 0.9, n=4096, gamma_phi=0.2, xp=jnp
        )
        rng = np.random.default_rng(5)
        vs = rng.uniform(0.3, 0.9, 16)
        got = np.asarray(eval_P_table(jnp.asarray(vs), tab, jnp))
        ref = np.array([dephased_probability(prof, v, 0.2) for v in vs])
        assert np.abs(got - ref).max() < 1e-6

    def test_matches_exact_lindblad_expm(self):
        """Independent cross-check of the D@R splitting: the exact
        per-segment Bloch generator is G = 2[B]_x - diag(Γ, Γ, 0) with
        B = (b, 0, a) (from dρ/dt = -i[H, ρ] + Γ/2 (σ_z ρ σ_z - ρ)), and
        its real 3x3 expm composed across segments is the exact channel.
        The kernel's rotation-then-decay splitting must agree to the
        O(Γ ω τ²) commutator error — driven to ~1e-6 by segment
        refinement (the same mechanism as its Magnus midpoint rule)."""
        from scipy.linalg import expm as scipy_expm

        from bdlz_tpu.lz.kernel import _segment_hamiltonians, propagate_bloch
        import jax.numpy as jnp

        prof = self._two_crossing_profile(alpha=0.5, kappa=0.4, x0=3.0, N=8001)
        v, gam = 0.6, 0.3
        a, b, dxi = (np.asarray(x) for x in _segment_hamiltonians(prof, np))
        tau = dxi / v
        r = np.array([0.0, 0.0, 1.0])
        for ai, bi, ti in zip(a, b, tau):
            Bx = np.array([
                [0.0, -ai, 0.0],
                [ai, 0.0, -bi],
                [0.0, bi, 0.0],
            ])  # 2[B]_x for B = (b, 0, a): cross-product matrix doubled
            G = 2.0 * Bx - np.diag([gam, gam, 0.0])
            r = scipy_expm(G * ti) @ r
        P_exact = 0.5 * (1.0 - r[2])

        aj, bj, dj = _segment_hamiltonians(prof, jnp)
        rk = np.asarray(propagate_bloch(
            aj, bj, dj, jnp.asarray(v), jnp.asarray(gam), jnp
        ))
        P_kernel = 0.5 * (1.0 - rk[2])
        assert P_kernel == pytest.approx(P_exact, abs=2e-6)

    def test_momentum_average_dephased(self):
        """The F(k) layer accepts the dephased estimator: Γ = 0 matches
        the coherent average, and a finite Γ stays a valid probability."""
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        prof = self._two_crossing_profile(N=801)
        P0, F0 = momentum_averaged_probability(
            prof, 0.5, 100.0, 0.95, n_k=32, n_mu=8,
            method="dephased", gamma_phi=0.0,
        )
        Pc, Fc = momentum_averaged_probability(
            prof, 0.5, 100.0, 0.95, n_k=32, n_mu=8, method="coherent",
        )
        assert P0 == pytest.approx(Pc, rel=1e-9)
        Pd, Fd = momentum_averaged_probability(
            prof, 0.5, 100.0, 0.95, n_k=32, n_mu=8,
            method="dephased", gamma_phi=0.5,
        )
        assert 0.0 <= Pd <= 1.0 and np.isfinite(Fd)

    def test_negative_gamma_rejected(self):
        from bdlz_tpu.lz.kernel import dephased_probability
        from bdlz_tpu.lz.sweep_bridge import probabilities_for_points

        prof = self._two_crossing_profile(N=201)
        with pytest.raises(ValueError, match="gamma_phi"):
            dephased_probability(prof, 0.5, -0.1)
        with pytest.raises(ValueError, match="gamma_phi"):
            probabilities_for_points(
                prof, [0.5], method="dephased", gamma_phi=-1.0
            )
        # a rate the method would silently ignore is a caller error
        with pytest.raises(ValueError, match="no effect"):
            probabilities_for_points(
                prof, [0.5], method="coherent", gamma_phi=0.5
            )
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        with pytest.raises(ValueError, match="no effect"):
            momentum_averaged_probability(
                prof, 0.5, 100.0, 0.95, n_k=16, n_mu=4,
                method="local", gamma_phi=0.5,
            )

    def test_cli_error_checks_negativity_first(self):
        # ADVICE r3: the negative-rate message must win regardless of the
        # method pairing, matching validate_gamma_phi's check order.
        from bdlz_tpu.lz.kernel import gamma_phi_cli_error

        assert gamma_phi_cli_error("dephased", 0.5) is None
        assert gamma_phi_cli_error("coherent", 0.0) is None
        for method in ("coherent", "local", "momentum", "dephased"):
            assert ">= 0" in gamma_phi_cli_error(method, -1.0)
        assert "dephased" in gamma_phi_cli_error("coherent", 0.5)

    def test_seam_contract(self, tmp_path):
        """(csv, v_w) → P ∈ [0,1] through probability_from_profile."""
        prof = self._two_crossing_profile(N=2001)
        p = tmp_path / "prof.csv"
        rows = "\n".join(
            f"{x},{d},{m}" for x, d, m in zip(prof.xi, prof.delta, prof.mix)
        )
        p.write_text("xi,delta,m_mix\n" + rows + "\n")
        P = probability_from_profile(str(p), 0.5, method="dephased", gamma_phi=0.3)
        assert 0.0 <= P <= 1.0
        P0 = probability_from_profile(str(p), 0.5, method="dephased", gamma_phi=0.0)
        Pc = probability_from_profile(str(p), 0.5, method="coherent")
        assert P0 == pytest.approx(Pc, rel=1e-9)


class TestPTable:
    """P(v_w) interpolation tables: the in-jit bridge that makes the
    coherent and momentum-averaged estimators samplable (MCMC) — built on
    a uniform 1/v grid because both the LZ exponents and the Stückelberg
    phases are smooth in u = 1/v."""

    def _gentle_profile(self):
        # short support => few Stückelberg oscillation periods over the
        # u-range, so the table error is dominated by cubic interpolation
        xi = np.linspace(-2.0, 2.0, 201)
        return BounceProfile(xi=xi, delta=2.0 * xi, mix=np.full_like(xi, 0.3))

    def test_coherent_table_matches_host_kernel(self):
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import (
            eval_P_table,
            make_P_of_vw_table,
            probabilities_for_points,
        )

        prof = self._gentle_profile()
        tab = make_P_of_vw_table(prof, "coherent", 0.2, 0.95, n=1024, xp=jnp)
        rng = np.random.default_rng(1)
        vs = rng.uniform(0.2, 0.95, 32)
        got = np.asarray(eval_P_table(jnp.asarray(vs), tab, jnp))
        ref = probabilities_for_points(prof, vs, method="coherent")
        # measured 2.6e-10 at n=1024 on this profile (4th-order cubic)
        assert np.abs(got - ref).max() < 1e-8

    def test_momentum_batch_matches_unbatched(self):
        from bdlz_tpu.lz.momentum import (
            local_momentum_average_batch,
            momentum_averaged_probability,
        )

        prof = self._gentle_profile()
        vws = np.array([0.07, 0.35, 0.8])
        batch = local_momentum_average_batch(prof, vws, 100.0, 0.95)
        for vw, got in zip(vws, batch):
            ref, _ = momentum_averaged_probability(
                prof, float(vw), 100.0, 0.95, method="local"
            )
            assert got == pytest.approx(ref, rel=1e-13), vw

    def test_momentum_table_matches_batch_kernel(self):
        import jax.numpy as jnp

        from bdlz_tpu.lz.momentum import local_momentum_average_batch
        from bdlz_tpu.lz.sweep_bridge import eval_P_table, make_P_of_vw_table

        prof = self._gentle_profile()
        tab = make_P_of_vw_table(
            prof, "local-momentum", 0.05, 0.95, n=512,
            T_p_GeV=100.0, m_chi_GeV=0.95, xp=jnp,
        )
        rng = np.random.default_rng(2)
        vs = rng.uniform(0.05, 0.95, 16)
        got = np.asarray(eval_P_table(jnp.asarray(vs), tab, jnp))
        ref = local_momentum_average_batch(prof, vs, 100.0, 0.95)
        assert np.abs(got - ref).max() < 1e-6

    def test_eval_clamps_to_domain(self):
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import eval_P_table, make_P_of_vw_table

        prof = self._gentle_profile()
        tab = make_P_of_vw_table(prof, "coherent", 0.3, 0.8, n=64, xp=jnp)
        inside = np.asarray(eval_P_table(jnp.asarray([0.3, 0.8]), tab, jnp))
        outside = np.asarray(eval_P_table(jnp.asarray([0.05, 0.99]), tab, jnp))
        np.testing.assert_allclose(outside, inside, rtol=1e-12)
        assert np.all((outside >= 0.0) & (outside <= 1.0))

    def test_rejects_local_and_bad_domains(self):
        from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_table

        prof = self._gentle_profile()
        with pytest.raises(ValueError, match="analytic"):
            make_P_of_vw_table(prof, "local", 0.1, 0.9)
        with pytest.raises(ValueError, match="v_lo"):
            make_P_of_vw_table(prof, "coherent", 0.9, 0.1)
        with pytest.raises(ValueError, match="pinned"):
            make_P_of_vw_table(prof, "local-momentum", 0.1, 0.9)


def test_local_momentum_points_match_unbatched_kernel():
    """The grouped jit-batched local-momentum sweep path must agree with
    the unbatched per-point average across mixed thermal states."""
    from bdlz_tpu.lz.momentum import momentum_averaged_probability
    from bdlz_tpu.lz.sweep_bridge import probabilities_for_points

    xi = np.linspace(-2.0, 2.0, 201)
    prof = BounceProfile(xi=xi, delta=2.0 * xi, mix=np.full_like(xi, 0.3))
    v_w = np.array([0.1, 0.5, 0.1, 0.8, 0.5])
    T_p = np.array([100.0, 100.0, 40.0, 40.0, 100.0])
    m = np.array([0.95, 0.95, 2.0, 2.0, 0.95])
    P = probabilities_for_points(
        prof, v_w, method="local-momentum", T_p_GeV=T_p, m_chi_GeV=m
    )
    for i in range(len(v_w)):
        ref, _ = momentum_averaged_probability(
            prof, float(v_w[i]), float(T_p[i]), float(m[i]), method="local"
        )
        assert P[i] == pytest.approx(ref, rel=1e-12), i
    # repeated (v, T, m) combinations get identical values
    assert P[1] == P[4]


class TestMomentumDephasedEdges:
    """lz/momentum.py dephased-averaging edge cases (scenario-plane PR
    satellite): the Γ_φ = 0 average must reduce to the coherent one
    BITWISE (the thermal_method_for dispatch routes zero rate through
    the quaternion path, not the ~1e-15-away SO(3) Bloch path), a
    single-node profile degenerates cleanly, and an empty speed window
    returns empty instead of crashing the batch builder."""

    xi = np.linspace(-20.0, 20.0, 401)
    prof = BounceProfile(
        xi=xi, delta=-0.08 * np.tanh(xi / 4.0), mix=np.full_like(xi, 0.02)
    )

    def test_gamma_zero_bitwise_reduces_to_coherent(self):
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        Pd, Fd = momentum_averaged_probability(
            self.prof, 0.3, 100.0, 0.95, n_k=32, n_mu=8,
            method="dephased", gamma_phi=0.0,
        )
        Pc, Fc = momentum_averaged_probability(
            self.prof, 0.3, 100.0, 0.95, n_k=32, n_mu=8, method="coherent",
        )
        # bitwise, not approx: same program on the same inputs
        assert Pd == Pc and Fd == Fc

    def test_gamma_positive_differs_from_coherent(self):
        # the dispatch must not swallow a real rate
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        Pd, _ = momentum_averaged_probability(
            self.prof, 0.3, 100.0, 0.95, n_k=32, n_mu=8,
            method="dephased", gamma_phi=0.5,
        )
        Pc, _ = momentum_averaged_probability(
            self.prof, 0.3, 100.0, 0.95, n_k=32, n_mu=8, method="coherent",
        )
        assert Pd != Pc

    def test_negative_gamma_still_rejected(self):
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        with pytest.raises(ValueError, match="gamma_phi"):
            momentum_averaged_probability(
                self.prof, 0.3, 100.0, 0.95,
                method="dephased", gamma_phi=-1.0,
            )

    def test_single_node_profile_degenerates_cleanly(self):
        # one profile sample = zero segments = identity propagator:
        # nothing converts, and F_k = <P>/P(v_w) is 0/0 -> nan, reported
        # not raised (the CLI's warn-and-fall-back seam absorbs it)
        from bdlz_tpu.lz.momentum import momentum_averaged_probability

        single = BounceProfile(
            xi=np.array([0.0]), delta=np.array([0.1]), mix=np.array([0.02])
        )
        P, F_k = momentum_averaged_probability(
            single, 0.3, 100.0, 0.95, n_k=16, n_mu=8,
            method="dephased", gamma_phi=0.0,
        )
        assert P == 0.0
        assert np.isnan(F_k)

    def test_empty_speed_window_returns_empty(self):
        from bdlz_tpu.lz.momentum import local_momentum_average_batch

        out = local_momentum_average_batch(self.prof, [], 100.0, 0.95)
        assert out.shape == (0,)
