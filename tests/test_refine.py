"""Closed-loop continuous-delivery tests (bdlz_tpu/refine/*; ROADMAP item 4).

The acceptance arc rides ONE module-scoped environment (`loop_env`):
a narrow-box seed emulator serves a fleet whose traffic has drifted
half out of the box, the refinement daemon detects the drift from the
armed per-query trace, persists the content-hashed snapshot, rebuilds
over the expanded box as elastic chunks steered by
``refine_signal="traffic"``, and the delivery pipeline auto-publishes
the winner — every test then reads the frozen outcome (fallback-rate
drop, identity keys, snapshot round-trip, bitwise far-OOD parity,
budget exhaustion) without re-running the cycle.

Everything is driven by a fake clock and explicit run_once/poll/step
calls — zero sleeps, zero wall-clock dependence (the test_fleet
contract).  The poisoned-candidate rollback test reuses the cycle's
published candidate against a fresh fault-armed fleet: promotion,
SLO breach, auto-rollback, and bit-identical seed answers on both
sides of the failed rollout.
"""
import dataclasses
import json
import types

import numpy as np
import pytest

from bdlz_tpu.config import ConfigError, config_from_dict, validate
from bdlz_tpu.emulator.artifact import (
    EmulatorArtifact,
    EmulatorArtifactError,
    build_identity,
    check_identity,
)
from bdlz_tpu.refine import (
    TRAFFIC_SCHEMA_VERSION,
    DeliveryPipeline,
    RefineError,
    RefinementDaemon,
    TrafficModel,
    TrafficSnapshot,
    TrafficSnapshotError,
    load_snapshot,
    resolve_self_improve,
    save_snapshot,
    snapshot_entry_name,
)
from bdlz_tpu.serve.service import (
    REASON_OOD,
    REASON_PREDICTED_ERROR,
    gate_fallback_masks,
)
from bdlz_tpu.utils.profiling import ServeStats


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


BASE = config_from_dict({
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
})

AXES = ("m_chi_GeV", "T_p_GeV")
#: The drifted request distribution: uniform over a box that hangs
#: ~half outside the seed emulator's domain (m_chi in [0.9, 1.0],
#: T_p in [90, 100]) — the OOD mass the closed loop must absorb.
DRIFT_LO = np.array([0.95, 95.0])
DRIFT_HI = np.array([1.08, 108.0])
#: Far outside BOTH the seed box and any traffic-expanded box: this
#: query takes the exact-pipeline fallback before AND after the
#: rollout, so its answer must be bit-identical across the cycle.
FAR_OOD = np.array([2.0, 150.0])
BUILD_KW = dict(n_probe=6, max_rounds=2, n_y=200, rtol=1e-3, chunk_size=16)


def _pump(svc, clock):
    clock.advance(0.01)
    svc.run_once(force=True)
    svc.poll(block=True)


def _serve_block(svc, clock, thetas):
    futs = [svc.submit(t) for t in np.atleast_2d(thetas)]
    _pump(svc, clock)
    return [f.result() for f in futs]


@pytest.fixture(scope="module")
def loop_env(tmp_path_factory, jit_warmup):
    """Run the full closed loop ONCE; tests assert on the frozen record."""
    from bdlz_tpu.emulator.build import AxisSpec, build_emulator
    from bdlz_tpu.provenance import Store
    from bdlz_tpu.serve.fleet import FleetService

    store = Store(str(tmp_path_factory.mktemp("refine_store")))
    spec = {
        "m_chi_GeV": AxisSpec(0.9, 1.0, 3, "log"),
        "T_p_GeV": AxisSpec(90.0, 100.0, 3, "log"),
    }
    seed_art, seed_report = build_emulator(BASE, spec, cache=store, **BUILD_KW)
    clock = FakeClock()
    svc = FleetService(
        seed_art, BASE, max_batch_size=8, n_replicas=2,
        routing="round_robin", max_wait_s=1e-3, clock=clock,
    )
    daemon = RefinementDaemon(
        svc, BASE, store=store, clock=clock, window=256, min_queries=32,
        drift_gated_rate=0.05, rebuild_budget=1, observe_s=0.5,
        build_kw=BUILD_KW, elastic=2,
    )
    rng = np.random.default_rng(7)

    far_before = _serve_block(svc, clock, FAR_OOD)[0]

    # hour 1: drifted traffic; the daemon steps between batches and
    # runs its one autonomous cycle the moment the window proves drift
    statuses = []
    for _ in range(8):
        _serve_block(svc, clock, rng.uniform(DRIFT_LO, DRIFT_HI, (8, 2)))
        statuses.append(daemon.step())
    fb1_rows = list(svc.stats.rows)
    fb1 = sum(r.n_fallback for r in fb1_rows) / sum(r.size for r in fb1_rows)

    far_after = _serve_block(svc, clock, FAR_OOD)[0]
    candidate_art = svc.artifact

    # hour 2: the SAME drifted distribution against the new surface
    h2_start = len(svc.stats.rows)
    for _ in range(8):
        _serve_block(svc, clock, rng.uniform(DRIFT_LO, DRIFT_HI, (8, 2)))
    h2_rows = svc.stats.rows[h2_start:]
    fb2 = sum(r.n_fallback for r in h2_rows) / sum(r.size for r in h2_rows)

    # a SECOND drift, past the budget: traffic far outside even the
    # rebuilt box must park the daemon in "exhausted", not rebuild
    for _ in range(5):
        _serve_block(
            svc, clock, rng.uniform([1.5, 150.0], [1.6, 160.0], (8, 2))
        )
    exhausted_status = daemon.step()

    return types.SimpleNamespace(
        store=store, clock=clock, svc=svc, daemon=daemon,
        seed_art=seed_art, seed_report=seed_report,
        seed_hash=seed_art.content_hash,
        candidate_art=candidate_art,
        statuses=statuses, history=list(daemon.history),
        fb1=fb1, fb2=fb2,
        far_before=far_before, far_after=far_after,
        exhausted_status=exhausted_status,
    )


# ---- satellite: vectorized gating parity ----------------------------


class TestGateFallbackMasks:
    @staticmethod
    def _loop_reference(inside, pred_err, tol):
        """The original per-request Python loop, kept as the parity
        oracle for the vectorized reason assignment."""
        inside = np.asarray(inside, dtype=bool)
        if tol is not None and pred_err is not None:
            gated = inside & (np.asarray(pred_err) > tol)
        else:
            gated = np.zeros(inside.shape, dtype=bool)
        fallback = ~inside | gated
        reasons = []
        for k in range(inside.shape[0]):
            if not inside[k]:
                reasons.append(REASON_OOD)
            elif gated[k]:
                reasons.append(REASON_PREDICTED_ERROR)
            else:
                reasons.append(None)
        return fallback, gated, reasons

    def test_bitwise_parity_with_loop_reference(self):
        rng = np.random.default_rng(11)
        for trial in range(50):
            n = int(rng.integers(0, 40))
            inside = rng.random(n) < 0.6
            pred_err = rng.random(n) * 2e-3
            tol = [None, 1e-3, 0.0][trial % 3]
            pe = None if trial % 5 == 0 else pred_err
            got = gate_fallback_masks(inside, pe, tol)
            want = self._loop_reference(inside, pe, tol)
            assert np.array_equal(got[0], want[0])
            assert np.array_equal(got[1], want[1])
            assert got[2] == want[2]

    def test_extremes(self):
        for inside in ([], [True] * 4, [False] * 4):
            got = gate_fallback_masks(np.array(inside, dtype=bool),
                                      np.zeros(len(inside)), 1e-3)
            want = self._loop_reference(np.array(inside, dtype=bool),
                                        np.zeros(len(inside)), 1e-3)
            assert got[2] == want[2]
            assert np.array_equal(got[0], want[0])

    def test_ood_wins_over_gate(self):
        # geometry is the stronger statement: an OOD request over the
        # error gate reads "ood", never "predicted_error"
        _, _, reasons = gate_fallback_masks(
            np.array([False]), np.array([1.0]), 1e-6
        )
        assert reasons == [REASON_OOD]


# ---- satellite: traffic trace is opt-in and schema-neutral ----------


class TestTrafficLog:
    def test_unarmed_record_is_noop(self):
        st = ServeStats()
        st.record_queries(np.ones((4, 2)), "ood")
        assert st.traffic_log is None

    def test_summary_schema_unchanged_by_arming(self):
        def fill(st):
            st.record_batch(batch_index=0, size=4, occupancy=0.5,
                            wait_s=0.01, n_fallback=1, seconds=0.1)
            st.record_latency(0.02)

        plain, armed = ServeStats(), ServeStats()
        fill(plain)
        armed.arm_traffic_log()
        fill(armed)
        armed.record_queries(np.ones((4, 2)), [None, "ood", None, None])
        assert json.dumps(plain.summary(), sort_keys=True) == json.dumps(
            armed.summary(), sort_keys=True
        )

    def test_armed_capture_broadcasts_reasons(self):
        st = ServeStats()
        st.arm_traffic_log()
        st.record_queries([1.0, 2.0])                   # one row, no reason
        st.record_queries(np.ones((2, 2)), "degraded")  # scalar broadcast
        st.record_queries(np.zeros((2, 2)), ["ood", None])
        assert [r for _, r in st.traffic_log] == [
            None, "degraded", "degraded", "ood", None,
        ]
        assert st.traffic_log[0][0] == (1.0, 2.0)


# ---- snapshots: construction, persistence, rejection ----------------


def _snap(n=8, seed=0, reasons=None):
    rng = np.random.default_rng(seed)
    locs = rng.uniform([0.9, 90.0], [1.1, 110.0], (n, 2))
    if reasons is None:
        reasons = tuple("ood" if k % 2 else None for k in range(n))
    return TrafficSnapshot(AXES, locs, reasons, {"default": 0.5})


class TestTrafficSnapshot:
    def test_rejects_nan_locations_loudly(self):
        locs = np.ones((3, 2))
        locs[1, 0] = np.nan
        with pytest.raises(TrafficSnapshotError, match="non-finite"):
            TrafficSnapshot(AXES, locs, (None, None, None))

    def test_rejects_shape_and_reason_mismatch(self):
        with pytest.raises(TrafficSnapshotError, match="does not match"):
            TrafficSnapshot(AXES, np.ones((3, 5)), (None,) * 3)
        with pytest.raises(TrafficSnapshotError, match="reasons"):
            TrafficSnapshot(AXES, np.ones((3, 2)), (None,) * 2)

    def test_rates(self):
        s = _snap(n=4, reasons=("ood", "ood", "predicted_error", None))
        assert s.ood_rate == 0.5
        assert s.gated_rate == 0.25
        assert s.fallback_rate == 0.75

    def test_fingerprint_is_content_addressed(self):
        a, b = _snap(seed=1), _snap(seed=1)
        assert a.fingerprint == b.fingerprint
        assert len(a.fingerprint) == 16
        c = _snap(seed=1, reasons=tuple("ood" for _ in range(8)))
        assert c.fingerprint != a.fingerprint
        d = TrafficSnapshot(a.axis_names, a.locations, a.reasons,
                            {"default": 0.9})
        assert d.fingerprint != a.fingerprint

    def test_split_holdout_deterministic_and_disjoint(self):
        s = _snap(n=40, seed=3)
        train, held = s.split_holdout(0.25)
        train2, held2 = s.split_holdout(0.25)
        assert np.array_equal(held, held2)
        assert held.shape[0] == 10
        assert train.n_queries == 30
        both = np.vstack([train.locations, held])
        assert both.shape[0] == s.n_queries
        # disjoint: every original row lands in exactly one side
        assert {tuple(r) for r in both} == {tuple(r) for r in s.locations}

    def test_split_holdout_tiny_window_trains_on_everything(self):
        s = _snap(n=5)
        train, held = s.split_holdout(0.25)
        assert train.n_queries == 5 and held.shape[0] == 5

    def test_split_holdout_bad_frac(self):
        with pytest.raises(TrafficSnapshotError, match="frac"):
            _snap().split_holdout(1.5)

    def test_persist_roundtrip(self, tmp_path):
        from bdlz_tpu.provenance import Store

        store = Store(str(tmp_path / "s"))
        s = _snap(n=12, seed=5)
        fp = save_snapshot(store, s)
        assert fp == s.fingerprint
        # atomic_write_json landed a real file under the store root
        assert (tmp_path / "s" / snapshot_entry_name(fp)).is_file()
        back = load_snapshot(store, fp)
        assert np.array_equal(back.locations, s.locations)
        assert back.reasons == s.reasons
        assert back.occupancy == s.occupancy
        assert back.fingerprint == fp

    def test_load_rejects_missing_and_skew_and_tamper(self, tmp_path):
        from bdlz_tpu.provenance import Store

        store = Store(str(tmp_path / "s"))
        with pytest.raises(TrafficSnapshotError, match="not in the store"):
            load_snapshot(store, "0" * 16)
        s = _snap(n=6, seed=9)
        fp = save_snapshot(store, s)
        # schema version skew: a future writer's payload is refused
        payload = store.get_json(snapshot_entry_name(fp))
        payload["schema"] = TRAFFIC_SCHEMA_VERSION + 1
        store.put_json(snapshot_entry_name(fp), payload)
        with pytest.raises(TrafficSnapshotError, match="schema version"):
            load_snapshot(store, fp)
        # content/name mismatch: the entry was renamed or edited
        payload["schema"] = TRAFFIC_SCHEMA_VERSION
        payload["reasons"] = ["ood"] * 6
        store.put_json(snapshot_entry_name(fp), payload)
        with pytest.raises(TrafficSnapshotError, match="hashes to"):
            load_snapshot(store, fp)


class TestTrafficModel:
    def test_fold_is_incremental_by_cursor(self):
        st = ServeStats()
        st.arm_traffic_log()
        m = TrafficModel(AXES, window=100)
        st.record_queries(np.ones((3, 2)), "ood")
        assert m.fold(st) == 3
        assert m.fold(st) == 0          # nothing new
        st.record_queries(np.zeros((2, 2)))
        assert m.fold(st) == 2
        assert m.n_queries == 5
        assert m.ood_rate == 0.6

    def test_window_bound_drops_oldest(self):
        st = ServeStats()
        st.arm_traffic_log()
        m = TrafficModel(AXES, window=4)
        st.record_queries(np.ones((3, 2)), "ood")
        st.record_queries(np.zeros((3, 2)))
        m.fold(st)
        assert m.n_queries == 4
        assert m.ood_rate == 0.25       # only one "ood" survives

    def test_reset_window_keeps_cursors(self):
        st = ServeStats()
        st.arm_traffic_log()
        m = TrafficModel(AXES)
        st.record_queries(np.ones((3, 2)))
        m.fold(st)
        m.reset_window()
        assert m.n_queries == 0
        assert m.fold(st) == 0          # old entries never re-folded

    def test_occupancy_rides_stats_rows(self):
        st = ServeStats()
        st.arm_traffic_log()
        st.record_batch(batch_index=0, size=4, occupancy=0.5,
                        wait_s=0.0, n_fallback=0, seconds=0.1)
        st.record_batch(batch_index=1, size=8, occupancy=1.0,
                        wait_s=0.0, n_fallback=0, seconds=0.1)
        m = TrafficModel(AXES)
        m.fold(st)
        assert m.occupancy() == {"default": 0.75}

    def test_empty_snapshot_raises(self):
        with pytest.raises(TrafficSnapshotError, match="nothing to"):
            TrafficModel(AXES).snapshot()

    def test_bad_window(self):
        with pytest.raises(TrafficSnapshotError, match="window"):
            TrafficModel(AXES, window=0)


# ---- satellite: identity keys ---------------------------------------


def _ident_artifact(**ident_kw):
    """A fabricated artifact carrying a real build identity (the
    test_fleet pattern) — the identity layer never looks at values."""
    from bdlz_tpu.config import static_choices_from_config

    static = static_choices_from_config(BASE)._replace(quad_panel_gl=False)
    nodes = (np.linspace(0.9, 1.1, 4), np.geomspace(90.0, 110.0, 5))
    rng = np.random.default_rng(42)
    return EmulatorArtifact(
        axis_names=AXES,
        axis_nodes=nodes,
        axis_scales=("log", "log"),
        values={"DM_over_B": np.exp(rng.normal(size=(4, 5)))},
        identity=build_identity(BASE, static, 400, "tabulated", **ident_kw),
        manifest={},
    )


class TestTrafficIdentity:
    def test_signal_and_fingerprint_split_the_hash(self):
        plain = _ident_artifact()
        fisher = _ident_artifact(refine_signal="fisher")
        traffic = _ident_artifact(refine_signal="traffic", traffic_fp="ab12")
        product = _ident_artifact(
            refine_signal="traffic*planck", traffic_fp="ab12"
        )
        other = _ident_artifact(refine_signal="traffic", traffic_fp="cd34")
        hashes = {a.content_hash
                  for a in (plain, fisher, traffic, product, other)}
        assert len(hashes) == 5
        # omit-at-default: the pre-traffic identity carries NO key, so
        # every artifact built before this PR keeps its hash
        assert "traffic" not in dict(plain.identity)
        assert "traffic" not in dict(fisher.identity)
        assert dict(traffic.identity)["traffic"] == "ab12"
        assert dict(traffic.identity)["refine_signal"] == "traffic"

    def test_check_identity_wildcard_when_unstated(self):
        art = _ident_artifact(refine_signal="traffic", traffic_fp="ab12")
        want = dict(_ident_artifact().identity)
        # a caller that says nothing about traffic admits any build
        check_identity(art, want)

    def test_check_identity_strict_when_stated(self):
        want = dict(
            _ident_artifact(refine_signal="traffic",
                            traffic_fp="ab12").identity
        )
        with pytest.raises(EmulatorArtifactError):
            check_identity(_ident_artifact(), want)       # key missing
        with pytest.raises(EmulatorArtifactError):
            check_identity(                                # key differs
                _ident_artifact(refine_signal="traffic", traffic_fp="cd34"),
                want,
            )
        check_identity(
            _ident_artifact(refine_signal="traffic", traffic_fp="ab12"),
            want,
        )

    def test_signal_flip_invalidates_resume(self):
        """An expected identity that names a refinement signal rejects a
        surface refined by a different one — flipping the knob can never
        silently resume onto the old artifact."""
        fisher = _ident_artifact(refine_signal="fisher")
        want_traffic = dict(
            _ident_artifact(refine_signal="traffic",
                            traffic_fp="ab12").identity
        )
        with pytest.raises(EmulatorArtifactError):
            check_identity(fisher, want_traffic)
        want_fisher = dict(fisher.identity)
        with pytest.raises(EmulatorArtifactError):
            check_identity(
                _ident_artifact(refine_signal="curvature"), want_fisher
            )


# ---- knobs ----------------------------------------------------------


class TestKnobs:
    def test_resolve_self_improve_tristate(self):
        auto = BASE
        on = dataclasses.replace(BASE, self_improve=True)
        off = dataclasses.replace(BASE, self_improve=False)
        assert auto.self_improve is None
        assert resolve_self_improve(auto) is False          # ambient: off
        assert resolve_self_improve(auto, explicit=True)    # daemon: on
        assert resolve_self_improve(on) and resolve_self_improve(
            on, explicit=True
        )
        assert not resolve_self_improve(off, explicit=True)  # forced off

    def test_validate_rejects_bad_knobs(self):
        with pytest.raises(ConfigError, match="drift_gated_rate"):
            validate(dataclasses.replace(BASE, drift_gated_rate=0.0))
        with pytest.raises(ConfigError, match="drift_gated_rate"):
            validate(dataclasses.replace(BASE, drift_gated_rate=1.5))
        with pytest.raises(ConfigError, match="rebuild_budget"):
            validate(dataclasses.replace(BASE, rebuild_budget=0))
        with pytest.raises(ConfigError, match="self_improve"):
            validate(dataclasses.replace(BASE, self_improve="yes"))
        validate(dataclasses.replace(
            BASE, self_improve=True, drift_gated_rate=0.2, rebuild_budget=3
        ))

    def test_daemon_refuses_forced_off_and_storeless(self, tmp_path):
        from bdlz_tpu.provenance import Store

        svc = types.SimpleNamespace(artifact=_ident_artifact(),
                                    stats=ServeStats())
        off = dataclasses.replace(BASE, self_improve=False)
        with pytest.raises(RefineError, match="forces the closed loop"):
            RefinementDaemon(svc, off, store=Store(str(tmp_path / "s")))
        with pytest.raises(RefineError, match="store"):
            RefinementDaemon(svc, BASE, store=None)

    def test_build_rejects_traffic_signal_mismatch(self):
        from bdlz_tpu.emulator.build import (
            AxisSpec,
            EmulatorBuildError,
            build_emulator,
        )

        spec = {"m_chi_GeV": AxisSpec(0.9, 1.0, 3, "log"),
                "T_p_GeV": AxisSpec(90.0, 100.0, 3, "log")}
        with pytest.raises(EmulatorBuildError, match="traffic"):
            build_emulator(BASE, spec, refine_signal="traffic")
        with pytest.raises(EmulatorBuildError, match="refine_signal"):
            build_emulator(BASE, spec, traffic=_snap())
        wrong_axes = TrafficSnapshot(
            ("T_p_GeV", "m_chi_GeV"), np.ones((4, 2)), (None,) * 4
        )
        with pytest.raises(EmulatorBuildError, match="axes"):
            build_emulator(
                BASE, spec, refine_signal="traffic", traffic=wrong_axes
            )


# ---- the acceptance arc ---------------------------------------------


class TestClosedLoop:
    def test_drift_detected_and_one_cycle_ran(self, loop_env):
        assert len(loop_env.history) == 1
        row = loop_env.history[0]
        assert row["build_converged"]
        assert row["n_queries"] >= 32
        assert row["snapshot_ood_rate"] > 0.05
        assert row["decision"]["outcome"] == "promoted"
        # the winner won on held-out traffic, strictly
        d = row["decision"]
        assert d["candidate_score"] < d["serving_score"]
        assert d["serving_hash"] == loop_env.seed_hash

    def test_fallback_rate_drops_at_least_2x(self, loop_env):
        assert loop_env.fb1 > 0.2
        assert loop_env.fb2 < loop_env.fb1 / 2

    def test_candidate_identity_names_signal_and_snapshot(self, loop_env):
        ident = dict(loop_env.candidate_art.identity)
        assert ident["refine_signal"] == "traffic"
        # the identity names the TRAIN split — exactly what steered the
        # rebuild, never the held-out rows the delivery gate scored on
        assert ident["traffic"] == loop_env.history[0]["train_snapshot"]
        assert ident["traffic"] != loop_env.history[0]["snapshot"]
        assert loop_env.candidate_art.content_hash != loop_env.seed_hash
        assert loop_env.svc.artifact_hash == (
            loop_env.history[0]["decision"]["published_hash"]
        )
        # the snapshot fingerprint also rides the manifest for humans
        man = loop_env.candidate_art.manifest
        assert man["traffic_fingerprint"] == ident["traffic"]
        assert man["traffic_queries"] > 0

    def test_snapshot_persisted_and_reverifies(self, loop_env):
        fp = loop_env.history[0]["snapshot"]
        snap = load_snapshot(loop_env.store, fp)
        assert snap.fingerprint == fp
        assert snap.n_queries == loop_env.history[0]["n_queries"]
        assert snap.axis_names == AXES
        assert "default" in snap.occupancy
        # the train split is persisted too: the candidate identity's
        # traffic hash resolves from the store alone
        train = load_snapshot(
            loop_env.store, loop_env.history[0]["train_snapshot"]
        )
        assert train.n_queries < snap.n_queries
        t2, _ = snap.split_holdout(0.25)
        assert t2.fingerprint == train.fingerprint

    def test_rebuilt_box_covers_observed_traffic(self, loop_env):
        from bdlz_tpu.emulator.grid import make_domain_fn
        import jax.numpy as jnp

        # the box covers every TRAIN query (what the rebuild was steered
        # by) — held-out rows may stay outside (the far-OOD probe does)
        train = load_snapshot(
            loop_env.store, loop_env.history[0]["train_snapshot"]
        )
        inside = np.asarray(
            make_domain_fn(loop_env.candidate_art)(
                jnp.asarray(train.locations)
            ),
            dtype=bool,
        )
        assert inside.all()

    def test_far_ood_answer_bit_identical_across_rollout(self, loop_env):
        assert loop_env.far_before.fallback_reason == REASON_OOD
        assert loop_env.far_after.fallback_reason == REASON_OOD
        b = np.float64(loop_env.far_before.value)
        a = np.float64(loop_env.far_after.value)
        assert b.tobytes() == a.tobytes()

    def test_budget_exhausted_parks_instead_of_rebuilding(self, loop_env):
        st = loop_env.exhausted_status
        assert st["state"] == "exhausted"
        assert st["drifted"] is True
        assert st["cycles"] == 1
        assert len(loop_env.history) == 1    # no second cycle
        assert loop_env.daemon.state == "exhausted"

    def test_elastic_rebuild_matches_serial_bitwise(self, loop_env):
        """The cycle's candidate was built as elastic chunks through the
        work-stealing scheduler; a from-scratch SERIAL rebuild of the
        same snapshot over the same expanded box must hash identically —
        elasticity buys wall-clock, never a different surface."""
        from bdlz_tpu.emulator.build import build_emulator

        train = load_snapshot(
            loop_env.store, loop_env.history[0]["train_snapshot"]
        )
        spec = loop_env.daemon._expanded_spec(
            train, artifact=loop_env.seed_art
        )
        kw = dict(BUILD_KW)
        if "impl" in dict(loop_env.seed_art.identity):
            kw["impl"] = dict(loop_env.seed_art.identity)["impl"]
        serial, _ = build_emulator(
            BASE, spec, refine_signal="traffic", traffic=train,
            cache=None, **kw,
        )
        assert serial.content_hash == loop_env.candidate_art.content_hash


# ---- poisoned candidate: auto-rollback ------------------------------


class TestPoisonedRollback:
    def test_breaching_rollout_rolls_back_bit_identically(self, loop_env):
        """The acceptance fault arc: the same winning candidate, staged
        onto a fleet whose replicas carry an injected slow fault, blows
        the post-cutover latency SLO on its first observed batch and is
        rolled back automatically — the hash rows show the N→N+1→N arc
        and the seed surface answers bit-identically on both sides of
        the failed rollout."""
        from bdlz_tpu.provenance import fetch_artifact
        from bdlz_tpu.serve.fleet import FleetService

        clock = FakeClock()
        cfg = dataclasses.replace(
            BASE,
            fault_plan=json.dumps({"faults": [{
                "site": "replica_dispatch", "kind": "slow", "delay_s": 2.0,
            }]}),
        )
        svc = FleetService(
            loop_env.seed_art, cfg, max_batch_size=8, n_replicas=2,
            routing="round_robin", max_wait_s=1e-3, clock=clock,
            health=False,
        )
        seed_hash = loop_env.seed_hash
        cand_hash = loop_env.candidate_art.content_hash
        probes = np.random.default_rng(13).uniform(
            [0.92, 92.0], [0.99, 99.0], (8, 2)
        )
        before = [r.value for r in _serve_block(svc, clock, probes)]

        pipe = DeliveryPipeline(
            svc, loop_env.store, observe_s=1.0,
            rollback_budget=0.1, latency_slo_s=0.5,
        )
        decision = pipe.deliver(
            fetch_artifact(loop_env.store, cand_hash),
            load_snapshot(
                loop_env.store, loop_env.history[0]["snapshot"]
            ).split_holdout(0.25)[1],
        )
        assert decision["outcome"] == "promoted"
        assert svc.artifact_hash == cand_hash

        # first post-cutover batch: +2 s injected → SLO breach → rollback
        _serve_block(svc, clock, probes)
        assert svc.artifact_hash == seed_hash
        rb = svc.stats.extras["rollbacks"]
        assert len(rb) == 1
        assert rb[0]["from"] == cand_hash and rb[0]["to"] == seed_hash
        assert "error budget exceeded" in rb[0]["reason"]

        after_resp = _serve_block(svc, clock, probes)
        after = [r.value for r in after_resp]
        assert np.asarray(before, dtype=np.float64).tobytes() == (
            np.asarray(after, dtype=np.float64).tobytes()
        )
        assert all(r.artifact_hash == seed_hash for r in after_resp)
        # exactly one batch was ever answered by the poisoned rollout
        hashes = [r.artifact_hash for r in svc.stats.rows]
        assert hashes.count(cand_hash) == 1
        assert hashes[0] == seed_hash and hashes[-1] == seed_hash


# ---- rejected candidates stay unpublished ---------------------------


class TestDeliveryGate:
    def test_non_improving_candidate_rejected_without_publish(
        self, loop_env
    ):
        """A candidate that cannot beat the serving surface on held-out
        traffic is dropped before the registry ever sees it: serving the
        CURRENT artifact as its own candidate scores identically, and
        identical is not better."""
        import os

        from bdlz_tpu.provenance.registry import ARTIFACT_KIND
        from bdlz_tpu.serve.fleet import FleetService

        clock = FakeClock()
        svc = FleetService(
            loop_env.candidate_art, BASE, max_batch_size=8, n_replicas=2,
            max_wait_s=1e-3, clock=clock,
        )
        held = load_snapshot(
            loop_env.store, loop_env.history[0]["snapshot"]
        ).split_holdout(0.25)[1]
        reg_dir = os.path.join(loop_env.store.root, ARTIFACT_KIND)
        published_before = sorted(os.listdir(reg_dir))
        pipe = DeliveryPipeline(svc, loop_env.store, observe_s=1.0)
        decision = pipe.deliver(loop_env.candidate_art, held)
        assert decision["outcome"] == "rejected"
        assert "published_hash" not in decision
        assert svc.artifact_hash == loop_env.candidate_art.content_hash
        assert sorted(os.listdir(reg_dir)) == published_before

    def test_tol_resolution_chain(self, loop_env):
        svc = types.SimpleNamespace(
            artifact=loop_env.seed_art, error_gate_tol=None,
            stats=ServeStats(),
        )
        pipe = DeliveryPipeline.__new__(DeliveryPipeline)
        pipe._tol = None
        pipe.service = svc
        # falls through to the candidate's advertised build tolerance
        assert pipe._resolve_tol(loop_env.seed_art) == pytest.approx(
            loop_env.seed_art.manifest["rtol_target"]
        )
        pipe._tol = 5e-3
        assert pipe._resolve_tol(loop_env.seed_art) == 5e-3
        svc.error_gate_tol = 2e-3
        pipe._tol = None
        assert pipe._resolve_tol(loop_env.seed_art) == 2e-3


# ---- satellite: lint pins -------------------------------------------


def test_refine_package_lint_clean():
    """The closed-loop subsystem is host-side orchestration by
    construction (daemon control flow, snapshot IO, delivery policy) —
    pinned per-file at zero unsuppressed findings so a regression names
    the module, and so the new STATIC_PARAM_NAMES entries
    (self_improve/drift_gated_rate/rebuild_budget) keep it out of
    tracer-analysis false positives."""
    import pathlib

    from bdlz_tpu.lint.analyzer import lint_paths

    pkg = pathlib.Path(__file__).resolve().parents[1] / "bdlz_tpu"
    report = lint_paths([
        str(pkg / "refine" / "__init__.py"),
        str(pkg / "refine" / "traffic.py"),
        str(pkg / "refine" / "daemon.py"),
        str(pkg / "refine" / "delivery.py"),
    ])
    assert report.files_scanned == 4
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"refine findings:\n{offenders}"


# ---- satellite: CLI flag-layer refusals -----------------------------


class TestServeCLIFlags:
    """`--self-improve` has exactly one home — the fleet front — and
    the refusals fire at the flag layer (argparse `ap.error`, exit 2),
    never mid-serve."""

    @staticmethod
    def _cfg(tmp_path):
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }))
        return str(cfg)

    def test_self_improve_requires_fleet_front(self, tiny_emulator,
                                               tmp_path):
        base, out_dir, _, _ = tiny_emulator
        from bdlz_tpu.serve.serve_cli import main

        with pytest.raises(SystemExit) as exc:
            main(["--config", self._cfg(tmp_path), "--artifact", out_dir,
                  "--self-improve", "on"])
        assert exc.value.code == 2

    def test_self_improve_refuses_tenant_map(self, tmp_path, capsys):
        from bdlz_tpu.serve.serve_cli import main

        with pytest.raises(SystemExit) as exc:
            main(["--config", self._cfg(tmp_path),
                  "--tenant-map", '{"coherent": "0123456789abcdef"}',
                  "--self-improve", "on"])
        assert exc.value.code == 2
        assert "tenant-map" in capsys.readouterr().err
