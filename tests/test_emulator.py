"""Yield-surface emulator tests (bdlz_tpu/emulator/).

Tier-1 pins, via the tiny session fixture (3 initial nodes per axis,
narrow box, n_y=400):

* build→save→load→query round-trips, with the refinement loop actually
  exercised (the lin-scale v_w axis must be split) and the held-out
  error inside the fixture's 1e-4 tolerance;
* every staleness/corruption path rejects LOUDLY with
  ``EmulatorArtifactError``: schema-version skew, content-hash mismatch
  (tampered knobs), NaN/inf and non-positive table cells;
* the emulator-backed MCMC fast mode agrees with the exact logp and
  enforces its preconditions;
* manifest writes across the repo are atomic (shared utils helper).

The wide-box build with heavy refinement is `slow`.
"""
import json
import os

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.emulator import (
    AxisSpec,
    EmulatorArtifactError,
    EmulatorBuildError,
    artifact_hash,
    build_emulator,
    check_identity,
    load_artifact,
    make_domain_fn,
    make_exact_evaluator,
    make_query_fn,
    save_artifact,
)
from bdlz_tpu.validation import GateFailure, relative_errors


def _corrupt_field(src_dir, dst_dir, mutate, rehash=True):
    """Copy an artifact dir, mutate one value cell, optionally re-hash.

    ``rehash=True`` keeps the manifest hash CONSISTENT with the
    corrupted table, so the load failure isolates the finiteness/
    positivity check; ``rehash=False`` exercises the hash check itself.
    """
    art = load_artifact(src_dir)
    values = {k: np.array(v) for k, v in art.values.items()}
    mutate(values)
    os.makedirs(dst_dir, exist_ok=True)
    arrays = {f"axis_{n}": np.asarray(a) for n, a in
              zip(art.axis_names, art.axis_nodes)}
    arrays.update({f"field_{n}": v for n, v in values.items()})
    np.savez(os.path.join(dst_dir, "artifact.npz"), **arrays)
    manifest = dict(art.manifest)
    if rehash:
        manifest["hash"] = artifact_hash(
            art.axis_names, art.axis_nodes, art.axis_scales, values,
            art.identity,
        )
    with open(os.path.join(dst_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    return dst_dir


class TestBuildAndQuery:
    def test_fixture_converged_within_tolerance(self, tiny_emulator):
        _, _, artifact, report = tiny_emulator
        assert report.converged
        # the acceptance tolerance, measured on a HELD-OUT random set
        assert report.max_rel_err <= 1e-4
        assert artifact.manifest["max_rel_err"] == report.max_rel_err
        assert artifact.manifest["converged"] is True

    def test_refinement_actually_ran(self, tiny_emulator):
        _, _, artifact, report = tiny_emulator
        # the lin-scale v_w axis carries real log-curvature: the build
        # must have split it past its 3 initial nodes, while the two
        # power-law log axes stay untouched
        assert report.axis_nodes["v_w"] > 3
        assert report.axis_nodes["m_chi_GeV"] == 3
        assert len(report.rounds) >= 2

    def test_save_load_query_round_trip(self, tiny_emulator):
        base, out_dir, artifact, _ = tiny_emulator
        loaded = load_artifact(out_dir)
        assert loaded.axis_names == artifact.axis_names
        assert loaded.axis_scales == artifact.axis_scales
        for f in artifact.values:
            np.testing.assert_array_equal(
                loaded.values[f], artifact.values[f]
            )
        # queries at the grid nodes reproduce the stored values exactly
        # (interpolation weights collapse onto one corner)
        nodes = loaded.axis_nodes
        corners = np.stack([
            [nodes[0][0], nodes[1][0], nodes[2][0]],
            [nodes[0][-1], nodes[1][-1], nodes[2][-1]],
            [nodes[0][1], nodes[1][1], nodes[2][1]],
        ])
        got = np.asarray(make_query_fn(loaded)(corners))
        want = [
            loaded.values["DM_over_B"][0, 0, 0],
            loaded.values["DM_over_B"][-1, -1, -1],
            loaded.values["DM_over_B"][1, 1, 1],
        ]
        np.testing.assert_allclose(got, want, rtol=1e-12)

    def test_query_matches_exact_at_random_points(self, tiny_emulator):
        base, out_dir, _, report = tiny_emulator
        loaded = load_artifact(out_dir)
        rng = np.random.default_rng(123)   # not the build's seeds
        n = 16
        thetas = np.stack([
            rng.uniform(0.9, 1.1, n),
            rng.uniform(90.0, 110.0, n),
            rng.uniform(0.25, 0.35, n),
        ], axis=1)
        emu = np.asarray(make_query_fn(loaded)(thetas))
        exact = make_exact_evaluator(
            base, static_choices_from_config(base),
            n_y=loaded.identity["n_y"], impl=loaded.identity["impl"],
            chunk_size=n,
        )({"m_chi_GeV": thetas[:, 0], "T_p_GeV": thetas[:, 1],
           "v_w": thetas[:, 2]})["DM_over_B"]
        errs = relative_errors(emu, exact)
        # fresh random points obey the same tolerance the held-out set
        # was scored at (generalization, not memorization)
        assert float(errs.max()) <= 1e-4

    def test_domain_fn(self, tiny_emulator):
        _, out_dir, _, _ = tiny_emulator
        loaded = load_artifact(out_dir)
        dom = make_domain_fn(loaded)
        inside = np.array([[1.0, 100.0, 0.30]])
        outside = np.array([[1.0, 100.0, 0.90], [5.0, 100.0, 0.30]])
        assert bool(np.asarray(dom(inside))[0])
        assert not np.asarray(dom(outside)).any()

    def test_build_rejects_bad_specs(self, tiny_emulator):
        base = tiny_emulator[0]
        with pytest.raises(EmulatorBuildError, match="unknown emulator axes"):
            build_emulator(base, {"bogus": AxisSpec(0.0, 1.0)})
        with pytest.raises(EmulatorBuildError, match="at least one axis"):
            build_emulator(base, {})
        with pytest.raises(EmulatorBuildError, match=">= 2 initial nodes"):
            build_emulator(base, {"v_w": AxisSpec(0.1, 0.9, 1)})
        with pytest.raises(EmulatorBuildError, match="scale"):
            build_emulator(base, {"v_w": AxisSpec(0.1, 0.9, 3, "cubic")})
        with pytest.raises(EmulatorBuildError, match="lo > 0"):
            build_emulator(base, {"v_w": AxisSpec(-0.1, 0.9, 3, "log")})


class TestArtifactRejection:
    def test_nan_cell_rejected_at_load(self, tiny_emulator, tmp_path):
        _, out_dir, _, _ = tiny_emulator

        def poison(values):
            values["DM_over_B"][1, 1, 1] = np.nan

        bad = _corrupt_field(out_dir, str(tmp_path / "nan"), poison)
        with pytest.raises(EmulatorArtifactError, match="non-finite"):
            load_artifact(bad)

    def test_nonpositive_cell_rejected_at_load(self, tiny_emulator, tmp_path):
        _, out_dir, _, _ = tiny_emulator

        def poison(values):
            values["Y_B"][0, 0, 0] = -1.0

        bad = _corrupt_field(out_dir, str(tmp_path / "neg"), poison)
        with pytest.raises(EmulatorArtifactError, match="non-positive"):
            load_artifact(bad)

    def test_tampered_table_fails_hash(self, tiny_emulator, tmp_path):
        _, out_dir, _, _ = tiny_emulator

        def poison(values):
            values["DM_over_B"][0, 0, 0] *= 1.5

        bad = _corrupt_field(
            out_dir, str(tmp_path / "tamper"), poison, rehash=False
        )
        with pytest.raises(EmulatorArtifactError, match="content-hash"):
            load_artifact(bad)

    def test_changed_knobs_fail_hash(self, tiny_emulator, tmp_path):
        """The satellite case: identity knobs edited after the build."""
        _, out_dir, _, _ = tiny_emulator
        dst = str(tmp_path / "knobs")
        os.makedirs(dst)
        import shutil

        shutil.copy(os.path.join(out_dir, "artifact.npz"), dst)
        with open(os.path.join(out_dir, "manifest.json")) as f:
            manifest = json.load(f)
        manifest["identity"]["n_y"] = 8000   # pretend a finer build
        with open(os.path.join(dst, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        with pytest.raises(EmulatorArtifactError, match="content-hash"):
            load_artifact(dst)

    def test_schema_version_skew_rejected(self, tiny_emulator, tmp_path):
        _, out_dir, _, _ = tiny_emulator
        dst = str(tmp_path / "schema")
        os.makedirs(dst)
        import shutil

        shutil.copy(os.path.join(out_dir, "artifact.npz"), dst)
        with open(os.path.join(out_dir, "manifest.json")) as f:
            manifest = json.load(f)
        manifest["schema_version"] += 1
        with open(os.path.join(dst, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        with pytest.raises(EmulatorArtifactError, match="schema_version"):
            load_artifact(dst)

    def test_missing_dir_rejected(self, tmp_path):
        with pytest.raises(EmulatorArtifactError, match="cannot read"):
            load_artifact(str(tmp_path / "nope"))

    def test_save_rejects_nan_table(self, tiny_emulator, tmp_path):
        _, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        values = {k: np.array(v) for k, v in art.values.items()}
        values["Y_chi"][0, 0, 0] = np.inf
        bad = art._replace(values=values)
        with pytest.raises(EmulatorArtifactError, match="non-finite"):
            save_artifact(str(tmp_path / "save_nan"), bad)


class TestQuadSchemeIdentity:
    """Surfaces computed under different y-quadrature schemes must never
    be confused: the resolved ``quad_panel_gl`` rides the artifact
    identity (and therefore its content hash), tri-state consumers adopt
    the recorded scheme, explicit consumers are compared strictly."""

    def test_artifact_records_resolved_quadrature(self, tiny_emulator):
        from bdlz_tpu.config import StaticChoices

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        # the fixture's narrow benchmark box is smooth: the build's audit
        # must have admitted the panel-GL fast path and recorded it
        assert art.identity.get("quad_panel_gl") is True
        # the knob is normalized OUT of the static tuple — the identity
        # key is its single home
        assert StaticChoices(*art.identity["static"]).quad_panel_gl is None

    def test_cross_scheme_artifact_rejected(self, tiny_emulator):
        from bdlz_tpu.emulator import build_identity, check_identity

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        static = static_choices_from_config(base)
        n_y = int(art.identity["n_y"])
        impl = str(art.identity["impl"])
        # explicit-trapezoid consumer vs a panel-GL surface: rejected
        with pytest.raises(EmulatorArtifactError, match="identity mismatch"):
            check_identity(art, build_identity(
                base, static._replace(quad_panel_gl=False), n_y, impl,
            ))
        # matching explicit scheme: accepted
        check_identity(art, build_identity(
            base, static._replace(quad_panel_gl=True), n_y, impl,
        ))
        # tri-state (None) consumer: wildcard — adopts the artifact's
        assert static.quad_panel_gl is None
        check_identity(art, build_identity(base, static, n_y, impl))

    def test_quad_scheme_changes_artifact_hash(self, tiny_emulator):
        """Identical tables under different recorded schemes hash
        differently — a copied .npz cannot masquerade as the other
        scheme's surface."""
        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        ident_other = dict(art.identity)
        ident_other["quad_panel_gl"] = False
        h_gl = artifact_hash(art.axis_names, art.axis_nodes,
                             art.axis_scales, art.values, art.identity,
                             predicted_error=art.predicted_error)
        h_tr = artifact_hash(art.axis_names, art.axis_nodes,
                             art.axis_scales, art.values, ident_other,
                             predicted_error=art.predicted_error)
        assert h_gl == art.manifest["hash"]
        assert h_gl != h_tr

    def test_service_adopts_artifact_scheme(self, tiny_emulator):
        from bdlz_tpu.serve.service import YieldService

        base, out_dir, _, _ = tiny_emulator
        # tri-state consumer constructs fine (adopts panel-GL fallback)
        YieldService(load_artifact(out_dir), base, max_batch_size=16)
        # explicit-trapezoid consumer is refused the panel-GL surface
        with pytest.raises(EmulatorArtifactError, match="identity mismatch"):
            YieldService(
                load_artifact(out_dir), base,
                static=static_choices_from_config(base)._replace(
                    quad_panel_gl=False
                ),
                max_batch_size=16,
            )


class TestEmulatorLogprob:
    def test_fast_mode_matches_exact_logp(self, tiny_emulator):
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table
        from bdlz_tpu.sampling.likelihoods import make_pipeline_logprob

        base, out_dir, _, _ = tiny_emulator
        static = static_choices_from_config(base)
        loaded = load_artifact(out_dir)
        table = make_f_table(base.I_p, jnp)
        keys = ("m_chi_GeV", "v_w")
        n_y = int(loaded.identity["n_y"])
        lp_exact = make_pipeline_logprob(
            base, static, table, param_keys=keys, n_y=n_y
        )
        lp_emu = make_pipeline_logprob(
            base, static, None, param_keys=keys, emulator=loaded
        )
        for th in ([0.95, 0.30], [1.05, 0.27], [1.0, 0.34]):
            a = float(lp_exact(jnp.asarray(th)))
            b = float(lp_emu(jnp.asarray(th)))
            # logp error ~ curvature-amplified surface rel-err; at the
            # fixture tolerance the two posteriors agree to ~1e-3 rel
            assert abs(a - b) <= 1e-3 * max(abs(a), 1.0), (th, a, b)

    def test_fast_mode_vmaps_and_scores_ood_minus_inf(self, tiny_emulator):
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.sampling.likelihoods import make_pipeline_logprob

        base, out_dir, _, _ = tiny_emulator
        static = static_choices_from_config(base)
        lp = make_pipeline_logprob(
            base, static, None, param_keys=("m_chi_GeV", "v_w"),
            emulator=load_artifact(out_dir),
        )
        vals = np.asarray(jax.jit(jax.vmap(lp))(jnp.asarray(
            [[0.95, 0.30], [5.0, 0.30], [1.0, 0.99]]
        )))
        assert np.isfinite(vals[0])
        assert vals[1] == -np.inf and vals[2] == -np.inf

    def test_fast_mode_preconditions(self, tiny_emulator):
        from bdlz_tpu.sampling.likelihoods import make_pipeline_logprob

        base, out_dir, _, _ = tiny_emulator
        static = static_choices_from_config(base)
        loaded = load_artifact(out_dir)
        with pytest.raises(ValueError, match="not axes of the emulator"):
            make_pipeline_logprob(
                base, static, None, param_keys=("beta_over_H",),
                emulator=loaded,
            )
        with pytest.raises(ValueError, match="mutually exclusive"):
            make_pipeline_logprob(
                base, static, None, param_keys=("v_w",),
                emulator=loaded, lz_lambda1=0.01,
            )

    def test_stale_artifact_rejected(self, tiny_emulator):
        import dataclasses

        from bdlz_tpu.sampling.likelihoods import make_pipeline_logprob

        base, out_dir, _, _ = tiny_emulator
        base2 = dataclasses.replace(base, source_shape_sigma_y=10.0)
        with pytest.raises(EmulatorArtifactError, match="identity mismatch"):
            make_pipeline_logprob(
                base2, static_choices_from_config(base2), None,
                param_keys=("m_chi_GeV", "v_w"),
                emulator=load_artifact(out_dir),
            )


class TestSharedHelpers:
    def test_relative_errors_zero_reference_rule(self):
        ref = np.array([1.0, 2.0, 0.0, 4.0])
        got = np.array([1.0, 2.2, 0.4, 4.0])
        errs = relative_errors(got, ref)
        np.testing.assert_allclose(errs[[0, 1, 3]], [0.0, 0.1, 0.0])
        # zero-ref point held to the median-nonzero scale (median = 2)
        assert errs[2] == pytest.approx(0.4 / 2.0)
        with pytest.raises(GateFailure, match="non-finite"):
            relative_errors(np.array([np.nan]), np.array([1.0]))
        with pytest.raises(GateFailure, match="identically zero"):
            relative_errors(np.array([1.0]), np.array([0.0]))

    def test_atomic_write_json(self, tmp_path):
        from bdlz_tpu.utils.io import atomic_write_json

        path = str(tmp_path / "m.json")
        atomic_write_json(path, {"a": 1})
        atomic_write_json(path, {"a": 2})
        with open(path) as f:
            assert json.load(f) == {"a": 2}
        # no temp droppings left next to the manifest
        assert sorted(os.listdir(tmp_path)) == ["m.json"]
        # unserializable payload: loud error, target untouched, no tmp
        with pytest.raises(TypeError):
            atomic_write_json(path, {"a": object()})
        with open(path) as f:
            assert json.load(f) == {"a": 2}
        assert sorted(os.listdir(tmp_path)) == ["m.json"]

    def test_sweep_and_checkpoint_manifests_use_atomic_writes(self):
        """The two satellite call sites must go through the helper —
        a direct json.dump into a manifest path is the torn-write bug
        this PR removes."""
        import inspect

        from bdlz_tpu.parallel import sweep
        from bdlz_tpu.sampling import checkpoint

        for mod in (sweep, checkpoint):
            src = inspect.getsource(mod)
            assert "atomic_write_json" in src, mod.__name__
            assert 'open(manifest_path, "w")' not in src, mod.__name__


@pytest.mark.slow
def test_full_build_wide_box_converges():
    """The wide-box build with heavy sigma_y refinement (the bench box);
    kept out of tier-1 — ~10 s of exact sweeps on CPU."""
    base = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    spec = {
        "m_chi_GeV": AxisSpec(0.1, 10.0, 3, "log"),
        "T_p_GeV": AxisSpec(30.0, 300.0, 5, "log"),
        "source_shape_sigma_y": AxisSpec(3.0, 18.0, 5, "lin"),
        "beta_over_H": AxisSpec(50.0, 500.0, 5, "log"),
    }
    artifact, report = build_emulator(
        base, spec, rtol=1e-4, n_probe=48, max_rounds=40, n_y=2000,
        chunk_size=512, require_converged=True,
    )
    assert report.converged and report.max_rel_err <= 1e-4
    # the curved axis was refined far past its 5 seed nodes; the
    # power-law axes were not
    assert report.axis_nodes["source_shape_sigma_y"] > 50
    assert report.axis_nodes["m_chi_GeV"] == 3


class TestFisherRefinement:
    """refine_signal='fisher' (sampling/grad.py by-product): the
    probe-split attribution uses exact-pipeline gradients instead of
    the axis-local |f''| stencil.  The PR's acceptance pin: on a
    seam-free benchmark box it reaches the SAME held-out tolerance
    with FEWER exact evaluations — the legacy rule is structurally
    blind on 2-node axes (no second difference exists, so it burns a
    hyperplane splitting an axis the surface is exactly log-linear
    in), the gradient signal is exactly the information it lacks."""

    #: Loose enough to keep the A/B cheap in tier-1 (the mechanism —
    #: blind 2-node-axis splits vs gradient attribution — is
    #: tolerance-independent; measured 132 vs 217 exact evals here,
    #: 184 vs 324 at 1e-4).
    RTOL = 3e-4

    def _bench_box(self):
        base = config_from_dict({
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        })
        # two EXACTLY log-linear 2-node axes (rho_B ∝ P and ∝ flux;
        # rho_DM independent) + one genuinely curved lin axis (1/v_w)
        spec = {
            "P_chi_to_B": AxisSpec(0.05, 0.5, 2, "log"),
            "incident_flux_scale": AxisSpec(0.9e-9, 1.2e-9, 2, "log"),
            "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
        }
        return base, spec

    def test_fisher_fewer_exact_evals_at_same_rtol(self):
        base, spec = self._bench_box()
        results = {}
        for rs in (None, "fisher"):
            artifact, report = build_emulator(
                base, spec, rtol=self.RTOL, n_probe=8, n_holdout=32,
                max_rounds=10, n_y=400, chunk_size=128,
                refine_signal=rs, require_converged=True,
            )
            results[rs] = (artifact, report)
        _, rep_curv = results[None]
        art_fish, rep_fish = results["fisher"]
        # both reach the advertised tolerance on the held-out set ...
        assert rep_curv.converged and rep_curv.max_rel_err <= self.RTOL
        assert rep_fish.converged and rep_fish.max_rel_err <= self.RTOL
        # ... and the gradient-aware build pays strictly fewer exact
        # pipeline points (the acceptance criterion, on the report)
        assert rep_fish.n_exact_evals < rep_curv.n_exact_evals, (
            rep_fish.n_exact_evals, rep_curv.n_exact_evals,
        )
        # the gradient bill is separate, visible, and small
        assert rep_fish.refine_signal == "fisher"
        assert 0 < rep_fish.n_grad_evals < rep_curv.n_exact_evals
        assert rep_curv.n_grad_evals == 0
        # mechanism pin: fisher left the exactly-log-linear 2-node axes
        # alone; the legacy stencil split them blindly
        assert rep_fish.axis_nodes["P_chi_to_B"] == 2
        assert rep_fish.axis_nodes["incident_flux_scale"] == 2
        assert rep_curv.axis_nodes["P_chi_to_B"] > 2
        # identity: the signal is the artifact's own key (single home),
        # wildcard for consumers with no expectation
        assert art_fish.identity["refine_signal"] == "fisher"
        assert "refine_signal" not in results[None][0].identity
        assert art_fish.content_hash != results[None][0].content_hash
        check_identity(
            art_fish,
            {k: v for k, v in art_fish.identity.items()
             if k != "refine_signal"},
        )
        with pytest.raises(EmulatorArtifactError, match="refine_signal"):
            check_identity(
                results[None][0],
                dict(results[None][0].identity, refine_signal="fisher"),
            )
        # manifest provenance rides the artifact
        assert art_fish.manifest["refine_signal"] == "fisher"
        assert art_fish.manifest["n_grad_evals"] == rep_fish.n_grad_evals

    def test_fisher_refuses_scenario_and_nontabulated(self):
        from bdlz_tpu.emulator.build import EmulatorBuildError

        base, spec = self._bench_box()
        with pytest.raises(EmulatorBuildError, match="refine_signal"):
            build_emulator(
                base, spec, rtol=1e-3, refine_signal="hessian",
            )
        # an I_p axis resolves impl='direct' — the differentiable
        # tabulated closure does not exist there, refuse loudly
        spec_ip = {"I_p": AxisSpec(0.3, 0.4, 3, "lin"),
                   "v_w": AxisSpec(0.25, 0.35, 3, "lin")}
        with pytest.raises(EmulatorBuildError, match="fisher"):
            build_emulator(
                base, spec_ip, rtol=1e-3, refine_signal="fisher",
                n_y=400,
            )
