"""Multi-tenant scenario-routed serving plane (bdlz_tpu/serve/tenancy.py).

Pins the ISSUE-14 acceptance contract: per-pool isolation (a saturated
tenant sheds its OWN traffic — its neighbor's shed rate is untouched),
autoscaler hysteresis (no replica flapping on an oscillating load
trace, growth only on a sustained streak), the evict → degraded-exact →
readmit-warm round trip with bit-identical pre/post-eviction answers,
cross-scenario skew rejected loudly (a chain-tagged request can never
be answered by a thermal pool), per-artifact answers bit-identical to a
single-tenant fleet, and the close() contract (every pool's pending —
and degraded-queued — futures fail with typed ServiceUnavailable on a
fake clock, never park).
"""
import dataclasses

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, validate
from bdlz_tpu.lz.profile import BounceProfile
from bdlz_tpu.serve import (
    MultiTenantService,
    QueueFull,
    REASON_POOL_EVICTED,
    ServiceUnavailable,
    TenancyError,
)

XI = np.linspace(-30.0, 30.0, 1001)
PROF = BounceProfile(
    xi=XI, delta=-0.08 * np.tanh(XI / 4.0), mix=np.full_like(XI, 0.02)
)

PHYS = {
    "regime": "nonthermal",
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


def _cfg(**kw):
    return validate(config_from_dict({**PHYS, **kw}), backend="tpu")


class _Tick:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def tenant_plane(tmp_path_factory, jit_warmup):
    """Two tiny published artifacts sharing one build base (coherent
    two-channel + N=3 chain) and the store that serves them — the
    minimal two-tenant world every test here routes through."""
    from bdlz_tpu.emulator import AxisSpec, build_emulator
    from bdlz_tpu.provenance import Store, publish_artifact

    base = _cfg(P_chi_to_B=0.1)
    base_chain = dataclasses.replace(base, lz_mode="chain", lz_n_levels=3)
    spec = {
        "m_chi_GeV": AxisSpec(0.9, 1.1, 2, "log"),
        "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
    }
    kw = dict(rtol=1e-2, n_probe=4, n_holdout=8, max_rounds=1, n_y=400,
              chunk_size=64, require_converged=False)
    root = tmp_path_factory.mktemp("tenancy")
    art_coh, _ = build_emulator(
        base, spec, out_dir=str(root / "coh"), **kw
    )
    art_chain, _ = build_emulator(
        base_chain, spec, out_dir=str(root / "chain"), lz_profile=PROF, **kw
    )
    store = Store(str(root / "store"))
    h_coh = publish_artifact(store, art_coh)
    h_chain = publish_artifact(store, art_chain)
    return {
        "base": base,
        "store": store,
        "art_coh": art_coh,
        "art_chain": art_chain,
        "tenant_map": {"coherent": h_coh, "chain": h_chain},
        "h_coh": h_coh,
        "h_chain": h_chain,
    }


def _service(plane, clock=None, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("lz_profile", PROF)
    return MultiTenantService(
        plane["base"], tenant_map=plane["tenant_map"],
        store=plane["store"], clock=clock or _Tick(), **kw
    )


def _thetas(n, seed=5):
    rng = np.random.default_rng(seed)
    return np.column_stack([
        rng.uniform(0.92, 1.08, n), rng.uniform(0.26, 0.34, n)
    ])


# ---------------------------------------------------------------------------
# routing + skew
# ---------------------------------------------------------------------------

class TestRoutingAndSkew:
    def test_cross_scenario_skew_rejected_loudly(self, tenant_plane):
        # a chain-tagged request can NEVER be answered by another
        # scenario's pool: a stated mode that contradicts the routed
        # pool is a typed refusal, not a silent wrong answer
        svc = _service(tenant_plane)
        try:
            theta = _thetas(1)[0]
            with pytest.raises(TenancyError, match="skew"):
                svc.submit(theta, scenario="chain", lz_mode="thermal")
            with pytest.raises(TenancyError, match="skew"):
                svc.submit(theta, scenario="coherent", lz_mode="chain")
            # mapping-style requests state the mode inside the point
            with pytest.raises(ValueError, match="skew"):
                svc.submit(
                    {"m_chi_GeV": 1.0, "v_w": 0.3, "lz_mode": "thermal"},
                    scenario="chain",
                )
        finally:
            svc.close()

    def test_mode_named_label_must_match_pool_mode(self, tenant_plane):
        # a tenant map that routes the label "thermal" to a CHAIN
        # artifact is cross-scenario skew at admission time
        svc = MultiTenantService(
            tenant_plane["base"],
            tenant_map={"thermal": tenant_plane["h_chain"]},
            store=tenant_plane["store"], max_batch_size=4, lz_profile=PROF,
            clock=_Tick(),
        )
        try:
            with pytest.raises(TenancyError, match="skew"):
                svc.submit(_thetas(1)[0], scenario="thermal")
        finally:
            svc.close()

    def test_routing_refusals_are_typed(self, tenant_plane):
        svc = _service(tenant_plane)
        try:
            with pytest.raises(TenancyError, match="unknown scenario"):
                svc.submit(_thetas(1)[0], scenario="nope")
            with pytest.raises(TenancyError, match="scenario tag"):
                svc.submit(_thetas(1)[0])  # scenario routing needs a tag
            with pytest.raises(TenancyError, match="conflicting"):
                svc.submit(_thetas(1)[0], scenario="chain",
                           artifact_hash=tenant_plane["h_coh"])
        finally:
            svc.close()

    def test_tenant_map_and_store_validated(self, tenant_plane,
                                            monkeypatch):
        with pytest.raises(TenancyError, match="16-hex"):
            MultiTenantService(
                tenant_plane["base"], tenant_map={"a": "not-a-hash"},
                store=tenant_plane["store"],
            )
        monkeypatch.delenv("BDLZ_CACHE_ROOT", raising=False)
        with pytest.raises(TenancyError, match="store"):
            MultiTenantService(
                tenant_plane["base"],
                tenant_map=tenant_plane["tenant_map"], store=None,
            )


# ---------------------------------------------------------------------------
# bit-identity + isolation
# ---------------------------------------------------------------------------

class TestPoolIsolation:
    def test_answers_bitwise_equal_single_tenant_fleet(self, tenant_plane):
        # the tentpole guarantee: routing through the multi-tenant plane
        # never buys a different answer than a dedicated fleet
        from bdlz_tpu.serve import FleetService

        thetas = _thetas(12)
        svc = _service(tenant_plane)
        try:
            futs = [
                (scn, svc.submit(t, scenario=scn))
                for t in thetas for scn in ("coherent", "chain")
            ]
            svc.drain()
            got = {
                scn: [f.result().value for s, f in futs if s == scn]
                for scn in ("coherent", "chain")
            }
            hashes = {
                f.result().artifact_hash for s, f in futs if s == "chain"
            }
            assert hashes == {tenant_plane["h_chain"]}
        finally:
            svc.close()
        for scn, art, prof in (
            ("coherent", tenant_plane["art_coh"], None),
            ("chain", tenant_plane["art_chain"], PROF),
        ):
            base = dataclasses.replace(
                tenant_plane["base"],
                **({"lz_mode": "chain", "lz_n_levels": 3}
                   if scn == "chain" else {}),
            )
            ref = FleetService(art, base, max_batch_size=4,
                               lz_profile=prof)
            rfuts = [ref.submit(t) for t in thetas]
            ref.drain()
            assert got[scn] == [f.result().value for f in rfuts]
            ref.close()

    def test_saturated_tenant_sheds_alone(self, tenant_plane):
        # tenant A (coherent) saturated at its own admission bound;
        # tenant B (chain) keeps its zero shed rate — isolation is the
        # whole point of per-pool queues
        tick = _Tick()
        svc = _service(tenant_plane, clock=tick, queue_bound=4)
        try:
            thetas = _thetas(16)
            rejected = 0
            for t in thetas:
                try:
                    svc.submit(t, scenario="coherent")
                except QueueFull:
                    rejected += 1
            assert rejected > 0
            for t in thetas[:4]:
                svc.submit(t, scenario="chain")
            svc.drain()
            pools = svc.summary()["pools"]
            coh = pools[tenant_plane["h_coh"]]
            chn = pools[tenant_plane["h_chain"]]
            assert coh["admission_rejects"] == rejected
            assert coh["shed_rate"] > 0.0
            assert chn["admission_rejects"] == 0
            assert chn["shed_rate"] == 0.0
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# eviction + degraded + readmit
# ---------------------------------------------------------------------------

class TestEvictReadmit:
    def test_evict_degraded_readmit_bitwise_round_trip(self, tenant_plane):
        tick = _Tick()
        plan = ('{"faults": [{"site": "pool_evict", "kind": "raise", '
                '"key": 0}]}')
        svc = _service(tenant_plane, clock=tick, fault_plan=plan)
        try:
            thetas = _thetas(8)
            pre = [svc.submit(t, scenario="coherent") for t in thetas]
            svc.drain()
            pre_vals = [f.result().value for f in pre]
            svc.run_once()  # pool idle -> the forced eviction fires
            pool = svc.pool("coherent")
            assert pool.evicted and svc.forced_evictions == 1
            assert pool.resident_bytes == 0

            # evicted-pool requests answer through the LOUD degraded
            # exact path — correct and slow, never an error
            deg = [svc.submit(t, scenario="coherent") for t in thetas]
            svc.drain()
            for f in deg:
                r = f.result()
                assert r.degraded is True
                assert r.fallback_reason == REASON_POOL_EVICTED
                assert r.replica == -1
                assert np.isfinite(r.value)

            # readmit re-fetches/warms/probes through cold admission;
            # the answers come back bit-identical to pre-eviction
            svc.readmit("coherent")
            assert not pool.evicted
            post = [svc.submit(t, scenario="coherent") for t in thetas]
            svc.drain()
            assert [f.result().value for f in post] == pre_vals
            ev = svc.admission_events
            assert [e["readmit"] for e in ev].count(True) == 1
            assert svc.summary()["readmissions"] == 1
        finally:
            svc.close()

    def test_memory_budget_evicts_lru_idle_pool(self, tenant_plane):
        tick = _Tick()
        svc = _service(tenant_plane, clock=tick)
        try:
            thetas = _thetas(4)
            a = [svc.submit(t, scenario="coherent") for t in thetas]
            tick.t += 1.0
            b = [svc.submit(t, scenario="chain") for t in thetas]
            svc.drain()
            for f in a + b:
                f.result()
            # budget that fits exactly one pool: the LRU (coherent)
            # pool is the victim on the next tick, the hot one stays
            svc.memory_budget_bytes = svc.pool("chain").resident_bytes
            svc.run_once()
            assert svc.pool("coherent").evicted
            assert not svc.pool("chain").evicted
            assert svc.evictions == 1 and svc.forced_evictions == 0
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# autoscaler hysteresis
# ---------------------------------------------------------------------------

class TestAutoscaler:
    def _pump_full_batch(self, svc, tick, scenario="coherent"):
        for t in _thetas(4):
            svc.submit(t, scenario=scenario)
        svc.run_once()
        svc.poll(block=True)
        # advance past the interval, then an idle tick so the pass
        # runs with nothing in flight (resizes need a quiesced pool)
        tick.t += 1.0
        svc.run_once()

    def test_sustained_load_grows_once_no_flapping_on_oscillation(
        self, tenant_plane
    ):
        tick = _Tick()
        svc = _service(tenant_plane, clock=tick, autoscale_interval_s=1.0,
                       n_replicas=1)
        try:
            # oscillating load: full batch, silence, full batch, ... —
            # every pass resets the opposite streak, so NO resize ever
            # happens (flapping is exactly what hysteresis forbids)
            for _ in range(4):
                self._pump_full_batch(svc, tick)   # occupancy-1.0 pass
                tick.t += 1.0
                svc.run_once()                     # empty (cold) pass
            assert svc.summary()["resizes"] == 0
            assert svc.pool("coherent").n_replicas == 1
            assert svc.summary()["autoscale_passes"] >= 8

            # sustained hot streak: UP_PASSES consecutive full-batch
            # passes grow the pool exactly once
            self._pump_full_batch(svc, tick)
            self._pump_full_batch(svc, tick)
            assert svc.pool("coherent").n_replicas == 2
            assert svc.summary()["resizes"] == 1
        finally:
            svc.close()

    def test_autoscale_fault_skips_pass(self, tenant_plane):
        tick = _Tick()
        plan = ('{"faults": [{"site": "autoscale", "kind": "raise", '
                '"key": 0}]}')
        svc = _service(tenant_plane, clock=tick, autoscale_interval_s=1.0,
                       fault_plan=plan, n_replicas=1)
        try:
            self._pump_full_batch(svc, tick)
            self._pump_full_batch(svc, tick)
            self._pump_full_batch(svc, tick)
            s = svc.summary()
            assert s["autoscale_skipped"] == 1
            assert s["autoscale_passes"] >= 2
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# close() contract (satellite: serve_cli/fleet close semantics)
# ---------------------------------------------------------------------------

class TestClose:
    def test_close_fails_pending_and_degraded_futures_typed(
        self, tenant_plane
    ):
        # fake clock: nothing ages out, nothing dispatches (batch of 4
        # never fills) — the requests are provably still pending when
        # close() runs, and every future must fail TYPED, never park
        tick = _Tick()
        plan = ('{"faults": [{"site": "pool_evict", "kind": "raise", '
                '"key": 0}]}')
        svc = _service(tenant_plane, clock=tick, fault_plan=plan)
        try:
            warm = [svc.submit(t, scenario="coherent") for t in _thetas(4)]
            svc.drain()
            for f in warm:
                f.result()
            svc.run_once()  # idle -> forced eviction
            assert svc.pool("coherent").evicted
            pend = [svc.submit(t, scenario="chain") for t in _thetas(2)]
            deg = [svc.submit(t, scenario="coherent") for t in _thetas(2)]
        finally:
            n = svc.close()
        assert n == 4
        for f in pend + deg:
            with pytest.raises(ServiceUnavailable):
                f.result(timeout=0)
        with pytest.raises(ServiceUnavailable):
            svc.submit(_thetas(1)[0], scenario="chain")
        assert svc.close() == 0  # idempotent

    def test_replica_budget_refusal_is_typed(self, tenant_plane):
        svc = _service(tenant_plane, n_replicas=1, replica_budget=1)
        try:
            svc.submit(_thetas(1)[0], scenario="coherent")
            with pytest.raises(TenancyError, match="replica budget"):
                svc.submit(_thetas(1)[0], scenario="chain")
        finally:
            svc.close()
