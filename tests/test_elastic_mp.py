"""Real-subprocess elastic fleet: ``sweep_cli --elastic`` across
processes, with churn, against the single-host bitwise baseline.

The in-process protocol coverage lives in ``tests/test_elastic.py``
(tier-1); these tests pay real process spawns, real wall-clock lease
expiry, and per-process jit compiles, so they are ``@pytest.mark.slow``
(tier-1's ``-m 'not slow'`` excludes them — see ``scripts/tier1.sh``).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.parallel.scheduler import run_sweep_elastic
from bdlz_tpu.parallel.sweep import run_sweep
from bdlz_tpu.provenance import Store
from bdlz_tpu.utils.retry import RetryPolicy

CFG = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}
AXIS_FLAGS = ["--axis", "m_chi_GeV=0.5,1.0,2.0", "--axis", "T_p_GeV=80.0,150.0"]
AXES = {"m_chi_GeV": [0.5, 1.0, 2.0], "T_p_GeV": [80.0, 150.0]}


def _child_env():
    env = dict(os.environ)
    # children must not inherit the axon TPU plugin (a dead relay would
    # hang their first backend touch) — pin host CPU explicitly
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return env


def _worker_cmd(cfg_path, store, worker_id, *extra):
    return [
        sys.executable, "-m", "bdlz_tpu.sweep_cli",
        "--config", str(cfg_path), *AXIS_FLAGS,
        "--chunk", "2", "--n-y", "200",
        "--elastic-store", str(store),
        "--lease-ttl", "5", "--poll", "0.2",
        "--worker-id", worker_id, *extra,
    ]


@pytest.fixture(scope="module")
def serial():
    base = config_from_dict(dict(CFG))
    static = static_choices_from_config(base)
    return run_sweep(
        base, AXES, static, mesh=None, chunk_size=2, n_y=200,
        retry=RetryPolicy(max_attempts=2, backoff_s=0.0, sleep=lambda s: None),
    )


def _run_fleet(cmds, timeout=420):
    procs = [
        subprocess.Popen(
            cmd, env=_child_env(),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for cmd in cmds
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"elastic CLI process failed (rc={rc}):\n{out}\n{err}"
    return outs


def _fold_and_compare(store_root, serial):
    """Fold the committed chunks in this process (pure prescan — no
    recompute) and pin them bitwise against the serial baseline."""
    base = config_from_dict(dict(CFG))
    static = static_choices_from_config(base)
    store = Store(str(store_root))
    res = run_sweep_elastic(
        base, AXES, static, store=store, chunk_size=2, n_y=200,
        retry=RetryPolicy(max_attempts=2, backoff_s=0.0, sleep=lambda s: None),
    )
    assert res.cache_hits == 3 and res.cache_misses == 0
    for f in serial.outputs:
        a, b = res.outputs[f], serial.outputs[f]
        assert a.dtype == b.dtype and a.tobytes() == b.tobytes(), (
            f"subprocess fleet drifted from serial on {f}"
        )
    assert not res.failed_mask.any() and not res.quarantined_mask.any()
    return res


@pytest.mark.slow
def test_subprocess_worker_fleet_with_crash_is_bitwise(tmp_path, serial):
    """Two real worker processes, one of which CRASHES on its first
    attempt at chunk 1; the survivor steals the expired lease and the
    folded result is bitwise-identical to the single-host engine."""
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(CFG))
    store = tmp_path / "store"
    crash = json.dumps([
        {"site": "worker_crash", "kind": "transient", "chunk": 1, "times": 1}
    ])
    outs = _run_fleet([
        _worker_cmd(cfg_path, store, "wA", "--elastic", "worker"),
        _worker_cmd(cfg_path, store, "wB", "--elastic", "worker",
                    "--churn-plan", crash),
    ])
    summaries = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in outs]
    assert all(s["elastic"] == "worker" for s in summaries)
    assert {s["worker"] for s in summaries} == {"wA", "wB"}
    assert len({s["job"] for s in summaries}) == 1  # same derived plan
    # every chunk was completed by SOMEONE (a steal double-complete can
    # push the sum past n_chunks; it can never fall short)
    assert sum(s["chunks_done"] for s in summaries) >= 3
    _fold_and_compare(store, serial)


@pytest.mark.slow
def test_subprocess_auto_election_drains_the_job(tmp_path, serial):
    """Two ``--elastic auto`` processes: exactly one wins the
    coordinator seat (store-lease election) and prints the fold-side
    summary; the other drains chunks as a worker.  No spec-level state
    crosses processes — both re-derive the plan from the same flags."""
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(CFG))
    store = tmp_path / "store"
    outs = _run_fleet([
        _worker_cmd(cfg_path, store, "nodeA", "--elastic", "auto",
                    "--elastic-workers", "1"),
        _worker_cmd(cfg_path, store, "nodeB", "--elastic", "auto",
                    "--elastic-workers", "1"),
    ])
    summaries = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in outs]
    coords = [s for s in summaries if "n_points" in s]
    workers = [s for s in summaries if s.get("elastic") == "worker"]
    assert len(coords) == 1 and len(workers) == 1
    assert coords[0]["n_points"] == 6
    assert coords[0]["n_failed"] == 0
    assert coords[0]["n_quarantined"] == 0
    _fold_and_compare(store, serial)
