"""Settling-factor diagnostics (paper §7, Eqs. 22-24) and the --planck CLI
block: the archived benchmark must reproduce f_settle = 0.94168 and
P_eff ~ 0.15850."""
import json

import numpy as np
import pytest

from bdlz_tpu.analysis import effective_probability, planck_comparison, settling_factor

GOLDEN_RATIO_RAW = 5.6889263349
GOLDEN_P = 0.14925839040304145


def test_settling_factor_benchmark_value():
    # paper Eq. 23 displays 5.357/5.6889263349 = 0.94168, but that quotient
    # is actually 0.9416540 — the paper's printed value comes from an
    # unrounded Planck ratio ~5.3571. We evaluate the definition with the
    # displayed Planck ratio 5.357 and check both to their real precision.
    assert settling_factor(GOLDEN_RATIO_RAW) == pytest.approx(0.9416540, abs=5e-7)
    assert settling_factor(GOLDEN_RATIO_RAW) == pytest.approx(0.94168, abs=3e-5)


def test_effective_probability_benchmark_value():
    # paper Eq. 24: P / f_settle ~ 0.15850 (same rounding caveat as Eq. 23)
    assert effective_probability(GOLDEN_P, GOLDEN_RATIO_RAW) == pytest.approx(
        0.158506, abs=5e-6
    )
    # consistency: P_eff * f_settle == P
    f = settling_factor(GOLDEN_RATIO_RAW)
    assert effective_probability(GOLDEN_P, GOLDEN_RATIO_RAW) * f == pytest.approx(
        GOLDEN_P, rel=1e-12
    )


def test_planck_comparison_batched():
    ratios = np.array([5.357, 5.6889263349, 10.714])
    Ps = np.array([0.1, GOLDEN_P, 0.2])
    cmp_ = planck_comparison(ratios, Ps)
    np.testing.assert_allclose(cmp_["f_settle"], [1.0, 0.9416540, 0.5], atol=5e-6)
    np.testing.assert_allclose(cmp_["P_eff"][0], 0.1, rtol=1e-12)
    np.testing.assert_allclose(cmp_["P_eff"][2], 0.4, rtol=1e-12)


def test_cli_planck_block(benchmark_config_path, tmp_path, capsys, monkeypatch):
    from bdlz_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    main(["--config", benchmark_config_path, "--planck"])
    out = capsys.readouterr().out
    assert "f_settle              = 0.94165" in out
    assert "P_eff                 = 0.15851" in out
    # the reference-contract result block is unchanged
    assert "DM/B ratio= 5.68893" in out
    assert json.load(open("yields_out.json"))["final"]["DM_over_B"] == pytest.approx(
        5.688926334903014, rel=1e-12
    )


def test_scalar_zero_ratio_matches_array_semantics():
    # a point with zero baryon yield: scalar use (CLI) must not raise and
    # must agree with the batched numpy behavior (inf)
    assert settling_factor(0.0) == float("inf")
    with np.errstate(divide="ignore"):
        arr = settling_factor(np.array([0.0]))
    assert np.isinf(arr[0])
