"""ESDIRK stiff-integrator tests (SURVEY §4.2/§4.5): analytic solutions,
stiff stability, the quadrature cross-check on a washout-free config, and
the Γ_wash=0.01 regression the reference cannot finish."""
import time

import numpy as np
import pytest

from bdlz_tpu.config import (
    config_from_dict,
    point_params_from_config,
    static_choices_from_config,
)
from bdlz_tpu.physics.percolation import make_kjma_grid
from bdlz_tpu.solvers.quadrature import integrate_YB_quadrature
from bdlz_tpu.solvers.sdirk import esdirk_solve, solve_boltzmann_esdirk


def bench_cfg(**over):
    base = {
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    }
    base.update(over)
    return config_from_dict(base)


class TestESDIRKCore:
    def test_linear_decay_exact(self):
        import jax.numpy as jnp

        lam = 3.0

        def rhs(x, y):
            return -lam * y

        sol = esdirk_solve(rhs, 0.0, 2.0, jnp.array([1.0, 0.5]), rtol=1e-10, atol=1e-14)
        assert bool(sol.success)
        expected = np.array([1.0, 0.5]) * np.exp(-lam * 2.0)
        np.testing.assert_allclose(np.asarray(sol.y), expected, rtol=1e-8)

    def test_stiff_decay_stable(self):
        """λ = 1e6 over unit interval: explicit methods explode, an
        L-stable ESDIRK takes few steps."""
        import jax.numpy as jnp

        def rhs(x, y):
            return -1e6 * (y - jnp.array([2.0, 3.0]))

        sol = esdirk_solve(rhs, 0.0, 1.0, jnp.array([0.0, 0.0]), rtol=1e-8, atol=1e-12)
        assert bool(sol.success)
        # An explicit method would need ~1e6 steps (stability limit
        # h < 2/λ); the L-stable ESDIRK needs only enough to *resolve*
        # the initial transient to rtol.
        assert int(sol.n_steps) < 2000
        np.testing.assert_allclose(np.asarray(sol.y), [2.0, 3.0], atol=1e-7)

    def test_nonautonomous_quadrature(self):
        """y' = cos(x): pure quadrature through the solver."""
        import jax.numpy as jnp

        def rhs(x, y):
            return jnp.full_like(y, jnp.cos(x))

        sol = esdirk_solve(rhs, 0.0, 1.5, jnp.zeros(2), rtol=1e-10, atol=1e-14)
        np.testing.assert_allclose(np.asarray(sol.y), np.sin(1.5), rtol=1e-7)

    def test_max_steps_reports_failure(self):
        import jax.numpy as jnp

        def rhs(x, y):
            return -1e6 * y

        sol = esdirk_solve(rhs, 0.0, 1.0, jnp.ones(2), rtol=1e-12, atol=1e-18, max_steps=3)
        assert not bool(sol.success)


class TestBoltzmannESDIRK:
    def test_matches_quadrature_when_source_only(self):
        """With σv=0, Γ_wash=0, no depletion, the ODE must reproduce the
        quadrature Y_B (the two solvers share only the RHS physics)."""
        import jax.numpy as jnp

        cfg = bench_cfg()
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)

        YB_quad = float(
            integrate_YB_quadrature(
                pp, static.chi_stats, make_kjma_grid(jnp), jnp, n_y=8000
            )
        )
        T_p = cfg.T_p_GeV
        sol = solve_boltzmann_esdirk(
            pp, static, grid, (4.90e-10, 0.0), 0.001 * T_p, 5.0 * T_p,
            rtol=1e-10, atol=1e-18,
        )
        assert bool(sol.success)
        assert float(sol.y[0]) == pytest.approx(4.90e-10, rel=1e-12)  # untouched
        assert float(sol.y[1]) == pytest.approx(YB_quad, rel=1e-4)

    def test_washout_config_finishes_fast(self):
        """The Γ_wash/H=0.01 config the reference cannot finish in 90 s
        (SURVEY §2.1) must complete here in seconds and show washout."""
        import jax.numpy as jnp

        cfg = bench_cfg(Gamma_wash_over_H=0.01)
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)
        T_p = cfg.T_p_GeV

        t0 = time.time()
        sol = solve_boltzmann_esdirk(
            pp, static, grid, (4.90e-10, 0.0), 0.001 * T_p, 5.0 * T_p,
            rtol=1e-10, atol=1e-18,
        )
        assert bool(sol.success)
        YB = float(sol.y[1])
        elapsed = time.time() - t0

        YB_no_wash = float(
            integrate_YB_quadrature(
                pp, static.chi_stats, make_kjma_grid(jnp), jnp, n_y=8000
            )
        )
        assert elapsed < 60.0  # includes compile; execution is ~ms
        assert 0.0 < YB < YB_no_wash  # washout strictly reduces Y_B
        assert YB == pytest.approx(YB_no_wash, rel=0.2)  # but mildly at 0.01

    # Battery spanning every stiff knob (washout / depletion /
    # annihilation, thermal and nonthermal starts).  The Radau reference
    # runs with the exact KJMA kernel (table_n=None — the reference's
    # 800-point spline carries ~1e-4 interpolation bias) and the
    # pulse-aware step cap (without any cap Radau coasts across the
    # source pulse and returns Y_B ~ 0, measured).  Per-component atol:
    # annihilation re-thermalizes Y_chi to ~4e-3 while Y_B sits at
    # ~1e-10, and the stiff thermalization transient is unattainable for
    # a 3rd-order method under a shared 1e-18 absolute floor.
    BATTERY = {
        "washout": dict(Gamma_wash_over_H=0.2),
        "deplete": dict(Gamma_wash_over_H=0.05, deplete_DM_from_source=True),
        "annihilate-nonthermal": dict(sigma_v_chi_GeV_m2=1e-12),
        "annihilate-thermal": dict(sigma_v_chi_GeV_m2=1e-12, thermal_start=True),
        "all-knobs": dict(Gamma_wash_over_H=0.1, deplete_DM_from_source=True,
                          sigma_v_chi_GeV_m2=3e-13, thermal_start=True),
    }

    @pytest.mark.parametrize("name", sorted(BATTERY))
    def test_cross_check_scipy_radau_1e6_contract(self, name):
        """ESDIRK vs exact-kernel pulse-capped Radau: ≤1e-6 relative on
        both final yields across the full stiff battery (the north-star
        accuracy contract on the ODE path; measured agreement ~1e-8)."""
        import jax.numpy as jnp

        from bdlz_tpu.physics.thermo import entropy_density, n_chi_equilibrium
        from bdlz_tpu.solvers.boltzmann import solve_scipy_radau

        over = dict(self.BATTERY[name])
        thermal_start = over.pop("thermal_start", False)
        cfg = bench_cfg(T_min_over_Tp=0.05, **over)
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)
        T_p = cfg.T_p_GeV
        T_lo, T_hi = 0.05 * T_p, 5.0 * T_p
        Y0chi = (
            float(n_chi_equilibrium(T_hi, cfg.m_chi_GeV, cfg.g_chi, "fermion", np)
                  / entropy_density(T_hi, cfg.g_star_s, np))
            if thermal_start else 4.90e-10
        )

        ref = solve_scipy_radau(
            pp, static.chi_stats, static.deplete_DM_from_source, grid,
            (Y0chi, 0.0), T_lo, T_hi, rtol=1e-12, atol=1e-22,
            reference_step_cap=False, pulse_step_cap=True, table_n=None,
        )
        assert ref.success
        sol = solve_boltzmann_esdirk(
            pp, static, grid, (Y0chi, 0.0), T_lo, T_hi,
            rtol=1e-10, atol=jnp.array([1e-13, 1e-20]), max_steps=40000,
        )
        assert bool(sol.success)
        assert float(sol.y[1]) == pytest.approx(ref.Y_B, rel=1e-6)
        assert float(sol.y[0]) == pytest.approx(ref.Y_chi, rel=1e-6)

    def test_radau_dense_spline_skips_pulse_without_cap(self):
        """Documents why the pulse cap exists: with a smooth dense A/V
        table and no step cap, Radau's local error control steps across
        the bounce pulse and loses the source entirely."""
        from bdlz_tpu.solvers.boltzmann import solve_scipy_radau

        cfg = bench_cfg(
            Gamma_wash_over_H=0.05, deplete_DM_from_source=True,
            T_min_over_Tp=0.05,
        )
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)
        T_p = cfg.T_p_GeV
        # at these exact tolerances the uncapped run was measured to coast
        # across the pulse (the failure is tolerance-sensitive: a tighter
        # atol happens to force small enough early steps to catch it —
        # which is precisely why an explicit physics-aware cap is needed
        # rather than luck)
        bad = solve_scipy_radau(
            pp, static.chi_stats, True, grid, (4.90e-10, 0.0),
            0.05 * T_p, 5.0 * T_p, rtol=1e-12, atol=1e-20,
            reference_step_cap=False, table_n=8000,
        )
        good = solve_scipy_radau(
            pp, static.chi_stats, True, grid, (4.90e-10, 0.0),
            0.05 * T_p, 5.0 * T_p, rtol=1e-12, atol=1e-20,
            reference_step_cap=False, pulse_step_cap=True, table_n=8000,
        )
        assert good.Y_B > 1e-12           # the physical yield
        assert abs(bad.Y_B) < 1e-15       # pulse skipped -> essentially zero


class TestMixedBatchFailure:
    def test_vmapped_lane_failure_isolated(self):
        """A vmapped batch where one lane exhausts max_steps: that lane
        reports failure, every other lane's yields are bit-identical to
        its solo run (VERDICT r1: failure budget under vmap)."""
        import jax
        import jax.numpy as jnp

        cfg = bench_cfg(Gamma_wash_over_H=0.05, T_min_over_Tp=0.05)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)
        T_p = cfg.T_p_GeV
        T_hi = 5.0 * T_p

        pp0 = point_params_from_config(cfg, cfg.P_chi_to_B)
        # Lane 1's absolute tolerance sits ~16 decades below the final
        # Y_B: the controller treadmills in the exponential source ramp
        # (measured: ~4 100 steps needed) and exhausts the 2 000-step
        # budget.  Lanes 0/2 are healthy (~250 steps at atol 1e-16).
        # (A giant beta/H no longer fails: the position-aware pulse cap
        # makes the step count beta-invariant — see
        # test_beta_invariant_step_count.)
        betas = jnp.array([100.0, 110.0, 120.0])
        pp_b = type(pp0)(*(
            jnp.full(3, f) if name != "beta_over_H" else betas
            for name, f in zip(pp0._fields, pp0)
        ))
        atols = jnp.array([1e-16, 1e-26, 1e-16])

        def solve_one(pp, atol):
            # method pinned: the step counts this test is built around
            # (healthy ~250, treadmill ~4100) are the kvaerno3 pair's
            return solve_boltzmann_esdirk(
                pp, static, grid, (4.90e-10, 0.0), 0.05 * T_p, T_hi,
                rtol=1e-8, atol=atol, max_steps=2000, method="kvaerno3",
            )

        batch = jax.vmap(solve_one)(pp_b, atols)
        ok = np.asarray(batch.success)
        assert ok.tolist() == [True, False, True]
        assert int(batch.n_steps[1]) == 2000  # budget exhaustion, not NaN

        for lane in (0, 2):
            pp_i = type(pp0)(*(np.asarray(f)[lane] for f in pp_b))
            solo = solve_one(pp_i, float(atols[lane]))
            assert float(batch.y[lane, 1]) == float(solo.y[1])
            assert float(batch.y[lane, 0]) == float(solo.y[0])

    def test_beta_invariant_step_count(self):
        """The position-aware pulse cap makes the attempted-step count
        essentially independent of beta/H: the pulse narrows as 1/beta but
        the capped region narrows with it (16 sigma_y/B wide at a
        sigma_y/(3B) cap). The global-cap design needed ~1e8 steps at
        beta/H = 1e7; this pins the fix."""
        cfg = bench_cfg(Gamma_wash_over_H=0.05, T_min_over_Tp=0.05)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)
        T_p = cfg.T_p_GeV
        pp0 = point_params_from_config(cfg, cfg.P_chi_to_B)
        steps = {}
        for beta in (100.0, 1e7):
            sol = solve_boltzmann_esdirk(
                pp0._replace(beta_over_H=beta), static, grid,
                (4.90e-10, 0.0), 0.05 * T_p, 5.0 * T_p,
                rtol=1e-10, atol=1e-18, max_steps=4000,
            )
            assert bool(sol.success), beta
            steps[beta] = int(sol.n_steps)
        assert steps[1e7] < 1.5 * steps[100.0], steps

    def test_sweep_masks_failed_lane_and_reports_position(self):
        """Through the sweep engine: the failing lane surfaces as NaN in
        the failure mask at the right position; healthy lanes unaffected.
        (The failing point is a non-physical corner — negative mass — that
        poisons every step attempt; a giant beta/H no longer fails under
        the position-aware pulse cap.)"""
        from bdlz_tpu.parallel import make_mesh, run_sweep

        cfg = bench_cfg(Gamma_wash_over_H=0.05, T_min_over_Tp=0.2)
        static = static_choices_from_config(cfg)
        mesh = make_mesh(shape=(4, 2))
        res = run_sweep(
            cfg, {"m_chi_GeV": [0.95, -1.0, 1.2]}, static, mesh=mesh,
            chunk_size=8, n_y=2000,
        )
        assert res.n_failed == 1
        assert res.failed_mask.tolist() == [False, True, False]
        assert np.isfinite(res.outputs["Y_B"][[0, 2]]).all()


class TestSDIRK4Tableau:
    """The 4th-order default pair: coefficient verification (no
    transcription leap of faith) and accuracy against uncapped Radau."""

    def test_kvaerno3_order_conditions_and_l_stability(self):
        from bdlz_tpu.solvers.sdirk import _tableau_kvaerno3

        c, A, b, b_emb, order, g, explicit_first = _tableau_kvaerno3()
        c, A = np.array(c), np.array(A)
        b, be = np.array(b), np.array(b_emb)
        assert order == 3.0 and explicit_first
        tol = 1e-14
        assert np.abs(A.sum(1) - c).max() < tol          # row sums
        assert abs(b.sum() - 1) < tol                    # order 1
        assert abs(b @ c - 1 / 2) < tol                  # order 2
        assert abs(b @ (c * c) - 1 / 3) < tol            # order 3
        assert abs(b @ (A @ c) - 1 / 6) < tol
        # embedded pair: order 2
        assert abs(be.sum() - 1) < tol
        assert abs(be @ c - 1 / 2) < tol
        # L-stability with the singular (explicit-first-stage) A: for a
        # stiffly accurate ESDIRK, R(inf) = -(A~^{-1} a_col)_last where
        # A~ is the implicit block and a_col its first column
        Ai, acol = A[1:, 1:], A[1:, 0]
        assert abs(np.linalg.solve(Ai, acol)[-1]) < 1e-12

    def test_order_conditions_and_l_stability(self):
        from bdlz_tpu.solvers.sdirk import _tableau_sdirk4

        c, A, b, b_emb, order, g, explicit_first = _tableau_sdirk4()
        c, A = np.array(c), np.array(A)
        b, be = np.array(b), np.array(b_emb)
        assert order == 4.0 and not explicit_first
        tol = 1e-14
        assert np.abs(A.sum(1) - c).max() < tol          # row sums
        assert abs(b.sum() - 1) < tol                    # order 1
        assert abs(b @ c - 1 / 2) < tol                  # order 2
        assert abs(b @ (c * c) - 1 / 3) < tol            # order 3
        assert abs(b @ (A @ c) - 1 / 6) < tol
        assert abs(b @ (c ** 3) - 1 / 4) < tol           # order 4
        assert abs((b * c) @ (A @ c) - 1 / 8) < tol
        assert abs(b @ (A @ (c * c)) - 1 / 12) < tol
        assert abs(b @ (A @ (A @ c)) - 1 / 24) < tol
        # embedded pair: order 3
        assert abs(be.sum() - 1) < tol
        assert abs(be @ c - 1 / 2) < tol
        assert abs(be @ (c * c) - 1 / 3) < tol
        assert abs(be @ (A @ c) - 1 / 6) < tol
        # L-stability: R(inf) = 1 - b A^{-1} 1 = 0
        assert abs(1 - b @ np.linalg.solve(A, np.ones(5))) < 1e-12

    def test_fourth_order_convergence(self):
        """Error vs rtol on a smooth nonlinear system with closed-form
        solution: y2 = e^-t, y1 = (1 + t) e^{-2t}."""
        import jax.numpy as jnp

        def rhs(t, y):
            return jnp.array([-2.0 * y[0] + y[1] ** 2, -y[1]])

        exact = np.array([(1.0 + 2.0) * np.exp(-4.0), np.exp(-2.0)])
        errs = {}
        for rtol in (1e-5, 1e-9):
            sol = esdirk_solve(rhs, 0.0, 2.0, jnp.array([1.0, 1.0]),
                               rtol=rtol, atol=1e-14, method="sdirk4")
            errs[rtol] = np.abs(np.asarray(sol.y) - exact).max()
        assert errs[1e-9] < 1e-10
        assert errs[1e-9] < errs[1e-5] / 50  # genuinely higher-order

    def test_matches_uncapped_radau_on_washout_config(self):
        """The default engine (sdirk4, atol 1e-17) against SciPy Radau at
        rtol 1e-12 with the exact kernel: the measured worst-corner error
        over the bench grid is 1.5e-8; this pins one corner to 1e-7."""
        from bdlz_tpu.solvers.boltzmann import solve_scipy_radau

        cfg = bench_cfg(Gamma_wash_over_H=0.0937, T_min_over_Tp=0.05)
        static = static_choices_from_config(cfg)
        T_p = cfg.T_p_GeV
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)._replace(
            m_chi_GeV=0.8786
        )
        grid_np = make_kjma_grid(np)
        ref = solve_scipy_radau(
            pp, static.chi_stats, static.deplete_DM_from_source, grid_np,
            (4.9e-10, 0.0), 0.05 * T_p, 5.0 * T_p,
            rtol=1e-12, atol=1e-22, reference_step_cap=False,
            table_n=None, pulse_step_cap=True,
        )
        sol = solve_boltzmann_esdirk(
            pp, static, grid_np, (4.9e-10, 0.0), 0.05 * T_p, 5.0 * T_p,
        )
        assert bool(sol.success)
        assert float(sol.y[1]) == pytest.approx(ref.Y_B, rel=1e-7)
