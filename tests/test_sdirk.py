"""ESDIRK stiff-integrator tests (SURVEY §4.2/§4.5): analytic solutions,
stiff stability, the quadrature cross-check on a washout-free config, and
the Γ_wash=0.01 regression the reference cannot finish."""
import time

import numpy as np
import pytest

from bdlz_tpu.config import (
    config_from_dict,
    point_params_from_config,
    static_choices_from_config,
)
from bdlz_tpu.physics.percolation import make_kjma_grid
from bdlz_tpu.solvers.quadrature import integrate_YB_quadrature
from bdlz_tpu.solvers.sdirk import esdirk_solve, solve_boltzmann_esdirk


def bench_cfg(**over):
    base = {
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    }
    base.update(over)
    return config_from_dict(base)


class TestESDIRKCore:
    def test_linear_decay_exact(self):
        import jax.numpy as jnp

        lam = 3.0

        def rhs(x, y):
            return -lam * y

        sol = esdirk_solve(rhs, 0.0, 2.0, jnp.array([1.0, 0.5]), rtol=1e-10, atol=1e-14)
        assert bool(sol.success)
        expected = np.array([1.0, 0.5]) * np.exp(-lam * 2.0)
        np.testing.assert_allclose(np.asarray(sol.y), expected, rtol=1e-8)

    def test_stiff_decay_stable(self):
        """λ = 1e6 over unit interval: explicit methods explode, an
        L-stable ESDIRK takes few steps."""
        import jax.numpy as jnp

        def rhs(x, y):
            return -1e6 * (y - jnp.array([2.0, 3.0]))

        sol = esdirk_solve(rhs, 0.0, 1.0, jnp.array([0.0, 0.0]), rtol=1e-8, atol=1e-12)
        assert bool(sol.success)
        # An explicit method would need ~1e6 steps (stability limit
        # h < 2/λ); the L-stable ESDIRK needs only enough to *resolve*
        # the initial transient to rtol.
        assert int(sol.n_steps) < 2000
        np.testing.assert_allclose(np.asarray(sol.y), [2.0, 3.0], atol=1e-7)

    def test_nonautonomous_quadrature(self):
        """y' = cos(x): pure quadrature through the solver."""
        import jax.numpy as jnp

        def rhs(x, y):
            return jnp.full_like(y, jnp.cos(x))

        sol = esdirk_solve(rhs, 0.0, 1.5, jnp.zeros(2), rtol=1e-10, atol=1e-14)
        np.testing.assert_allclose(np.asarray(sol.y), np.sin(1.5), rtol=1e-7)

    def test_max_steps_reports_failure(self):
        import jax.numpy as jnp

        def rhs(x, y):
            return -1e6 * y

        sol = esdirk_solve(rhs, 0.0, 1.0, jnp.ones(2), rtol=1e-12, atol=1e-18, max_steps=3)
        assert not bool(sol.success)


class TestBoltzmannESDIRK:
    def test_matches_quadrature_when_source_only(self):
        """With σv=0, Γ_wash=0, no depletion, the ODE must reproduce the
        quadrature Y_B (the two solvers share only the RHS physics)."""
        import jax.numpy as jnp

        cfg = bench_cfg()
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)

        YB_quad = float(
            integrate_YB_quadrature(
                pp, static.chi_stats, make_kjma_grid(jnp), jnp, n_y=8000
            )
        )
        T_p = cfg.T_p_GeV
        sol = solve_boltzmann_esdirk(
            pp, static, grid, (4.90e-10, 0.0), 0.001 * T_p, 5.0 * T_p,
            rtol=1e-10, atol=1e-18,
        )
        assert bool(sol.success)
        assert float(sol.y[0]) == pytest.approx(4.90e-10, rel=1e-12)  # untouched
        assert float(sol.y[1]) == pytest.approx(YB_quad, rel=1e-4)

    def test_washout_config_finishes_fast(self):
        """The Γ_wash/H=0.01 config the reference cannot finish in 90 s
        (SURVEY §2.1) must complete here in seconds and show washout."""
        import jax.numpy as jnp

        cfg = bench_cfg(Gamma_wash_over_H=0.01)
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)
        T_p = cfg.T_p_GeV

        t0 = time.time()
        sol = solve_boltzmann_esdirk(
            pp, static, grid, (4.90e-10, 0.0), 0.001 * T_p, 5.0 * T_p,
            rtol=1e-10, atol=1e-18,
        )
        assert bool(sol.success)
        YB = float(sol.y[1])
        elapsed = time.time() - t0

        YB_no_wash = float(
            integrate_YB_quadrature(
                pp, static.chi_stats, make_kjma_grid(jnp), jnp, n_y=8000
            )
        )
        assert elapsed < 60.0  # includes compile; execution is ~ms
        assert 0.0 < YB < YB_no_wash  # washout strictly reduces Y_B
        assert YB == pytest.approx(YB_no_wash, rel=0.2)  # but mildly at 0.01

    def test_cross_check_scipy_radau_uncapped(self):
        """Backend parity on the ODE path: ESDIRK (JAX) vs SciPy Radau with
        the step cap disabled, on a depletion+washout toy config."""
        from bdlz_tpu.solvers.boltzmann import solve_scipy_radau

        cfg = bench_cfg(
            Gamma_wash_over_H=0.05,
            deplete_DM_from_source=True,
            T_min_over_Tp=0.05,
        )
        pp = point_params_from_config(cfg, cfg.P_chi_to_B)
        static = static_choices_from_config(cfg)
        grid = make_kjma_grid(np)
        T_p = cfg.T_p_GeV
        T_lo, T_hi = 0.05 * T_p, 5.0 * T_p

        ref = solve_scipy_radau(
            pp, static.chi_stats, True, grid, (4.90e-10, 0.0), T_lo, T_hi,
            rtol=1e-10, atol=1e-18, reference_step_cap=False,
        )
        assert ref.success
        sol = solve_boltzmann_esdirk(
            pp, static, grid, (4.90e-10, 0.0), T_lo, T_hi, rtol=1e-10, atol=1e-18
        )
        assert bool(sol.success)
        assert float(sol.y[1]) == pytest.approx(ref.Y_B, rel=1e-5)
        assert float(sol.y[0]) == pytest.approx(ref.Y_chi, rel=1e-6)
