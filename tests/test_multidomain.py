"""Seam-split emulator domains + predicted-error-gated serving tests.

Rides the session ``seam_emulator`` fixture (a seam-crossing (m_chi,
T_p) box built both split and single-domain, plus the saved bundle).
The pins mirror the PR's acceptance criteria at tier-1 size:

* domain-stitch BIT-parity against a standalone build of the same
  sub-box (stitching adds zero error);
* per-domain held-out error inside the advertised tolerance, with the
  split build spending fewer exact points than the single-domain
  comparator at equal tolerance;
* a fake-clock serve trace pinning the gated-vs-ungated fallback
  counts and the per-request fallback reasons;
* multi-domain bundle tamper / schema-skew / impersonation rejection,
  registry publish/fetch of the whole bundle;
* the posterior-weighted refinement hook (weight joins the artifact
  identity, dead regions coarsen).
"""
import json
import os
import shutil

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.emulator import (
    AxisSpec,
    EmulatorArtifactError,
    MultiDomainArtifact,
    build_emulator,
    domain_artifacts,
    error_floor,
    has_error_grid,
    load_any_artifact,
    load_artifact,
    load_multidomain_artifact,
    make_domain_fn,
    make_error_fn,
    make_query_fn,
    seam_band_for_box,
)
from bdlz_tpu.emulator.multidomain import (
    MultiDomainBuildError,
    multidomain_hash,
)


def _trace(n=96, seed=17):
    rng = np.random.default_rng(seed)
    return np.stack([
        10 ** rng.uniform(np.log10(20.0), np.log10(600.0), n),
        10 ** rng.uniform(np.log10(95.0), np.log10(105.0), n),
    ], axis=1)


def _in_band(bundle, trace):
    band = bundle.seam_band
    k = bundle.axis_names.index(band["axis"])
    lo_hull, hi_hull = bundle.hull
    inside_hull = np.all((trace >= lo_hull) & (trace <= hi_hull), axis=1)
    return inside_hull & (trace[:, k] > band["lo"]) & (
        trace[:, k] < band["hi"]
    )


class TestSeamBand:
    def test_band_descriptor(self, seam_emulator):
        _, _, bundle, report, _, _, kw = seam_emulator
        band = bundle.seam_band
        assert band["axis"] == "m_chi_GeV"
        assert band["kind"] == "T=m/3 flux seam"
        # the band brackets the m = 3*T_p diagonal for T_p in [95, 105]
        assert 20.0 < band["lo"] < 3.0 * 95.0
        assert 3.0 * 105.0 < band["hi"] < 600.0
        assert report.seam_band == band

    def test_smooth_box_has_no_band(self, seam_emulator):
        base = seam_emulator[0]
        spec = {
            "m_chi_GeV": AxisSpec(0.9, 1.1, 3, "log"),
            "T_p_GeV": AxisSpec(90.0, 110.0, 3, "log"),
        }
        assert seam_band_for_box(base, spec, rtol=1e-4) is None
        # forcing the split on a smooth box is a loud error, not a
        # silent single-domain build
        with pytest.raises(MultiDomainBuildError, match="never crosses"):
            build_emulator(base, spec, seam_split=True, rtol=1e-2,
                           n_probe=2, max_rounds=0, n_y=200)

    def test_seam_split_false_forces_single_domain(self, seam_emulator):
        _, _, _, _, single, _, _ = seam_emulator
        # the fixture's comparator came from seam_split=False over the
        # crossing box: a plain artifact, not a bundle
        assert not isinstance(single, MultiDomainArtifact)
        assert single.predicted_error is not None


class TestSplitBuild:
    def test_domains_disjoint_ordered_shared_identity(self, seam_emulator):
        _, _, bundle, _, _, _, _ = seam_emulator
        assert len(bundle.domains) == 2
        band = bundle.seam_band
        lo_dom, hi_dom = bundle.domains
        assert lo_dom.manifest["seam_side"] == "below_seam"
        assert hi_dom.manifest["seam_side"] == "above_seam"
        assert lo_dom.domain["m_chi_GeV"][1] <= band["lo"] * (1 + 1e-12)
        assert hi_dom.domain["m_chi_GeV"][0] >= band["hi"] * (1 - 1e-12)
        assert lo_dom.identity == hi_dom.identity == bundle.identity

    def test_per_domain_held_out_within_tolerance(self, seam_emulator):
        """The acceptance pin: every domain's held-out error (fresh
        random points inside ITS sub-box, never seen by refinement)
        meets the advertised tolerance — the split turned an
        unconvergeable box into two convergeable ones."""
        _, _, bundle, report, _, _, kw = seam_emulator
        assert report.converged
        assert len(report.domain_reports) == 2
        for dom, rep in zip(bundle.domains, report.domain_reports):
            assert rep.converged, dom.manifest["seam_side"]
            assert rep.max_rel_err <= kw["rtol"]
            assert dom.manifest["max_rel_err"] == rep.max_rel_err
        assert report.max_rel_err == max(
            r.max_rel_err for r in report.domain_reports
        )

    def test_split_spends_fewer_exact_points_at_equal_tolerance(
        self, seam_emulator
    ):
        """The build-A/B pin (tier-1 shadow of the bench line): at equal
        rtol AND equal round budget the split build converges while the
        single-domain build grinds first-order against the diagonal
        kink — and still spends MORE exact sweep points."""
        _, _, _, report, _, single_report, _ = seam_emulator
        assert report.converged and not single_report.converged
        assert report.n_exact_evals < single_report.n_exact_evals

    def test_report_aggregates(self, seam_emulator):
        _, _, bundle, report, _, _, _ = seam_emulator
        assert report.n_exact_evals == sum(
            r.n_exact_evals for r in report.domain_reports
        )
        sides = {row["seam_side"] for row in report.rounds}
        assert sides == {"below_seam", "above_seam"}
        assert bundle.manifest["n_exact_evals"] == report.n_exact_evals
        assert bundle.n_points == sum(d.n_points for d in bundle.domains)


class TestStitchBitParity:
    def test_domain_values_bitwise_equal_standalone_build(
        self, seam_emulator
    ):
        """THE stitching contract: a bundle domain's table, and the
        bundle kernel's answers inside that domain, are BITWISE
        identical to a standalone artifact built over the same sub-box
        — stitching adds zero error."""
        base, _, bundle, _, _, _, kw = seam_emulator
        dom = bundle.domains[0]
        lo, hi = dom.domain["m_chi_GeV"]
        spec = {
            "m_chi_GeV": AxisSpec(lo, hi, 3, "log"),
            "T_p_GeV": AxisSpec(95.0, 105.0, 2, "log"),
        }
        # the bundle resolved one quadrature scheme for every side;
        # the standalone comparator must state the same scheme
        static = static_choices_from_config(base)._replace(
            quad_panel_gl=bool(dom.identity.get("quad_panel_gl", False))
        )
        standalone, _rep = build_emulator(
            base, spec, static, seam_split=False, **kw
        )
        for f in dom.values:
            np.testing.assert_array_equal(
                standalone.values[f], dom.values[f], err_msg=f
            )
        for a, b in zip(standalone.axis_nodes, dom.axis_nodes):
            np.testing.assert_array_equal(a, b)
        # and the STITCHED query kernel returns those exact bits
        rng = np.random.default_rng(3)
        t = np.stack([
            10 ** rng.uniform(np.log10(lo), np.log10(hi), 32),
            10 ** rng.uniform(np.log10(95.0), np.log10(105.0), 32),
        ], axis=1)
        v_bundle = np.asarray(make_query_fn(bundle)(t))
        v_alone = np.asarray(make_query_fn(standalone)(t))
        np.testing.assert_array_equal(v_bundle, v_alone)

    def test_band_is_out_of_domain(self, seam_emulator):
        _, _, bundle, _, _, _, _ = seam_emulator
        band = bundle.seam_band
        mid = np.sqrt(band["lo"] * band["hi"])
        dom_fn = make_domain_fn(bundle)
        t = np.array([
            [mid, 100.0],            # inside the seam band
            [50.0, 100.0],           # below_seam domain
            [500.0, 100.0],          # above_seam domain
            [1000.0, 100.0],         # beyond the hull
        ])
        inside = np.asarray(dom_fn(t))
        assert list(inside) == [False, True, True, False]

    def test_error_fn_routes_per_domain(self, seam_emulator):
        _, _, bundle, _, _, _, kw = seam_emulator
        assert has_error_grid(bundle)
        err = np.asarray(make_error_fn(bundle)(
            np.array([[50.0, 100.0], [500.0, 100.0]])
        ))
        # converged domains: per-cell estimates under the internal
        # refinement target (rtol/safety), floored at 0
        assert np.all(err >= 0.0) and np.all(err <= kw["rtol"])


class TestBundleArtifact:
    def test_save_load_round_trip(self, seam_emulator):
        _, bundle_dir, bundle, _, _, _, _ = seam_emulator
        loaded = load_multidomain_artifact(bundle_dir)
        assert loaded.content_hash == bundle.content_hash
        assert loaded.seam_band == bundle.seam_band
        for a, b in zip(loaded.domains, bundle.domains):
            for f in b.values:
                np.testing.assert_array_equal(a.values[f], b.values[f])
            np.testing.assert_array_equal(
                a.predicted_error, b.predicted_error
            )
        # kind dispatch: load_any on both kinds
        assert isinstance(load_any_artifact(bundle_dir), MultiDomainArtifact)

    def test_single_loader_rejects_bundle_loudly(self, seam_emulator):
        _, bundle_dir, _, _, _, _, _ = seam_emulator
        with pytest.raises(EmulatorArtifactError, match="MULTI-DOMAIN"):
            load_artifact(bundle_dir)

    def test_bundle_values_view_refuses_array_access(self, seam_emulator):
        """``field in bundle.values`` works (the single-artifact checks
        consumers run) but ARRAY access raises — silently handing out
        one domain's table as "the" surface would cover half the box."""
        _, _, bundle, _, _, _, _ = seam_emulator
        assert "DM_over_B" in bundle.values
        assert sorted(bundle.values) == sorted(bundle.domains[0].values)
        with pytest.raises(EmulatorArtifactError, match="per domain"):
            bundle.values["DM_over_B"]

    def test_multidomain_loader_rejects_single(self, tiny_emulator):
        _, out_dir, _, _ = tiny_emulator
        with pytest.raises(EmulatorArtifactError, match="not a multi-domain"):
            load_multidomain_artifact(out_dir)

    def _copy(self, bundle_dir, tmp_path, name):
        dst = str(tmp_path / name)
        shutil.copytree(bundle_dir, dst)
        return dst

    def test_tampered_domain_rejected(self, seam_emulator, tmp_path):
        _, bundle_dir, _, _, _, _, _ = seam_emulator
        dst = self._copy(bundle_dir, tmp_path, "tamper")
        npz = os.path.join(dst, "domain_00", "artifact.npz")
        with np.load(npz) as data:
            arrays = {k: np.array(data[k]) for k in data.files}
        key = next(k for k in arrays if k.startswith("field_"))
        arrays[key][(0,) * arrays[key].ndim] *= 1.5
        np.savez(npz, **arrays)
        with pytest.raises(EmulatorArtifactError, match="content-hash"):
            load_multidomain_artifact(dst)

    def test_swapped_domain_rejected(self, seam_emulator, tmp_path):
        """A domain directory replaced by ANOTHER valid artifact (its
        own hash verifies) must still be refused: the bundle manifest
        names the hash it was built with."""
        _, bundle_dir, _, _, _, _, _ = seam_emulator
        dst = self._copy(bundle_dir, tmp_path, "swap")
        shutil.rmtree(os.path.join(dst, "domain_00"))
        shutil.copytree(os.path.join(dst, "domain_01"),
                        os.path.join(dst, "domain_00"))
        with pytest.raises(EmulatorArtifactError,
                           match="swapped/impersonating"):
            load_multidomain_artifact(dst)

    def test_schema_skew_rejected(self, seam_emulator, tmp_path):
        _, bundle_dir, _, _, _, _, _ = seam_emulator
        dst = self._copy(bundle_dir, tmp_path, "schema")
        mpath = os.path.join(dst, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["schema_version"] += 1
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        with pytest.raises(EmulatorArtifactError, match="schema_version"):
            load_multidomain_artifact(dst)

    def test_tampered_band_rejected(self, seam_emulator, tmp_path):
        """The seam band joins the COMPOSITE hash: editing it (which
        would move queries between the emulator and the exact path)
        fails the bundle's content check even though every domain still
        verifies."""
        _, bundle_dir, _, _, _, _, _ = seam_emulator
        dst = self._copy(bundle_dir, tmp_path, "band")
        mpath = os.path.join(dst, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["seam_band"]["hi"] *= 1.01
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        with pytest.raises(EmulatorArtifactError, match="composite"):
            load_multidomain_artifact(dst)

    def test_composite_hash_construction(self, seam_emulator):
        _, _, bundle, _, _, _, _ = seam_emulator
        assert bundle.content_hash == multidomain_hash(
            [d.content_hash for d in bundle.domains],
            bundle.seam_band, bundle.identity,
        )

    def test_registry_publish_fetch_bundle(self, seam_emulator, tmp_path):
        """Registry satellite: the WHOLE bundle publishes/fetches as one
        unit under its composite hash, with full validation on fetch."""
        from bdlz_tpu.provenance import Store, fetch_artifact, publish_artifact

        _, bundle_dir, bundle, _, _, _, _ = seam_emulator
        store = Store(str(tmp_path / "store"))
        h = publish_artifact(store, bundle_dir)
        assert h == bundle.content_hash
        fetched = fetch_artifact(store, h)
        assert isinstance(fetched, MultiDomainArtifact)
        assert fetched.content_hash == bundle.content_hash
        # corrupt the published entry: fetch deletes it and raises
        npz = os.path.join(store.root, "emulator_artifact", h,
                           "domain_00", "artifact.npz")
        with open(npz, "r+b") as f:
            f.seek(200)
            f.write(b"\x00" * 16)
        with pytest.raises(EmulatorArtifactError):
            fetch_artifact(store, h)
        assert not os.path.isdir(
            os.path.join(store.root, "emulator_artifact", h)
        )

    def test_rollout_stages_bundle(self, seam_emulator):
        """Blue/green over a bundle: a FleetService serving the bundle
        accepts a re-staged copy of the same bundle (identity match),
        swaps atomically, and responses carry the composite hash."""
        from bdlz_tpu.serve.fleet import FleetService
        from bdlz_tpu.serve.rollout import ArtifactRollout

        base, bundle_dir, bundle, _, _, _, _ = seam_emulator
        svc = FleetService(
            bundle, base, max_batch_size=8, n_replicas=1, max_wait_s=0.001,
        )
        rollout = ArtifactRollout(svc)
        staged_hash = rollout.stage(bundle_dir)
        assert staged_hash == bundle.content_hash
        old, new = rollout.cutover()
        assert old == new == bundle.content_hash
        fut = svc.submit([50.0, 100.0])
        svc.run_once(force=True)
        svc.drain()
        assert fut.result(timeout=0).artifact_hash == bundle.content_hash


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestGatedServing:
    def test_default_gate_resolution(self, seam_emulator):
        from bdlz_tpu.serve.service import YieldService, resolve_error_gate

        base, _, bundle, _, _, _, kw = seam_emulator
        # converged bundle with error grids: engine default = rtol_target
        assert resolve_error_gate(bundle, base) == kw["rtol"]
        # explicit disable
        assert resolve_error_gate(bundle, base, False) is None
        svc = YieldService(bundle, base, max_batch_size=16, warm=False,
                           error_gate_tol=False)
        assert svc.error_gate_tol is None
        with pytest.raises(ValueError, match="positive"):
            resolve_error_gate(bundle, base, -1.0)
        # True through the ARGUMENT path must be as loud as through the
        # config (float(True)=1.0 would silently disable the gate)
        with pytest.raises(ValueError, match="ambiguous"):
            resolve_error_gate(bundle, base, True)

    def test_config_knob_resolves(self, seam_emulator):
        import dataclasses

        from bdlz_tpu.serve.service import resolve_error_gate

        base, _, bundle, _, _, _, _ = seam_emulator
        base_off = dataclasses.replace(base, error_gate_tol=False)
        assert resolve_error_gate(bundle, base_off) is None
        base_tol = dataclasses.replace(base, error_gate_tol=3e-3)
        assert resolve_error_gate(bundle, base_tol) == 3e-3
        # explicit argument wins over the config knob
        assert resolve_error_gate(bundle, base_off, 1e-2) == 1e-2

    def test_error_floor_semantics(self, seam_emulator):
        """An artifact that missed its advertised tolerance is floored
        at +inf: under ANY active gate, EVERY in-domain query is
        answered by the exact path (the old "serve exact" policy for
        untrusted surfaces, now automatic) — because its own estimates
        provably failed (a lucky held-out draw can pass while the
        surface serves kink cells wrong)."""
        from bdlz_tpu.serve.service import YieldService

        base, _, _, _, single, _, _ = seam_emulator
        bad = single._replace(manifest={
            **single.manifest, "converged": False, "max_rel_err": 0.5,
        })
        assert error_floor(bad) == float("inf")
        svc = YieldService(bad, base, max_batch_size=32, warm=False)
        trace = _trace(24)
        values, n_fallback, errors, _r, reasons, n_gated = (
            svc._evaluate_isolated(trace)
        )
        lo, hi = bad.hull
        inside = np.all((trace >= lo) & (trace <= hi), axis=1)
        assert n_fallback == 24
        assert n_gated == int(inside.sum()) > 0
        for i, r in enumerate(reasons):
            assert r == ("predicted_error" if inside[i] else "ood")

    def test_fake_clock_trace_pins_gated_vs_ungated_counts(
        self, seam_emulator
    ):
        """The serve-trace pin: on one deterministic seam-crossing
        trace through the fake-clock batcher, the UNGATED service falls
        back exactly for the out-of-domain (seam-band) queries, the
        GATED service adds exactly the over-threshold cells, and the
        ServeStats rows carry the n_gated split."""
        from bdlz_tpu.serve.service import YieldService

        base, _, bundle, _, _, _, _ = seam_emulator
        trace = _trace(64)
        in_band = _in_band(bundle, trace)
        pred = np.asarray(make_error_fn(bundle)(trace))
        tol = 1e-6  # far below the converged cells' spread: some gate
        expect_gated = (~in_band) & (pred > tol)
        assert in_band.any(), "trace must cross the seam band"
        assert expect_gated.any(), "tol must gate some in-domain cells"

        counts = {}
        for name, gate in (("ungated", False), ("gated", tol)):
            svc = YieldService(
                bundle, base, max_batch_size=64, warm=False,
                error_gate_tol=gate,
            )
            clock = FakeClock()
            mb = svc.make_batcher(max_wait_s=0.005, clock=clock)
            futs = [mb.submit(t) for t in trace]
            assert mb.run_once() == 64
            for f in futs:
                assert np.isfinite(f.result(timeout=0))
            s = svc.stats.summary()
            counts[name] = (s["fallbacks"], s["gated_fallbacks"])
        assert counts["ungated"] == (int(in_band.sum()), 0)
        assert counts["gated"] == (
            int(in_band.sum() + expect_gated.sum()),
            int(expect_gated.sum()),
        )

    def test_annotated_batcher_reports_reasons(self, seam_emulator):
        from bdlz_tpu.serve.service import ServeAnswer, YieldService

        base, _, bundle, _, _, _, _ = seam_emulator
        band = bundle.seam_band
        mid = float(np.sqrt(band["lo"] * band["hi"]))
        svc = YieldService(bundle, base, max_batch_size=4, warm=False)
        clock = FakeClock()
        mb = svc.make_batcher(max_wait_s=0.005, clock=clock, annotate=True)
        f_in = mb.submit([50.0, 100.0])
        f_band = mb.submit([mid, 100.0])
        f_out = mb.submit([5000.0, 100.0])
        clock.advance(0.006)
        assert mb.run_once() == 3
        for f, want in ((f_in, None), (f_band, "ood"), (f_out, "ood")):
            ans = f.result(timeout=0)
            assert isinstance(ans, ServeAnswer)
            assert ans.fallback_reason == want
            assert np.isfinite(ans.value)

    def test_fleet_reasons_and_gating(self, seam_emulator):
        """FleetResponse carries the fallback reason; the fleet's fused
        per-replica kernel gates identically to YieldService."""
        from bdlz_tpu.serve.fleet import FleetService

        base, _, bundle, _, _, _, _ = seam_emulator
        band = bundle.seam_band
        mid = float(np.sqrt(band["lo"] * band["hi"]))
        clock = FakeClock()
        svc = FleetService(
            bundle, base, max_batch_size=4, n_replicas=2,
            max_wait_s=0.005, clock=clock, error_gate_tol=1e-6,
        )
        thetas = [[50.0, 100.0], [mid, 100.0], [5000.0, 100.0]]
        futs = [svc.submit(t) for t in thetas]
        clock.advance(0.006)
        svc.run_once()
        svc.drain()
        resps = [f.result(timeout=0) for f in futs]
        assert resps[1].fallback_reason == "ood"        # seam band
        assert resps[2].fallback_reason == "ood"        # beyond hull
        pred = float(np.asarray(make_error_fn(bundle)(
            np.array([thetas[0]])
        ))[0])
        want = "predicted_error" if pred > 1e-6 else None
        assert resps[0].fallback_reason == want
        rows = svc.stats.as_rows()
        assert sum(r["n_gated"] for r in rows) == int(pred > 1e-6)
        assert all(r.artifact_hash == bundle.content_hash for r in resps)

    def test_fleet_values_match_service_bitwise(self, seam_emulator):
        """The fused fleet kernel and the service kernels answer the
        same trace with the same bits (fallback slots included — both
        run the same exact engine)."""
        from bdlz_tpu.serve.fleet import FleetService
        from bdlz_tpu.serve.service import YieldService

        base, _, bundle, _, _, _, _ = seam_emulator
        trace = _trace(24, seed=23)
        svc = YieldService(bundle, base, max_batch_size=24, warm=False)
        vals_svc, _ = svc.evaluate(trace)
        clock = FakeClock()
        fleet = FleetService(
            bundle, base, max_batch_size=24, n_replicas=2,
            max_wait_s=0.001, clock=clock,
        )
        futs = [fleet.submit(t) for t in trace]
        clock.advance(0.01)
        fleet.run_once()
        fleet.drain()
        vals_fleet = np.array([f.result(timeout=0).value for f in futs])
        np.testing.assert_array_equal(vals_svc, vals_fleet)


class TestLogprobMulti:
    def test_fast_mode_accepts_bundle(self, seam_emulator):
        """Satellite: make_pipeline_logprob(emulator=<bundle dir>) —
        MCMC rides the multi-domain surface with no call-site changes.
        Walkers route to their domain; the seam band and the outside
        both score -inf."""
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.sampling.likelihoods import make_pipeline_logprob

        base, bundle_dir, bundle, _, _, _, _ = seam_emulator
        static = static_choices_from_config(base)
        lp = make_pipeline_logprob(
            base, static, None, param_keys=("m_chi_GeV",),
            emulator=bundle_dir,
        )
        band = bundle.seam_band
        mid = float(np.sqrt(band["lo"] * band["hi"]))
        vals = np.asarray(jax.jit(jax.vmap(lp))(jnp.asarray(
            [[50.0], [500.0], [mid], [5000.0]]
        )))
        # in-domain walkers score finite or -inf-from-Planck; band and
        # out-of-hull walkers are -inf by domain routing
        assert vals[2] == -np.inf and vals[3] == -np.inf
        # the in-domain scores equal the per-domain interpolation's
        from bdlz_tpu.constants import RHO_CRIT_OVER_H2_KG_M3  # noqa: F401
        assert np.isfinite(vals[0]) or vals[0] == -np.inf
        assert np.isfinite(vals[1]) or vals[1] == -np.inf

    def test_pinned_axis_inside_seam_band_rejected(self, seam_emulator):
        """A non-sampled axis pinned INSIDE the seam band can never be
        contained by any domain — every walker would score -inf; the
        construction must fail loudly (domain membership, not the hull,
        is the check)."""
        import dataclasses

        from bdlz_tpu.sampling.likelihoods import make_pipeline_logprob

        base, _, bundle, _, _, _, _ = seam_emulator
        band = bundle.seam_band
        mid = float(np.sqrt(band["lo"] * band["hi"]))
        base_in_band = dataclasses.replace(base, m_chi_GeV=mid)
        with pytest.raises(ValueError, match="every emulator domain"):
            make_pipeline_logprob(
                base_in_band, static_choices_from_config(base_in_band),
                None, param_keys=("T_p_GeV",), emulator=bundle,
            )

    def test_stale_bundle_rejected(self, seam_emulator):
        import dataclasses

        from bdlz_tpu.sampling.likelihoods import make_pipeline_logprob

        base, bundle_dir, _, _, _, _, _ = seam_emulator
        base2 = dataclasses.replace(base, incident_flux_scale=2e-9)
        with pytest.raises(EmulatorArtifactError, match="identity mismatch"):
            make_pipeline_logprob(
                base2, static_choices_from_config(base2), None,
                param_keys=("m_chi_GeV",), emulator=bundle_dir,
            )


class TestPosteriorWeight:
    @pytest.fixture(scope="class")
    def weighted_pair(self):
        """A small smooth box built unweighted and Planck-weighted."""
        base = config_from_dict({
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        })
        spec = {
            "m_chi_GeV": AxisSpec(0.9, 1.1, 3, "log"),
            "T_p_GeV": AxisSpec(90.0, 110.0, 3, "log"),
            "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
        }
        kw = dict(rtol=1e-4, n_probe=8, n_holdout=24, max_rounds=6,
                  n_y=300, chunk_size=64)
        plain, plain_rep = build_emulator(base, spec, **kw)
        weighted, weighted_rep = build_emulator(
            base, spec, posterior_weight="planck", **kw
        )
        return base, plain, plain_rep, weighted, weighted_rep

    def test_weight_coarsens_and_joins_identity(self, weighted_pair):
        base, plain, plain_rep, weighted, weighted_rep = weighted_pair
        # the weighted criterion can only relax splits: never MORE
        # exact points, and in a box where the Planck posterior is
        # non-uniform, strictly fewer
        assert weighted_rep.n_exact_evals <= plain_rep.n_exact_evals
        assert weighted_rep.converged
        # weighted held-out meets tolerance UNDER THE WEIGHT; the raw
        # number is recorded too and may exceed it (dead regions)
        assert weighted_rep.weighted_max_rel_err is not None
        assert weighted_rep.posterior_weight == "planck"
        assert plain_rep.posterior_weight is None
        # single identity home: the artifact's posterior_weight key
        assert weighted.identity.get("posterior_weight") == "planck"
        assert "posterior_weight" not in plain.identity
        assert weighted.manifest["posterior_weight"] == "planck"
        assert weighted.content_hash != plain.content_hash

    def test_identity_wildcard_and_strict(self, weighted_pair):
        import dataclasses

        from bdlz_tpu.emulator import build_identity, check_identity

        base, plain, _, weighted, _ = weighted_pair
        static = static_choices_from_config(base)._replace(
            quad_panel_gl=bool(
                weighted.identity.get("quad_panel_gl", False)
            )
        )
        n_y = int(weighted.identity["n_y"])
        impl = str(weighted.identity["impl"])
        # caller with no expectation (knob unset): matches either
        check_identity(weighted, build_identity(base, static, n_y, impl))
        check_identity(plain, build_identity(base, static, n_y, impl))
        # caller naming the weight: strict both ways
        base_w = dataclasses.replace(base, posterior_weight="planck")
        check_identity(
            weighted, build_identity(base_w, static, n_y, impl)
        )
        with pytest.raises(EmulatorArtifactError, match="identity mismatch"):
            check_identity(
                plain, build_identity(base_w, static, n_y, impl)
            )

    def test_gate_covers_dead_regions(self, weighted_pair):
        """The composition the PR exists for: the weighted build's
        persisted per-cell estimates stay RAW, so wherever the weight
        left the surface coarse, the serve gate routes queries to the
        exact path instead of serving the coarse value."""
        base, plain, _, weighted, weighted_rep = weighted_pair
        assert weighted.predicted_error is not None
        # raw estimates are recorded unweighted: anywhere the weighted
        # build stopped refining early, the raw cell estimate exceeds
        # what the plain build left behind
        assert float(np.max(weighted.predicted_error)) >= float(
            np.max(plain.predicted_error)
        )
