"""Tier-1 pins for bdlz-lint (the JAX-aware static-analysis pass).

Two directions, both load-bearing:

* the package itself must stay at ZERO unsuppressed findings (and zero
  stale suppression comments) — every rule-class regression (host np in
  jit, tracer branches, host syncs, magic floats, stray config writes,
  missing static_argnums, knob-contract drift R8–R12) becomes a CI
  failure from now on;
* the analyzer must actually catch each class: a fixture with one
  seeded violation per rule must trip all of R1–R7 (per-file) and
  R8–R12 (the cross-file contract fixture package).
"""
import json
import pathlib
import subprocess
import sys
import textwrap

from bdlz_tpu.lint import RULES, lint_paths, lint_source

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "bdlz_tpu"
FIXTURE = (
    REPO_ROOT / "tests" / "fixtures" / "lint" / "physics"
    / "seeded_violations.py"
)
CONTRACT_FIXTURE = REPO_ROOT / "tests" / "fixtures" / "lint" / "contractpkg"

PER_FILE_RULES = {"R1", "R2", "R3", "R4", "R5", "R6", "R7"}
CONTRACT_RULES = {"R8", "R9", "R10", "R11", "R12"}


def _run_cli(*argv: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "bdlz_tpu.lint", *argv],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_package_has_zero_unsuppressed_findings():
    report = lint_paths([str(PACKAGE)])
    assert report.files_scanned > 40
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"unsuppressed bdlz-lint findings:\n{offenders}"
    stale = "\n".join(s.render() for s in report.stale_suppressions)
    assert not report.stale_suppressions, f"stale suppressions:\n{stale}"


def test_cli_exits_zero_on_package():
    proc = _run_cli("bdlz_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_fixture_trips_every_rule():
    # the per-file fixture trips R1-R7; the contract fixture package
    # (cross-file: config + identity constructor + driver) trips R8-R12
    # — one lint run over both must trip the FULL rule table
    report = lint_paths([str(FIXTURE), str(CONTRACT_FIXTURE)])
    tripped = {f.rule for f in report.active}
    assert tripped == set(RULES), (
        f"expected all of {sorted(RULES)}, got {sorted(tripped)}"
    )


def test_contract_fixture_one_seeded_violation_per_new_rule():
    report = lint_paths([str(CONTRACT_FIXTURE)])
    by_rule = {}
    for f in report.active:
        by_rule.setdefault(f.rule, []).append(f)
    assert {r: len(fs) for r, fs in by_rule.items()} == {
        r: 1 for r in CONTRACT_RULES
    }, "\n".join(f.render() for f in report.active)
    # the R8 finding IS the PR-7 drift class, caught statically: the
    # quad_panel_gl tri-state with no identity home would let a flipped
    # resolution silently resume results computed under the other one
    (r8,) = by_rule["R8"]
    assert "quad_panel_gl" in r8.message
    assert r8.path.endswith("config.py")
    # R10/R11/R12 land in the driver module, R8/R9 in the config module
    # — the pass is genuinely cross-file, not per-file
    assert {by_rule[r][0].path.endswith("tool_cli.py")
            for r in ("R10", "R11", "R12")} == {True}


def test_cli_exits_nonzero_on_fixture_with_json_report():
    proc = _run_cli(str(FIXTURE), "--format", "json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["n_findings"] == 7
    assert set(payload["counts_by_rule"]) == PER_FILE_RULES
    assert all(
        {"path", "line", "col", "rule", "message", "hint", "suppressed"}
        <= set(f)
        for f in payload["findings"]
    )


def test_per_line_suppression_syntax():
    source = FIXTURE.read_text()
    suppressed = source.replace(
        "y = np.asarray(x)",
        "y = np.asarray(x)  # bdlz-lint: disable=R1",
    )
    report = lint_source(suppressed, path="physics/seeded_variant.py")
    assert {f.rule for f in report.active} == PER_FILE_RULES - {"R1"}
    assert [f.rule for f in report.suppressed] == ["R1"]

    all_off = "\n".join(
        line + "  # bdlz-lint: disable=all" for line in source.splitlines()
    )
    report = lint_source(all_off, path="physics/seeded_variant.py")
    assert not report.active
    assert len(report.suppressed) == 7


def test_rule_subset_selection():
    proc = _run_cli(str(FIXTURE), "--rules", "R5", "--format", "json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert set(payload["counts_by_rule"]) == {"R5"}


def test_shape_metadata_branches_are_not_tracer_branches():
    # xs.shape[0] is trace-static: looping on it is host control flow
    source = (
        "import jax\n"
        "def body(xs):\n"
        "    while xs.shape[0] > 1:\n"
        "        xs = xs.reshape((-1, 2) + xs.shape[1:])[:, 0]\n"
        "    return xs\n"
        "run = jax.jit(body)\n"
    )
    report = lint_source(source, path="ops/tree_product.py")
    assert not [f for f in report.active if f.rule == "R2"]


def test_lint_sh_clean_including_batching_engine():
    """scripts/lint.sh — the repo's one lint command — is part of tier-1:
    it must exit 0 on the tree, and the lane-repacking stiff engine
    specifically (solvers/batching.py + the solvers it drives) must carry
    zero unsuppressed findings (host-orchestration np use is exactly the
    surface R1 exists to police, so it is pinned per-file, not only via
    the package-wide sweep)."""
    proc = subprocess.run(
        ["bash", str(REPO_ROOT / "scripts" / "lint.sh")],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = lint_paths([
        str(PACKAGE / "solvers" / "batching.py"),
        str(PACKAGE / "solvers" / "sdirk.py"),
    ])
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"stiff-engine findings:\n{offenders}"


def test_panel_quadrature_module_clean():
    """solvers/panels.py builds its node/weight tables with host NumPy
    and runs its edge-snapping inside jit/vmap — exactly the R1/R2/R3
    surface bdlz-lint polices — so the new module is pinned per-file
    (scripts/lint.sh covers it via the package sweep too), along with
    the quadrature module it extends and the validation audit that
    gates it."""
    report = lint_paths([
        str(PACKAGE / "solvers" / "panels.py"),
        str(PACKAGE / "solvers" / "quadrature.py"),
        str(PACKAGE / "validation.py"),
    ])
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"panel-quadrature findings:\n{offenders}"


def test_faults_and_retry_modules_clean():
    """The robustness layer (deterministic fault injection + bounded
    retry) is host-side orchestration by construction — exactly the code
    bdlz-lint's STATIC_PARAM_NAMES additions (fault_plan/retry_policy/…)
    must keep out of tracer-analysis false positives — so the two
    modules are pinned per-file at zero unsuppressed findings."""
    report = lint_paths([
        str(PACKAGE / "faults.py"),
        str(PACKAGE / "utils" / "retry.py"),
    ])
    assert report.files_scanned == 2
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"robustness-layer findings:\n{offenders}"


def test_scheduler_and_worker_modules_clean():
    """The elastic scheduler/worker pair is the R7 rule's reason to
    exist (all waiting through injectable clock/sleep seams, no bare
    time.sleep) and leans on the elastic STATIC_PARAM_NAMES additions
    (lease_ttl_s/n_workers/churn_plan/…) — pinned per-file at zero
    unsuppressed findings so a regression names the module."""
    report = lint_paths([
        str(PACKAGE / "parallel" / "scheduler.py"),
        str(PACKAGE / "parallel" / "worker.py"),
    ])
    assert report.files_scanned == 2
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"elastic-scheduler findings:\n{offenders}"


def test_emulator_and_serve_packages_clean():
    """The emulator's jitted query kernel is a prime R1/R3 surface (host
    np in a jit-reachable interpolation, device syncs in the batcher hot
    path) — pinned per-package like the stiff engine, not only via the
    package-wide sweep."""
    report = lint_paths([
        str(PACKAGE / "emulator"),
        str(PACKAGE / "serve"),
    ])
    assert report.files_scanned >= 9
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"emulator/serve findings:\n{offenders}"


def test_provenance_package_clean():
    """The provenance plane (typed identities, hardened store, artifact
    registry) is host-side by construction — exactly the code the
    STATIC_PARAM_NAMES additions (cache_enabled/cache_root) must keep
    out of tracer-analysis false positives — and its hash construction
    now backs every result identity in the repo, so the package is
    pinned per-file at zero unsuppressed findings alongside the two
    cache consumers it rewired (sweep chunk loop, refcache)."""
    report = lint_paths([
        str(PACKAGE / "provenance"),
        str(PACKAGE / "parallel" / "sweep.py"),
        str(PACKAGE / "validation.py"),
    ])
    assert report.files_scanned >= 6
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"provenance findings:\n{offenders}"


def test_fleet_and_rollout_modules_clean():
    """The fleet's per-replica jitted closure (device-put tables feeding
    interp_log_fields under jit/vmap) is exactly the R1/R2 surface the
    STATIC_PARAM_NAMES additions (n_replicas/queue_bound/routing/
    rollout) must keep free of false positives, and the rollout driver
    is pure host orchestration — both new modules are pinned per-file at
    zero unsuppressed findings."""
    report = lint_paths([
        str(PACKAGE / "serve" / "fleet.py"),
        str(PACKAGE / "serve" / "rollout.py"),
    ])
    assert report.files_scanned == 2
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"fleet/rollout findings:\n{offenders}"


def test_health_plane_module_clean():
    """The replica health plane (serve/health.py) is pure host-side
    bookkeeping on the injectable clock — no jax import at all — and
    the fleet/rollout healing hooks must stay that way: pinned per-file
    at zero unsuppressed findings alongside the fleet modules above
    (STATIC_PARAM_NAMES additions: health/health_enabled/
    breaker_window/breaker_threshold/rollback_budget)."""
    report = lint_paths([str(PACKAGE / "serve" / "health.py")])
    assert report.files_scanned == 1
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"health-plane findings:\n{offenders}"


def test_tenancy_module_clean():
    """The multi-tenant plane (serve/tenancy.py) is pure host-side
    orchestration — pool routing, the autoscaler's hysteresis on the
    injectable clock, LRU eviction, cold admission by content hash —
    that delegates every computation to the pool fleets: pinned
    per-file at zero unsuppressed findings (STATIC_PARAM_NAMES
    additions: tenant_map/tenant_routing/memory_budget_bytes/
    autoscale_interval_s/pool_min_replicas/replica_budget)."""
    report = lint_paths([str(PACKAGE / "serve" / "tenancy.py")])
    assert report.files_scanned == 1
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"tenancy findings:\n{offenders}"


def test_seam_split_and_gating_modules_clean():
    """The seam-split plane: multidomain.py is host-side orchestration
    (band scan, sub-builds, bundle IO), grid.py gained the jitted
    predicted-error gather and the multi-domain where-select routing
    (prime R1/R2 surface), and the serve/likelihood layers were rewired
    for gating + reasons — exactly the code the STATIC_PARAM_NAMES
    additions (seam_split/error_gate_tol/posterior_weight) must keep
    out of tracer-analysis false positives.  All pinned per-file at
    zero unsuppressed findings."""
    report = lint_paths([
        str(PACKAGE / "emulator" / "multidomain.py"),
        str(PACKAGE / "emulator" / "grid.py"),
        str(PACKAGE / "emulator" / "build.py"),
        str(PACKAGE / "serve" / "service.py"),
        str(PACKAGE / "serve" / "fleet.py"),
        str(PACKAGE / "sampling" / "likelihoods.py"),
    ])
    assert report.files_scanned == 6
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"seam-split findings:\n{offenders}"


def test_scenario_plane_modules_clean():
    """The LZ scenario plane (docs/scenarios.md): chain.py carries the
    jitted N-level eigendecomposition propagator (prime R1/R2 surface —
    host np use next to traced xp math), thermal.py the host-side bath
    rate + dispatch, options.py the shared CLI flag surface, and
    sweep_bridge.py gained the scenario dispatch + the N-aware P table
    — exactly the code the STATIC_PARAM_NAMES additions
    (lz_mode/lz_n_levels/lz_bath_eta/lz_bath_omega_c/n_levels) must
    keep out of tracer-analysis false positives.  All pinned per-file
    at zero unsuppressed findings."""
    report = lint_paths([
        str(PACKAGE / "lz" / "chain.py"),
        str(PACKAGE / "lz" / "thermal.py"),
        str(PACKAGE / "lz" / "options.py"),
        str(PACKAGE / "lz" / "sweep_bridge.py"),
    ])
    assert report.files_scanned == 4
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"scenario-plane findings:\n{offenders}"


def test_gradient_and_nuts_modules_clean():
    """The differentiable-posterior layer (sampling/grad.py — jax.grad
    closures, FD harness, Fisher fields), the NUTS sampler
    (sampling/nuts.py — jitted tree-building with host-side adaptation
    orchestration next to traced math, prime R1/R2 surface), and the
    likelihood module whose bounds loop was vectorized
    (sampling/likelihoods.py) are exactly the code the
    STATIC_PARAM_NAMES additions (sampler/mass_matrix/target_accept)
    must keep out of tracer-analysis false positives.  All pinned
    per-file at zero unsuppressed findings, plus the checkpoint layer
    that grew the sampler dispatch."""
    report = lint_paths([
        str(PACKAGE / "sampling" / "grad.py"),
        str(PACKAGE / "sampling" / "nuts.py"),
        str(PACKAGE / "sampling" / "likelihoods.py"),
        str(PACKAGE / "sampling" / "checkpoint.py"),
        str(PACKAGE / "sampling" / "diagnostics.py"),
    ])
    assert report.files_scanned == 5
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"gradient/NUTS-layer findings:\n{offenders}"


def test_bounce_modules_clean():
    """The in-framework bounce solver (docs/scenarios.md
    "Potential-space axes"): shooting.py carries the jitted
    fixed-lane-width vmapped program (prime R1/R2 surface — host np
    padding/stacking next to traced xp segment math), potential.py the
    dual-use V/V' operators + host Newton vacua, and bounce_cli.py the
    operator surface — exactly the code the STATIC_PARAM_NAMES
    additions (bounce/lane_width/n_segments/n_bisect/n_dense/n_xi/
    rho_max) must keep out of tracer-analysis false positives.  All
    pinned per-file at zero unsuppressed findings."""
    report = lint_paths([
        str(PACKAGE / "bounce" / "potential.py"),
        str(PACKAGE / "bounce" / "shooting.py"),
        str(PACKAGE / "bounce" / "__init__.py"),
        str(PACKAGE / "bounce_cli.py"),
    ])
    assert report.files_scanned == 4
    offenders = "\n".join(f.render() for f in report.active)
    assert not report.active, f"bounce-solver findings:\n{offenders}"


# ---------------------------------------------------------------------------
# v2: knob-contract analyzer (R8-R12), stale suppressions, SARIF, cache


def test_static_param_names_cover_every_static_choices_field():
    """Auto-derived pin: a new StaticChoices field cannot forget the
    manual STATIC_PARAM_NAMES += step (the field would start tripping
    R2/R6 false positives in every consumer) — and no tracer-valued
    PointParams field may ever leak INTO the static set, which would
    exempt real physics inputs from the tracer rules."""
    from bdlz_tpu.config import PointParams, StaticChoices
    from bdlz_tpu.lint.analyzer import STATIC_PARAM_NAMES

    missing = set(StaticChoices._fields) - STATIC_PARAM_NAMES
    assert not missing, (
        f"StaticChoices fields missing from STATIC_PARAM_NAMES: "
        f"{sorted(missing)}"
    )
    leaked = set(PointParams._fields) & STATIC_PARAM_NAMES
    assert not leaked, (
        f"tracer-valued PointParams fields in STATIC_PARAM_NAMES: "
        f"{sorted(leaked)}"
    )


def test_stale_suppression_detected_and_fails_cli(tmp_path):
    # a disable comment on a clean line suppresses nothing -> reported
    # as stale, and the CLI exits nonzero on it even with 0 findings
    clean = "def f():\n    return 1  # bdlz-lint: disable=R4\n"
    report = lint_source(clean, path="ops/clean.py")
    assert not report.active
    assert [(s.rule, s.line) for s in report.stale_suppressions] == [
        ("R4", 2)
    ]
    mod = tmp_path / "clean.py"
    mod.write_text(clean)
    proc = _run_cli(str(mod))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale suppression" in proc.stdout


def test_live_suppression_is_not_stale():
    source = FIXTURE.read_text().replace(
        "y = np.asarray(x)",
        "y = np.asarray(x)  # bdlz-lint: disable=R1",
    )
    report = lint_source(source, path="physics/seeded_variant.py")
    assert not report.stale_suppressions
    # an unknown rule id never suppresses anything -> always stale
    report = lint_source(
        "x = 1  # bdlz-lint: disable=R99\n", path="ops/clean.py"
    )
    assert [s.rule for s in report.stale_suppressions] == ["R99"]


def test_rule_subset_does_not_misreport_other_rules_as_stale():
    # a live R1 suppression must not be called stale by a run that
    # never evaluated R1
    source = FIXTURE.read_text().replace(
        "y = np.asarray(x)",
        "y = np.asarray(x)  # bdlz-lint: disable=R1",
    )
    report = lint_source(source, path="physics/seeded_variant.py",
                         rules=["R5"])
    assert not report.stale_suppressions


_CROSSFILE_CONFIG = textwrap.dedent(
    """
    from dataclasses import dataclass
    from typing import Optional

    REFERENCE_KEYS = ("x0",)
    {tuples}

    @dataclass
    class Config:
        x0: float = 1.0
        tri: Optional[bool] = None
    """
)

_CROSSFILE_IDENTITY = textwrap.dedent(
    """
    def build_identity(cfg):
        hash_extra = {extra}
        return repr(sorted(hash_extra.items()))
    """
)


def _crossfile_r8(tmp_path, name, tuples, extra):
    pkg = tmp_path / name
    pkg.mkdir()
    (pkg / "config.py").write_text(
        _CROSSFILE_CONFIG.format(tuples=tuples)
    )
    (pkg / "identity.py").write_text(
        _CROSSFILE_IDENTITY.format(extra=extra)
    )
    report = lint_paths([str(pkg)], rules=["R8"])
    return [f for f in report.active if f.rule == "R8"]


def test_cross_file_symbol_table_exactly_one_home_is_clean(tmp_path):
    # the tri-state's one home is the hash_extra key in the SIBLING
    # module — connecting them requires the cross-file symbol table
    findings = _crossfile_r8(
        tmp_path, "clean_pkg",
        tuples="",
        extra='{"tri": cfg.tri}',
    )
    assert findings == []


def test_cross_file_symbol_table_zero_homes_is_the_drift_class(tmp_path):
    # same two modules, identity key removed: zero homes -> the PR-7
    # silent-resume drift class, caught statically
    findings = _crossfile_r8(
        tmp_path, "zero_home_pkg",
        tuples="",
        extra='{"unrelated": 1}',
    )
    assert len(findings) == 1
    assert "tri" in findings[0].message
    assert "no identity home" in findings[0].message


def test_cross_file_symbol_table_two_exclusion_sets_is_a_finding(tmp_path):
    # membership in TWO exclusion tuples: two subsystems claim the
    # knob -> finding even though an identity key also exists
    findings = _crossfile_r8(
        tmp_path, "two_home_pkg",
        tuples=(
            'A_CONFIG_FIELDS = ("tri",)\n'
            'B_CONFIG_FIELDS = ("tri",)'
        ),
        extra='{"tri": cfg.tri}',
    )
    assert len(findings) == 1
    assert "two exclusion tuples" in findings[0].message


def test_r12_not_tripped_when_declared_static_or_loop_invariant():
    base = (
        "import jax\n"
        "def kernel(x, n_levels):\n"
        "    return x * n_levels\n"
        "compiled = jax.jit(kernel{static})\n"
        "def churn(x, levels):\n"
        "    out = []\n"
        "    for n in levels:\n"
        "        out.append(compiled(x, n_levels={value}))\n"
        "    return out\n"
    )
    # varying + not static -> finding
    report = lint_source(
        base.format(static="", value="n"), path="ops/churn.py",
        rules=["R12"],
    )
    assert [f.rule for f in report.active] == ["R12"]
    # declared static -> intentional per-value recompile, no finding
    report = lint_source(
        base.format(static=', static_argnames=("n_levels",)', value="n"),
        path="ops/churn.py", rules=["R12"],
    )
    assert not report.active
    # loop-invariant value -> no finding
    report = lint_source(
        base.format(static="", value="3"), path="ops/churn.py",
        rules=["R12"],
    )
    assert not report.active


def test_sarif_output_schema_and_contents():
    proc = _run_cli(str(CONTRACT_FIXTURE), "--format", "sarif")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    log = json.loads(proc.stdout)
    assert log["version"] == "2.1.0"
    (run,) = log["runs"]
    assert run["tool"]["driver"]["name"] == "bdlz-lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert set(RULES) <= rule_ids
    result_rules = {r["ruleId"] for r in run["results"]}
    assert result_rules == CONTRACT_RULES
    for r in run["results"]:
        loc = r["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1  # SARIF is 1-based


def test_cache_roundtrip_hit_and_content_invalidation(tmp_path):
    from bdlz_tpu.lint.cache import cached_lint_paths
    from bdlz_tpu.provenance.store import Store

    src = tmp_path / "mod.py"
    src.write_text("import time\ntime.sleep(0.0)\n")
    store = Store(str(tmp_path / "store"))

    live, hit = cached_lint_paths([str(src)], store=store)
    assert not hit and [f.rule for f in live.active] == ["R7"]
    cached, hit = cached_lint_paths([str(src)], store=store)
    assert hit
    # bit-for-bit: the cached report renders and serializes identically
    assert cached.to_dict() == live.to_dict()

    # content change -> new key -> live re-run sees the fix
    src.write_text("import time\n")
    fresh, hit = cached_lint_paths([str(src)], store=store)
    assert not hit and not fresh.active


def test_changed_only_restriction_is_reporting_not_analysis():
    report = lint_paths([str(FIXTURE), str(CONTRACT_FIXTURE)])
    # restricting to the contract package's config keeps ONLY its
    # findings, but those findings came from the whole-program pass
    cfg_path = str(CONTRACT_FIXTURE / "config.py")
    view = report.restrict_to([cfg_path])
    assert {f.rule for f in view.active} == {"R8", "R9"}
    assert view.files_scanned == report.files_scanned
    # the un-restricted report still carries everything
    assert {f.rule for f in report.active} == set(RULES)
