"""Serving-layer tests (bdlz_tpu/serve/).

The batcher's dispatch policy is tested with a FAKE CLOCK and direct
``run_once`` calls — no sleeping, no background threads in tier-1 (the
threaded loop is the CLI's; the policy is what has behavior worth
pinning).  The service tests ride the tiny session emulator fixture.
"""
import json

import numpy as np
import pytest

from bdlz_tpu.emulator import load_artifact
from bdlz_tpu.serve import (
    BatchResult,
    DeadlineExceeded,
    MicroBatcher,
    YieldService,
)
from bdlz_tpu.utils.profiling import ServeStats
from bdlz_tpu.utils.retry import RetryPolicy


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _echo_batcher(max_batch_size=4, max_wait_s=0.010, process=None):
    clock = FakeClock()
    calls = []

    def default_process(thetas):
        calls.append(np.array(thetas))
        return BatchResult(values=[float(t[0]) for t in thetas],
                           n_fallback=0)

    mb = MicroBatcher(
        process or default_process,
        max_batch_size=max_batch_size,
        max_wait_s=max_wait_s,
        clock=clock,
        stats=ServeStats(),
    )
    return mb, clock, calls


class TestMicroBatcherPolicy:
    def test_partial_batch_waits_for_max_wait(self):
        mb, clock, calls = _echo_batcher()
        futs = [mb.submit([float(i)]) for i in range(3)]
        # under max_batch and under max_wait: the policy holds
        assert not mb.ready_at()
        assert mb.run_once() == 0 and not calls
        # the latency bound: oldest request's age crosses max_wait
        clock.advance(0.011)
        assert mb.ready_at()
        assert mb.run_once() == 3
        assert [f.result(timeout=0) for f in futs] == [0.0, 1.0, 2.0]
        assert mb.pending() == 0

    def test_full_batch_dispatches_immediately(self):
        mb, clock, calls = _echo_batcher(max_batch_size=4)
        futs = [mb.submit([float(i)]) for i in range(4)]
        assert mb.ready_at()          # no clock advance needed
        assert mb.run_once() == 4
        assert len(calls) == 1 and calls[0].shape == (4, 1)
        assert [f.result(timeout=0) for f in futs] == [0.0, 1.0, 2.0, 3.0]

    def test_overfull_queue_dispatches_in_batch_size_chunks(self):
        mb, clock, _ = _echo_batcher(max_batch_size=4)
        futs = [mb.submit([float(i)]) for i in range(10)]
        assert mb.run_once() == 4
        assert mb.run_once() == 4
        # tail is a partial batch: waits for age, or force-drains
        assert mb.run_once() == 0
        assert mb.run_once(force=True) == 2
        assert [f.result(timeout=0) for f in futs] == [float(i) for i in range(10)]

    def test_stats_rows(self):
        mb, clock, _ = _echo_batcher(max_batch_size=4)
        for i in range(4):
            mb.submit([float(i)])
        mb.run_once()
        mb.submit([9.0])
        clock.advance(0.02)
        mb.run_once()
        rows = mb.stats.as_rows()
        assert [r["size"] for r in rows] == [4, 1]
        assert rows[0]["occupancy"] == 1.0
        assert rows[1]["occupancy"] == 0.25
        assert rows[1]["wait_s"] == pytest.approx(0.02)
        s = mb.stats.summary()
        assert s["batches"] == 2 and s["requests"] == 5
        assert s["mean_occupancy"] == pytest.approx(0.625)

    def test_process_failure_delivered_per_request_queue_survives(self):
        boom = RuntimeError("kernel exploded")

        def bad_then_good(thetas):
            if bad_then_good.fail:
                bad_then_good.fail = False
                raise boom
            return [float(t[0]) for t in thetas]

        bad_then_good.fail = True
        mb, clock, _ = _echo_batcher(max_batch_size=2, process=bad_then_good)
        f1, f2 = mb.submit([1.0]), mb.submit([2.0])
        assert mb.run_once() == 2
        with pytest.raises(RuntimeError, match="kernel exploded"):
            f1.result(timeout=0)
        with pytest.raises(RuntimeError, match="kernel exploded"):
            f2.result(timeout=0)
        # the queue is not wedged: the next batch serves normally
        f3 = mb.submit([3.0])
        clock.advance(1.0)
        assert mb.run_once() == 1
        assert f3.result(timeout=0) == 3.0

    def test_ragged_batch_is_delivered_not_fatal(self):
        """Mixed request dimensions make np.stack raise INSIDE the
        dispatch: the failure must land on the batch's futures, not
        escape and kill the background loop (which would hang every
        pending result() forever)."""
        mb, clock, _ = _echo_batcher(max_batch_size=2)
        f1, f2 = mb.submit([1.0, 2.0]), mb.submit([1.0])
        assert mb.run_once() == 2
        for f in (f1, f2):
            with pytest.raises(ValueError):
                f.result(timeout=0)
        f3 = mb.submit([3.0])
        clock.advance(1.0)
        assert mb.run_once() == 1
        assert f3.result(timeout=0) == 3.0

    def test_wrong_length_result_is_an_error_not_a_hang(self):
        mb, clock, _ = _echo_batcher(
            max_batch_size=2, process=lambda thetas: [1.0]
        )
        f1, f2 = mb.submit([1.0]), mb.submit([2.0])
        mb.run_once()
        for f in (f1, f2):
            with pytest.raises(RuntimeError, match="returned 1 values"):
                f.result(timeout=0)

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="max_batch_size"):
            MicroBatcher(lambda t: [], max_batch_size=0)
        with pytest.raises(ValueError, match="max_wait_s"):
            MicroBatcher(lambda t: [], max_wait_s=-1.0)
        with pytest.raises(ValueError, match="deadline_s"):
            MicroBatcher(lambda t: [], deadline_s=0.0)


class TestDeadlines:
    """Per-request deadlines on the injectable clock: an expired request
    is answered with the typed DeadlineExceeded at dispatch instead of
    aging the batch — and tier-1 never sleeps to prove it."""

    def _batcher(self, deadline_s=0.05, process=None):
        clock = FakeClock()
        mb = MicroBatcher(
            process or (lambda thetas: [float(t[0]) for t in thetas]),
            max_batch_size=4, max_wait_s=0.010, clock=clock,
            stats=ServeStats(), deadline_s=deadline_s,
        )
        return mb, clock

    def test_expired_requests_killed_fresh_ones_served(self):
        mb, clock = self._batcher()
        stale = [mb.submit([1.0]), mb.submit([2.0])]
        clock.advance(0.06)            # both stale past the deadline
        fresh = mb.submit([3.0])
        clock.advance(0.011)           # policy fires on max_wait age
        assert mb.run_once() == 3      # 2 killed + 1 served
        for f in stale:
            with pytest.raises(DeadlineExceeded, match="deadline"):
                f.result(timeout=0)
        assert fresh.result(timeout=0) == 3.0
        s = mb.stats.summary()
        assert s["deadline_kills"] == 2
        # the served batch never saw the stale requests' wait
        assert s["requests"] == 1 and s["batches"] == 1

    def test_deadline_must_exceed_max_wait(self):
        """deadline_s <= max_wait_s would deterministically shed every
        sparse request (the wait policy ages lone requests to max_wait_s
        before dispatch) — rejected at construction."""
        with pytest.raises(ValueError, match="must exceed max_wait_s"):
            MicroBatcher(
                lambda t: [], max_wait_s=0.005, deadline_s=0.002,
            )

    def test_expired_requests_free_their_dispatch_slots(self):
        """Expired requests are drained from the queue head BEFORE the
        batch is sliced, so dead requests never consume dispatch slots
        that still-live requests behind them need."""
        mb, clock = self._batcher(deadline_s=0.05)
        stale = [mb.submit([float(i)]) for i in range(3)]
        clock.advance(0.06)
        live = [mb.submit([10.0 + i]) for i in range(4)]  # a full batch
        assert mb.run_once() == 7      # 3 killed + 4 served in ONE pass
        for f in stale:
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=0)
        assert [f.result(timeout=0) for f in live] == [10.0, 11.0, 12.0, 13.0]
        s = mb.stats.summary()
        assert s["deadline_kills"] == 3
        assert s["batches"] == 1 and s["requests"] == 4

    def test_fully_expired_dispatch_records_no_batch_row(self):
        mb, clock = self._batcher()
        f = mb.submit([1.0])
        clock.advance(1.0)
        assert mb.run_once() == 1
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=0)
        s = mb.stats.summary()
        assert s["deadline_kills"] == 1 and s["batches"] == 0

    def test_injected_slow_clock_triggers_deadline_kills(self):
        """The "slow collections" fault class: an injected clock delay
        (site "clock", applied THROUGH the injectable clock, never a
        real sleep) ages the queue past the deadline at dispatch."""
        from bdlz_tpu.faults import FaultPlan

        clock = FakeClock()
        mb = MicroBatcher(
            lambda thetas: [float(t[0]) for t in thetas],
            max_batch_size=4, max_wait_s=0.010, clock=clock,
            stats=ServeStats(), deadline_s=0.05,
            fault_plan=FaultPlan.from_obj(
                [{"site": "clock", "kind": "slow", "delay_s": 1.0}]
            ),
        )
        f = mb.submit([1.0])
        assert mb.run_once() == 1   # injected delay: ready AND expired
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=0)
        assert mb.stats.summary()["deadline_kills"] == 1
        assert clock.t == 0.0       # the real clock never moved

    def test_within_deadline_served_normally(self):
        mb, clock = self._batcher(deadline_s=10.0)
        f = mb.submit([4.0])
        clock.advance(0.02)
        assert mb.run_once() == 1
        assert f.result(timeout=0) == 4.0
        assert mb.stats.summary()["deadline_kills"] == 0


class TestPerRequestErrors:
    def test_batch_result_errors_isolated_per_future(self):
        """A BatchResult carrying per-request errors fails ONLY those
        futures; batchmates deliver, and the stats row records the
        degraded-mode counters."""
        boom = RuntimeError("exact fallback dead")

        def process(thetas):
            errs = [boom if t[0] > 1.5 else None for t in thetas]
            return BatchResult(
                values=[float(t[0]) for t in thetas],
                n_fallback=1, errors=errs, n_retries=1,
            )

        mb, clock, _ = _echo_batcher(max_batch_size=2, process=process)
        f_ok, f_bad = mb.submit([1.0]), mb.submit([2.0])
        assert mb.run_once() == 2
        assert f_ok.result(timeout=0) == 1.0
        with pytest.raises(RuntimeError, match="exact fallback dead"):
            f_bad.result(timeout=0)
        s = mb.stats.summary()
        assert s["errors"] == 1 and s["retries"] == 1
        assert s["quarantine_rate"] == pytest.approx(0.5)


class TestYieldService:
    def test_out_of_domain_falls_back_to_exact(self, tiny_emulator):
        base, out_dir, _, _ = tiny_emulator
        svc = YieldService(load_artifact(out_dir), base, max_batch_size=8)
        thetas = np.array([
            [1.0, 100.0, 0.30],    # inside
            [1.0, 100.0, 0.60],    # v_w outside the tiny box
            [0.95, 95.0, 0.28],    # inside
        ])
        values, n_fallback = svc.evaluate(thetas)
        assert n_fallback == 1
        assert np.isfinite(values).all()
        # the fallback answered with the EXACT pipeline, not a clamped
        # table edge: compare against the exact evaluator directly
        from bdlz_tpu.config import static_choices_from_config
        from bdlz_tpu.emulator import make_exact_evaluator

        art = svc.artifact
        # at the artifact's FULL recorded scheme — n_y, engine, AND the
        # resolved y-quadrature the service adopts for its fallback
        static_art = static_choices_from_config(base)._replace(
            quad_panel_gl=bool(art.identity.get("quad_panel_gl", False))
        )
        exact = make_exact_evaluator(
            base, static_art,
            n_y=art.identity["n_y"], impl=art.identity["impl"],
            chunk_size=8,
        )({"m_chi_GeV": thetas[1:2, 0], "T_p_GeV": thetas[1:2, 1],
           "v_w": thetas[1:2, 2]})["DM_over_B"]
        np.testing.assert_allclose(values[1], exact[0], rtol=1e-12)

    def test_batcher_integration_counts_fallbacks(self, tiny_emulator):
        base, out_dir, _, _ = tiny_emulator
        svc = YieldService(load_artifact(out_dir), base, max_batch_size=4)
        clock = FakeClock()
        mb = svc.make_batcher(max_wait_s=0.005, clock=clock)
        futs = [
            mb.submit([1.0, 100.0, 0.30]),
            mb.submit([1.0, 100.0, 0.60]),   # out-of-domain
            mb.submit([0.95, 95.0, 0.28]),
        ]
        clock.advance(0.006)
        assert mb.run_once() == 3
        assert all(np.isfinite(f.result(timeout=0)) for f in futs)
        assert svc.stats.summary()["fallbacks"] == 1
        assert svc.stats.summary()["fallback_rate"] == pytest.approx(
            1 / 3, abs=1e-4   # summary rounds to 4 decimals
        )

    def test_query_shape_and_mapping_validation(self, tiny_emulator):
        base, out_dir, _, _ = tiny_emulator
        svc = YieldService(load_artifact(out_dir), base, max_batch_size=4)
        with pytest.raises(ValueError, match="3 coordinates"):
            svc.evaluate(np.zeros((2, 2)))
        with pytest.raises(ValueError, match="missing axes"):
            svc.theta_from_mapping({"m_chi_GeV": 1.0})
        with pytest.raises(ValueError, match="unknown axes"):
            svc.theta_from_mapping({
                "m_chi_GeV": 1.0, "T_p_GeV": 100.0, "v_w": 0.3,
                "bogus": 1.0,
            })
        theta = svc.theta_from_mapping(
            {"m_chi_GeV": 1.0, "T_p_GeV": 100.0, "v_w": 0.3}
        )
        np.testing.assert_allclose(theta, [1.0, 100.0, 0.3])

    def test_exact_fallback_failure_isolated_per_request(self, tiny_emulator):
        """A persistently failing exact fallback (site "serve_exact",
        every call) poisons ONLY the out-of-domain requests; the
        emulator-path results still deliver through the batcher."""
        base, out_dir, _, _ = tiny_emulator
        svc = YieldService(
            load_artifact(out_dir), base, max_batch_size=4,
            fault_plan='{"faults": [{"site": "serve_exact", "kind": "raise"}]}',
            retry=RetryPolicy(max_attempts=2, backoff_s=0.0,
                              sleep=lambda s: None),
        )
        clock = FakeClock()
        mb = svc.make_batcher(max_wait_s=0.005, clock=clock)
        f_in = mb.submit([1.0, 100.0, 0.30])
        f_ood = mb.submit([1.0, 100.0, 0.60])    # out-of-domain
        f_in2 = mb.submit([0.95, 95.0, 0.28])
        clock.advance(0.006)
        assert mb.run_once() == 3
        assert np.isfinite(f_in.result(timeout=0))
        assert np.isfinite(f_in2.result(timeout=0))
        with pytest.raises(RuntimeError, match="injected fault"):
            f_ood.result(timeout=0)
        s = svc.stats.summary()
        assert s["errors"] == 1 and s["retries"] == 1
        assert s["quarantine_rate"] == pytest.approx(1 / 3, abs=1e-4)

    def test_exact_fallback_transient_retried_once(self, tiny_emulator):
        """One transient exact failure costs one (injected, never slept)
        backoff, not the request: the retried call answers with the real
        exact value."""
        base, out_dir, _, _ = tiny_emulator
        sleeps = []
        svc = YieldService(
            load_artifact(out_dir), base, max_batch_size=4,
            fault_plan='{"faults": [{"site": "serve_exact", '
                       '"kind": "transient", "times": 1}]}',
            retry=RetryPolicy(max_attempts=2, backoff_s=0.01,
                              sleep=sleeps.append),
        )
        ref = YieldService(load_artifact(out_dir), base, max_batch_size=4)
        thetas = np.array([[1.0, 100.0, 0.30], [1.0, 100.0, 0.60]])
        (values, n_fallback, errors, n_retries, reasons,
         n_gated) = svc._evaluate_isolated(thetas)
        assert n_fallback == 1 and n_retries == 1
        assert errors == [None, None]
        assert reasons == [None, "ood"] and n_gated == 0
        assert len(sleeps) == 1
        np.testing.assert_array_equal(values, ref.evaluate(thetas)[0])

    def test_evaluate_keeps_loud_contract(self, tiny_emulator):
        """Direct evaluate() callers still get the raise (the batcher
        path is where isolation lives)."""
        base, out_dir, _, _ = tiny_emulator
        svc = YieldService(
            load_artifact(out_dir), base, max_batch_size=4,
            fault_plan='{"faults": [{"site": "serve_exact", "kind": "raise"}]}',
            retry=RetryPolicy(max_attempts=2, backoff_s=0.0,
                              sleep=lambda s: None),
        )
        with pytest.raises(RuntimeError, match="injected fault"):
            svc.evaluate(np.array([[1.0, 100.0, 0.60]]))
        # in-domain-only batches never touch the fallback: still served
        vals, n_fallback = svc.evaluate(np.array([[1.0, 100.0, 0.30]]))
        assert n_fallback == 0 and np.isfinite(vals).all()

    def test_stale_physics_rejected_at_construction(self, tiny_emulator):
        import dataclasses

        from bdlz_tpu.emulator import EmulatorArtifactError

        base, out_dir, _, _ = tiny_emulator
        base2 = dataclasses.replace(base, incident_flux_scale=2e-9)
        with pytest.raises(EmulatorArtifactError, match="identity mismatch"):
            YieldService(load_artifact(out_dir), base2)

    def test_warm_start_records_seconds(self, tiny_emulator):
        """Satellite pin: construction pre-compiles the padded query +
        domain kernels and records the seconds in ServeStats (the
        first-query compile spike moves out of p99); warm=False keeps
        the old lazy behavior for compile-cost-sensitive callers."""
        base, out_dir, _, _ = tiny_emulator
        svc = YieldService(load_artifact(out_dir), base, max_batch_size=8)
        assert svc.stats.summary()["warmup_seconds"] > 0.0
        cold = YieldService(load_artifact(out_dir), base, max_batch_size=8,
                            warm=False)
        assert cold.stats.summary()["warmup_seconds"] == 0.0
        # warmed and cold services answer identically
        thetas = np.array([[1.0, 100.0, 0.30], [0.95, 95.0, 0.28]])
        np.testing.assert_array_equal(
            svc.evaluate(thetas)[0], cold.evaluate(thetas)[0]
        )


class TestServeCLI:
    def test_requests_file_round_trip(self, tiny_emulator, tmp_path, capsys):
        base, out_dir, _, _ = tiny_emulator
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }))
        reqs = tmp_path / "reqs.jsonl"
        reqs.write_text("\n".join([
            json.dumps({"id": "a", "m_chi_GeV": 1.0, "T_p_GeV": 100.0,
                        "v_w": 0.30}),
            json.dumps({"id": "b", "theta": [0.95, 95.0, 0.33]}),
            json.dumps({"id": "ood", "m_chi_GeV": 1.0, "T_p_GeV": 100.0,
                        "v_w": 0.60}),
        ]) + "\n")
        from bdlz_tpu.serve.serve_cli import main

        rc = main([
            "--config", str(cfg), "--artifact", out_dir,
            "--requests", str(reqs), "--max-batch", "8",
            "--max-wait-ms", "1",
        ])
        assert rc == 0
        out_lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert [r["id"] for r in out_lines] == ["a", "b", "ood"]
        assert all(np.isfinite(r["value"]) for r in out_lines)
        assert all(r["latency_s"] >= 0 for r in out_lines)
        # the fallback-reason satellite: every JSONL answer names what
        # produced it — emulator fast path (null) vs domain miss ("ood")
        assert [r["fallback_reason"] for r in out_lines] == [
            None, None, "ood"
        ]

    def test_malformed_lines_answered_not_fatal(self, tiny_emulator,
                                                tmp_path, capsys):
        """A malformed / axis-missing request line gets a structured
        per-line error record and the stream keeps draining; exit is 0
        because at least one line served."""
        base, out_dir, _, _ = tiny_emulator
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }))
        reqs = tmp_path / "reqs.jsonl"
        reqs.write_text("\n".join([
            "{not json at all",
            json.dumps({"id": "missing", "m_chi_GeV": 1.0}),  # axes absent
            json.dumps({"id": "short", "theta": [1.0]}),      # wrong dim
            json.dumps({"id": "good", "m_chi_GeV": 1.0, "T_p_GeV": 100.0,
                        "v_w": 0.30}),
        ]) + "\n")
        from bdlz_tpu.serve.serve_cli import main

        rc = main([
            "--config", str(cfg), "--artifact", out_dir,
            "--requests", str(reqs), "--max-batch", "8",
            "--max-wait-ms", "1",
        ])
        assert rc == 0
        out_lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert len(out_lines) == 4
        assert out_lines[0]["error"] and out_lines[0]["line"] == 1
        assert out_lines[0]["id"] is None  # unparseable: no id to echo
        assert "missing axes" in out_lines[1]["error"]
        assert out_lines[1]["id"] == "missing"  # client id echoed back
        assert "coordinates" in out_lines[2]["error"]
        assert out_lines[3]["id"] == "good"
        assert np.isfinite(out_lines[3]["value"])

    def test_fleet_requests_round_trip(self, tiny_emulator, tmp_path,
                                       capsys):
        """--replicas routes through the fleet front: same answers as
        the single-kernel path, plus the artifact-hash provenance on
        every response line."""
        base, out_dir, _, _ = tiny_emulator
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }))
        reqs = tmp_path / "reqs.jsonl"
        reqs.write_text("\n".join([
            json.dumps({"id": "a", "m_chi_GeV": 1.0, "T_p_GeV": 100.0,
                        "v_w": 0.30}),
            json.dumps({"id": "b", "theta": [0.95, 95.0, 0.33]}),
            json.dumps({"id": "ood", "m_chi_GeV": 1.0, "T_p_GeV": 100.0,
                        "v_w": 0.60}),
        ]) + "\n")
        from bdlz_tpu.emulator.artifact import load_artifact as _load
        from bdlz_tpu.serve.serve_cli import main

        rc = main([
            "--config", str(cfg), "--artifact", out_dir,
            "--requests", str(reqs), "--max-batch", "8",
            "--max-wait-ms", "1", "--replicas", "2",
        ])
        assert rc == 0
        out_lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert [r["id"] for r in out_lines] == ["a", "b", "ood"]
        assert all(np.isfinite(r["value"]) for r in out_lines)
        want_hash = _load(out_dir).content_hash
        assert all(r["artifact_hash"] == want_hash for r in out_lines)
        assert all(r["latency_s"] >= 0 for r in out_lines)
        # fallback reasons ride the fleet responses too
        assert [r["fallback_reason"] for r in out_lines] == [
            None, None, "ood"
        ]

    def test_all_lines_failed_exits_nonzero(self, tiny_emulator, tmp_path,
                                            capsys):
        base, out_dir, _, _ = tiny_emulator
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }))
        reqs = tmp_path / "reqs.jsonl"
        reqs.write_text("{broken\n" + json.dumps({"id": "x"}) + "\n")
        from bdlz_tpu.serve.serve_cli import main

        rc = main([
            "--config", str(cfg), "--artifact", out_dir,
            "--requests", str(reqs), "--max-batch", "8",
            "--max-wait-ms", "1",
        ])
        assert rc == 1
        out_lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert len(out_lines) == 2
        assert all("error" in r for r in out_lines)
