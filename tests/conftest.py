"""Test bootstrap: force JAX onto an 8-device host-CPU platform.

The build environment has exactly one physical TPU chip, so multi-chip mesh
code is validated the standard JAX way: 8 virtual CPU devices via
``xla_force_host_platform_device_count`` (SURVEY §4.4). These env vars must
be set before the first ``import jax`` anywhere in the test process, which
is why they live at conftest import time.
"""
import os

# NOTE on this environment (gotchas, see .claude/skills/verify/SKILL.md):
# * JAX_PLATFORMS=cpu is IGNORED (the axon TPU plugin still wins) and the
#   interpreter pre-imports parts of jax at startup, so env vars set here
#   can be too late. jax.config.update() before first backend use is the
#   reliable override.
# * The axon TPU rejects complex128, and every eager op goes through a
#   remote compile — tests MUST run on host CPU.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

import jax

# 'jax_platforms' (not the deprecated 'jax_platform_name') is what
# reliably undoes the sitecustomize-forced axon platform: with it set to
# cpu, the axon backend is never initialized — which also keeps the suite
# alive when the axon relay is down (observed: a dead relay makes ANY
# jax.devices() call hang if axon is still in the platform list).
jax.config.update("jax_platforms", "cpu")
# 'jax_num_cpu_devices' only exists in newer JAX (>= 0.5); older releases
# get the 8 virtual devices from the XLA_FLAGS fallback set above, which
# must be in the environment before the first `import jax`.
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)
assert jax.devices()[0].platform == "cpu", "tests must run on host CPU"

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def jit_warmup():
    """The shared first-jit warm-up for bitwise-parity tests.

    The first jit-compiled execution in a process can differ from later
    identical runs by ~3e-9 rel on XLA-CPU (documented in
    docs/scenarios.md and the provenance notes; cache/replay pins
    compare against the run that WROTE them for the same reason).  Any
    test asserting bitwise equality of two runs of the same program
    must flush that wobble first — previously handled ad hoc per test
    file (the seam_emulator fixture below was the pattern).  Usage::

        def test_bitwise(jit_warmup):
            jit_warmup(fn, *args)       # throwaway first run
            assert np.array_equal(fn(*args), fn(*args))

    Returns the throwaway result (blocked until ready, so the compile
    AND the first execution have both completed).
    """
    import jax

    def _warm(fn, *args, **kwargs):
        out = fn(*args, **kwargs)
        try:
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — non-array results (tuples of
            pass           # host objects) are already concrete
        return out

    return _warm


@pytest.fixture(scope="session")
def tiny_emulator(tmp_path_factory):
    """A tiny 3-axis (3 initial nodes per axis) emulator artifact.

    Narrow box around the archived benchmark point, n_y=400, built AND
    saved once per session — tier-1 exercises build→save→load→query plus
    a real refinement pass (the lin-scale v_w axis carries genuine
    log-curvature the build must split; the two log axes are power-law
    exact) without the slow full-box build, which is a `slow` test.
    Returns (base_config, artifact_dir, artifact, report).
    """
    from bdlz_tpu.config import config_from_dict
    from bdlz_tpu.emulator import AxisSpec, build_emulator

    base = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    spec = {
        "m_chi_GeV": AxisSpec(0.9, 1.1, 3, "log"),
        "T_p_GeV": AxisSpec(90.0, 110.0, 3, "log"),
        "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
    }
    out_dir = str(tmp_path_factory.mktemp("emu") / "artifact_dir")
    artifact, report = build_emulator(
        base, spec, rtol=1e-4, n_probe=8, n_holdout=24, max_rounds=6,
        n_y=400, chunk_size=64, out_dir=out_dir, require_converged=True,
    )
    return base, out_dir, artifact, report


@pytest.fixture(scope="session")
def seam_emulator(tmp_path_factory, jit_warmup):
    """A seam-crossing (m_chi, T_p) box built BOTH ways, once per
    session: seam-split into a two-domain bundle (saved to disk) and as
    the legacy single-domain artifact at the same tolerance.

    The box straddles the T = m/3 flux-seam band (m ∈ [20, 600] GeV at
    T_p ≈ 100 with a narrow sigma_y = 1.5 source, so the band is a thin
    diagonal strip) — the exact configuration the PR-3 limitation note
    documents as "split at the band or serve exact".  A throwaway
    warm-up build runs first: the first jit execution in a process can
    differ by ~3e-9 rel on XLA-CPU, and the stitch bit-parity pins must
    compare post-warm-up runs.

    Returns (base_config, bundle_dir, bundle, bundle_report,
    single_artifact, single_report, build_kwargs).
    """
    from bdlz_tpu.config import config_from_dict
    from bdlz_tpu.emulator import AxisSpec, build_emulator

    base = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 1.5,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    spec = {
        "m_chi_GeV": AxisSpec(20.0, 600.0, 3, "log"),
        "T_p_GeV": AxisSpec(95.0, 105.0, 2, "log"),
    }
    kw = dict(
        rtol=1e-3, n_probe=6, n_holdout=24, max_rounds=6,
        max_nodes_per_axis=96, n_y=200, chunk_size=64, seed=0,
    )
    # flush the first-run jit wobble (shared jit_warmup fixture) before
    # any compared build
    jit_warmup(
        build_emulator, base,
        {"m_chi_GeV": AxisSpec(25.0, 30.0, 2, "log"),
         "T_p_GeV": AxisSpec(95.0, 105.0, 2, "log")},
        seam_split=False, rtol=1e-1, n_probe=2, n_holdout=4,
        max_rounds=0, n_y=200, chunk_size=64,
    )
    bundle_dir = str(tmp_path_factory.mktemp("seam") / "bundle_dir")
    bundle, report = build_emulator(
        base, spec, out_dir=bundle_dir, **kw
    )
    single, single_report = build_emulator(base, spec, seam_split=False, **kw)
    return base, bundle_dir, bundle, report, single, single_report, dict(kw)


@pytest.fixture(scope="session")
def benchmark_config_path(tmp_path_factory):
    """A copy of the archived benchmark config (equal-mass point)."""
    import json

    cfg = {
        "regime": "nonthermal",
        "m_chi_GeV": 0.95,
        "g_chi": 2,
        "chi_stats": "fermion",
        "sigma_v_chi_GeV_m2": 0.0,
        "T_p_GeV": 100.0,
        "beta_over_H": 100.0,
        "v_w": 0.30,
        "I_p": 0.34,
        "g_star": 106.75,
        "g_star_s": 106.75,
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "Gamma_wash_over_H": 0.0,
        "incident_flux_scale": 1.07e-9,
        "deplete_DM_from_source": False,
        "T_max_over_Tp": 5.0,
        "T_min_over_Tp": 0.001,
        "Y_chi_init": 4.90e-10,
        "n_chi_at_Tp_GeV3": None,
    }
    path = tmp_path_factory.mktemp("cfg") / "yields_config_equal_mass.json"
    path.write_text(json.dumps(cfg, indent=2))
    return str(path)
