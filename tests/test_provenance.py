"""Provenance plane pins (docs/provenance.md).

Three load-bearing claims:

* **identity byte-compatibility** — the four legacy content-identity
  systems (sweep manifest hash, emulator artifact hash, validation
  refcache key, MCMC segment hash) now construct through
  ``bdlz_tpu/provenance`` and their digests are BYTE-identical to the
  pre-provenance hand-rolled implementations, so every artifact already
  on disk keeps resolving — each compat test re-implements the legacy
  hash inline and compares;
* **store hardening** — untrusted roots refused, corrupt entries
  deleted-and-missed, partial writes evicted by age, concurrent writers
  safe, armed-fault identities disjoint from clean ones;
* **chunk-cache semantics** — a warm ``run_sweep``/``build_emulator``
  re-run serves BIT-identical results from the store, directory resume
  wins over the cache, identity changes miss, and the self-healing
  bookkeeping (quarantine masks, retry counters) round-trips through
  entries.
"""
import hashlib
import json
import os

import numpy as np
import pytest

from bdlz_tpu.config import (
    ROBUSTNESS_STATIC_FIELDS,
    config_from_dict,
    config_identity_dict,
    static_choices_from_config,
)
from bdlz_tpu.provenance import (
    Store,
    StoreUntrustedError,
    fetch_artifact,
    mcmc_segment_identity,
    publish_artifact,
    refcache_identity,
    resolve_store,
)


def _base(**over):
    return config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
        **over,
    })


AXES = {"m_chi_GeV": np.geomspace(0.3, 3.0, 8).tolist()}


class TestIdentityCompat:
    """Digest byte-compatibility with the pre-provenance constructions."""

    def test_sweep_identity_matches_legacy_grid_hash(self):
        from bdlz_tpu.parallel.sweep import grid_hash

        base = _base()
        for extra in (None, {"quad": {"panel_gl": True}}):
            payload = {
                "base": config_identity_dict(base),
                "axes": {k: list(map(float, v)) for k, v in AXES.items()},
                "n_y": 2000,
                "impl": "tabulated",
            }
            if extra:
                payload["extra"] = dict(extra)
            legacy = hashlib.sha256(
                json.dumps(payload, sort_keys=True).encode()
            ).hexdigest()[:16]
            assert grid_hash(base, AXES, 2000, extra=extra) == legacy

    def test_artifact_hash_matches_pinned_construction(self, tiny_emulator):
        """The schema-2 byte rule, pinned by manual re-derivation: the
        JSON header (now carrying ``error_grid`` when the per-cell
        estimate grid is present) followed by the field-sorted value
        bytes, then the predicted-error bytes.  (Schema 1's digest was
        byte-compatible with the pre-provenance implementation; schema 2
        is the seam-split PR's deliberate loud bump — v1 artifacts
        reject at the version check before any hash work.)"""
        from bdlz_tpu.emulator.artifact import SCHEMA_VERSION, artifact_hash

        _, _, art, _ = tiny_emulator
        assert SCHEMA_VERSION == 2 and art.predicted_error is not None
        payload = {
            "schema_version": SCHEMA_VERSION,
            "axes": {
                str(n): [float(v) for v in np.asarray(nodes)]
                for n, nodes in zip(art.axis_names, art.axis_nodes)
            },
            "scales": [str(s) for s in art.axis_scales],
            "identity": dict(art.identity),
            "fields": sorted(art.values),
            "error_grid": True,
        }
        h = hashlib.sha256()
        h.update(json.dumps(payload, sort_keys=True).encode())
        for name in sorted(art.values):
            h.update(name.encode())
            h.update(np.ascontiguousarray(
                np.asarray(art.values[name], dtype=np.float64)
            ).tobytes())
        h.update(b"predicted_error")
        h.update(np.ascontiguousarray(
            np.asarray(art.predicted_error, dtype=np.float64)
        ).tobytes())
        pinned = h.hexdigest()[:16]
        assert artifact_hash(
            art.axis_names, art.axis_nodes, art.axis_scales, art.values,
            art.identity, predicted_error=art.predicted_error,
        ) == pinned
        # and the saved artifact's recorded hash still verifies
        assert art.content_hash == pinned

    def test_refcache_key_matches_legacy_construction(self, tmp_path):
        """A ``ref_*.npy`` written under the LEGACY key must be a HIT for
        the provenance-routed cache — pre-existing refcache dirs keep
        paying out."""
        from bdlz_tpu.validation import (
            build_audit_population,
            reference_ratios_cached,
        )

        base = _base()
        static = static_choices_from_config(base)
        pop = build_audit_population(base, 4, seed=7)

        # the pre-provenance key construction, verbatim
        import bdlz_tpu.constants
        import bdlz_tpu.models.yields_pipeline
        import bdlz_tpu.ops.kjma_table
        import bdlz_tpu.physics.percolation
        import bdlz_tpu.physics.source
        import bdlz_tpu.physics.thermo
        import bdlz_tpu.solvers.panels
        import bdlz_tpu.solvers.quadrature
        import inspect

        fp = hashlib.sha256()
        for mod in (
            bdlz_tpu.constants, bdlz_tpu.models.yields_pipeline,
            bdlz_tpu.ops.kjma_table, bdlz_tpu.physics.percolation,
            bdlz_tpu.physics.source, bdlz_tpu.physics.thermo,
            bdlz_tpu.solvers.panels, bdlz_tpu.solvers.quadrature,
        ):
            fp.update(inspect.getsource(mod).encode())
        h = hashlib.sha256()
        for f in pop.grid:
            h.update(np.ascontiguousarray(
                np.asarray(f, dtype=np.float64)
            ).tobytes())
        # the scenario-plane fields postdate the legacy key and are
        # excluded from the payload (config.SCENARIO_STATIC_FIELDS —
        # their single identity home is the omit-at-default lz_scenario
        # key), which is precisely what keeps this digest byte-stable:
        # the legacy tuple never contained them
        from bdlz_tpu.config import SCENARIO_STATIC_FIELDS

        ident = tuple(
            v for f, v in zip(type(static)._fields, static)
            if f not in ROBUSTNESS_STATIC_FIELDS
            and f not in SCENARIO_STATIC_FIELDS
        )
        h.update(repr((ident, 200)).encode())
        h.update(fp.hexdigest()[:16].encode())
        legacy_key = h.hexdigest()[:24]
        assert refcache_identity(pop.grid, static, 200).digest(24) == legacy_key

        # plant a sentinel under the legacy filename: the new code must
        # HIT it (never recompute), proving key + layout compatibility
        d = tmp_path / "rc"
        d.mkdir(mode=0o700)
        sentinel = np.arange(4, dtype=np.float64)
        np.save(d / f"ref_{legacy_key}.npy", sentinel)
        stats = {}
        out = reference_ratios_cached(
            pop.grid, static, n_y=200, cache_dir=str(d), stats=stats
        )
        assert stats["cache_hit"] is True
        np.testing.assert_array_equal(out, sentinel)

    def test_mcmc_segment_identity_legacy_and_schema_bump(self):
        init = 0.1 * np.arange(8, dtype=np.float64).reshape(4, 2)
        ident = {"config": "A", "params": {"m_chi_GeV": [0.1, 10.0]}}
        payload = {
            "init": hashlib.sha256(
                np.ascontiguousarray(init).tobytes()
            ).hexdigest(),
            "seed": 5, "n_steps": 60, "checkpoint_every": 20,
            "a": 2.0, "thin": 1, "identity": ident,
        }
        legacy = hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()
        ).hexdigest()[:16]
        no_static = mcmc_segment_identity(init, 5, 60, 20, 2.0, 1, ident)
        assert no_static.digest(16) == legacy
        # folding the resolved static in is a LOUD bump: different hash,
        # and a resolved-knob flip changes it again
        st = static_choices_from_config(_base())._replace(quad_panel_gl=False)
        with_static = mcmc_segment_identity(
            init, 5, 60, 20, 2.0, 1, ident, static=st
        )
        assert with_static.digest(16) != legacy
        flipped = mcmc_segment_identity(
            init, 5, 60, 20, 2.0, 1, ident,
            static=st._replace(quad_panel_gl=True),
        )
        assert flipped.digest(16) != with_static.digest(16)

    def test_cache_knobs_excluded_from_every_identity(self):
        """cache_enabled/cache_root are orchestration: toggling them must
        stale nothing (CACHE_CONFIG_FIELDS exclusion)."""
        from bdlz_tpu.parallel.sweep import grid_hash

        base = _base()
        tuned = _base(cache_enabled=True, cache_root="/tmp/elsewhere")
        assert config_identity_dict(base) == config_identity_dict(tuned)
        assert grid_hash(base, AXES, 2000) == grid_hash(tuned, AXES, 2000)

    def test_fault_armed_chunk_keys_never_collide_with_clean(self):
        from bdlz_tpu.faults import FaultPlan
        from bdlz_tpu.parallel.sweep import (
            build_grid,
            chunk_cache_key,
            engine_identity_extra,
        )

        base = _base()
        static = static_choices_from_config(base)._replace(quad_panel_gl=False)
        pp = build_grid(base, AXES)
        plan = FaultPlan.from_obj({"faults": [
            {"site": "step", "kind": "poison", "point": 2},
        ]})
        kw = dict(n_y=400, impl="tabulated")
        clean = chunk_cache_key(base, static, pp, 0, 4, extra={}, **kw)
        armed = chunk_cache_key(
            base, static, pp, 0, 4,
            extra=engine_identity_extra(static, "tabulated", faults=plan),
            fault_ctx=("step", 0, 0, 4), **kw,
        )
        assert clean != armed
        # the injection WINDOW keys too: same slice at another chunk
        # position is a different injected result
        armed_shifted = chunk_cache_key(
            base, static, pp, 0, 4,
            extra=engine_identity_extra(static, "tabulated", faults=plan),
            fault_ctx=("step", 1, 4, 8), **kw,
        )
        assert armed != armed_shifted
        # and the platform is part of the clean core (no cross-platform
        # bit reuse)
        other = chunk_cache_key(
            base, static, pp, 0, 4, extra={}, platform="tpu", **kw
        )
        assert clean != other


class TestStore:
    def test_typed_roundtrips_and_counters(self, tmp_path):
        s = Store(str(tmp_path / "store"))
        assert s.get_json("a.json") is None          # miss
        s.put_json("a.json", {"x": 1})
        assert s.get_json("a.json") == {"x": 1}      # hit
        s.put_array("kind/b.npy", np.arange(3.0))
        np.testing.assert_array_equal(
            s.get_array("kind/b.npy"), np.arange(3.0)
        )
        s.put_npz("kind/c.npz", {"v": np.ones(2), "m": np.zeros(2, bool)})
        ent = s.get_npz("kind/c.npz")
        np.testing.assert_array_equal(ent["v"], np.ones(2))
        assert s.stats.hits == 3 and s.stats.misses == 1 and s.stats.writes == 3
        # one-level kind dirs are created 0700
        assert (tmp_path / "store" / "kind").is_dir()

    def test_entry_name_validation(self, tmp_path):
        s = Store(str(tmp_path / "store"))
        for bad in ("../x.npy", "a/b/c.npy", ".hidden", "a b.npy", ""):
            with pytest.raises(ValueError):
                s.path_for(bad)

    def test_corrupt_entry_deleted_and_missed(self, tmp_path, capsys):
        s = Store(str(tmp_path / "store"))
        s.put_npz("sweep_chunk/x.npz", {"v": np.ones(2)})
        path = s.path_for("sweep_chunk/x.npz")
        with open(path, "wb") as f:
            f.write(b"not a zip")
        assert s.get_npz("sweep_chunk/x.npz") is None
        assert "corrupt" in capsys.readouterr().err
        assert not os.path.exists(path)              # poisoned file gone
        assert s.stats.dropped_corrupt == 1
        # a rewrite makes the next read a clean hit
        s.put_npz("sweep_chunk/x.npz", {"v": np.ones(2)})
        assert s.get_npz("sweep_chunk/x.npz") is not None

    def test_partial_write_eviction_by_age(self, tmp_path):
        s = Store(str(tmp_path / "store"))
        old = tmp_path / "store" / "stale.tmp.npy"
        old.write_bytes(b"dead writer dropping")
        os.utime(old, (1, 1))                        # ancient mtime
        young = tmp_path / "store" / "live.tmp.npy"
        young.write_bytes(b"in-flight writer")
        # a publisher that died before its rename leaves a temp DIRECTORY
        # (registry.publish_artifact) — aged ones must go too
        old_dir = tmp_path / "store" / "pubXYZ.tmp"
        old_dir.mkdir()
        (old_dir / "artifact.npz").write_bytes(b"half a publish")
        os.utime(old_dir, (1, 1))
        assert s.evict_partials(max_age_s=3600) == 2
        assert not old.exists() and not old_dir.exists()
        assert young.exists()                        # may be a live writer

    def test_untrusted_roots_refused(self, tmp_path, capsys):
        real = tmp_path / "real"
        real.mkdir(mode=0o700)
        link = tmp_path / "link"
        link.symlink_to(real)
        with pytest.raises(StoreUntrustedError, match="symlink"):
            Store(str(link))
        loose = tmp_path / "loose"
        loose.mkdir()
        os.chmod(loose, 0o770)
        with pytest.raises(StoreUntrustedError, match="group/other-writable"):
            Store(str(loose))
        # resolve_store degrades to caching-disabled LOUDLY, never trusts
        assert resolve_store(str(link), label="test") is None
        assert "symlink" in capsys.readouterr().err

    def test_concurrent_writers_same_key(self, tmp_path):
        """Two processes racing the same entry: last-writer-wins on
        identical content, and the entry is readable afterwards (atomic
        mkstemp+replace — no torn zip)."""
        import multiprocessing as mp

        root = str(tmp_path / "store")
        Store(root)  # create+harden once, parent-side

        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(target=_race_writer, args=(root, i))
            for i in range(2)
        ]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=120)
            assert p.exitcode == 0
        ent = Store(root).get_npz("sweep_chunk/raced.npz")
        assert ent is not None
        np.testing.assert_array_equal(ent["v"], np.arange(64.0))


def _race_writer(root: str, worker: int) -> None:
    """Spawned by test_concurrent_writers_same_key: hammer the same key."""
    import numpy as _np

    from bdlz_tpu.provenance import Store as _Store

    s = _Store(root)
    for _ in range(25):
        s.put_npz("sweep_chunk/raced.npz", {"v": _np.arange(64.0)})
        ent = s.get_npz("sweep_chunk/raced.npz")
        assert ent is not None and ent["v"].shape == (64,)


class TestResolveStore:
    def test_tri_state_resolution(self, tmp_path, monkeypatch):
        monkeypatch.delenv("BDLZ_CACHE_ROOT", raising=False)
        base = _base()
        # default: no root configured anywhere -> caching off
        assert resolve_store(None, base) is None
        # explicit path wins
        st = resolve_store(str(tmp_path / "a"), base)
        assert isinstance(st, Store)
        # config root
        st = resolve_store(None, _base(cache_root=str(tmp_path / "b")))
        assert st is not None and st.root == str(tmp_path / "b")
        # env root
        monkeypatch.setenv("BDLZ_CACHE_ROOT", str(tmp_path / "c"))
        assert resolve_store(None, base).root == str(tmp_path / "c")
        # cache_enabled=False force-disables even an explicit store
        off = _base(cache_enabled=False, cache_root=str(tmp_path / "b"))
        assert resolve_store(Store(str(tmp_path / "a")), off) is None
        # cache_enabled=True with no root -> the XDG default
        monkeypatch.delenv("BDLZ_CACHE_ROOT", raising=False)
        monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path / "xdg"))
        st = resolve_store(None, _base(cache_enabled=True))
        assert st is not None and st.root.endswith("bdlz_store")
        assert st.root.startswith(str(tmp_path / "xdg"))


class TestSweepChunkCache:
    def _setup(self):
        base = _base()
        static = static_choices_from_config(base)._replace(
            quad_panel_gl=False  # skip the audit: keep the unit fast
        )
        return base, static

    def test_warm_rerun_hits_bitwise(self, tmp_path):
        from bdlz_tpu.parallel.sweep import run_sweep

        base, static = self._setup()
        root = str(tmp_path / "store")
        cold = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                         cache=root)
        assert cold.cache_hits == 0 and cold.cache_misses == cold.chunks == 2
        warm = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                         cache=root)
        assert warm.cache_hits == 2 and warm.cache_misses == 0
        np.testing.assert_array_equal(
            cold.outputs["DM_over_B"], warm.outputs["DM_over_B"]
        )
        assert not warm.failed_mask.any()
        # no store configured -> counters are null, outputs still computed
        plain = run_sweep(base, AXES, static, chunk_size=4, n_y=400)
        assert plain.cache_hits is None and plain.cache_misses is None

    def test_identity_change_misses(self, tmp_path):
        from bdlz_tpu.parallel.sweep import run_sweep

        base, static = self._setup()
        root = str(tmp_path / "store")
        run_sweep(base, AXES, static, chunk_size=4, n_y=400, cache=root)
        other = run_sweep(base, AXES, static, chunk_size=4, n_y=800,
                          cache=root)
        assert other.cache_hits == 0 and other.cache_misses == 2

    def test_overlapping_grid_reuses_slices(self, tmp_path):
        """Keys carry no axes/chunk position: a different sweep whose
        chunk slices repeat point values another sweep paid for hits."""
        from bdlz_tpu.parallel.sweep import run_sweep

        base, static = self._setup()
        root = str(tmp_path / "store")
        run_sweep(base, AXES, static, chunk_size=4, n_y=400, cache=root)
        # the first half of AXES as its own sweep: its single chunk is
        # byte-identical to the first chunk of the full sweep
        half = {"m_chi_GeV": AXES["m_chi_GeV"][:4]}
        res = run_sweep(base, half, static, chunk_size=4, n_y=400,
                        cache=root)
        assert res.cache_hits == 1 and res.cache_misses == 0

    def test_out_dir_resume_wins_over_cache(self, tmp_path):
        from bdlz_tpu.parallel.sweep import run_sweep

        base, static = self._setup()
        root = str(tmp_path / "store")
        out = str(tmp_path / "sweep")
        first = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                          cache=root, out_dir=out)
        again = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                          cache=root, out_dir=out)
        assert again.resumed_chunks == first.chunks == 2
        assert again.cache_hits == 0          # resume won every chunk
        np.testing.assert_array_equal(
            first.outputs["DM_over_B"], again.outputs["DM_over_B"]
        )
        # a FRESH out_dir falls through to the cache and REBUILDS the
        # sweep directory from cached bytes (still resumable after)
        out2 = str(tmp_path / "sweep2")
        cached = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                           cache=root, out_dir=out2)
        assert cached.cache_hits == 2
        assert os.path.exists(os.path.join(out2, "chunk_00000.npz"))
        resumed = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                            out_dir=out2)
        assert resumed.resumed_chunks == 2
        np.testing.assert_array_equal(
            first.outputs["DM_over_B"], resumed.outputs["DM_over_B"]
        )

    def test_quarantine_retry_roundtrip_under_armed_plan(self, tmp_path):
        """PR-5 semantics survive the cache bit-for-bit: a chaos run's
        quarantine mask AND retry counters come back identical on a warm
        hit, without re-running the healing machinery."""
        from bdlz_tpu.faults import FaultPlan
        from bdlz_tpu.parallel.sweep import run_sweep
        from bdlz_tpu.utils.retry import RetryPolicy

        base, static = self._setup()
        root = str(tmp_path / "store")
        plan = FaultPlan.from_obj({"faults": [
            {"site": "step", "kind": "transient", "key": 0, "times": 1},
            {"site": "step", "kind": "poison", "point": 2},
        ]})
        retry = RetryPolicy(max_attempts=2, backoff_s=0.0,
                            sleep=lambda s: None)
        cold = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                         cache=root, fault_plan=plan, retry=retry)
        assert cold.n_quarantined == 1 and cold.n_retries >= 1
        warm = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                         cache=root, fault_plan=plan, retry=retry)
        assert warm.cache_hits == 2
        assert warm.n_quarantined == cold.n_quarantined
        assert warm.n_retries == cold.n_retries
        np.testing.assert_array_equal(
            cold.quarantined_mask, warm.quarantined_mask
        )
        np.testing.assert_array_equal(
            cold.outputs["DM_over_B"], warm.outputs["DM_over_B"]
        )

    def test_clean_run_never_hits_armed_entries(self, tmp_path):
        from bdlz_tpu.faults import FaultPlan
        from bdlz_tpu.parallel.sweep import run_sweep
        from bdlz_tpu.utils.retry import RetryPolicy

        base, static = self._setup()
        root = str(tmp_path / "store")
        plan = FaultPlan.from_obj({"faults": [
            {"site": "step", "kind": "poison", "point": 2},
        ]})
        retry = RetryPolicy(max_attempts=2, backoff_s=0.0,
                            sleep=lambda s: None)
        chaos = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                          cache=root, fault_plan=plan, retry=retry)
        assert chaos.n_quarantined == 1
        clean = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                          cache=root)
        assert clean.cache_hits == 0          # armed entries invisible
        assert clean.n_failed == 0            # and no NaN leaked through
        # ... and the chaos run can still hit its OWN entries
        rechaos = run_sweep(base, AXES, static, chunk_size=4, n_y=400,
                            cache=root, fault_plan=plan, retry=retry)
        assert rechaos.cache_hits == 2


class TestEmulatorBuildCache:
    def test_warm_rebuild_is_bitwise_and_fully_hit(self, tmp_path):
        from bdlz_tpu.emulator import AxisSpec, build_emulator

        base = _base()
        static = static_choices_from_config(base)._replace(
            quad_panel_gl=False
        )
        spec = {
            "m_chi_GeV": AxisSpec(0.9, 1.1, 3, "log"),
            "T_p_GeV": AxisSpec(90.0, 110.0, 3, "log"),
        }
        root = str(tmp_path / "store")
        kw = dict(rtol=1e-3, n_probe=8, n_holdout=16, max_rounds=2,
                  n_y=400, chunk_size=32, seed=3)
        s1 = Store(root)
        art1, _ = build_emulator(base, spec, static, cache=s1, **kw)
        assert s1.stats.writes > 0
        s2 = Store(root)
        art2, _ = build_emulator(base, spec, static, cache=s2, **kw)
        assert s2.stats.misses == 0 and s2.stats.hits > 0
        for f in art1.values:
            np.testing.assert_array_equal(art1.values[f], art2.values[f])
        assert art1.content_hash == art2.content_hash


class TestCheckpointStaticIdentity:
    """The PR-7 drift fix: the resolved StaticChoices joins the MCMC run
    identity, so a quadrature-scheme flip invalidates resume instead of
    silently splicing a trapezoid-era chain."""

    def _logp(self):
        import jax.numpy as jnp

        def logp(theta):
            r = (theta - jnp.array([1.0, -2.0])) / jnp.array([0.7, 1.3])
            return -0.5 * jnp.sum(r * r)

        return logp

    def _init(self, W=16):
        import jax

        return 0.1 * np.asarray(
            jax.random.normal(jax.random.PRNGKey(3), (W, 2))
        )

    def test_resolved_static_flip_invalidates_resume(self, tmp_path, capsys):
        from bdlz_tpu.sampling import run_ensemble_checkpointed

        st = static_choices_from_config(_base())._replace(
            quad_panel_gl=False, ode_auto_h0=False,
            ode_pi_controller=False, ode_tabulated_av=False,
        )
        out = str(tmp_path / "chain")
        full = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=40, out_dir=out,
            checkpoint_every=20, static=st,
        )
        assert full.segments == 2 and full.resumed_segments == 0
        # same resolved static -> full resume
        again = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=40, out_dir=out,
            checkpoint_every=20, static=st,
        )
        assert again.resumed_segments == 2
        # the resolved quadrature flips (the exact PR-4 hazard) -> the
        # manifest is invalidated LOUDLY and nothing resumes
        flipped = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=40, out_dir=out,
            checkpoint_every=20, static=st._replace(quad_panel_gl=True),
        )
        assert flipped.resumed_segments == 0
        assert "different run identity" in capsys.readouterr().err
        # and a legacy (static-less) caller is also invalidated by the
        # schema bump rather than resuming the static-keyed chain
        legacy = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=40, out_dir=out,
            checkpoint_every=20,
        )
        assert legacy.resumed_segments == 0


class TestRegistryAndRollout:
    def test_publish_fetch_roundtrip(self, tmp_path, tiny_emulator):
        _, _, art, _ = tiny_emulator
        store = Store(str(tmp_path / "store"))
        h = publish_artifact(store, art)
        assert h == art.content_hash
        fetched = fetch_artifact(store, h)
        for f in art.values:
            np.testing.assert_array_equal(fetched.values[f], art.values[f])
        # republishing the same content is a no-op (same hash = same bytes)
        assert publish_artifact(store, art) == h

    def test_fetch_rejects_absent_and_impersonating(self, tmp_path,
                                                    tiny_emulator):
        from bdlz_tpu.emulator.artifact import EmulatorArtifactError

        _, _, art, _ = tiny_emulator
        store = Store(str(tmp_path / "store"))
        with pytest.raises(EmulatorArtifactError, match="no published"):
            fetch_artifact(store, "0" * 16)
        h = publish_artifact(store, art)
        # rename the entry under a different hash: the fetch re-verifies
        # and refuses the impersonating entry
        src = os.path.join(store.root, "emulator_artifact", h)
        dst = os.path.join(store.root, "emulator_artifact", "f" * 16)
        os.rename(src, dst)
        with pytest.raises(EmulatorArtifactError, match="impersonating"):
            fetch_artifact(store, "f" * 16)

    def test_corrupt_registry_entry_deleted_on_fetch(self, tmp_path,
                                                     tiny_emulator):
        from bdlz_tpu.emulator.artifact import EmulatorArtifactError

        _, _, art, _ = tiny_emulator
        store = Store(str(tmp_path / "store"))
        h = publish_artifact(store, art)
        npz = os.path.join(store.root, "emulator_artifact", h, "artifact.npz")
        with open(npz, "wb") as f:
            f.write(b"torn copy")
        with pytest.raises(EmulatorArtifactError):
            fetch_artifact(store, h)
        assert not os.path.exists(os.path.dirname(npz))  # entry evicted
        # a re-publish starts clean
        assert publish_artifact(store, art) == h
        assert fetch_artifact(store, h).content_hash == h

    def test_rollout_stage_by_content_hash(self, tmp_path, tiny_emulator):
        from bdlz_tpu.serve.fleet import FleetService
        from bdlz_tpu.serve.rollout import ArtifactRollout

        base, _, art, _ = tiny_emulator
        store = Store(str(tmp_path / "store"))
        h = publish_artifact(store, art)
        svc = FleetService(art, base, max_batch_size=8, n_replicas=1)
        rollout = ArtifactRollout(svc, store=store)
        staged = rollout.stage(h, warm=False)
        assert staged == h and rollout.staged_hash == h


class TestRegistryRace:
    """The real 2-process fetch-vs-evict race (ISSUE-14 satellite).

    One contested store root, two OS processes: a churner that loops
    publish -> corrupt-one-byte -> evict-on-fetch -> republish (the
    same content hash), and a fetcher hammering ``fetch_artifact`` the
    whole time.  The registry contract under churn: every fetch either
    serves a FULLY VALIDATED artifact (table bytes identical to the
    pristine copy — asserted in the worker) or raises typed — never a
    torn read.  Real subprocesses + wall-clock churn, so slow-marked
    like the other ``_mp`` siblings (tier-1 covers the single-process
    corrupt-entry eviction above).
    """

    @pytest.mark.slow
    def test_concurrent_fetch_during_evict_and_republish(
        self, tmp_path, tiny_emulator
    ):
        import subprocess
        import sys
        import time

        _, art_dir, art, _ = tiny_emulator
        contested = str(tmp_path / "contested")
        Store(contested)  # create + trust the shared root up front
        worker = os.path.join(os.path.dirname(__file__),
                              "_mp_registry_worker.py")
        deadline = str(time.time() + 4.0)
        procs = {
            role: subprocess.Popen(
                [sys.executable, worker, role, contested, str(art_dir),
                 art.content_hash, deadline],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for role in ("churner", "fetcher")
        }
        results = {}
        for role, p in procs.items():
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, (
                f"{role} violated the registry contract:\n{out}\n{err}"
            )
            results[role] = json.loads(out.strip().splitlines()[-1])
        # the churn was real (entries were corrupted/evicted and
        # republished under the fetcher's feet) AND validated fetches
        # got through it
        assert results["churner"]["published"] >= 2
        assert results["churner"]["evicted"] >= 1
        assert results["fetcher"]["ok"] >= 1
