"""Ensemble-sampler tests: statistical correctness on an analytic target,
mesh-sharded walkers, and the Planck pipeline likelihood (SURVEY §7.7)."""
import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.sampling import (
    make_pipeline_logprob,
    omegas_from_result,
    planck_gaussian_logp,
    run_ensemble,
)

BENCH_OVER = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


class TestStretchMoveOnGaussian:
    def _run(self, mesh=None, W=64, steps=600):
        import jax
        import jax.numpy as jnp

        mean = jnp.array([1.0, -2.0])
        sigma = jnp.array([0.7, 1.3])

        def logp(theta):
            r = (theta - mean) / sigma
            return -0.5 * jnp.sum(r * r)

        key = jax.random.PRNGKey(0)
        init = mean + 0.1 * jax.random.normal(key, (W, 2))
        return run_ensemble(
            jax.random.PRNGKey(1), logp, init, n_steps=steps, mesh=mesh
        ), np.asarray(mean), np.asarray(sigma)

    def test_recovers_gaussian_moments(self):
        run, mean, sigma = self._run()
        # discard burn-in
        samples = np.asarray(run.chain[200:]).reshape(-1, 2)
        assert np.allclose(samples.mean(axis=0), mean, atol=0.08)
        assert np.allclose(samples.std(axis=0), sigma, rtol=0.12)

    def test_acceptance_fraction_sane(self):
        run, *_ = self._run()
        assert 0.2 < float(run.acceptance) < 0.9

    def test_sharded_walkers_match_statistics(self):
        from bdlz_tpu.parallel import make_mesh

        run, mean, sigma = self._run(mesh=make_mesh(shape=(4, 2)))
        samples = np.asarray(run.chain[200:]).reshape(-1, 2)
        assert np.allclose(samples.mean(axis=0), mean, atol=0.08)

    def test_walker_validation(self):
        import jax
        import jax.numpy as jnp

        with pytest.raises(ValueError, match="even"):
            run_ensemble(jax.random.PRNGKey(0), lambda t: 0.0, jnp.zeros((5, 2)), 10)
        with pytest.raises(ValueError, match="walkers"):
            run_ensemble(jax.random.PRNGKey(0), lambda t: 0.0, jnp.zeros((4, 2)), 10)


class TestPlanckLikelihood:
    def test_gaussian_logp_peak(self):
        from bdlz_tpu.constants import PLANCK_OMEGA_B_H2, PLANCK_OMEGA_DM_H2

        assert float(planck_gaussian_logp(PLANCK_OMEGA_B_H2, PLANCK_OMEGA_DM_H2)) == 0.0
        assert float(planck_gaussian_logp(PLANCK_OMEGA_B_H2 * 1.1, PLANCK_OMEGA_DM_H2)) < 0

    def test_pipeline_logprob_finite_and_bounded(self):
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp)
        logp = make_pipeline_logprob(
            base, static, table,
            param_keys=("m_chi_GeV", "P_chi_to_B"),
            bounds={"m_chi_GeV": (0.1, 10.0), "P_chi_to_B": (0.0, 1.0)},
        )
        v = float(logp(jnp.array([0.95, 0.14925839040304145])))
        assert np.isfinite(v)
        assert float(logp(jnp.array([50.0, 0.5]))) == -np.inf  # out of bounds

    def test_pipeline_omegas_at_benchmark(self):
        """At the archived point the predicted ratio is 5.689 (reference
        PDF Eq. 21) — the likelihood machinery must reproduce the same
        densities the CLI prints."""
        import jax.numpy as jnp

        from bdlz_tpu.config import point_params_from_config
        from bdlz_tpu.models.yields_pipeline import point_yields_fast
        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp)
        pp = point_params_from_config(base, base.P_chi_to_B)
        pp = type(pp)(*(jnp.asarray(f) for f in pp))
        res = point_yields_fast(pp, static, table, jnp)
        ob, od = omegas_from_result(res)
        assert float(od / ob) == pytest.approx(5.6889263349, rel=1e-9)

    def test_short_chain_moves_toward_planck(self):
        """A short sampled chain over (m_chi, P) should improve the Planck
        likelihood over its starting ensemble."""
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        logp = make_pipeline_logprob(
            base, static, table,
            param_keys=("m_chi_GeV", "P_chi_to_B"),
            bounds={"m_chi_GeV": (0.05, 20.0), "P_chi_to_B": (1e-4, 1.0)},
            n_y=2000,
        )
        key = jax.random.PRNGKey(7)
        init = jnp.stack(
            [
                10 ** jax.random.uniform(key, (16,), minval=-1.0, maxval=1.0),
                jax.random.uniform(jax.random.PRNGKey(8), (16,), minval=0.01, maxval=0.9),
            ],
            axis=1,
        )
        run = run_ensemble(jax.random.PRNGKey(9), logp, init, n_steps=40)
        assert float(run.logp_chain[-1].max()) > float(run.logp_chain[0].max()) - 1e-9
        assert np.isfinite(np.asarray(run.final.walkers)).all()


class TestLikelihoodRegressions:
    """Regressions for review findings on the likelihood layer."""

    def _base(self):
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        return base, static, table

    def test_m_B_GeV_sampled_in_GeV_not_kg(self):
        """Sampling m_B_GeV must convert to kg exactly like build_grid does:
        logp at the proton mass in GeV must equal logp of the base config
        (whose m_B_kg is the proton mass)."""
        import jax.numpy as jnp

        from bdlz_tpu.constants import M_PROTON_KG, GEV_TO_KG

        base, static, table = self._base()
        logp = make_pipeline_logprob(
            base, static, table, param_keys=("m_B_GeV",), n_y=2000
        )
        ref = make_pipeline_logprob(
            base, static, table, param_keys=("P_chi_to_B",), n_y=2000
        )
        m_p_GeV = M_PROTON_KG / GEV_TO_KG
        got = float(logp(jnp.array([m_p_GeV])))
        want = float(ref(jnp.array([base.P_chi_to_B])))
        assert got == pytest.approx(want, rel=1e-12)

    def test_I_p_rejected_on_tabulated_path(self):
        base, static, table = self._base()
        with pytest.raises(ValueError, match="I_p"):
            make_pipeline_logprob(base, static, table, param_keys=("I_p",))

    def test_mcmc_cli_burn_ge_steps_rejected(self, tmp_path):
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        with pytest.raises(SystemExit, match="burn"):
            mcmc_main([
                "--config", str(cfg), "--param", "m_chi_GeV=0.5:2",
                "--steps", "10", "--burn", "10",
            ])
