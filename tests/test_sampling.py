"""Ensemble-sampler tests: statistical correctness on an analytic target,
mesh-sharded walkers, and the Planck pipeline likelihood (SURVEY §7.7)."""
import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.sampling import (
    make_pipeline_logprob,
    omegas_from_result,
    planck_gaussian_logp,
    run_ensemble,
)

BENCH_OVER = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


class TestStretchMoveOnGaussian:
    def _run(self, mesh=None, W=64, steps=600):
        import jax
        import jax.numpy as jnp

        mean = jnp.array([1.0, -2.0])
        sigma = jnp.array([0.7, 1.3])

        def logp(theta):
            r = (theta - mean) / sigma
            return -0.5 * jnp.sum(r * r)

        key = jax.random.PRNGKey(0)
        init = mean + 0.1 * jax.random.normal(key, (W, 2))
        return run_ensemble(
            jax.random.PRNGKey(1), logp, init, n_steps=steps, mesh=mesh
        ), np.asarray(mean), np.asarray(sigma)

    def test_recovers_gaussian_moments(self):
        run, mean, sigma = self._run()
        # discard burn-in
        samples = np.asarray(run.chain[200:]).reshape(-1, 2)
        assert np.allclose(samples.mean(axis=0), mean, atol=0.08)
        assert np.allclose(samples.std(axis=0), sigma, rtol=0.12)

    def test_acceptance_fraction_sane(self):
        run, *_ = self._run()
        assert 0.2 < float(run.acceptance) < 0.9

    def test_sharded_walkers_match_statistics(self):
        from bdlz_tpu.parallel import make_mesh

        run, mean, sigma = self._run(mesh=make_mesh(shape=(4, 2)))
        samples = np.asarray(run.chain[200:]).reshape(-1, 2)
        assert np.allclose(samples.mean(axis=0), mean, atol=0.08)

    def test_walker_validation(self):
        import jax
        import jax.numpy as jnp

        with pytest.raises(ValueError, match="even"):
            run_ensemble(jax.random.PRNGKey(0), lambda t: 0.0, jnp.zeros((5, 2)), 10)
        with pytest.raises(ValueError, match="walkers"):
            run_ensemble(jax.random.PRNGKey(0), lambda t: 0.0, jnp.zeros((4, 2)), 10)


class TestPlanckLikelihood:
    def test_gaussian_logp_peak(self):
        from bdlz_tpu.constants import PLANCK_OMEGA_B_H2, PLANCK_OMEGA_DM_H2

        assert float(planck_gaussian_logp(PLANCK_OMEGA_B_H2, PLANCK_OMEGA_DM_H2)) == 0.0
        assert float(planck_gaussian_logp(PLANCK_OMEGA_B_H2 * 1.1, PLANCK_OMEGA_DM_H2)) < 0

    def test_pipeline_logprob_finite_and_bounded(self):
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp)
        logp = make_pipeline_logprob(
            base, static, table,
            param_keys=("m_chi_GeV", "P_chi_to_B"),
            bounds={"m_chi_GeV": (0.1, 10.0), "P_chi_to_B": (0.0, 1.0)},
        )
        v = float(logp(jnp.array([0.95, 0.14925839040304145])))
        assert np.isfinite(v)
        assert float(logp(jnp.array([50.0, 0.5]))) == -np.inf  # out of bounds

    def test_pipeline_omegas_at_benchmark(self):
        """At the archived point the predicted ratio is 5.689 (reference
        PDF Eq. 21) — the likelihood machinery must reproduce the same
        densities the CLI prints."""
        import jax.numpy as jnp

        from bdlz_tpu.config import point_params_from_config
        from bdlz_tpu.models.yields_pipeline import point_yields_fast
        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp)
        pp = point_params_from_config(base, base.P_chi_to_B)
        pp = type(pp)(*(jnp.asarray(f) for f in pp))
        res = point_yields_fast(pp, static, table, jnp)
        ob, od = omegas_from_result(res)
        assert float(od / ob) == pytest.approx(5.6889263349, rel=1e-9)

    def test_short_chain_moves_toward_planck(self):
        """A short sampled chain over (m_chi, P) should improve the Planck
        likelihood over its starting ensemble."""
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        logp = make_pipeline_logprob(
            base, static, table,
            param_keys=("m_chi_GeV", "P_chi_to_B"),
            bounds={"m_chi_GeV": (0.05, 20.0), "P_chi_to_B": (1e-4, 1.0)},
            n_y=2000,
        )
        key = jax.random.PRNGKey(7)
        init = jnp.stack(
            [
                10 ** jax.random.uniform(key, (16,), minval=-1.0, maxval=1.0),
                jax.random.uniform(jax.random.PRNGKey(8), (16,), minval=0.01, maxval=0.9),
            ],
            axis=1,
        )
        run = run_ensemble(jax.random.PRNGKey(9), logp, init, n_steps=40)
        assert float(run.logp_chain[-1].max()) > float(run.logp_chain[0].max()) - 1e-9
        assert np.isfinite(np.asarray(run.final.walkers)).all()


class TestLikelihoodRegressions:
    """Regressions for review findings on the likelihood layer."""

    def _base(self):
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        return base, static, table

    def test_m_B_GeV_sampled_in_GeV_not_kg(self):
        """Sampling m_B_GeV must convert to kg exactly like build_grid does:
        logp at the proton mass in GeV must equal logp of the base config
        (whose m_B_kg is the proton mass)."""
        import jax.numpy as jnp

        from bdlz_tpu.constants import M_PROTON_KG, GEV_TO_KG

        base, static, table = self._base()
        logp = make_pipeline_logprob(
            base, static, table, param_keys=("m_B_GeV",), n_y=2000
        )
        ref = make_pipeline_logprob(
            base, static, table, param_keys=("P_chi_to_B",), n_y=2000
        )
        m_p_GeV = M_PROTON_KG / GEV_TO_KG
        got = float(logp(jnp.array([m_p_GeV])))
        want = float(ref(jnp.array([base.P_chi_to_B])))
        assert got == pytest.approx(want, rel=1e-12)

    def test_I_p_rejected_on_tabulated_path(self):
        base, static, table = self._base()
        with pytest.raises(ValueError, match="I_p"):
            make_pipeline_logprob(base, static, table, param_keys=("I_p",))

    def test_mcmc_cli_burn_ge_steps_rejected(self, tmp_path):
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        with pytest.raises(SystemExit, match="burn"):
            mcmc_main([
                "--config", str(cfg), "--param", "m_chi_GeV=0.5:2",
                "--steps", "10", "--burn", "10",
            ])


class TestDiagnostics:
    def test_tau_iid_near_one(self):
        from bdlz_tpu.sampling import integrated_autocorr_time

        rng = np.random.default_rng(0)
        chain = rng.normal(size=(2000, 8, 2))
        tau = integrated_autocorr_time(chain)
        assert tau.shape == (2,)
        assert np.all(tau < 1.5)

    def test_tau_detects_correlation(self):
        """An AR(1) chain with rho=0.9 has tau ~ (1+rho)/(1-rho) = 19."""
        from bdlz_tpu.sampling import integrated_autocorr_time

        rng = np.random.default_rng(1)
        n, W = 20000, 4
        x = np.zeros((n, W, 1))
        eps = rng.normal(size=(n, W, 1))
        for t in range(1, n):
            x[t] = 0.9 * x[t - 1] + eps[t]
        tau = integrated_autocorr_time(x)
        assert tau[0] == pytest.approx(19.0, rel=0.25)

    def test_split_rhat_converged_vs_diverged(self):
        from bdlz_tpu.sampling import split_rhat

        rng = np.random.default_rng(2)
        good = rng.normal(size=(1000, 8, 2))
        assert np.all(split_rhat(good) < 1.01)
        # walkers stuck at different means -> large R-hat
        bad = rng.normal(size=(1000, 8, 1)) + np.arange(8)[None, :, None] * 5.0
        assert split_rhat(bad)[0] > 1.5
        # within-chain drift (first half vs second half) is what SPLIT
        # R-hat exists to catch
        drift = rng.normal(size=(1000, 8, 1))
        drift[500:] += 5.0
        assert split_rhat(drift)[0] > 1.5

    def test_constant_chain_safe(self):
        from bdlz_tpu.sampling import integrated_autocorr_time, split_rhat

        chain = np.ones((100, 4, 1))
        assert np.isfinite(integrated_autocorr_time(chain)).all()
        assert split_rhat(chain)[0] == 1.0


class TestCheckpointResume:
    """Incremental chains (SURVEY §5): interrupt/resume must be bitwise
    identical to the uninterrupted run."""

    def _logp(self):
        import jax.numpy as jnp

        def logp(theta):
            r = (theta - jnp.array([1.0, -2.0])) / jnp.array([0.7, 1.3])
            return -0.5 * jnp.sum(r * r)

        return logp

    def _init(self, W=16):
        import jax

        return 0.1 * np.asarray(jax.random.normal(jax.random.PRNGKey(3), (W, 2)))

    def test_fresh_run_writes_segments(self, tmp_path):
        from bdlz_tpu.sampling import run_ensemble_checkpointed

        out = str(tmp_path / "chain")
        run = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=60, out_dir=out,
            checkpoint_every=20,
        )
        assert run.segments == 3 and run.resumed_segments == 0
        assert run.chain.shape == (60, 16, 2)
        import os

        assert sorted(os.listdir(out)) == [
            "manifest.json", "seg_00000.npz", "seg_00001.npz", "seg_00002.npz",
        ]

    def test_resume_after_kill_is_bitwise_identical(self, tmp_path):
        """Simulate a mid-run kill: keep only the first segment's file and
        manifest entry, rerun, and require the exact uninterrupted chain."""
        import json as _json
        import os

        from bdlz_tpu.sampling import run_ensemble_checkpointed

        out_full = str(tmp_path / "full")
        full = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=60, out_dir=out_full,
            checkpoint_every=20,
        )

        out_kill = str(tmp_path / "killed")
        run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=60, out_dir=out_kill,
            checkpoint_every=20,
        )
        # "kill" after segment 0: drop later segments as if never written
        os.remove(f"{out_kill}/seg_00001.npz")
        os.remove(f"{out_kill}/seg_00002.npz")
        mpath = f"{out_kill}/manifest.json"
        m = _json.load(open(mpath))
        m["done"] = [0]
        _json.dump(m, open(mpath, "w"))

        resumed = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=60, out_dir=out_kill,
            checkpoint_every=20,
        )
        assert resumed.resumed_segments == 1
        np.testing.assert_array_equal(resumed.chain, full.chain)
        np.testing.assert_array_equal(resumed.logp_chain, full.logp_chain)
        assert resumed.acceptance == full.acceptance

    def test_missing_middle_segment_recomputed(self, tmp_path, capsys):
        import os

        from bdlz_tpu.sampling import run_ensemble_checkpointed

        out = str(tmp_path / "chain")
        full = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=60, out_dir=out,
            checkpoint_every=20,
        )
        os.remove(f"{out}/seg_00001.npz")
        resumed = run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=60, out_dir=out,
            checkpoint_every=20,
        )
        assert resumed.resumed_segments == 1  # prefix truncated at the hole
        assert "recomputing" in capsys.readouterr().err
        np.testing.assert_array_equal(resumed.chain, full.chain)

    def test_changed_run_invalidates_manifest(self, tmp_path):
        from bdlz_tpu.sampling import run_ensemble_checkpointed

        out = str(tmp_path / "chain")
        run_ensemble_checkpointed(
            5, self._logp(), self._init(), n_steps=40, out_dir=out,
            checkpoint_every=20,
        )
        r = run_ensemble_checkpointed(
            6, self._logp(), self._init(), n_steps=40, out_dir=out,
            checkpoint_every=20,
        )
        assert r.resumed_segments == 0


def test_mcmc_cli_checkpoint_and_diagnostics(tmp_path, capsys):
    """End-to-end CLI: checkpointed run emits tau/R-hat/n_eff in the summary
    and a rerun resumes every segment."""
    import json as _json

    from bdlz_tpu.mcmc_cli import main as mcmc_main

    cfg = tmp_path / "cfg.json"
    cfg.write_text(_json.dumps(BENCH_OVER))
    argv = [
        "--config", str(cfg),
        "--param", "m_chi_GeV=0.5:2", "--param", "P_chi_to_B=0.01:0.9",
        "--walkers", "16", "--steps", "20", "--burn", "4",
        "--checkpoint-dir", str(tmp_path / "ckpt"), "--checkpoint-every", "10",
    ]
    mcmc_main(argv)
    s1 = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert s1["resumed_segments"] == 0
    assert set(s1["tau_int"]) == {"m_chi_GeV", "P_chi_to_B"}
    assert set(s1["split_rhat"]) == {"m_chi_GeV", "P_chi_to_B"}
    assert "tau_reliable" in s1

    mcmc_main(argv)
    s2 = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert s2["resumed_segments"] == 2
    assert s2["posterior_mean"] == s1["posterior_mean"]


class TestCheckpointIdentity:
    def test_changed_likelihood_identity_invalidates_manifest(self, tmp_path):
        """Segments are samples of a specific posterior: a changed logp
        fingerprint must force a fresh chain, never splice (review
        regression)."""
        import jax.numpy as jnp

        from bdlz_tpu.sampling import run_ensemble_checkpointed

        import jax

        init = 0.1 * np.asarray(jax.random.normal(jax.random.PRNGKey(3), (16, 2)))
        out = str(tmp_path / "chain")

        def logp_a(theta):
            return -0.5 * jnp.sum(theta * theta)

        def logp_b(theta):
            return -0.5 * jnp.sum((theta - 3.0) ** 2)

        run_ensemble_checkpointed(
            5, logp_a, init, n_steps=40, out_dir=out, checkpoint_every=20,
            identity={"config": "A"},
        )
        r = run_ensemble_checkpointed(
            5, logp_b, init, n_steps=40, out_dir=out, checkpoint_every=20,
            identity={"config": "B"},
        )
        assert r.resumed_segments == 0


def test_mcmc_cli_short_chain_still_summarizes(tmp_path, capsys):
    """steps - burn < 4 must yield a summary with null split_rhat, not a
    traceback after the sampling already ran (review regression)."""
    import json as _json

    from bdlz_tpu.mcmc_cli import main as mcmc_main

    cfg = tmp_path / "cfg.json"
    cfg.write_text(_json.dumps(BENCH_OVER))
    mcmc_main([
        "--config", str(cfg), "--param", "m_chi_GeV=0.5:2",
        "--walkers", "16", "--steps", "5", "--burn", "3",
    ])
    s = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert s["split_rhat"]["m_chi_GeV"] is None
    assert np.isfinite(s["map_logp"])


class TestLZTiedLikelihood:
    def test_lz_lambda1_ties_P_to_wall_speed(self):
        """Sampling v_w with lz_lambda1 must equal sampling P explicitly at
        P(v_w) = 1 - exp(-2 pi lam1 / v_w)."""
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        lam1 = 0.004
        logp_vw = make_pipeline_logprob(
            base, static, table, param_keys=("v_w",), n_y=2000,
            lz_lambda1=lam1,
        )
        logp_P = make_pipeline_logprob(
            base, static, table, param_keys=("v_w", "P_chi_to_B"), n_y=2000,
        )
        for vw in (0.1, 0.3, 0.6):
            P = 1.0 - np.exp(-2 * np.pi * lam1 / vw)
            got = float(logp_vw(jnp.array([vw])))
            want = float(logp_P(jnp.array([vw, P])))
            assert got == pytest.approx(want, rel=1e-12), vw

    def test_lz_lambda1_conflicts_with_sampled_P(self):
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        with pytest.raises(ValueError, match="P_chi_to_B"):
            make_pipeline_logprob(
                base, static, table, param_keys=("P_chi_to_B",),
                lz_lambda1=0.01,
            )


class TestLZTableLikelihood:
    """The coherent/momentum estimators become samplable through a P(v_w)
    interpolation table evaluated inside the jitted logp (they are
    host-side per-point computations with no closed form in v_w)."""

    def _profile(self):
        from bdlz_tpu.lz.profile import BounceProfile

        xi = np.linspace(-2.0, 2.0, 201)
        return BounceProfile(xi=xi, delta=2.0 * xi, mix=np.full_like(xi, 0.3))

    def test_coherent_table_ties_P_to_wall_speed(self):
        """logp sampling v_w with the coherent table must equal logp with P
        pinned explicitly at the host-side coherent kernel's value, up to
        the table's interpolation error."""
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_table, probabilities_for_points
        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        prof = self._profile()
        ptab = make_P_of_vw_table(prof, "coherent", 0.2, 0.9, n=1024, xp=jnp)
        logp_vw = make_pipeline_logprob(
            base, static, table, param_keys=("v_w",), n_y=2000,
            lz_P_table=ptab,
        )
        logp_P = make_pipeline_logprob(
            base, static, table, param_keys=("v_w", "P_chi_to_B"), n_y=2000,
        )
        for vw in (0.25, 0.5, 0.85):
            P_host = float(probabilities_for_points(prof, np.array([vw]),
                                                    method="coherent")[0])
            got = float(logp_vw(jnp.array([vw])))
            want = float(logp_P(jnp.array([vw, P_host])))
            # logp is smooth in P; 1e-8 table error -> ~1e-7 logp shift
            assert got == pytest.approx(want, rel=1e-6, abs=1e-6), vw

    def test_table_conflicts(self):
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_table
        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        ptab = make_P_of_vw_table(self._profile(), "coherent", 0.2, 0.9, n=64,
                                  xp=jnp)
        with pytest.raises(ValueError, match="P_chi_to_B"):
            make_pipeline_logprob(
                base, static, table, param_keys=("P_chi_to_B",),
                lz_P_table=ptab,
            )
        with pytest.raises(ValueError, match="at most one"):
            make_pipeline_logprob(
                base, static, table, param_keys=("v_w",),
                lz_lambda1=0.01, lz_P_table=ptab,
            )

    def test_mcmc_cli_coherent_end_to_end(self, tmp_path, capsys):
        """`mcmc_cli --lz-profile --lz-method coherent` runs end to end."""
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        prof = self._profile()
        csv = tmp_path / "profile.csv"
        csv.write_text(
            "xi,delta,m_mix\n"
            + "\n".join(f"{x},{d},{m}" for x, d, m in
                        zip(prof.xi, prof.delta, prof.mix))
            + "\n"
        )
        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        mcmc_main([
            "--config", str(cfg), "--param", "v_w=0.2:0.9",
            "--walkers", "16", "--steps", "6", "--burn", "2",
            "--lz-profile", str(csv), "--lz-method", "coherent",
            "--lz-table-n", "256",
        ])
        s = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert s["lz"]["method"] == "coherent"
        assert np.isfinite(s["map_logp"])

    def test_gamma_table_2d_matches_host_kernel(self):
        """P(v_w, Γ) bicubic interpolation vs the host dephased kernel."""
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.lz.kernel import dephased_probability
        from bdlz_tpu.lz.sweep_bridge import (
            eval_P_table_2d,
            make_P_of_vw_gamma_table,
        )

        prof = self._profile()
        tab = make_P_of_vw_gamma_table(
            prof, 0.2, 0.9, 0.0, 1.0, n_v=512, n_g=33, xp=jnp
        )
        rng = np.random.default_rng(9)
        vs = rng.uniform(0.2, 0.9, 12)
        gs = rng.uniform(0.0, 1.0, 12)
        got = np.asarray(jax.vmap(
            lambda v, g: eval_P_table_2d(v, g, tab, jnp)
        )(jnp.asarray(vs), jnp.asarray(gs)))
        ref = np.array([
            dephased_probability(prof, float(v), float(g))
            for v, g in zip(vs, gs)
        ])
        assert np.abs(got - ref).max() < 1e-6

    def test_sampled_gamma_matches_pinned_rate_table(self):
        """logp sampling (v_w, lz_gamma_phi) with the 2-D table must match
        logp with the 1-D dephased table pinned at that rate, up to the
        tables' interpolation error."""
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import (
            make_P_of_vw_gamma_table,
            make_P_of_vw_table,
        )
        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        prof = self._profile()
        # gamma node value -> the 2-D interpolation in gamma is exact there
        gam = 0.25
        tab2 = make_P_of_vw_gamma_table(
            prof, 0.2, 0.9, 0.0, 1.0, n_v=1024, n_g=17, xp=jnp
        )
        tab1 = make_P_of_vw_table(
            prof, "dephased", 0.2, 0.9, n=1024, gamma_phi=gam, xp=jnp
        )
        logp_2d = make_pipeline_logprob(
            base, static, table, param_keys=("v_w", "lz_gamma_phi"),
            n_y=2000, lz_P_table2d=tab2,
        )
        logp_1d = make_pipeline_logprob(
            base, static, table, param_keys=("v_w",), n_y=2000,
            lz_P_table=tab1,
        )
        for vw in (0.25, 0.5, 0.85):
            got = float(logp_2d(jnp.array([vw, gam])))
            want = float(logp_1d(jnp.array([vw])))
            assert got == pytest.approx(want, rel=1e-6, abs=1e-6), vw

    def test_gamma_table_2d_clamps_to_domain(self):
        """Queries outside the (v, Γ) table domain clamp to the edges on
        both axes, and every result stays a probability."""
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import (
            eval_P_table_2d,
            make_P_of_vw_gamma_table,
        )

        tab = make_P_of_vw_gamma_table(
            self._profile(), 0.3, 0.8, 0.1, 1.0, n_v=64, n_g=9, xp=jnp
        )
        corners = [(0.3, 0.1), (0.8, 0.1), (0.3, 1.0), (0.8, 1.0)]
        outside = [(0.05, 0.0), (0.99, 0.0), (0.05, 5.0), (0.99, 5.0)]
        for (vi, gi), (vo, go) in zip(corners, outside):
            pin = float(eval_P_table_2d(
                jnp.asarray(vi), jnp.asarray(gi), tab, jnp))
            pout = float(eval_P_table_2d(
                jnp.asarray(vo), jnp.asarray(go), tab, jnp))
            assert pout == pytest.approx(pin, rel=1e-12), (vi, gi)
            assert 0.0 <= pout <= 1.0

    def test_gamma_table_conflicts(self):
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_gamma_table
        from bdlz_tpu.ops.kjma_table import make_f_table

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        tab2 = make_P_of_vw_gamma_table(
            self._profile(), 0.2, 0.9, 0.0, 1.0, n_v=64, n_g=9, xp=jnp
        )
        # gamma key without the 2-D table
        with pytest.raises(ValueError, match="lz_P_table2d"):
            make_pipeline_logprob(
                base, static, table, param_keys=("v_w", "lz_gamma_phi"),
            )
        # 2-D table without the gamma key
        with pytest.raises(ValueError, match="lz_gamma_phi"):
            make_pipeline_logprob(
                base, static, table, param_keys=("v_w",), lz_P_table2d=tab2,
            )

    def test_mcmc_cli_sampled_gamma_end_to_end(self, tmp_path, capsys):
        """`--param lz_gamma_phi=... --lz-method dephased` runs end to end."""
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        prof = self._profile()
        csv = tmp_path / "profile.csv"
        csv.write_text(
            "xi,delta,m_mix\n"
            + "\n".join(f"{x},{d},{m}" for x, d, m in
                        zip(prof.xi, prof.delta, prof.mix))
            + "\n"
        )
        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        mcmc_main([
            "--config", str(cfg), "--param", "v_w=0.2:0.9",
            "--param", "lz_gamma_phi=0.0:1.0",
            "--walkers", "16", "--steps", "6", "--burn", "2",
            "--lz-profile", str(csv), "--lz-method", "dephased",
            "--lz-table-n", "128",
        ])
        s = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert s["lz"]["method"] == "dephased"
        assert "lz_gamma_phi" in s["posterior_mean"]
        assert np.isfinite(s["map_logp"])

    def test_mcmc_cli_rejects_sampled_P_with_profile(self, tmp_path):
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        prof = self._profile()
        csv = tmp_path / "profile.csv"
        csv.write_text(
            "xi,delta,m_mix\n"
            + "\n".join(f"{x},{d},{m}" for x, d, m in
                        zip(prof.xi, prof.delta, prof.mix))
            + "\n"
        )
        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        with pytest.raises(SystemExit, match="v_w"):
            mcmc_main([
                "--config", str(cfg), "--param", "P_chi_to_B=0.01:0.9",
                "--walkers", "16", "--steps", "6", "--burn", "2",
                "--lz-profile", str(csv), "--lz-method", "coherent",
            ])

    def test_mcmc_cli_gamma_sampling_validation(self, tmp_path):
        """Sampled lz_gamma_phi: requires dephased, a sampled v_w, and no
        pinned --lz-gamma-phi flag."""
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        prof = self._profile()
        csv = tmp_path / "profile.csv"
        csv.write_text(
            "xi,delta,m_mix\n"
            + "\n".join(f"{x},{d},{m}" for x, d, m in
                        zip(prof.xi, prof.delta, prof.mix))
            + "\n"
        )
        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        common = ["--config", str(cfg), "--walkers", "16", "--steps", "6",
                  "--burn", "2", "--lz-profile", str(csv)]
        with pytest.raises(SystemExit, match="dephased"):
            mcmc_main(common + ["--param", "v_w=0.2:0.9",
                                "--param", "lz_gamma_phi=0:1",
                                "--lz-method", "coherent"])
        with pytest.raises(SystemExit, match="drop the flag"):
            mcmc_main(common + ["--param", "v_w=0.2:0.9",
                                "--param", "lz_gamma_phi=0:1",
                                "--lz-method", "dephased",
                                "--lz-gamma-phi", "0.5"])
        with pytest.raises(SystemExit, match="v_w"):
            mcmc_main(common + ["--param", "lz_gamma_phi=0:1",
                                "--lz-method", "dephased"])
        with pytest.raises(SystemExit, match="lz-profile"):
            mcmc_main(["--config", str(cfg), "--walkers", "16",
                       "--steps", "6", "--burn", "2",
                       "--param", "v_w=0.2:0.9",
                       "--param", "lz_gamma_phi=0:1"])

    def test_mcmc_cli_pinned_vw_resolves_P_without_table(self, tmp_path, capsys):
        """Not sampling v_w with --lz-profile resolves P once host-side
        (no table build); the chain then samples other parameters."""
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        prof = self._profile()
        csv = tmp_path / "profile.csv"
        csv.write_text(
            "xi,delta,m_mix\n"
            + "\n".join(f"{x},{d},{m}" for x, d, m in
                        zip(prof.xi, prof.delta, prof.mix))
            + "\n"
        )
        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        mcmc_main([
            "--config", str(cfg), "--param", "m_chi_GeV=0.5:2",
            "--walkers", "16", "--steps", "6", "--burn", "2",
            "--lz-profile", str(csv), "--lz-method", "coherent",
        ])
        s = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert s["lz"]["method"] == "coherent"
        assert np.isfinite(s["map_logp"])

    def test_mcmc_cli_lz_flags_require_profile(self, tmp_path):
        import json as _json

        from bdlz_tpu.mcmc_cli import main as mcmc_main

        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps(BENCH_OVER))
        with pytest.raises(SystemExit, match="lz-profile"):
            mcmc_main([
                "--config", str(cfg), "--param", "v_w=0.2:0.9",
                "--walkers", "16", "--steps", "6", "--burn", "2",
                "--lz-method", "coherent",
            ])

    def test_lz_table_logp_under_sharded_walkers(self):
        """The P(v_w)-table likelihood must run under the mesh-sharded
        ensemble (the table constants replicate into the shard_map'd
        logp); posterior stays finite and inside the prior."""
        import jax
        import jax.numpy as jnp

        from bdlz_tpu.lz.sweep_bridge import make_P_of_vw_table
        from bdlz_tpu.ops.kjma_table import make_f_table
        from bdlz_tpu.parallel import make_mesh

        base = config_from_dict(dict(BENCH_OVER))
        static = static_choices_from_config(base)
        table = make_f_table(base.I_p, jnp, n=4096)
        ptab = make_P_of_vw_table(self._profile(), "coherent", 0.2, 0.9,
                                  n=256, xp=jnp)
        logp = make_pipeline_logprob(
            base, static, table, param_keys=("v_w",),
            bounds={"v_w": (0.2, 0.9)}, n_y=2000, lz_P_table=ptab,
        )
        mesh = make_mesh(shape=(4, 2))
        key = jax.random.PRNGKey(11)
        init = jax.random.uniform(key, (16, 1), minval=0.3, maxval=0.8)
        run = run_ensemble(jax.random.PRNGKey(12), logp, init,
                           n_steps=10, mesh=mesh)
        chain = np.asarray(run.chain)
        assert np.isfinite(np.asarray(run.logp_chain)).all()
        assert ((chain >= 0.2) & (chain <= 0.9)).all()
