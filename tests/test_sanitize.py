"""Runtime-sanitizer layer: NaN localization, dtype drift, and the
byte-for-byte no-op contract of the default path.

The headline case is the ISSUE's: a config that drives
``n_chi_equilibrium`` into NaN territory (negative percolation
temperature — ``validate()`` trusts T_p exactly as the reference does,
and ``T**1.5`` at T<0 is NaN in the selected Maxwell-Boltzmann branch)
must (a) raise under ``--sanitize`` with the offending layer boundary
named, and (b) run byte-for-byte unchanged without it — the NaN is
silently where-masked into a garbage DM/B ratio, which is exactly the
failure class the sanitizer exists to catch.
"""
import json
import pathlib

import numpy as np
import pytest

from bdlz_tpu import sanitize
from bdlz_tpu.cli import main as cli_main
from bdlz_tpu.sanitize import SanitizerError

_BASE_CFG = {
    "regime": "nonthermal",
    "m_chi_GeV": 0.95,
    "g_chi": 2,
    "chi_stats": "fermion",
    "sigma_v_chi_GeV_m2": 0.0,
    "T_p_GeV": 100.0,
    "beta_over_H": 100.0,
    "v_w": 0.30,
    "I_p": 0.34,
    "g_star": 106.75,
    "g_star_s": 106.75,
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "Gamma_wash_over_H": 0.0,
    "incident_flux_scale": 1.07e-9,
    "deplete_DM_from_source": False,
    "T_max_over_Tp": 5.0,
    "T_min_over_Tp": 0.001,
    "Y_chi_init": 4.90e-10,
    "n_chi_at_Tp_GeV3": None,
}


@pytest.fixture(autouse=True)
def _sanitizer_off_after():
    yield
    sanitize.disable()


def _write_cfg(tmp_path: pathlib.Path, name: str, **overrides) -> str:
    cfg = dict(_BASE_CFG, **overrides)
    path = tmp_path / name
    path.write_text(json.dumps(cfg, indent=2))
    return str(path)


def _run_cli(monkeypatch, tmp_path, capsys, argv):
    monkeypatch.chdir(tmp_path)
    cli_main(argv)
    out = capsys.readouterr().out
    return out, (tmp_path / "yields_out.json").read_bytes()


def test_nan_config_trips_sanitizer_with_boundary_named(tmp_path, monkeypatch):
    cfg = _write_cfg(tmp_path, "nan.json", T_p_GeV=-100.0)
    monkeypatch.chdir(tmp_path)
    with pytest.raises(SanitizerError) as exc_info:
        cli_main(["--config", cfg, "--sanitize"])
    message = str(exc_info.value)
    assert sanitize.BOUNDARY_THERMO in message  # the offending boundary
    assert "J_chi" in message                   # ... and quantity
    assert exc_info.value.boundary == sanitize.BOUNDARY_THERMO


def test_nan_config_without_flag_is_byte_identical(
    tmp_path, monkeypatch, capsys
):
    """No --sanitize => no behavioral delta, even after an arm/disarm
    cycle has exercised the sanitizer machinery in-process."""
    cfg = _write_cfg(tmp_path, "nan.json", T_p_GeV=-100.0)
    argv = ["--config", cfg]

    out_before, json_before = _run_cli(monkeypatch, tmp_path, capsys, argv)

    sanitize.enable(jax_nans=False)
    sanitize.disable()

    out_after, json_after = _run_cli(monkeypatch, tmp_path, capsys, argv)

    assert out_before == out_after
    assert json_before == json_after
    # and the run really did mask the NaN into the archived outputs —
    # the silent failure class the sanitizer exists for
    assert "=== Results (today) ===" in out_before


def test_clean_config_passes_sanitized_and_matches_default(
    tmp_path, monkeypatch, capsys
):
    cfg = _write_cfg(tmp_path, "ok.json")
    out_plain, json_plain = _run_cli(
        monkeypatch, tmp_path, capsys, ["--config", cfg]
    )
    sanitize.disable()  # the --sanitize run below re-arms it itself
    out_san, json_san = _run_cli(
        monkeypatch, tmp_path, capsys, ["--config", cfg, "--sanitize"]
    )
    assert out_plain == out_san
    assert json_plain == json_san


def test_checkpoint_flags_dtype_drift():
    sanitize.enable(jax_nans=False)
    with pytest.raises(SanitizerError) as exc_info:
        sanitize.checkpoint(
            sanitize.BOUNDARY_SOLVER, Y_B=np.ones(4, dtype=np.float32)
        )
    assert "float32" in str(exc_info.value)
    assert "Y_B" in str(exc_info.value)


def test_checkpoint_is_noop_when_disabled():
    sanitize.disable()
    sanitize.checkpoint(
        sanitize.BOUNDARY_SOLVER,
        Y_B=np.array([np.nan]),
        bad_dtype=np.ones(2, dtype=np.float32),
    )  # must not raise


def test_check_tree_named_tuple_and_allow_nan():
    from bdlz_tpu.models.yields_pipeline import YieldsResult

    sanitize.enable(jax_nans=False)
    good = YieldsResult(*(np.float64(v) for v in (1.0, 2.0, 3.0, 4.0, 5.0)))
    sanitize.check_tree(sanitize.BOUNDARY_SOLVER, good)

    bad = good._replace(Y_B=np.float64(np.nan))
    with pytest.raises(SanitizerError) as exc_info:
        sanitize.check_tree(sanitize.BOUNDARY_SOLVER, bad)
    assert "Y_B" in str(exc_info.value)

    # allow_nan keeps only the dtype contract (sweep outputs carry
    # in-band NaN for failed points by design)
    sanitize.check_tree(sanitize.BOUNDARY_SOLVER, bad, allow_nan=True)
    with pytest.raises(SanitizerError):
        sanitize.check_tree(
            sanitize.BOUNDARY_SOLVER,
            bad._replace(Y_chi=np.ones(2, dtype=np.float32)),
            allow_nan=True,
        )
