"""LZ scenario plane (docs/scenarios.md): the N-level chain and
finite-T thermal-bath modes as first-class config/sweep/emulator/serve
axes.

Pins the acceptance contract: the N = 2 chain reduces to the coherent
two-channel kernel to <= 1e-12 rel, the thermal T -> 0 limit reproduces
the coherent kernel BITWISE (after the shared jit warm-up), the
scenario knobs have ONE identity home (the omit-at-default
``lz_scenario`` key — legacy hashes byte-stable), and both modes
round-trip sweep -> emulator build -> registry publish -> fleet query
with the mode on the artifact identity and every ServeStats row, with
cross-mode artifact/request skew rejected loudly.
"""
import dataclasses
import json

import numpy as np
import pytest

from bdlz_tpu.config import (
    Config,
    ConfigError,
    config_from_dict,
    config_identity_dict,
    static_choices_from_config,
    validate,
)
from bdlz_tpu.lz.profile import BounceProfile
from bdlz_tpu.lz.sweep_bridge import (
    probabilities_for_points,
    profile_fingerprint,
    scenario_identity,
    scenario_probabilities_for_points,
)

XI = np.linspace(-30.0, 30.0, 1001)
PROF = BounceProfile(
    xi=XI, delta=-0.08 * np.tanh(XI / 4.0), mix=np.full_like(XI, 0.02)
)

#: The tiny_emulator-style physics base the scenario boxes build on.
PHYS = {
    "regime": "nonthermal",
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


def _cfg(**kw):
    return validate(config_from_dict({**PHYS, **kw}), backend="tpu")


def _write_profile_csv(path):
    rows = "\n".join(
        f"{x},{d},{m}" for x, d, m in zip(PROF.xi, PROF.delta, PROF.mix)
    )
    path.write_text("xi,delta,m_mix\n" + rows + "\n")
    return str(path)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

class TestChainKernel:
    def test_n2_reduces_to_coherent_within_1e12(self):
        # the acceptance pin: the chain at N = 2 must REDUCE to the
        # existing coherent transfer-matrix kernel, not approximate it
        from bdlz_tpu.lz.chain import chain_probabilities_for_points

        v = np.geomspace(0.02, 0.95, 24)
        P2 = chain_probabilities_for_points(PROF, v, 2)
        P_ref = probabilities_for_points(PROF, v, method="coherent")
        rel = np.max(np.abs(P2 / np.where(P_ref == 0, 1.0, P_ref) - 1.0))
        assert rel <= 1e-12, rel

    def test_three_level_flat_band_matches_analytic(self):
        # Δ ≡ 0, constant mix: the closed-form path-graph spectrum —
        # the midpoint segmentation is exact for a constant Hamiltonian
        from bdlz_tpu.lz.chain import (
            chain_populations_for_speeds,
            uniform_chain_populations_analytic,
        )

        L, m = 6.0, 0.35
        xi = np.linspace(0.0, L, 257)
        flat = BounceProfile(
            xi=xi, delta=np.zeros_like(xi), mix=np.full_like(xi, m)
        )
        for n_levels in (2, 3, 5):
            for v in (0.2, 0.6):
                got = chain_populations_for_speeds(flat, [v], n_levels)[0]
                ref = uniform_chain_populations_analytic(n_levels, m, L, v)
                assert np.abs(got - ref).max() < 1e-10, (n_levels, v)

    def test_populations_unitary_and_clipped(self):
        from bdlz_tpu.lz.chain import chain_populations_for_speeds

        P = chain_populations_for_speeds(PROF, np.linspace(0.1, 0.9, 7), 4)
        assert P.shape == (7, 4)
        assert np.all(P >= 0.0) and np.all(P <= 1.0)
        assert np.abs(P.sum(axis=1) - 1.0).max() < 1e-10

    def test_n_levels_contract(self):
        from bdlz_tpu.lz.chain import chain_populations, validate_n_levels

        with pytest.raises(ValueError, match="lz_n_levels"):
            validate_n_levels(1)
        with pytest.raises(ValueError, match="lz_n_levels"):
            chain_populations(PROF, 0.3, 0)

    def test_chain_mode_audit_passes(self):
        from bdlz_tpu.validation import chain_mode_audit

        audit = chain_mode_audit(PROF, n_levels=3)
        assert audit.ok, audit.reason
        assert audit.n2_vs_coherent <= 1e-12
        assert audit.analytic_flat_band <= 1e-10


class TestThermalKernel:
    def test_rate_formula_and_limits(self):
        from bdlz_tpu.lz.thermal import thermal_gamma_phi

        eta, wc = 0.3, 1.0
        # classic Ohmic 2ηT below the cutoff
        assert thermal_gamma_phi(1e-3 * wc, eta, wc) == pytest.approx(
            2.0 * eta * 1e-3 * wc, rel=1e-12
        )
        # saturation at 2ηω_c above it
        assert thermal_gamma_phi(1e6 * wc, eta, wc) == pytest.approx(
            2.0 * eta * wc, rel=1e-3
        )
        # the cold limit is an exact 0.0, not an underflow artifact
        assert thermal_gamma_phi(0.0, eta, wc) == 0.0
        assert thermal_gamma_phi(-1.0, eta, wc) == 0.0
        # monotone in T
        T = np.geomspace(1e-3, 1e3, 64)
        gam = thermal_gamma_phi(T, eta, wc)
        assert np.all(np.diff(gam) >= 0.0)

    def test_bath_contract(self):
        from bdlz_tpu.lz.thermal import thermal_gamma_phi, validate_bath

        with pytest.raises(ValueError, match="eta"):
            validate_bath(-0.1, 1.0)
        with pytest.raises(ValueError, match="eta"):
            thermal_gamma_phi(1.0, 0.1, -1.0)

    def test_cold_limit_bitwise_after_warmup(self, jit_warmup):
        # acceptance pin: Γ = 0 dispatches through the quaternion path
        # itself, so T -> 0 (and η -> 0) reproduce the coherent kernel
        # bit for bit — the first-jit wobble flushed by the shared
        # fixture first
        from bdlz_tpu.lz.thermal import thermal_probabilities_for_points

        v = np.geomspace(0.05, 0.9, 12)
        jit_warmup(probabilities_for_points, PROF, v, method="coherent")
        P_ref = probabilities_for_points(PROF, v, method="coherent")
        P_cold = thermal_probabilities_for_points(PROF, v, 0.0, 0.3, 1.0)
        P_eta0 = thermal_probabilities_for_points(PROF, v, 100.0, 0.0, 1.0)
        assert np.array_equal(P_cold, P_ref)
        assert np.array_equal(P_eta0, P_ref)

    def test_hot_bath_differs_and_groups_by_rate(self):
        from bdlz_tpu.lz.thermal import thermal_probabilities_for_points

        v = np.full(6, 0.3)
        T = np.array([50.0, 50.0, 100.0, 100.0, 0.0, np.nan])
        P = thermal_probabilities_for_points(PROF, v, T, 0.3, 1.0)
        # same derived rate -> identical P; different rate -> different
        assert P[0] == P[1] and P[2] == P[3]
        assert P[0] != P[2]
        # non-finite T stays NaN, mask-and-report style
        assert np.isnan(P[5]) and np.isfinite(P[:5]).all()

    def test_thermal_mode_audit_passes(self):
        from bdlz_tpu.validation import thermal_mode_audit

        audit = thermal_mode_audit(PROF, 0.3, 1.0, n_sample=8)
        assert audit.ok, audit.reason
        assert audit.cold_limit_bitwise is True
        assert audit.monotonicity_defect <= 0.0


# ---------------------------------------------------------------------------
# config + identity rules
# ---------------------------------------------------------------------------

class TestScenarioConfig:
    def test_valid_modes(self):
        assert _cfg(P_chi_to_B=0.1).lz_mode == "two_channel"
        assert _cfg(lz_mode="chain", lz_n_levels=4).lz_n_levels == 4
        c = _cfg(lz_mode="thermal", lz_bath_eta=0.1, lz_bath_omega_c=1.0)
        assert c.lz_bath_eta == 0.1

    def test_invalid_mode_and_pairings(self):
        with pytest.raises(ConfigError, match="lz_mode"):
            _cfg(lz_mode="dissipative")
        with pytest.raises(ConfigError, match="lz_n_levels"):
            _cfg(lz_mode="chain", lz_n_levels=1)
        with pytest.raises(ConfigError, match="lz_n_levels"):
            _cfg(lz_n_levels=3)  # no effect without chain
        with pytest.raises(ConfigError, match="lz_bath"):
            _cfg(lz_bath_eta=0.1)  # no effect without thermal
        with pytest.raises(ConfigError, match="omega_c"):
            # η > 0 with no cutoff: Γ ≡ 0 — a silently-coherent "bath"
            _cfg(lz_mode="thermal", lz_bath_eta=0.1, lz_bath_omega_c=0.0)

    def test_scenario_fields_excluded_from_config_identity(self):
        # single-home rule: the knobs must NOT enter the shared config
        # payload (they join via the lz_scenario key instead), so legacy
        # refcache/checkpoint identities stay byte-stable
        a = _cfg(P_chi_to_B=0.1)
        b = validate(dataclasses.replace(
            a, lz_mode="chain", lz_n_levels=5
        ), backend="tpu")
        assert config_identity_dict(a) == config_identity_dict(b)

    def test_scenario_fields_excluded_from_static_payload(self):
        from bdlz_tpu.provenance.identity import static_payload

        sa = static_choices_from_config(_cfg(P_chi_to_B=0.1))
        sb = sa._replace(lz_mode="thermal", lz_bath_eta=0.2,
                         lz_bath_omega_c=1.0)
        assert static_payload(sa) == static_payload(sb)

    def test_scenario_identity_single_home(self):
        from bdlz_tpu.parallel.sweep import engine_identity_extra

        s2 = static_choices_from_config(_cfg(P_chi_to_B=0.1))
        assert scenario_identity(s2) is None          # omit-at-default
        sc = static_choices_from_config(_cfg(lz_mode="chain",
                                             lz_n_levels=3))
        assert scenario_identity(sc) == {"mode": "chain", "n_levels": 3}
        st = static_choices_from_config(_cfg(
            lz_mode="thermal", lz_bath_eta=0.1, lz_bath_omega_c=2.0
        ))
        assert scenario_identity(st) == {
            "mode": "thermal", "eta": 0.1, "omega_c": 2.0
        }
        # engine_identity_extra folds it in (and stays empty at default,
        # keeping every pre-existing manifest hash byte-stable)
        assert "lz_scenario" not in engine_identity_extra(s2, "tabulated")
        extra = engine_identity_extra(sc, "tabulated")
        assert extra["lz_scenario"] == {"mode": "chain", "n_levels": 3}

    def test_scenario_dispatch_contract(self):
        s2 = static_choices_from_config(_cfg(P_chi_to_B=0.1))
        with pytest.raises(ValueError, match="two-channel"):
            scenario_probabilities_for_points(PROF, s2, [0.3])
        st = static_choices_from_config(_cfg(
            lz_mode="thermal", lz_bath_eta=0.1, lz_bath_omega_c=1.0
        ))
        with pytest.raises(ValueError, match="T_p_GeV"):
            scenario_probabilities_for_points(PROF, st, [0.3])

    def test_chain_dispatch_matches_kernel(self):
        from bdlz_tpu.lz.chain import chain_probabilities_for_points

        sc = static_choices_from_config(_cfg(lz_mode="chain",
                                             lz_n_levels=3))
        v = np.linspace(0.2, 0.6, 5)
        assert np.array_equal(
            scenario_probabilities_for_points(PROF, sc, v),
            chain_probabilities_for_points(PROF, v, 3),
        )


class TestPTableN:
    def test_table_matches_direct_chain_and_layout(self):
        from bdlz_tpu.lz.chain import chain_populations_for_speeds
        from bdlz_tpu.lz.sweep_bridge import eval_P_table_n, make_P_table_n

        tab = make_P_table_n(PROF, 3, 0.1, 0.9, n=512)
        assert tab.n_levels == 3 and tab.values.shape == (512, 3)
        for v in (0.15, 0.4, 0.82):
            got = np.asarray(eval_P_table_n(v, tab, np))
            ref = chain_populations_for_speeds(PROF, [v], 3)[0]
            assert got.shape == (3,)
            # cubic interpolation on the dense 1/v grid
            assert np.abs(got - ref).max() < 5e-4

    def test_table_contract(self):
        from bdlz_tpu.lz.sweep_bridge import make_P_table_n

        with pytest.raises(ValueError, match="v_lo"):
            make_P_table_n(PROF, 3, 0.9, 0.1)
        with pytest.raises(ValueError, match="nodes"):
            make_P_table_n(PROF, 3, 0.1, 0.9, n=4)


# ---------------------------------------------------------------------------
# sweep integration
# ---------------------------------------------------------------------------

class TestScenarioSweep:
    AXES = {"v_w": np.linspace(0.2, 0.6, 6)}

    def _run(self, cfg, out_dir=None, **kw):
        from bdlz_tpu.parallel import run_sweep

        static = static_choices_from_config(cfg)
        return run_sweep(
            cfg, dict(self.AXES), static, mesh=None, chunk_size=8,
            n_y=400, out_dir=out_dir, keep_outputs=True, **kw
        )

    def test_chain_sweep_runs_and_hashes_apart(self, tmp_path):
        cfg = _cfg(lz_mode="chain", lz_n_levels=3, P_chi_to_B=0.1)
        res3 = self._run(cfg, out_dir=str(tmp_path / "n3"), lz_profile=PROF)
        assert res3.n_failed == 0
        cfg4 = _cfg(lz_mode="chain", lz_n_levels=4, P_chi_to_B=0.1)
        res4 = self._run(cfg4, out_dir=str(tmp_path / "n4"),
                         lz_profile=PROF)
        coh = self._run(
            _cfg(P_chi_to_B=0.1), out_dir=str(tmp_path / "coh"),
            lz_profile=PROF, lz_method="coherent",
        )
        hashes = [
            json.load(open(tmp_path / d / "manifest.json"))["hash"]
            for d in ("n3", "n4", "coh")
        ]
        # the resolved scenario joins the manifest hash: N=3, N=4 and
        # two-channel-coherent sweeps can never splice on resume
        assert len(set(hashes)) == 3
        # and different physics really flowed through the pipeline
        assert not np.array_equal(
            res3.outputs["DM_over_B"], coh.outputs["DM_over_B"]
        )
        assert not np.array_equal(
            res3.outputs["DM_over_B"], res4.outputs["DM_over_B"]
        )

    def test_chain_n2_sweep_tracks_coherent(self, tmp_path):
        # N=2 P agrees with coherent to <=1e-12, so the yields do too
        # (smoothly) — the end-to-end expression of the reduction pin
        cfg = _cfg(lz_mode="chain", lz_n_levels=2, P_chi_to_B=0.1)
        res2 = self._run(cfg, lz_profile=PROF)
        coh = self._run(_cfg(P_chi_to_B=0.1), lz_profile=PROF,
                        lz_method="coherent")
        np.testing.assert_allclose(
            res2.outputs["DM_over_B"], coh.outputs["DM_over_B"],
            rtol=1e-8,
        )

    def test_thermal_sweep_derives_per_point_rate(self):
        from bdlz_tpu.lz.thermal import thermal_probabilities_for_points

        cfg = _cfg(lz_mode="thermal", lz_bath_eta=0.3,
                   lz_bath_omega_c=1.0, P_chi_to_B=0.1, T_p_GeV=80.0)
        res = self._run(cfg, lz_profile=PROF)
        assert res.n_failed == 0
        # the same points through a hotter bath give different yields
        hot = _cfg(lz_mode="thermal", lz_bath_eta=0.6,
                   lz_bath_omega_c=1.0, P_chi_to_B=0.1, T_p_GeV=80.0)
        res_hot = self._run(hot, lz_profile=PROF)
        assert not np.array_equal(
            res.outputs["DM_over_B"], res_hot.outputs["DM_over_B"]
        )
        # and the derivation really is the thermal kernel's
        P_direct = thermal_probabilities_for_points(
            PROF, self.AXES["v_w"], 80.0, 0.3, 1.0
        )
        assert np.isfinite(P_direct).all()

    def test_scenario_requires_profile_and_forbids_gamma(self):
        cfg = _cfg(lz_mode="chain", lz_n_levels=3, P_chi_to_B=0.1)
        with pytest.raises(ValueError, match="bounce"):
            self._run(cfg)
        with pytest.raises(ValueError, match="lz_gamma_phi"):
            self._run(cfg, lz_profile=PROF, lz_method="dephased",
                      lz_gamma_phi=0.5)
        # an explicit non-default estimator is a discarded choice, not
        # a no-op — library callers get the same loud contract the
        # CLIs enforce at the flag layer
        with pytest.raises(ValueError, match="owns the kernel"):
            self._run(cfg, lz_profile=PROF, lz_method="coherent")


# ---------------------------------------------------------------------------
# emulator build -> registry publish -> fleet query (the round-trip)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def chain_emulator(tmp_path_factory, jit_warmup):
    """A tiny chain-mode (N = 3) emulator box over (m_chi, v_w)."""
    from bdlz_tpu.emulator import AxisSpec, build_emulator

    base = _cfg(lz_mode="chain", lz_n_levels=3, P_chi_to_B=0.1)
    spec = {
        "m_chi_GeV": AxisSpec(0.9, 1.1, 2, "log"),
        "v_w": AxisSpec(0.25, 0.35, 3, "lin"),
    }
    out = str(tmp_path_factory.mktemp("chain_emu") / "artifact")
    artifact, report = build_emulator(
        base, spec, rtol=1e-2, n_probe=4, n_holdout=8, max_rounds=1,
        n_y=400, chunk_size=64, out_dir=out, require_converged=False,
        lz_profile=PROF,
    )
    return base, out, artifact, report


@pytest.fixture(scope="module")
def thermal_emulator(tmp_path_factory):
    """A tiny thermal-mode emulator box over (T_p, v_w)."""
    from bdlz_tpu.emulator import AxisSpec, build_emulator

    base = _cfg(lz_mode="thermal", lz_bath_eta=0.3, lz_bath_omega_c=1.0,
                P_chi_to_B=0.1)
    spec = {
        "T_p_GeV": AxisSpec(90.0, 110.0, 2, "log"),
        "v_w": AxisSpec(0.25, 0.35, 2, "lin"),
    }
    out = str(tmp_path_factory.mktemp("thermal_emu") / "artifact")
    artifact, report = build_emulator(
        base, spec, rtol=1e-2, n_probe=4, n_holdout=8, max_rounds=1,
        n_y=400, chunk_size=64, out_dir=out, require_converged=False,
        lz_profile=PROF,
    )
    return base, out, artifact, report


class TestEmulatorScenario:
    def test_identity_carries_scenario_and_profile(self, chain_emulator):
        _, _, artifact, _ = chain_emulator
        ident = dict(artifact.identity)
        assert ident["lz_scenario"] == {"mode": "chain", "n_levels": 3}
        assert ident["lz_profile"] == profile_fingerprint(PROF)

    def test_build_contract_errors(self):
        from bdlz_tpu.emulator import AxisSpec, build_emulator
        from bdlz_tpu.emulator.build import EmulatorBuildError

        base = _cfg(lz_mode="chain", lz_n_levels=3, P_chi_to_B=0.1)
        spec = {"v_w": AxisSpec(0.25, 0.35, 2, "lin")}
        with pytest.raises(EmulatorBuildError, match="bounce"):
            build_emulator(base, spec, max_rounds=0, n_y=400,
                           require_converged=False)
        with pytest.raises(EmulatorBuildError, match="P_chi_to_B"):
            build_emulator(
                base,
                {**spec, "P_chi_to_B": AxisSpec(0.1, 0.2, 2, "lin")},
                max_rounds=0, n_y=400, require_converged=False,
                lz_profile=PROF,
            )
        two = _cfg(P_chi_to_B=0.1)
        with pytest.raises(EmulatorBuildError, match="lz_profile"):
            build_emulator(two, spec, max_rounds=0, n_y=400,
                           require_converged=False, lz_profile=PROF)

    def test_load_round_trip_keeps_scenario(self, chain_emulator):
        from bdlz_tpu.emulator import load_any_artifact

        _, out, artifact, _ = chain_emulator
        loaded = load_any_artifact(out)
        assert dict(loaded.identity)["lz_scenario"] == {
            "mode": "chain", "n_levels": 3
        }
        assert loaded.content_hash == artifact.content_hash

    def test_emulator_values_match_scenario_exact(self, chain_emulator):
        # the surface really was populated from chain-mode physics:
        # re-deriving one grid node exactly through the scenario
        # evaluator reproduces the stored value
        from bdlz_tpu.emulator.build import make_exact_evaluator

        base, _, artifact, _ = chain_emulator
        static = static_choices_from_config(base)
        ev = make_exact_evaluator(
            base, static, n_y=400, impl="tabulated", chunk_size=16,
            lz_profile=PROF,
        )
        i, j = 1, 2
        axes = {
            "m_chi_GeV": np.asarray([artifact.axis_nodes[0][i]]),
            "v_w": np.asarray([artifact.axis_nodes[1][j]]),
        }
        got = ev(axes)["DM_over_B"][0]
        # rel 1e-8, not bitwise: the build ran at chunk_size=64 and this
        # evaluator at 16 — different padded chunk shapes shift XLA
        # fusion by ulps (plus the documented ~3e-9 first-jit wobble);
        # a cross-mode value would be off at the 1e-2 level
        assert got == pytest.approx(
            float(artifact.values["DM_over_B"][i, j]), rel=1e-8
        )


class TestRegistryAndFleetRoundTrip:
    def _drain_one(self, fleet, theta, lz_mode=None):
        point = dict(theta)
        if lz_mode is not None:
            point["lz_mode"] = lz_mode
        fut = fleet.submit(fleet.theta_from_mapping(point))
        fleet.run_once(force=True)
        fleet.poll(block=True)
        return fut.result(timeout=5)

    @pytest.mark.parametrize("which", ["chain", "thermal"])
    def test_publish_fetch_fleet_round_trip(
        self, which, chain_emulator, thermal_emulator, tmp_path
    ):
        from bdlz_tpu.provenance import Store, fetch_artifact, publish_artifact
        from bdlz_tpu.serve.fleet import FleetService

        base, _, artifact, _ = (
            chain_emulator if which == "chain" else thermal_emulator
        )
        store = Store(str(tmp_path / "store"))
        h = publish_artifact(store, artifact)
        fetched = fetch_artifact(store, h)
        assert dict(fetched.identity)["lz_scenario"]["mode"] == which

        fleet = FleetService(
            fetched, base, n_replicas=2, max_batch_size=8,
            lz_profile=PROF, error_gate_tol=False, warm=True,
        )
        try:
            assert fleet.lz_mode == which
            assert fleet.expected_identity["lz_scenario"]["mode"] == which
            mid = {
                n: float(np.sqrt(nodes[0] * nodes[-1]))
                for n, nodes in zip(artifact.axis_names,
                                    artifact.axis_nodes)
            }
            # a request STATING the mode is accepted and answered with
            # the mode stamped on the response
            resp = self._drain_one(fleet, mid, lz_mode=which)
            assert np.isfinite(resp.value)
            assert resp.lz_mode == which
            assert resp.artifact_hash == h
            assert resp.fallback_reason is None
            # out-of-domain: the exact fallback derives P from the
            # profile through the scenario evaluator
            ood = dict(mid)
            ood["v_w"] = 0.6
            resp_ood = self._drain_one(fleet, ood)
            assert resp_ood.fallback_reason == "ood"
            assert np.isfinite(resp_ood.value)
            assert resp_ood.lz_mode == which
            # EVERY stats row names the mode (the acceptance pin)
            rows = fleet.stats.as_rows()
            assert rows and all(r["lz_mode"] == which for r in rows)
        finally:
            fleet.close()

    def test_yield_service_rows_carry_mode(self, chain_emulator):
        from bdlz_tpu.serve.service import YieldService

        base, _, artifact, _ = chain_emulator
        svc = YieldService(
            artifact, base, max_batch_size=4, warm=False,
            lz_profile=PROF, error_gate_tol=False,
        )
        assert svc.lz_mode == "chain"
        batcher = svc.make_batcher(clock=lambda: 0.0)
        theta = svc.theta_from_mapping({
            "m_chi_GeV": 1.0, "v_w": 0.3, "lz_mode": "chain",
        })
        fut = batcher.submit(theta)
        batcher.run_once(force=True)
        assert np.isfinite(fut.result(timeout=5))
        rows = svc.stats.as_rows()
        assert rows and all(r["lz_mode"] == "chain" for r in rows)


class TestCrossModeSkewRejection:
    def test_service_rejects_cross_mode_base(self, chain_emulator):
        from bdlz_tpu.emulator.artifact import EmulatorArtifactError
        from bdlz_tpu.serve.service import YieldService

        _, _, artifact, _ = chain_emulator
        two = _cfg(P_chi_to_B=0.1)
        with pytest.raises(EmulatorArtifactError, match="lz_scenario"):
            YieldService(artifact, two, warm=False, lz_profile=PROF)

    def test_service_rejects_wrong_scenario_params(self, chain_emulator):
        from bdlz_tpu.emulator.artifact import EmulatorArtifactError
        from bdlz_tpu.serve.service import YieldService

        _, _, artifact, _ = chain_emulator
        other = _cfg(lz_mode="chain", lz_n_levels=4, P_chi_to_B=0.1)
        with pytest.raises(EmulatorArtifactError, match="lz_scenario"):
            YieldService(artifact, other, warm=False, lz_profile=PROF)

    def test_two_channel_artifact_rejects_scenario_consumer(
        self, tiny_emulator
    ):
        from bdlz_tpu.emulator.artifact import EmulatorArtifactError
        from bdlz_tpu.serve.service import YieldService

        base, _, artifact, _ = tiny_emulator
        chain_base = validate(dataclasses.replace(
            base, lz_mode="chain", lz_n_levels=3
        ), backend="tpu")
        with pytest.raises(EmulatorArtifactError, match="lz_scenario"):
            YieldService(artifact, chain_base, warm=False, lz_profile=PROF)

    def test_request_mode_skew_rejected(self, chain_emulator):
        from bdlz_tpu.serve.service import theta_from_mapping

        _, _, artifact, _ = chain_emulator
        with pytest.raises(ValueError, match="cross-mode"):
            theta_from_mapping(
                artifact,
                {"m_chi_GeV": 1.0, "v_w": 0.3, "lz_mode": "two_channel"},
            )

    def test_profile_contract(self, chain_emulator, tiny_emulator):
        from bdlz_tpu.serve.service import resolve_service_profile

        _, _, chain_art, _ = chain_emulator
        # scenario artifact without a profile: loud
        with pytest.raises(ValueError, match="bounce profile"):
            resolve_service_profile(chain_art, None)
        # wrong profile: fingerprint skew is loud
        other = BounceProfile(
            xi=XI, delta=-0.1 * np.tanh(XI / 4.0),
            mix=np.full_like(XI, 0.02),
        )
        with pytest.raises(ValueError, match="fingerprint"):
            resolve_service_profile(chain_art, other)
        # two-channel artifact with a profile: a caller error, not a
        # no-op
        _, _, two_art, _ = tiny_emulator
        with pytest.raises(ValueError, match="two-channel"):
            resolve_service_profile(two_art, PROF)


# ---------------------------------------------------------------------------
# CLI surface (lz/options.py — the deduped flag helper + scenario flags)
# ---------------------------------------------------------------------------

class TestSharedCliOptions:
    def _args(self, **kw):
        import argparse

        from bdlz_tpu.lz.options import (
            SWEEP_METHODS,
            add_lz_method_flags,
            add_lz_scenario_flags,
        )

        ap = argparse.ArgumentParser()
        add_lz_method_flags(ap, default="local", choices=SWEEP_METHODS,
                            method_help="m")
        add_lz_scenario_flags(ap)
        argv = []
        for k, v in kw.items():
            argv += [f"--{k.replace('_', '-')}", str(v)]
        return ap.parse_args(argv)

    def test_gamma_pairing_preserved(self):
        from bdlz_tpu.lz.options import lz_flags_error

        assert lz_flags_error(self._args()) is None
        err = lz_flags_error(self._args(lz_gamma_phi=0.5),
                             default_method="local")
        assert "dephased" in err
        err = lz_flags_error(self._args(lz_gamma_phi=-1.0))
        assert ">= 0" in err

    def test_scenario_pairings(self):
        from bdlz_tpu.lz.options import lz_flags_error

        ok = self._args(lz_mode="chain", lz_n_levels=3)
        assert lz_flags_error(ok, default_method="local") is None
        err = lz_flags_error(
            self._args(lz_mode="chain", lz_method="coherent"),
            default_method="local",
        )
        assert "owns the kernel" in err
        err = lz_flags_error(
            self._args(lz_mode="thermal", lz_gamma_phi=0.5),
            default_method="local",
        )
        assert "derives its own" in err
        err = lz_flags_error(self._args(lz_n_levels=3),
                             default_method="local")
        assert "--lz-mode chain" in err
        err = lz_flags_error(self._args(lz_bath_eta=0.1),
                             default_method="local")
        assert "--lz-mode thermal" in err

    def test_apply_scenario_flags_overrides_config(self):
        from bdlz_tpu.lz.options import apply_scenario_flags

        cfg = _cfg(P_chi_to_B=0.1)
        out = apply_scenario_flags(
            cfg, self._args(lz_mode="chain", lz_n_levels=4)
        )
        assert out.lz_mode == "chain" and out.lz_n_levels == 4
        # no flags = untouched config object (reference-shaped runs)
        assert apply_scenario_flags(cfg, self._args()) is cfg
        # an invalid combination surfaces as the config's own error
        with pytest.raises(ConfigError):
            apply_scenario_flags(cfg, self._args(lz_mode="thermal",
                                                 lz_bath_eta=0.1))


class TestScenarioCli:
    def test_sweep_cli_chain(self, tmp_path, capsys):
        from bdlz_tpu import sweep_cli

        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps({**PHYS, "P_chi_to_B": 0.1}))
        prof_path = _write_profile_csv(tmp_path / "prof.csv")
        sweep_cli.main([
            "--config", str(cfg_path),
            "--axis", "v_w=lin:0.2:0.5:4",
            "--chunk", "4", "--n-y", "400",
            "--lz-profile", prof_path,
            "--lz-mode", "chain", "--lz-n-levels", "3",
        ])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["lz_mode"] == "chain"
        assert out["n_points"] == 4 and out["n_failed"] == 0

    def test_sweep_cli_scenario_needs_profile(self, tmp_path):
        from bdlz_tpu import sweep_cli

        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(
            {**PHYS, "P_chi_to_B": 0.1, "lz_mode": "chain",
             "lz_n_levels": 3}
        ))
        with pytest.raises(SystemExit, match="bounce"):
            sweep_cli.main([
                "--config", str(cfg_path),
                "--axis", "v_w=lin:0.2:0.5:4",
            ])

    def test_mcmc_cli_thermal_pinned_vw(self, tmp_path, capsys):
        # pinned wall speed: the scenario P resolves host-side and the
        # sampler runs on the pinned config — the cheap scenario path
        from bdlz_tpu import mcmc_cli

        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(
            {**PHYS, "P_chi_to_B": 0.1, "v_w": 0.3, "T_p_GeV": 100.0}
        ))
        prof_path = _write_profile_csv(tmp_path / "prof.csv")
        mcmc_cli.main([
            "--config", str(cfg_path),
            "--param", "m_chi_GeV=0.9:1.1",
            "--walkers", "16", "--steps", "6", "--burn", "2",
            "--lz-profile", prof_path,
            "--lz-mode", "thermal", "--lz-bath-eta", "0.3",
            "--lz-bath-omega-c", "1.0",
        ])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["lz"]["mode"] == "thermal"
        assert out["lz"]["scenario"] == {
            "mode": "thermal", "eta": 0.3, "omega_c": 1.0
        }
        assert "method" not in out["lz"]

    def test_point_cli_rejects_scenario_config(self, tmp_path, capsys):
        # the single-point CLI has no scenario path: a chain/thermal
        # config must refuse loudly, never silently derive P under the
        # two-channel kernel
        from bdlz_tpu import cli

        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(
            {**PHYS, "P_chi_to_B": 0.1, "lz_mode": "chain",
             "lz_n_levels": 4}
        ))
        prof_path = _write_profile_csv(tmp_path / "prof.csv")
        with pytest.raises(SystemExit) as exc:
            cli.main([
                "--config", str(cfg_path),
                "--maybe-compute-P-from-profile", prof_path,
                "--lz-method", "coherent",
            ])
        assert exc.value.code == 2
        assert "two-channel kernel only" in capsys.readouterr().err

    def test_mcmc_cli_scenario_forbids_gamma_sampling(self, tmp_path):
        from bdlz_tpu import mcmc_cli

        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(
            {**PHYS, "P_chi_to_B": 0.1, "v_w": 0.3}
        ))
        prof_path = _write_profile_csv(tmp_path / "prof.csv")
        with pytest.raises(SystemExit, match="lz_gamma_phi"):
            mcmc_cli.main([
                "--config", str(cfg_path),
                "--param", "v_w=0.2:0.4",
                "--param", "lz_gamma_phi=0.0:1.0",
                "--walkers", "16", "--steps", "4", "--burn", "0",
                "--lz-profile", prof_path,
                "--lz-mode", "chain", "--lz-n-levels", "3",
            ])
