"""Replica health plane / circuit breaker / auto-rollback tests
(bdlz_tpu/serve/health.py + the fleet/rollout integration).

Same testability contract as the fleet suite: every breaker decision —
trip, cooldown, half-open probe, re-close — and the rollout observation
window run on a FAKE clock with explicit run_once/poll calls; zero
sleeps, zero background threads.  Injected replica faults come from the
extended FaultPlan (site ``replica_dispatch``, keyed by replica index;
site ``registry_fetch`` for the re-provision path).

The two contracts everything here defends:

* healing is INVISIBLE in the values — a healed/re-answered batch is
  bit-identical to the clean run (every replica runs the same fused
  kernel on the same table bytes);
* disabling the plane (``health_enabled=false``) is byte-identical to
  the pre-health service: same values, same ServeStats schema (the
  zero-overhead pin).
"""
import dataclasses
import json

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.emulator.artifact import EmulatorArtifact, build_identity
from bdlz_tpu.serve import ArtifactRollout, FleetService, ServiceUnavailable
from bdlz_tpu.serve.health import (
    STATE_CLOSED,
    STATE_OPEN,
    BreakerPolicy,
    HealthPlane,
    resolve_health_policy,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


BASE = config_from_dict({
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
})
STATIC = static_choices_from_config(BASE)._replace(quad_panel_gl=False)
AXES = ("m_chi_GeV", "T_p_GeV", "v_w")
NODES = (
    np.linspace(0.9, 1.1, 4),
    np.geomspace(90.0, 110.0, 5),
    np.linspace(0.25, 0.35, 3),
)
LO = np.array([n[0] for n in NODES])
HI = np.array([n[-1] for n in NODES])

#: The pre-health ServeStats schema (PR-8) the zero-overhead pin
#: freezes: with the plane disabled, summary() and as_rows() must carry
#: EXACTLY these keys.
PRE_HEALTH_SUMMARY_KEYS = (
    "batches", "requests", "fallbacks", "fallback_rate",
    "gated_fallbacks", "gated_rate", "mean_batch", "mean_occupancy",
    "max_wait_s", "seconds", "retries", "deadline_kills", "errors",
    "quarantine_rate", "accepted", "admission_rejects", "shed_rate",
    "p50_latency_s", "p99_latency_s", "warmup_seconds",
)
PRE_HEALTH_ROW_KEYS = (
    "batch_index", "size", "occupancy", "wait_s", "n_fallback",
    "seconds", "n_retries", "n_error", "n_gated", "artifact_hash",
    "replica",
    # the scenario plane (docs/scenarios.md) stamps the serving mode on
    # every row for BOTH health states — a schema extension, not health
    # overhead, so it belongs in the frozen baseline
    "lz_mode",
    # the cross-host fabric (docs/serving.md) stamps host identity the
    # same way — trailing-optional (None off-fabric), both health states
    "host_id",
)


def _make_artifact(scale=1.0, base=BASE):
    rng = np.random.default_rng(42)
    vals = np.exp(rng.normal(size=(4, 5, 3))) * scale
    return EmulatorArtifact(
        axis_names=AXES,
        axis_nodes=NODES,
        axis_scales=("log", "log", "lin"),
        values={"DM_over_B": vals},
        identity=build_identity(base, STATIC, 400, "tabulated"),
        manifest={},
    )


def _thetas(n, seed=0):
    return np.random.default_rng(seed).uniform(LO, HI, size=(n, 3))


def _plan(*specs):
    return json.dumps({"faults": list(specs)})


def _fleet(fault_plan=None, clock=None, artifact=None, base=BASE, **kw):
    """A 2-replica round-robin fleet with one-strike breakers and a
    short fake-clock cooldown — the canonical trip/probe test shape
    (round_robin so replica 1 is hit on every second batch)."""
    cfg = dataclasses.replace(
        base,
        fault_plan=fault_plan,
        fault_injection=None if fault_plan else False,
        breaker_window=1,
        breaker_cooldown_s=0.05,
    )
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_wait_s", 0.001)
    kw.setdefault("n_replicas", 2)
    kw.setdefault("routing", "round_robin")
    return FleetService(
        artifact if artifact is not None else _make_artifact(),
        cfg, static=STATIC, clock=clock or FakeClock(), **kw,
    )


def _serve(svc, clock, thetas, batch=4, tick=0.01):
    """Closed-loop pump: submit, tick the fake clock per batch,
    dispatch, resolve.  Returns the per-request values (NaN where the
    future raised) and the raised exceptions."""
    futs = []
    for i, t in enumerate(thetas):
        futs.append(svc.submit(t))
        if (i + 1) % batch == 0:
            clock.advance(tick)
            svc.run_once()
            svc.poll(block=True)
    svc.drain()
    vals = np.full(len(thetas), np.nan)
    errs = []
    for i, f in enumerate(futs):
        try:
            vals[i] = f.result(timeout=0).value
        except Exception as exc:  # noqa: BLE001 — asserted by callers
            errs.append(exc)
    return vals, errs


class TestBreakerUnit:
    def test_policy_resolution_tri_state(self):
        assert resolve_health_policy(False, BASE) is None
        assert resolve_health_policy(
            None, dataclasses.replace(BASE, health_enabled=False)
        ) is None
        # explicit True overrides a config False
        assert resolve_health_policy(
            True, dataclasses.replace(BASE, health_enabled=False)
        ) is not None
        pol = resolve_health_policy(None, dataclasses.replace(
            BASE, breaker_window=3, breaker_threshold=0.25,
            breaker_cooldown_s=2.0, breaker_latency_slo_s=0.75,
        ))
        assert pol == BreakerPolicy(3, 0.25, 2.0, 0.75)

    def test_score_denominator_is_window_length(self):
        """One hiccup in a wide window must NOT trip the breaker: the
        score divides by the window LENGTH, so threshold*window actual
        failures are required."""
        plane = HealthPlane(1, BreakerPolicy(window=4, threshold=0.5))
        plane.record_outcome(0, ok=False, now=0.0)
        assert plane.breakers[0].state == STATE_CLOSED
        plane.record_outcome(0, ok=True, now=0.0)
        plane.record_outcome(0, ok=False, now=0.0)
        assert plane.breakers[0].state == STATE_OPEN  # 2/4 >= 0.5

    def test_probe_scheduling_on_clock(self):
        plane = HealthPlane(2, BreakerPolicy(window=1, cooldown_s=1.0))
        plane.record_outcome(1, ok=False, now=5.0)
        assert plane.breakers[1].state == STATE_OPEN
        assert plane.routable(5.5) == ([0], None)   # cooling down
        assert plane.routable(6.0) == ([0], 1)      # probe due
        plane.probe_started(1, 6.0)
        assert plane.routable(6.0) == ([0], None)   # one probe at a time
        plane.record_outcome(1, ok=True, now=6.5, probe=True)  # probe OK
        assert plane.breakers[1].state == STATE_CLOSED
        assert plane.recoveries_s == [pytest.approx(1.5)]

    def test_non_probe_outcome_never_resolves_half_open(self):
        """Only THE probe batch decides a half-open breaker: an older
        batch (dispatched while the breaker was still closed) resolving
        during the probe window must neither re-open on failure nor
        close on success — its outcome only lands in the window."""
        from bdlz_tpu.serve.health import STATE_HALF_OPEN

        plane = HealthPlane(2, BreakerPolicy(window=2, cooldown_s=1.0))
        plane.record_outcome(1, ok=False, now=0.0)
        assert plane.breakers[1].state == STATE_OPEN
        opens_before = plane.opens
        plane.probe_started(1, 1.0)
        plane.record_outcome(1, ok=False, now=1.1, probe=False)  # old batch
        assert plane.breakers[1].state == STATE_HALF_OPEN
        assert plane.breakers[1].probe_inflight
        assert plane.opens == opens_before      # no spurious re-open
        plane.record_outcome(1, ok=True, now=1.2, probe=False)   # old batch
        assert plane.breakers[1].state == STATE_HALF_OPEN        # not closed
        plane.record_outcome(1, ok=True, now=1.3, probe=True)    # THE probe
        assert plane.breakers[1].state == STATE_CLOSED

    def test_latency_slo_downgrades_ok(self):
        plane = HealthPlane(
            1, BreakerPolicy(window=1, latency_slo_s=0.5)
        )
        plane.record_outcome(0, ok=True, now=0.0, seconds=0.75)
        assert plane.breakers[0].state == STATE_OPEN
        assert plane.events[-1]["cause"] == "slow"


class TestFaultSites:
    """The extended FaultPlan surface (bdlz_tpu/faults.py)."""

    def test_replica_nan_needs_no_point_but_step_nan_does(self):
        from bdlz_tpu.faults import FaultPlan, FaultPlanError

        FaultPlan.from_obj({"faults": [
            {"site": "replica_dispatch", "kind": "nan", "key": 0},
        ]})
        with pytest.raises(FaultPlanError, match="needs a 'point'"):
            FaultPlan.from_obj({"faults": [
                {"site": "step", "kind": "nan", "key": 0},
            ]})

    def test_nan_batch_times_budget(self):
        from bdlz_tpu.faults import FaultPlan

        p = FaultPlan.from_obj({"faults": [
            {"site": "replica_dispatch", "kind": "nan", "key": 1,
             "times": 2},
        ]})
        assert not p.nan_batch("replica_dispatch", 0)  # wrong replica
        assert p.nan_batch("replica_dispatch", 1)
        assert p.nan_batch("replica_dispatch", 1)
        assert not p.nan_batch("replica_dispatch", 1)  # budget spent

    def test_corrupt_bytes_flips_once(self, tmp_path):
        from bdlz_tpu.faults import FaultPlan

        p = FaultPlan.from_obj({"faults": [
            {"site": "registry_fetch", "kind": "corrupt", "key": 0},
        ]})
        f = tmp_path / "payload.bin"
        original = bytes(range(64))
        f.write_bytes(original)
        assert p.corrupt_bytes("registry_fetch", 0, str(f))
        assert f.read_bytes() != original
        assert len(f.read_bytes()) == 64           # flipped, not torn
        damaged = f.read_bytes()
        assert not p.corrupt_bytes("registry_fetch", 0, str(f))
        assert f.read_bytes() == damaged           # fires once

    def test_new_sites_validated(self):
        from bdlz_tpu.faults import FaultPlan, FaultPlanError

        with pytest.raises(FaultPlanError, match="site"):
            FaultPlan.from_obj({"faults": [
                {"site": "replica", "kind": "raise"},
            ]})
        plan = FaultPlan.from_obj({"faults": [
            {"site": "registry_fetch", "kind": "torn", "key": 3},
        ]})
        assert plan.describe() == [
            {"site": "registry_fetch", "kind": "torn", "key": 3},
        ]


class TestBreakerTripsAndHeals:
    def test_dispatch_fault_heals_bit_identical_and_opens_breaker(self):
        """A replica raising at dispatch costs nothing visible: the
        batch is re-routed to a healthy replica and every value is
        bit-identical to the clean run; the sick replica's breaker
        opens and traffic stops routing to it."""
        thetas = _thetas(24)
        clean_clock = FakeClock()
        clean, _ = _serve(_fleet(clock=clean_clock), clean_clock, thetas)
        clock = FakeClock()
        svc = _fleet(
            _plan({"site": "replica_dispatch", "kind": "raise", "key": 1}),
            clock=clock,
        )
        vals, errs = _serve(svc, clock, thetas)
        assert not errs
        assert np.array_equal(vals, clean)  # bitwise, not allclose
        health = svc.stats.extras["health"]
        assert health["states"][1] == STATE_OPEN
        assert health["opens"] >= 1
        # after the trip every batch ran on replica 0 (or -1 never:
        # replica 0 stays healthy, no degraded batches)
        assert health["degraded_batches"] == 0
        rows = svc.stats.as_rows()
        assert all(r["replica"] == 0 for r in rows[2:])

    def test_nan_batch_detected_at_gather_and_reanswered(self):
        """A NaN-emitting replica is caught at gather (finite tables
        cannot produce NaN) and the batch is re-answered on a healthy
        replica, bit-identical."""
        thetas = _thetas(8)
        clean_clock = FakeClock()
        clean, _ = _serve(_fleet(clock=clean_clock), clean_clock, thetas)
        clock = FakeClock()
        svc = _fleet(
            _plan({"site": "replica_dispatch", "kind": "nan", "key": 1,
                   "times": 1}),
            clock=clock,
        )
        vals, errs = _serve(svc, clock, thetas)
        assert not errs
        assert np.array_equal(vals, clean)
        health = svc.stats.extras["health"]
        assert health["healed_batches"] == 1
        assert health["states"][1] == STATE_OPEN
        # the healed batch's stats row names the replica that ANSWERED
        assert svc.stats.as_rows()[1]["replica"] == 0

    def test_transient_fault_full_recovery_cycle(self):
        """transient(times=2) + one NaN probe: trip → cooldown → failed
        probe → cooldown → NaN probe (healed) → cooldown → clean probe
        → breaker RE-CLOSES, recovery time recorded, traffic resumes on
        both replicas — all on the fake clock."""
        thetas = _thetas(160)
        clean_clock = FakeClock()
        clean, _ = _serve(_fleet(clock=clean_clock), clean_clock, thetas)
        clock = FakeClock()
        svc = _fleet(
            _plan(
                {"site": "replica_dispatch", "kind": "transient",
                 "key": 1, "times": 2},
                {"site": "replica_dispatch", "kind": "nan", "key": 1,
                 "times": 1},
            ),
            clock=clock,
        )
        vals, errs = _serve(svc, clock, thetas)
        assert not errs
        assert np.array_equal(vals, clean)
        health = svc.stats.extras["health"]
        assert health["states"] == [STATE_CLOSED, STATE_CLOSED]
        assert health["opens"] == 3          # trip + 2 failed probes
        assert health["closes"] == 1
        assert health["recoveries"] == 1
        assert health["last_recovery_s"] == pytest.approx(0.16, abs=0.03)
        assert health["healed_batches"] == 1  # the NaN probe batch
        # replica 1 serves again after the re-close
        tail = [r["replica"] for r in svc.stats.as_rows()[-6:]]
        assert 1 in tail

    def test_probe_not_scheduled_before_cooldown(self):
        clock = FakeClock()
        svc = _fleet(
            _plan({"site": "replica_dispatch", "kind": "raise", "key": 1}),
            clock=clock,
        )
        thetas = _thetas(16)
        _serve(svc, clock, thetas, tick=0.005)  # 4 ticks < cooldown 0.05
        # breaker opened on the first replica-1 batch and stayed open
        # with NO probe attempted (no half_open transition yet)
        transitions = [
            e for e in svc.health.events if e["to"] == "half_open"
        ]
        assert svc.health.breakers[1].state == STATE_OPEN
        assert not transitions

    def test_slow_replica_latency_slo_trips_breaker(self):
        """An injected slow-replica fault surfaces as batch seconds
        through the clock seam; with a latency SLO configured the
        breaker treats it as a bad outcome."""
        clock = FakeClock()
        cfg = dataclasses.replace(
            BASE,
            fault_plan=_plan({"site": "replica_dispatch", "kind": "slow",
                              "key": 1, "delay_s": 2.0}),
            breaker_window=1, breaker_cooldown_s=99.0,
            breaker_latency_slo_s=0.5,
        )
        svc = FleetService(
            _make_artifact(), cfg, static=STATIC, clock=clock,
            max_batch_size=4, n_replicas=2, routing="round_robin",
            max_wait_s=0.001,
        )
        thetas = _thetas(16)
        vals, errs = _serve(svc, clock, thetas)
        assert not errs and np.isfinite(vals).all()
        assert svc.health.breakers[1].state == STATE_OPEN
        assert svc.health.events[0]["cause"] == "slow"
        # the slow batch's stats row carries the injected seconds
        slow_rows = [r for r in svc.stats.as_rows() if r["seconds"] > 1.0]
        assert slow_rows and all(r["replica"] == 1 for r in slow_rows)

    def test_host_fallback_time_not_charged_to_breaker_slo(
        self, tiny_emulator
    ):
        """OOD/gated requests pay the exact pipeline on the HOST; that
        time must never count against the replica's latency SLO — a
        slow exact path would otherwise open every breaker on a
        perfectly healthy fleet and push it into (even slower)
        degraded mode."""
        from bdlz_tpu.emulator import load_artifact

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        clock = FakeClock()
        cfg = dataclasses.replace(
            base, breaker_window=1, breaker_cooldown_s=99.0,
            breaker_latency_slo_s=0.5,
        )
        svc = FleetService(
            art, cfg, max_batch_size=2, n_replicas=2, clock=clock,
            max_wait_s=0.001,
        )
        inner = svc._fallback

        def slow_exact(axes, retries_box):
            clock.advance(10.0)  # 20x over the SLO, all host-side
            return inner(axes, retries_box)

        svc._fallback = slow_exact
        # one OOD request per batch, two batches -> BOTH replicas pay
        # the slow host fallback once
        thetas = np.array([
            [1.0, 100.0, 0.60],   # v_w outside the tiny box
            [0.95, 95.0, 0.28],
            [1.0, 100.0, 0.65],   # OOD again
            [1.0, 100.0, 0.30],
        ])
        vals, errs = _serve(svc, clock, thetas, batch=2)
        assert not errs and np.isfinite(vals).all()
        assert all(b.state == STATE_CLOSED for b in svc.health.breakers)
        assert not [e for e in svc.health.events if e["cause"] == "slow"]
        # the stats rows still report the TRUE batch seconds (the
        # fallback time stays visible — it just never scores a breaker)
        assert any(r["seconds"] > 0.5 for r in svc.stats.as_rows())


class TestDegradedMode:
    def test_all_open_serves_degraded_exact(self, tiny_emulator):
        """Every breaker open → the batch is answered by the EXACT
        pipeline, loudly: degraded=True, reason "degraded", replica -1
        on the stats row — correct answers, never silent garbage."""
        from bdlz_tpu.emulator import load_artifact
        from bdlz_tpu.serve import YieldService

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        clock = FakeClock()
        cfg = dataclasses.replace(
            base,
            fault_plan=_plan({"site": "replica_dispatch", "kind": "raise"}),
            breaker_window=1, breaker_cooldown_s=99.0,
        )
        svc = FleetService(
            art, cfg, max_batch_size=4, n_replicas=2, clock=clock,
            max_wait_s=0.001,
        )
        thetas = np.array([
            [1.0, 100.0, 0.30],
            [0.95, 95.0, 0.28],
        ])
        futs = [svc.submit(t) for t in thetas]
        clock.advance(0.01)
        svc.run_once()
        got = [f.result(timeout=0) for f in futs]
        assert all(r.degraded for r in got)
        assert all(r.fallback_reason == "degraded" for r in got)
        assert all(r.replica == -1 for r in got)
        # degraded answers come from the EXACT pipeline: they agree
        # with the emulator reference to the artifact's tolerance (the
        # build's rtol is 1e-4), not bit-for-bit
        ref = YieldService(art, base, max_batch_size=4, warm=False)
        want, _ = ref.evaluate(thetas)
        np.testing.assert_allclose(
            [r.value for r in got], want, rtol=1e-3
        )
        health = svc.stats.extras["health"]
        assert health["degraded_batches"] == 1
        assert svc.stats.as_rows()[-1]["replica"] == -1

    def test_all_open_dead_exact_raises_service_unavailable(self):
        """The end of the degradation ladder: all replicas open AND the
        exact path dead → typed ServiceUnavailable per request, never a
        hang, never a silent wrong answer."""
        clock = FakeClock()
        svc = _fleet(
            _plan(
                {"site": "replica_dispatch", "kind": "raise"},
                {"site": "serve_exact", "kind": "raise"},
            ),
            clock=clock,
        )
        futs = [svc.submit(t) for t in _thetas(4)]
        clock.advance(0.01)
        svc.run_once()
        for f in futs:
            with pytest.raises(ServiceUnavailable, match="circuit-open"):
                f.result(timeout=0)
        assert svc.stats.summary()["errors"] == 4


class TestReprovision:
    def _store_with_artifact(self, tmp_path, artifact):
        from bdlz_tpu.provenance import Store, publish_artifact, registry

        registry.reset_fetch_counter()
        store = Store(str(tmp_path / "store"))
        publish_artifact(store, artifact)
        return store

    def test_persistent_sickness_reprovisions_from_registry(self, tmp_path):
        """After the probe budget burns (2 consecutive opens), the sick
        replica is rebuilt from the registry's published copy by
        content hash; the next probe then re-closes the breaker."""
        art = _make_artifact()
        store = self._store_with_artifact(tmp_path, art)
        clock = FakeClock()
        svc = _fleet(
            _plan({"site": "replica_dispatch", "kind": "transient",
                   "key": 1, "times": 3}),
            clock=clock, artifact=art, store=store,
        )
        thetas = _thetas(160)
        clean_clock = FakeClock()
        clean, _ = _serve(
            _fleet(clock=clean_clock, artifact=_make_artifact()),
            clean_clock, thetas,
        )
        vals, errs = _serve(svc, clock, thetas)
        assert not errs
        assert np.array_equal(vals, clean)  # reprovision kept the bits
        health = svc.stats.extras["health"]
        assert health["reprovisions"] == 1
        assert health["reprovision_failures"] == 0
        assert health["states"] == [STATE_CLOSED, STATE_CLOSED]

    def test_registry_fetch_fault_counts_failure_breaker_survives(
        self, tmp_path,
    ):
        """A torn/corrupt registry entry fails the re-provision (and the
        corrupt-entry eviction deletes it); the breaker simply stays on
        its probe cycle and still recovers once the fault clears."""
        art = _make_artifact()
        store = self._store_with_artifact(tmp_path, art)
        clock = FakeClock()
        svc = _fleet(
            _plan(
                {"site": "replica_dispatch", "kind": "transient",
                 "key": 1, "times": 3},
                {"site": "registry_fetch", "kind": "corrupt", "key": 0},
            ),
            clock=clock, artifact=art, store=store,
        )
        vals, errs = _serve(svc, clock, _thetas(160))
        assert not errs and np.isfinite(vals).all()
        health = svc.stats.extras["health"]
        assert health["reprovision_failures"] == 1
        assert health["reprovisions"] == 0
        # recovery did not need the reprovision: the transient cleared
        assert health["states"] == [STATE_CLOSED, STATE_CLOSED]

    def test_fetch_missing_and_garbage_hash(self, tmp_path):
        """Satellite: registry fetch of an absent hash refuses loudly;
        a garbage entry is evicted on fetch."""
        from bdlz_tpu.emulator.artifact import EmulatorArtifactError
        from bdlz_tpu.provenance import Store, fetch_artifact

        store = Store(str(tmp_path / "store"))
        with pytest.raises(EmulatorArtifactError, match="no published"):
            fetch_artifact(store, "0" * 16)
        # a garbage entry: a directory of junk under a hash-like name
        entry = (
            tmp_path / "store" / "emulator_artifact" / "deadbeefdeadbeef"
        )
        entry.mkdir(parents=True)
        (entry / "manifest.json").write_text("{not json")
        with pytest.raises(EmulatorArtifactError):
            fetch_artifact(store, "deadbeefdeadbeef")
        assert not entry.exists()  # corrupt entry evicted


class TestAutoRollback:
    def test_blown_error_budget_rolls_back_within_window(self):
        """The acceptance pin: a staged artifact that blows its error
        budget post-cutover is rolled back automatically inside the
        observation window — the old artifact hash serves again, the
        per-batch hash rows show the N→N+1→N arc, and the reason is
        recorded on stats."""
        art_n = _make_artifact()
        art_n1 = _make_artifact(scale=1.5)
        h_n, h_n1 = art_n.content_hash, art_n1.content_hash
        clock = FakeClock()
        # slow faults on EVERY replica: post-cutover batches breach the
        # observation's latency SLO and charge the budget (pre-cutover
        # rows are outside the window — the observer only scores rows
        # carrying the NEW artifact's hash)
        cfg = dataclasses.replace(
            BASE,
            fault_plan=_plan({"site": "replica_dispatch", "kind": "slow",
                              "delay_s": 2.0}),
            rollback_budget=0.1,
        )
        svc = FleetService(
            art_n, cfg, static=STATIC, max_batch_size=4, n_replicas=2,
            clock=clock, max_wait_s=0.001, health=False,
        )
        ro = ArtifactRollout(svc)
        thetas = _thetas(64, seed=3)

        def pump(i):
            for k in range(4):
                svc.submit(thetas[(4 * i + k) % 64])
            clock.advance(0.01)
            svc.run_once()
            svc.poll(block=True)

        for i in range(3):
            pump(i)
        ro.stage(art_n1)
        ro.cutover(observe_s=1.0, latency_slo_s=0.5)
        assert svc.artifact_hash == h_n1
        pump(3)  # first post-cutover batch blows the budget
        assert svc.artifact_hash == h_n          # rolled back
        assert ro.rolled_back is not None
        assert ro.rolled_back.artifact_hash == h_n1
        assert ro.observation is None            # disarmed
        for i in range(4, 6):
            pump(i)
        rows = [r["artifact_hash"] for r in svc.stats.as_rows()]
        flip_in = rows.index(h_n1)
        assert set(rows[:flip_in]) == {h_n}
        assert rows[flip_in:].count(h_n1) == 1   # exactly one bad batch
        assert set(rows[flip_in + 1:]) == {h_n}  # N serving again
        rb = svc.stats.extras["rollbacks"]
        assert len(rb) == 1
        assert rb[0]["from"] == h_n1 and rb[0]["to"] == h_n
        assert "error budget exceeded" in rb[0]["reason"]
        # the budget charge is a true per-request fraction: an
        # SLO-breaching batch charges its size ONCE (never errors on
        # top), so bad can never exceed requests
        assert rb[0]["bad"] <= rb[0]["requests"] == 4
        # the record rides the stats summary for dashboards
        assert svc.stats.summary()["rollbacks"] == rb

    def test_gated_fallback_budget_also_charges(self, tiny_emulator):
        """The budget counts predicted-error-gated fallbacks too: a
        rollout whose surface gates most traffic to the exact path is
        a failed rollout even when every answer is correct."""
        from bdlz_tpu.emulator import load_artifact

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        clock = FakeClock()
        svc = FleetService(
            art, base, max_batch_size=2, n_replicas=2, clock=clock,
            max_wait_s=0.001, health=False,
        )
        ro = ArtifactRollout(svc)
        # stage a copy whose persisted error estimates are enormous:
        # identical identity/values, but EVERY in-domain query trips
        # the predicted-error gate post-cutover
        bad = art._replace(
            predicted_error=np.full(
                tuple(len(n) - 1 for n in art.axis_nodes), 1.0
            ),
            # drop the stored hash so content_hash recomputes over the
            # tampered error grid (a different build, same identity)
            manifest={
                **{k: v for k, v in art.manifest.items() if k != "hash"},
                "rtol_target": 1e-4, "converged": True,
            },
        )
        assert bad.content_hash != art.content_hash
        ro.stage(bad)
        ro.cutover(observe_s=1.0, budget=0.5)
        for t in ([1.0, 100.0, 0.30], [0.95, 95.0, 0.28]):
            svc.submit(np.asarray(t))
        clock.advance(0.01)
        svc.run_once()
        svc.poll(block=True)
        # both requests were gated → 2/2 bad > 0.5 → rolled back
        assert svc.artifact_hash == art.content_hash
        assert "error budget exceeded" in (
            svc.stats.extras["rollbacks"][0]["reason"]
        )

    def test_clean_window_passes_and_disarms(self):
        art_n, art_n1 = _make_artifact(), _make_artifact(scale=1.5)
        clock = FakeClock()
        svc = FleetService(
            art_n, BASE, static=STATIC, max_batch_size=4, n_replicas=2,
            clock=clock, max_wait_s=0.001, health=False,
        )
        ro = ArtifactRollout(svc)
        ro.stage(art_n1)
        ro.cutover(observe_s=0.05)
        thetas = _thetas(32)
        _serve(svc, clock, thetas)  # clean traffic past the window
        assert svc.artifact_hash == art_n1.content_hash  # stuck
        assert ro.observation is None and svc._observer is None
        obs = svc.stats.extras["rollout_observations"]
        assert obs[0]["passed"] is True and obs[0]["bad"] == 0
        assert "rollbacks" not in svc.stats.extras

    def test_degraded_batches_charge_budget(self, tiny_emulator):
        """A catastrophically bad rollout — every replica raising, all
        breakers open, batches answered degraded through the exact
        path — must blow the budget and roll back: degraded rows carry
        n_error=0/n_gated=0 (the exact pipeline copes), so they charge
        by replica == -1."""
        from bdlz_tpu.emulator import load_artifact

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        clock = FakeClock()
        cfg = dataclasses.replace(
            base,
            fault_plan=_plan({"site": "replica_dispatch", "kind": "raise"}),
            breaker_window=1, breaker_cooldown_s=99.0,
        )
        svc = FleetService(
            art, cfg, max_batch_size=2, n_replicas=2, clock=clock,
            max_wait_s=0.001,
        )
        ro = ArtifactRollout(svc)
        # same axes, doubled values: a different build of the same box
        bad = art._replace(
            values={k: v * 2.0 for k, v in art.values.items()},
            manifest={k: v for k, v in art.manifest.items() if k != "hash"},
        )
        assert bad.content_hash != art.content_hash
        ro.stage(bad)
        ro.cutover(observe_s=5.0, budget=0.5)
        assert svc.artifact_hash == bad.content_hash
        futs = [svc.submit(np.asarray(t))
                for t in ([1.0, 100.0, 0.30], [0.95, 95.0, 0.28])]
        clock.advance(0.01)
        svc.run_once()
        got = [f.result(timeout=0) for f in futs]
        # every replica raised at dispatch -> the batch went out
        # degraded on the NEW hash, which charges 2/2 > 0.5: rollback
        assert all(r.degraded for r in got)
        assert svc.artifact_hash == art.content_hash
        rb = svc.stats.extras["rollbacks"]
        assert len(rb) == 1 and "error budget exceeded" in rb[0]["reason"]
        assert rb[0]["from"] == bad.content_hash

    def test_budget_blow_after_window_elapsed_sticks(self, tiny_emulator):
        """A bad batch resolving long AFTER the observation window
        elapsed must disarm the observation (the rollout already
        stuck) — never revert it retroactively."""
        from bdlz_tpu.emulator import load_artifact

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        clock = FakeClock()
        svc = FleetService(
            art, base, max_batch_size=2, n_replicas=2, clock=clock,
            max_wait_s=0.001, health=False,
        )
        ro = ArtifactRollout(svc)
        bad = art._replace(
            predicted_error=np.full(
                tuple(len(n) - 1 for n in art.axis_nodes), 1.0
            ),
            manifest={
                **{k: v for k, v in art.manifest.items() if k != "hash"},
                "rtol_target": 1e-4, "converged": True,
            },
        )
        ro.stage(bad)
        ro.cutover(observe_s=1.0, budget=0.5)
        clock.advance(10.0)  # the window ends with no traffic at all
        for t in ([1.0, 100.0, 0.30], [0.95, 95.0, 0.28]):
            svc.submit(np.asarray(t))
        clock.advance(0.01)
        svc.run_once()
        svc.poll(block=True)
        # 2/2 requests gated — but hours past the window: it sticks
        assert svc.artifact_hash == bad.content_hash
        assert ro.observation is None and svc._observer is None
        obs = svc.stats.extras["rollout_observations"]
        assert obs[0]["passed"] is True
        assert "rollbacks" not in svc.stats.extras

    def test_cutover_kwargs_range_checked(self):
        """budget/observe_s/latency_slo_s kwargs get the same range
        checks as their validated config twins (budget=0 would roll
        back on the first gated request, budget<0 on a fully CLEAN
        batch) and a refused cutover leaves stage + service untouched."""
        art_n, art_n1 = _make_artifact(), _make_artifact(scale=1.5)
        svc = FleetService(
            art_n, BASE, static=STATIC, max_batch_size=4, n_replicas=2,
            clock=FakeClock(), max_wait_s=0.001, health=False,
        )
        ro = ArtifactRollout(svc)
        ro.stage(art_n1)
        for kw in (
            {"observe_s": 0.0},
            {"observe_s": -1.0},
            {"observe_s": 1.0, "budget": 0.0},
            {"observe_s": 1.0, "budget": -0.5},
            {"observe_s": 1.0, "budget": 1.5},
            {"observe_s": 1.0, "latency_slo_s": 0.0},
        ):
            with pytest.raises(ValueError):
                ro.cutover(**kw)
            assert svc.artifact_hash == art_n.content_hash  # untouched
        ro.cutover(observe_s=1.0, budget=0.5)  # stage survived refusals
        assert svc.artifact_hash == art_n1.content_hash

    def test_auto_rollback_without_previous_refuses(self):
        from bdlz_tpu.serve import RolloutError

        svc = _fleet()
        ro = ArtifactRollout(svc)
        with pytest.raises(RolloutError, match="no previous"):
            ro.auto_rollback("manual")


class TestCloseAndShutdown:
    def test_close_fails_pending_and_inflight_futures(self):
        """Satellite pin (fake clock): close() fails every pending AND
        in-flight future with the typed ServiceUnavailable instead of
        leaving them hanging into interpreter exit."""
        clock = FakeClock()
        svc = _fleet(clock=clock)
        inflight = [svc.submit(t) for t in _thetas(4)]
        clock.advance(0.01)
        svc.run_once()                        # dispatched, unresolved
        pending = [svc.submit(t) for t in _thetas(2, seed=1)]
        assert svc.in_flight() == 1 and svc.pending() == 2
        n = svc.close()
        assert n == 6
        for f in inflight + pending:
            with pytest.raises(ServiceUnavailable):
                f.result(timeout=0)
        # post-close: typed synchronous refusal, idempotent close
        with pytest.raises(ServiceUnavailable, match="closed"):
            svc.submit(_thetas(1)[0])
        assert svc.close() == 0
        # the replicas' in-flight slots were released with the gather
        assert all(r.in_flight == 0 for r in svc.replica_set.replicas)


class TestZeroOverheadPin:
    def test_disabled_schema_and_values_byte_identical(self):
        """The acceptance pin: with health_enabled off, behavior and
        the ServeStats schema are byte-identical to the pre-health
        (PR-8) service — no plane, no extras, the frozen key sets."""
        thetas = _thetas(24)
        clock_off = FakeClock()
        svc_off = _fleet(clock=clock_off, health=False)
        vals_off, errs = _serve(svc_off, clock_off, thetas)
        assert not errs
        assert svc_off.health is None
        s = svc_off.stats.summary()
        assert tuple(s.keys()) == PRE_HEALTH_SUMMARY_KEYS
        rows = svc_off.stats.as_rows()
        assert all(tuple(r.keys()) == PRE_HEALTH_ROW_KEYS for r in rows)
        json.dumps(s, allow_nan=False)
        # same trace with the plane ON (no faults): same bits out
        clock_on = FakeClock()
        svc_on = _fleet(clock=clock_on)
        vals_on, _ = _serve(svc_on, clock_on, thetas)
        assert np.array_equal(vals_off, vals_on)
        # the plane's summary rides ONLY the enabled service
        assert "health" in svc_on.stats.summary()
        assert "health" not in s

    def test_config_knobs_excluded_from_identity(self):
        from bdlz_tpu.config import config_identity_dict

        tuned = dataclasses.replace(
            BASE, health_enabled=True, breaker_window=3,
            breaker_threshold=0.9, breaker_cooldown_s=7.0,
            breaker_latency_slo_s=0.2, rollback_budget=0.01,
        )
        assert config_identity_dict(tuned) == config_identity_dict(BASE)
