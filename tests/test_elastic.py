"""Elastic work-stealing sweep scheduler (parallel/scheduler.py + worker.py).

The contract under test: an unreliable fleet — worker crashes, expired
leases, torn store reads — produces output fields BITWISE equal to
single-host ``run_sweep(mesh=None)``.  Protocol units (lease plane,
publish-then-commit, coordinator election, cross-process backoff
determinism) run without touching the engine; the engine-driving tests
share one small grid and module-scoped results so tier-1 pays a handful
of jit compiles, not one per assertion.

Real-subprocess churn tests (external ``sweep_cli --elastic worker``
fleets) live in ``tests/test_elastic_mp.py`` under ``@pytest.mark.slow``
and are excluded from tier-1; the fast lease-expiry and single-process
churn coverage here is the tier-1 face of the same protocol.
"""
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.faults import FaultPlan
from bdlz_tpu.parallel.scheduler import (
    CommitMismatchError,
    ElasticError,
    LeasePlane,
    ManualClock,
    WallClock,
    ensure_job_record,
    plan_elastic_sweep,
    publish_chunk,
    run_sweep_elastic,
)
from bdlz_tpu.parallel.sweep import run_sweep
from bdlz_tpu.parallel.worker import run_worker_loop
from bdlz_tpu.provenance import Store, lease_entry_name, read_lease
from bdlz_tpu.utils.retry import RetryPolicy

AXES = {"m_chi_GeV": [0.5, 1.0, 2.0], "T_p_GeV": [80.0, 150.0]}
CHUNK = 2
N_Y = 200


def _retry():
    return RetryPolicy(max_attempts=2, backoff_s=0.0, sleep=lambda s: None)


@pytest.fixture(scope="module")
def base_cfg():
    return config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })


@pytest.fixture(scope="module")
def static(base_cfg):
    return static_choices_from_config(base_cfg)


@pytest.fixture(scope="module")
def plan(base_cfg, static):
    return plan_elastic_sweep(
        base_cfg, AXES, static, chunk_size=CHUNK, n_y=N_Y, retry=_retry(),
    )


@pytest.fixture(scope="module")
def serial(base_cfg, static):
    """Single-host baseline every elastic run must match bitwise."""
    return run_sweep(
        base_cfg, AXES, static, mesh=None, chunk_size=CHUNK, n_y=N_Y,
        retry=_retry(),
    )


@pytest.fixture(scope="module")
def elastic_clean(base_cfg, static, tmp_path_factory):
    """One clean elastic run, shared: (result, on_chunk events, store)."""
    store = Store(str(tmp_path_factory.mktemp("elastic_clean")))
    events = []
    res = run_sweep_elastic(
        base_cfg, AXES, static, store=store, chunk_size=CHUNK, n_y=N_Y,
        retry=_retry(), n_workers=2,
        on_chunk=lambda ci, lo, hi, ent: events.append(
            (ci, lo, hi, {k: np.array(v) for k, v in ent.items()})
        ),
    )
    return res, events, store


def assert_bitwise(a, b, label):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype and a.shape == b.shape, label
    assert a.tobytes() == b.tobytes(), (
        f"{label}: elastic result drifted from the serial engine "
        f"(max abs diff {np.nanmax(np.abs(a - b))!r})"
    )


# ---- plan / job record --------------------------------------------------


class TestPlan:
    def test_plan_is_deterministic(self, base_cfg, static, plan):
        again = plan_elastic_sweep(
            base_cfg, AXES, static, chunk_size=CHUNK, n_y=N_Y,
            retry=_retry(),
        )
        assert again.job == plan.job
        assert again.n_total == plan.n_total == 6
        assert again.n_chunks == plan.n_chunks == 3
        assert [again.chunk_bounds(i) for i in range(3)] == [
            plan.chunk_bounds(i) for i in range(3)
        ] == [(0, 2), (2, 4), (4, 6)]
        assert [again.entry_name(i) for i in range(3)] == [
            plan.entry_name(i) for i in range(3)
        ]

    def test_identity_knobs_join_the_job(self, base_cfg, static, plan):
        other = plan_elastic_sweep(
            base_cfg, AXES, static, chunk_size=CHUNK, n_y=N_Y + 40,
            retry=_retry(),
        )
        assert other.job != plan.job

    def test_chunk_size_drift_is_caught_by_the_record(
        self, base_cfg, static, plan, tmp_path
    ):
        # chunking is OPERATIONAL, not result identity: it shares the
        # job hash — so the record, not the namespace, must catch it
        store = Store(str(tmp_path / "store"))
        ensure_job_record(store, plan)
        other = plan_elastic_sweep(
            base_cfg, AXES, static, chunk_size=3, n_y=N_Y, retry=_retry(),
        )
        assert other.job == plan.job
        with pytest.raises(ElasticError, match="does not match"):
            ensure_job_record(store, other)

    def test_job_record_round_trip_and_drift(self, plan, tmp_path):
        store = Store(str(tmp_path / "store"))
        first = ensure_job_record(store, plan)
        assert first == plan.job_record()
        # identical re-derivation cross-validates cleanly
        assert ensure_job_record(store, plan) == first
        # a drifted record (a role launched with different inputs) is
        # a LOUD error, never a silent mixed-spec fold
        bad = dict(plan.job_record())
        bad["chunk_size"] = int(bad.get("chunk_size", 0)) + 1
        store.put_json(f"elastic/{plan.job}.json", bad)
        with pytest.raises(ElasticError, match="does not match"):
            ensure_job_record(store, plan)

    def test_torn_job_record_is_rewritten(self, plan, tmp_path):
        store = Store(str(tmp_path / "store"))
        ensure_job_record(store, plan)
        path = store.path_for(f"elastic/{plan.job}.json")
        with open(path, "w", encoding="utf-8") as f:
            f.write('{"schema": 1, "job":')  # torn mid-write
        assert ensure_job_record(store, plan) == plan.job_record()


# ---- lease plane (ManualClock, no engine) -------------------------------


class TestLeasePlane:
    def _plane(self, tmp_path, **kw):
        clock = ManualClock()
        store = Store(str(tmp_path / "leases"))
        kw.setdefault("ttl_s", 10.0)
        kw.setdefault("quarantine_after", 2)
        plane = LeasePlane(store, "job0", 3, clock=clock, **kw)
        return plane, clock, store

    def test_claim_is_exclusive_while_live(self, tmp_path):
        plane, clock, _ = self._plane(tmp_path)
        assert plane.claim(0, "w0")
        assert not plane.claim(0, "w1")
        assert plane.state(0) == "leased"
        assert plane.state(1) == "queued"  # untouched chunks are free

    def test_heartbeat_extends_the_lease(self, tmp_path):
        plane, clock, _ = self._plane(tmp_path)
        assert plane.claim(0, "w0")
        clock.advance(8.0)
        assert plane.heartbeat(0, "w0")
        clock.advance(8.0)  # 16s since claim, 8s since heartbeat
        assert not plane.claim(0, "w1")  # still live
        assert not plane.heartbeat(0, "w1")  # non-holders cannot extend

    def test_expired_lease_is_stolen_with_failure_credit(self, tmp_path):
        plane, clock, _ = self._plane(tmp_path)
        assert plane.claim(0, "w0")
        clock.advance(11.0)
        assert plane.claim(0, "w1")  # steal
        rec = plane.read(0)
        assert rec["worker"] == "w1"
        assert rec["failures"] == ["w0"]
        assert rec["generation"] == 1
        # the stale holder's heartbeat finds its lease gone
        assert not plane.heartbeat(0, "w0")

    def test_done_and_quarantined_are_terminal(self, tmp_path):
        plane, clock, _ = self._plane(tmp_path)
        assert plane.claim(0, "w0")
        plane.complete(0, "w0")
        assert plane.state(0) == "done"
        assert not plane.claim(0, "w1")
        clock.advance(100.0)
        assert not plane.claim(0, "w1")  # done never expires

    def test_distinct_failures_quarantine_fleet_wide(self, tmp_path):
        plane, clock, _ = self._plane(tmp_path)  # quarantine_after=2
        plane.fail(0, "w0", err=RuntimeError("boom"))
        assert plane.state(0) == "queued"  # one strike: requeued
        assert plane.claim(0, "w1")
        plane.fail(0, "w1", err=RuntimeError("boom"))
        assert plane.state(0) == "quarantined"
        assert not plane.claim(0, "w2")
        assert sorted(plane.read(0)["failures"]) == ["w0", "w1"]

    def test_repeat_failure_by_same_worker_counts_once(self, tmp_path):
        plane, clock, _ = self._plane(tmp_path)
        plane.fail(0, "w0")
        plane.fail(0, "w0")
        assert plane.state(0) == "queued"  # still one DISTINCT worker
        assert plane.read(0)["failures"] == ["w0"]

    def test_requeue_expired_sweeps_the_whole_plane(self, tmp_path):
        plane, clock, _ = self._plane(tmp_path)
        assert plane.claim(0, "w0")
        assert plane.claim(1, "w1")
        plane.complete(1, "w1")
        clock.advance(11.0)
        assert plane.requeue_expired() == [0]  # done chunk untouched
        assert plane.state(0) == "queued"
        assert plane.read(0)["failures"] == ["w0"]
        assert plane.requeue_expired() == []  # idempotent

    def test_torn_lease_record_frees_the_chunk(self, tmp_path):
        plane, clock, store = self._plane(tmp_path)
        assert plane.claim(0, "w0")
        path = store.path_for(lease_entry_name("job0", 0))
        with open(path, "w", encoding="utf-8") as f:
            f.write('{"state": "le')  # torn mid-write
        # the corrupt record reads as a miss AND is evicted, so the
        # exclusive create can win again — no permanently wedged chunk
        assert plane.read(0) is None
        assert plane.claim(0, "w1")
        assert plane.read(0)["worker"] == "w1"

    def test_lease_fault_fails_the_claim_only(self, tmp_path):
        churn = FaultPlan.from_obj(
            [{"site": "lease", "kind": "transient", "chunk": 0, "times": 1}]
        )
        clock = ManualClock()
        store = Store(str(tmp_path / "leases"))
        plane = LeasePlane(
            store, "job0", 3, ttl_s=10.0, quarantine_after=2,
            clock=clock, faults=churn,
        )
        assert not plane.claim(0, "w0")  # flaky claim RPC
        assert plane.state(0) == "queued"  # chunk stays claimable
        assert plane.claim(0, "w0")  # budget spent: recovered


class TestClocks:
    def test_manual_clock_advances_deterministically(self):
        clock = ManualClock()
        t0 = clock()
        assert clock() == t0  # reading does not advance
        t1 = clock.advance(2.5)
        assert t1 == clock() == t0 + 2.5

    def test_wall_clock_sleeps_through_the_seam(self):
        t = [100.0]
        slept = []

        def fake_sleep(s):
            slept.append(s)
            t[0] += s

        clock = WallClock(time_fn=lambda: t[0], sleep=fake_sleep)
        assert clock() == 100.0
        assert clock.advance(3.0) == 103.0
        assert slept == [3.0]


# ---- publish-then-commit ------------------------------------------------


class TestPublishCommit:
    def _host(self, plan, ci, bump=0.0):
        lo, hi = plan.chunk_bounds(ci)
        n = hi - lo
        return {
            f: np.linspace(1.0, 2.0, n) + i + bump
            for i, f in enumerate(plan.fields)
        }

    def test_first_commit_wins_second_verifies(self, plan, tmp_path):
        store = Store(str(tmp_path / "store"))
        host = self._host(plan, 0)
        assert publish_chunk(store, plan, 0, host) is True
        # an honest double-compute (stolen lease) verifies and defers
        assert publish_chunk(store, plan, 0, host) is False
        # retry count is operational history, not result identity
        assert publish_chunk(store, plan, 0, host, n_retries=7) is False

    def test_commit_mismatch_raises_loudly(self, plan, tmp_path):
        store = Store(str(tmp_path / "store"))
        assert publish_chunk(store, plan, 0, self._host(plan, 0))
        drifted = self._host(plan, 0)
        drifted[plan.fields[0]] = drifted[plan.fields[0]] + 1e-9
        with pytest.raises(CommitMismatchError, match="re-commit disagrees"):
            publish_chunk(store, plan, 0, drifted)

    def test_quarantine_mask_joins_the_verification(self, plan, tmp_path):
        store = Store(str(tmp_path / "store"))
        assert publish_chunk(store, plan, 0, self._host(plan, 0))
        lo, hi = plan.chunk_bounds(0)
        qmask = np.ones(hi - lo, dtype=bool)
        with pytest.raises(CommitMismatchError, match="quarantine mask"):
            publish_chunk(store, plan, 0, self._host(plan, 0), qmask=qmask)

    def test_torn_entry_recommits(self, plan, tmp_path):
        store = Store(str(tmp_path / "store"))
        host = self._host(plan, 0)
        assert publish_chunk(store, plan, 0, host)
        path = store.path_for(plan.entry_name(0))
        with open(path, "rb") as f:
            data = f.read()
        with open(path, "wb") as f:
            f.write(data[: len(data) // 2])  # torn write
        # the torn entry reads as a miss, so this commit wins again
        assert publish_chunk(store, plan, 0, host) is True
        assert store.get_npz(plan.entry_name(0)) is not None


# ---- store satellites ---------------------------------------------------


class TestStoreRobustness:
    def test_torn_store_read_detects_and_recomputes(self, tmp_path):
        store = Store(str(tmp_path / "store"))
        store.put_npz("sweep_chunk/torn-probe.npz", {"a": np.arange(8.0)})
        store.arm_faults(FaultPlan.from_obj(
            [{"site": "store_read", "kind": "torn", "call": 0}]
        ))
        # read 0 is torn mid-flight: detected, evicted, reported a miss
        assert store.get_npz("sweep_chunk/torn-probe.npz") is None
        assert store.stats.dropped_corrupt == 1
        assert not store.has("sweep_chunk/torn-probe.npz")
        # recompute-and-rewrite heals it (the fault fires once)
        store.put_npz("sweep_chunk/torn-probe.npz", {"a": np.arange(8.0)})
        out = store.get_npz("sweep_chunk/torn-probe.npz")
        np.testing.assert_array_equal(out["a"], np.arange(8.0))

    def test_durable_puts_fsync_file_and_directory(self, tmp_path, monkeypatch):
        import os as _os

        synced = []
        real_fsync = _os.fsync

        def counting_fsync(fd):
            synced.append(fd)
            return real_fsync(fd)

        monkeypatch.setattr(_os, "fsync", counting_fsync)
        store = Store(str(tmp_path / "store"))
        synced.clear()
        store.put_npz("sweep_chunk/durability-probe.npz", {"a": np.arange(4.0)})
        # commit durability: the temp file AND its directory entry must
        # both hit disk before the rename publishes the chunk
        assert len(synced) >= 2
        synced.clear()
        store.put_json("elastic/durability-probe.json", {"ok": True})
        assert len(synced) >= 2


# ---- coordinator election ----------------------------------------------


class TestElection:
    def test_first_create_wins_then_ttl_steal(self, tmp_path):
        from bdlz_tpu.parallel.multihost import elect_coordinator

        store = Store(str(tmp_path / "store"))
        t = [0.0]
        clock = lambda: t[0]  # noqa: E731
        assert elect_coordinator(store, "jobX", "a", ttl_s=30.0, clock=clock)
        assert not elect_coordinator(store, "jobX", "b", ttl_s=30.0, clock=clock)
        # re-election by the holder extends the seat
        assert elect_coordinator(store, "jobX", "a", ttl_s=30.0, clock=clock)
        t[0] = 31.0
        # holder extended at t=0, so its lease runs to t=30: expired now
        assert elect_coordinator(store, "jobX", "b", ttl_s=30.0, clock=clock)
        assert not elect_coordinator(store, "jobX", "a", ttl_s=30.0, clock=clock)


# ---- cross-process backoff determinism (satellite) ----------------------


def test_backoff_schedule_identical_across_processes():
    """Two unrelated processes must derive byte-identical backoff
    schedules from the same policy inputs — claim/steal fairness and
    event-log replayability rest on it."""
    worker = pathlib.Path(__file__).parent / "_mp_backoff_worker.py"
    runs = [
        subprocess.run(
            [sys.executable, str(worker)],
            capture_output=True, text=True, timeout=60,
        )
        for _ in range(2)
    ]
    for r in runs:
        assert r.returncode == 0, r.stderr
    lines = runs[0].stdout.splitlines()
    assert len(lines) == 3 * 4 * 5  # seeds x labels x attempts
    assert all(float(ln) >= 0.0 for ln in lines)
    assert runs[0].stdout == runs[1].stdout


# ---- the elastic engine (shared compiles) -------------------------------


class TestElasticEngine:
    def test_needs_a_store(self, base_cfg, static):
        with pytest.raises(ElasticError, match="store"):
            run_sweep_elastic(
                base_cfg, AXES, static, store=None, chunk_size=CHUNK,
                n_y=N_Y,
            )

    def test_clean_run_bitwise_equals_serial(self, serial, elastic_clean):
        res, _, _ = elastic_clean
        assert res.n_points == serial.n_points
        assert res.chunks == serial.chunks
        assert (res.quad_impl, res.n_quad_nodes) == (
            serial.quad_impl, serial.n_quad_nodes,
        )
        for f in serial.outputs:
            assert_bitwise(res.outputs[f], serial.outputs[f], f)
        np.testing.assert_array_equal(res.failed_mask, serial.failed_mask)
        np.testing.assert_array_equal(
            res.quarantined_mask, serial.quarantined_mask
        )
        assert res.n_quarantined == 0

    def test_streaming_consumer_sees_every_fold(self, serial, elastic_clean):
        res, events, _ = elastic_clean
        assert sorted(ci for ci, _, _, _ in events) == [0, 1, 2]
        covered = np.zeros(res.n_points, dtype=bool)
        for ci, lo, hi, ent in events:
            assert (lo, hi) == (2 * ci, 2 * ci + 2)
            covered[lo:hi] = True
            # the streamed entry IS the committed result, not a preview
            for f in serial.outputs:
                assert_bitwise(ent[f], serial.outputs[f][lo:hi], f)
            assert not np.asarray(ent["failed"]).any()
        assert covered.all()

    def test_elastic_store_warms_run_sweep_cache(
        self, base_cfg, static, serial, elastic_clean
    ):
        """Key-drift pin: elastic commits land under the SAME
        content-addressed names run_sweep's cache uses, so a later
        serial run folds entirely warm."""
        _, _, store = elastic_clean
        res = run_sweep(
            base_cfg, AXES, static, mesh=None, chunk_size=CHUNK, n_y=N_Y,
            retry=_retry(), cache=store,
        )
        assert res.cache_hits == res.chunks == 3
        assert res.cache_misses == 0
        for f in serial.outputs:
            assert_bitwise(res.outputs[f], serial.outputs[f], f)

    def test_second_elastic_run_folds_from_prescan(
        self, base_cfg, static, serial, elastic_clean
    ):
        _, _, store = elastic_clean
        res = run_sweep_elastic(
            base_cfg, AXES, static, store=store, chunk_size=CHUNK, n_y=N_Y,
            retry=_retry(),
        )
        assert res.cache_hits == 3 and res.cache_misses == 0
        for f in serial.outputs:
            assert_bitwise(res.outputs[f], serial.outputs[f], f)

    def test_churn_run_bitwise_equals_serial(
        self, base_cfg, static, serial, tmp_path
    ):
        """THE acceptance pin: a worker crash, an expiring lease, a torn
        store read, and scripted fleet churn — and the folded result is
        still bitwise-identical to the single-host engine."""
        store = Store(str(tmp_path / "churn"))
        churn = FaultPlan.from_obj([
            {"site": "worker_crash", "kind": "transient", "chunk": 1,
             "times": 1},
            {"site": "lease", "kind": "transient", "chunk": 2, "times": 1},
            {"site": "store_read", "kind": "torn", "call": 0},
        ])
        res = run_sweep_elastic(
            base_cfg, AXES, static, store=store, chunk_size=CHUNK, n_y=N_Y,
            retry=_retry(), n_workers=2, lease_ttl_s=5.0,
            churn_plan=churn,
            churn_schedule=[(1, "kill"), (2, "spawn")],
        )
        for f in serial.outputs:
            assert_bitwise(res.outputs[f], serial.outputs[f], f)
        np.testing.assert_array_equal(res.failed_mask, serial.failed_mask)
        assert res.n_quarantined == 0
        assert not res.quarantined_mask.any()
        # the churn genuinely happened: the torn read was detected and
        # evicted, and the crashed worker's lease expired onto the
        # failure list before the chunk was re-run elsewhere
        assert store.stats.dropped_corrupt >= 1
        plan = plan_elastic_sweep(
            base_cfg, AXES, static, chunk_size=CHUNK, n_y=N_Y,
            retry=_retry(),
        )
        rec = read_lease(store, plan.job, 1)
        assert rec["state"] == "done"
        assert len(rec["failures"]) >= 1

    def test_fleet_quarantine_isolates_the_chunk(
        self, base_cfg, static, serial, tmp_path
    ):
        """A chunk that kills quarantine_after DISTINCT workers is
        quarantined fleet-wide: NaN + mask for its points, every other
        point still bitwise-equal to serial."""
        store = Store(str(tmp_path / "quar"))
        churn = FaultPlan.from_obj([
            {"site": "worker_crash", "kind": "transient", "chunk": 1,
             "times": 2},
        ])
        res = run_sweep_elastic(
            base_cfg, AXES, static, store=store, chunk_size=CHUNK, n_y=N_Y,
            retry=_retry(), n_workers=2, lease_ttl_s=2.0,
            quarantine_after=2, churn_plan=churn,
        )
        lo, hi = 2, 4  # chunk 1's points
        assert res.n_quarantined == 2
        assert res.quarantined_mask[lo:hi].all()
        assert not res.quarantined_mask[:lo].any()
        assert not res.quarantined_mask[hi:].any()
        assert res.failed_mask[lo:hi].all()
        for f in serial.outputs:
            assert np.isnan(res.outputs[f][lo:hi]).all(), f
            assert_bitwise(res.outputs[f][:lo], serial.outputs[f][:lo], f)
            assert_bitwise(res.outputs[f][hi:], serial.outputs[f][hi:], f)

    def test_external_worker_drains_then_coordinator_folds(
        self, base_cfg, static, serial, tmp_path
    ):
        """The sweep_cli worker-role protocol, in-process: an external
        worker (own clock, own sleep seam) drains the job, then a
        coordinator folds the committed chunks without recomputing."""
        store = Store(str(tmp_path / "roles"))
        t = [0.0]
        summary = run_worker_loop(
            base_cfg, AXES, static, store=store, worker_id="wext",
            chunk_size=CHUNK, n_y=N_Y, retry=_retry(),
            lease_ttl_s=30.0, poll_s=0.5,
            clock=lambda: t[0],
            sleep=lambda s: t.__setitem__(0, t[0] + s),
        )
        assert summary["alive"] and summary["chunks_done"] == 3
        res = run_sweep_elastic(
            base_cfg, AXES, static, store=store, chunk_size=CHUNK, n_y=N_Y,
            retry=_retry(),
        )
        assert res.cache_hits == 3  # pure fold, no recompute
        for f in serial.outputs:
            assert_bitwise(res.outputs[f], serial.outputs[f], f)

    def test_stuck_protocol_raises_not_hangs(self, base_cfg, static, tmp_path):
        # every claim on every chunk fails forever: no engine build,
        # no progress — the driver must detect the deadlock loudly
        churn = FaultPlan.from_obj([
            {"site": "lease", "kind": "transient", "chunk": ci,
             "times": 10**6}
            for ci in range(3)
        ])
        with pytest.raises(ElasticError, match="no full progress"):
            run_sweep_elastic(
                base_cfg, AXES, static,
                store=str(tmp_path / "stuck"), chunk_size=CHUNK, n_y=N_Y,
                retry=_retry(), churn_plan=churn, max_rounds=4,
            )


# ---- emulator streaming consumer ----------------------------------------


class TestEmulatorElastic:
    def test_exact_fields_elastic_parity(
        self, base_cfg, static, serial, elastic_clean
    ):
        """The emulator's streaming elastic build fills the same surface
        as the serial engine (folded warm here — the commit names are
        content-addressed, so the clean run's store already holds every
        chunk of this spec)."""
        from bdlz_tpu.emulator.build import _exact_fields

        _, _, store = elastic_clean
        flat, n_pts = _exact_fields(
            base_cfg, AXES, static, product=True, mesh=None,
            chunk_size=CHUNK, n_y=N_Y, retry=_retry(), impl="tabulated",
            cache=store, elastic={"n_workers": 1},
        )
        assert n_pts == 6
        for f in flat:
            assert_bitwise(flat[f], serial.outputs[f], f)

    def test_elastic_build_requires_a_store(self, base_cfg, static):
        from bdlz_tpu.emulator.build import EmulatorBuildError, _exact_fields

        with pytest.raises(EmulatorBuildError, match="shared store"):
            _exact_fields(
                base_cfg, AXES, static, product=True, mesh=None,
                chunk_size=CHUNK, n_y=N_Y, impl="tabulated",
                cache=None, elastic=2,
            )
