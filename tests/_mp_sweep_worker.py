"""Worker for the real 2-process jax.distributed sweep test.

Launched twice by ``tests/test_multihost.py::test_two_process_sweep`` as
``python _mp_sweep_worker.py <port> <process_id> <out_dir>``.  Each process
joins the distributed runtime (2 processes × 2 local CPU devices = 4
global devices), runs the mesh-sharded sweep over the *global* mesh —
exercising the multi-process branches of ``shard_global_chunk``,
``process_local_bounds``, ``gather_to_host``, and the broadcast resume
plan — and dumps the gathered outputs so the parent can assert both
processes produced the single-process answer.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from _mp_common import force_local_device_count, pin_worker_platform

# must run before the first `import jax` (overrides the parent pytest
# process's 8-device flag)
force_local_device_count(2)


def main() -> None:
    port, pid, out_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax

    # In-process config (not env vars) is the reliable way to pin the CPU
    # platform in this container; must happen before any backend touch.
    pin_worker_platform(jax, 2)

    from bdlz_tpu.parallel.multihost import init_multihost

    assert init_multihost(f"localhost:{port}", 2, pid) is True
    # idempotency: second call must be a no-op, not a RuntimeError
    assert init_multihost(f"localhost:{port}", 2, pid) is True

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.parallel import make_mesh, run_sweep

    cfg = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    static = static_choices_from_config(cfg)
    axes = {"m_chi_GeV": np.geomspace(0.3, 3.0, 8).tolist()}
    mesh = make_mesh(shape=(4, 1))  # all 4 global devices on dp

    res = run_sweep(
        cfg, axes, static, mesh=mesh, chunk_size=4, n_y=2000,
        out_dir=f"{out_dir}/sweep",
    )
    assert res.n_failed == 0
    assert res.failed_mask is not None and not res.failed_mask.any()

    # resume pass: the broadcast plan must skip every chunk on both
    # processes identically (divergence would deadlock, which the parent's
    # timeout converts into a failure)
    res2 = run_sweep(
        cfg, axes, static, mesh=mesh, chunk_size=4, n_y=2000,
        out_dir=f"{out_dir}/sweep",
    )
    assert res2.resumed_chunks == res.chunks, (res2.resumed_chunks, res.chunks)
    np.testing.assert_array_equal(res.outputs["DM_over_B"], res2.outputs["DM_over_B"])

    np.savez(f"{out_dir}/result_p{pid}.npz", **res.outputs)
    print(f"worker {pid} OK")


if __name__ == "__main__":
    main()
