"""Worker for the real 2-process jax.distributed MCMC test.

Launched twice by ``tests/test_multihost.py::test_two_process_mcmc`` as
``python _mp_mcmc_worker.py <port> <process_id> <out_dir>``.  Each process
joins the distributed runtime (2 processes × 2 local CPU devices = 4
global devices) and runs a checkpointed ensemble chain over the *global*
mesh — exercising the multi-process branches the MCMC layer gained in r4:
``gather_to_host`` on the per-segment chain/state (global arrays a bare
``np.asarray`` would reject) and coordinator-only segment/manifest writes.
A second, resumed invocation must reproduce the chain bitwise from the
coordinator's files.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from _mp_common import force_local_device_count, pin_worker_platform

# must run before the first `import jax` (overrides the parent pytest
# process's 8-device flag)
force_local_device_count(2)


def main() -> None:
    port, pid, out_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax

    pin_worker_platform(jax, 2)

    from bdlz_tpu.parallel.multihost import init_multihost

    assert init_multihost(f"localhost:{port}", 2, pid) is True
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    import jax.numpy as jnp
    import numpy as np

    from bdlz_tpu.parallel import make_mesh
    from bdlz_tpu.sampling.checkpoint import run_ensemble_checkpointed

    def logp(theta):  # (D,) -> scalar: correlated Gaussian, cheap but real
        return -0.5 * (theta[0] ** 2 + 2.0 * (theta[1] - theta[0]) ** 2)

    W, D = 16, 2
    init = np.asarray(
        jax.random.uniform(jax.random.PRNGKey(7), (W, D), minval=-1.0,
                           maxval=1.0, dtype=jnp.float64)
    )
    mesh = make_mesh(shape=(4, 1))

    run = run_ensemble_checkpointed(
        seed=3, logp_fn=logp, init_walkers=init, n_steps=24,
        out_dir=f"{out_dir}/chain", checkpoint_every=8, mesh=mesh,
        identity={"toy": "gaussian-v1"},
    )
    assert run.segments == 3 and run.resumed_segments == 0
    assert run.chain.shape == (24, W, D), run.chain.shape

    # resume pass: every segment must load from the coordinator's files,
    # on both processes, and reproduce the chain bitwise
    run2 = run_ensemble_checkpointed(
        seed=3, logp_fn=logp, init_walkers=init, n_steps=24,
        out_dir=f"{out_dir}/chain", checkpoint_every=8, mesh=mesh,
        identity={"toy": "gaussian-v1"},
    )
    assert run2.resumed_segments == 3, run2.resumed_segments
    np.testing.assert_array_equal(run.chain, run2.chain)
    np.testing.assert_array_equal(run.logp_chain, run2.logp_chain)

    np.savez(f"{out_dir}/mcmc_p{pid}.npz", chain=run.chain,
             logp=run.logp_chain)
    print(f"worker {pid} OK")


if __name__ == "__main__":
    main()
