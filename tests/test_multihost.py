"""Multi-host layer (SURVEY §2.3/§5): env-driven jax.distributed init and
host-local chunk placement. Real multi-process runs need a cluster; these
tests pin the single-process degenerate behavior the multi-process path
must reduce to, plus the layout assumptions."""
import numpy as np
import pytest

from bdlz_tpu.parallel import (
    batch_sharding,
    init_multihost,
    make_mesh,
    process_local_bounds,
    shard_global_chunk,
)


def test_init_multihost_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_multihost() is False


def test_process_local_bounds_single_process():
    # one process owns the whole batch (any length divides 1)
    assert process_local_bounds(16) == (0, 16)
    assert process_local_bounds(17) == (0, 17)


def test_shard_global_chunk_matches_device_put():
    """Single-process path must be bitwise device_put; the sharding must
    actually distribute the batch across the mesh."""
    import jax
    import jax.numpy as jnp

    mesh = make_mesh()
    sharding = batch_sharding(mesh)
    chunk = {"a": np.arange(16, dtype=np.float64), "b": np.ones(16)}
    placed = shard_global_chunk(chunk, sharding)
    np.testing.assert_array_equal(np.asarray(placed["a"]), chunk["a"])
    assert placed["a"].sharding == sharding
    # device 0 holds exactly its 1/8 shard
    shard0 = placed["a"].addressable_shards[0]
    assert shard0.data.shape == (2,)
