"""Multi-host layer (SURVEY §2.3/§5): env-driven jax.distributed init and
host-local chunk placement. Single-process tests pin the degenerate
behavior the multi-process path must reduce to; the 2-process test at the
bottom executes the real thing — ``jax.distributed.initialize`` over
localhost with two CPU processes sharing one global mesh."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from _mp_common import assert_worker_ok

from bdlz_tpu.parallel import (
    batch_sharding,
    init_multihost,
    make_mesh,
    process_local_bounds,
    shard_global_chunk,
)


def test_init_multihost_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_multihost() is False


def test_process_local_bounds_single_process():
    # one process owns the whole batch (any length divides 1)
    assert process_local_bounds(16) == (0, 16)
    assert process_local_bounds(17) == (0, 17)


def test_gather_to_host_single_process_roundtrip():
    """gather_to_host must be a plain asarray single-process, including on
    mesh-sharded global arrays (the exact shape run_sweep feeds it)."""
    import jax

    from bdlz_tpu.parallel.multihost import gather_to_host

    mesh = make_mesh()
    chunk = {"a": np.arange(16, dtype=np.float64)}
    placed = shard_global_chunk(chunk, batch_sharding(mesh))
    back = gather_to_host(placed)
    np.testing.assert_array_equal(back["a"], chunk["a"])
    assert isinstance(back["a"], np.ndarray)


def test_broadcast_from_coordinator_single_process_identity():
    from bdlz_tpu.parallel.multihost import broadcast_from_coordinator, is_coordinator

    assert is_coordinator() is True
    plan = np.array([[1, 3], [0, 0]], dtype=np.int64)
    np.testing.assert_array_equal(broadcast_from_coordinator(plan), plan)


def test_shard_global_chunk_matches_device_put():
    """Single-process path must be bitwise device_put; the sharding must
    actually distribute the batch across the mesh."""
    import jax
    import jax.numpy as jnp

    mesh = make_mesh()
    sharding = batch_sharding(mesh)
    chunk = {"a": np.arange(16, dtype=np.float64), "b": np.ones(16)}
    placed = shard_global_chunk(chunk, sharding)
    np.testing.assert_array_equal(np.asarray(placed["a"]), chunk["a"])
    assert placed["a"].sharding == sharding
    # device 0 holds exactly its 1/8 shard
    shard0 = placed["a"].addressable_shards[0]
    assert shard0.data.shape == (2,)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_sweep(tmp_path):
    """Launch 2 real processes via jax.distributed.initialize on localhost
    CPU (2 local devices each -> 4 global) and run the mesh-sharded sweep
    through the multi-process branches of shard_global_chunk /
    process_local_bounds / gather_to_host, plus a resume pass over the
    broadcast plan. Both processes must produce the single-process answer."""
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_mp_sweep_worker.py")

    env = dict(os.environ)
    # Children must not inherit the axon TPU plugin (empty pool-IPs gates
    # registration off) nor the parent's 8-device XLA flag — the worker
    # pins 2 CPU devices per process itself.
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    env.pop("JAX_PROCESS_ID", None)

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert_worker_ok(rc, out, err)
        assert "OK" in out

    # Both processes saw the identical gathered result, and it matches a
    # single-process run of the same grid on this (8-device) runtime.
    r0 = np.load(tmp_path / "result_p0.npz")
    r1 = np.load(tmp_path / "result_p1.npz")
    np.testing.assert_array_equal(r0["DM_over_B"], r1["DM_over_B"])

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.parallel import run_sweep

    cfg = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    static = static_choices_from_config(cfg)
    axes = {"m_chi_GeV": np.geomspace(0.3, 3.0, 8).tolist()}
    ref = run_sweep(cfg, axes, static, mesh=make_mesh(), chunk_size=4, n_y=2000)
    np.testing.assert_allclose(r0["DM_over_B"], ref.outputs["DM_over_B"], rtol=1e-12)


def test_two_process_fault_healing(tmp_path):
    """The robustness tentpole, executed for real across 2 processes: a
    deterministic fault plan (transient chunk error + poison point) runs
    through the mesh-sharded sweep on both controllers.  The
    attempt-outcome agreement must keep retry/bisect decisions in
    lockstep (divergence deadlocks — the parent timeout catches it),
    both processes must produce the identical quarantine mask, and every
    unaffected point must bitwise-match a clean single-process run."""
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_mp_faults_worker.py")

    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    env.pop("JAX_PROCESS_ID", None)
    env.pop("BDLZ_FAULT_PLAN", None)  # the plan is the worker's, inline

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert_worker_ok(rc, out, err)
        assert "OK" in out

    r0 = np.load(tmp_path / "faults_p0.npz")
    r1 = np.load(tmp_path / "faults_p1.npz")
    np.testing.assert_array_equal(r0["quarantined"], r1["quarantined"])
    np.testing.assert_array_equal(r0["failed"], r1["failed"])
    np.testing.assert_array_equal(r0["DM_over_B"], r1["DM_over_B"])
    expected = np.zeros(8, dtype=bool)
    expected[5] = True
    np.testing.assert_array_equal(r0["quarantined"], expected)

    # unaffected points bitwise-match a clean (no faults) run of the same
    # grid on this runtime
    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.parallel import run_sweep

    cfg = config_from_dict({
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    })
    static = static_choices_from_config(cfg)
    axes = {"m_chi_GeV": np.geomspace(0.3, 3.0, 8).tolist()}
    ref = run_sweep(cfg, axes, static, mesh=make_mesh(), chunk_size=4, n_y=2000)
    keep = ~expected
    np.testing.assert_allclose(
        r0["DM_over_B"][keep], ref.outputs["DM_over_B"][keep], rtol=1e-12
    )
    assert np.isnan(r0["DM_over_B"][5])


def test_two_process_mcmc(tmp_path):
    """The r4 multihost MCMC wiring, executed for real: 2 processes run a
    checkpointed chain over one global mesh; per-segment chains gather via
    gather_to_host (a bare np.asarray raises on those global arrays), only
    the coordinator writes segment/manifest files, and a resume pass
    reproduces the chain bitwise on both processes."""
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_mp_mcmc_worker.py")

    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    env.pop("JAX_PROCESS_ID", None)

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert_worker_ok(rc, out, err)
        assert "OK" in out

    # both processes gathered the identical global chain
    r0 = np.load(tmp_path / "mcmc_p0.npz")
    r1 = np.load(tmp_path / "mcmc_p1.npz")
    np.testing.assert_array_equal(r0["chain"], r1["chain"])
    np.testing.assert_array_equal(r0["logp"], r1["logp"])
    # coordinator-only writes: 3 segments + manifest, written exactly once
    seg_files = sorted(p.name for p in (tmp_path / "chain").iterdir())
    assert seg_files == [
        "manifest.json", "seg_00000.npz", "seg_00001.npz", "seg_00002.npz",
    ]


def test_divergent_kernel_knob_raises_fleetwide(tmp_path):
    """A per-host BDLZ_PALLAS_COL_BLOCK divergence must raise the
    startup-agreement RuntimeError on BOTH processes (r4: the knob keys
    the kernel's numerics and the grid hash; one host raising while the
    other entered a chunk collective would deadlock — the parent's
    timeout converts that into a failure)."""
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_mp_knob_worker.py")

    base_env = dict(os.environ)
    base_env["PALLAS_AXON_POOL_IPS"] = ""
    for k in ("XLA_FLAGS", "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        base_env.pop(k, None)

    procs = []
    for pid, cb in ((0, "8"), (1, "16")):
        env = dict(base_env)
        env["BDLZ_PALLAS_COL_BLOCK"] = cb
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(port), str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert_worker_ok(rc, out, err)
        assert "KNOB-MISMATCH-RAISED" in out


def test_two_process_chunk_cache(tmp_path):
    """The provenance-plane fleet pin: a 2-process run writes its chunk
    entries into a SHARED content-addressed store (coordinator-only
    writes), then a warm 2-process run serves every chunk from the
    broadcast hit-plan — process 1, which never wrote a byte, reads the
    chunks the coordinator stored and reproduces the cold outputs
    bitwise.  Plan divergence would deadlock; the parent timeout
    converts that into a failure."""
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_mp_cache_worker.py")

    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    for k in ("XLA_FLAGS", "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID", "BDLZ_CACHE_ROOT"):
        env.pop(k, None)

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert_worker_ok(rc, out, err)
        assert "OK" in out

    # both processes gathered the identical warm (cache-served) result
    r0 = np.load(tmp_path / "result_p0.npz")
    r1 = np.load(tmp_path / "result_p1.npz")
    np.testing.assert_array_equal(r0["DM_over_B"], r1["DM_over_B"])
    # and the shared store holds exactly the sweep's two chunk entries
    entries = sorted(os.listdir(tmp_path / "store" / "sweep_chunk"))
    assert len(entries) == 2 and all(e.endswith(".npz") for e in entries)
