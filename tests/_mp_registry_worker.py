"""Worker for the real 2-process registry fetch-vs-evict race test.

Launched twice by ``tests/test_provenance.py::TestRegistryRace`` as
``python _mp_registry_worker.py <role> <contested_root> <artifact_dir>
<content_hash> <deadline_epoch>``.  Both processes share one contested
store root:

* the **churner** loops publish → truncate-the-npz → fetch (which
  detects the corrupt entry and evicts it) → republish the same hash,
  i.e. it keeps the entry permanently mid-transition;
* the **fetcher** hammers ``fetch_artifact`` the whole time and asserts
  the registry contract under that churn: every call either returns a
  FULLY VALIDATED artifact whose table bytes are identical to the
  pristine copy, or raises typed
  (``EmulatorArtifactError``/``OSError``) — never a torn read.

Exit 0 with a JSON result line on stdout; any contract violation is a
loud traceback + nonzero exit the parent test surfaces.
"""
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _entry_dir(root: str, content_hash: str) -> str:
    from bdlz_tpu.provenance.registry import ARTIFACT_KIND

    return os.path.join(root, ARTIFACT_KIND, content_hash)


def churner(store, art_dir: str, content_hash: str, deadline: float):
    """Publish / corrupt / evict / republish until the deadline."""
    from bdlz_tpu.emulator.artifact import EmulatorArtifactError
    from bdlz_tpu.provenance import fetch_artifact, publish_artifact

    published = evicted = 0
    while time.time() < deadline:
        assert publish_artifact(store, art_dir) == content_hash
        published += 1
        entry = _entry_dir(store.root, content_hash)
        try:
            victim = next(
                os.path.join(entry, n) for n in sorted(os.listdir(entry))
                if n.endswith(".npz")
            )
            # truncate rather than flip a header byte: zipfile decodes
            # members from the CENTRAL directory, so a flipped local-
            # header byte loads fine — a half-file can never parse
            with open(victim, "r+b") as fh:
                fh.truncate(max(1, os.path.getsize(victim) // 2))
        except (OSError, StopIteration):
            continue  # the fetcher's eviction won the race; republish
        try:
            fetch_artifact(store, content_hash)
        except (EmulatorArtifactError, OSError):
            evicted += 1  # corrupt entry detected -> deleted, as pinned
    return {"published": published, "evicted": evicted}


def fetcher(store, art_dir: str, content_hash: str, deadline: float):
    """Assert every concurrent fetch is validated-or-typed, never torn."""
    import numpy as np

    from bdlz_tpu.emulator.artifact import EmulatorArtifactError
    from bdlz_tpu.emulator.multidomain import load_any_artifact
    from bdlz_tpu.provenance import fetch_artifact

    pristine = load_any_artifact(art_dir)
    ref = {
        k: np.asarray(v) for k, v in pristine.values.items()
    }
    ok = refused = 0
    while time.time() < deadline:
        try:
            art = fetch_artifact(store, content_hash)
        except (EmulatorArtifactError, OSError):
            refused += 1  # typed refusal: absent, corrupt, or mid-evict
            continue
        # a served artifact must be the pristine one, bit for bit —
        # anything else is the torn read this test exists to catch
        assert art.content_hash == content_hash
        for k, v in ref.items():
            assert np.array_equal(np.asarray(art.values[k]), v), (
                f"torn read: field {k} differs from the pristine artifact"
            )
        ok += 1
    return {"ok": ok, "refused": refused}


def main() -> None:
    role, contested_root, art_dir, content_hash, deadline = sys.argv[1:6]

    from bdlz_tpu.provenance import Store

    store = Store(contested_root)
    run = {"churner": churner, "fetcher": fetcher}[role]
    result = run(store, art_dir, content_hash, float(deadline))
    print(json.dumps({"role": role, **result}))


if __name__ == "__main__":
    main()
