"""Pallas KJMA kernel: interpret-mode parity vs the tabulated fast path.

The kernel itself (`bdlz_tpu/ops/kjma_pallas.py`) reformulates the table
gather as one-hot MXU matmuls; on CPU we run it through the Pallas
interpreter, which executes the identical kernel semantics, so these
tests pin down correctness (the TPU-side speed is covered by bench.py).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.models.yields_pipeline import point_yields_fast
from bdlz_tpu.ops.kjma_pallas import (
    build_shifted_table,
    integrate_YB_pallas,
    point_yields_pallas,
)
from bdlz_tpu.ops.kjma_table import make_f_table
from bdlz_tpu.parallel.sweep import build_grid


@pytest.fixture(scope="module")
def setup():
    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    static = static_choices_from_config(base)
    table = make_f_table(base.I_p, jnp, n=16384)
    t4 = build_shifted_table(table)
    return base, static, table, t4


def test_shifted_table_layout(setup):
    _, _, table, t4 = setup
    vals = np.asarray(table.values)
    t4 = np.asarray(t4)
    assert t4.shape == (512, 128)  # transposed for the canonical matmul
    # spot-check the stencil shifts: T4[k*128+c, m] == F[m*128+c+k-1]
    rng = np.random.default_rng(0)
    for _ in range(50):
        m = int(rng.integers(0, 128))
        c = int(rng.integers(0, 128))
        for k in range(4):
            flat = np.clip(m * 128 + c + k - 1, 0, vals.size - 1)
            assert t4[k * 128 + c, m] == np.float32(vals[flat])


def test_pallas_matches_tabulated_path(setup):
    base, static, table, t4 = setup
    rng = np.random.default_rng(42)
    n = 8
    grid = build_grid(
        base,
        {
            # include heavy-mass / low-T_p points so the Maxwell-Boltzmann
            # branch (T <= m/3) is exercised, not just the relativistic one
            "m_chi_GeV": np.concatenate([rng.uniform(0.1, 5.0, n - 3),
                                         [120.0, 400.0, 1000.0]]),
            "T_p_GeV": np.concatenate([rng.uniform(50.0, 200.0, n - 3),
                                       [30.0, 35.0, 30.0]]),
            "P_chi_to_B": rng.uniform(0.01, 0.9, n),
            "v_w": rng.uniform(0.05, 0.95, n),
            "source_shape_sigma_y": rng.uniform(2.0, 20.0, n),
        },
        product=False,
    )
    grid = jax.tree.map(jnp.asarray, grid)

    ref = jax.vmap(lambda p: point_yields_fast(p, static, table, jnp, n_y=2048).Y_B)(grid)
    got = integrate_YB_pallas(grid, static.chi_stats, table, t4, n_y=2048, interpret=True)

    ref = np.asarray(ref)
    got = np.asarray(got)
    assert np.all(np.isfinite(got))
    rel = np.abs(got - ref) / np.abs(ref)
    # f32 streams + f32 interp arithmetic: well inside the 1e-6 contract
    assert rel.max() < 5e-7, rel.max()


def test_pallas_thermal_regime_and_results(setup):
    base, _, table, t4 = setup
    cfg = dataclasses.replace(base, regime="thermal")
    static = static_choices_from_config(cfg)
    grid = build_grid(cfg, {"m_chi_GeV": [0.5, 0.95, 2.0]})
    grid = jax.tree.map(jnp.asarray, grid)

    res = point_yields_pallas(grid, static, table, t4, n_y=2048, interpret=True)
    ref = jax.vmap(lambda p: point_yields_fast(p, static, table, jnp, n_y=2048))(grid)
    np.testing.assert_allclose(
        np.asarray(res.DM_over_B), np.asarray(ref.DM_over_B), rtol=1e-6
    )
    np.testing.assert_allclose(np.asarray(res.Y_chi), np.asarray(ref.Y_chi), rtol=1e-12)


def test_pallas_empty_window_is_zero(setup):
    base, static, table, t4 = setup
    # T window entirely above the percolation support: y_hi < y_lo after clip
    cfg = dataclasses.replace(base, T_min_over_Tp=4.0, T_max_over_Tp=5.0)
    grid = build_grid(cfg, {"m_chi_GeV": [0.95]})
    grid = jax.tree.map(jnp.asarray, grid)
    got = integrate_YB_pallas(grid, static.chi_stats, table, t4, n_y=2048, interpret=True)
    ref = jax.vmap(lambda p: point_yields_fast(p, static, table, jnp, n_y=2048).Y_B)(grid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)


def test_exp_neg_f32_accuracy():
    """The in-kernel Cody-Waite exp must hold ~2e-7 relative over the
    normalized-argument range and the positive overshoot corners
    (TPU's native f32 exp is ~7e-6)."""
    from bdlz_tpu.ops.kjma_pallas import exp_neg_f32, split_f64

    a = jnp.asarray(np.linspace(-87.0, 40.0, 200001))
    hi, lo = split_f64(a)
    got = np.asarray(exp_neg_f32(hi, lo), dtype=np.float64)
    ref = np.exp(np.asarray(a))
    rel = np.abs(got / ref - 1.0)
    assert rel.max() < 3e-7, rel.max()
    # flush region
    hi2, lo2 = split_f64(jnp.asarray(np.array([-88.0, -500.0])))
    assert np.all(np.asarray(exp_neg_f32(hi2, lo2)) == 0.0)


def test_pallas_fused_exp_matches_tabulated(setup):
    base, static, table, t4 = setup
    rng = np.random.default_rng(7)
    n = 8
    grid = build_grid(
        base,
        {
            "m_chi_GeV": np.concatenate([rng.uniform(0.1, 5.0, n - 3),
                                         [120.0, 400.0, 1000.0]]),
            "T_p_GeV": np.concatenate([rng.uniform(50.0, 200.0, n - 3),
                                       [30.0, 35.0, 30.0]]),
            "P_chi_to_B": rng.uniform(0.01, 0.9, n),
            "v_w": rng.uniform(0.05, 0.95, n),
            "source_shape_sigma_y": rng.uniform(2.0, 20.0, n),
        },
        product=False,
    )
    grid = jax.tree.map(jnp.asarray, grid)
    ref = jax.vmap(lambda p: point_yields_fast(p, static, table, jnp, n_y=2048).Y_B)(grid)
    got = integrate_YB_pallas(
        grid, static.chi_stats, table, t4, n_y=2048, interpret=True, fuse_exp=True
    )
    rel = np.abs(np.asarray(got) - np.asarray(ref)) / np.abs(np.asarray(ref))
    assert rel.max() < 5e-7, rel.max()


def test_pallas_parity_vs_numpy_reference_population(setup):
    """Broad interpret-mode parity: 64 randomized configs spanning both
    n_eq branches, clip edges, and the T = m/3 seam, against the
    bit-reproducible NumPy reference path (not just the tabulated JAX
    path) — the same population shape as scripts/accuracy_audit.py."""
    from bdlz_tpu.models.yields_pipeline import point_yields
    from bdlz_tpu.physics.percolation import make_kjma_grid

    base, static, table, t4 = setup
    rng = np.random.default_rng(11)
    n = 64
    m = 10 ** rng.uniform(-1.0, 1.0, n)
    T_p = 10 ** rng.uniform(1.5, 2.5, n)
    m[-8:] = 3.0 * T_p[-8:] * rng.uniform(0.8, 1.2, 8)   # seam inside window
    m[-16:-8] = 10 ** rng.uniform(1.5, 3.0, 8)           # deep MB
    grid = build_grid(
        base,
        {
            "m_chi_GeV": m,
            "T_p_GeV": T_p,
            "source_shape_sigma_y": rng.uniform(2.0, 20.0, n),
            "beta_over_H": rng.uniform(50.0, 500.0, n),
            "v_w": rng.uniform(0.05, 0.95, n),
            "P_chi_to_B": rng.uniform(0.01, 0.9, n),
        },
        product=False,
    )
    grid_j = jax.tree.map(jnp.asarray, grid)
    got = np.asarray(integrate_YB_pallas(
        grid_j, static.chi_stats, table, t4, n_y=8000, interpret=True
    ))
    grid_np = make_kjma_grid(np)
    ref = np.array([
        point_yields(
            type(grid)(*(float(np.asarray(f)[i]) for f in grid)),
            static, grid_np, np,
        ).Y_B
        for i in range(n)
    ])
    rel = np.abs(got / ref - 1.0)
    assert rel.max() < 1e-6, rel.max()


def test_scaling_linearity_in_P_and_flux(setup):
    """Paper §8 physics contract: Y_B is exactly linear in P_chi_to_B and
    in the incident flux scale on the quadrature path — the pallas kernel
    must preserve the scaling bitwise-level (both enter one per-point
    prefactor)."""
    base, static, table, t4 = setup
    grid1 = build_grid(base, {"m_chi_GeV": [0.5, 0.95, 2.0]})
    g2 = grid1._replace(P=grid1.P * 2.0, flux_scale=grid1.flux_scale * 3.0)
    y1 = np.asarray(integrate_YB_pallas(
        jax.tree.map(jnp.asarray, grid1), static.chi_stats, table, t4,
        n_y=2048, interpret=True,
    ))
    y2 = np.asarray(integrate_YB_pallas(
        jax.tree.map(jnp.asarray, g2), static.chi_stats, table, t4,
        n_y=2048, interpret=True,
    ))
    np.testing.assert_allclose(y2, 6.0 * y1, rtol=1e-12)


def test_reduce_modes_agree(setup):
    """In-kernel Kahan reduction vs streaming the full integrand: same
    Y_B to ~f32-eps (the compensated sum reconstructs the f64 host sum),
    for both kernel variants."""
    base, static, table, t4 = setup
    rng = np.random.default_rng(3)
    n = 8
    grid = build_grid(
        base,
        {
            "m_chi_GeV": np.concatenate([rng.uniform(0.1, 5.0, n - 2),
                                         [300.0, 900.0]]),
            "T_p_GeV": rng.uniform(30.0, 300.0, n),
            "v_w": rng.uniform(0.05, 0.95, n),
            "source_shape_sigma_y": rng.uniform(2.0, 20.0, n),
        },
        product=False,
    )
    grid = jax.tree.map(jnp.asarray, grid)
    for fuse in (False, True):
        full = np.asarray(integrate_YB_pallas(
            grid, static.chi_stats, table, t4, n_y=2048, interpret=True,
            fuse_exp=fuse, reduce=False,
        ))
        red = np.asarray(integrate_YB_pallas(
            grid, static.chi_stats, table, t4, n_y=2048, interpret=True,
            fuse_exp=fuse, reduce=True,
        ))
        np.testing.assert_allclose(red, full, rtol=3e-7)


def test_preflight_reports_failure_without_raising():
    """On a platform where the real (non-interpret) kernel cannot run —
    this CPU test env — the preflight must come back as a failure report,
    never an exception: the bench/sweep gates branch on it."""
    from bdlz_tpu.ops.kjma_pallas import pallas_preflight

    ok, rel, detail = pallas_preflight(n_points=8)
    assert isinstance(ok, bool)
    assert isinstance(detail, str) and detail
    if not ok:  # the expected outcome on CPU
        assert rel == float("inf") or rel > 1e-6


def test_col_block_env_override_parity(tmp_path):
    """BDLZ_PALLAS_COL_BLOCK retunes the grid-step unroll at import (the
    hardware shootout sweeps it per-subprocess); a non-default block must
    preserve tabulated-path parity and reject misaligned values."""
    import os
    import subprocess
    import sys

    code = r"""
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.models.yields_pipeline import point_yields_fast
from bdlz_tpu.ops import kjma_pallas as kp
from bdlz_tpu.ops.kjma_table import make_f_table
from bdlz_tpu.parallel.sweep import build_grid

assert kp.COL_BLOCK == 16, kp.COL_BLOCK
base = config_from_dict({
    "regime": "nonthermal", "P_chi_to_B": 0.149,
    "source_shape_sigma_y": 9.0, "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
})
static = static_choices_from_config(base)
table = make_f_table(base.I_p, jnp, n=16384)
t4 = kp.build_shifted_table(table)
rng = np.random.default_rng(5)
grid = build_grid(base, {
    "m_chi_GeV": rng.uniform(0.3, 3.0, 4),
    "T_p_GeV": rng.uniform(50.0, 200.0, 4),
}, product=False)
grid = jax.tree.map(jnp.asarray, grid)
got = np.asarray(kp.integrate_YB_pallas(
    grid, static.chi_stats, table, t4, n_y=2048, interpret=True))
want = np.asarray(jax.vmap(
    lambda p: point_yields_fast(p, static, table, jnp, n_y=2048).Y_B
)(grid))
np.testing.assert_allclose(got, want, rtol=3e-7)
print("colblock16 OK")
"""
    import pathlib

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env.update(PYTHONPATH=repo, PALLAS_AXON_POOL_IPS="",
               BDLZ_PALLAS_COL_BLOCK="16")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "colblock16 OK" in r.stdout

    # misaligned values are an import-time error, not a silent mis-tile
    env["BDLZ_PALLAS_COL_BLOCK"] = "6"
    r = subprocess.run(
        [sys.executable, "-c", "import bdlz_tpu.ops.kjma_pallas"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode != 0
    assert "multiple of 8" in r.stderr


def test_split3_masked_table_reconstruction(setup):
    """The bf16x3 mantissa-masked split must reconstruct the f32 table
    bit-exactly for every normal-range entry (truncating masks, no
    rounding — unlike a naive bf16 cast), and to within 2^-133 absolute
    for the handful of f32-subnormal underflow-tail entries."""
    from bdlz_tpu.ops.kjma_pallas import STENCIL_ROWS

    _, _, table, t4 = setup
    t4_np = np.asarray(t4, dtype=np.float32)
    s3 = np.asarray(
        build_shifted_table(table, split3=True), dtype=np.float32
    )
    assert s3.shape == (3 * STENCIL_ROWS, t4_np.shape[1])
    recon = (
        s3[:STENCIL_ROWS]
        + s3[STENCIL_ROWS:2 * STENCIL_ROWS]
        + s3[2 * STENCIL_ROWS:]
    )
    # pieces are bf16-exact: casting bf16 -> f32 -> sum reproduces f32
    normal = np.abs(t4_np) >= np.finfo(np.float32).tiny * 2.0 ** 17
    normal |= t4_np == 0.0
    np.testing.assert_array_equal(recon[normal], t4_np[normal])
    resid = np.abs(recon[~normal] - t4_np[~normal])
    assert resid.size == 0 or resid.max() <= 2.0 ** -133


def test_split3_kernel_matches_f32_kernel(setup):
    """The bf16x3 table layout through the same kernel entry points must
    reproduce the f32 layout's Y_B essentially bitwise (the only
    differences can come from the ~30 subnormal underflow-tail table
    entries, ~1e-30 relative at worst)."""
    base, static, table, t4 = setup
    t4s = build_shifted_table(table, split3=True)
    rng = np.random.default_rng(11)
    n = 6
    grid = build_grid(
        base,
        {
            "m_chi_GeV": rng.uniform(0.3, 3.0, n),
            "T_p_GeV": rng.uniform(50.0, 200.0, n),
            "source_shape_sigma_y": rng.uniform(4.0, 15.0, n),
        },
        product=False,
    )
    grid = jax.tree.map(jnp.asarray, grid)
    for fuse in (False, True):
        for reduce in (False, True):
            a = np.asarray(integrate_YB_pallas(
                grid, static.chi_stats, table, t4, n_y=2048,
                interpret=True, fuse_exp=fuse, reduce=reduce,
            ))
            b = np.asarray(integrate_YB_pallas(
                grid, static.chi_stats, table, t4s, n_y=2048,
                interpret=True, fuse_exp=fuse, reduce=reduce,
            ))
            np.testing.assert_allclose(b, a, rtol=1e-12)


def test_row_select_contraction_precision_pinned():
    """The f32-layout one-hot dot must stage with Precision.HIGHEST.

    Load-bearing for hardware only: Mosaic's DEFAULT contract precision
    may demote f32 operands to one bf16 MXU pass (~4e-3 rel err), but
    CPU dots are exact at any setting — a regression here would pass
    every interpret-mode accuracy test and only fail on the chip, so
    the pin is asserted at the jaxpr level.  The bf16x3 layout's dots
    intentionally stay at DEFAULT (single pass per exact piece).
    """
    from bdlz_tpu.ops import kjma_pallas as kp
    from bdlz_tpu.ops.kjma_pallas import LANES, ROWS, STENCIL_ROWS

    subl = jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANES), 0)
    i1t = jnp.ones((8, LANES), jnp.int32)
    st = jnp.zeros((8, LANES), jnp.float32)

    def precisions(t4t):
        jaxpr = jax.make_jaxpr(
            lambda a, b, c, d: kp._interp_column(a, b, c, d, 0)
        )(t4t, subl, i1t, st)
        return [e.params.get("precision") for e in jaxpr.jaxpr.eqns
                if e.primitive.name == "dot_general"]

    f32_prec = precisions(jnp.zeros((STENCIL_ROWS, ROWS), jnp.float32))
    assert f32_prec == [(jax.lax.Precision.HIGHEST,) * 2], f32_prec

    s3_prec = precisions(jnp.zeros((3 * STENCIL_ROWS, ROWS), jnp.bfloat16))
    assert len(s3_prec) == 3, s3_prec
    assert all(p is None or p == (jax.lax.Precision.DEFAULT,) * 2
               for p in s3_prec), s3_prec
