"""Unit tests for the shared accuracy-gate loop (bdlz_tpu/validation.py).

The gate is the one place both measurement tools (bench.py and
scripts/impl_shootout.py) compute their max-rel-err number, so its corner
behavior — non-finite outputs, zero-reference points (ADVICE r4) — is
pinned here directly with synthetic chunk runners.
"""
import os

import numpy as np
import pytest

from bdlz_tpu.validation import GateFailure, population_max_rel


def _runner(values):
    values = np.asarray(values, dtype=float)

    def run_chunk(lo, hi):
        return values[lo:hi]

    return run_chunk


def test_max_rel_over_plain_population():
    ref = np.array([1.0, 2.0, -4.0])
    got = ref * np.array([1.0, 1.0 + 3e-7, 1.0 - 1e-6])
    rel = population_max_rel(_runner(got), 2, ref)
    assert rel == pytest.approx(1e-6, rel=1e-6)


def test_nonfinite_engine_output_raises():
    ref = np.ones(4)
    got = np.array([1.0, np.nan, 1.0, np.inf])
    with pytest.raises(GateFailure, match="2/4 non-finite"):
        population_max_rel(_runner(got), 4, ref)


def test_all_zero_reference_raises():
    with pytest.raises(GateFailure, match="identically zero"):
        population_max_rel(_runner(np.zeros(3)), 3, np.zeros(3))


def test_ref_zero_points_held_to_abs_tol(capsys):
    """ref==0 points are excluded from max-rel but bounded by an absolute
    tolerance scaled to the TYPICAL population magnitude (1e-6 * median
    nonzero |ref| — max|ref| over a 15-decade population would be ~10
    decades too loose, ADVICE r5); the exclusion count is logged to
    stderr, keeping stdout JSON-clean."""
    ref = np.array([10.0, 0.0, -5.0, 0.0])
    got = np.array([10.0, 5e-6, -5.0 * (1 + 2e-7), -4e-6])
    rel = population_max_rel(_runner(got), 2, ref)
    assert rel == pytest.approx(2e-7, rel=1e-6)
    captured = capsys.readouterr()
    assert captured.out == ""
    assert "2/4 ref==0 points" in captured.err


def test_reference_ratios_cache_roundtrip(tmp_path):
    """The on-disk reference cache returns bit-identical values on a hit,
    and the key includes the population, n_y, and the reference source
    fingerprint (a different population must miss)."""
    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.validation import (
        build_audit_population,
        reference_ratios,
        reference_ratios_cached,
    )

    base = config_from_dict({
        "regime": "nonthermal", "P_chi_to_B": 0.149,
        "Y_chi_init": 4.9e-10, "incident_flux_scale": 1.07e-9,
    })
    static = static_choices_from_config(base)
    pop = build_audit_population(base, 6, seed=3)
    cache = str(tmp_path / "refcache")

    direct = reference_ratios(pop.grid, static, n_y=400)
    first = reference_ratios_cached(pop.grid, static, n_y=400, cache_dir=cache)
    np.testing.assert_array_equal(first, direct)
    files = list((tmp_path / "refcache").glob("ref_*.npy"))
    assert len(files) == 1
    # poison the cached file: a hit must come from disk, not recompute
    np.save(files[0], direct + 1.0)
    poisoned = reference_ratios_cached(
        pop.grid, static, n_y=400, cache_dir=cache
    )
    np.testing.assert_array_equal(poisoned, direct + 1.0)
    # different n_y -> different key -> fresh compute, second file
    fresh = reference_ratios_cached(pop.grid, static, n_y=300, cache_dir=cache)
    assert len(list((tmp_path / "refcache").glob("ref_*.npy"))) == 2
    np.testing.assert_array_equal(
        fresh, reference_ratios(pop.grid, static, n_y=300)
    )
    # empty cache_dir disables caching entirely
    off = reference_ratios_cached(pop.grid, static, n_y=400, cache_dir="")
    np.testing.assert_array_equal(off, direct)
    # a cache dir owned by another uid is refused (the cache is the
    # gate's ground truth); falls back to recompute
    if os.getuid() == 0:
        foreign = tmp_path / "foreign"
        foreign.mkdir()
        os.chown(foreign, 12345, 12345)
        np.save(foreign / "poison.npy", direct + 9.0)
        got = reference_ratios_cached(
            pop.grid, static, n_y=400, cache_dir=str(foreign)
        )
        np.testing.assert_array_equal(got, direct)
        assert not list(foreign.glob("ref_*.npy"))  # nothing written there


def test_ref_zero_point_with_large_engine_value_fails():
    """A large finite engine value at a zero-reference point must FAIL the
    gate, not be silently dropped (ADVICE r4)."""
    ref = np.array([10.0, 0.0, -5.0])
    got = np.array([10.0, 0.5, -5.0])
    with pytest.raises(GateFailure, match="zero-reference point"):
        population_max_rel(_runner(got), 3, ref)


def test_abs_tol_scales_to_median_not_max():
    """One 15-decade outlier in the reference population must not loosen
    the zero-point tolerance by 15 decades (ADVICE r5): a value that is
    huge relative to the TYPICAL output scale fails even though it is
    tiny next to max|ref|."""
    ref = np.array([1e6, 1.0, 1.0, 0.0])
    got = np.array([1e6, 1.0, 1.0, 0.5])  # 0.5 ≪ 1e-6*max but ≫ 1e-6*median
    with pytest.raises(GateFailure, match="zero-reference point"):
        population_max_rel(_runner(got), 4, ref)


class TestRefcacheHardening:
    """The cache dir IS the accuracy gate's ground truth (ADVICE r5):
    symlinks, foreign write bits, and corrupt payloads must all fail
    SAFE — recompute, never trust."""

    def _pop(self):
        from bdlz_tpu.config import config_from_dict, static_choices_from_config
        from bdlz_tpu.validation import build_audit_population

        base = config_from_dict({
            "regime": "nonthermal", "P_chi_to_B": 0.149,
            "Y_chi_init": 4.9e-10, "incident_flux_scale": 1.07e-9,
        })
        pop = build_audit_population(base, 4, seed=7)
        return pop.grid, static_choices_from_config(base)

    def test_symlinked_cache_dir_refused(self, tmp_path, capsys):
        from bdlz_tpu.validation import reference_ratios_cached

        grid, static = self._pop()
        real = tmp_path / "real"
        real.mkdir(mode=0o700)
        link = tmp_path / "link"
        link.symlink_to(real)
        stats = {}
        out = reference_ratios_cached(
            grid, static, n_y=200, cache_dir=str(link), stats=stats
        )
        assert "symlink" in capsys.readouterr().err
        assert stats["cache_hit"] is False
        assert not list(real.glob("ref_*.npy"))  # nothing written through it
        np.testing.assert_array_equal(
            out,
            reference_ratios_cached(grid, static, n_y=200, cache_dir=""),
        )

    def test_group_writable_cache_dir_refused(self, tmp_path, capsys):
        import os

        from bdlz_tpu.validation import reference_ratios_cached

        grid, static = self._pop()
        d = tmp_path / "loose"
        d.mkdir()
        os.chmod(d, 0o770)
        reference_ratios_cached(grid, static, n_y=200, cache_dir=str(d))
        assert "group/other-writable" in capsys.readouterr().err
        assert not list(d.glob("ref_*.npy"))

    def test_corrupt_cache_file_deleted_and_recomputed(self, tmp_path, capsys):
        from bdlz_tpu.validation import reference_ratios_cached

        grid, static = self._pop()
        d = str(tmp_path / "cache")
        first = reference_ratios_cached(grid, static, n_y=200, cache_dir=d)
        files = list((tmp_path / "cache").glob("ref_*.npy"))
        assert len(files) == 1
        files[0].write_bytes(b"not a numpy file")
        stats = {}
        again = reference_ratios_cached(
            grid, static, n_y=200, cache_dir=d, stats=stats
        )
        assert "corrupt" in capsys.readouterr().err
        assert stats["cache_hit"] is False
        np.testing.assert_array_equal(again, first)
        # the rewritten file is valid again: third call is a clean hit
        stats = {}
        third = reference_ratios_cached(
            grid, static, n_y=200, cache_dir=d, stats=stats
        )
        assert stats["cache_hit"] is True
        np.testing.assert_array_equal(third, first)

    def test_default_dir_under_user_cache_root(self, tmp_path, monkeypatch):
        """The default cache root honors XDG_CACHE_HOME (and therefore
        never lands in the world-writable system temp dir)."""
        from bdlz_tpu.validation import reference_ratios_cached

        grid, static = self._pop()
        monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path / "xdg"))
        monkeypatch.delenv("BDLZ_REF_CACHE_DIR", raising=False)
        reference_ratios_cached(grid, static, n_y=200)
        assert list((tmp_path / "xdg" / "bdlz_refcache").glob("ref_*.npy"))
