"""bdlz-lint test fixture: exactly one seeded violation per rule R1-R7.

Lives under a ``physics/`` directory on purpose — that puts it in scope
for the directory-scoped rules (R3 hot paths, R4 magic floats). Never
imported; parsed by the analyzer only (tests/test_lint.py).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

# R5: global config write outside backend.py/conftest.py
jax.config.update("jax_enable_x64", True)

# R7: bare time.sleep call outside utils/retry.py
time.sleep(0.0)


def hot_kernel(x, n_y):
    # R2: Python branch on the traced parameter `x`
    if x > 0.0:
        x = x + 1.0
    # R1: host numpy call inside jit-reachable code
    y = np.asarray(x)
    # R3: host sync inside a hot path
    z = float(x)
    # R4: magic float in a physics module (belongs in constants.py)
    return jnp.sin(y) * 1.6603 + z


# R6: jitted entry point leaves the structural parameter n_y non-static
compiled = jax.jit(hot_kernel)
