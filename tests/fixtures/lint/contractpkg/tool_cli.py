"""bdlz-lint contract fixture: the driver half of the package.

Seeds exactly one R11 violation (``--mystery-flag`` has no Config twin,
no alias, no operational-dest entry) next to a clean structurally-named
flag; seeds exactly one R10 violation (direct truthiness on the
``seam_split`` tri-state outside a resolver) and one R12 violation (the
jitted kernel re-invoked in a loop with a varying structural argument).
Never imported; parsed by the analyzer only (tests/test_lint.py).
"""
import argparse

import jax


def make_parser():
    ap = argparse.ArgumentParser()
    # clean: dest names its Config twin
    ap.add_argument("--t-p-gev", type=float, dest="T_p_GeV")
    # R11 (seeded): no twin, no alias, not declared operational
    ap.add_argument("--mystery-flag", type=float, dest="mystery_flag")
    return ap


def pick_seam(cfg):
    # R10 (seeded): None ("engine decides") collapses to False here
    if cfg.seam_split:
        return "split"
    return "single"


def kernel(x, n_levels):
    return x * n_levels


compiled = jax.jit(kernel)


def churn(x, levels):
    out = []
    for n in levels:
        # R12 (seeded): structural argument varies per iteration and is
        # not declared static at the jit site — recompiles every pass
        out.append(compiled(x, n_levels=n))
    return out
