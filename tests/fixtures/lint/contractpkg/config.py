"""bdlz-lint contract fixture: the Config half of a two-module package.

Never imported; parsed by the analyzer only (tests/test_lint.py).  Seeds
exactly one violation each for R8 and R9; the identity constructor the
clean fields rely on lives in the SIBLING module (identity.py), so these
findings exercise the cross-file symbol table, not a per-file pass.
"""
from dataclasses import dataclass
from typing import Any, Dict, Optional

REFERENCE_KEYS = ("T_p_GeV",)

#: Orchestration knobs with their one identity home: this tuple.
ROBUSTNESS_CONFIG_FIELDS = ("fault_injection",)


@dataclass
class Config:
    T_p_GeV: float = 100.0
    n_levels: int = 2
    # clean tri-state: its identity home is the "seam_split" key the
    # SIBLING module's constructor (identity.py) folds into hash_extra
    # — resolvable only through the cross-file symbol table
    seam_split: Optional[bool] = None
    # clean tri-state: excluded (ROBUSTNESS_CONFIG_FIELDS) + exempt
    fault_injection: Optional[bool] = None
    # R8 (seeded): the PR-7 drift class — a tri-state knob with ZERO
    # identity homes (not an identity key, not excluded, no
    # StaticChoices berth): a resumed run silently reuses results
    # computed under the other resolution
    quad_panel_gl: Optional[bool] = None
    # R9 (seeded): accepted by the schema, bounded nowhere
    mystery_knob: float = 1.0


#: R9 allowlist: fields validate() trusts as-given, on purpose.
VALIDATION_EXEMPT_FIELDS = ("seam_split", "fault_injection", "quad_panel_gl")


def validate(cfg: Config) -> Config:
    if cfg.T_p_GeV <= 0.0:
        raise ValueError("T_p_GeV must be positive")
    if cfg.n_levels < 2:
        raise ValueError("n_levels needs at least two levels")
    return cfg


def config_identity_dict(cfg: Config) -> Dict[str, Any]:
    out: Dict[str, Any] = {k: getattr(cfg, k) for k in REFERENCE_KEYS}
    for k, v in vars(cfg).items():
        if k in REFERENCE_KEYS or k in ROBUSTNESS_CONFIG_FIELDS:
            continue
        out[k] = v
    return out
