"""bdlz-lint contract fixture: the identity half of the package.

The ``seam_split`` key below is the ONE identity home of the sibling
config.py's ``seam_split`` tri-state — the analyzer can only connect
the two through its cross-file symbol table.  ``quad_panel_gl`` is
deliberately absent: that is the seeded R8 drift.
"""
import hashlib
import json


def build_identity(cfg) -> str:
    hash_extra = {
        "seam_split": cfg.seam_split,
        "n_levels": cfg.n_levels,
    }
    payload = {"T_p_GeV": cfg.T_p_GeV, **hash_extra}
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()
