"""NUTS sampler tests: statistical correctness on analytic targets,
mass-matrix options, vmapped chains, checkpoint/resume with the sampler
joined to the run identity, and the mcmc_cli sampler knob."""
import json
import numpy as np
import pytest

from bdlz_tpu.sampling import bulk_ess, rank_normalized_split_rhat, run_nuts

BENCH_OVER = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


class TestNUTSOnGaussian:
    _cache: dict = {}

    def _run(self, mass_matrix="diag", C=4, steps=320, warmup=200, seed=1):
        """One adapted run per arg tuple, memoized: the moment /
        acceptance / eval-counter tests all inspect the SAME chain (a
        NUTS compile is several seconds — tier-1 pays it once)."""
        key = (mass_matrix, C, steps, warmup, seed)
        if key in self._cache:
            return self._cache[key]
        import jax
        import jax.numpy as jnp

        mean = jnp.array([1.0, -2.0, 0.5])
        sigma = jnp.array([0.7, 1.3, 0.1])

        def logp(theta):
            r = (theta - mean) / sigma
            return -0.5 * jnp.sum(r * r)

        init = mean + 0.05 * jax.random.normal(
            jax.random.PRNGKey(0), (C, 3)
        ) * sigma
        run = run_nuts(
            jax.random.PRNGKey(seed), logp, init, n_steps=steps,
            n_warmup=warmup, mass_matrix=mass_matrix,
        )
        out = (run, np.asarray(mean), np.asarray(sigma))
        self._cache[key] = out
        return out

    def test_recovers_gaussian_moments(self):
        run, mean, sigma = self._run()
        s = np.asarray(run.chain).reshape(-1, 3)
        # per-axis tolerance: ~4-5 standard errors at this chain length
        assert np.all(np.abs(s.mean(axis=0) - mean) < 0.2 * sigma)
        assert np.allclose(s.std(axis=0), sigma, rtol=0.12)
        assert run.n_divergent == 0
        # the adapted diag inverse mass tracks the target variances
        assert np.allclose(run.inv_mass, sigma**2, rtol=0.5)

    def test_acceptance_near_target(self):
        run, *_ = self._run()
        assert 0.6 < run.acceptance < 0.99

    def test_eval_counter_is_honest(self):
        """n_leapfrog counts every gradient evaluation: the sampling
        phase alone must account for >= one leapfrog per draw, and
        n_logp_evals adds only the per-phase initializations (chains +
        the two bounded ε searches)."""
        run, *_ = self._run()                     # the memoized run
        assert run.n_leapfrog >= 320 * 4          # >= 1 leapfrog per draw
        assert run.n_logp_evals > run.n_leapfrog
        assert run.n_logp_evals - run.n_leapfrog < 200

    @pytest.mark.slow
    def test_dense_mass_on_correlated_target(self):
        # slow: statistical validation of the dense metric; the dense
        # path's wiring stays in tier-1 via the CLI config-knob test
        import jax
        import jax.numpy as jnp

        cov = np.array([[1.0, 0.95], [0.95, 1.0]])
        Li = np.linalg.cholesky(np.linalg.inv(cov))

        def logp(theta):
            y = jnp.asarray(Li).T @ theta
            return -0.5 * jnp.sum(y * y)

        init = 0.1 * jax.random.normal(jax.random.PRNGKey(2), (4, 2))
        run = run_nuts(
            jax.random.PRNGKey(3), logp, init, n_steps=288, n_warmup=160,
            mass_matrix="dense",
        )
        s = np.asarray(run.chain).reshape(-1, 2)
        assert abs(np.corrcoef(s.T)[0, 1] - 0.95) < 0.07
        assert run.inv_mass.shape == (2, 2)
        # the dense metric learned the off-diagonal structure
        assert run.inv_mass[0, 1] > 0.3
        # ... which makes the sampler nearly iid: bulk ESS per draw
        # stays a healthy fraction of the draw count
        assert float(np.min(bulk_ess(np.asarray(run.chain)))) > 0.2 * s.shape[0]

    def test_free_particle_never_uturns(self):
        """Review regression: on a FLAT log-density every trajectory is
        a straight line, so a correct no-U-turn criterion never fires
        and every draw must exhaust the depth cap.  The original
        within-subtree checkpoint check evaluated the displacement in
        ITERATION order, which is time-reversed in backward subtrees —
        sign-inverting the criterion there (spurious stops on straight
        flow, mean depth ~3 at cap 6)."""
        import jax
        import jax.numpy as jnp

        def logp(theta):
            return jnp.zeros(()) * jnp.sum(theta)   # flat, grad 0

        init = np.zeros((4, 2))
        run = run_nuts(
            jax.random.PRNGKey(7), logp, init, n_steps=32, n_warmup=0,
            step_size=0.1, inv_mass=np.ones(2), max_tree_depth=6,
        )
        assert run.mean_tree_depth == 6.0
        assert run.n_divergent == 0

    def test_deterministic_given_step_and_mass(self):
        import jax
        import jax.numpy as jnp

        def logp(theta):
            return -0.5 * jnp.sum(theta * theta)

        init = 0.1 * np.asarray(
            jax.random.normal(jax.random.PRNGKey(5), (3, 2))
        )
        kw = dict(n_steps=60, n_warmup=0, step_size=0.8,
                  inv_mass=np.ones(2))
        a = run_nuts(jax.random.PRNGKey(9), logp, init, **kw)
        b = run_nuts(jax.random.PRNGKey(9), logp, init, **kw)
        assert np.array_equal(np.asarray(a.chain), np.asarray(b.chain))

    def test_validation(self):
        import jax.numpy as jnp

        def logp(theta):
            return -0.5 * jnp.sum(theta * theta)

        init = np.zeros((2, 2))
        with pytest.raises(ValueError, match="mass_matrix"):
            run_nuts(0, logp, init, 10, mass_matrix="full")
        with pytest.raises(ValueError, match="target_accept"):
            run_nuts(0, logp, init, 10, target_accept=1.2)
        with pytest.raises(ValueError, match="both step_size"):
            run_nuts(0, logp, init, 10, step_size=0.1)
        with pytest.raises(ValueError, match="n_warmup"):
            run_nuts(0, logp, init, 10, step_size=0.1,
                     inv_mass=np.ones(2), n_warmup=50)
        with pytest.raises(ValueError, match="thin"):
            run_nuts(0, logp, init, 11, thin=2)
        with pytest.raises(ValueError, match="finite"):
            run_nuts(
                0, lambda t: jnp.asarray(-jnp.inf), init, 10,
            )


class TestBulkDiagnostics:
    """The in-repo instruments the nuts_ess_per_eval bench claim is
    computed with: rank-normalized bulk ESS and split-R̂ on synthetic
    AR(1) chains of KNOWN effective sample size."""

    def _ar1(self, phi, n=4000, m=8, seed=0):
        rng = np.random.default_rng(seed)
        x = np.zeros((n, m))
        e = rng.standard_normal((n, m))
        for t in range(1, n):
            x[t] = phi * x[t - 1] + np.sqrt(1 - phi * phi) * e[t]
        return x[:, :, None]

    @pytest.mark.parametrize("phi", [0.0, 0.5, 0.9])
    def test_bulk_ess_matches_ar1_theory(self, phi):
        chain = self._ar1(phi)
        n, m, _ = chain.shape
        want = n * m * (1 - phi) / (1 + phi)   # ESS = N/τ, τ=(1+φ)/(1−φ)
        got = float(bulk_ess(chain)[0])
        assert 0.75 * want <= got <= 1.35 * want, (phi, got, want)

    def test_bulk_ess_per_parameter(self):
        chain = np.concatenate(
            [self._ar1(0.0, seed=1), self._ar1(0.9, seed=2)], axis=2
        )
        ess = bulk_ess(chain)
        assert ess.shape == (2,)
        assert ess[0] > 3.0 * ess[1]

    def test_rank_rhat_converged_vs_diverged(self):
        conv = self._ar1(0.3, n=500, m=8, seed=3)
        r = rank_normalized_split_rhat(conv)[0]
        assert r < 1.05
        rng = np.random.default_rng(4)
        div = np.concatenate([
            rng.standard_normal((500, 4)),
            5.0 + rng.standard_normal((500, 4)),
        ], axis=1)[:, :, None]
        assert rank_normalized_split_rhat(div)[0] > 1.3

    def test_bulk_ess_validation(self):
        with pytest.raises(ValueError, match="n_steps, W, D"):
            bulk_ess(np.zeros((10, 4)))
        with pytest.raises(ValueError, match="8 steps"):
            bulk_ess(np.zeros((4, 4, 1)))


class TestNUTSCheckpoint:
    def _logp(self):
        import jax.numpy as jnp

        def logp(theta):
            return -0.5 * jnp.sum((theta - 1.0) ** 2)

        return logp

    def _init(self, C=3):
        import jax

        return 1.0 + 0.1 * np.asarray(
            jax.random.normal(jax.random.PRNGKey(3), (C, 2))
        )

    def test_resume_is_bitwise_identical(self, tmp_path, jit_warmup):
        """An interrupted NUTS run resumes bitwise: the adapted (ε,
        mass) and positions ride the segment files, and segment keys
        are fold_in-derived — the stretch contract, inherited.  Doubles
        as the fresh-run segment-layout pin (one NUTS warmup per test
        is seconds of compile — tier-1 pays it once here)."""
        from bdlz_tpu.sampling import run_ensemble_checkpointed

        kw = dict(
            n_steps=20, checkpoint_every=10, identity={"c": 1},
            sampler="nuts", sampler_opts={"n_warmup": 24},
        )
        full = run_ensemble_checkpointed(
            5, self._logp(), self._init(),
            out_dir=str(tmp_path / "full"), **kw,
        )
        # fresh-run contract: NUTS provenance on the result AND in the
        # segment files (stretch byte-layout plus the nuts_* keys)
        assert full.sampler == "nuts"
        assert full.chain.shape == (20, 3, 2)
        assert full.segments == 2 and full.resumed_segments == 0
        assert full.step_size is not None and full.step_size > 0
        assert full.inv_mass.shape == (2,)
        assert full.n_logp_evals > 0
        import os

        seg0 = np.load(os.path.join(str(tmp_path / "full"), "seg_00000.npz"))
        assert "nuts_step_size" in seg0.files
        assert "nuts_inv_mass" in seg0.files
        # interrupted twin: run only the first segment's worth by
        # pointing a fresh run at a directory pre-seeded with it
        import shutil

        part = str(tmp_path / "part")
        shutil.copytree(str(tmp_path / "full"), part)
        import os

        os.remove(os.path.join(part, "seg_00001.npz"))
        with open(os.path.join(part, "manifest.json")) as f:
            manifest = json.load(f)
        manifest["done"] = [0]
        with open(os.path.join(part, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        resumed = run_ensemble_checkpointed(
            5, self._logp(), self._init(), out_dir=part, **kw,
        )
        assert resumed.resumed_segments == 1
        assert np.array_equal(resumed.chain, full.chain)
        assert resumed.step_size == full.step_size
        assert np.array_equal(resumed.inv_mass, full.inv_mass)
        assert resumed.n_logp_evals == full.n_logp_evals
        assert resumed.n_divergent == full.n_divergent

    @pytest.mark.slow
    def test_sampler_flip_invalidates_resume(self, tmp_path, capsys):
        # slow: the digest split a sampler/knob flip causes is pinned
        # cheaply in test_config (test_sampler_home_is_checkpoint_
        # identity); this is the directory-level integration twin
        from bdlz_tpu.sampling import run_ensemble_checkpointed

        out = str(tmp_path / "chain")
        run_ensemble_checkpointed(
            5, self._logp(), self._init(8), n_steps=10, out_dir=out,
            checkpoint_every=10, identity={"c": 1},
        )
        r = run_ensemble_checkpointed(
            5, self._logp(), self._init(8), n_steps=10, out_dir=out,
            checkpoint_every=10, identity={"c": 1}, sampler="nuts",
            sampler_opts={"n_warmup": 16},
        )
        assert r.resumed_segments == 0   # stretch chain never spliced
        # (a NUTS-KNOB flip splits the digest too — pinned cheaply in
        # tests/test_config.py::test_sampler_home_is_checkpoint_identity)

    def test_stretch_opts_rejected(self, tmp_path):
        from bdlz_tpu.sampling import run_ensemble_checkpointed

        with pytest.raises(ValueError, match="sampler_opts"):
            run_ensemble_checkpointed(
                5, self._logp(), self._init(), n_steps=20,
                out_dir=str(tmp_path / "x"), checkpoint_every=10,
                sampler_opts={"n_warmup": 30},
            )
        with pytest.raises(ValueError, match="unknown NUTS"):
            run_ensemble_checkpointed(
                5, self._logp(), self._init(), n_steps=20,
                out_dir=str(tmp_path / "y"), checkpoint_every=10,
                sampler="nuts", sampler_opts={"step": 0.1},
            )


class TestMcmcCliSampler:
    def _cfg(self, tmp_path):
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps(BENCH_OVER))
        return str(cfg)

    def _run(self, argv, capsys):
        from bdlz_tpu.mcmc_cli import main as mcmc_main

        mcmc_main(argv)
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    def test_nuts_end_to_end(self, tmp_path, capsys):
        s = self._run([
            "--config", self._cfg(tmp_path),
            "--param", "m_chi_GeV=0.5:2", "--param", "P_chi_to_B=0.01:0.9",
            "--walkers", "4", "--steps", "10", "--burn", "2",
            "--sampler", "nuts", "--nuts-warmup", "24",
        ], capsys)
        assert s["sampler"] == "nuts"
        assert s["walkers"] == 4                  # chains, not rounded up
        assert s["nuts"]["mass_matrix"] == "diag"
        assert s["nuts"]["step_size"] > 0
        assert s["nuts"]["n_logp_evals"] > 10 * 4
        assert "mean_tree_depth" in s["nuts"]
        assert np.isfinite(s["map_logp"])
        assert set(s["tau_int"]) == {"m_chi_GeV", "P_chi_to_B"}

    @pytest.mark.slow
    def test_nuts_checkpoint_resume(self, tmp_path, capsys):
        # slow: the resume contract itself is pinned bitwise (and
        # cheaper) at the library level in TestNUTSCheckpoint; this is
        # the CLI-wiring integration twin
        argv = [
            "--config", self._cfg(tmp_path),
            "--param", "m_chi_GeV=0.5:2", "--param", "P_chi_to_B=0.01:0.9",
            "--walkers", "4", "--steps", "8", "--burn", "2",
            "--sampler", "nuts", "--nuts-warmup", "24",
            "--checkpoint-dir", str(tmp_path / "ckpt"),
            "--checkpoint-every", "4",
        ]
        s1 = self._run(argv, capsys)
        assert s1["resumed_segments"] == 0
        s2 = self._run(argv, capsys)
        assert s2["resumed_segments"] == 2
        assert s2["posterior_mean"] == s1["posterior_mean"]

    @pytest.mark.slow
    def test_config_knob_selects_sampler(self, tmp_path, capsys):
        # slow: the resolution branch itself (flags > config > default)
        # is three lines; the flag path runs in tier-1 via the e2e test
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps(dict(
            BENCH_OVER, sampler="nuts", mass_matrix="dense",
            target_accept=0.85,
        )))
        s = self._run([
            "--config", str(cfg), "--param", "m_chi_GeV=0.5:2",
            "--walkers", "3", "--steps", "8", "--burn", "2",
            "--nuts-warmup", "16",
        ], capsys)
        assert s["sampler"] == "nuts"
        assert s["nuts"]["mass_matrix"] == "dense"

    def test_nuts_knobs_rejected_with_stretch(self, tmp_path):
        from bdlz_tpu.mcmc_cli import main as mcmc_main

        with pytest.raises(SystemExit, match="stretch"):
            mcmc_main([
                "--config", self._cfg(tmp_path),
                "--param", "m_chi_GeV=0.5:2",
                "--walkers", "8", "--steps", "8", "--burn", "2",
                "--mass-matrix", "dense",
            ])
        with pytest.raises(SystemExit, match="target-accept"):
            mcmc_main([
                "--config", self._cfg(tmp_path),
                "--param", "m_chi_GeV=0.5:2",
                "--walkers", "8", "--steps", "8", "--burn", "2",
                "--sampler", "nuts", "--target-accept", "1.5",
            ])
