"""Lane-repacking batched ESDIRK engine (solvers/batching.py).

The engine's contract has two halves, both pinned here:

* with the acceleration knobs OFF it is a pure EXECUTION-ORDER
  transformation — every lane's step sequence, counters, and final state
  are bit-identical to the lockstep vmapped engine, regardless of round
  budget, batch composition, or input lane order;
* with the knobs ON (its defaults) it stays inside the stiff path's
  accuracy contract versus the lockstep engine while retiring lanes
  monotonically (the compaction stats are the evidence surface).
"""
import dataclasses

import numpy as np
import pytest

from bdlz_tpu.config import (
    config_from_dict,
    static_choices_from_config,
)
from bdlz_tpu.parallel.sweep import build_grid
from bdlz_tpu.utils.profiling import CompactionStats


def bench_cfg(**over):
    base = {
        "regime": "nonthermal",
        "P_chi_to_B": 0.14925839040304145,
        "source_shape_sigma_y": 9.0,
        "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.90e-10,
    }
    base.update(over)
    return config_from_dict(base)


def mixed_grid(n_side: int):
    """A mixed-stiffness flat grid: washout strength and pulse width both
    spread over their interesting ranges so per-lane step counts diverge
    (which is what makes repacking non-trivial)."""
    cfg = dataclasses.replace(
        bench_cfg(), Gamma_wash_over_H=0.01, T_min_over_Tp=0.1
    )
    axes = {
        "m_chi_GeV": np.geomspace(0.3, 3.0, n_side).tolist(),
        "Gamma_wash_over_H": np.geomspace(1e-3, 0.5, n_side).tolist(),
        "source_shape_sigma_y": [3.0, 15.0],
    }
    return cfg, build_grid(cfg, axes)


def lockstep_solve(pp, static):
    """The reference: jit(vmap(solve_boltzmann_esdirk)) — the legacy
    lockstep strategy, bit-pinned by the golden/Radau battery."""
    import jax
    import jax.numpy as jnp

    from bdlz_tpu.physics.percolation import make_kjma_grid
    from bdlz_tpu.solvers.sdirk import solve_boltzmann_esdirk

    grid = make_kjma_grid(jnp)

    def one(pp_i):
        T_hi = pp_i.T_max_over_Tp * pp_i.T_p_GeV
        T_lo = pp_i.T_min_over_Tp * pp_i.T_p_GeV
        return solve_boltzmann_esdirk(
            pp_i, static, grid, (pp_i.Y_chi_init, 0.0), T_lo, T_hi
        )

    ppj = jax.tree.map(jnp.asarray, pp)
    return jax.jit(jax.vmap(one))(ppj)


def repacked_solve(pp, static, **kw):
    import jax.numpy as jnp

    from bdlz_tpu.physics.percolation import make_kjma_grid
    from bdlz_tpu.solvers.batching import solve_boltzmann_esdirk_batch

    return solve_boltzmann_esdirk_batch(
        pp, static, make_kjma_grid(jnp), **kw
    )


KNOBS_OFF = dict(
    ode_auto_h0=False, ode_pi_controller=False, ode_tabulated_av=False
)


class TestBitParityWithLockstep:
    def _assert_bit_identical(self, pp, static, round_steps):
        ref = lockstep_solve(pp, static)
        stats = CompactionStats()
        sol = repacked_solve(
            pp, static, round_steps=round_steps, stats=stats
        )
        np.testing.assert_array_equal(np.asarray(sol.y), np.asarray(ref.y))
        np.testing.assert_array_equal(
            np.asarray(sol.n_steps), np.asarray(ref.n_steps)
        )
        np.testing.assert_array_equal(
            np.asarray(sol.n_accepted), np.asarray(ref.n_accepted)
        )
        np.testing.assert_array_equal(
            np.asarray(sol.n_rejected), np.asarray(ref.n_rejected)
        )
        np.testing.assert_array_equal(
            np.asarray(sol.success), np.asarray(ref.success)
        )
        return stats

    def test_bit_identical_small_mixed_batch(self):
        """Knobs off, small budget (forces several pause/compact/resume
        cycles): per-lane bits match the lockstep engine exactly."""
        cfg, pp = mixed_grid(2)  # 8 lanes
        static = static_choices_from_config(cfg)._replace(**KNOBS_OFF)
        stats = self._assert_bit_identical(pp, static, round_steps=48)
        assert stats.n_rounds > 1  # the pause/resume path actually ran

    @pytest.mark.slow
    def test_bit_identical_32_lane_mixed_batch(self):
        """The full 32-lane mixed-stiffness case (slow: the lockstep
        reference pays the exact-kernel z-integral on every lane)."""
        cfg, pp = mixed_grid(4)  # 32 lanes
        static = static_choices_from_config(cfg)._replace(**KNOBS_OFF)
        stats = self._assert_bit_identical(pp, static, round_steps=40)
        assert stats.n_rounds > 1

    def test_lane_order_independence(self):
        """Shuffling the input lanes permutes the outputs and nothing
        else — the stiffness-proxy sort and the unsort are exact
        inverses, and vmapped lanes do not interact."""
        cfg, pp = mixed_grid(2)
        static = static_choices_from_config(cfg)._replace(**KNOBS_OFF)
        sol = repacked_solve(pp, static, round_steps=48)
        rng = np.random.default_rng(11)
        perm = rng.permutation(8)
        pp_shuf = type(pp)(*(np.asarray(f)[perm] for f in pp))
        sol_shuf = repacked_solve(pp_shuf, static, round_steps=48)
        np.testing.assert_array_equal(
            np.asarray(sol_shuf.y), np.asarray(sol.y)[perm]
        )
        np.testing.assert_array_equal(
            np.asarray(sol_shuf.n_steps), np.asarray(sol.n_steps)[perm]
        )


class TestRoundsAndRetirement:
    def test_retires_monotonically(self):
        """Active lane counts never increase across rounds, every lane
        retires exactly once, and the recorded accept/reject counters
        reconcile with the solution's totals."""
        cfg, pp = mixed_grid(2)
        static = static_choices_from_config(cfg)
        stats = CompactionStats()
        sol = repacked_solve(pp, static, round_steps=32, stats=stats)
        active = [r.active_lanes for r in stats.rounds]
        assert all(a >= b for a, b in zip(active, active[1:]))
        assert sum(r.lanes_retired for r in stats.rounds) == 8
        assert sum(r.steps_accepted for r in stats.rounds) == int(
            np.asarray(sol.n_accepted).sum()
        )
        assert sum(r.steps_rejected for r in stats.rounds) == int(
            np.asarray(sol.n_rejected).sum()
        )
        assert all(r.seconds >= 0.0 for r in stats.rounds)
        s = stats.summary()
        assert 0.0 <= s["pad_waste"] < 1.0

    def test_all_lanes_converge_in_round_one(self):
        """A budget larger than any lane's step count: exactly one round,
        everyone retires in it."""
        cfg, pp = mixed_grid(2)
        static = static_choices_from_config(cfg)
        stats = CompactionStats()
        sol = repacked_solve(pp, static, round_steps=100_000, stats=stats)
        assert stats.n_rounds == 1
        assert stats.rounds[0].lanes_retired == 8
        assert bool(np.asarray(sol.success).all())

    def test_no_lane_converges(self):
        """max_steps below any lane's need: every lane exhausts its
        budget, reports failure (not NaN, not a hang), and the round loop
        terminates after ceil(max_steps/round_steps) rounds."""
        cfg, pp = mixed_grid(2)
        static = static_choices_from_config(cfg)
        stats = CompactionStats()
        sol = repacked_solve(
            pp, static, round_steps=10, max_steps=25, stats=stats
        )
        assert not bool(np.asarray(sol.success).any())
        np.testing.assert_array_equal(np.asarray(sol.n_steps), 25)
        assert stats.n_rounds == 3  # 10 + 10 + 5
        # no lane "retires" by converging, but all leave the active set
        assert sum(r.lanes_retired for r in stats.rounds) == 8


class TestAcceleratedDefaults:
    def test_accelerated_engine_stays_in_contract(self):
        """The engine's default knobs (auto-h0 + PI + tabulated A/V) move
        results by ~1e-8 on the washout grid — well inside the stiff
        path's 1e-6 contract vs the Radau-pinned lockstep engine."""
        cfg, pp = mixed_grid(2)
        static = static_choices_from_config(cfg)
        ref = lockstep_solve(pp, static)
        sol = repacked_solve(pp, static)
        ok = np.asarray(ref.success) & np.asarray(sol.success)
        assert ok.all()
        YB_r, YB_s = np.asarray(ref.y)[:, 1], np.asarray(sol.y)[:, 1]
        assert np.max(np.abs(YB_s / YB_r - 1.0)) < 1e-6
        Yc_r, Yc_s = np.asarray(ref.y)[:, 0], np.asarray(sol.y)[:, 0]
        assert np.max(np.abs(Yc_s / Yc_r - 1.0)) < 1e-6

    def test_mixed_ip_batch_falls_back_to_exact_kernel(self):
        """The F(y) table is per-I_p: a batch sweeping I_p silently runs
        the exact-kernel RHS instead (resolution is per-batch, and the
        knob resolution is what the sweep folds into its resume hash)."""
        from bdlz_tpu.solvers.batching import resolve_engine_knobs

        cfg, pp = mixed_grid(2)
        static = static_choices_from_config(cfg)
        assert resolve_engine_knobs(static, np.asarray(pp.I_p)) == {
            "auto_h0": True, "pi_controller": True, "tabulated_av": True,
        }
        ip_mixed = np.asarray(pp.I_p).copy()
        ip_mixed[0] = 0.5
        assert resolve_engine_knobs(static, ip_mixed)["tabulated_av"] is False
        # explicit config override beats the engine default
        static_off = static._replace(ode_tabulated_av=False)
        assert resolve_engine_knobs(
            static_off, np.asarray(pp.I_p)
        )["tabulated_av"] is False
        # and the mixed-I_p batch still solves correctly end to end
        pp_mixed = pp._replace(I_p=ip_mixed)
        sol = repacked_solve(pp_mixed, static)
        ref = lockstep_solve(pp_mixed, static._replace(**KNOBS_OFF))
        assert bool(np.asarray(sol.success).all())
        rel = np.abs(
            np.asarray(sol.y)[:, 1] / np.asarray(ref.y)[:, 1] - 1.0
        )
        assert np.max(rel) < 1e-6


class TestSweepIntegration:
    def test_sweep_default_is_repacked_and_matches_engine(self):
        """run_sweep's stiff default (impl='esdirk') reproduces a direct
        batch-engine solve.  The sweep layer adds chunk padding and mesh
        sharding; a sharded one-lane-per-device dispatch was measured to
        re-tile the z-integral's trapezoid reduction and shift results by
        ~1 ulp (6e-14 rel), so the cross-EXECUTION-SHAPE comparison is
        pinned at 1e-12 — the strict bitwise contract lives in
        TestBitParityWithLockstep, where both engines run the same
        shape."""
        from bdlz_tpu.models.yields_pipeline import present_day
        from bdlz_tpu.parallel import make_mesh, run_sweep

        cfg = dataclasses.replace(
            bench_cfg(), Gamma_wash_over_H=0.05, T_min_over_Tp=0.2
        )
        static = static_choices_from_config(cfg)
        axes = {"m_chi_GeV": [0.5, 0.95, 1.4]}
        mesh = make_mesh(shape=(4, 2))
        res = run_sweep(cfg, axes, static, mesh=mesh, chunk_size=8)
        assert res.n_failed == 0
        pp = build_grid(cfg, axes)
        sol = repacked_solve(pp, static)
        ref = present_day(
            np.asarray(sol.y)[:, 1], np.asarray(sol.y)[:, 0],
            np.asarray(pp.m_chi_GeV), np.asarray(pp.m_B_kg), np,
        )
        np.testing.assert_allclose(res.outputs["Y_B"], ref.Y_B, rtol=1e-12)
        np.testing.assert_allclose(
            res.outputs["DM_over_B"], ref.DM_over_B, rtol=1e-12
        )

    def test_lockstep_strategy_still_selectable(self):
        """impl='esdirk_lockstep' stays available for A/B and reproduces
        the repacked engine within the contract."""
        from bdlz_tpu.parallel import make_mesh, run_sweep

        cfg = dataclasses.replace(
            bench_cfg(), Gamma_wash_over_H=0.05, T_min_over_Tp=0.2
        )
        static = static_choices_from_config(cfg)
        axes = {"m_chi_GeV": [0.5, 0.95]}
        mesh = make_mesh(shape=(4, 2))
        res_new = run_sweep(cfg, axes, static, mesh=mesh, chunk_size=8)
        res_old = run_sweep(
            cfg, axes, static, mesh=mesh, chunk_size=8,
            impl="esdirk_lockstep",
        )
        np.testing.assert_allclose(
            res_new.outputs["Y_B"], res_old.outputs["Y_B"], rtol=1e-6
        )

    def test_esdirk_resume_hash_pins_resolved_knobs(self, tmp_path):
        """A directory computed at one knob resolution must not resume
        under another: flipping a tri-state knob changes the manifest
        hash, so the sweep recomputes from scratch."""
        from bdlz_tpu.parallel import make_mesh, run_sweep

        cfg = dataclasses.replace(
            bench_cfg(), Gamma_wash_over_H=0.05, T_min_over_Tp=0.2
        )
        axes = {"m_chi_GeV": [0.5, 0.95]}
        mesh = make_mesh(shape=(4, 2))
        out = str(tmp_path / "sweep")
        static = static_choices_from_config(cfg)
        run_sweep(cfg, axes, static, mesh=mesh, chunk_size=8, out_dir=out)
        r_same = run_sweep(
            cfg, axes, static, mesh=mesh, chunk_size=8, out_dir=out
        )
        assert r_same.resumed_chunks == 1
        r_flip = run_sweep(
            cfg, axes, static._replace(ode_pi_controller=False),
            mesh=mesh, chunk_size=8, out_dir=out,
        )
        assert r_flip.resumed_chunks == 0

    def test_chunk_boundaries_never_flip_the_rhs_kernel(self):
        """A stiff sweep over an I_p axis resolves tabulated_av=False at
        the SWEEP level: chunks that happen to land inside one I_p block
        (here every chunk, at chunk_size=2 on an I_p-slowest grid) must
        NOT silently upgrade to the F-table RHS — results are identical
        whether chunk boundaries align with I_p blocks or not (review
        finding r6: per-chunk knob resolution keyed numerics on
        chunk_size, which the resume hash does not include)."""
        from bdlz_tpu.parallel import run_sweep

        cfg = dataclasses.replace(
            bench_cfg(), Gamma_wash_over_H=0.05, T_min_over_Tp=0.2
        )
        static = static_choices_from_config(cfg)
        # I_p varies slowest: chunk_size=2 puts each chunk inside one
        # I_p block (uniform), chunk_size=4 spans both blocks (mixed)
        axes = {"I_p": [0.3, 0.34], "m_chi_GeV": [0.5, 0.95]}
        res_aligned = run_sweep(cfg, axes, static, chunk_size=2)
        res_mixed = run_sweep(cfg, axes, static, chunk_size=4)
        assert res_aligned.n_failed == res_mixed.n_failed == 0
        np.testing.assert_array_equal(
            res_aligned.outputs["Y_B"], res_mixed.outputs["Y_B"]
        )

    def test_event_log_carries_compaction_rounds(self, tmp_path):
        """The per-round compaction stats surface through the sweep's
        event log (one esdirk_rounds event per chunk)."""
        from bdlz_tpu.parallel import make_mesh, run_sweep
        from bdlz_tpu.utils.logging import EventLog

        cfg = dataclasses.replace(
            bench_cfg(), Gamma_wash_over_H=0.05, T_min_over_Tp=0.2
        )
        static = static_choices_from_config(cfg)
        mesh = make_mesh(shape=(4, 2))
        log_path = tmp_path / "events.jsonl"
        ev = EventLog(path=str(log_path))
        run_sweep(
            cfg, {"m_chi_GeV": [0.5, 0.95]}, static, mesh=mesh,
            chunk_size=8, event_log=ev,
        )
        ev.close()
        import json

        events = [json.loads(ln) for ln in log_path.read_text().splitlines()]
        rounds = [e for e in events if e["event"] == "esdirk_rounds"]
        assert len(rounds) == 1
        # the chunk is padded to chunk_size, so the engine retires the
        # padding lanes too — 8, not 2
        assert rounds[0]["lanes_retired"] == 8
        assert rounds[0]["rounds"] >= 1
        assert isinstance(rounds[0]["per_round"], list)
