"""Native C++ CSV parser tests (builds on demand; skips without a toolchain)."""
import numpy as np
import pytest

from bdlz_tpu.native import NativeParseError, native_available, read_csv_native

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


def test_parse_matches_numpy(tmp_path):
    rng = np.random.default_rng(3)
    data = rng.normal(size=(500, 4))
    p = tmp_path / "prof.csv"
    header = "xi,m11,m22,m12"
    np.savetxt(p, data, delimiter=",", header=header, comments="")
    names, table = read_csv_native(str(p))
    assert names == header.split(",")
    np.testing.assert_allclose(table, data, rtol=1e-15)


def test_scientific_notation_and_blank_lines(tmp_path):
    p = tmp_path / "prof.csv"
    p.write_text("xi,delta,m_mix\n-1e-3,2.5E+2,0.1\n\n4,-5e-1,0.2\n")
    names, table = read_csv_native(str(p))
    np.testing.assert_allclose(table, [[-1e-3, 2.5e2, 0.1], [4.0, -0.5, 0.2]])


def test_malformed_row_rejected(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("a,b\n1.0,2.0\n3.0\n")
    with pytest.raises(NativeParseError, match="malformed"):
        read_csv_native(str(p))


def test_non_numeric_cell_rejected(tmp_path):
    p = tmp_path / "bad2.csv"
    p.write_text("a,b\n1.0,spam\n")
    with pytest.raises(NativeParseError, match="malformed"):
        read_csv_native(str(p))


def test_missing_file():
    with pytest.raises(NativeParseError, match="could not open"):
        read_csv_native("/nonexistent/x.csv")


def test_profile_loader_uses_native_consistently(tmp_path):
    """lz.load_profile_csv must give identical profiles through both
    engines (the native fast path and the NumPy fallback)."""
    from bdlz_tpu.lz import load_profile_csv
    from bdlz_tpu.lz import profile as profile_mod

    p = tmp_path / "prof.csv"
    xi = np.linspace(-5, 5, 101)
    np.savetxt(
        p,
        np.column_stack([xi, xi * 2, np.full_like(xi, 0.3)]),
        delimiter=",", header="xi,delta,m_mix", comments="",
    )
    native = load_profile_csv(str(p))

    # force the numpy fallback
    orig = profile_mod._read_csv
    try:
        def numpy_only(path):
            data = np.genfromtxt(path, delimiter=",", names=True, dtype=float)
            names = list(data.dtype.names)
            table = np.column_stack(
                [np.atleast_1d(np.asarray(data[n], float)) for n in names]
            )
            return names, table

        profile_mod._read_csv = numpy_only
        fallback = load_profile_csv(str(p))
    finally:
        profile_mod._read_csv = orig

    np.testing.assert_array_equal(native.xi, fallback.xi)
    np.testing.assert_array_equal(native.delta, fallback.delta)
    np.testing.assert_array_equal(native.mix, fallback.mix)
