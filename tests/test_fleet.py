"""Sharded-fleet tests (bdlz_tpu/serve/fleet.py + rollout.py).

Same testability contract as the batcher suite: every policy decision
(admission, deadline shedding, dispatch readiness, rollout cutover) is
driven with a FAKE CLOCK and explicit run_once/poll calls — zero sleeps,
zero background threads.  Device work is real (the conftest 8-virtual-
device CPU mesh), but only its RESULTS are asserted (bit-parity,
hashes), never its timing.

Most tests ride a synthetic artifact (valid identity, fabricated
positive table) instead of the session emulator build: the fleet layer
only interpolates — the correct-physics pins live in test_serve /
test_emulator — and the fabricated table makes N vs N+1 rollout
artifacts cheap to construct.
"""
import dataclasses
import json

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, static_choices_from_config
from bdlz_tpu.emulator.artifact import (
    EmulatorArtifact,
    EmulatorArtifactError,
    build_identity,
)
from bdlz_tpu.serve import (
    ArtifactRollout,
    DeadlineExceeded,
    FleetService,
    QueueFull,
    ReplicaSet,
    RolloutError,
)
from bdlz_tpu.utils.profiling import ServeStats


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


BASE = config_from_dict({
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
})
STATIC = static_choices_from_config(BASE)._replace(quad_panel_gl=False)
AXES = ("m_chi_GeV", "T_p_GeV", "v_w")
NODES = (
    np.linspace(0.9, 1.1, 4),
    np.geomspace(90.0, 110.0, 5),
    np.linspace(0.25, 0.35, 3),
)
LO = np.array([n[0] for n in NODES])
HI = np.array([n[-1] for n in NODES])


def _make_artifact(scale=1.0, base=BASE):
    """A valid-identity artifact with a fabricated positive table.

    ``scale`` multiplies the values — the N+1 rollout artifact: same
    identity (same physics), different content hash.
    """
    rng = np.random.default_rng(42)
    vals = np.exp(rng.normal(size=(4, 5, 3))) * scale
    return EmulatorArtifact(
        axis_names=AXES,
        axis_nodes=NODES,
        axis_scales=("log", "log", "lin"),
        values={"DM_over_B": vals},
        identity=build_identity(base, STATIC, 400, "tabulated"),
        manifest={},
    )


def _thetas(n, seed=0):
    return np.random.default_rng(seed).uniform(LO, HI, size=(n, 3))


def _fleet(artifact=None, clock=None, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_wait_s", 0.010)
    kw.setdefault("n_replicas", 4)
    return FleetService(
        artifact if artifact is not None else _make_artifact(),
        BASE, static=STATIC, clock=clock or FakeClock(), **kw,
    )


class TestReplicaSet:
    def test_multi_vs_single_replica_bit_parity(self):
        """The acceptance contract: the SAME request stream through 1
        replica and 4 replicas returns BIT-identical values — scaling
        must never buy a different answer."""
        art = _make_artifact()
        rs1 = ReplicaSet(art, n_replicas=1, max_batch_size=16)
        rs4 = ReplicaSet(art, n_replicas=4, max_batch_size=16)
        assert rs4.n_devices == 4  # conftest pins an 8-device CPU mesh
        thetas = _thetas(128)

        def stream(rs):
            handles = [rs.dispatch(thetas[i:i + 16])
                       for i in range(0, 128, 16)]
            return np.concatenate([h.gather()[0] for h in handles])

        v1, v4 = stream(rs1), stream(rs4)
        assert np.array_equal(v1, v4)  # bitwise, not allclose
        assert np.isfinite(v1).all()

    def test_warm_start_precompiles_and_records_seconds(self):
        """Satellite pin: kernels compile at LOAD (per device, at the
        bucket shape), the seconds land in ServeStats, and warming is
        idempotent."""
        stats = ServeStats()
        rs = ReplicaSet(_make_artifact(), n_replicas=2, max_batch_size=8,
                        stats=stats)
        assert rs.warmed
        assert rs.warmup_seconds > 0.0
        assert stats.summary()["warmup_seconds"] == pytest.approx(
            rs.warmup_seconds, abs=1e-3
        )
        assert rs.warm() == 0.0  # idempotent: no second compile pass

    def test_round_robin_rotation_and_least_loaded_pick(self):
        art = _make_artifact()
        rr = ReplicaSet(art, n_replicas=3, max_batch_size=4,
                        routing="round_robin")
        picked = [rr.dispatch(_thetas(4)).replica.index for _ in range(6)]
        assert picked == [0, 1, 2, 0, 1, 2]

        ll = ReplicaSet(art, n_replicas=3, max_batch_size=4,
                        routing="least_loaded")
        h0 = ll.dispatch(_thetas(4))
        h1 = ll.dispatch(_thetas(4))
        # two in flight on 0 and 1 → next goes to the idle replica 2
        assert (h0.replica.index, h1.replica.index) == (0, 1)
        assert ll.pick().index == 2
        # gathering replica 0 frees its slot → ties break to lowest index
        h0.gather()
        assert ll.pick().index == 0

    def test_validation(self):
        art = _make_artifact()
        with pytest.raises(ValueError, match="routing"):
            ReplicaSet(art, routing="random")
        with pytest.raises(ValueError, match="n_replicas"):
            ReplicaSet(art, n_replicas=0)
        with pytest.raises(KeyError, match="field"):
            ReplicaSet(art, field="bogus")
        rs = ReplicaSet(art, n_replicas=1, max_batch_size=4)
        with pytest.raises(ValueError, match="exceeds max_batch_size"):
            rs.dispatch(_thetas(5))
        with pytest.raises(ValueError, match="coordinates"):
            rs.dispatch(np.zeros((2, 2)))


class TestAdmissionAndShedding:
    def test_sustained_load_admission_deterministic(self):
        """The satellite's sustained-load pin: beyond queue_bound every
        submit rejects with the typed QueueFull, the shed rate is a pure
        function of the trace, and the accepted requests all serve."""
        clock = FakeClock()
        svc = _fleet(clock=clock, queue_bound=8)
        futs, rejects = [], 0
        for i in range(20):  # burst with no dispatch between: 8 fit
            try:
                futs.append(svc.submit(_thetas(20)[i]))
            except QueueFull:
                rejects += 1
        assert len(futs) == 8 and rejects == 12
        svc.drain()
        s = svc.stats.summary()
        assert s["accepted"] == 8
        assert s["admission_rejects"] == 12
        assert s["shed_rate"] == pytest.approx(12 / 20)
        assert all(np.isfinite(f.result(timeout=0).value) for f in futs)

    def test_deadline_shed_prefix_then_serve(self):
        clock = FakeClock()
        svc = _fleet(clock=clock, deadline_s=0.05)
        stale = [svc.submit(t) for t in _thetas(3)]
        clock.advance(0.06)
        live = [svc.submit(t) for t in _thetas(4, seed=1)]  # a full batch
        assert svc.run_once() == 7  # 3 killed + 4 dispatched in ONE pass
        for f in stale:
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=0)
        assert svc.poll(block=True) == 4
        assert all(np.isfinite(f.result(timeout=0).value) for f in live)
        s = svc.stats.summary()
        assert s["deadline_kills"] == 3
        assert s["batches"] == 1 and s["requests"] == 4
        # shed accounting: 3 of 7 offered were shed
        assert s["shed_rate"] == pytest.approx(3 / 7, abs=1e-4)

    def test_policy_pure_in_queue_and_now(self):
        clock = FakeClock()
        svc = _fleet(clock=clock)
        svc.submit(_thetas(1)[0])
        assert not svc.ready_at()          # under max_wait, under batch
        assert svc.ready_at(now=0.011)     # pure: no side effects
        assert not svc.ready_at(now=0.009)
        assert svc.run_once() == 0         # real now still says wait
        clock.advance(0.011)
        assert svc.run_once() == 1

    def test_latencies_recorded_on_injected_clock(self):
        clock = FakeClock()
        svc = _fleet(clock=clock)
        svc.submit(_thetas(1)[0])
        clock.advance(0.02)
        svc.run_once()
        clock.advance(0.005)
        svc.poll(block=True)
        s = svc.stats.summary()
        assert s["p50_latency_s"] == pytest.approx(0.025)
        assert s["p99_latency_s"] == pytest.approx(0.025)

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="queue_bound"):
            _fleet(queue_bound=2, max_batch_size=4)
        with pytest.raises(ValueError, match="deadline_s"):
            _fleet(deadline_s=0.001, max_wait_s=0.01)
        with pytest.raises(ValueError, match="coordinates"):
            _fleet().submit([1.0])

    def test_config_knobs_resolve_and_stay_out_of_identity(self):
        from bdlz_tpu.config import config_identity_dict

        base2 = dataclasses.replace(BASE, n_replicas=2, queue_bound=8)
        svc = FleetService(
            _make_artifact(base=base2), base2, static=STATIC,
            max_batch_size=4, clock=FakeClock(),
        )
        assert svc.replica_set.n_replicas == 2
        assert svc.queue_bound == 8
        # deployment shape must not stale artifacts/manifests
        assert config_identity_dict(base2) == config_identity_dict(BASE)

    def test_exact_fallback_isolated_per_request(self, tiny_emulator):
        """The fleet answers out-of-domain rows through the SAME
        retried exact fallback as YieldService, isolated per request."""
        from bdlz_tpu.emulator import load_artifact
        from bdlz_tpu.serve import YieldService

        base, out_dir, _, _ = tiny_emulator
        art = load_artifact(out_dir)
        clock = FakeClock()
        svc = FleetService(art, base, max_batch_size=4, n_replicas=2,
                           clock=clock, max_wait_s=0.005)
        ref = YieldService(art, base, max_batch_size=4, warm=False)
        thetas = np.array([
            [1.0, 100.0, 0.30],   # inside
            [1.0, 100.0, 0.60],   # v_w outside the tiny box
            [0.95, 95.0, 0.28],   # inside
        ])
        futs = [svc.submit(t) for t in thetas]
        clock.advance(0.006)
        assert svc.run_once() == 3
        assert svc.poll(block=True) == 3
        got = np.array([f.result(timeout=0).value for f in futs])
        want, n_fallback = ref.evaluate(thetas)
        assert n_fallback == 1
        np.testing.assert_array_equal(got, want)
        assert svc.stats.summary()["fallbacks"] == 1


class TestRollout:
    def test_rollout_under_load_zero_drops_no_mixed_batches(self):
        """The zero-downtime pin: under continuous fake-clock load, the
        N→N+1 cutover drops nothing, every response carries a valid
        artifact hash (N or N+1, never mixed within a batch), and the
        per-batch hash sequence is a clean N…N / N+1…N+1 transition."""
        art_n = _make_artifact()
        art_n1 = _make_artifact(scale=1.5)
        clock = FakeClock()
        svc = _fleet(artifact=art_n, clock=clock)
        ro = ArtifactRollout(svc)
        h_n, h_n1 = art_n.content_hash, art_n1.content_hash
        assert h_n != h_n1

        thetas = _thetas(64, seed=3)
        futs = []
        for round_i in range(16):           # 16 full batches of 4
            for k in range(4):
                futs.append(svc.submit(thetas[(4 * round_i + k) % 64]))
            svc.run_once()
            svc.poll(block=False)           # load keeps flowing
            if round_i == 7:                # mid-stream rollout
                assert ro.stage(art_n1) == h_n1
                old, new = ro.cutover()
                assert (old, new) == (h_n, h_n1)
        svc.drain()

        # zero drops: every submitted request resolves with a value
        responses = [f.result(timeout=0) for f in futs]
        assert len(responses) == 64
        hashes = [r.artifact_hash for r in responses]
        assert set(hashes) == {h_n, h_n1}
        # never mixed within a batch, and the per-batch sequence is a
        # single monotone N→N+1 transition
        rows = svc.stats.as_rows()
        row_hashes = [r["artifact_hash"] for r in rows]
        assert all(h in (h_n, h_n1) for h in row_hashes)
        flip = row_hashes.index(h_n1)
        assert all(h == h_n for h in row_hashes[:flip])
        assert all(h == h_n1 for h in row_hashes[flip:])
        # the answers actually moved to the new surface (1.5x table)
        by_hash = {}
        for r, f in zip(responses, futs):
            by_hash.setdefault(r.artifact_hash, []).append(r.value)
        assert np.isfinite(by_hash[h_n]).all()
        assert np.isfinite(by_hash[h_n1]).all()

    def test_in_flight_batches_resolve_with_old_artifact(self):
        """The drain guarantee: a batch dispatched against N before the
        cutover resolves with N's hash and N's values even though N+1 is
        active by the time it is gathered."""
        art_n, art_n1 = _make_artifact(), _make_artifact(scale=2.0)
        clock = FakeClock()
        svc = _fleet(artifact=art_n, clock=clock)
        ro = ArtifactRollout(svc)
        theta = _thetas(4, seed=5)
        pre = [svc.submit(t) for t in theta]
        svc.run_once()                      # in flight on N
        ro.stage(art_n1)
        ro.cutover()
        post = [svc.submit(t) for t in theta]
        svc.run_once()
        svc.drain()
        pre_r = [f.result(timeout=0) for f in pre]
        post_r = [f.result(timeout=0) for f in post]
        assert {r.artifact_hash for r in pre_r} == {art_n.content_hash}
        assert {r.artifact_hash for r in post_r} == {art_n1.content_hash}
        for a, b in zip(pre_r, post_r):     # same theta, 2x table
            assert b.value == pytest.approx(2.0 * a.value, rel=1e-12)

    def test_identity_skew_rejected_at_stage(self):
        """An artifact built for DIFFERENT physics can never be staged:
        the PR-3 identity check fires before any replica exists."""
        svc = _fleet()
        ro = ArtifactRollout(svc)
        base_bad = dataclasses.replace(BASE, incident_flux_scale=2e-9)
        art_bad = _make_artifact()._replace(
            identity=build_identity(base_bad, STATIC, 400, "tabulated")
        )
        with pytest.raises(EmulatorArtifactError, match="identity mismatch"):
            ro.stage(art_bad)
        assert ro.staged_hash is None       # nothing half-staged

    def test_cutover_refuses_cold_or_empty_stage(self):
        svc = _fleet()
        ro = ArtifactRollout(svc)
        with pytest.raises(RolloutError, match="nothing staged"):
            ro.cutover()
        ro.stage(_make_artifact(scale=1.1), warm=False)
        with pytest.raises(RolloutError, match="cold"):
            ro.cutover()
        ro.warm()
        old, new = ro.cutover()
        assert new == _make_artifact(scale=1.1).content_hash
        assert ro.previous is not None      # rollback seam
        # the drained stage is gone: a second cutover has nothing
        with pytest.raises(RolloutError, match="nothing staged"):
            ro.cutover()

    def test_abort_leaves_active_untouched(self):
        svc = _fleet()
        ro = ArtifactRollout(svc)
        h0 = svc.artifact_hash
        ro.stage(_make_artifact(scale=3.0))
        ro.abort()
        assert ro.staged_hash is None
        assert svc.artifact_hash == h0

    def test_abort_while_stage_warmed_then_restage_cutover(self):
        """Satellite edge: aborting a WARMED stage (compiled kernels)
        drops it cleanly — not ready, active untouched, idempotent —
        and a fresh stage afterwards cuts over normally."""
        svc = _fleet()
        ro = ArtifactRollout(svc)
        h0 = svc.artifact_hash
        ro.stage(_make_artifact(scale=2.0))     # warm=True default
        assert ro.ready()                       # warmed and staged
        ro.abort()
        assert ro.staged_hash is None and not ro.ready()
        assert svc.artifact_hash == h0
        ro.abort()                              # idempotent on empty
        art3 = _make_artifact(scale=3.0)
        ro.stage(art3)
        old, new = ro.cutover()
        assert (old, new) == (h0, art3.content_hash)
        assert svc.artifact_hash == art3.content_hash

    def test_stage_by_hash_missing_entry_refuses(self, tmp_path):
        """Satellite edge: staging a content hash the registry never
        published refuses loudly, with nothing half-staged."""
        from bdlz_tpu.provenance import Store

        svc = _fleet()
        ro = ArtifactRollout(svc, store=Store(str(tmp_path / "store")))
        with pytest.raises(EmulatorArtifactError, match="no published"):
            ro.stage("0123456789abcdef")
        assert ro.staged_hash is None
        assert svc.artifact_hash  # still serving the original

    def test_swap_replica_set_drains_in_flight_slots(self):
        """Satellite edge: a batch in flight on the OLD set when the
        swap lands resolves with the old hash/values AND releases the
        old replicas' in-flight slots — the retired set drains to
        idle, nothing leaks."""
        art_n, art_n1 = _make_artifact(), _make_artifact(scale=2.0)
        clock = FakeClock()
        svc = _fleet(artifact=art_n, clock=clock)
        ro = ArtifactRollout(svc)
        pre = [svc.submit(t) for t in _thetas(4, seed=7)]
        svc.run_once()                          # full batch: in flight on N
        old_set = svc.replica_set
        assert sum(r.in_flight for r in old_set.replicas) == 1
        ro.stage(art_n1)
        ro.cutover()
        assert svc.replica_set is not old_set
        assert svc.poll(block=True) == 4
        responses = [f.result(timeout=0) for f in pre]
        assert {r.artifact_hash for r in responses} == {
            art_n.content_hash
        }
        assert sum(r.in_flight for r in old_set.replicas) == 0
        assert svc.in_flight() == 0

    def test_broadcast_text_roundtrip(self):
        """The rollout's hash-agreement wire helper (single-process =
        identity; width overflow is loud, not truncated)."""
        from bdlz_tpu.parallel.multihost import broadcast_text

        assert broadcast_text("0123abcd9999ffff", width=64) == (
            "0123abcd9999ffff"
        )
        with pytest.raises(ValueError, match="exceeds"):
            broadcast_text("x" * 65, width=64)


class TestServeStatsAudit:
    """Satellite pin: every rate/percentile field is None — never NaN,
    never a fabricated 0.0 — on an empty window, and the summary stays
    strict-JSON-safe under total overload."""

    EMPTY_NULL_FIELDS = (
        "fallback_rate", "mean_batch", "mean_occupancy", "max_wait_s",
        "quarantine_rate", "shed_rate", "p50_latency_s", "p99_latency_s",
    )

    def test_empty_window_rates_are_null(self):
        s = ServeStats().summary()
        for key in self.EMPTY_NULL_FIELDS:
            assert s[key] is None, key
        json.dumps(s, allow_nan=False)  # strict JSON, no NaN/inf

    def test_all_requests_shed_window(self):
        """Zero batches dispatched, everything shed: the rates that have
        a denominator report it, the rest stay null."""
        st = ServeStats()
        st.record_accepted(3)
        st.record_deadline_kills(3)
        st.record_admission_rejects(2)
        s = st.summary()
        assert s["batches"] == 0 and s["requests"] == 0
        assert s["shed_rate"] == pytest.approx(1.0)  # (3+2)/(3+2)
        for key in ("fallback_rate", "mean_batch", "mean_occupancy",
                    "max_wait_s", "quarantine_rate", "p50_latency_s",
                    "p99_latency_s"):
            assert s[key] is None, key
        json.dumps(s, allow_nan=False)

    def test_batcher_queue_bound(self):
        """MicroBatcher admission control: the single-kernel front gets
        the same typed rejection as the fleet."""
        from bdlz_tpu.serve import MicroBatcher

        clock = FakeClock()
        mb = MicroBatcher(
            lambda thetas: [float(t[0]) for t in thetas],
            max_batch_size=2, max_wait_s=0.01, clock=clock,
            queue_bound=2,
        )
        f1, f2 = mb.submit([1.0]), mb.submit([2.0])
        with pytest.raises(QueueFull, match="admission bound"):
            mb.submit([3.0])
        assert mb.run_once() == 2
        assert f1.result(timeout=0) == 1.0 and f2.result(timeout=0) == 2.0
        s = mb.stats.summary()
        assert s["accepted"] == 2 and s["admission_rejects"] == 1
        assert s["shed_rate"] == pytest.approx(1 / 3, abs=1e-4)
        with pytest.raises(ValueError, match="queue_bound"):
            MicroBatcher(lambda t: [], max_batch_size=4, queue_bound=2)
