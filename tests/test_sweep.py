"""Sweep-engine tests on the 8-device virtual CPU mesh (SURVEY §4.3/§4.4):
backend parity, mesh sharding, grid-sharded quadrature, checkpoint/resume,
and failure masking."""
import numpy as np
import pytest

from bdlz_tpu.config import (
    config_from_dict,
    point_params_from_config,
    static_choices_from_config,
)
from bdlz_tpu.models.yields_pipeline import point_yields
from bdlz_tpu.ops.kjma_table import make_f_table
from bdlz_tpu.parallel import build_grid, make_mesh, run_sweep
from bdlz_tpu.physics.percolation import make_kjma_grid

BENCH_OVER = {
    "regime": "nonthermal",
    "P_chi_to_B": 0.14925839040304145,
    "source_shape_sigma_y": 9.0,
    "incident_flux_scale": 1.07e-9,
    "Y_chi_init": 4.90e-10,
}


@pytest.fixture(scope="module")
def base_cfg():
    return config_from_dict(dict(BENCH_OVER))


@pytest.fixture(scope="module")
def mesh8():
    import jax

    assert len(jax.devices()) == 8
    return make_mesh(shape=(4, 2))


class TestGridBuild:
    def test_product_grid(self, base_cfg):
        pp = build_grid(base_cfg, {"m_chi_GeV": [0.5, 1.0], "v_w": [0.1, 0.3, 0.5]})
        assert pp.m_chi_GeV.shape == (6,)
        # first axis varies slowest (C-order)
        np.testing.assert_allclose(pp.m_chi_GeV, [0.5] * 3 + [1.0] * 3)
        np.testing.assert_allclose(pp.v_w, [0.1, 0.3, 0.5] * 2)
        # un-swept fields keep base values
        np.testing.assert_allclose(pp.P, base_cfg.P_chi_to_B)

    def test_zip_grid(self, base_cfg):
        pp = build_grid(
            base_cfg, {"m_chi_GeV": [0.5, 1.0], "T_p_GeV": [50.0, 200.0]}, product=False
        )
        assert pp.m_chi_GeV.shape == (2,)
        np.testing.assert_allclose(pp.T_p_GeV, [50.0, 200.0])

    def test_unknown_axis_rejected(self, base_cfg):
        with pytest.raises(ValueError, match="Unknown sweep axes"):
            build_grid(base_cfg, {"bogus": [1.0]})

    def test_m_B_converted_to_kg(self, base_cfg):
        from bdlz_tpu.constants import GEV_TO_KG

        pp = build_grid(base_cfg, {"m_B_GeV": [1.0, 2.0]})
        np.testing.assert_allclose(pp.m_B_kg, [GEV_TO_KG, 2 * GEV_TO_KG])


class TestSweepParity:
    def test_sharded_sweep_matches_pointwise_numpy(self, base_cfg, mesh8):
        """The mesh-sharded vmapped fast path must agree with the NumPy
        per-point reference pipeline to ~1e-10 (backend-parity contract,
        SURVEY §4.3 — target ≤1e-6, delivered much tighter)."""
        static = static_choices_from_config(base_cfg)
        axes = {
            "m_chi_GeV": np.geomspace(0.05, 5.0, 4),
            "T_p_GeV": np.geomspace(50.0, 400.0, 4),
            "P_chi_to_B": np.linspace(0.05, 0.9, 2),
        }
        res = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=16)
        assert res.n_points == 32
        assert res.n_failed == 0

        pp_all = build_grid(base_cfg, axes)
        grid_np = make_kjma_grid(np)
        for i in range(0, 32, 7):
            pp_i = type(pp_all)(*(np.asarray(f)[i] for f in pp_all))
            ref = point_yields(pp_i, static, grid_np, np)
            got = res.outputs["DM_over_B"][i]
            assert got == pytest.approx(float(ref.DM_over_B), rel=1e-9), i

    def test_benchmark_point_through_sweep(self, base_cfg, mesh8):
        """The archived benchmark point embedded in a sweep reproduces the
        golden ratio through the whole sharded fast path."""
        static = static_choices_from_config(base_cfg)
        axes = {"m_chi_GeV": [0.5, 0.95, 2.0]}
        res = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8)
        assert res.outputs["DM_over_B"][1] == pytest.approx(5.6889263349, rel=1e-9)
        assert res.outputs["Y_B"][1] == pytest.approx(8.7208853627e-11, rel=1e-9)


class TestCheckpointResume:
    def test_resume_skips_completed_chunks(self, base_cfg, mesh8, tmp_path):
        static = static_choices_from_config(base_cfg)
        axes = {"m_chi_GeV": np.geomspace(0.1, 2.0, 24)}
        out = str(tmp_path / "sweep")
        r1 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
        assert r1.chunks == 3 and r1.resumed_chunks == 0
        r2 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
        assert r2.resumed_chunks == 3
        np.testing.assert_array_equal(
            r1.outputs["DM_over_B"], r2.outputs["DM_over_B"]
        )

    def test_changed_grid_invalidates_manifest(self, base_cfg, mesh8, tmp_path):
        static = static_choices_from_config(base_cfg)
        out = str(tmp_path / "sweep")
        run_sweep(base_cfg, {"m_chi_GeV": [0.5, 1.0]}, static, mesh=mesh8,
                  chunk_size=2, out_dir=out)
        r = run_sweep(base_cfg, {"m_chi_GeV": [0.5, 2.0]}, static, mesh=mesh8,
                      chunk_size=2, out_dir=out)
        assert r.resumed_chunks == 0


class TestFailureMasking:
    def test_nonfinite_points_masked_not_fatal(self, base_cfg, mesh8):
        """A pathological corner (m_chi=0 -> rho_DM=0 -> ratio=0; flux
        scale inf -> nonfinite) must be reported, not abort the sweep."""
        static = static_choices_from_config(base_cfg)
        axes = {"incident_flux_scale": [1.07e-9, np.inf]}
        res = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=2)
        assert res.n_points == 2
        assert res.n_failed == 1
        assert np.isfinite(res.outputs["DM_over_B"][0])


class TestGridShardedQuadrature:
    def test_sp_matches_single_device(self, base_cfg, mesh8):
        import jax.numpy as jnp

        from bdlz_tpu.parallel.gridshard import make_sp_quadrature
        from bdlz_tpu.solvers.quadrature import integrate_YB_quadrature_tabulated

        static = static_choices_from_config(base_cfg)
        pp = point_params_from_config(base_cfg, base_cfg.P_chi_to_B)
        table = make_f_table(base_cfg.I_p, jnp)

        fn = make_sp_quadrature(static, mesh8, n_y=8192)
        YB_sp = float(fn(pp, table))
        YB_ref = float(
            integrate_YB_quadrature_tabulated(pp, static.chi_stats, table, jnp, n_y=8192)
        )
        assert YB_sp == pytest.approx(YB_ref, rel=1e-12)

    def test_sp_requires_divisible_grid(self, base_cfg, mesh8):
        from bdlz_tpu.parallel.gridshard import make_sp_quadrature

        static = static_choices_from_config(base_cfg)
        with pytest.raises(ValueError, match="divisible"):
            make_sp_quadrature(static, mesh8, n_y=8191)


def test_sweep_cli_all_failed_summary_is_strict_json(base_cfg, tmp_path, capsys):
    """When every point fails, the stdout summary must still be valid strict
    JSON (closest_to_planck: null), not bare NaN (review regression)."""
    import dataclasses
    import json

    from bdlz_tpu.sweep_cli import main as sweep_main

    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps(dataclasses.asdict(base_cfg)))
    sweep_main([
        "--config", str(cfg),
        "--axis", "m_chi_GeV=1e300,1e300",
        "--chunk", "16", "--n-y", "2000",
    ])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(out, parse_constant=lambda s: pytest.fail(f"non-strict JSON {s}"))
    assert summary["closest_to_planck"] is None
    assert summary["n_failed"] == summary["n_points"] == 2


def test_pallas_impl_sweep_matches_tabulated(base_cfg, mesh8):
    """run_sweep(impl="pallas") on the 8-device mesh (interpret mode on CPU)
    agrees with the tabulated XLA path to f32-stream accuracy."""
    static = static_choices_from_config(base_cfg)
    axes = {"m_chi_GeV": np.geomspace(0.3, 3.0, 16).tolist()}
    res_p = run_sweep(
        base_cfg, axes, static, mesh=mesh8, chunk_size=16, n_y=2048,
        impl="pallas", interpret=True,
    )
    res_t = run_sweep(
        base_cfg, axes, static, mesh=mesh8, chunk_size=16, n_y=2048,
    )
    assert res_p.n_failed == 0
    np.testing.assert_allclose(
        res_p.outputs["DM_over_B"], res_t.outputs["DM_over_B"], rtol=1e-6
    )


class TestODESweep:
    def test_washout_sweep_routes_to_esdirk_and_matches_pointwise(self, base_cfg, mesh8):
        """Sweeping Gamma_wash forces the stiff ESDIRK path (the quadrature
        impls are invalid there) and reproduces the per-point solver."""
        import dataclasses

        import jax.numpy as jnp

        from bdlz_tpu.models.yields_pipeline import present_day
        from bdlz_tpu.solvers.sdirk import solve_boltzmann_esdirk

        cfg = dataclasses.replace(base_cfg, T_min_over_Tp=0.2)
        static = static_choices_from_config(cfg)
        axes = {"Gamma_wash_over_H": [0.0, 0.01, 0.1]}
        res = run_sweep(cfg, axes, static, mesh=mesh8, chunk_size=8)
        assert res.n_failed == 0
        # washout monotonically depletes the baryon yield
        YB = res.outputs["Y_B"]
        assert YB[0] > YB[1] > YB[2] > 0.0

        pp_all = build_grid(cfg, axes)
        grid = make_kjma_grid(jnp)
        i = 2
        pp_i = type(pp_all)(*(jnp.asarray(np.asarray(f)[i]) for f in pp_all))
        T_hi = float(pp_i.T_max_over_Tp * pp_i.T_p_GeV)
        T_lo = float(pp_i.T_min_over_Tp * pp_i.T_p_GeV)
        sol = solve_boltzmann_esdirk(
            pp_i, static, grid, (float(pp_i.Y_chi_init), 0.0), T_lo, T_hi
        )
        ref = present_day(sol.y[1], sol.y[0], pp_i.m_chi_GeV, pp_i.m_B_kg, jnp)
        # the sweep's default stiff engine is the repacked batch engine
        # with the acceleration knobs on (~2e-8 vs the bit-pinned
        # per-point path); ABSOLUTE tolerance, because approx's rel on a
        # ~1e-10 yield would silently be dominated by its 1e-12 abs
        # default.  The bit-level sweep↔engine pin lives in
        # tests/test_sdirk_batching.py.
        assert YB[i] == pytest.approx(float(ref.Y_B), rel=1e-6, abs=0.0)

    def test_quadrature_limit_agreement(self, base_cfg, mesh8):
        """With all ODE knobs at zero, the esdirk sweep must agree with the
        quadrature fast path to the integrator tolerance."""
        import dataclasses

        cfg = dataclasses.replace(base_cfg, T_min_over_Tp=0.2)
        static = static_choices_from_config(cfg)
        axes = {"m_chi_GeV": [0.95]}
        res_q = run_sweep(cfg, axes, static, mesh=mesh8, chunk_size=8)
        res_o = run_sweep(cfg, axes, static, mesh=mesh8, chunk_size=8, impl="esdirk")
        assert res_o.outputs["Y_B"][0] == pytest.approx(
            res_q.outputs["Y_B"][0], rel=1e-4
        )


def test_resume_invalidated_by_engine_change(base_cfg, mesh8, tmp_path):
    """Chunks computed by different engines must never be mixed: changing the
    impl invalidates the manifest (review regression)."""
    import dataclasses

    cfg = dataclasses.replace(base_cfg, T_min_over_Tp=0.2)
    static = static_choices_from_config(cfg)
    axes = {"m_chi_GeV": [0.5, 0.95]}
    out = str(tmp_path / "sweep")
    run_sweep(cfg, axes, static, mesh=mesh8, chunk_size=2, out_dir=out)
    r = run_sweep(cfg, axes, static, mesh=mesh8, chunk_size=2, out_dir=out,
                  impl="esdirk")
    assert r.resumed_chunks == 0


def test_pallas_tier_resolver_degrades(monkeypatch):
    """The shared tier ladder (reduce -> streaming) only runs on
    accelerator platforms, so CI pins its logic with a faked platform and
    preflight: default request degrades past a broken reduction kernel;
    an explicit request never silently switches tiers."""
    import jax

    import bdlz_tpu.ops.kjma_pallas as kp
    from bdlz_tpu.parallel.sweep import resolve_pallas_tier

    class _Dev:
        platform = "tpu"

    monkeypatch.setattr(jax, "devices", lambda *a, **k: [_Dev()])
    calls = []

    def fake_preflight(chi_stats="fermion", n_points=128, n_y=2000,
                       fuse_exp=False, tol=1e-6, table_n=16384,
                       reduce=kp.REDUCE_DEFAULT):
        calls.append(reduce)
        ok = not reduce  # the reduction kernel "fails to lower"
        return ok, (0.0 if ok else float("inf")), "fake"

    monkeypatch.setattr(kp, "pallas_preflight", fake_preflight)

    tier, msg = resolve_pallas_tier("fermion", 8000)
    assert tier is False and calls == [True, False]
    assert "FAIL [reduce=True]" in msg and "PASS [reduce=False]" in msg

    # explicit tier request: no silent degrade to a different kernel
    calls.clear()
    tier2, msg2 = resolve_pallas_tier("fermion", 8000, reduce=True)
    assert tier2 is None and calls == [True]

    # both tiers broken -> None
    monkeypatch.setattr(
        kp, "pallas_preflight",
        lambda **kw: (False, float("inf"), "dead"),
    )
    tier3, _ = resolve_pallas_tier("fermion", 8000)
    assert tier3 is None


def test_tier_wire_codes_min_is_conservative():
    """The fleet-agreement encoding's invariant: min() over any mix of
    wire codes picks the most conservative outcome.  In particular 'no
    hardware preflight' (kernel default) must sort ABOVE both
    hardware-proven tiers, so a hypothetical mixed fleet lands on the
    proven tier, never the unproven default (ADVICE r4)."""
    from bdlz_tpu.parallel.sweep import (
        _TIER_CODE, _TIER_FAILED, _TIER_FROM_CODE,
    )

    assert _TIER_FAILED < min(_TIER_CODE.values())
    assert _TIER_CODE[None] > _TIER_CODE[True] > _TIER_CODE[False]
    # round-trip
    for tier, code in _TIER_CODE.items():
        assert _TIER_FROM_CODE[code] is tier
    # mixed fleets: hardware-proven beats no-preflight; streaming beats
    # reduction; failure beats everything
    assert _TIER_FROM_CODE[min(_TIER_CODE[None], _TIER_CODE[True])] is True
    assert _TIER_FROM_CODE[min(_TIER_CODE[None], _TIER_CODE[False])] is False
    assert _TIER_FROM_CODE[min(_TIER_CODE[True], _TIER_CODE[False])] is False
    assert min(_TIER_FAILED, *_TIER_CODE.values()) == _TIER_FAILED


def test_resume_invalidated_by_pallas_knob_change(base_cfg, mesh8, tmp_path):
    """Pallas kernel knobs (fuse_exp; the in-kernel reduce default) join
    the resume identity: results differ at ~1e-7 between kernel variants,
    so a directory written with one must not be resumed with another
    (review regression, r3)."""
    static = static_choices_from_config(base_cfg)
    axes = {"m_chi_GeV": [0.5, 0.95]}
    out = str(tmp_path / "sweep")
    run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=2, out_dir=out,
              impl="pallas", interpret=True)
    # same knobs → resumes
    r_same = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=2,
                       out_dir=out, impl="pallas", interpret=True)
    assert r_same.resumed_chunks == 1
    # different exp algorithm → full recompute
    r_fuse = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=2,
                       out_dir=out, impl="pallas", interpret=True,
                       fuse_exp=True)
    assert r_fuse.resumed_chunks == 0


class TestResumeHardening:
    def test_missing_chunk_file_recomputed_not_fatal(self, base_cfg, mesh8,
                                                     tmp_path, capsys):
        """A chunk listed in the manifest whose .npz vanished must be
        recomputed with a warning, not crash the resume (mask-and-report
        extends to storage failures)."""
        import os

        static = static_choices_from_config(base_cfg)
        axes = {"m_chi_GeV": np.geomspace(0.1, 2.0, 24)}
        out = str(tmp_path / "sweep")
        r1 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
        assert r1.chunks == 3
        os.remove(f"{out}/chunk_00001.npz")
        r2 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
        assert r2.resumed_chunks == 2  # the healthy two skipped
        assert "recomputing" in capsys.readouterr().err
        np.testing.assert_array_equal(
            r1.outputs["DM_over_B"], r2.outputs["DM_over_B"]
        )
        # the recomputed chunk file is back on disk and resumable again
        r3 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
        assert r3.resumed_chunks == 3

    def test_corrupt_chunk_file_recomputed(self, base_cfg, mesh8, tmp_path, capsys):
        static = static_choices_from_config(base_cfg)
        axes = {"m_chi_GeV": np.geomspace(0.1, 2.0, 16)}
        out = str(tmp_path / "sweep")
        r1 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
        with open(f"{out}/chunk_00000.npz", "wb") as f:
            f.write(b"not a zipfile")
        r2 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
        assert r2.resumed_chunks == 1
        assert "recomputing" in capsys.readouterr().err
        np.testing.assert_array_equal(
            r1.outputs["DM_over_B"], r2.outputs["DM_over_B"]
        )


class TestFailureMask:
    def test_failed_mask_locates_bad_points(self, base_cfg, mesh8):
        """SweepResult.failed_mask pinpoints which parameter corners failed,
        not just how many (VERDICT r1 weak #4)."""
        static = static_choices_from_config(base_cfg)
        axes = {"incident_flux_scale": [1.07e-9, np.inf, 1.07e-9, np.inf]}
        res = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=4)
        assert res.n_failed == 2
        np.testing.assert_array_equal(
            res.failed_mask, [False, True, False, True]
        )

    def test_failed_mask_survives_resume(self, base_cfg, mesh8, tmp_path):
        static = static_choices_from_config(base_cfg)
        axes = {"incident_flux_scale": [1.07e-9, np.inf, 1.07e-9, np.inf]}
        out = str(tmp_path / "sweep")
        run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=2, out_dir=out)
        r2 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=2, out_dir=out)
        assert r2.resumed_chunks == r2.chunks  # chunk_size rounds up to the mesh
        np.testing.assert_array_equal(
            r2.failed_mask, [False, True, False, True]
        )


class TestLZProfileSweep:
    """The LZ kernel connected to the sweep engine: P derived per point
    from the profile at that point's wall speed (reference seam :317-328
    resolved inside scans)."""

    def _profile(self, tmp_path):
        xi = np.linspace(-200.0, 200.0, 20000)
        path = tmp_path / "prof.csv"
        rows = "\n".join(
            f"{x},{1.0 * x},{0.05}" for x in xi
        )
        path.write_text("xi,delta,m_mix\n" + rows + "\n")
        return str(path)

    @staticmethod
    def _assert_pointwise_parity(res, base_cfg, static, v_ws, P_pts):
        """Each sweep point equals a pointwise run at the profile-derived P."""
        grid_np = make_kjma_grid(np)
        pp_all = build_grid(base_cfg, {"v_w": v_ws})
        for i in range(len(v_ws)):
            pp_i = type(pp_all)(
                *(np.asarray(f)[i] for f in pp_all)
            )._replace(P=P_pts[i])
            ref = point_yields(pp_i, static, grid_np, np)
            assert res.outputs["DM_over_B"][i] == pytest.approx(
                float(ref.DM_over_B), rel=1e-9
            ), i

    def test_v_w_scan_uses_profile_P(self, base_cfg, mesh8, tmp_path):
        from bdlz_tpu.lz import load_profile_csv, probabilities_for_points

        prof_path = self._profile(tmp_path)
        static = static_choices_from_config(base_cfg)
        v_ws = [0.1, 0.3, 0.6]
        res = run_sweep(
            base_cfg, {"v_w": v_ws}, static, mesh=mesh8, chunk_size=8,
            n_y=2000, lz_profile=prof_path,
        )
        assert res.n_failed == 0
        prof = load_profile_csv(prof_path)
        P_pts = probabilities_for_points(prof, np.asarray(v_ws))
        self._assert_pointwise_parity(res, base_cfg, static, v_ws, P_pts)

    def test_P_axis_conflict_rejected(self, base_cfg, mesh8, tmp_path):
        static = static_choices_from_config(base_cfg)
        with pytest.raises(ValueError, match="P_chi_to_B"):
            run_sweep(
                base_cfg, {"P_chi_to_B": [0.1, 0.2]}, static, mesh=mesh8,
                lz_profile=self._profile(tmp_path),
            )

    def test_dephased_sweep_and_gamma_identity(self, base_cfg, mesh8, tmp_path):
        """A dephased v_w scan derives each point's P from the Bloch
        transport at the sweep's Γ_φ, and a changed rate invalidates
        resume (different Γ are different sweeps)."""
        from bdlz_tpu.lz import load_profile_csv, probabilities_for_points

        prof_path = self._profile(tmp_path)
        static = static_choices_from_config(base_cfg)
        v_ws = [0.2, 0.5]
        out = str(tmp_path / "sweep")
        res = run_sweep(
            base_cfg, {"v_w": v_ws}, static, mesh=mesh8, chunk_size=2,
            n_y=2000, out_dir=out, lz_profile=prof_path,
            lz_method="dephased", lz_gamma_phi=0.3,
        )
        assert res.n_failed == 0
        prof = load_profile_csv(prof_path)
        P_pts = probabilities_for_points(
            prof, np.asarray(v_ws), method="dephased", gamma_phi=0.3
        )
        self._assert_pointwise_parity(res, base_cfg, static, v_ws, P_pts)
        # same gamma resumes; different gamma recomputes
        r_same = run_sweep(
            base_cfg, {"v_w": v_ws}, static, mesh=mesh8, chunk_size=2,
            n_y=2000, out_dir=out, lz_profile=prof_path,
            lz_method="dephased", lz_gamma_phi=0.3,
        )
        assert r_same.resumed_chunks == 1
        r_other = run_sweep(
            base_cfg, {"v_w": v_ws}, static, mesh=mesh8, chunk_size=2,
            n_y=2000, out_dir=out, lz_profile=prof_path,
            lz_method="dephased", lz_gamma_phi=0.6,
        )
        assert r_other.resumed_chunks == 0

    def test_gamma_with_wrong_method_rejected(self, base_cfg, mesh8, tmp_path):
        """A dephasing rate the chosen estimator would silently ignore is
        a caller error at the sweep level too."""
        static = static_choices_from_config(base_cfg)
        with pytest.raises(ValueError, match="no effect"):
            run_sweep(
                base_cfg, {"v_w": [0.2, 0.4]}, static, mesh=mesh8,
                chunk_size=2, n_y=2000,
                lz_profile=self._profile(tmp_path),
                lz_method="coherent", lz_gamma_phi=0.5,
            )

    def test_changed_profile_invalidates_resume(self, base_cfg, mesh8, tmp_path):
        static = static_choices_from_config(base_cfg)
        out = str(tmp_path / "sweep")
        prof_a = self._profile(tmp_path)
        run_sweep(base_cfg, {"v_w": [0.2, 0.4]}, static, mesh=mesh8,
                  chunk_size=2, n_y=2000, out_dir=out, lz_profile=prof_a)
        # different mixing -> different probabilities -> fresh sweep
        xi = np.linspace(-200.0, 200.0, 20000)
        prof_b = tmp_path / "prof_b.csv"
        prof_b.write_text(
            "xi,delta,m_mix\n"
            + "\n".join(f"{x},{1.0 * x},{0.08}" for x in xi) + "\n"
        )
        r = run_sweep(base_cfg, {"v_w": [0.2, 0.4]}, static, mesh=mesh8,
                      chunk_size=2, n_y=2000, out_dir=out, lz_profile=str(prof_b))
        assert r.resumed_chunks == 0


def test_lz_profile_sweep_with_unset_P(base_cfg, mesh8, tmp_path):
    """The natural --lz-profile usage leaves P_chi_to_B unset (None): the
    profile supplies P, so grid build must not choke on the None
    placeholder (review regression)."""
    import dataclasses

    xi = np.linspace(-200.0, 200.0, 20000)
    prof = tmp_path / "prof.csv"
    prof.write_text(
        "xi,delta,m_mix\n"
        + "\n".join(f"{x},{1.0 * x},{0.05}" for x in xi) + "\n"
    )
    cfg = dataclasses.replace(base_cfg, P_chi_to_B=None)
    static = static_choices_from_config(cfg)
    res = run_sweep(
        cfg, {"v_w": [0.2, 0.5]}, static, mesh=mesh8, chunk_size=8,
        n_y=2000, lz_profile=str(prof),
    )
    assert res.n_failed == 0
    assert np.isfinite(res.outputs["DM_over_B"]).all()


def test_resume_invalidated_by_chunk_size_change(base_cfg, mesh8, tmp_path,
                                                 capsys):
    """Chunk boundaries index the chunk files: a directory written at one
    chunk_size must be recomputed, not mis-sliced, when resumed at
    another (reachable via --chunk or the device-memory clamp)."""
    static = static_choices_from_config(base_cfg)
    axes = {"m_chi_GeV": np.geomspace(0.1, 2.0, 24)}
    out = str(tmp_path / "sweep")
    r1 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=8, out_dir=out)
    r2 = run_sweep(base_cfg, axes, static, mesh=mesh8, chunk_size=16, out_dir=out)
    assert r2.resumed_chunks == 0
    assert "chunk_size" in capsys.readouterr().err
    # values agree per point (bitwise identity is only promised for
    # identical batch shapes — XLA vectorization differs per shape)
    np.testing.assert_allclose(
        r1.outputs["DM_over_B"], r2.outputs["DM_over_B"], rtol=1e-12
    )


def test_tier_agreement_wire_version_skew(monkeypatch):
    """The fleet tier agreement sends [version, -version, code]: a fleet
    mixing wire-format versions must fail with the explicit skew error on
    every host, never interpret another build's tier code (satellite of
    the r6 wire-format break; see docs/multihost.md)."""
    import bdlz_tpu.parallel.multihost as mh
    from bdlz_tpu.parallel.sweep import (
        _TIER_WIRE_VERSION,
        _agree_tier_code,
    )

    # healthy single-process path: identity allreduce, code passes through
    assert _agree_tier_code(1) == 1
    assert _agree_tier_code(-2) == -2

    # simulate a fleet where another host runs wire version v+1: the
    # elementwise min over [v, -v, code] columns yields min_v != max_v
    def skewed_armin(arr):
        other = np.array(
            [_TIER_WIRE_VERSION + 1, -(_TIER_WIRE_VERSION + 1), 0],
            dtype=np.int64,
        )
        return np.minimum(np.asarray(arr), other)

    monkeypatch.setattr(mh, "allreduce_min", skewed_armin)
    with pytest.raises(RuntimeError, match="version skew"):
        _agree_tier_code(1)
