"""Accuracy-audit harness tests: the stage probe must stay tied to the
real fast path, and the audit script (the grid-wide 1e-6 proof artifact
generator) must keep producing its schema."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bdlz_tpu.config import config_from_dict, point_params_from_config, \
    static_choices_from_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _point():
    cfg = config_from_dict({
        "regime": "nonthermal", "P_chi_to_B": 0.149,
        "source_shape_sigma_y": 9.0, "incident_flux_scale": 1.07e-9,
        "Y_chi_init": 4.9e-10,
    })
    return cfg, static_choices_from_config(cfg), \
        point_params_from_config(cfg, cfg.P_chi_to_B)


def test_probe_matches_fast_path_both_namespaces():
    import jax.numpy as jnp

    from bdlz_tpu.ops.kjma_table import make_f_table
    from bdlz_tpu.solvers.quadrature import (
        integrand_stream_probe,
        integrate_YB_quadrature_tabulated,
    )

    cfg, static, pp = _point()
    for xp in (np, jnp):
        table = make_f_table(cfg.I_p, xp, n=4096)
        probe = integrand_stream_probe(pp, static, table, xp, n_y=2000)
        assert set(probe) == {
            "thermo_prefactor", "source_window", "area_over_volume",
            "integrand", "trapezoid_YB",
        }
        YB = integrate_YB_quadrature_tabulated(
            pp, static.chi_stats, table, xp, n_y=2000
        )
        # the probe's trapezoid_YB IS the fast path's Y_B
        assert float(probe["trapezoid_YB"]) == pytest.approx(
            float(YB), rel=1e-14
        )


def test_audit_script_smoke(tmp_path):
    out = str(tmp_path / "audit.json")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "accuracy_audit.py"),
         "--points", "8", "--n-y", "2000", "--out", out],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    d = json.load(open(out))
    assert d["n_points"] == 8
    assert d["contract_1e-6_ok"] is True
    stages = d["stage_attribution_worst_point"]
    assert stages["f_table_values"] == 0.0  # host-built table is bitwise
    assert all(np.isfinite(v) for v in stages.values())
    assert len(d["worst_points"]) == 5
