"""The refinement daemon: drift watch → traffic-weighted rebuild → delivery.

:class:`RefinementDaemon` closes ROADMAP item 4's loop on one
:class:`~bdlz_tpu.serve.fleet.FleetService`.  It arms the service's
per-query traffic trace, folds it into a :class:`~bdlz_tpu.refine.traffic.TrafficModel`
on every :meth:`~RefinementDaemon.step`, and — when the observed window
drifts (gated-fallback rate or out-of-domain mass over the
``drift_gated_rate`` knob) — runs one autonomous cycle:

1. freeze + persist the traffic snapshot (content-hashed, atomic);
2. rebuild the emulator over a box EXPANDED to cover the observed
   traffic, steered by ``refine_signal="traffic"`` (the snapshot's
   train split), optionally as elastic chunks through
   ``parallel/scheduler.py``;
3. hand the candidate to the :class:`~bdlz_tpu.refine.delivery.DeliveryPipeline`
   (held-out scoring → publish → stage → cutover under observation with
   auto-rollback).

Everything runs on the service's injectable clock — tier-1 drives the
whole loop with a fake clock and a replayed trace.  The daemon is
driven by explicit ``step()`` calls (the serve CLI ticks it between
batches); it deliberately does NOT hook ``FleetService._observer``,
which the rollout observation window owns.  ``rebuild_budget`` bounds
the autonomous cycles per daemon lifetime: a distribution the surface
cannot satisfy must eventually page an operator instead of rebuilding
forever.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np  # host-side orchestration only (bdlz-lint R1 audit)

from bdlz_tpu.refine.delivery import DeliveryPipeline
from bdlz_tpu.refine.traffic import (
    TrafficModel,
    TrafficSnapshot,
    save_snapshot,
)


class RefineError(RuntimeError):
    """Daemon misuse: self-improvement forced off, no store, or a
    rebuild attempted past the budget."""


def resolve_self_improve(base, explicit: bool = False) -> bool:
    """Resolve the tri-state ``self_improve`` knob (``Config``).

    ``None`` means the engine decides: constructing a
    :class:`RefinementDaemon` directly IS the decision (``explicit=True``
    → on), while ambient attachment points (the serve CLI) stay off.
    ``True``/``False`` force.  A forced-off config makes daemon
    construction raise — the operator said never.
    """
    v = getattr(base, "self_improve", None)
    if v is None:
        return bool(explicit)
    return bool(v)


class RefinementDaemon:
    """Closed-loop controller for one serving fleet (module docstring).

    ``build_kw`` passes through to
    :func:`~bdlz_tpu.emulator.build.build_emulator` for the rebuild
    (probe counts, rounds, ``n_y`` — defaults are adopted from the
    serving artifact's identity/manifest so the candidate answers the
    same physics at the same advertised tolerance).  ``elastic``
    (worker count / kwarg dict) routes the rebuild's product sweeps
    through the elastic work-stealing fleet.
    """

    def __init__(
        self,
        service,
        base,
        *,
        store,
        clock=None,
        window: int = 512,
        min_queries: int = 32,
        drift_gated_rate: Optional[float] = None,
        rebuild_budget: Optional[int] = None,
        holdout_frac: float = 0.25,
        box_margin: float = 0.02,
        elastic=None,
        build_kw: Optional[Dict[str, Any]] = None,
        observe_s: float = 1.0,
        rollback_budget: Optional[float] = None,
        latency_slo_s: Optional[float] = None,
        event_log=None,
    ) -> None:
        if not resolve_self_improve(base, explicit=True):
            raise RefineError(
                "self_improve=False forces the closed loop off; "
                "drop the daemon or set the knob to None/True"
            )
        if store is None:
            raise RefineError(
                "the daemon persists snapshots and publishes candidates "
                "through the provenance store; pass store="
            )
        self.service = service
        self.base = base
        self.store = store
        self._clock = (
            clock if clock is not None
            else getattr(service, "_clock", time.monotonic)
        )
        self.drift_gated_rate = float(
            drift_gated_rate if drift_gated_rate is not None
            else getattr(base, "drift_gated_rate", 0.05)
        )
        self.rebuild_budget = int(
            rebuild_budget if rebuild_budget is not None
            else getattr(base, "rebuild_budget", 1)
        )
        if self.rebuild_budget < 1:
            raise RefineError(
                f"rebuild_budget must be >= 1, got {self.rebuild_budget}"
            )
        self.min_queries = int(min_queries)
        self.holdout_frac = float(holdout_frac)
        self.box_margin = float(box_margin)
        self.elastic = elastic
        self.build_kw = dict(build_kw or {})
        self.event_log = event_log
        self.model = TrafficModel(service.artifact.axis_names, window=window)
        self.pipeline = DeliveryPipeline(
            service, store,
            observe_s=observe_s, rollback_budget=rollback_budget,
            latency_slo_s=latency_slo_s, event_log=event_log,
        )
        #: "idle" | "rebuilding" | "delivering" | "exhausted"
        self.state = "idle"
        self.cycles = 0
        #: One row per completed autonomous cycle (snapshot fingerprint,
        #: drift rates, delivery decision).
        self.history: List[Dict[str, Any]] = []
        # the whole loop starts here: per-query recording is opt-in and
        # off until a daemon exists
        service.stats.arm_traffic_log()

    # ---- drift test -------------------------------------------------

    def drifted(self) -> bool:
        """True when the current window says the serving surface no
        longer fits the traffic: gated-fallback rate OR out-of-domain
        mass over ``drift_gated_rate``, with at least ``min_queries``
        observed (a 3-query window proves nothing)."""
        if self.model.n_queries < self.min_queries:
            return False
        return (
            self.model.gated_rate > self.drift_gated_rate
            or self.model.ood_rate > self.drift_gated_rate
        )

    # ---- the loop ---------------------------------------------------

    def step(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One daemon tick: fold fresh traffic, and when drift is
        detected (and budget remains) run one full rebuild + delivery
        cycle synchronously.  Returns the tick's status row."""
        now = float(self._clock() if now is None else now)
        folded = self.model.fold(self.service.stats)
        status: Dict[str, Any] = {
            "now": now,
            "state": self.state,
            "folded": folded,
            "window": self.model.n_queries,
            "gated_rate": round(self.model.gated_rate, 4),
            "ood_rate": round(self.model.ood_rate, 4),
            "cycles": self.cycles,
        }
        if not self.drifted():
            return status
        if self.cycles >= self.rebuild_budget:
            self.state = "exhausted"
            status.update(state=self.state, drifted=True)
            if self.event_log is not None:
                self.event_log.emit("refine_budget_exhausted", **status)
            return status
        status.update(drifted=True, **self._run_cycle(now))
        status["state"] = self.state
        status["cycles"] = self.cycles
        return status

    def _run_cycle(self, now: float) -> Dict[str, Any]:
        snap = self.model.snapshot()
        fp = save_snapshot(self.store, snap)
        if self.event_log is not None:
            self.event_log.emit(
                "refine_drift_detected", fingerprint=fp,
                n_queries=snap.n_queries,
                gated_rate=round(snap.gated_rate, 4),
                ood_rate=round(snap.ood_rate, 4),
            )
        train, held = snap.split_holdout(self.holdout_frac)
        # the TRAIN split is what actually steers the rebuild, so its
        # fingerprint is the one that joins the candidate identity —
        # persist it too, or the identity would name an unresolvable hash
        train_fp = save_snapshot(self.store, train)
        self.state = "rebuilding"
        candidate, report = self._rebuild(train)
        self.state = "delivering"
        decision = self.pipeline.deliver(candidate, held)
        self.cycles += 1
        self.state = "idle"
        # fresh window: drift on the (possibly new) surface must be
        # measured from traffic that surface actually served
        self.model.reset_window()
        row = {
            "snapshot": fp,
            "train_snapshot": train_fp,
            "n_queries": snap.n_queries,
            "snapshot_gated_rate": round(snap.gated_rate, 4),
            "snapshot_ood_rate": round(snap.ood_rate, 4),
            "build_converged": bool(report.converged),
            "decision": decision,
        }
        self.history.append(row)
        return row

    # ---- rebuild ----------------------------------------------------

    def _expanded_spec(self, snap: TrafficSnapshot, artifact=None):
        """The rebuild box: the serving artifact's box, widened (in
        each axis's scale coordinate, by ``box_margin`` relative pad)
        to cover every observed query — the OOD mass that triggered the
        drift is exactly what the new surface must absorb.  ``artifact``
        overrides the serving artifact (tests replay a cycle's spec
        against the surface that was serving when the cycle ran)."""
        from bdlz_tpu.emulator.build import AxisSpec
        from bdlz_tpu.emulator.grid import domain_artifacts

        artifact = artifact if artifact is not None else self.service.artifact
        doms = domain_artifacts(artifact)
        spec: Dict[str, AxisSpec] = {}
        for k, name in enumerate(artifact.axis_names):
            los = [float(d.axis_nodes[k][0]) for d in doms]
            his = [float(d.axis_nodes[k][-1]) for d in doms]
            scale = doms[0].axis_scales[k]
            lo, hi = min(los), max(his)
            t_lo = float(snap.locations[:, k].min())
            t_hi = float(snap.locations[:, k].max())
            if t_lo < lo or t_hi > hi:
                if scale == "log":
                    u_lo = np.log10(min(lo, t_lo))
                    u_hi = np.log10(max(hi, t_hi))
                    pad = self.box_margin * (u_hi - u_lo)
                    lo = float(10.0 ** (u_lo - pad))
                    hi = float(10.0 ** (u_hi + pad))
                else:
                    u_lo, u_hi = min(lo, t_lo), max(hi, t_hi)
                    pad = self.box_margin * (u_hi - u_lo)
                    lo, hi = float(u_lo - pad), float(u_hi + pad)
            n0 = max(3, len(doms[0].axis_nodes[k]))
            spec[name] = AxisSpec(lo, hi, n0, scale)
        return spec

    def _rebuild(self, train: TrafficSnapshot):
        from bdlz_tpu.emulator.build import build_emulator

        ident = dict(self.service.artifact.identity)
        manifest = getattr(self.service.artifact, "manifest", {}) or {}
        kw: Dict[str, Any] = {
            "rtol": float(manifest.get("rtol_target", 1e-4)),
        }
        if "n_y" in ident:
            kw["n_y"] = int(ident["n_y"])
        if "impl" in ident:
            kw["impl"] = str(ident["impl"])
        kw.update(self.build_kw)
        rs = getattr(self.base, "refine_signal", None)
        if rs not in ("traffic", "traffic*planck"):
            rs = "traffic"
        if self.event_log is not None:
            self.event_log.emit(
                "refine_rebuild_start", refine_signal=rs,
                n_train=train.n_queries, elastic=bool(self.elastic),
            )
        return build_emulator(
            self.base, self._expanded_spec(train),
            refine_signal=rs, traffic=train,
            cache=self.store, elastic=self.elastic,
            event_log=self.event_log,
            **kw,
        )
