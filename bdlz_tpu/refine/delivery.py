"""Auto-publishing delivery gate: candidate vs serving, on held-out traffic.

The daemon's rebuild is a CANDIDATE, not a release: before any replica
serves it, :class:`DeliveryPipeline` scores both surfaces on the
held-out slice of the very traffic that triggered the rebuild — the
per-query **miss score** is 1 for an out-of-domain query (it pays the
exact-pipeline fallback) and ``clip(predicted_error / tol, 0, 1)``
inside (it pays the gate with that probability) — and only a candidate
whose mean miss score beats the serving artifact's proceeds.  Winning
candidates go through the full provenance + rollout chain with zero
operator action: registry publish (content-addressed), blue/green
stage + warm, atomic cutover armed with the post-cutover observation
window, auto-rollback on error-budget breach
(``serve/rollout.py``).  Losing candidates are dropped without
publishing — the registry only ever holds surfaces that earned their
traffic."""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np  # host-side orchestration only (bdlz-lint R1 audit)


class DeliveryError(RuntimeError):
    """A delivery step that must not proceed (no usable gate tolerance,
    empty scoring set)."""


def traffic_miss_score(artifact, locations: np.ndarray, tol: float) -> float:
    """Mean per-query miss probability of ``artifact`` over the
    held-out traffic ``locations`` (see module docstring)."""
    from bdlz_tpu.emulator.grid import make_domain_fn, make_error_fn

    locs = np.atleast_2d(np.asarray(locations, dtype=np.float64))
    if locs.shape[0] == 0:
        raise DeliveryError("empty held-out traffic set; nothing to score")
    import jax.numpy as jnp

    thetas = jnp.asarray(locs)
    inside = np.asarray(make_domain_fn(artifact)(thetas), dtype=bool)
    err = np.asarray(make_error_fn(artifact)(thetas), dtype=np.float64)
    miss = np.where(
        ~inside, 1.0, np.clip(err / float(tol), 0.0, 1.0)
    )
    return float(miss.mean())


class DeliveryPipeline:
    """Score → publish → stage → cutover-under-observation, for one
    :class:`~bdlz_tpu.serve.fleet.FleetService`."""

    def __init__(
        self,
        service,
        store,
        *,
        observe_s: float = 1.0,
        rollback_budget: Optional[float] = None,
        latency_slo_s: Optional[float] = None,
        tol: Optional[float] = None,
        event_log=None,
    ) -> None:
        from bdlz_tpu.serve.rollout import ArtifactRollout

        self.service = service
        self.store = store
        self.rollout = ArtifactRollout(service, store=store)
        if not float(observe_s) > 0.0:
            raise DeliveryError(
                f"observe_s must be > 0, got {observe_s!r}"
            )
        self.observe_s = float(observe_s)
        self.rollback_budget = rollback_budget
        self.latency_slo_s = latency_slo_s
        self._tol = tol
        self.event_log = event_log
        #: Append-only record of every delivery decision (the daemon's
        #: history references these rows).
        self.decisions: list = []

    def _resolve_tol(self, candidate) -> float:
        """The gate tolerance miss scores are normalized by: explicit
        ``tol`` > the service's own error gate > the candidate's
        advertised build tolerance."""
        if self._tol is not None:
            return float(self._tol)
        svc_tol = getattr(self.service, "error_gate_tol", None)
        if isinstance(svc_tol, (int, float)) and not isinstance(
            svc_tol, bool
        ) and float(svc_tol) > 0.0:
            return float(svc_tol)
        from bdlz_tpu.emulator.grid import domain_artifacts

        manifest = getattr(domain_artifacts(candidate)[0], "manifest", {})
        rtol = manifest.get("rtol_target")
        if rtol:
            return float(rtol)
        raise DeliveryError(
            "no gate tolerance anywhere (pipeline tol, service "
            "error_gate_tol, candidate manifest rtol_target) — miss "
            "scores would be unnormalizable"
        )

    def deliver(
        self, candidate, holdout_locations: np.ndarray
    ) -> Dict[str, Any]:
        """Run the full gate for one candidate; returns the decision row
        (also appended to :attr:`decisions`).  ``outcome`` is
        ``"promoted"`` (published + cut over, observation armed) or
        ``"rejected"`` (serving artifact stays, nothing published)."""
        tol = self._resolve_tol(candidate)
        score_new = traffic_miss_score(candidate, holdout_locations, tol)
        score_old = traffic_miss_score(
            self.service.artifact, holdout_locations, tol
        )
        row: Dict[str, Any] = {
            "candidate_score": round(score_new, 6),
            "serving_score": round(score_old, 6),
            "tol": tol,
            "n_holdout": int(np.atleast_2d(holdout_locations).shape[0]),
            "serving_hash": self.service.artifact_hash,
        }
        if score_new >= score_old:
            row["outcome"] = "rejected"
            self.decisions.append(row)
            if self.event_log is not None:
                self.event_log.emit("delivery_decision", **row)
            return row
        from bdlz_tpu.provenance import publish_artifact

        content_hash = publish_artifact(self.store, candidate)
        # stage by BARE HASH, not the in-memory object: the replicas
        # must serve exactly what the registry re-verified, the same
        # admission path any other host of the fleet would take
        self.rollout.stage(content_hash, warm=True)
        old_hash, new_hash = self.rollout.cutover(
            observe_s=self.observe_s,
            budget=self.rollback_budget,
            latency_slo_s=self.latency_slo_s,
        )
        row.update(
            outcome="promoted",
            published_hash=content_hash,
            old_hash=old_hash,
            new_hash=new_hash,
            observe_s=self.observe_s,
        )
        self.decisions.append(row)
        if self.event_log is not None:
            self.event_log.emit("delivery_decision", **row)
        return row
