"""Traffic snapshots: the closed loop's observed-distribution record.

A :class:`TrafficModel` folds a service's per-query trace
(``ServeStats.traffic_log`` — armed by the refinement daemon, zero
overhead otherwise) into an append-only window; :meth:`TrafficModel.snapshot`
freezes the window into a :class:`TrafficSnapshot` whose content
fingerprint (``provenance.traffic_snapshot_identity``) names exactly
what the rebuild was steered by — the fingerprint joins the candidate
artifact's identity as its ``traffic`` key, so "which traffic produced
this surface" is answerable from the hash alone.

Snapshots persist through the provenance :class:`~bdlz_tpu.provenance.Store`
(``put_json`` → ``utils.io.atomic_write_json``, durable): a reader
never sees a torn snapshot, and :func:`load_snapshot` rejects schema
version skew, fingerprint mismatches, and non-finite locations loudly
— the artifact-manifest rules, applied to the traffic plane.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np  # host-side orchestration only (bdlz-lint R1 audit)

#: Bump on ANY change to the persisted snapshot payload shape.  A
#: version-skewed snapshot is rejected loudly at load — silently
#: re-steering a rebuild from a half-understood payload is exactly the
#: failure the artifact manifest rules exist to prevent.
TRAFFIC_SCHEMA_VERSION = 1

#: Store entry prefix (docs/provenance.md store layout).
SNAPSHOT_KIND = "traffic_snapshot"


class TrafficSnapshotError(RuntimeError):
    """A snapshot that must not be used: NaN locations, schema version
    skew, fingerprint mismatch, or shape disagreement."""


def snapshot_entry_name(fingerprint: str) -> str:
    return f"{SNAPSHOT_KIND}/{fingerprint}.json"


@dataclass(frozen=True)
class TrafficSnapshot:
    """One frozen window of served traffic: query locations in the
    emulator's axis order, the per-query fallback reason (None =
    emulator fast path), and the per-pool batch occupancy observed
    while the window accumulated."""

    axis_names: Tuple[str, ...]
    locations: np.ndarray                     # (N, d) float64
    reasons: Tuple[Optional[str], ...]        # len N
    occupancy: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self) -> None:
        locs = np.atleast_2d(np.asarray(self.locations, dtype=np.float64))
        if locs.ndim != 2 or locs.shape[1] != len(self.axis_names):
            raise TrafficSnapshotError(
                f"locations shape {locs.shape} does not match "
                f"{len(self.axis_names)} axes {tuple(self.axis_names)}"
            )
        if not np.all(np.isfinite(locs)):
            # a NaN location would silently vanish from the histogram
            # the rebuild steers on — reject at the source, loudly
            bad = int((~np.isfinite(locs)).any(axis=1).sum())
            raise TrafficSnapshotError(
                f"{bad}/{locs.shape[0]} query locations are non-finite; "
                "refusing to build a snapshot that would silently "
                "mis-weight the rebuild"
            )
        if len(self.reasons) != locs.shape[0]:
            raise TrafficSnapshotError(
                f"{len(self.reasons)} reasons for {locs.shape[0]} "
                "query locations"
            )
        object.__setattr__(self, "locations", locs)

    # ---- derived rates (the daemon's drift inputs) ------------------

    @property
    def n_queries(self) -> int:
        return int(self.locations.shape[0])

    def _rate(self, *names: str) -> float:
        if not self.reasons:
            return 0.0
        return sum(r in names for r in self.reasons) / len(self.reasons)

    @property
    def ood_rate(self) -> float:
        return self._rate("ood")

    @property
    def gated_rate(self) -> float:
        return self._rate("predicted_error")

    @property
    def fallback_rate(self) -> float:
        return sum(r is not None for r in self.reasons) / max(
            len(self.reasons), 1
        )

    # ---- identity ---------------------------------------------------

    @property
    def fingerprint(self) -> str:
        """Content hash (16 hex) of exactly what steers a rebuild."""
        from bdlz_tpu.provenance import traffic_snapshot_identity

        return traffic_snapshot_identity(
            self.axis_names, self.locations, self.reasons, self.occupancy
        ).digest(16)

    # ---- held-out split (the delivery gate's scoring set) -----------

    def split_holdout(
        self, frac: float = 0.25
    ) -> Tuple["TrafficSnapshot", np.ndarray]:
        """Deterministically hold out ~``frac`` of the queries: every
        k-th row (k = round(1/frac)) becomes the held-out scoring set
        the DELIVERY gate judges candidates on, the rest steer the
        rebuild — the build must never be graded on points it was
        weighted toward.  Returns ``(train_snapshot, held_locations)``;
        with fewer than ``2/frac`` queries the full snapshot trains and
        the full location set scores (too little traffic to split)."""
        if not (0.0 < float(frac) < 1.0):
            raise TrafficSnapshotError(
                f"holdout frac must be in (0, 1), got {frac!r}"
            )
        k = max(int(round(1.0 / float(frac))), 2)
        if self.n_queries < 2 * k:
            return self, np.array(self.locations, copy=True)
        held = np.arange(self.n_queries) % k == 0
        train = TrafficSnapshot(
            axis_names=self.axis_names,
            locations=self.locations[~held],
            reasons=tuple(
                r for r, h in zip(self.reasons, held) if not h
            ),
            occupancy=dict(self.occupancy),
        )
        return train, np.array(self.locations[held], copy=True)


class TrafficModel:
    """Folds a service's ``ServeStats`` into a rolling traffic window.

    Incremental by cursor: each :meth:`fold` consumes only the
    ``traffic_log`` entries (and occupancy rows) appended since the
    last call, so the daemon can fold on every tick without rescanning
    history.  ``window`` bounds the retained queries (oldest dropped) —
    drift detection must see the CURRENT distribution, not the
    all-time mixture that a growing unbounded window converges to.
    """

    def __init__(
        self,
        axis_names,
        *,
        window: Optional[int] = 512,
    ) -> None:
        self.axis_names = tuple(str(n) for n in axis_names)
        if window is not None and int(window) < 1:
            raise TrafficSnapshotError(
                f"window must be a positive query count, got {window!r}"
            )
        self.window = None if window is None else int(window)
        self._queries: List[Tuple[Tuple[float, ...], Optional[str]]] = []
        self._log_cursor: Dict[int, int] = {}
        self._row_cursor: Dict[int, int] = {}
        self._occ_sum: Dict[str, float] = {}
        self._occ_n: Dict[str, int] = {}

    def fold(self, stats, pool: str = "default") -> int:
        """Consume the NEW entries of ``stats`` (a ``ServeStats``);
        returns how many queries were folded.  ``pool`` labels the
        occupancy stream (one key per served pool under tenancy)."""
        key = id(stats)
        folded = 0
        log = stats.traffic_log
        if log is not None:
            start = self._log_cursor.get(key, 0)
            fresh = log[start:]
            self._log_cursor[key] = len(log)
            for theta, reason in fresh:
                self._queries.append((
                    tuple(float(v) for v in theta),
                    None if reason is None else str(reason),
                ))
                folded += 1
        row_start = self._row_cursor.get(key, 0)
        for row in stats.rows[row_start:]:
            self._occ_sum[pool] = (
                self._occ_sum.get(pool, 0.0) + float(row.occupancy)
            )
            self._occ_n[pool] = self._occ_n.get(pool, 0) + 1
        self._row_cursor[key] = len(stats.rows)
        if self.window is not None and len(self._queries) > self.window:
            del self._queries[: len(self._queries) - self.window]
        return folded

    # ---- window introspection (the daemon's drift test) -------------

    @property
    def n_queries(self) -> int:
        return len(self._queries)

    def _rate(self, *names: str) -> float:
        if not self._queries:
            return 0.0
        return sum(
            r in names for _, r in self._queries
        ) / len(self._queries)

    @property
    def ood_rate(self) -> float:
        return self._rate("ood")

    @property
    def gated_rate(self) -> float:
        return self._rate("predicted_error")

    def reset_window(self) -> None:
        """Drop the accumulated queries (cursors stay — already-consumed
        log entries are never re-folded).  The daemon calls this after
        every delivery cycle: drift on the NEW surface must be measured
        from fresh traffic, not from the window that triggered the last
        rebuild."""
        self._queries = []

    def occupancy(self) -> Dict[str, float]:
        return {
            pool: round(self._occ_sum[pool] / self._occ_n[pool], 4)
            for pool in sorted(self._occ_sum)
            if self._occ_n.get(pool)
        }

    def snapshot(self) -> TrafficSnapshot:
        """Freeze the current window (raises on an empty one — there is
        nothing to steer a rebuild by)."""
        if not self._queries:
            raise TrafficSnapshotError(
                "no served queries folded yet; nothing to snapshot"
            )
        return TrafficSnapshot(
            axis_names=self.axis_names,
            locations=np.asarray(
                [q for q, _ in self._queries], dtype=np.float64
            ),
            reasons=tuple(r for _, r in self._queries),
            occupancy=self.occupancy(),
        )


# ---- persistence (provenance store; atomic + schema-pinned) ---------


def save_snapshot(store, snap: TrafficSnapshot) -> str:
    """Persist ``snap`` into the provenance store under its own
    fingerprint (``Store.put_json`` → ``atomic_write_json(durable=True)``:
    a reader concurrent with the write sees the old entry or the new
    one, never a torn file).  Returns the fingerprint."""
    fp = snap.fingerprint
    store.put_json(snapshot_entry_name(fp), {
        "schema": TRAFFIC_SCHEMA_VERSION,
        "fingerprint": fp,
        "axis_names": list(snap.axis_names),
        "locations": [[float(v) for v in row] for row in snap.locations],
        "reasons": list(snap.reasons),
        "occupancy": dict(snap.occupancy),
    })
    return fp


def load_snapshot(store, fingerprint: str) -> TrafficSnapshot:
    """Load + fully re-verify a persisted snapshot: absent entries,
    schema version skew, and content drift (recomputed fingerprint ≠
    entry name) all raise :class:`TrafficSnapshotError` — a rebuild
    steered by a snapshot that is not exactly what its name claims
    would poison the artifact identity chain downstream."""
    payload: Optional[Dict[str, Any]] = store.get_json(
        snapshot_entry_name(fingerprint)
    )
    if payload is None:
        raise TrafficSnapshotError(
            f"traffic snapshot {fingerprint} is not in the store"
        )
    schema = payload.get("schema")
    if schema != TRAFFIC_SCHEMA_VERSION:
        raise TrafficSnapshotError(
            f"traffic snapshot {fingerprint} has schema version "
            f"{schema!r}; this build reads version "
            f"{TRAFFIC_SCHEMA_VERSION} — refusing to guess at a "
            "version-skewed payload"
        )
    snap = TrafficSnapshot(
        axis_names=tuple(payload["axis_names"]),
        locations=np.asarray(payload["locations"], dtype=np.float64),
        reasons=tuple(payload["reasons"]),
        occupancy=dict(payload.get("occupancy", {})),
    )
    if snap.fingerprint != fingerprint:
        raise TrafficSnapshotError(
            f"traffic snapshot content hashes to {snap.fingerprint}, "
            f"not the requested {fingerprint} — the entry was renamed "
            "or tampered with"
        )
    return snap
