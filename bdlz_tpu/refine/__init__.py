"""Closed-loop continuous delivery (docs/serving.md "Closed loop").

The serving plane records where traffic actually lands
(``ServeStats.traffic_log``); this package folds that trace into a
content-hashed :class:`TrafficSnapshot`, watches the observed
distribution for drift (:class:`RefinementDaemon`), rebuilds the
emulator weighted by the observed density (``refine_signal="traffic"``,
``emulator/build.py``), and — when the candidate beats the serving
surface on held-out traffic — publishes and cuts it over under
observation with automatic rollback (:class:`DeliveryPipeline`), with
zero operator action.
"""
from bdlz_tpu.refine.daemon import (
    RefineError,
    RefinementDaemon,
    resolve_self_improve,
)
from bdlz_tpu.refine.delivery import DeliveryPipeline
from bdlz_tpu.refine.traffic import (
    TRAFFIC_SCHEMA_VERSION,
    TrafficModel,
    TrafficSnapshot,
    TrafficSnapshotError,
    load_snapshot,
    save_snapshot,
    snapshot_entry_name,
)

__all__ = [
    "TRAFFIC_SCHEMA_VERSION",
    "DeliveryPipeline",
    "RefineError",
    "RefinementDaemon",
    "TrafficModel",
    "TrafficSnapshot",
    "TrafficSnapshotError",
    "load_snapshot",
    "resolve_self_improve",
    "save_snapshot",
    "snapshot_entry_name",
]
