"""The emulator's jitted query kernel: log-space tensor-grid interpolation.

Queries are (batch, d) parameter vectors in config-schema units (axis
order = the artifact's ``axis_names``).  Values are interpolated
multilinearly in **log10 of the stored field** over the (possibly
non-uniform — refinement inserts midpoints where the surface curves)
per-axis node arrays: the yield surface spans many decades and is far
closer to log-linear than linear across a cell, so log-space
interpolation is what makes the adaptive build's rel-tol target cheap
to hit.  Everything inside the kernel is pure gathers + FMAs on
``jnp`` arrays captured at closure time — trace-safe, vmapped, jitted
once per artifact shape (the closure pins the arrays, so one compiled
program serves every query batch of the same length).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Sequence

import numpy as np  # host-side use only; jitted paths go through the backend.py xp seam (bdlz-lint R1 audit)

from bdlz_tpu.emulator.artifact import EmulatorArtifact

Array = Any


def axis_coord(x: Array, scale: str, xp) -> Array:
    """The interpolation coordinate of one axis value.

    ``"log"`` axes interpolate in log10(x): the yield surface is near
    power-law in those parameters, so a linear-in-x stencil on geomspace
    nodes would carry curvature no amount of node doubling removes
    cheaply — in log-log a power law is exactly linear.  ``"lin"`` axes
    interpolate in x.
    """
    if scale == "log":
        return xp.log10(x)
    return x


def interp_log_fields(
    theta: Array,
    axis_nodes: Sequence[Array],
    axis_scales: Sequence[str],
    log_values: Dict[str, Array],
    xp,
) -> Dict[str, Array]:
    """Interpolate every field at ONE query point ``theta`` (shape (d,)).

    Trace-safe core shared by the vmapped query kernel and the
    likelihood fast path (which evaluates one walker at a time under
    the ensemble's vmap).  Coordinates are clamped into the node range
    — domain policy (reject / exact fallback / −inf prior) is the
    CALLER'S job via :func:`in_domain_one`; clamping here keeps the
    kernel total so a jitted caller can mask afterwards.

    Multilinear over the 2^d cell corners, in log10 of the VALUES, with
    each axis's fractional offset computed in that axis's own scale
    coordinate (:func:`axis_coord`); the bracketing search runs on the
    raw (possibly non-uniform — refinement inserts midpoints) node
    arrays, which a monotone coordinate transform leaves valid.
    """
    d = len(axis_nodes)
    idx = []
    frac = []
    for k in range(d):
        nodes = axis_nodes[k]
        scale = axis_scales[k]
        n_k = nodes.shape[0]
        x = xp.clip(theta[k], nodes[0], nodes[-1])
        i = xp.clip(
            xp.searchsorted(nodes, x, side="right") - 1, 0, n_k - 2
        ).astype("int32")
        u = axis_coord(x, scale, xp)
        u0 = axis_coord(nodes[i], scale, xp)
        u1 = axis_coord(nodes[i + 1], scale, xp)
        t = (u - u0) / (u1 - u0)
        idx.append(i)
        frac.append(t)

    out: Dict[str, Array] = {}
    # d is trace-static (artifact shape), so the 2^d corner loop unrolls
    # at trace time into pure gathers + FMAs.
    corner_weights = []
    corner_indices = []
    for corner in range(1 << d):
        w = 1.0
        ind = []
        for k in range(d):
            bit = (corner >> k) & 1
            w = w * (frac[k] if bit else (1.0 - frac[k]))
            ind.append(idx[k] + bit)
        corner_weights.append(w)
        corner_indices.append(tuple(ind))
    for name, logv in log_values.items():
        acc = 0.0
        for w, ind in zip(corner_weights, corner_indices):
            acc = acc + w * logv[ind]
        out[name] = acc
    return out


def in_domain_one(theta: Array, axis_nodes: Sequence[Array], xp) -> Array:
    """True iff every coordinate of one (d,) query lies inside the box."""
    ok = True
    for k, nodes in enumerate(axis_nodes):
        ok = xp.logical_and(
            ok,
            xp.logical_and(theta[k] >= nodes[0], theta[k] <= nodes[-1]),
        )
    return ok


def device_tables(artifact: EmulatorArtifact, fields: Sequence[str]):
    """(axis_nodes, log_values) as jnp arrays — the one host→device ship."""
    from bdlz_tpu.backend import ensure_x64

    ensure_x64()
    import jax.numpy as jnp

    nodes = tuple(jnp.asarray(np.asarray(a, dtype=np.float64))
                  for a in artifact.axis_nodes)
    logv = {
        name: jnp.asarray(np.log10(np.asarray(artifact.values[name],
                                              dtype=np.float64)))
        for name in fields
    }
    return nodes, logv


def make_query_fn(
    artifact: EmulatorArtifact, field: str = "DM_over_B"
) -> Callable:
    """Jitted, vmapped ``query(thetas (B, d)) -> values (B,)``.

    Compiles once per (artifact shape, batch length): the node/value
    arrays are closure-captured device constants, so repeated calls at
    a fixed batch size reuse one XLA program — the serving layer pads
    its batches to a fixed size for exactly this reason.
    """
    if field not in artifact.values:
        raise KeyError(
            f"field {field!r} not in artifact (has {sorted(artifact.values)})"
        )
    import jax
    import jax.numpy as jnp

    nodes, logv = device_tables(artifact, (field,))
    scales = artifact.axis_scales

    def one(theta):
        log_f = interp_log_fields(theta, nodes, scales, logv, jnp)[field]
        return 10.0 ** log_f

    return jax.jit(jax.vmap(one))


def make_domain_fn(artifact: EmulatorArtifact) -> Callable:
    """Jitted, vmapped ``in_domain(thetas (B, d)) -> bool (B,)``."""
    import jax
    import jax.numpy as jnp

    nodes, _ = device_tables(artifact, ())

    def one(theta):
        return in_domain_one(theta, nodes, jnp)

    return jax.jit(jax.vmap(one))
