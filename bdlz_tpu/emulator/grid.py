"""The emulator's jitted query kernel: log-space tensor-grid interpolation.

Queries are (batch, d) parameter vectors in config-schema units (axis
order = the artifact's ``axis_names``).  Values are interpolated
multilinearly in **log10 of the stored field** over the (possibly
non-uniform — refinement inserts midpoints where the surface curves)
per-axis node arrays: the yield surface spans many decades and is far
closer to log-linear than linear across a cell, so log-space
interpolation is what makes the adaptive build's rel-tol target cheap
to hit.  Everything inside the kernel is pure gathers + FMAs on
``jnp`` arrays captured at closure time — trace-safe, vmapped, jitted
once per artifact shape (the closure pins the arrays, so one compiled
program serves every query batch of the same length).

Every kernel builder here accepts a single-domain
:class:`~bdlz_tpu.emulator.artifact.EmulatorArtifact` OR a seam-split
:class:`~bdlz_tpu.emulator.multidomain.MultiDomainArtifact`: the
multi-domain case evaluates every domain's (identical-arithmetic)
stencil and routes each query to the domain that contains it with a
``where`` select — per-domain values are therefore BIT-identical to a
standalone query of that sub-artifact (pinned in
``tests/test_multidomain.py``); a query inside no domain (the seam
band, or outside the hull) is simply out-of-domain and takes whatever
fallback policy the caller owns.

The per-cell PREDICTED ERROR kernel (:func:`make_error_fn`) gathers the
artifact's persisted a-posteriori estimate for the cell a query lands
in; an artifact that missed its advertised tolerance (``converged``
false — its estimates demonstrably under-predicted somewhere) is
floored at its held-out ``max_rel_err``, so the serve layer's error
gate can never trust a surface more than its own validation did.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import numpy as np  # host-side use only; jitted paths go through the backend.py xp seam (bdlz-lint R1 audit)

from bdlz_tpu.emulator.artifact import EmulatorArtifact

Array = Any


def domain_artifacts(artifact) -> Tuple[EmulatorArtifact, ...]:
    """The single-domain artifacts behind ``artifact`` (itself, or a
    multi-domain bundle's ordered domain tuple) — the one adapter every
    kernel builder and serving front goes through."""
    domains = getattr(artifact, "domains", None)
    if domains is not None:
        return tuple(domains)
    return (artifact,)


def artifact_hull(artifact) -> Tuple[np.ndarray, np.ndarray]:
    """(lo, hi) corner vectors of the artifact's overall box (the union
    hull for a multi-domain bundle) — warm-start probes and bench trace
    generators use this instead of reaching into ``axis_nodes``."""
    return artifact.hull


def error_floor(artifact) -> float:
    """The artifact-level lower bound on its predicted error.

    A build that CONVERGED (pool clean, every interval estimate under
    the internal target, held-out inside tolerance) has earned per-cell
    trust: floor 0.0.  A build that missed its contract gets +inf: its
    own estimates demonstrably failed to control the error (MEASURED on
    the seam box: a held-out draw can score 8e-5 while the surface
    serves answers 8e-3 wrong — second differences straddling a kink
    under-predict), so no finite per-cell statement from it is
    trustworthy and any active error gate routes every in-domain query
    to the exact path — the old "serve exact" policy for untrusted
    surfaces, now automatic and measured.  An operator who wants the
    unverified surface anyway disables the gate explicitly
    (``error_gate_tol=false``).
    """
    return 0.0 if artifact.manifest.get("converged") is True else float("inf")


def has_error_grid(artifact) -> bool:
    """True when every domain carries a per-cell predicted-error grid."""
    return all(
        d.predicted_error is not None for d in domain_artifacts(artifact)
    )


def axis_coord(x: Array, scale: str, xp) -> Array:
    """The interpolation coordinate of one axis value.

    ``"log"`` axes interpolate in log10(x): the yield surface is near
    power-law in those parameters, so a linear-in-x stencil on geomspace
    nodes would carry curvature no amount of node doubling removes
    cheaply — in log-log a power law is exactly linear.  ``"lin"`` axes
    interpolate in x.
    """
    if scale == "log":
        return xp.log10(x)
    return x


def interp_log_fields(
    theta: Array,
    axis_nodes: Sequence[Array],
    axis_scales: Sequence[str],
    log_values: Dict[str, Array],
    xp,
) -> Dict[str, Array]:
    """Interpolate every field at ONE query point ``theta`` (shape (d,)).

    Trace-safe core shared by the vmapped query kernel and the
    likelihood fast path (which evaluates one walker at a time under
    the ensemble's vmap).  Coordinates are clamped into the node range
    — domain policy (reject / exact fallback / −inf prior) is the
    CALLER'S job via :func:`in_domain_one`; clamping here keeps the
    kernel total so a jitted caller can mask afterwards.

    Multilinear over the 2^d cell corners, in log10 of the VALUES, with
    each axis's fractional offset computed in that axis's own scale
    coordinate (:func:`axis_coord`); the bracketing search runs on the
    raw (possibly non-uniform — refinement inserts midpoints) node
    arrays, which a monotone coordinate transform leaves valid.
    """
    d = len(axis_nodes)
    idx = []
    frac = []
    for k in range(d):
        nodes = axis_nodes[k]
        scale = axis_scales[k]
        n_k = nodes.shape[0]
        x = xp.clip(theta[k], nodes[0], nodes[-1])
        i = xp.clip(
            xp.searchsorted(nodes, x, side="right") - 1, 0, n_k - 2
        ).astype("int32")
        u = axis_coord(x, scale, xp)
        u0 = axis_coord(nodes[i], scale, xp)
        u1 = axis_coord(nodes[i + 1], scale, xp)
        t = (u - u0) / (u1 - u0)
        idx.append(i)
        frac.append(t)

    out: Dict[str, Array] = {}
    # d is trace-static (artifact shape), so the 2^d corner loop unrolls
    # at trace time into pure gathers + FMAs.
    corner_weights = []
    corner_indices = []
    for corner in range(1 << d):
        w = 1.0
        ind = []
        for k in range(d):
            bit = (corner >> k) & 1
            w = w * (frac[k] if bit else (1.0 - frac[k]))
            ind.append(idx[k] + bit)
        corner_weights.append(w)
        corner_indices.append(tuple(ind))
    for name, logv in log_values.items():
        acc = 0.0
        for w, ind in zip(corner_weights, corner_indices):
            acc = acc + w * logv[ind]
        out[name] = acc
    return out


def in_domain_one(theta: Array, axis_nodes: Sequence[Array], xp) -> Array:
    """True iff every coordinate of one (d,) query lies inside the box."""
    ok = True
    for k, nodes in enumerate(axis_nodes):
        ok = xp.logical_and(
            ok,
            xp.logical_and(theta[k] >= nodes[0], theta[k] <= nodes[-1]),
        )
    return ok


def device_tables(artifact: EmulatorArtifact, fields: Sequence[str]):
    """(axis_nodes, log_values) as jnp arrays — the one host→device ship."""
    from bdlz_tpu.backend import ensure_x64

    ensure_x64()
    import jax.numpy as jnp

    nodes = tuple(jnp.asarray(np.asarray(a, dtype=np.float64))
                  for a in artifact.axis_nodes)
    logv = {
        name: jnp.asarray(np.log10(np.asarray(artifact.values[name],
                                              dtype=np.float64)))
        for name in fields
    }
    return nodes, logv


def predicted_error_one(
    theta: Array,
    axis_nodes: Sequence[Array],
    error_grid: Array,
    floor,
    xp,
) -> Array:
    """Predicted relative error of the cell one (d,) query lands in.

    Same clamped bracketing rule as :func:`interp_log_fields` (so the
    gathered cell IS the interpolation cell), then a single gather from
    the persisted ``(n_1-1, ..., n_d-1)`` estimate grid, floored at the
    artifact-level :func:`error_floor`.  Trace-safe: pure clips,
    searchsorted, and one gather.
    """
    idx = []
    for k, nodes in enumerate(axis_nodes):
        n_k = nodes.shape[0]
        x = xp.clip(theta[k], nodes[0], nodes[-1])
        idx.append(xp.clip(
            xp.searchsorted(nodes, x, side="right") - 1, 0, n_k - 2
        ).astype("int32"))
    return xp.maximum(error_grid[tuple(idx)], floor)


def domain_error_table(dom: EmulatorArtifact, xp):
    """The device-resident (error_grid, floor) pair of one domain; a
    grid-less domain degrades to a constant grid at its floor."""
    floor = error_floor(dom)
    if dom.predicted_error is None:
        cells = tuple(len(n) - 1 for n in dom.axis_nodes)
        grid = np.zeros(cells)
    else:
        grid = np.asarray(dom.predicted_error, dtype=np.float64)
    return xp.asarray(grid), floor


def select_domains(theta, tables, eval_one, xp):
    """THE multi-domain routing rule, shared by every jitted consumer
    (query/error kernels here, the fleet's fused replica kernel, the
    likelihood fast mode): evaluate ``eval_one(table, theta) ->
    (payload_tuple, inside)`` per domain and fold a ``where`` select —
    the FIRST domain's payload is the out-of-domain default (edge-
    clamped; callers mask via the returned ``inside_any``), later
    domains overwrite where they contain the query.  Domains are
    disjoint by construction, so at most one select fires and a
    contained query's payload is BIT-identical to evaluating that
    domain alone.  Returns ``(payload_tuple, inside_any)``."""
    out = None
    inside_any = False
    for table in tables:
        payload, inside = eval_one(table, theta)
        if out is None:
            out = list(payload)
        else:
            out = [xp.where(inside, p, o) for p, o in zip(payload, out)]
        inside_any = xp.logical_or(inside_any, inside)
    return tuple(out), inside_any


def make_query_fn(artifact, field: str = "DM_over_B") -> Callable:
    """Jitted, vmapped ``query(thetas (B, d)) -> values (B,)``.

    Compiles once per (artifact shape, batch length): the node/value
    arrays are closure-captured device constants, so repeated calls at
    a fixed batch size reuse one XLA program — the serving layer pads
    its batches to a fixed size for exactly this reason.  A
    multi-domain bundle routes each query to its containing domain via
    a ``where`` select over the per-domain stencils (domains are
    disjoint, so at most one select fires; a query in no domain returns
    the FIRST domain's edge-clamped value, which the caller masks via
    :func:`make_domain_fn`).
    """
    doms = domain_artifacts(artifact)
    for dom in doms:
        if field not in dom.values:
            raise KeyError(
                f"field {field!r} not in artifact "
                f"(has {sorted(dom.values)})"
            )
    import jax
    import jax.numpy as jnp

    tables = [(device_tables(d, (field,)), d.axis_scales) for d in doms]

    def eval_one(table, theta):
        (nodes, logv), scales = table
        val = 10.0 ** interp_log_fields(theta, nodes, scales, logv, jnp)[field]
        return (val,), in_domain_one(theta, nodes, jnp)

    def one(theta):
        (val,), _inside = select_domains(theta, tables, eval_one, jnp)
        return val

    return jax.jit(jax.vmap(one))


def make_domain_fn(artifact) -> Callable:
    """Jitted, vmapped ``in_domain(thetas (B, d)) -> bool (B,)`` — for a
    multi-domain bundle, True iff SOME domain contains the query (the
    seam band between domains is out-of-domain by construction)."""
    import jax
    import jax.numpy as jnp

    all_nodes = [device_tables(d, ())[0] for d in domain_artifacts(artifact)]

    def eval_one(nodes, theta):
        return (), in_domain_one(theta, nodes, jnp)

    def one(theta):
        _none, inside = select_domains(theta, all_nodes, eval_one, jnp)
        return inside

    return jax.jit(jax.vmap(one))


def make_error_fn(artifact) -> Callable:
    """Jitted, vmapped ``predicted_error(thetas (B, d)) -> err (B,)``.

    The serving layer's gate input: the per-cell a-posteriori estimate
    of the cell each query lands in (floored at the artifact-level
    :func:`error_floor`), routed to the containing domain exactly like
    :func:`make_query_fn`.  Out-of-domain queries return the first
    domain's clamped-cell value — meaningless but harmless, because the
    gate only applies to in-domain traffic (OOD already falls back).
    """
    import jax
    import jax.numpy as jnp

    doms = domain_artifacts(artifact)
    tables = [
        (device_tables(d, ())[0], domain_error_table(d, jnp)) for d in doms
    ]

    def eval_one(table, theta):
        nodes, (grid, floor) = table
        err = predicted_error_one(theta, nodes, grid, floor, jnp)
        return (err,), in_domain_one(theta, nodes, jnp)

    def one(theta):
        (err,), _inside = select_domains(theta, tables, eval_one, jnp)
        return err

    return jax.jit(jax.vmap(one))
