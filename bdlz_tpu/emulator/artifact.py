"""Versioned yield-surface emulator artifacts: save, load, reject-loudly.

An artifact is a directory holding

* ``artifact.npz`` — one ``axis_<name>`` node array per parameter axis
  (strictly increasing, config-schema units) and one ``field_<name>``
  value array per emitted pipeline output, shaped ``(n_1, …, n_d)`` in
  axis order (C-order, matching ``parallel.sweep.build_grid``'s
  first-axis-slowest convention);
* ``manifest.json`` — schema version, identity (the resolved base
  config / static choices / n_y / engine the surface was computed
  with), build provenance (refinement rounds, held-out max rel err,
  build seconds), and a content hash.

The hash follows the ``run_sweep`` resume-hash pattern
(``grid_hash``: config identity + axes + n_y + impl) extended with the
value bytes and the schema version, so EVERY way an artifact can go
stale is loud: changed physics knobs change the identity hash, a
modified/corrupt ``.npz`` changes the value hash, and a schema change
changes the version.  A mismatch is :class:`EmulatorArtifactError` at
load — a stale emulator silently serving wrong yields is the one
failure mode this layer must never have.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Mapping, NamedTuple, Sequence, Tuple

import numpy as np  # host-side use only; jitted paths go through the backend.py xp seam (bdlz-lint R1 audit)

#: Bump whenever the artifact layout or manifest meaning changes: a
#: version mismatch at load is an explicit error, never a reinterpret.
#: v2 (seam-split PR): artifacts persist the per-cell a-posteriori
#: predicted-error grid the refiner computes (the serve layer's exact-
#: fallback gate) — it joins the content hash, so v1 artifacts reject
#: LOUDLY at the version check and must be rebuilt.
SCHEMA_VERSION = 2

#: The pipeline outputs an artifact carries (YieldsResult field order).
FIELDS = ("Y_B", "Y_chi", "rho_B_kg_m3", "rho_DM_kg_m3", "DM_over_B")


class EmulatorArtifactError(ValueError):
    """A stale, tampered, or malformed emulator artifact.

    A dedicated type so callers can distinguish "this artifact must be
    rebuilt" from unrelated ValueErrors — and so tests can pin that
    every rejection path raises it explicitly."""


class EmulatorArtifact(NamedTuple):
    """One loaded (or freshly built) yield-surface emulator."""

    axis_names: Tuple[str, ...]            # config-schema axis names, in order
    axis_nodes: Tuple[np.ndarray, ...]     # strictly increasing f64 nodes
    axis_scales: Tuple[str, ...]           # "lin" | "log" interpolation coord
    values: Dict[str, np.ndarray]          # field -> (n_1, ..., n_d) f64
    identity: Dict[str, Any]               # resolved config/static/n_y/impl
    manifest: Dict[str, Any]               # full manifest payload
    #: Per-cell a-posteriori relative-error estimate (|f2|h^2/8*ln10,
    #: maxed over fields and axes), shape ``(n_1-1, ..., n_d-1)`` — the
    #: numbers the refiner steered on, persisted so the serving layer
    #: can gate exact fallback on PREDICTED error instead of only on
    #: domain membership.  None on artifacts that never computed one
    #: (hand-assembled fixtures); the serve gate then degrades to the
    #: artifact-level held-out number.
    predicted_error: "np.ndarray | None" = None

    @property
    def domain(self) -> Dict[str, Tuple[float, float]]:
        return {
            name: (float(nodes[0]), float(nodes[-1]))
            for name, nodes in zip(self.axis_names, self.axis_nodes)
        }

    @property
    def hull(self) -> Tuple[np.ndarray, np.ndarray]:
        """(lo, hi) corner vectors of the box, in axis order — the one
        rule every warm-start probe and bench trace generator uses, and
        the piece of the interface a multi-domain bundle shares."""
        return (
            np.asarray([float(n[0]) for n in self.axis_nodes]),
            np.asarray([float(n[-1]) for n in self.axis_nodes]),
        )

    @property
    def n_points(self) -> int:
        n = 1
        for nodes in self.axis_nodes:
            n *= len(nodes)
        return n

    @property
    def content_hash(self) -> str:
        """The artifact's content hash — the token the serving fleet
        stamps on every response and the rollout layer agrees on across
        hosts.  Loaded artifacts carry it in the manifest (already
        verified against the bytes at load); a freshly built, not yet
        saved artifact computes it on demand — either way the value is
        identical to what :func:`save_artifact` would write."""
        h = self.manifest.get("hash")
        if h is not None:
            return str(h)
        return artifact_hash(
            self.axis_names, self.axis_nodes, self.axis_scales,
            self.values, self.identity,
            predicted_error=self.predicted_error,
        )


def build_identity(
    base, static, n_y: int, impl: str,
    posterior_weight: "str | None" = None,
    lz_profile_fp: "str | None" = None,
    refine_signal: "str | None" = None,
    bounce_fp: "str | None" = None,
    traffic_fp: "str | None" = None,
) -> Dict[str, Any]:
    """The physics identity an artifact is valid for.

    Same ingredients as ``parallel.sweep.grid_hash`` (config through
    ``config_identity_dict`` so adding a defaulted extension field does
    not invalidate every existing artifact; resolved StaticChoices;
    n_y; engine) — an emulator is a cache of ``run_sweep`` output and
    must go stale exactly when a sweep directory would.

    The quadrature tri-state is carried as its own ``quad_panel_gl``
    key, present IFF the caller's static resolves it (True or False) —
    surfaces computed under different y-quadrature schemes hash (and
    therefore reject) differently, while a consumer whose static leaves
    the knob ``None`` emits no key and is expected to ADOPT the
    artifact's recorded scheme before checking (see
    :func:`check_identity` / the serve + likelihood layers).  The knob
    is normalized OUT of the static tuple so this key is its single
    home in the identity.

    ``posterior_weight`` follows the same single-home pattern: when the
    build's refinement criterion was posterior-weighted (explicit
    argument, else the base config's knob), the resolved weight name is
    its own ``posterior_weight`` key — weighted and unweighted surfaces
    over the same box place nodes differently and must never be
    confused, while a consumer that states no expectation matches
    either (``check_identity``'s wildcard rule).  The knob is excluded
    from the config payload (``config.EMULATOR_CONFIG_FIELDS``), so
    this key is its single home too.

    The LZ scenario plane (docs/scenarios.md) joins the same way: a
    chain/thermal surface carries its resolved scenario as its own
    ``lz_scenario`` key (mode + parameters; omit-at-default, single
    home — ``config.SCENARIO_*_FIELDS`` exclude the knobs everywhere
    else) and is STRICT both ways in ``check_identity`` — cross-mode
    artifact/consumer skew must reject loudly.  ``lz_profile_fp``
    (the bounce-profile fingerprint the per-point P was derived from)
    is its own ``lz_profile`` key with the posterior_weight wildcard
    rule: strict when the caller states a profile, wildcard when not.
    ``bounce_fp`` (the POTENTIAL fingerprint when the profile was shot
    in-framework from a :class:`~bdlz_tpu.bounce.PotentialSpec` rather
    than loaded from a CSV) joins the same way as its own ``bounce``
    key — wildcard-when-unstated, so profile-fed artifacts keep their
    hashes, but two potentials can never share a surface.

    ``traffic_fp`` (the content fingerprint of the served-traffic
    snapshot a ``refine_signal="traffic"``/``"traffic*planck"`` build
    was weighted by, ``bdlz_tpu/refine/traffic.py``) joins as its own
    ``traffic`` key with the same wildcard rule: two snapshots place
    nodes differently and must never share a surface, while a consumer
    that states no snapshot (every pre-closed-loop caller) matches any.
    """
    from bdlz_tpu.config import (
        ROBUSTNESS_STATIC_FIELDS,
        SCENARIO_STATIC_FIELDS,
        config_identity_dict,
    )
    from bdlz_tpu.lz.sweep_bridge import scenario_identity

    quad = static.quad_panel_gl
    st = static._replace(quad_panel_gl=None)
    if posterior_weight is None:
        posterior_weight = getattr(base, "posterior_weight", None)
    excluded = set(ROBUSTNESS_STATIC_FIELDS) | set(SCENARIO_STATIC_FIELDS)
    out = {
        "base": config_identity_dict(base),
        # robustness knobs (retry/fault gates) are orchestration-only
        # and excluded: with faults off they cannot change a value bit,
        # and keying them in would stale every pre-existing artifact.
        # The scenario knobs are excluded from the POSITIONAL list too —
        # their single home is the lz_scenario key below, which keeps
        # every pre-scenario artifact hash byte-stable.
        "static": [
            v for f, v in zip(type(st)._fields, st) if f not in excluded
        ],
        "n_y": int(n_y),
        "impl": str(impl),
    }
    if quad is not None:
        out["quad_panel_gl"] = bool(quad)
    if posterior_weight is not None:
        out["posterior_weight"] = str(posterior_weight)
    if refine_signal is None:
        refine_signal = getattr(base, "refine_signal", None)
    if refine_signal is not None:
        # the Fisher-aware refinement signal moves nodes exactly like a
        # posterior weighting: same single-home omit-at-default key,
        # same wildcard rule in check_identity
        out["refine_signal"] = str(refine_signal)
    if traffic_fp is not None:
        # the traffic-weighted refinement signal moves nodes per
        # SNAPSHOT, not just per signal name: the snapshot fingerprint
        # is its own key (wildcard rule in check_identity) so two
        # traffic-specialized builds over different query distributions
        # can never be confused
        out["traffic"] = str(traffic_fp)
    scen = scenario_identity(static)
    if scen is not None:
        out["lz_scenario"] = scen
    if lz_profile_fp is not None:
        out["lz_profile"] = str(lz_profile_fp)
    if bounce_fp is not None:
        out["bounce"] = str(bounce_fp)
    return out


def artifact_hash(
    axis_names: Sequence[str],
    axis_nodes: Sequence[np.ndarray],
    axis_scales: Sequence[str],
    values: Mapping[str, np.ndarray],
    identity: Mapping[str, Any],
    predicted_error: "np.ndarray | None" = None,
) -> str:
    """Content hash over axes + value bytes + error grid + identity +
    schema version.

    The axis SCALES are part of the identity: they select each axis's
    interpolation coordinate, so the same table queried under a
    different scale list returns different numbers.  The per-cell
    predicted-error grid is hashed too: the serve layer gates exact
    fallback on it, so tampering with it must be as loud as tampering
    with the value table.

    Construction lives in the shared provenance layer
    (:func:`bdlz_tpu.provenance.emulator_artifact_identity`); the pinned
    construction in ``tests/test_provenance.py`` documents the current
    (schema-2) byte rule.
    """
    from bdlz_tpu.provenance import emulator_artifact_identity

    return emulator_artifact_identity(
        axis_names, axis_nodes, axis_scales, values, identity,
        SCHEMA_VERSION, predicted_error=predicted_error,
    ).digest(16)


def _validate_table(artifact: EmulatorArtifact, where: str) -> None:
    """Reject non-finite or non-positive cells LOUDLY.

    The query kernel interpolates in log-space: a NaN/inf cell would
    poison every query in its 2^d-cell neighborhood, and a zero or
    negative cell has no logarithm — both must fail at the boundary
    (build or load), never surface as a quietly wrong served yield.
    """
    if len(artifact.axis_names) != len(artifact.axis_nodes):
        raise EmulatorArtifactError(
            f"{where}: {len(artifact.axis_names)} axis names but "
            f"{len(artifact.axis_nodes)} node arrays"
        )
    shape = tuple(len(n) for n in artifact.axis_nodes)
    if len(artifact.axis_scales) != len(artifact.axis_names):
        raise EmulatorArtifactError(
            f"{where}: {len(artifact.axis_names)} axes but "
            f"{len(artifact.axis_scales)} scales"
        )
    for name, nodes, scale in zip(
        artifact.axis_names, artifact.axis_nodes, artifact.axis_scales
    ):
        nodes = np.asarray(nodes)
        if scale not in ("lin", "log"):
            raise EmulatorArtifactError(
                f"{where}: axis {name!r} has unknown scale {scale!r}"
            )
        if nodes.ndim != 1 or len(nodes) < 2:
            raise EmulatorArtifactError(
                f"{where}: axis {name!r} needs >= 2 one-dimensional nodes"
            )
        if not np.all(np.isfinite(nodes)) or not np.all(np.diff(nodes) > 0):
            raise EmulatorArtifactError(
                f"{where}: axis {name!r} nodes must be finite and strictly "
                "increasing"
            )
        if scale == "log" and nodes[0] <= 0.0:
            raise EmulatorArtifactError(
                f"{where}: log-scale axis {name!r} needs positive nodes"
            )
    if not artifact.values:
        raise EmulatorArtifactError(f"{where}: artifact carries no fields")
    for fname, vals in artifact.values.items():
        vals = np.asarray(vals)
        if vals.shape != shape:
            raise EmulatorArtifactError(
                f"{where}: field {fname!r} has shape {vals.shape}, expected "
                f"{shape} from the axis node counts"
            )
        bad = ~np.isfinite(vals)
        if bad.any():
            idx = tuple(int(i) for i in np.argwhere(bad)[0])
            raise EmulatorArtifactError(
                f"{where}: field {fname!r} holds {int(bad.sum())} "
                f"non-finite cell(s), first at grid index {idx} — the "
                "emulator build masks nothing; rebuild over a domain where "
                "the exact pipeline succeeds"
            )
        nonpos = vals <= 0.0
        if nonpos.any():
            idx = tuple(int(i) for i in np.argwhere(nonpos)[0])
            raise EmulatorArtifactError(
                f"{where}: field {fname!r} holds {int(nonpos.sum())} "
                f"non-positive cell(s), first at grid index {idx} — the "
                "log-space query kernel needs strictly positive values"
            )
    if artifact.predicted_error is not None:
        err = np.asarray(artifact.predicted_error)
        cells = tuple(max(n - 1, 1) for n in shape)
        if err.shape != cells:
            raise EmulatorArtifactError(
                f"{where}: predicted-error grid has shape {err.shape}, "
                f"expected the cell shape {cells} from the axis node "
                "counts"
            )
        if not np.all(np.isfinite(err)) or (err < 0.0).any():
            raise EmulatorArtifactError(
                f"{where}: predicted-error grid must be finite and "
                ">= 0 — the serve layer gates exact fallback on it"
            )


def save_artifact(out_dir: str, artifact: EmulatorArtifact) -> str:
    """Write ``artifact.npz`` + ``manifest.json`` into ``out_dir``.

    Both writes are atomic (tmp + ``os.replace``; the manifest through
    the shared ``utils.io.atomic_write_json`` helper) and the manifest
    goes LAST — a reader never sees a manifest whose hash refers to a
    half-written ``.npz``.
    """
    from bdlz_tpu.utils.io import atomic_write_json

    _validate_table(artifact, where="save")
    os.makedirs(out_dir, exist_ok=True)
    npz_path = os.path.join(out_dir, "artifact.npz")

    arrays: Dict[str, np.ndarray] = {}
    for name, nodes in zip(artifact.axis_names, artifact.axis_nodes):
        arrays[f"axis_{name}"] = np.asarray(nodes, dtype=np.float64)
    for name, vals in artifact.values.items():
        arrays[f"field_{name}"] = np.asarray(vals, dtype=np.float64)
    if artifact.predicted_error is not None:
        arrays["predicted_error"] = np.asarray(
            artifact.predicted_error, dtype=np.float64
        )
    from bdlz_tpu.utils.io import atomic_savez

    atomic_savez(npz_path, **arrays)

    manifest = dict(artifact.manifest)
    manifest["schema_version"] = SCHEMA_VERSION
    manifest["axes"] = list(artifact.axis_names)
    manifest["axis_scales"] = {
        n: s for n, s in zip(artifact.axis_names, artifact.axis_scales)
    }
    manifest["fields"] = sorted(artifact.values)
    manifest["error_grid"] = artifact.predicted_error is not None
    manifest["identity"] = artifact.identity
    manifest["hash"] = artifact_hash(
        artifact.axis_names, artifact.axis_nodes, artifact.axis_scales,
        artifact.values, artifact.identity,
        predicted_error=artifact.predicted_error,
    )
    atomic_write_json(os.path.join(out_dir, "manifest.json"), manifest, indent=2)
    return out_dir


def load_artifact(
    path: str, expect_identity: "Mapping[str, Any] | None" = None
) -> EmulatorArtifact:
    """Load and fully validate an artifact directory.

    Rejections (all :class:`EmulatorArtifactError`, all explicit about
    what went stale):

    * missing/unparsable manifest or ``.npz``;
    * ``schema_version`` mismatch (the reader would misinterpret the
      layout);
    * content-hash mismatch — the ``.npz`` or the manifest's identity
      was modified after the build (torn copy, hand edit, bit rot);
    * non-finite or non-positive table cells (see ``_validate_table``);
    * ``expect_identity`` given and != the stored identity — the caller
      is about to serve physics the artifact was not built for (changed
      config knobs, different engine, different n_y).
    """
    manifest_path = os.path.join(path, "manifest.json")
    npz_path = os.path.join(path, "artifact.npz")
    try:
        with open(manifest_path, encoding="utf-8") as f:
            manifest = json.load(f)
    except Exception as exc:
        raise EmulatorArtifactError(
            f"cannot read emulator manifest {manifest_path}: {exc!r}"
        ) from exc
    if manifest.get("kind") == "multi_domain":
        raise EmulatorArtifactError(
            f"{path} is a MULTI-DOMAIN emulator bundle (seam-split "
            "domains stitched at query time); load it with "
            "emulator.multidomain.load_multidomain_artifact or the "
            "kind-dispatching emulator.load_any_artifact"
        )
    version = manifest.get("schema_version")
    if version != SCHEMA_VERSION:
        raise EmulatorArtifactError(
            f"emulator artifact {path} has schema_version {version!r}, this "
            f"build reads {SCHEMA_VERSION}; rebuild the artifact"
        )
    axis_names = tuple(str(n) for n in manifest.get("axes", ()))
    field_names = [str(n) for n in manifest.get("fields", ())]
    identity = manifest.get("identity")
    scales_map = manifest.get("axis_scales")
    if (
        not axis_names or not field_names
        or not isinstance(identity, dict) or not isinstance(scales_map, dict)
    ):
        raise EmulatorArtifactError(
            f"emulator manifest {manifest_path} is missing "
            "axes/axis_scales/fields/identity"
        )
    axis_scales = tuple(str(scales_map.get(n, "lin")) for n in axis_names)
    try:
        with np.load(npz_path) as data:
            axis_nodes = tuple(
                np.asarray(data[f"axis_{n}"], dtype=np.float64)
                for n in axis_names
            )
            values = {
                n: np.asarray(data[f"field_{n}"], dtype=np.float64)
                for n in field_names
            }
            predicted_error = (
                np.asarray(data["predicted_error"], dtype=np.float64)
                if "predicted_error" in data.files else None
            )
    except EmulatorArtifactError:
        raise
    except Exception as exc:
        raise EmulatorArtifactError(
            f"cannot read emulator table {npz_path}: {exc!r}"
        ) from exc

    got_hash = artifact_hash(
        axis_names, axis_nodes, axis_scales, values, identity,
        predicted_error=predicted_error,
    )
    if got_hash != manifest.get("hash"):
        raise EmulatorArtifactError(
            f"emulator artifact {path} failed its content-hash check "
            f"(manifest {manifest.get('hash')!r}, recomputed {got_hash!r}): "
            "the table or its identity changed after the build — rebuild "
            "instead of serving a stale/tampered surface"
        )
    artifact = EmulatorArtifact(
        axis_names=axis_names,
        axis_nodes=axis_nodes,
        axis_scales=axis_scales,
        values=values,
        identity=identity,
        manifest=manifest,
        predicted_error=predicted_error,
    )
    _validate_table(artifact, where=f"load {path}")
    if expect_identity is not None:
        check_identity(artifact, expect_identity)
    return artifact


def check_identity(
    artifact: EmulatorArtifact,
    expect: Mapping[str, Any],
    exempt_config_keys: Sequence[str] = (),
) -> None:
    """Raise unless the artifact was built for the expected physics.

    ``exempt_config_keys`` names base-config keys whose stored value is
    irrelevant because they are artifact AXES (the per-point value
    overrides them) — the likelihood layer uses this so a caller whose
    base config differs only in a swept field is not falsely rejected.

    The ``quad_panel_gl`` key is strict whenever the CALLER states a
    scheme (an explicit True/False in their static): an artifact built
    under the other y-quadrature is rejected.  A caller whose
    expectation carries no key (tri-state ``None`` — "use whatever the
    artifact used") matches either; such callers must adopt the
    artifact's recorded scheme for their exact-fallback path, which the
    serve/likelihood layers do.  The ``posterior_weight`` key follows
    the same rule: strict when the caller names a weighting, wildcard
    when their knob is unset (weighting moves nodes, never what the
    exact engine computes at them — the fallback path is unaffected),
    and ``lz_profile`` (the scenario bounce-profile fingerprint) and
    ``bounce`` (the in-framework potential fingerprint) too.
    The ``lz_scenario`` key is deliberately STRICT both ways: a chain
    or thermal surface served to a two-channel consumer (or vice
    versa) is cross-mode skew and must reject loudly — there is no
    "adopt the artifact's physics scenario" story the way there is for
    a quadrature scheme.
    """
    stored = dict(artifact.identity)
    want = dict(expect)
    if "quad_panel_gl" not in want:
        stored.pop("quad_panel_gl", None)
    if "posterior_weight" not in want:
        stored.pop("posterior_weight", None)
    if "refine_signal" not in want:
        # wildcard like posterior_weight: the signal steers node
        # placement during the build, never what the exact engine
        # computes — a caller with no expectation matches either
        stored.pop("refine_signal", None)
    if "lz_profile" not in want:
        stored.pop("lz_profile", None)
    if "bounce" not in want:
        # wildcard like lz_profile: the potential fingerprint names the
        # SOURCE of the derived profile; a caller that states no
        # potential matches either, while stating one pins it strictly
        # (cross-potential artifact/consumer skew must reject loudly)
        stored.pop("bounce", None)
    if "traffic" not in want:
        # wildcard like refine_signal: the snapshot fingerprint steers
        # node placement, never what the exact engine computes — a
        # caller with no stated snapshot (every serving front) matches
        # either; stating one pins it strictly
        stored.pop("traffic", None)
    sb = dict(stored.get("base", {}))
    wb = dict(want.get("base", {}))
    for key in set(exempt_config_keys) | set(artifact.axis_names):
        sb.pop(key, None)
        wb.pop(key, None)
    stored["base"], want["base"] = sb, wb
    diffs: List[str] = []
    for key in sorted(set(stored) | set(want)):
        if stored.get(key) != want.get(key):
            diffs.append(
                f"{key}: artifact={stored.get(key)!r} caller={want.get(key)!r}"
            )
    if diffs:
        raise EmulatorArtifactError(
            "emulator artifact identity mismatch (stale artifact — the "
            "physics knobs changed since the build; rebuild it):\n  "
            + "\n  ".join(diffs)
        )
