"""Seam-split emulator domains: split at the T = m/3 flux-seam band,
stitch at query time.

The tensor-grid emulator's one documented blind spot (PR 3, measured in
docs/perf_notes.md) is a box crossing the **T = m/3 statistics seam**:
``n_eq`` jumps ~5.6x and the mean χ speed ~1.09x where the percolation
window sweeps the seam through the flux peak, the yield surface carries
a kink along the m ≈ 3·T_p DIAGONAL, and axis-aligned refinement goes
first-order on BOTH axes (228x239 nodes and still 3e-3 after 40
rounds).  This module closes it the way the limitation note prescribes
("split at the band or serve exact"):

* :func:`seam_band_for_box` locates the band with the same machinery
  the panel-GL quadrature snaps its edges with
  (``solvers.panels.y_branch_seam`` / ``quadrature_bounds``): the seam
  matters where it sits INSIDE the y-window with non-negligible source
  weight, ``|y_seam| <= c·sigma_y`` with ``c`` chosen so the Gaussian
  envelope ``exp(-y^2/2σ^2)`` bounds the seam's relative contribution
  below the build's refinement target (headroom for the ~5.6x n_eq
  jump included) — beyond the band the kink cannot move the surface at
  tolerance, so the sub-boxes refine spectrally again;
* :func:`build_seam_split_emulator` builds one ordinary single-scheme
  sub-artifact per side of the band (each through the UNCHANGED
  ``build_emulator`` code path — per-domain bytes are identical to a
  standalone build of that sub-box by construction, and the query
  kernels preserve that bit-for-bit, pinned in tests) and assembles a
  :class:`MultiDomainArtifact`;
* the bundle is saved/loaded/published as one unit under a COMPOSITE
  content hash over the ordered per-domain hashes + the seam-band
  descriptor + the shared physics identity
  (:func:`bdlz_tpu.provenance.multidomain_artifact_identity`), so a
  bundle goes stale exactly when any of its parts would.

Queries inside the band belong to no domain: they are out-of-domain by
construction and take the serving layer's exact fallback — which, with
the per-cell error gate this PR adds, is the ONLY traffic on a
seam-crossing box that still pays the ~1600x exact-path cost.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np  # host-side use only; jitted paths go through the backend.py xp seam (bdlz-lint R1 audit)

from bdlz_tpu.emulator.artifact import (
    EmulatorArtifact,
    EmulatorArtifactError,
    load_artifact,
    save_artifact,
)

#: Schema version of the BUNDLE manifest.  A pre-seam (schema-1) reader
#: pointed at a bundle fails its version check loudly; the current
#: single-domain loader rejects bundles EARLIER, on the manifest's
#: ``kind`` tag — either way a bundle directory is never misread as a
#: single artifact.
MULTI_SCHEMA_VERSION = 2

#: The manifest ``kind`` tag that dispatches bundle loading.
MULTI_DOMAIN_KIND = "multi_domain"

#: What the seam-band descriptor describes.
SEAM_BAND_KIND = "T=m/3 flux seam"

#: Headroom multiplier on the band tolerance for the seam's jump
#: amplitude (n_eq ~5.6x, v_bar ~1.09x — bounded by 10x) times the
#: probe-safety margin: the band must exclude the kink down to WELL
#: under the refinement's internal target, or edge cells of the
#: sub-boxes would still stall first-order.
_BAND_TOL_HEADROOM = 40.0

_SEAM_RELEVANT = (
    "m_chi_GeV", "T_p_GeV", "beta_over_H", "T_min_over_Tp",
    "T_max_over_Tp", "source_shape_sigma_y",
)


class MultiDomainBuildError(EmulatorArtifactError):
    """A seam-split build or bundle that cannot be trusted: no seam to
    split on under ``seam_split=true``, the whole box inside the band,
    per-domain identity skew, or a malformed bundle directory."""


class _FieldsView:
    """Field-NAME view of a bundle: supports the membership/iteration
    checks single-artifact consumers run (``field in artifact.values``,
    ``sorted(artifact.values)``) but REFUSES array access loudly —
    value tables live per domain, and silently handing out one domain's
    table as "the" surface would cover half the box."""

    def __init__(self, names):
        self._names = tuple(names)

    def __contains__(self, name) -> bool:
        return name in self._names

    def __iter__(self):
        return iter(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def __getitem__(self, name):
        raise EmulatorArtifactError(
            "a MultiDomainArtifact has no single value table: read "
            "artifact.domains[i].values[...] per domain, or query the "
            "stitched surface through emulator.grid.make_query_fn"
        )

    def __repr__(self) -> str:
        return f"_FieldsView({sorted(self._names)})"


class MultiDomainArtifact(NamedTuple):
    """One seam-split emulator bundle: ordered, disjoint single-domain
    artifacts plus the seam-band descriptor separating them, behind the
    same query-facing interface as a single artifact (``axis_names``,
    ``hull``, ``content_hash``, ``manifest`` — the grid/serve layers
    dispatch through :func:`bdlz_tpu.emulator.grid.domain_artifacts`)."""

    domains: Tuple[EmulatorArtifact, ...]
    seam_band: Dict[str, Any]     # {"axis", "lo", "hi", "kind", ...}
    identity: Dict[str, Any]      # the SHARED physics identity
    manifest: Dict[str, Any]

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return self.domains[0].axis_names

    @property
    def axis_scales(self) -> Tuple[str, ...]:
        return self.domains[0].axis_scales

    @property
    def values(self) -> "_FieldsView":
        """Field-name view (for ``field in artifact.values`` checks);
        the arrays themselves live per domain — array access through
        this view raises instead of silently serving one domain."""
        return _FieldsView(self.domains[0].values)

    @property
    def domain(self) -> Dict[str, Tuple[float, float]]:
        lo, hi = self.hull
        return {
            name: (float(lo[k]), float(hi[k]))
            for k, name in enumerate(self.axis_names)
        }

    @property
    def hull(self) -> Tuple[np.ndarray, np.ndarray]:
        los, his = zip(*(d.hull for d in self.domains))
        return (
            np.min(np.stack(los), axis=0),
            np.max(np.stack(his), axis=0),
        )

    @property
    def n_points(self) -> int:
        return sum(d.n_points for d in self.domains)

    @property
    def predicted_error(self):
        """Present iff every domain persists an estimate grid (the
        serve gate asks through ``grid.has_error_grid``)."""
        grids = [d.predicted_error for d in self.domains]
        return grids if all(g is not None for g in grids) else None

    @property
    def content_hash(self) -> str:
        h = self.manifest.get("hash")
        if h is not None:
            return str(h)
        return multidomain_hash(
            [d.content_hash for d in self.domains], self.seam_band,
            self.identity,
        )


class MultiDomainBuildReport(NamedTuple):
    """Aggregate provenance of one seam-split build (headline fields
    mirror :class:`~bdlz_tpu.emulator.build.BuildReport` so bench/test
    consumers read either kind)."""

    domain_reports: Tuple[Any, ...]   # one BuildReport per domain
    seam_band: Dict[str, Any]
    converged: bool                   # every domain converged
    max_rel_err: float                # worst domain's held-out error
    rtol: float
    n_exact_evals: int                # summed over domains
    build_seconds: float
    rounds: List[Dict[str, Any]]      # per-domain rows, domain-tagged


def seam_band_tolerance(rtol: float, safety: float) -> float:
    """The relative seam contribution below which the band ends."""
    return float(rtol) / (_BAND_TOL_HEADROOM * float(safety))


def seam_band_for_box(
    base,
    spec,
    *,
    rtol: float = 1e-4,
    safety: float = 2.0,
    band_tol: Optional[float] = None,
    axis: Optional[str] = None,
    n_scan: int = 4097,
) -> Optional[Dict[str, Any]]:
    """Locate the seam band inside an emulator box, or None.

    Scans the split axis (``m_chi_GeV`` if it is in the spec, else
    ``T_p_GeV``) densely while the other seam-relevant parameters sit
    at their box extremes (they enter ``y_seam``/the window bounds
    monotonically, so extremes bound the union), and marks a scan value
    in-band when, for ANY extreme combination, the seam sits inside the
    clipped y-window with source weight above ``band_tol``:
    ``exp(-y_seam^2 / 2 sigma_y^2) > band_tol`` — outside that the
    Gaussian envelope bounds the kink's relative contribution to the
    yield integral below the refinement target and the sub-box refines
    cleanly (the same exactness reasoning the panel quadrature's
    edge-snapping uses; see docs/perf_notes.md).

    Returns ``{"axis", "lo", "hi", "kind", "band_tol"}`` with [lo, hi]
    widened by one scan step on each side (the predicate is sampled),
    intersected with the box — or None when the box never touches the
    band.
    """
    from bdlz_tpu.parallel.sweep import AXIS_MAP, build_grid
    from bdlz_tpu.solvers.panels import y_branch_seam
    from bdlz_tpu.solvers.quadrature import quadrature_bounds

    if band_tol is None:
        band_tol = seam_band_tolerance(rtol, safety)
    if axis is None:
        axis = next(
            (a for a in ("m_chi_GeV", "T_p_GeV") if a in spec), None
        )
    if axis is None:
        return None
    ax = spec[axis]
    if ax.scale == "log":
        scan = np.geomspace(ax.lo, ax.hi, int(n_scan))
    else:
        scan = np.linspace(ax.lo, ax.hi, int(n_scan))

    other_extremes = []
    for name in _SEAM_RELEVANT:
        if name == axis or name not in spec or name not in AXIS_MAP:
            continue
        other_extremes.append(
            (name, (float(spec[name].lo), float(spec[name].hi)))
        )
    combos = list(itertools.product(
        *(vals for _name, vals in other_extremes)
    )) or [()]

    # the source-weight threshold in y: |y_seam| <= c * sigma_y
    c = float(np.sqrt(max(2.0 * np.log(1.0 / band_tol), 0.0)))
    inside_any = np.zeros(len(scan), dtype=bool)
    for combo in combos:
        axes = {axis: scan}
        for (name, _vals), v in zip(other_extremes, combo):
            axes[name] = np.full(len(scan), v)
        pp = build_grid(base, axes, product=False)
        y_lo, y_hi = quadrature_bounds(pp, np)
        y_seam = y_branch_seam(pp, np)
        sigma = np.maximum(np.asarray(pp.sigma_y, dtype=np.float64), 1e-6)
        inside_any |= (
            (y_seam > y_lo) & (y_seam < y_hi) & (y_hi > y_lo)
            & (np.abs(y_seam) <= c * sigma)
        )
    if not inside_any.any():
        return None
    idx = np.flatnonzero(inside_any)
    lo = float(scan[max(int(idx[0]) - 1, 0)])
    hi = float(scan[min(int(idx[-1]) + 1, len(scan) - 1)])
    return {
        "axis": axis,
        "lo": lo,
        "hi": hi,
        "kind": SEAM_BAND_KIND,
        "band_tol": float(band_tol),
    }


def resolve_seam_split(
    base, spec, seam_split: Optional[bool], *,
    rtol: float, safety: float,
) -> Optional[Dict[str, Any]]:
    """The tri-state resolution (ode_* pattern): explicit argument wins
    over ``Config.seam_split``; ``None`` means split iff the box crosses
    the band; ``True`` REQUIRES a crossing (a smooth box has nothing to
    split at — loud error, not a silent single-domain build).  Returns
    the band descriptor when the build should split, else None."""
    resolved = (
        seam_split if seam_split is not None
        else getattr(base, "seam_split", None)
    )
    if resolved is False:
        return None
    band = seam_band_for_box(base, spec, rtol=rtol, safety=safety)
    if band is None:
        if resolved is True:
            raise MultiDomainBuildError(
                "seam_split=true but the emulator box never crosses the "
                "T = m/3 flux-seam band (no m_chi_GeV/T_p_GeV axis, or "
                "the seam's source weight is negligible across the box); "
                "drop the knob or widen the box"
            )
        return None
    return band


def multidomain_hash(
    domain_hashes, seam_band, identity, n: int = 16
) -> str:
    """The bundle's composite content hash (see
    :func:`bdlz_tpu.provenance.multidomain_artifact_identity`)."""
    from bdlz_tpu.provenance import multidomain_artifact_identity

    return multidomain_artifact_identity(
        list(domain_hashes), dict(seam_band), dict(identity),
        MULTI_SCHEMA_VERSION,
    ).digest(n)


def _split_spec(spec, band) -> List[Dict[str, Any]]:
    """The per-side sub-specs: the split axis truncated at the band
    edges (each side keeps its full initial node count — refinement
    redistributes), every other axis untouched.  A side swallowed by
    the band is dropped; both sides gone is an error (the whole box is
    seam band — there is nothing an emulator can honestly serve)."""
    axis, lo, hi = band["axis"], band["lo"], band["hi"]
    ax = spec[axis]
    sides = []
    if lo > ax.lo:
        sides.append(("below_seam", ax._replace(hi=lo)))
    if hi < ax.hi:
        sides.append(("above_seam", ax._replace(lo=hi)))
    if not sides:
        raise MultiDomainBuildError(
            f"the whole {axis} range [{ax.lo}, {ax.hi}] lies inside the "
            f"T = m/3 seam band [{lo}, {hi}]: no seam-free side remains "
            "— serve this box from the exact path instead of an emulator"
        )
    out = []
    for name, sub_ax in sides:
        sub = dict(spec)
        sub[axis] = sub_ax
        out.append({"name": name, "spec": sub})
    return out


def build_seam_split_emulator(
    base,
    spec,
    static=None,
    *,
    band: Optional[Dict[str, Any]] = None,
    out_dir: Optional[str] = None,
    event_log=None,
    **build_kw,
) -> Tuple[MultiDomainArtifact, MultiDomainBuildReport]:
    """Build one single-scheme sub-artifact per side of the seam band
    and stitch them into a :class:`MultiDomainArtifact`.

    Each side goes through the unchanged :func:`build_emulator` path
    (``seam_split=False`` — per-domain bytes identical to a standalone
    build of that sub-box).  The y-quadrature tri-state is resolved
    ONCE across the sides (panel-GL only when EVERY side's audit admits
    it): the bundle's exact fallback runs one scheme, so the domains
    must agree — a mixed resolution forces the reference trapezoid on
    all sides, loudly.  Per-domain identities must come out equal; the
    shared identity plus the ordered domain hashes plus the band form
    the composite identity the registry/rollout layers address the
    bundle by.
    """
    from bdlz_tpu.config import static_choices_from_config, validate
    from bdlz_tpu.emulator.build import EmulatorBuildError, build_emulator

    t0 = time.time()
    validate(base)
    rtol = float(build_kw.get("rtol", 1e-4))
    safety = float(build_kw.get("safety", 2.0))
    if static is None:
        static = static_choices_from_config(base)
    if band is None:
        band = seam_band_for_box(base, spec, rtol=rtol, safety=safety)
        if band is None:
            raise MultiDomainBuildError(
                "build_seam_split_emulator needs a box that crosses the "
                "T = m/3 seam band; use build_emulator for smooth boxes"
            )
    sides = _split_spec(spec, band)

    # One quadrature scheme for the whole bundle: audit each side's
    # initial grid; panel-GL only if every side passes (mirrors
    # build_emulator's own resolution — an explicit True/False in the
    # static short-circuits, exactly like there).
    static = _resolve_bundle_quad(base, static, sides, build_kw)

    artifacts: List[EmulatorArtifact] = []
    reports: List[Any] = []
    for side in sides:
        try:
            art, rep = build_emulator(
                base, side["spec"], static, seam_split=False,
                out_dir=None, event_log=event_log, **build_kw,
            )
        except EmulatorBuildError as exc:
            raise MultiDomainBuildError(
                f"seam-split sub-build {side['name']!r} failed: {exc}"
            ) from exc
        art = art._replace(manifest={
            **art.manifest, "seam_side": side["name"],
        })
        artifacts.append(art)
        reports.append(rep)

    identity = artifacts[0].identity
    for art, side in zip(artifacts[1:], sides[1:]):
        if art.identity != identity:
            raise MultiDomainBuildError(
                f"per-domain identity skew between sub-builds "
                f"{sides[0]['name']!r} and {side['name']!r} — the bundle "
                "shares ONE exact-fallback engine, so every domain must "
                "resolve the same physics/engine/quadrature"
            )

    max_rel_err = max(r.max_rel_err for r in reports)
    converged = all(r.converged for r in reports)
    seconds = time.time() - t0
    rows: List[Dict[str, Any]] = []
    for side, rep in zip(sides, reports):
        rows.extend({**row, "seam_side": side["name"]} for row in rep.rounds)
    domain_hashes = [a.content_hash for a in artifacts]
    manifest = {
        "kind": MULTI_DOMAIN_KIND,
        "seam_band": dict(band),
        "rtol_target": rtol,
        "max_rel_err": max_rel_err,
        "converged": bool(converged),
        "n_exact_evals": int(sum(r.n_exact_evals for r in reports)),
        "build_seconds": round(seconds, 3),
        "domains": domain_hashes,
        "domain_sides": [s["name"] for s in sides],
        "per_domain_max_rel_err": [float(r.max_rel_err) for r in reports],
        "error_grid": all(
            a.predicted_error is not None for a in artifacts
        ),
    }
    bundle = MultiDomainArtifact(
        domains=tuple(artifacts),
        seam_band=dict(band),
        identity=identity,
        manifest=manifest,
    )
    report = MultiDomainBuildReport(
        domain_reports=tuple(reports),
        seam_band=dict(band),
        converged=bool(converged),
        max_rel_err=float(max_rel_err),
        rtol=rtol,
        n_exact_evals=int(sum(r.n_exact_evals for r in reports)),
        build_seconds=round(seconds, 3),
        rounds=rows,
    )
    if event_log is not None:
        event_log.emit(
            "emulator_seam_split_done",
            seam_band=dict(band), n_domains=len(artifacts),
            converged=bool(converged), max_rel_err=max_rel_err,
            n_exact_evals=report.n_exact_evals, seconds=round(seconds, 3),
        )
    if out_dir is not None:
        save_multidomain_artifact(out_dir, bundle)
    return bundle, report


def _resolve_bundle_quad(base, static, sides, build_kw):
    """Resolve the y-quadrature tri-state once, across every side."""
    from bdlz_tpu.config import needs_ode_path
    from bdlz_tpu.emulator.build import _axis_nodes
    from bdlz_tpu.validation import resolve_quad_panel_gl

    impl = str(build_kw.get("impl", "tabulated"))
    n_y = int(build_kw.get("n_y", 2000))
    if needs_ode_path(base) and impl != "esdirk_lockstep":
        impl = "esdirk"
    if static.quad_panel_gl is not None or impl != "tabulated":
        return static
    from bdlz_tpu.parallel.sweep import build_grid

    resolved = []
    for side in sides:
        sub = side["spec"]
        if "I_p" in sub:  # per-I_p table unavailable: direct engine
            return static
        grid = build_grid(
            base,
            {k: _axis_nodes(ax) for k, ax in sub.items()},
            product=True,
        )
        on, _audit = resolve_quad_panel_gl(
            grid, static, impl, n_y, label=f"emulator[{side['name']}]",
        )
        resolved.append(bool(on))
    scheme = all(resolved)
    if not scheme and any(resolved):
        print(
            "[emulator] seam-split sides resolved MIXED y-quadrature "
            "schemes; forcing the reference trapezoid on every domain "
            "so the bundle serves one scheme",
            file=sys.stderr,
        )
    return static._replace(quad_panel_gl=scheme)


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------

def _domain_dirname(i: int) -> str:
    return f"domain_{i:02d}"


def save_multidomain_artifact(out_dir: str, bundle: MultiDomainArtifact) -> str:
    """Write the bundle: one standard artifact directory per domain,
    then the bundle ``manifest.json`` LAST (atomic) — a reader never
    sees a manifest naming half-written domains."""
    from bdlz_tpu.utils.io import atomic_write_json

    os.makedirs(out_dir, exist_ok=True)
    domain_hashes = []
    for i, dom in enumerate(bundle.domains):
        save_artifact(os.path.join(out_dir, _domain_dirname(i)), dom)
        domain_hashes.append(dom.content_hash)
    manifest = dict(bundle.manifest)
    manifest["kind"] = MULTI_DOMAIN_KIND
    manifest["schema_version"] = MULTI_SCHEMA_VERSION
    manifest["domains"] = domain_hashes
    manifest["domain_dirs"] = [
        _domain_dirname(i) for i in range(len(bundle.domains))
    ]
    manifest["seam_band"] = dict(bundle.seam_band)
    manifest["identity"] = bundle.identity
    manifest["hash"] = multidomain_hash(
        domain_hashes, bundle.seam_band, bundle.identity
    )
    atomic_write_json(
        os.path.join(out_dir, "manifest.json"), manifest, indent=2
    )
    return out_dir


def load_multidomain_artifact(path: str) -> MultiDomainArtifact:
    """Load + fully validate a seam-split bundle.

    Every rejection is a loud :class:`EmulatorArtifactError`: missing or
    unparsable manifest, schema-version or ``kind`` skew, any domain
    failing ITS full single-artifact validation (schema, content hash,
    finite/positive tables), a domain directory whose verified hash is
    not the one the bundle manifest names (an impersonating or swapped
    domain), composite-hash mismatch, per-domain identity skew, or
    domains that overlap along the split axis.
    """
    manifest_path = os.path.join(path, "manifest.json")
    try:
        with open(manifest_path, encoding="utf-8") as f:
            manifest = json.load(f)
    except Exception as exc:
        raise EmulatorArtifactError(
            f"cannot read emulator bundle manifest {manifest_path}: {exc!r}"
        ) from exc
    if manifest.get("kind") != MULTI_DOMAIN_KIND:
        raise EmulatorArtifactError(
            f"{path} is not a multi-domain bundle (kind="
            f"{manifest.get('kind')!r}); load single artifacts with "
            "emulator.load_artifact"
        )
    version = manifest.get("schema_version")
    if version != MULTI_SCHEMA_VERSION:
        raise EmulatorArtifactError(
            f"emulator bundle {path} has schema_version {version!r}, this "
            f"build reads {MULTI_SCHEMA_VERSION}; rebuild the bundle"
        )
    want_hashes = [str(h) for h in manifest.get("domains", ())]
    dirs = [str(d) for d in manifest.get("domain_dirs", ())]
    band = manifest.get("seam_band")
    if not want_hashes or len(want_hashes) != len(dirs) or not isinstance(
        band, dict
    ):
        raise EmulatorArtifactError(
            f"emulator bundle manifest {manifest_path} is missing "
            "domains/domain_dirs/seam_band"
        )
    domains: List[EmulatorArtifact] = []
    for want, sub in zip(want_hashes, dirs):
        dom = load_artifact(os.path.join(path, sub))
        if dom.content_hash != want:
            raise EmulatorArtifactError(
                f"bundle domain {sub!r} verifies as "
                f"{dom.content_hash!r}, but the bundle manifest names "
                f"{want!r}: refusing the swapped/impersonating domain"
            )
        domains.append(dom)
    identity = manifest.get("identity")
    if not isinstance(identity, dict):
        raise EmulatorArtifactError(
            f"emulator bundle manifest {manifest_path} is missing identity"
        )
    for sub, dom in zip(dirs, domains):
        if dom.identity != identity:
            raise EmulatorArtifactError(
                f"bundle domain {sub!r} carries a different physics "
                "identity than the bundle manifest — the shared exact "
                "fallback cannot serve both; rebuild the bundle"
            )
    got = multidomain_hash(want_hashes, band, identity)
    if got != manifest.get("hash"):
        raise EmulatorArtifactError(
            f"emulator bundle {path} failed its composite content-hash "
            f"check (manifest {manifest.get('hash')!r}, recomputed "
            f"{got!r}): a domain, the seam band, or the identity changed "
            "after the build — rebuild instead of serving a stale bundle"
        )
    bundle = MultiDomainArtifact(
        domains=tuple(domains),
        seam_band=dict(band),
        identity=identity,
        manifest=manifest,
    )
    _validate_bundle_geometry(bundle, where=f"load {path}")
    return bundle


def _validate_bundle_geometry(bundle: MultiDomainArtifact, where: str) -> None:
    """Domains must share axes/scales and be disjoint along the split
    axis, ordered below→above the band."""
    names = bundle.domains[0].axis_names
    scales = bundle.domains[0].axis_scales
    for dom in bundle.domains[1:]:
        if dom.axis_names != names or dom.axis_scales != scales:
            raise EmulatorArtifactError(
                f"{where}: bundle domains disagree on axis names/scales"
            )
    axis = bundle.seam_band.get("axis")
    if axis not in names:
        raise EmulatorArtifactError(
            f"{where}: seam-band axis {axis!r} is not a bundle axis "
            f"({list(names)})"
        )
    k = names.index(axis)
    spans = sorted(
        (float(d.axis_nodes[k][0]), float(d.axis_nodes[k][-1]))
        for d in bundle.domains
    )
    for (lo_a, hi_a), (lo_b, _hi_b) in zip(spans, spans[1:]):
        if lo_b < hi_a:
            raise EmulatorArtifactError(
                f"{where}: bundle domains OVERLAP along {axis!r} "
                f"([{lo_a}, {hi_a}] vs one starting at {lo_b}) — query "
                "routing would be ambiguous"
            )


def load_any_artifact(path: str):
    """Load whichever artifact kind ``path`` holds (single-domain
    :class:`EmulatorArtifact` or seam-split
    :class:`MultiDomainArtifact`), dispatching on the manifest's
    ``kind`` tag with full validation either way."""
    manifest_path = os.path.join(path, "manifest.json")
    try:
        with open(manifest_path, encoding="utf-8") as f:
            kind = json.load(f).get("kind")
    except Exception as exc:
        raise EmulatorArtifactError(
            f"cannot read emulator manifest {manifest_path}: {exc!r}"
        ) from exc
    if kind == MULTI_DOMAIN_KIND:
        return load_multidomain_artifact(path)
    return load_artifact(path)
