"""Adaptive tensor-grid emulator builds: populate, probe, refine, save.

The build drives the production sweep engine
(:func:`bdlz_tpu.parallel.sweep.run_sweep`) in chunks over a tensor
grid of the configured parameter box, then iterates:

1. draw random probe points, evaluate the EXACT pipeline at them (paid
   once — their exact values join an accumulating POOL that every later
   round re-scores for free) and the interim emulator's log-space
   interpolation;
2. score per-probe errors with the shared gate rule
   (:func:`bdlz_tpu.validation.relative_errors` — rel where the
   reference is nonzero, median-nonzero-scaled abs at zero references);
3. for every pool probe over the internal target (``rtol/safety``),
   insert a midpoint node into the ONE axis whose local log-curvature
   (second divided difference of the stored surface, in the axis's own
   scale coordinate) is largest — tensor structure means each insert
   buys a whole hyperplane of new exact evaluations, so the refinement
   spends its budget on the axes that actually bend;
4. evaluate only the NEW hyperplanes (never the full grid again) and
   merge them into the table.

The loop ends when the WHOLE pool scores clean (one lucky round of
fresh probes cannot end the build — localized features like the
T = m/3 flux-seam band hide from small draws) or ``max_rounds`` is
exhausted; either way a FRESH, larger held-out set (different seed) is
scored and recorded in the manifest as ``max_rel_err`` — the number a
consumer trusts is never measured on the points that steered the build.
"""
from __future__ import annotations

import sys
import time
from typing import Any, Dict, List, Mapping, NamedTuple, Optional, Tuple

import numpy as np  # host-side use only; jitted paths go through the backend.py xp seam (bdlz-lint R1 audit)

from bdlz_tpu.emulator.artifact import (
    FIELDS,
    EmulatorArtifact,
    build_identity,
    save_artifact,
)
from bdlz_tpu.emulator.grid import axis_coord, interp_log_fields

VALID_SCALES = ("lin", "log")

#: The fields the Fisher-aware signal compares gradients of, in the
#: order ``sampling.grad.make_field_log10_jacobian`` emits them.  The
#: other stored fields are affine images of these in log-space (Y_B,
#: Y_chi are fixed rescalings; DM_over_B is their difference), so their
#: gradient mismatch is bounded by these two — no information is lost
#: by not differentiating all five.
_GRAD_FIELDS = ["rho_B_kg_m3", "rho_DM_kg_m3"]

#: Node spacing below which a midpoint insert is refused (relative to
#: the axis span): past this the surface error is not interpolation-
#: limited and further splitting just burns sweep evaluations.
_MIN_REL_GAP = 1e-9

_LN10 = float(np.log(10.0))


class EmulatorBuildError(RuntimeError):
    """The build could not produce a trustworthy surface (failed exact
    points inside the box, invalid spec, refinement budget exhausted
    with ``require_converged=True``)."""


class AxisSpec(NamedTuple):
    """One parameter axis of the emulator box (config-schema units)."""

    lo: float
    hi: float
    n0: int = 5          # initial node count
    scale: str = "lin"   # "lin" | "log" — node placement and midpoints


class BuildReport(NamedTuple):
    """Provenance of one build, mirrored into the artifact manifest."""

    rounds: List[Dict[str, Any]]   # per-round: probes failed, nodes added, …
    converged: bool                # pool clean AND no interval estimate over target
    max_rel_err: float             # held-out set, AFTER refinement
    rtol: float
    n_exact_evals: int             # total exact-pipeline points paid
    build_seconds: float
    axis_nodes: Dict[str, int]     # final per-axis node counts
    #: Probes dropped because their exact evaluation stayed dead after
    #: the retry budget (infrastructure quarantine, never physics NaN —
    #: that still aborts the build loudly).  The refinement continues
    #: around them; the count is mirrored into the artifact manifest.
    quarantined_probes: int = 0
    #: The posterior weighting the refinement criterion ran under (None
    #: = curvature-only).  With a weight armed, ``converged`` and the
    #: splitting criterion are WEIGHTED statements; ``max_rel_err``
    #: stays the raw held-out number (dead regions may exceed rtol by
    #: design — the serve layer's error gate covers them), and
    #: ``weighted_max_rel_err`` is the held-out error under the weight.
    posterior_weight: "str | None" = None
    weighted_max_rel_err: "float | None" = None
    #: The probe-split attribution signal (None = the legacy axis-local
    #: |f''| stencil; "fisher" = exact-pipeline gradient mismatch — see
    #: ``build_emulator``).  ``n_grad_evals`` counts the reverse-mode
    #: pipeline Jacobians the fisher signal paid (they are NOT exact
    #: point evaluations and are billed separately on purpose: the
    #: acceptance comparison is on ``n_exact_evals`` with the gradient
    #: bill in plain sight).
    refine_signal: "str | None" = None
    n_grad_evals: int = 0


def _axis_nodes(spec: AxisSpec) -> np.ndarray:
    if not (np.isfinite(spec.lo) and np.isfinite(spec.hi) and spec.lo < spec.hi):
        raise EmulatorBuildError(f"axis bounds must be finite with lo < hi, got {spec}")
    if spec.n0 < 2:
        raise EmulatorBuildError(f"axis needs >= 2 initial nodes, got {spec}")
    if spec.scale not in VALID_SCALES:
        raise EmulatorBuildError(
            f"axis scale must be one of {VALID_SCALES}, got {spec.scale!r}"
        )
    if spec.scale == "log":
        if spec.lo <= 0:
            raise EmulatorBuildError(f"log axis needs lo > 0, got {spec}")
        return np.geomspace(spec.lo, spec.hi, spec.n0)
    return np.linspace(spec.lo, spec.hi, spec.n0)


def _midpoint(lo: float, hi: float, scale: str) -> float:
    if scale == "log":
        return float(np.sqrt(lo * hi))
    return 0.5 * (lo + hi)


def _draw_probes(
    spec: Mapping[str, AxisSpec], n: int, rng: np.random.Generator
) -> Dict[str, np.ndarray]:
    """n random points, per-axis uniform in the axis's own scale."""
    cols: Dict[str, np.ndarray] = {}
    for name, ax in spec.items():
        if ax.scale == "log":
            cols[name] = 10.0 ** rng.uniform(
                np.log10(ax.lo), np.log10(ax.hi), n
            )
        else:
            cols[name] = rng.uniform(ax.lo, ax.hi, n)
    return cols


def _exact_fields(
    base, axes: Mapping[str, np.ndarray], static, *, product: bool,
    mesh, chunk_size: int, n_y: int, impl: str,
    fault_plan=None, retry=None, cache=None, lz_profile=None,
    elastic=None,
) -> Tuple[Dict[str, np.ndarray], int]:
    """Exact pipeline over a product grid via the production sweep engine.

    Chunk-level healing (retry → bisect → quarantine) is inherited from
    ``run_sweep``; transient infrastructure faults therefore cost
    retries, not the build.  A point that stays failed — physics
    non-finite OR irreducibly quarantined — is an
    :class:`EmulatorBuildError`: the table masks nothing — a log-space
    surface with holes must be rebuilt over a domain where the pipeline
    works (probes, by contrast, are droppable and tolerate quarantine;
    see ``build_emulator``).

    ``elastic`` (a worker count, or a kwarg dict forwarded to
    :func:`~bdlz_tpu.parallel.scheduler.run_sweep_elastic`) routes the
    grid through the elastic fleet instead of single-host ``run_sweep``
    and folds chunks STREAMING as workers commit them — the build does
    not wait for the sweep's final gather, and a worker lost mid-grid
    costs one lease TTL, not the build.  Elastic results are bitwise-
    equal to the serial engine, so both paths fill the same surface.
    """
    from bdlz_tpu.parallel.sweep import run_sweep

    assert product, "zipped probe evaluation goes through make_exact_evaluator"
    if elastic:
        from bdlz_tpu.parallel.scheduler import run_sweep_elastic

        if lz_profile is not None:
            raise EmulatorBuildError(
                "elastic build cannot ship per-point bounce profiles; "
                "drop elastic=... or lz_profile=..."
            )
        if cache is None:
            raise EmulatorBuildError(
                "elastic build needs a shared store for the lease/commit "
                "plane; pass cache=... (a store root or Store)"
            )
        opts = (
            dict(elastic) if isinstance(elastic, Mapping)
            else {"n_workers": int(elastic)}
        )
        n_total = int(np.prod([len(np.asarray(v)) for v in axes.values()]))
        flat: Dict[str, np.ndarray] = {}

        def _consume(ci, lo, hi, ent):
            # streaming fold: each chunk lands the moment its commit is
            # observed, into preallocated columns (NaN = not yet landed)
            for f in ent:
                if f in ("failed", "quarantined", "n_retries"):
                    continue
                if f not in flat:
                    flat[f] = np.full(n_total, np.nan)
                flat[f][lo:hi] = np.asarray(ent[f])

        res = run_sweep_elastic(
            base, dict(axes), static, store=cache, chunk_size=chunk_size,
            n_y=n_y, impl=impl, fault_plan=fault_plan, retry=retry,
            on_chunk=_consume, keep_outputs=False, **opts,
        )
    else:
        res = run_sweep(
            base, dict(axes), static, mesh=mesh, chunk_size=chunk_size,
            n_y=n_y, out_dir=None, keep_outputs=True, impl=impl,
            fault_plan=fault_plan, retry=retry, cache=cache,
            lz_profile=lz_profile,
        )
    n_pts = res.n_points
    if res.n_failed:
        bad = np.argwhere(np.asarray(res.failed_mask))[:, 0]
        quarantined = (
            f", {res.n_quarantined} of them infrastructure-quarantined"
            if res.n_quarantined else ""
        )
        raise EmulatorBuildError(
            f"{res.n_failed}/{n_pts} exact pipeline points failed "
            f"(non-finite) inside the emulator box{quarantined} (first "
            f"flat index {int(bad[0])}); shrink the box or fix the "
            "configuration"
        )
    if elastic:
        return flat, n_pts
    return dict(res.outputs), n_pts


def make_exact_evaluator(
    base, static, *, n_y: int, impl: str, mesh=None, chunk_size: int = 2048,
    retry=None, fault_plan=None, quarantine_sink=None, cache=None,
    lz_profile=None,
):
    """Zipped exact-pipeline evaluator through the production engine.

    Returns ``evaluate(axes) -> {field: (n,) array}`` where ``axes``
    maps config-schema names to equal-length per-point value arrays.
    Non-finite outputs pass through as NaN (mask-and-report — the
    SERVING layer's out-of-domain fallback must answer garbage corners
    with NaN, not die); the build's probe path layers its own loud
    rejection on top.  The step/aux pairing matches ``run_sweep``'s, so
    emulator refinement compares against exactly the engine that filled
    the table, and chunks are padded to one fixed shape (one compile).

    Robustness seams (all OFF by default — the evaluator stays
    raise-through for the serve layer, which does its own isolation):
    with a ``retry`` policy each chunk call is retried with
    deterministic backoff, and a chunk that stays dead is QUARANTINED —
    NaN outputs plus a True region in the boolean mask handed to
    ``quarantine_sink`` after every ``evaluate`` call — instead of
    killing the caller.  ``fault_plan`` fires injected ``probe`` faults
    keyed by the evaluator's chunk-call counter.

    ``cache`` (a :class:`~bdlz_tpu.provenance.Store`) consults the SAME
    content-addressed chunk entries ``run_sweep`` writes
    (``parallel.sweep.chunk_cache_key`` — keys carry no axes or chunk
    position, only resolved identity + slice bytes), so a warm emulator
    rebuild's probe/held-out evaluations hit the chunks the cold build
    paid; the engine (device tables + jit) is built lazily, only when a
    chunk actually misses.  Cached probe-fault quarantine round-trips
    through entries like the sweep's; entries are only WRITTEN for
    clean chunks unless a fault plan is armed (armed plans join the
    key, so chaos probes can never pollute clean runs).
    """
    import jax
    import jax.numpy as jnp

    from bdlz_tpu.models.yields_pipeline import YieldsResult
    from bdlz_tpu.ops.kjma_table import make_f_table
    from bdlz_tpu.parallel.sweep import (
        _pad_chunk,
        build_grid,
        chunk_cache_key,
        chunk_entry_arrays,
        chunk_entry_ok,
        engine_identity_extra,
        make_sweep_step,
    )
    from bdlz_tpu.physics.percolation import make_kjma_grid
    from bdlz_tpu.utils.retry import call_with_retry

    interpret = impl == "pallas" and jax.devices()[0].platform == "cpu"
    fields = YieldsResult._fields

    # LZ scenario plane (docs/scenarios.md): a chain/thermal mode in the
    # static derives each evaluated point's P from the bounce profile —
    # required up-front so a scenario service/build cannot be
    # constructed without the physics it needs to answer exactly.
    lz_mode = getattr(static, "lz_mode", "two_channel")
    if lz_mode != "two_channel":
        if lz_profile is None:
            raise ValueError(
                f"lz_mode={lz_mode!r} derives P per point from a bounce "
                "profile; pass lz_profile to the exact evaluator"
            )
        from bdlz_tpu.lz.profile import load_profile_csv

        if isinstance(lz_profile, str):
            lz_profile = load_profile_csv(lz_profile)

    # lazy engine: a fully cache-hit evaluate() pays no table build and
    # no compile — most of the warm-rebuild win for probe rounds
    _engine: Dict[str, Any] = {}

    def _ensure_engine():
        if "step" in _engine:
            return _engine["step"], _engine["aux"]
        _engine["step"] = make_sweep_step(
            static, mesh=mesh, n_y=n_y, impl=impl, interpret=interpret
        )
        if impl == "tabulated":
            _engine["aux"] = make_f_table(float(base.I_p), jnp)
        elif impl == "pallas":
            from bdlz_tpu.ops.kjma_pallas import build_shifted_table

            table = make_f_table(float(base.I_p), jnp)
            _engine["aux"] = (table, build_shifted_table(table))
        else:
            _engine["aux"] = make_kjma_grid(jnp)
        return _engine["step"], _engine["aux"]

    def _chunk_extra(pp, lo, hi):
        esdirk_knobs = None
        if impl == "esdirk":
            # mirrors the engine's own per-chunk resolution (knobs=None)
            from bdlz_tpu.solvers.batching import resolve_engine_knobs

            esdirk_knobs = resolve_engine_knobs(
                static, np.asarray(pp.I_p)[lo:hi]
            )
        return engine_identity_extra(
            static, impl, esdirk_knobs=esdirk_knobs, faults=fault_plan,
            interpret=interpret,
        )

    calls = [0]  # the probe-fault key: one count per chunk dispatch

    def evaluate(axes: Mapping[str, Any]) -> Dict[str, np.ndarray]:
        # scenario configs may leave P_chi_to_B unset (the natural way
        # to use a profile-derived P) — placeholder, overwritten below
        pp = build_grid(
            base, dict(axes),
            P_base=0.0 if lz_mode != "two_channel" else None,
            product=False,
        )
        if lz_mode != "two_channel":
            # scenario P per point, BEFORE the chunk loop: the derived P
            # joins the PointParams slice bytes, so chunk-cache keys and
            # the step inputs see exactly what run_sweep's scenario path
            # would have fed them
            from bdlz_tpu.lz.sweep_bridge import (
                scenario_probabilities_for_points,
            )

            pp = pp._replace(P=scenario_probabilities_for_points(
                lz_profile, static, np.asarray(pp.v_w),
                T_p_GeV=np.asarray(pp.T_p_GeV),
            ))
        n = int(np.asarray(pp.m_chi_GeV).shape[0])
        chunk = min(int(chunk_size), n) if chunk_size else n
        out: Dict[str, List[np.ndarray]] = {f: [] for f in fields}
        qmask = np.zeros(n, dtype=bool)
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            # the fault key is the LOGICAL chunk call — retries share it,
            # so a keyed "raise" spec stays persistent across the retry
            call_idx = calls[0]
            calls[0] += 1

            key = None
            if cache is not None:
                key = chunk_cache_key(
                    base, static, pp, lo, hi, n_y=n_y, impl=impl,
                    extra=_chunk_extra(pp, lo, hi),
                    fault_ctx=(
                        ("probe", call_idx, lo, hi)
                        if fault_plan is not None else None
                    ),
                )
                ent = cache.get_npz(f"sweep_chunk/{key}.npz")
                if chunk_entry_ok(ent, hi - lo):
                    for f in fields:
                        out[f].append(ent[f])
                    qm = ent.get("quarantined")
                    if qm is not None:
                        qmask[lo:hi] = np.asarray(qm, dtype=bool)
                    continue

            attempts = [0]  # counts one_chunk calls → retries = calls - 1

            def one_chunk(lo=lo, hi=hi, call_idx=call_idx,
                          attempts=attempts):
                attempts[0] += 1
                if fault_plan is not None:
                    fault_plan.fire("probe", call_idx)
                step, aux = _ensure_engine()
                res = step(_pad_chunk(pp, lo, hi, chunk), aux)
                return {
                    f: np.asarray(getattr(res, f))[: hi - lo]
                    for f in fields
                }

            quarantined_here = False
            try:
                host = (
                    call_with_retry(one_chunk, retry, label=f"probe{lo}")
                    if retry is not None else one_chunk()
                )
            except Exception:  # noqa: BLE001 — quarantined when allowed
                if quarantine_sink is None:
                    raise
                host = {f: np.full(hi - lo, np.nan) for f in fields}
                qmask[lo:hi] = True
                quarantined_here = True
            if cache is not None and (
                not quarantined_here or fault_plan is not None
            ):
                cache.put_npz(
                    f"sweep_chunk/{key}.npz",
                    chunk_entry_arrays(
                        host,
                        n_retries=max(attempts[0] - 1, 0),
                        qmask=(
                            np.ones(hi - lo, dtype=bool)
                            if quarantined_here else None
                        ),
                    ),
                )
            for f in fields:
                out[f].append(host[f])
        if quarantine_sink is not None:
            quarantine_sink(qmask)
        return {f: np.concatenate(v) for f, v in out.items()}

    return evaluate


def _emulated_fields(
    axis_nodes: List[np.ndarray],
    axis_scales: List[str],
    log_values: Dict[str, np.ndarray],
    probes: np.ndarray,
) -> Dict[str, np.ndarray]:
    """Host-side interim-emulator prediction at (n, d) probe points.

    Uses the SAME trace-safe interpolation core as the jitted query
    kernel (``grid.interp_log_fields``) with ``xp=np`` — the build's
    error estimates and the served values cannot drift apart.
    """
    n = probes.shape[0]
    out = {f: np.empty(n) for f in log_values}
    for i in range(n):
        logs = interp_log_fields(
            probes[i], axis_nodes, axis_scales, log_values, np
        )
        for f, v in logs.items():
            out[f][i] = 10.0 ** v
    return out


def _probe_errors(
    emu: Dict[str, np.ndarray], exact: Dict[str, np.ndarray]
) -> np.ndarray:
    """Per-probe error = max over fields of the shared gate rule."""
    from bdlz_tpu.validation import relative_errors

    per_field = [relative_errors(emu[f], exact[f]) for f in emu]
    return np.max(np.stack(per_field), axis=0)


def _curvature_scores(
    log_values: Dict[str, np.ndarray],
    axis_nodes: List[np.ndarray],
    axis_scales: List[str],
    probe: np.ndarray,
) -> np.ndarray:
    """Per-axis estimated local interpolation error at one probe.

    For each axis: ``|f''| · h²`` of log10(value) along that axis at the
    probe's nearest grid point, with the second DIVIDED difference taken
    in the axis's own interpolation coordinate (:func:`grid.axis_coord`
    — index-space differences would be blind to non-uniform spacing,
    which refinement creates by design) and ``h`` the probe's bracketing
    gap in that coordinate.  This is, up to a constant, the multilinear
    interpolation error the refinement is trying to kill — so ranking
    axes by it spends each insert where it buys the most.  A 2-node axis
    has no curvature estimate yet and scores +inf: it must be split
    before anything can be said about it.
    """
    from bdlz_tpu.emulator.grid import axis_coord

    d = len(axis_nodes)
    near = tuple(
        int(np.clip(np.searchsorted(axis_nodes[k], probe[k]), 0,
                    len(axis_nodes[k]) - 1))
        for k in range(d)
    )
    scores = np.zeros(d)
    for k in range(d):
        nodes = axis_nodes[k]
        n_k = len(nodes)
        if n_k < 3:
            scores[k] = np.inf
            continue
        i = int(np.clip(near[k], 1, n_k - 2))
        u = axis_coord(np.asarray(nodes), axis_scales[k], np)
        bracket = int(np.clip(np.searchsorted(nodes, probe[k]) - 1, 0, n_k - 2))
        h = float(u[bracket + 1] - u[bracket])
        du_lo = float(u[i] - u[i - 1])
        du_hi = float(u[i + 1] - u[i])
        for logv in log_values.values():
            lo = near[:k] + (i - 1,) + near[k + 1:]
            mid = near[:k] + (i,) + near[k + 1:]
            hi = near[:k] + (i + 1,) + near[k + 1:]
            f2 = 2.0 * (
                (float(logv[hi]) - float(logv[mid])) / du_hi
                - (float(logv[mid]) - float(logv[lo])) / du_lo
            ) / (du_lo + du_hi)
            scores[k] = max(scores[k], abs(f2) * h * h)
    return scores


def _interp_grad_at(
    log_values: Dict[str, np.ndarray],
    axis_nodes: List[np.ndarray],
    axis_scales: List[str],
    probe: np.ndarray,
) -> np.ndarray:
    """Gradient of the INTERPOLANT at one probe, (n_fields, d), in each
    axis's scale coordinate.

    ∂/∂u_k of the multilinear surface = (face value difference)/Δu_k,
    with the two faces evaluated by the same shared stencil
    (:func:`grid.interp_log_fields`) at the probe with coordinate k
    pinned to its bracketing nodes — so the gradient compared against
    the exact pipeline's is exactly the served surface's, not a
    re-derivation that could drift.
    """
    d = len(axis_nodes)
    fields = list(log_values)
    out = np.zeros((len(fields), d))
    for k in range(d):
        nodes = axis_nodes[k]
        i = int(np.clip(np.searchsorted(nodes, probe[k], side="right") - 1,
                        0, len(nodes) - 2))
        u = axis_coord(np.asarray(nodes[[i, i + 1]]), axis_scales[k], np)
        du = float(u[1] - u[0])
        lo_p = probe.copy()
        lo_p[k] = nodes[i]
        hi_p = probe.copy()
        hi_p[k] = nodes[i + 1]
        lo_v = interp_log_fields(lo_p, axis_nodes, axis_scales, log_values, np)
        hi_v = interp_log_fields(hi_p, axis_nodes, axis_scales, log_values, np)
        for f_i, f in enumerate(fields):
            out[f_i, k] = (float(hi_v[f]) - float(lo_v[f])) / du
    return out


def _fisher_axis_scores(
    jac_exact: np.ndarray,
    log_values: Dict[str, np.ndarray],
    axis_nodes: List[np.ndarray],
    axis_scales: List[str],
    probe: np.ndarray,
    fields: List[str],
) -> np.ndarray:
    """Per-axis error attribution at one failing probe, gradient-aware.

    ``|∂log10f/∂u_k (exact) − ∂log10f/∂u_k (interpolant)| · h_k`` maxed
    over fields, with ``h_k`` the probe's bracketing gap in the axis's
    scale coordinate: a first-order bound on the log-interpolation
    error ATTRIBUTABLE to axis k's resolution at this exact probe.  The
    legacy signal (:func:`_curvature_scores`) can only inspect an
    axis-local second-difference stencil at the nearest grid node — on
    anisotropic surfaces it misattributes, and every misattributed
    insert costs a full hyperplane of exact evaluations.  An axis whose
    direction the surface is exactly (log-)linear in scores ~0 here and
    is never split on a probe's account — INCLUDING 2-node axes, where
    the legacy rule is structurally blind (no second difference exists,
    so it scores +inf and burns a full hyperplane on the first failing
    probe even when the surface is a pure power law along that axis;
    the gradient field is exactly the information it lacks).  A curved
    2-node axis is not missed systematically: a single probe can sit
    near its cell midpoint (where the mismatch vanishes), but the pool
    accumulates probes at fresh offsets every round, and the held-out
    gate still vouches for the final surface.
    """
    d = len(axis_nodes)
    g_emu = _interp_grad_at(log_values, axis_nodes, axis_scales, probe)
    order = {f: i for i, f in enumerate(log_values)}
    scores = np.zeros(d)
    for k in range(d):
        nodes = axis_nodes[k]
        i = int(np.clip(np.searchsorted(nodes, probe[k], side="right") - 1,
                        0, len(nodes) - 2))
        u = axis_coord(np.asarray(nodes[[i, i + 1]]), axis_scales[k], np)
        h = float(u[1] - u[0])
        for f_i, f in enumerate(fields):
            mismatch = abs(float(jac_exact[f_i, k]) - g_emu[order[f], k])
            scores[k] = max(scores[k], mismatch * h)
    return scores


def _axis_interval_estimates(
    log_values: Dict[str, np.ndarray],
    nodes: List[np.ndarray],
    scales: List[str],
    k: int,
    weights: "np.ndarray | None" = None,
) -> "np.ndarray | None":
    """Per-interval a-posteriori error estimate along axis ``k``.

    ``|f''|·h²/8·ln10`` — the standard linear-interpolation bound on
    log10(value), converted to a VALUE-relative error — with ``f''`` the
    second divided difference of every field in the axis's scale
    coordinate, maxed over fields AND over the rest of the tensor grid.
    This is what lets the refinement control the sup-norm: a random
    probe pool only measures error where probes land, while the table
    itself knows where it curves — intervals no probe ever hit still
    get split when their estimate exceeds the target.  Returns one
    estimate per interval (len n_k − 1), or None for a 2-node axis (no
    curvature information until a probe forces a split).

    ``weights`` (node-level tensor over the full grid, in [floor, 1] —
    see :func:`_posterior_node_weights`) multiplies the curvature
    BEFORE the max over the rest of the grid: with the posterior hook
    armed, an interval only demands a split where posterior mass and
    curvature coincide, so the build coarsens dead regions by design.
    """
    u = np.asarray(axis_coord(np.asarray(nodes[k]), scales[k], np))
    n_k = len(u)
    if n_k < 3:
        return None
    du = np.diff(u)
    c = np.zeros(n_k - 2)
    w_flat = (
        None if weights is None
        else np.moveaxis(weights, k, 0).reshape(n_k, -1)
    )
    for logv in log_values.values():
        f = np.moveaxis(logv, k, 0).reshape(n_k, -1)
        d1 = np.diff(f, axis=0) / du[:, None]
        d2 = 2.0 * np.diff(d1, axis=0) / (du[:-1] + du[1:])[:, None]
        d2 = np.abs(d2)
        if w_flat is not None:
            d2 = d2 * w_flat[1:-1]
        c = np.maximum(c, np.max(d2, axis=1))
    # node-level curvature (ends take their neighbor's), then per
    # interval the worse endpoint
    c_node = np.concatenate([c[:1], c, c[-1:]])
    return np.maximum(c_node[:-1], c_node[1:]) * du * du / 8.0 * _LN10


def _node_to_cell_max(arr: np.ndarray) -> np.ndarray:
    """Reduce a node-level tensor to cell level: per cell, the max over
    its 2^d corners (pairwise max along every axis)."""
    for k in range(arr.ndim):
        lo = tuple(
            slice(None, -1) if j == k else slice(None)
            for j in range(arr.ndim)
        )
        hi = tuple(
            slice(1, None) if j == k else slice(None)
            for j in range(arr.ndim)
        )
        arr = np.maximum(arr[lo], arr[hi])
    return arr


def cell_error_estimates(
    log_values: Dict[str, np.ndarray],
    nodes: List[np.ndarray],
    scales: List[str],
) -> np.ndarray:
    """Per-CELL a-posteriori relative-error estimate of the final table.

    The same ``|f''|·h²/8·ln10`` linear-interpolation bound the
    refinement steers on, but evaluated LOCALLY (no max over the rest
    of the grid): for each axis the second divided differences of every
    field in the axis's scale coordinate, endpoint-extended to node
    level, reduced to cells by corner max, scaled by the cell's own
    axis width, then maxed over axes and fields.  A 2-node axis carries
    no curvature information and contributes 0 (its error is vouched
    for by the probe pool alone — exactly the build's refinement
    contract).  Shape ``(n_1-1, …, n_d-1)``; persisted into the
    artifact so the serving layer can gate exact fallback per query.

    Always UNWEIGHTED, even under a posterior-weighted build: the gate
    must see the surface's honest local error — dead regions a weighted
    build deliberately left coarse then fall back to the exact path,
    which is the whole point of composing the two features.
    """
    d = len(nodes)
    cells = tuple(len(a) - 1 for a in nodes)
    total = np.zeros(cells)
    for k in range(d):
        u = np.asarray(axis_coord(np.asarray(nodes[k]), scales[k], np))
        n_k = len(u)
        if n_k < 3:
            continue
        du = np.diff(u)
        du_shape = tuple(len(du) if j == k else 1 for j in range(d))
        c_node = None
        for logv in log_values.values():
            f = np.moveaxis(logv, k, 0)
            d1 = np.diff(f, axis=0) / du.reshape(-1, *([1] * (d - 1)))
            d2 = 2.0 * np.diff(d1, axis=0) / (
                (du[:-1] + du[1:]).reshape(-1, *([1] * (d - 1)))
            )
            d2 = np.abs(d2)
            ext = np.concatenate([d2[:1], d2, d2[-1:]], axis=0)
            ext = np.moveaxis(ext, 0, k)
            c_node = ext if c_node is None else np.maximum(c_node, ext)
        est_k = _node_to_cell_max(c_node) * (
            du.reshape(du_shape) ** 2
        ) / 8.0 * _LN10
        total = np.maximum(total, est_k)
    return total


def _posterior_node_weights(
    log_values: Dict[str, np.ndarray], floor: float = 1e-3
) -> Tuple[np.ndarray, float]:
    """Planck-likelihood weight of every grid node, from the surface
    itself: ``w = clip(exp(logp − max logp), floor, 1)`` with the
    Planck Gaussian logp evaluated on the stored log10(ρ_B), log10(ρ_DM)
    tables (``sampling.likelihoods.planck_gaussian_logp`` — the hook the
    tentpole names).  The floor keeps dead regions under COARSE control
    instead of none (a served query there still meets rtol/floor, and
    the per-cell error gate covers the rest).  Returns (weights,
    max_logp) — the max is the normalization probes reuse.
    """
    from bdlz_tpu.constants import RHO_CRIT_OVER_H2_KG_M3
    from bdlz_tpu.sampling.likelihoods import planck_gaussian_logp

    ob = 10.0 ** log_values["rho_B_kg_m3"] / RHO_CRIT_OVER_H2_KG_M3
    od = 10.0 ** log_values["rho_DM_kg_m3"] / RHO_CRIT_OVER_H2_KG_M3
    lp = np.asarray(planck_gaussian_logp(ob, od))
    lp_max = float(lp.max())
    return np.clip(np.exp(lp - lp_max), floor, 1.0), lp_max


def _posterior_probe_weights(
    exact: Dict[str, np.ndarray], lp_max: float, floor: float = 1e-3
) -> np.ndarray:
    """The same weight at probe points, from their EXACT values (paid
    anyway), normalized against the node grid's max logp."""
    from bdlz_tpu.constants import RHO_CRIT_OVER_H2_KG_M3
    from bdlz_tpu.sampling.likelihoods import planck_gaussian_logp

    lp = np.asarray(planck_gaussian_logp(
        exact["rho_B_kg_m3"] / RHO_CRIT_OVER_H2_KG_M3,
        exact["rho_DM_kg_m3"] / RHO_CRIT_OVER_H2_KG_M3,
    ))
    return np.clip(np.exp(lp - lp_max), floor, 1.0)


def _traffic_node_weights(
    nodes: List[np.ndarray],
    locations: np.ndarray,
    floor: float = 1e-3,
) -> Tuple[np.ndarray, np.ndarray]:
    """Traffic weight of every grid node, from a served-query snapshot
    (the closed-loop hook, bdlz_tpu/refine): query locations are clipped
    into the box (out-of-box mass pulls refinement toward the nearest
    edge cell — exactly where an expanded rebuild needs resolution),
    binned per CELL on the current node grid, normalized to
    ``clip(count / max count, floor, 1)``, then lifted to node level by
    corner max (a node bordering a hot cell is hot).  The floor keeps
    unvisited regions under COARSE control instead of none — same
    contract as :func:`_posterior_node_weights`.  Returns
    ``(node_weights, cell_weights)``; the cell weights score probes.
    """
    locs = np.atleast_2d(np.asarray(locations, dtype=np.float64))
    for k, ax in enumerate(nodes):
        locs[:, k] = np.clip(locs[:, k], float(ax[0]), float(ax[-1]))
    counts, _ = np.histogramdd(locs, bins=[np.asarray(a) for a in nodes])
    top = counts.max()
    w_cell = (
        np.clip(counts / top, floor, 1.0) if top > 0
        else np.full(counts.shape, floor)
    )
    # cell -> node by adjacent-cell max (the inverse of
    # _node_to_cell_max): node i touches cells i-1 and i along each axis
    w_node = w_cell
    for k in range(w_node.ndim):
        edge_lo = tuple(
            slice(0, 1) if j == k else slice(None)
            for j in range(w_node.ndim)
        )
        edge_hi = tuple(
            slice(-1, None) if j == k else slice(None)
            for j in range(w_node.ndim)
        )
        ext = np.concatenate(
            [w_node[edge_lo], w_node, w_node[edge_hi]], axis=k
        )
        lo = tuple(
            slice(None, -1) if j == k else slice(None)
            for j in range(ext.ndim)
        )
        hi = tuple(
            slice(1, None) if j == k else slice(None)
            for j in range(ext.ndim)
        )
        w_node = np.maximum(ext[lo], ext[hi])
    return w_node, w_cell


def _traffic_probe_weights(
    nodes: List[np.ndarray], probes: np.ndarray, w_cell: np.ndarray
) -> np.ndarray:
    """The traffic cell weight at each probe point (cell lookup on the
    current grid — probes in cold cells stop demanding splits)."""
    idx = tuple(
        np.clip(
            np.searchsorted(nodes[k], probes[:, k], side="right") - 1,
            0, len(nodes[k]) - 2,
        )
        for k in range(len(nodes))
    )
    return w_cell[idx]


def build_emulator(
    base,
    spec: Mapping[str, AxisSpec],
    static=None,
    *,
    rtol: float = 1e-4,
    safety: float = 2.0,
    n_probe: int = 64,
    n_holdout: Optional[int] = None,
    max_rounds: int = 8,
    max_nodes_per_axis: int = 1024,
    seed: int = 0,
    n_y: int = 2000,
    impl: str = "tabulated",
    chunk_size: int = 2048,
    mesh=None,
    out_dir: Optional[str] = None,
    event_log=None,
    require_converged: bool = False,
    fault_plan=None,
    retry=None,
    cache=None,
    seam_split: Optional[bool] = None,
    posterior_weight: Optional[str] = None,
    refine_signal: Optional[str] = None,
    lz_profile=None,
    bounce=None,
    elastic=None,
    traffic=None,
) -> Tuple[EmulatorArtifact, BuildReport]:
    """Build (and optionally save) an error-controlled yield-surface emulator.

    ``spec`` maps config-schema axis names (``parallel.sweep.AXIS_MAP``
    keys) to :class:`AxisSpec` boxes; axis order fixes the artifact's
    coordinate order.  ``rtol`` is the ADVERTISED tolerance under the
    shared gate rule; internally the refinement targets ``rtol/safety``
    (default half-tolerance), because the probe pool is a sample — a
    pool converged exactly AT rtol leaves the held-out set scoring just
    above it.  The recorded ``max_rel_err`` is measured at the end on a
    held-out random point set (``n_holdout``, default 4×``n_probe``)
    that the refinement never saw.  With ``require_converged=True`` a
    budget-exhausted build raises instead of saving a surface that
    missed its tolerance.

    ``cache`` (store / root path / None — resolved like ``run_sweep``'s)
    routes every exact evaluation the build pays — the initial tensor
    grid, refinement hyperplanes, probe rounds, the held-out set —
    through the content-addressed sweep chunk cache
    (docs/provenance.md): a warm rebuild of the same box skips straight
    to gather with a bit-identical surface (the ``sweep_cache`` bench
    line measures exactly this), and an overlapping rebuild reuses
    whatever hyperplane slices an earlier build already paid for.

    ``elastic`` (a worker count or a kwarg dict for
    :func:`~bdlz_tpu.parallel.scheduler.run_sweep_elastic`; needs
    ``cache``) runs every product-grid population on the elastic
    work-stealing fleet and folds chunks into the surface streaming as
    they commit — bitwise the same table, but a lost worker costs one
    lease TTL instead of the build.  Probe rounds (zipped evaluation)
    and seam-split sub-builds stay on the serial engine.

    ``seam_split`` (tri-state, ``Config.seam_split`` when None): a box
    crossing the T = m/3 flux-seam band is split at the band into one
    single-scheme sub-artifact per side and returned as a
    :class:`~bdlz_tpu.emulator.multidomain.MultiDomainArtifact` (with a
    :class:`~bdlz_tpu.emulator.multidomain.MultiDomainBuildReport`)
    instead of grinding first-order refinement against the diagonal
    kink — see ``emulator/multidomain.py``.  ``posterior_weight``
    ("planck", or ``Config.posterior_weight`` when None) multiplies the
    refinement criterion by the Planck-likelihood weight of the interim
    surface: the build spends exact sweep points where posterior mass
    concentrates and coarsens dead regions (their held-out error may
    exceed ``rtol`` by design — the persisted per-cell estimates keep
    the serving layer's error gate honest there), and the resolved
    weight name joins the artifact identity.

    ``refine_signal`` ("fisher", or ``Config.refine_signal`` when None;
    None = legacy) upgrades the PROBE-driven split attribution from the
    axis-local |f''| stencil to the exact pipeline's gradient field
    (:func:`bdlz_tpu.sampling.grad.make_field_log10_jacobian` — the
    differentiable-posterior by-product): each failing probe pays one
    reverse-mode Jacobian (billed separately as ``n_grad_evals`` on the
    report) and splits the axis whose exact-vs-interpolant gradient
    mismatch actually causes its error.  Second-order where the stencil
    is axis-local: the same held-out tolerance is reached with fewer
    exact hyperplane evaluations (A/B-pinned in tests).  Two-channel +
    tabulated-impl only, loudly — a scenario mode derives P host-side
    (no in-graph gradient) and the stiff/direct engines never evaluate
    through the differentiable closure this signal uses.

    ``refine_signal="traffic"`` (or ``"traffic*planck"``) weights the
    refinement criterion by OBSERVED query density instead: ``traffic``
    (a :class:`~bdlz_tpu.refine.TrafficSnapshot`, required for these
    signals and rejected without them) supplies served-query locations
    that are binned per cell on the current grid each round —
    ``clip(count/max, 1e-3, 1)`` — so the build spends exact
    evaluations where the service's traffic actually lands and coarsens
    unvisited regions under the same floor/error-gate contract as the
    posterior hook.  ``"traffic*planck"`` composes both weights
    multiplicatively even when ``posterior_weight`` is off.  The
    snapshot fingerprint joins the artifact identity as its own
    ``traffic`` key (wildcard-when-unstated), so two builds steered by
    different snapshots hash apart.

    ``bounce`` (a :class:`~bdlz_tpu.bounce.PotentialSpec` / mapping /
    JSON path; scenario modes only, mutually exclusive with
    ``lz_profile``) shoots the wall profile in-framework from the
    potential instead of loading a CSV; the potential fingerprint joins
    the artifact identity as its own ``bounce`` key (wildcard-when-
    unstated, like ``lz_profile``) so cross-potential artifact reuse
    rejects loudly at admission.
    """
    from bdlz_tpu.config import (
        VALID_POSTERIOR_WEIGHTS,
        VALID_REFINE_SIGNALS,
        static_choices_from_config,
        validate,
    )
    from bdlz_tpu.parallel.sweep import AXIS_MAP

    t0 = time.time()
    validate(base)
    if not (safety >= 1.0):
        raise EmulatorBuildError(f"safety must be >= 1, got {safety}")
    refine_tol = float(rtol) / float(safety)
    if static is None:
        static = static_choices_from_config(base)
    if not spec:
        raise EmulatorBuildError("emulator spec needs at least one axis")
    unknown = sorted(set(spec) - set(AXIS_MAP))
    if unknown:
        raise EmulatorBuildError(
            f"unknown emulator axes {unknown}; valid: {sorted(AXIS_MAP)}"
        )
    pw = (
        posterior_weight if posterior_weight is not None
        else getattr(base, "posterior_weight", None)
    )
    if pw is not None and pw not in VALID_POSTERIOR_WEIGHTS:
        raise EmulatorBuildError(
            f"posterior_weight={pw!r} is not one of "
            f"{VALID_POSTERIOR_WEIGHTS} (or None)"
        )
    rs = (
        refine_signal if refine_signal is not None
        else getattr(base, "refine_signal", None)
    )
    if rs is not None and rs not in VALID_REFINE_SIGNALS:
        raise EmulatorBuildError(
            f"refine_signal={rs!r} is not one of "
            f"{VALID_REFINE_SIGNALS} (or None = curvature)"
        )
    # --- traffic-weighted refinement (closed-loop plane, bdlz_tpu/refine):
    # a traffic signal multiplies the criterion by observed query density,
    # so it REQUIRES the snapshot — and a snapshot without the signal
    # would silently change nothing, which is a caller error, not a no-op
    # (the lz_profile/scenario pairing rule, applied again). ---
    traffic_on = rs in ("traffic", "traffic*planck")
    if traffic_on and traffic is None:
        raise EmulatorBuildError(
            f"refine_signal={rs!r} weights refinement by served traffic; "
            "pass traffic=<TrafficSnapshot> (bdlz_tpu.refine) to "
            "build_emulator"
        )
    if traffic is not None and not traffic_on:
        raise EmulatorBuildError(
            f"traffic=<snapshot> requires refine_signal 'traffic' or "
            f"'traffic*planck' (resolved: {rs!r}) — a snapshot the "
            "refinement never consults would silently change nothing"
        )
    traffic_fp = None
    traffic_locs = None
    if traffic is not None:
        t_axes = tuple(str(n) for n in traffic.axis_names)
        if t_axes != tuple(spec):
            raise EmulatorBuildError(
                f"traffic snapshot axes {t_axes} do not match the "
                f"emulator spec axes {tuple(spec)} (order included) — "
                "query locations would be binned against the wrong "
                "coordinates"
            )
        traffic_locs = np.atleast_2d(
            np.asarray(traffic.locations, dtype=np.float64)
        )
        if traffic_locs.shape[0] == 0:
            raise EmulatorBuildError(
                "traffic snapshot carries zero query locations; nothing "
                "to weight by — serve traffic first or drop the signal"
            )
        traffic_fp = str(traffic.fingerprint)
    # "traffic*planck" composes BOTH weights multiplicatively even when
    # the posterior_weight knob itself is off — the product signal is
    # the point of the name
    use_planck = pw is not None or rs == "traffic*planck"
    # Potential-space plane (docs/scenarios.md): a bounce spec is shot
    # into a wall profile once, host-side, then rides the lz_profile
    # machinery below unchanged — the potential fingerprint joins the
    # artifact identity as its own ``bounce`` key alongside the derived
    # profile's ``lz_profile`` fingerprint.  Seam-split sub-builds
    # re-derive from the SPEC (pure function of the knobs), so both
    # sides resolve the identical identity.
    lz_mode = getattr(static, "lz_mode", "two_channel")
    bounce_fp = None
    if bounce is not None:
        if lz_profile is not None:
            raise EmulatorBuildError(
                "pass either bounce or lz_profile, not both — the bounce "
                "solver derives the profile the lz_profile seam would load"
            )
        if elastic:
            raise EmulatorBuildError(
                "elastic build cannot ship per-point bounce profiles; "
                "drop elastic=... or bounce=..."
            )
        if lz_mode == "two_channel":
            raise EmulatorBuildError(
                "bounce requires a scenario lz_mode ('chain'/'thermal') "
                "in the config/static — the two-channel emulator takes P "
                "from the config or a P_chi_to_B axis"
            )
        from bdlz_tpu.bounce import (
            as_potential_spec,
            bounce_profile,
            potential_fingerprint,
        )

        bounce = as_potential_spec(bounce)
        bounce_fp = potential_fingerprint(bounce)
        lz_profile = bounce_profile(bounce)
    # LZ scenario plane (docs/scenarios.md): a chain/thermal mode builds
    # the surface over profile-derived per-point P, so the profile is
    # required — and a profile without a scenario mode would silently
    # change nothing (the two-channel emulator evaluates P from the
    # config/axes), which is a caller error, not a no-op.
    lz_fp = None
    if lz_mode != "two_channel":
        if lz_profile is None:
            raise EmulatorBuildError(
                f"lz_mode={lz_mode!r} derives P per point from a bounce "
                "profile; pass lz_profile to build_emulator"
            )
        from bdlz_tpu.lz.profile import load_profile_csv
        from bdlz_tpu.lz.sweep_bridge import profile_fingerprint

        if isinstance(lz_profile, str):
            lz_profile = load_profile_csv(lz_profile)
        lz_fp = profile_fingerprint(lz_profile)
        if "P_chi_to_B" in spec:
            raise EmulatorBuildError(
                "P_chi_to_B cannot be an emulator axis when the scenario "
                "derives P per point; use v_w (and T_p_GeV for thermal)"
            )
    elif lz_profile is not None:
        raise EmulatorBuildError(
            "lz_profile requires a scenario lz_mode ('chain'/'thermal') "
            "in the config/static — the two-channel emulator takes P from "
            "the config or a P_chi_to_B axis"
        )

    # --- seam-split resolution (tri-state; emulator/multidomain.py) ---
    from bdlz_tpu.emulator.multidomain import (
        build_seam_split_emulator,
        resolve_seam_split,
    )

    band = resolve_seam_split(
        base, spec, seam_split, rtol=float(rtol), safety=float(safety),
    )
    if band is not None:
        if elastic:
            print(
                "[emulator] seam-split builds run the serial sweep "
                "engine per sub-domain; ignoring elastic=...",
                file=sys.stderr,
            )
        return build_seam_split_emulator(
            base, spec, static, band=band, out_dir=out_dir,
            event_log=event_log, rtol=rtol, safety=safety,
            n_probe=n_probe, n_holdout=n_holdout, max_rounds=max_rounds,
            max_nodes_per_axis=max_nodes_per_axis, seed=seed, n_y=n_y,
            impl=impl, chunk_size=chunk_size, mesh=mesh,
            require_converged=require_converged, fault_plan=fault_plan,
            retry=retry, cache=cache, posterior_weight=pw,
            refine_signal=rs,
            # sub-builds take the SPEC and re-derive (pure in the knobs);
            # handing them the already-derived profile too would trip the
            # either/or guard above
            lz_profile=None if bounce_fp is not None else lz_profile,
            bounce=bounce,
            traffic=traffic,
        )
    # Engine resolution mirrors run_sweep, and is done HERE (once) so the
    # product population, the probe evaluations, and the artifact identity
    # all name the same engine — a split would gate the emulator against a
    # different engine than the one that filled its table.
    from bdlz_tpu.config import needs_ode_path

    if needs_ode_path(base) and impl != "esdirk_lockstep":
        impl = "esdirk"
    if "I_p" in spec and impl in ("tabulated", "pallas"):
        # mirrors run_sweep's use_table guard — the F-table is per-I_p
        impl = "direct"
    spec = dict(spec)
    axis_names: List[str] = list(spec)
    nodes: List[np.ndarray] = [_axis_nodes(spec[k]) for k in axis_names]
    scales: List[str] = [spec[k].scale for k in axis_names]
    rng = np.random.default_rng(seed)

    # Robustness resolution (docs/robustness.md): grid sweeps inherit
    # chunk-level healing through run_sweep; the probe evaluator gets a
    # retry + quarantine seam of its own so one dead probe chunk drops
    # those probes (recorded) instead of killing the build.
    from bdlz_tpu.faults import FaultPlan
    from bdlz_tpu.utils.retry import resolve_engine_retry

    faults = FaultPlan.resolve(fault_plan, base)
    retry_policy = resolve_engine_retry(retry, base, static)
    # One store for every exact evaluation of the build (grid sweeps
    # inherit it through run_sweep; probes through the evaluator), so
    # hyperplane and probe chunks land in — and hit — the same entries.
    from bdlz_tpu.provenance import resolve_store

    store = resolve_store(cache, base, label="emulator")

    # Resolve the quadrature tri-state ONCE, over the initial tensor
    # grid, and pass the explicit bool to EVERY internal sweep (the
    # initial population, the hyperplane refinements, the probe
    # evaluator): per-call re-resolution could flip schemes between
    # hyperplanes, splicing two quadratures into one surface.  The
    # resolved value joins the artifact identity through the static
    # (build_identity's quad_panel_gl key), so surfaces built under
    # different quad schemes can never be confused.
    from bdlz_tpu.validation import resolve_quad_panel_gl

    audit_grid = None
    if impl == "tabulated" and static.quad_panel_gl is None:
        from bdlz_tpu.parallel.sweep import build_grid

        audit_grid = build_grid(
            base, {k: a for k, a in zip(axis_names, nodes)}, product=True,
        )
    quad_on, _ = resolve_quad_panel_gl(
        audit_grid, static, impl, n_y, label="emulator",
    )
    static = static._replace(quad_panel_gl=quad_on)

    # --- Fisher-aware refinement signal (gradient layer by-product) ---
    field_jac = None
    n_grad_evals = 0
    if rs == "fisher":
        if lz_mode != "two_channel":
            raise EmulatorBuildError(
                f"refine_signal='fisher' needs the differentiable "
                f"two-channel path; lz_mode={lz_mode!r} derives P "
                "host-side per point (no in-graph gradient — a silent "
                "zero would mis-steer every split)"
            )
        if impl != "tabulated":
            raise EmulatorBuildError(
                f"refine_signal='fisher' differentiates the tabulated "
                f"fast path; the resolved engine is impl={impl!r} "
                "(I_p axes and stiff configs keep the curvature signal)"
            )
        import jax.numpy as jnp

        from bdlz_tpu.ops.kjma_table import make_f_table
        from bdlz_tpu.sampling.grad import make_field_log10_jacobian

        field_jac = make_field_log10_jacobian(
            base, static, make_f_table(float(base.I_p), jnp),
            axis_names, scales, n_y=n_y,
        )

    def grid_shape() -> Tuple[int, ...]:
        return tuple(len(a) for a in nodes)

    # --- initial population: one product sweep over the tensor grid ---
    flat, n_exact = _exact_fields(
        base, {k: a for k, a in zip(axis_names, nodes)}, static,
        product=True, mesh=mesh, chunk_size=chunk_size, n_y=n_y, impl=impl,
        fault_plan=faults, retry=retry_policy, cache=store,
        lz_profile=lz_profile, elastic=elastic,
    )
    values = {f: np.asarray(flat[f]).reshape(grid_shape()) for f in FIELDS}
    _check_positive(values)
    log_values = {f: np.log10(values[f]) for f in FIELDS}

    # ONE compiled probe evaluator for every refinement round and the
    # held-out pass (re-building it per round would re-jit per round)
    qsink: List[np.ndarray] = []
    exact_eval = make_exact_evaluator(
        base, static, n_y=n_y, impl=impl, mesh=mesh,
        chunk_size=min(int(chunk_size), int(n_probe)),
        retry=retry_policy, fault_plan=faults,
        quarantine_sink=qsink.append, cache=store,
        lz_profile=lz_profile,
    )
    n_quarantined_probes = 0

    def exact_zip(axes):
        qsink.clear()
        flat = exact_eval(axes)
        q = (
            qsink[-1] if qsink
            else np.zeros(len(next(iter(flat.values()))), dtype=bool)
        )
        # every SCORED field must be finite, not just the ratio: a probe
        # whose rho overflows while DM_over_B stays finite would
        # otherwise NaN its error score, and NaN > tol is False — the
        # probe would silently pass and the build falsely converge.
        # Quarantined probes are exempt: infrastructure failure is the
        # CALLER's droppable case, physics NaN stays fatal.
        for fname in FIELDS:
            bad = ~np.isfinite(flat[fname]) & ~q
            if bad.any():
                raise EmulatorBuildError(
                    f"{int(bad.sum())}/{len(bad)} exact probe points have "
                    f"non-finite {fname} inside the emulator box; shrink "
                    "the box or fix the configuration"
                )
        return flat, q

    # The probe POOL accumulates across rounds: every probe's exact value
    # is paid once and cached, and convergence means the WHOLE pool is
    # clean — a single lucky round of fresh probes must not end the
    # build, because localized features (the T = m/3 flux-seam band cuts
    # a diagonal through (m_chi, T_p) boxes) hide from any one small
    # draw.  Re-scoring the pool costs host-side interpolation only.
    pool_probes = np.empty((0, len(axis_names)))
    pool_exact: Dict[str, np.ndarray] = {f: np.empty(0) for f in FIELDS}
    rounds: List[Dict[str, Any]] = []
    converged = False
    for r in range(int(max_rounds) + 1):
        probe_cols = _draw_probes(spec, int(n_probe), rng)
        probes = np.stack([probe_cols[k] for k in axis_names], axis=1)
        exact, q_probe = exact_zip(probe_cols)
        n_exact += int(n_probe)
        if q_probe.any():
            # tolerate quarantined probes: they never enter the pool (a
            # NaN exact value cannot steer refinement), the build keeps
            # refining around them, and the drop is recorded
            n_quarantined_probes += int(q_probe.sum())
            probes = probes[~q_probe]
            exact = {f: exact[f][~q_probe] for f in FIELDS}
        pool_probes = np.concatenate([pool_probes, probes])
        for f in FIELDS:
            pool_exact[f] = np.concatenate([pool_exact[f], exact[f]])
        # posterior weighting (armed hook): node- and probe-level Planck
        # weights of the CURRENT surface, recomputed each round — the
        # criterion below then asks for accuracy only where posterior
        # mass lives, coarsening dead regions by the weight floor
        w_nodes = None
        lp_max = 0.0
        if use_planck:
            w_nodes, lp_max = _posterior_node_weights(log_values)
        # traffic weights are recomputed per round too — the locations
        # are fixed but the cell grid they bin into just grew
        w_cell_traffic = None
        if traffic_on:
            w_traffic, w_cell_traffic = _traffic_node_weights(
                nodes, traffic_locs
            )
            w_nodes = (
                w_traffic if w_nodes is None else w_nodes * w_traffic
            )
        if pool_probes.shape[0]:
            emu = _emulated_fields(nodes, scales, log_values, pool_probes)
            errs = _probe_errors(emu, pool_exact)
            score = errs
            if use_planck:
                score = score * _posterior_probe_weights(pool_exact, lp_max)
            if w_cell_traffic is not None:
                score = score * _traffic_probe_weights(
                    nodes, pool_probes, w_cell_traffic
                )
            failing = np.flatnonzero(score > refine_tol)
        else:
            # every probe so far was infrastructure-quarantined: nothing
            # to score this round (and nothing to converge on — the
            # convergence test below requires a non-empty pool)
            errs = np.zeros(0)
            failing = np.zeros(0, dtype=np.int64)

        # Curvature-driven split candidates (sup-norm control): every
        # interval whose a-posteriori estimate exceeds the internal
        # target gets split, probe or no probe — randomized probes
        # alone leave the un-probed intervals' error uncontrolled (a
        # 200-node axis has more intervals than a round has probes).
        curv: Dict[int, List[Tuple[float, float]]] = {}
        for k in range(len(axis_names)):
            est = _axis_interval_estimates(
                log_values, nodes, scales, k, weights=w_nodes
            )
            if est is None:
                continue
            ax = nodes[k]
            span = float(ax[-1] - ax[0])
            for j in np.flatnonzero(est > refine_tol):
                j = int(j)
                if (ax[j + 1] - ax[j]) <= _MIN_REL_GAP * span:
                    continue
                curv.setdefault(k, []).append((
                    float(est[j]),
                    _midpoint(float(ax[j]), float(ax[j + 1]),
                              spec[axis_names[k]].scale),
                ))
        row = {
            "round": r,
            "pool_size": int(pool_probes.shape[0]),
            "n_failing": int(len(failing)),
            "n_est_splits": sum(len(v) for v in curv.values()),
            "max_rel_err": float(errs.max(initial=0.0)),
            "grid_shape": list(grid_shape()),
        }
        if event_log is not None:
            event_log.emit("emulator_refine_round", **row)
        if pool_probes.shape[0] and not len(failing) and not curv:
            rounds.append(row)
            converged = True
            break
        if r == int(max_rounds):
            rounds.append(row)
            break

        # --- probe-driven inserts: one midpoint per failing pool probe
        # (measured error — it goes in even where the estimate is calm) ---
        inserts: Dict[int, set] = {}
        fail_jacs = None
        if field_jac is not None and len(failing):
            # one vmapped reverse-mode Jacobian batch per round, failing
            # probes only — billed on the report as n_grad_evals
            import jax.numpy as jnp

            fail_jacs = np.asarray(field_jac(
                jnp.asarray(pool_probes[np.asarray(failing)])
            ))
            n_grad_evals += int(len(failing))
        for j_f, p in enumerate(failing):
            if fail_jacs is not None:
                scores = _fisher_axis_scores(
                    fail_jacs[j_f], log_values, nodes, scales,
                    pool_probes[p], _GRAD_FIELDS,
                )
            else:
                scores = _curvature_scores(
                    log_values, nodes, scales, pool_probes[p]
                )
            for k in np.argsort(-scores):
                k = int(k)
                ax = nodes[k]
                if len(ax) + len(inserts.get(k, ())) >= int(max_nodes_per_axis):
                    continue  # axis at cap; try the next-best one
                i = int(np.clip(np.searchsorted(ax, pool_probes[p, k]) - 1,
                                0, len(ax) - 2))
                mid = _midpoint(float(ax[i]), float(ax[i + 1]),
                                spec[axis_names[k]].scale)
                span = float(ax[-1] - ax[0])
                if (ax[i + 1] - ax[i]) <= _MIN_REL_GAP * span:
                    continue  # interval already saturated; next-best axis
                inserts.setdefault(k, set()).add(mid)
                break
        # --- estimate-driven inserts, worst intervals first, bounded so
        # a pathological axis cannot blow the tensor grid past the cap ---
        for k, cands in curv.items():
            room = (
                int(max_nodes_per_axis) - len(nodes[k])
                - len(inserts.get(k, ()))
            )
            for _, mid in sorted(cands, reverse=True)[: max(room, 0)]:
                inserts.setdefault(k, set()).add(mid)
        if not inserts:
            if not pool_probes.shape[0]:
                # nothing split AND nothing scored (every probe so far
                # quarantined): keep drawing — a later round's probes
                # may land after the infrastructure recovers
                rounds.append({
                    **row,
                    "note": "pool empty (probes quarantined); redrawing",
                })
                continue
            rounds.append({**row, "note": "no refinable interval left"})
            break

        # --- evaluate only the new hyperplanes, axis by axis ---
        added = 0
        for k in sorted(inserts):
            new_vals = np.asarray(sorted(inserts[k]), dtype=np.float64)
            axes_eval = {
                name: (new_vals if j == k else nodes[j])
                for j, name in enumerate(axis_names)
            }
            flat, n_new = _exact_fields(
                base, axes_eval, static, product=True, mesh=mesh,
                chunk_size=chunk_size, n_y=n_y, impl=impl,
                fault_plan=faults, retry=retry_policy, cache=store,
                lz_profile=lz_profile, elastic=elastic,
            )
            n_exact += n_new
            slab_shape = tuple(
                len(new_vals) if j == k else len(nodes[j])
                for j in range(len(axis_names))
            )
            pos = np.searchsorted(nodes[k], new_vals)
            for f in FIELDS:
                slab = np.asarray(flat[f]).reshape(slab_shape)
                _check_positive({f: slab})
                values[f] = np.insert(values[f], pos, slab, axis=k)
                log_values[f] = np.log10(values[f])
            nodes[k] = np.insert(nodes[k], pos, new_vals)
            added += len(new_vals)
        row["nodes_added"] = added
        rounds.append(row)

    # --- held-out validation: points the refinement never saw, and a
    # LARGER draw than any single round (the recorded number is what a
    # consumer trusts — it must not inherit one round's sampling luck) ---
    n_holdout = max(4 * int(n_probe), 64) if n_holdout is None else int(n_holdout)
    held_cols = _draw_probes(
        spec, n_holdout, np.random.default_rng(seed + 10_000)
    )
    held = np.stack([held_cols[k] for k in axis_names], axis=1)
    exact, q_held = exact_zip(held_cols)
    n_exact += n_holdout
    if q_held.any():
        n_quarantined_probes += int(q_held.sum())
        held = held[~q_held]
        exact = {f: exact[f][~q_held] for f in FIELDS}
        if held.shape[0] == 0:
            raise EmulatorBuildError(
                "every held-out probe was infrastructure-quarantined; "
                "the recorded max_rel_err would be meaningless — fix the "
                "environment and rebuild"
            )
    held_errs = _probe_errors(
        _emulated_fields(nodes, scales, log_values, held), exact
    )
    max_rel_err = float(held_errs.max())
    weighted_max_rel_err = None
    if use_planck or traffic_on:
        w_held = np.ones_like(held_errs)
        if use_planck:
            _w_final, lp_max_final = _posterior_node_weights(log_values)
            w_held = w_held * _posterior_probe_weights(exact, lp_max_final)
        if traffic_on:
            _, w_cell_final = _traffic_node_weights(nodes, traffic_locs)
            w_held = w_held * _traffic_probe_weights(
                nodes, held, w_cell_final
            )
        weighted_max_rel_err = float((held_errs * w_held).max())
    if not converged:
        msg = (
            f"emulator refinement exhausted {max_rounds} rounds with "
            f"held-out max rel err {max_rel_err:.3e} vs target {rtol:.1e}"
        )
        if weighted_max_rel_err is not None:
            msg += f" (weighted: {weighted_max_rel_err:.3e})"
        if require_converged:
            raise EmulatorBuildError(msg)
        print(f"[emulator] WARNING: {msg}", file=sys.stderr)

    # the per-cell a-posteriori error grid the refinement steered on —
    # persisted (and content-hashed) so the serving layer can gate exact
    # fallback on PREDICTED error instead of only on domain membership
    predicted = cell_error_estimates(log_values, nodes, scales)

    seconds = time.time() - t0
    report = BuildReport(
        rounds=rounds,
        converged=converged,
        max_rel_err=max_rel_err,
        rtol=float(rtol),
        n_exact_evals=int(n_exact),
        build_seconds=round(seconds, 3),
        axis_nodes={k: len(a) for k, a in zip(axis_names, nodes)},
        quarantined_probes=int(n_quarantined_probes),
        posterior_weight=pw,
        weighted_max_rel_err=weighted_max_rel_err,
        refine_signal=rs,
        n_grad_evals=int(n_grad_evals),
    )
    manifest = {
        "rtol_target": float(rtol),
        "max_rel_err": max_rel_err,
        "converged": bool(converged),
        "refinement_rounds": len(rounds),
        "build_seconds": report.build_seconds,
        "n_exact_evals": report.n_exact_evals,
        "quarantined_probes": int(n_quarantined_probes),
        "max_cell_est": float(predicted.max(initial=0.0)),
        "axis_scales": {k: spec[k].scale for k in axis_names},
        "domain": {
            k: [float(a[0]), float(a[-1])]
            for k, a in zip(axis_names, nodes)
        },
    }
    if pw is not None:
        manifest["posterior_weight"] = pw
        manifest["weighted_max_rel_err"] = weighted_max_rel_err
    if rs is not None:
        manifest["refine_signal"] = rs
        manifest["n_grad_evals"] = int(n_grad_evals)
    if traffic_fp is not None:
        manifest["traffic_fingerprint"] = traffic_fp
        manifest["traffic_queries"] = int(traffic_locs.shape[0])
    artifact = EmulatorArtifact(
        axis_names=tuple(axis_names),
        axis_nodes=tuple(nodes),
        axis_scales=tuple(scales),
        values=values,
        identity=build_identity(
            base, static, n_y, impl, posterior_weight=pw,
            lz_profile_fp=lz_fp, refine_signal=rs, bounce_fp=bounce_fp,
            traffic_fp=traffic_fp,
        ),
        manifest=manifest,
        predicted_error=predicted,
    )
    if event_log is not None:
        event_log.emit(
            "emulator_build_done", converged=bool(converged),
            max_rel_err=max_rel_err, n_exact_evals=n_exact,
            quarantined_probes=int(n_quarantined_probes),
            seconds=report.build_seconds,
            grid_shape=list(grid_shape()),
        )
    if out_dir is not None:
        save_artifact(out_dir, artifact)
    return artifact, report


def _check_positive(values: Mapping[str, np.ndarray]) -> None:
    """Loud rejection at build time — same contract the loader enforces."""
    for f, v in values.items():
        v = np.asarray(v)
        if not np.all(np.isfinite(v)):
            raise EmulatorBuildError(
                f"exact pipeline produced non-finite {f} inside the box"
            )
        if not np.all(v > 0.0):
            raise EmulatorBuildError(
                f"exact pipeline produced non-positive {f} inside the box; "
                "the log-space emulator needs strictly positive fields — "
                "shrink the box"
            )
