"""Yield-surface emulator: error-controlled tensor-grid surrogate of the
full exact pipeline, built by driving the production sweep engine and
served through a jitted log-space interpolation kernel (microsecond
queries vs milliseconds-to-seconds per exact point).  See
ARCHITECTURE.md "Emulator + serving layer" for the artifact format and
staleness rules."""
from bdlz_tpu.emulator.artifact import (  # noqa: F401
    FIELDS,
    SCHEMA_VERSION,
    EmulatorArtifact,
    EmulatorArtifactError,
    artifact_hash,
    build_identity,
    check_identity,
    load_artifact,
    save_artifact,
)
from bdlz_tpu.emulator.build import (  # noqa: F401
    AxisSpec,
    BuildReport,
    EmulatorBuildError,
    build_emulator,
    cell_error_estimates,
    make_exact_evaluator,
)
from bdlz_tpu.emulator.grid import (  # noqa: F401
    artifact_hull,
    domain_artifacts,
    domain_error_table,
    error_floor,
    has_error_grid,
    in_domain_one,
    interp_log_fields,
    make_domain_fn,
    make_error_fn,
    make_query_fn,
    predicted_error_one,
    select_domains,
)
from bdlz_tpu.emulator.multidomain import (  # noqa: F401
    MULTI_SCHEMA_VERSION,
    MultiDomainArtifact,
    MultiDomainBuildError,
    MultiDomainBuildReport,
    build_seam_split_emulator,
    load_any_artifact,
    load_multidomain_artifact,
    save_multidomain_artifact,
    seam_band_for_box,
)
