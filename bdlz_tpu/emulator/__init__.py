"""Yield-surface emulator: error-controlled tensor-grid surrogate of the
full exact pipeline, built by driving the production sweep engine and
served through a jitted log-space interpolation kernel (microsecond
queries vs milliseconds-to-seconds per exact point).  See
ARCHITECTURE.md "Emulator + serving layer" for the artifact format and
staleness rules."""
from bdlz_tpu.emulator.artifact import (  # noqa: F401
    FIELDS,
    SCHEMA_VERSION,
    EmulatorArtifact,
    EmulatorArtifactError,
    artifact_hash,
    build_identity,
    check_identity,
    load_artifact,
    save_artifact,
)
from bdlz_tpu.emulator.build import (  # noqa: F401
    AxisSpec,
    BuildReport,
    EmulatorBuildError,
    build_emulator,
    make_exact_evaluator,
)
from bdlz_tpu.emulator.grid import (  # noqa: F401
    in_domain_one,
    interp_log_fields,
    make_domain_fn,
    make_query_fn,
)
