"""Execution-backend selection (the `"backend"` config key).

The physics layer (:mod:`bdlz_tpu.physics`) is written against an abstract
array namespace ``xp`` so the same formulas run on

* ``numpy`` — the bit-reproducible CPU reference path (golden outputs of
  the archived run, reference `first_principles_yields.py` via run.txt), and
* ``jax.numpy`` — the jitted / vmapped / mesh-sharded TPU path.

JAX is imported lazily so that pure-NumPy usage never pays JAX start-up, and
so tests can set ``XLA_FLAGS`` / ``JAX_PLATFORMS`` before first import.

The TPU path runs in float64 (``jax_enable_x64``): the north-star accuracy
contract is <=1e-6 relative error on Omega_b/Omega_DM versus the SciPy
reference, and the quadrature reductions (8000-point y-grid x 1200-point
z-grid trapezoids) need f64 accumulation to hold that with margin.
"""
from __future__ import annotations

from typing import Any

_JAX_BACKENDS = ("jax", "tpu", "gpu", "cpu-jax")
_NUMPY_BACKENDS = ("numpy", "reference", "cpu")

VALID_BACKENDS = _NUMPY_BACKENDS + _JAX_BACKENDS


def is_jax_backend(backend: str) -> bool:
    b = str(backend).lower()
    if b in _JAX_BACKENDS:
        return True
    if b in _NUMPY_BACKENDS:
        return False
    raise ValueError(
        f"Unknown backend {backend!r}; expected one of {VALID_BACKENDS}"
    )


def get_namespace(backend: str) -> Any:
    """Return the array namespace (``numpy`` or ``jax.numpy``) for a backend."""
    if is_jax_backend(backend):
        return jax_numpy()
    import numpy

    return numpy


def ensure_x64() -> None:
    """Enable float64 on the JAX path (idempotent).

    The single sanctioned home for this config write: bdlz-lint rule R5
    pins ``jax.config.update`` to this module (and tests/conftest.py), so
    modules that need the x64 contract call this instead of touching the
    global config themselves.
    """
    import jax

    jax.config.update("jax_enable_x64", True)


def set_debug_nans(enable: bool = True) -> None:
    """Toggle ``jax_debug_nans`` — any NaN produced under jit raises.

    The runtime half of the sanitizer layer (:mod:`bdlz_tpu.sanitize`)
    and the sweep CLI's ``--debug-nans`` both route through here so the
    global-config write stays inside the R5 allowlist.
    """
    import jax

    jax.config.update("jax_debug_nans", bool(enable))


def jax_numpy() -> Any:
    """Import and return ``jax.numpy`` with float64 enabled.

    Probes the accelerator relay first: with the axon plugin registered
    and its relay dead, the first backend touch hangs forever — pin host
    CPU instead (the f64 CPU path satisfies the same accuracy contract).
    """
    from bdlz_tpu.utils.platform import ensure_live_backend

    ensure_live_backend("backend")

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    return jnp
