"""Planck comparison and the settling-factor diagnostic (paper §7).

The reference's technical note defines two derived diagnostics on top of
the raw pipeline outputs (PDF Eqs. 22-24; the script itself stops at the
raw ratio, `first_principles_yields.py:419-422`):

* the **settling factor**
  ``f_settle = (rho_DM/rho_b)_Planck / (rho_DM/rho_b)_raw`` — an O(1)
  number quantifying the gap between the minimal LZ estimator and the
  Planck target ratio ~5.357 (archived benchmark: 0.94168);
* the **effective conversion probability**
  ``P_eff = P_chi_to_B / f_settle`` — the P that would settle the raw
  ratio onto Planck, using the benchmark-regime scaling
  (rho_DM/rho_b) ∝ 1/P (archived: ~0.15850; the paper's §8 scaling
  checks and our tests confirm Y_B is linear in P on the fast path).

Both are pure functions of arrays, so they apply equally to a single CLI
run and to sweep outputs (e.g. ranking a million-point grid by
|f_settle - 1| to find Planck-compatible parameter regions).

Numerical note: the paper prints f_settle = 0.94168, but its own displayed
quotient 5.357/5.6889263349 evaluates to 0.9416540 — the printed value
implies an unrounded Planck ratio ~5.3571.  We evaluate the definitions
exactly with the constant :data:`bdlz_tpu.constants.PLANCK_DM_OVER_B`
(= 5.357, the paper's displayed target), giving 0.94165 / 0.15851.
"""
from __future__ import annotations

from typing import Any, Dict

from bdlz_tpu.constants import PLANCK_DM_OVER_B

Array = Any


def _div(a, b):
    """Division matching IEEE array semantics for plain Python scalars too
    (x/0 -> signed inf, 0/0 -> nan) so scalar CLI use and batched sweep use
    behave identically."""
    if hasattr(a, "dtype") or hasattr(b, "dtype"):
        return a / b  # numpy/jax arrays already follow IEEE
    try:
        return a / b
    except ZeroDivisionError:
        if a == 0:
            return float("nan")
        return float("inf") if (a > 0) == (b >= 0) else float("-inf")


def settling_factor(ratio_raw: Array, planck_ratio: float = PLANCK_DM_OVER_B) -> Array:
    """f_settle = (Ω_DM/Ω_b)_Planck / (Ω_DM/Ω_b)_raw  (paper Eq. 23).

    Returns inf for a zero raw ratio (no baryon production at this point);
    NaN propagates.
    """
    return _div(planck_ratio, ratio_raw)


def effective_probability(
    P_chi_to_B: Array, ratio_raw: Array, planck_ratio: float = PLANCK_DM_OVER_B
) -> Array:
    """P_eff = P·(ratio_raw/ratio_Planck) = P / f_settle  (paper Eq. 24)."""
    return P_chi_to_B * ratio_raw / planck_ratio


def planck_comparison(
    dm_over_b: Array,
    P_chi_to_B: Array,
    planck_ratio: float = PLANCK_DM_OVER_B,
) -> Dict[str, Array]:
    """The full §7 diagnostic block for scalar or batched pipeline outputs."""
    f = settling_factor(dm_over_b, planck_ratio)
    return {
        "ratio_raw": dm_over_b,
        "ratio_planck": planck_ratio,
        "f_settle": f,
        "P_eff": effective_probability(P_chi_to_B, dm_over_b, planck_ratio),
    }
