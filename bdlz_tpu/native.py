"""ctypes bindings to the native IO runtime (native/bdlz_io.cpp).

The shared library is built on demand (`make -C native`, g++ only — no
pybind11 in this environment) and cached. Every entry point has a pure
NumPy fallback, so the framework works without a compiler; the native path
is ~40× faster on large bounce-profile CSVs.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np  # host-side use only; jitted paths go through the backend.py xp seam (bdlz-lint R1 audit)

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_PKG_DIR, "lib", "libbdlz_io.so")
_NATIVE_DIR = os.path.normpath(os.path.join(_PKG_DIR, "..", "native"))

_lib: Optional[ctypes.CDLL] = None
_tried_build = False

_ERRORS = {
    -1: "could not open file",
    -2: "empty file or missing header",
    -3: "malformed row (wrong column count or non-numeric cell)",
    -4: "header too long",
    -5: "row count changed between probe and fill",
}


class NativeParseError(ValueError):
    pass


def _load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried_build
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and not _tried_build:
        _tried_build = True
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception:
            return None
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        lib.bdlz_csv_dims.restype = ctypes.c_int
        lib.bdlz_csv_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.bdlz_csv_fill.restype = ctypes.c_int
        lib.bdlz_csv_fill.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_long,
            ctypes.c_int,
        ]
        _lib = lib
    except OSError:
        return None
    return _lib


def native_available() -> bool:
    return _load() is not None


def read_csv_native(path: str) -> Tuple[list, np.ndarray]:
    """(column_names, data[rows, cols]) via the native parser.

    Raises NativeParseError on malformed input, OSError if the library is
    unavailable (callers fall back to NumPy).
    """
    lib = _load()
    if lib is None:
        raise OSError("native IO library unavailable")
    rows = ctypes.c_long()
    cols = ctypes.c_int()
    header = ctypes.create_string_buffer(1 << 15)
    rc = lib.bdlz_csv_dims(path.encode(), ctypes.byref(rows), ctypes.byref(cols),
                           header, len(header))
    if rc != 0:
        raise NativeParseError(f"{path}: {_ERRORS.get(rc, f'error {rc}')}")
    data = np.empty((rows.value, cols.value), dtype=np.float64)
    rc = lib.bdlz_csv_fill(path.encode(), data, rows.value, cols.value)
    if rc != 0:
        raise NativeParseError(f"{path}: {_ERRORS.get(rc, f'error {rc}')}")
    names = [c.strip() for c in header.value.decode().split(",")]
    return names, data
