"""Config system (framework layer L5).

The JSON schema is the reference's 20-key `yields_config_*.json` contract
(`first_principles_yields.py:44-79`, :291-312): a JSON file is merged *over*
the defaults and instantiated into a frozen dataclass, so an unknown key is
a hard error (the reference's implicit strict schema via
``Config(**merged)`` TypeError, :307 — here made explicit with a clearer
message). New framework-only keys are appended with defaults that leave the
reference path byte-identical when absent:

* ``backend``  — "numpy" (bit-reproducible CPU reference) or "tpu"/"jax";
* ``m_B_GeV``  — baryon mass for the present-day conversion (None keeps the
  reference's hard-coded proton mass, :415), enabling the unequal-mass
  (m_DM/m_B ratio) scans of the north star;
* ``n_y``      — quadrature y-grid resolution (reference `main` hard-codes
  8000 at :374).

Known reference quirk, resolved here: ``regime: "auto"`` is documented in
the reference (:50) but crashes its quadrature path with
``UnboundLocalError`` (:376-384 has no else), while its ODE path accepts
it via an else-branch thermal default (:399-400). This framework
reproduces the working ODE-path behavior on the reference backend and
rejects the crashing/strict cases at validation; see ``validate()``.
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from typing import Any, Dict, NamedTuple, Optional

from bdlz_tpu.constants import GEV_TO_KG, M_PROTON_KG

#: Keys understood by the reference pipeline, in its declaration order.
REFERENCE_KEYS = (
    "m_chi_GeV", "g_chi", "chi_stats", "regime", "sigma_v_chi_GeV_m2",
    "T_p_GeV", "beta_over_H", "v_w", "I_p", "g_star", "g_star_s",
    "P_chi_to_B", "source_shape_sigma_y", "Gamma_wash_over_H",
    "incident_flux_scale", "deplete_DM_from_source",
    "T_max_over_Tp", "T_min_over_Tp", "Y_chi_init", "n_chi_at_Tp_GeV3",
)

VALID_REGIMES = ("thermal", "nonthermal")
VALID_STATS = ("fermion", "boson")
#: Stiff-integrator tableaus (must match solvers.sdirk._TABLEAUS; a test
#: asserts the two stay in sync without a config→solver import cycle).
VALID_ODE_METHODS = ("sdirk4", "kvaerno3")


class ConfigError(ValueError):
    """Raised for unknown keys or invalid field values."""


@dataclass(frozen=True)
class Config:
    """One parameter point of the yields pipeline.

    Field names/defaults mirror the reference `Config` dataclass
    (`first_principles_yields.py:44-79`); trailing fields are
    framework-only extensions.
    """

    # Microphysics / DM
    m_chi_GeV: float = 0.95
    g_chi: int = 2
    chi_stats: str = "fermion"
    regime: str = "nonthermal"
    sigma_v_chi_GeV_m2: float = 0.0

    # Transition / percolation inputs
    T_p_GeV: float = 100.0
    beta_over_H: float = 100.0
    v_w: float = 0.30
    I_p: float = 0.34

    # Effective relativistic dof (assumed constant over the window)
    g_star: float = 106.75
    g_star_s: float = 106.75

    # Source normalisation / shape
    P_chi_to_B: Optional[float] = None
    source_shape_sigma_y: float = 15.0
    Gamma_wash_over_H: float = 0.0

    # Incident flux scaling and optional DM depletion
    incident_flux_scale: float = 1.0
    deplete_DM_from_source: bool = False

    # Integration window
    T_max_over_Tp: float = 5.0
    T_min_over_Tp: float = 1e-3

    # Nonthermal initial condition
    Y_chi_init: Optional[float] = 4.90e-10
    n_chi_at_Tp_GeV3: Optional[float] = None

    # ---- framework extensions (absent => reference behavior) ----
    backend: str = "numpy"
    m_B_GeV: Optional[float] = None
    n_y: int = 8000
    # The reference caps Radau steps so hard its ODE path takes >=1e6 steps
    # at defaults (documented hang, SURVEY §2.1). True keeps that behavior
    # for parity; False lets SciPy pick adaptive steps.
    ode_reference_step_cap: bool = True
    # Stiff-integrator tableau on the JAX backend (solvers/sdirk.py):
    # "sdirk4" (4th-order Hairer-Wanner pair, the default) or "kvaerno3".
    ode_method: str = "sdirk4"
    # Stiff-integrator tolerances. Y_B accuracy is atol-bound (the final
    # yield sits below rtol*Y_B for practical rtol) — see the measured
    # accuracy/steps tradeoff table in docs/perf_notes.md.
    ode_rtol: float = 1e-8
    ode_atol: float = 1e-17
    # Stiff-engine acceleration knobs (solvers/batching.py).  None means
    # "engine decides": the repacked batch engine (the sweep default)
    # turns them ON, the bit-pinned lockstep/per-point paths keep them
    # OFF so existing golden results are byte-identical.  Explicit
    # True/False overrides both engines.
    #   ode_auto_h0       — Hairer–Wanner automatic initial-step selection
    #   ode_pi_controller — PI (proportional–integral) step-size control
    #   ode_tabulated_av  — evaluate A/V through the F(y) table instead of
    #                       the per-step (n_z,) z-integral (~2e-11 rel
    #                       shift; requires uniform I_p across the batch)
    ode_auto_h0: Optional[bool] = None
    ode_pi_controller: Optional[bool] = None
    ode_tabulated_av: Optional[bool] = None
    # Spectral panel quadrature for the y-integral (solvers/panels.py):
    # snapped-panel Gauss-Legendre instead of the 8000-node trapezoid,
    # ~14x less integrand work at <=1e-9 agreement on audited
    # populations.  None = "engine decides": run_sweep / the emulator
    # build turn it ON after their per-population convergence audit
    # (validation.panel_gl_population_audit) passes, the bit-pinned
    # per-point paths keep it OFF.  Explicit True/False overrides both
    # (True skips the audit - the caller asserts convergence).
    quad_panel_gl: Optional[bool] = None
    # ---- robustness / fault-injection knobs (bdlz_tpu/faults.py,
    # utils/retry.py; docs/robustness.md) ----
    # Tri-state gate for deterministic fault injection: None = on iff a
    # plan is configured (fault_plan or BDLZ_FAULT_PLAN), False = force
    # off, True = require a plan.  Default: OFF, zero overhead.
    fault_injection: Optional[bool] = None
    # The fault plan itself: JSON text or a path to a JSON file (see
    # faults.FaultPlan); None = no injected faults.
    fault_plan: Optional[str] = None
    # Tri-state self-healing gate (ode_* pattern): None = engine decides
    # (the chunked sweep / serve engines retry-bisect-quarantine, the
    # bit-pinned per-point paths are unaffected), False = raise-through.
    retry_enabled: Optional[bool] = None
    # Bounded-retry budget and base backoff (doubled per retry with
    # deterministic jitter; tests inject a no-op sleep).
    retry_max_attempts: int = 3
    retry_backoff_s: float = 0.05
    # ---- serving-fleet knobs (bdlz_tpu/serve/fleet.py,
    # docs/serving.md) — host-side orchestration like the retry knobs:
    # they change WHERE queries run and what overload sheds, never a
    # served value's bits, so they are excluded from every result
    # identity (SERVE_CONFIG_FIELDS below). ----
    # Device query replicas per process: None = one per local device.
    n_replicas: Optional[int] = None
    # Admission-control bound on the serve queue: submit beyond this
    # many waiting requests is rejected with the typed QueueFull.
    # None = unbounded (the pre-fleet behavior).
    queue_bound: Optional[int] = None
    # ---- replica health plane / circuit breakers (bdlz_tpu/serve/
    # health.py, docs/robustness.md "Replica health plane") — same
    # orchestration-only exclusion rule as the fleet-shape knobs. ----
    # Tri-state gate: None = engine decides (ON for FleetService, the
    # production front; the bare ReplicaSet / YieldService have no
    # replicas to break), False = PR-8 behavior (byte-identical, zero
    # overhead — pinned), True = force on.
    health_enabled: Optional[bool] = None
    # Sliding-window length (per-replica batch outcomes) the breaker
    # scores over; a breaker opens when bad outcomes reach
    # breaker_threshold * breaker_window within the window.
    breaker_window: int = 8
    breaker_threshold: float = 0.5
    # Seconds (service clock) an open breaker cools down before one
    # half-open probe batch is routed to the replica.
    breaker_cooldown_s: float = 1.0
    # Optional per-batch latency SLO: a batch slower than this counts
    # as a bad outcome for its replica (None = latency not scored).
    breaker_latency_slo_s: Optional[float] = None
    # Post-cutover error budget for rollout auto-rollback: the staged
    # artifact is rolled back when more than this fraction of its
    # observed requests are bad (per-request errors, predicted-error
    # gated fallbacks, or requests in latency-SLO-breaching batches)
    # inside the observation window (serve/rollout.py).
    rollback_budget: float = 0.1
    # ---- multi-tenant serving plane (bdlz_tpu/serve/tenancy.py,
    # docs/serving.md "Multi-tenant plane") — same orchestration-only
    # exclusion rule: pools, budgets and autoscaling change WHICH fleet
    # answers and when a pool sheds, never a served value's bits (the
    # tenancy parity tests pin bit-identity vs a single-tenant fleet).
    # Routing policy for tagged requests: None = engine decides
    # ("scenario" when a tenant map is configured, "hash" otherwise),
    # "scenario" = requests must carry a scenario tag resolved through
    # the tenant map, "hash" = requests must carry an artifact hash.
    tenant_routing: Optional[str] = None
    # Device-memory budget across resident pools: None = unbounded,
    # else idle pools are LRU-evicted when the estimated resident
    # artifact bytes exceed the budget (evicted pools answer via the
    # loud degraded exact path, reason "pool_evicted").
    memory_budget_bytes: Optional[int] = None
    # Seconds (service clock) between autoscaler rebalance passes over
    # observed per-pool occupancy/p99.
    autoscale_interval_s: float = 5.0
    # Autoscaler floor: no resident pool is ever scaled below this many
    # replicas (the ceiling is the service's fleet-wide replica budget).
    pool_min_replicas: int = 1
    # ---- closed-loop continuous delivery (bdlz_tpu/refine/,
    # docs/serving.md "Closed loop") — same orchestration-only exclusion
    # rule: the daemon decides WHEN a traffic-specialized artifact is
    # rebuilt and WHICH generation serves, never what any kernel
    # computes (the candidate's own identity carries its traffic
    # fingerprint; unaffected answers are pinned bit-identical). ----
    # Tri-state gate (ode_* pattern): None = engine decides (OFF for a
    # bare service; constructing a RefinementDaemon arms it), False =
    # force off (a daemon refuses to attach), True = force on (the
    # serve CLI arms traffic recording + the daemon loop).
    self_improve: Optional[bool] = None
    # Drift threshold for the refinement daemon: a stats window whose
    # gated_rate OR out-of-domain fallback rate exceeds this fraction
    # flags distribution drift and triggers an autonomous rebuild.
    drift_gated_rate: float = 0.05
    # Budget on autonomous spending: the maximum rebuild+rollout cycles
    # one daemon may launch over its lifetime (each cycle pays a full
    # elastic emulator rebuild).
    rebuild_budget: int = 1
    # ---- provenance / result-cache knobs (bdlz_tpu/provenance/,
    # docs/provenance.md) — orchestration like the serve knobs: caching
    # changes WHERE a result comes from, never its bits (the sweep_cache
    # bench line pins bitwise equality), so both are excluded from every
    # identity (CACHE_CONFIG_FIELDS below). ----
    # Tri-state gate for the content-addressed result cache: None = on
    # iff a root is configured (cache_root or BDLZ_CACHE_ROOT), False =
    # force off, True = on (default root under ~/.cache when unset).
    cache_enabled: Optional[bool] = None
    # Store root for cached sweep chunks / published artifacts; None
    # defers to the BDLZ_CACHE_ROOT env var.
    cache_root: Optional[str] = None
    # ---- emulator seam/gating knobs (bdlz_tpu/emulator/multidomain.py,
    # serve/service.py; docs/perf_notes.md "Seam-split emulator
    # domains") ----
    # Tri-state seam-split gate (ode_* pattern): None = engine decides
    # (build_emulator splits along the T = m/3 flux-seam band iff the
    # box crosses it), True = require the split (a non-crossing box is
    # an error), False = force the legacy single-domain build.
    seam_split: Optional[bool] = None
    # Exact-fallback error gate for the serving layer: None = engine
    # decides (gate at the artifact's recorded rtol_target when it
    # carries per-cell predicted-error estimates), false = gate on
    # domain membership only (the pre-gate behavior), a positive float
    # = gate at that relative tolerance.  Serving-side only: it selects
    # WHICH path answers a query, so it is excluded from every result
    # identity (EMULATOR_CONFIG_FIELDS below).
    error_gate_tol: "Optional[bool | float]" = None
    # Posterior weighting of the emulator build's refinement criterion:
    # None = curvature-only (the legacy build), "planck" = multiply the
    # a-posteriori interval estimates and probe errors by the Planck
    # 2018 likelihood weight of the interim surface, so the build spends
    # exact sweep points where posterior mass concentrates and coarsens
    # dead regions.  Node placement (and therefore the artifact bytes)
    # depends on it, so the RESOLVED value joins the artifact identity
    # as its own key (emulator.artifact.build_identity).
    posterior_weight: Optional[str] = None
    # ---- LZ scenario plane (bdlz_tpu/lz/chain.py, lz/thermal.py;
    # docs/scenarios.md).  The physics scenario the per-point conversion
    # probability is derived under when a bounce profile is supplied:
    #   "two_channel" — the legacy chi/B two-level kernel (lz_method /
    #                   lz_gamma_phi select the estimator, as before);
    #   "chain"       — N-level banded LZ chain (arXiv:1212.2907,
    #                   multi-species dark sectors; lz_n_levels sets N,
    #                   N=2 reduces to the coherent two-channel kernel);
    #   "thermal"     — finite-temperature oscillator-bath dephasing
    #                   (arXiv:1410.0516): Gamma_phi is DERIVED as
    #                   Gamma(T_p, eta, omega_c) instead of being a free
    #                   knob; eta -> 0 or T -> 0 recovers the coherent
    #                   kernel bitwise.
    # Identity rule: the resolved scenario joins sweep/artifact
    # identities as its own "lz_scenario" key (omit-at-default, single
    # home — parallel.sweep.engine_identity_extra and
    # emulator.artifact.build_identity), so these fields are EXCLUDED
    # from the shared config payload (SCENARIO_CONFIG_FIELDS below).
    lz_mode: str = "two_channel"
    lz_n_levels: int = 2
    lz_bath_eta: float = 0.0
    lz_bath_omega_c: float = 0.0
    # ---- MCMC sampler knobs (bdlz_tpu/sampling/nuts.py, mcmc_cli;
    # docs/perf_notes.md "Gradient-based inference").  Which sampler
    # explores the Planck posterior and how NUTS adapts:
    #   sampler       — "stretch" (the affine-invariant default, bit-
    #                   stable against every existing chain) or "nuts"
    #                   (gradient-based No-U-Turn, orders of magnitude
    #                   better ESS per pipeline evaluation);
    #   mass_matrix   — NUTS warmup metric: "diag" variances or "dense"
    #                   covariance (aligns the thin curved Planck ridge);
    #   target_accept — NUTS dual-averaging acceptance target.
    # Identity rule: excluded from the shared config payload
    # (SAMPLER_CONFIG_FIELDS below) — the sampler cannot stale sweep
    # manifests or emulator artifacts it never touches; its single
    # identity home is the MCMC checkpoint identity, which mcmc_cli
    # joins with the RESOLVED sampler (omit-at-default: stretch chains
    # keep their hashes, a sampler flip invalidates resume loudly).
    sampler: str = "stretch"
    mass_matrix: str = "diag"
    target_accept: float = 0.8
    # Emulator refinement signal (emulator/build.py): None = the legacy
    # axis-local |f''| curvature criterion; "fisher" = gradient-aware —
    # exact-pipeline Jacobians at failing probes attribute each error to
    # the axis whose resolution causes it (sampling/grad.py), reaching
    # the same held-out tolerance with fewer exact evaluations.  Node
    # placement depends on it, so its identity home is the artifact's
    # own refine_signal key (build_identity), like posterior_weight.
    refine_signal: Optional[str] = None


def default_config() -> Dict[str, Any]:
    """Defaults as a plain dict (the template payload), reference :291-301."""
    return {f.name: f.default for f in dataclasses.fields(Config)}


def config_from_dict(raw: Dict[str, Any]) -> Config:
    """Merge ``raw`` over the defaults; unknown keys are a hard error."""
    base = default_config()
    unknown = sorted(set(raw) - set(base))
    if unknown:
        raise ConfigError(
            f"Unknown config key(s) {unknown}; valid keys are {sorted(base)}"
        )
    base.update(raw)
    return Config(**base)


def load_config(path: str) -> Config:
    """Load a yields_config JSON file (reference :303-307 semantics)."""
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    return config_from_dict(raw)


def write_template(path: str, include_extensions: bool = False) -> None:
    """Write the default config as a JSON template (reference :309-312).

    The default artifact is exactly the reference's 20-key template
    (`json.dump(default_config(), indent=2)` over :291-301's dict, in
    its declaration order — byte-identical, pinned by a test);
    ``include_extensions=True`` appends the framework keys for users
    opting into the TPU features.
    """
    cfg = default_config()
    if not include_extensions:
        cfg = {k: cfg[k] for k in REFERENCE_KEYS}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(cfg, f, indent=2)
    print(f"Wrote template config to {path}")


#: Extension keys whose RESOLVED values are always part of a resume
#: identity: they change numerical results, so a future change to their
#: *defaults* must also invalidate old checkpoints (omit-at-default
#: would silently splice results computed at two different settings).
#: The tri-state engine knobs (ode_auto_h0/ode_pi_controller/
#: ode_tabulated_av/quad_panel_gl) are NOT listed here because their None default is
#: resolved per-engine — the sweep layer folds the RESOLVED values into
#: its manifest hash instead (run_sweep's esdirk hash_extra), which pins
#: the same invariant without invalidating every non-stiff directory.
RESULT_AFFECTING_EXTENSIONS = ("ode_method", "ode_rtol", "ode_atol")

#: Config fields that must NEVER enter a resume/artifact identity even
#: when non-default: retry/fault handling is host-side orchestration —
#: it cannot change a single output bit (with faults disabled, pinned),
#: and keying it in would stale every sweep manifest / emulator artifact
#: the moment an operator tunes a retry knob or arms a fault plan.
#: The StaticChoices twin is ROBUSTNESS_STATIC_FIELDS below.
ROBUSTNESS_CONFIG_FIELDS = (
    "fault_injection", "fault_plan", "retry_enabled",
    "retry_max_attempts", "retry_backoff_s",
)

#: Serving-fleet knobs with the same exclusion rule: replica count and
#: admission bounds are deployment shape, not physics — a served value
#: is bit-identical at any replica count (pinned by the fleet parity
#: tests), and keying them into identities would stale every artifact
#: whenever an operator resizes the fleet.
SERVE_CONFIG_FIELDS = (
    "n_replicas", "queue_bound",
    # the replica health plane / auto-rollback knobs share the rule:
    # breakers and budgets change WHICH replica (or which artifact
    # generation) answers, never what any kernel computes — the healed
    # re-answer is bit-identical by construction (pinned in
    # tests/test_health.py)
    "health_enabled", "breaker_window", "breaker_threshold",
    "breaker_cooldown_s", "breaker_latency_slo_s", "rollback_budget",
    # the multi-tenant plane knobs (serve/tenancy.py) share the rule:
    # routing, memory budgets and autoscaling pick WHICH pool/fleet
    # answers and when an idle pool is evicted — per-artifact answers
    # stay bit-identical to a single-tenant fleet (pinned in
    # tests/test_tenancy.py), so resizing tenancy stales no identity
    "tenant_routing", "memory_budget_bytes", "autoscale_interval_s",
    "pool_min_replicas",
    # the closed-loop knobs (bdlz_tpu/refine/) share the rule: they
    # gate WHEN the daemon rebuilds and WHICH artifact generation
    # serves — the candidate surface itself self-identifies through
    # its own refine_signal + traffic keys (build_identity), so these
    # orchestration gates must never stale an identity
    "self_improve", "drift_gated_rate", "rebuild_budget",
)

#: Valid values of the ``tenant_routing`` knob (None = engine decides).
VALID_TENANT_ROUTING = ("scenario", "hash")

#: Provenance-cache knobs with the same exclusion rule: a cache hit
#: returns the bytes a cold run would compute (the sweep_cache bench
#: line pins bitwise equality), so where results are cached can never
#: join what identifies them — keying these in would also stale every
#: artifact the moment an operator pointed the cache at a new disk.
CACHE_CONFIG_FIELDS = ("cache_enabled", "cache_root")

#: Emulator seam/gating knobs, excluded from the CONFIG identity payload
#: deliberately (pinned in tests/test_config.py):
#: * ``seam_split`` — build orchestration: it changes the artifact's
#:   STRUCTURE (one surface vs a stitched bundle), and every structure
#:   self-identifies through its own content hash — keying the knob into
#:   config identities would stale sweep manifests it cannot affect;
#: * ``error_gate_tol`` — pure serving policy: it selects WHICH path
#:   answers a query (emulator vs exact), never what either path
#:   computes, exactly like the fleet-shape knobs above;
#: * ``posterior_weight`` — DOES affect artifact bytes (node placement),
#:   but its single identity home is the artifact's own
#:   ``posterior_weight`` key (``emulator.artifact.build_identity``),
#:   mirroring ``quad_panel_gl`` — folding it into the shared config
#:   payload would also stale sweep/MCMC identities it cannot touch.
#: ``refine_signal`` rides this list for the same single-home reason as
#: ``posterior_weight``: it changes artifact BYTES (node placement) but
#: its identity home is the artifact's own ``refine_signal`` key.
EMULATOR_CONFIG_FIELDS = (
    "seam_split", "error_gate_tol", "posterior_weight", "refine_signal",
)

#: Valid values of the ``posterior_weight`` knob (None = off).
VALID_POSTERIOR_WEIGHTS = ("planck",)

#: Valid values of the ``refine_signal`` knob (None = legacy curvature).
#: ``"traffic"`` weights the refinement criterion by the observed query
#: density of a served traffic snapshot (bdlz_tpu/refine/traffic.py);
#: ``"traffic*planck"`` multiplies that by the Planck posterior weight —
#: the closed-loop daemon's default product signal.  Both need a
#: snapshot passed to ``build_emulator(traffic=...)`` and stamp its
#: fingerprint on the artifact identity (``traffic`` key).
VALID_REFINE_SIGNALS = ("fisher", "traffic", "traffic*planck")

#: Valid MCMC samplers (mcmc_cli / sampling layer).
VALID_SAMPLERS = ("stretch", "nuts")
VALID_MASS_MATRICES = ("diag", "dense")

#: MCMC sampler knobs, excluded from the shared config identity payload
#: deliberately (pinned in tests/test_config.py): the sampler explores a
#: posterior — it cannot change what a sweep computes or what an
#: emulator artifact contains, so folding it into config identities
#: would stale manifests/artifacts it never touches.  Its single
#: identity home is the MCMC checkpoint identity: ``mcmc_cli`` passes
#: the RESOLVED sampler spec to ``provenance.mcmc_segment_identity``
#: (omit-at-default — every existing stretch chain keeps its hash, and
#: flipping the sampler invalidates resume loudly, the PR-7 pattern).
SAMPLER_CONFIG_FIELDS = ("sampler", "mass_matrix", "target_accept")

#: Valid LZ scenario modes (docs/scenarios.md).
VALID_LZ_MODES = ("two_channel", "chain", "thermal")

#: LZ scenario knobs, excluded from the shared config identity payload
#: deliberately (pinned in tests/test_scenarios.py): like
#: ``posterior_weight``, the resolved scenario has ONE identity home —
#: the ``lz_scenario`` key that ``parallel.sweep.engine_identity_extra``
#: folds into sweep manifest/chunk identities and
#: ``emulator.artifact.build_identity`` stamps on artifacts
#: (omit-at-default, so every pre-existing two-channel hash is
#: untouched).  Folding them into the config payload too would stale
#: MCMC checkpoints and refcache entries the scenario cannot affect
#: (the per-point P already enters those through the grid bytes).
SCENARIO_CONFIG_FIELDS = (
    "lz_mode", "lz_n_levels", "lz_bath_eta", "lz_bath_omega_c",
)

#: R9 validation allowlist (bdlz-lint): fields ``validate()`` takes
#: as-given, on purpose.  These are the reference-physics inputs the
#: reference implementation trusts verbatim — any float is a legal
#: model point (an MCMC walker may legitimately propose extreme masses,
#: couplings or temperatures, and clamping them here would bias the
#: posterior), the booleans/enums among them are exercised structurally
#: (``deplete_DM_from_source`` routes the engine via
#: ``needs_ode_path``; ``chi_stats`` selects the occupancy kernel and
#: any unknown value fails loudly at kernel dispatch), and
#: ``ode_reference_step_cap`` mirrors the reference's unchecked cap.
#: Everything NOT listed here must be checked in ``validate()`` — the
#: linter (rule R9) enforces the exact partition, both directions: an
#: unlisted unchecked field is a finding, and so is a listed field
#: that ``validate()`` later grows a check for (stale exemption).
VALIDATION_EXEMPT_FIELDS = (
    "m_chi_GeV",
    "g_chi",
    "chi_stats",
    "sigma_v_chi_GeV_m2",
    "T_p_GeV",
    "beta_over_H",
    "v_w",
    "I_p",
    "g_star",
    "g_star_s",
    "P_chi_to_B",
    "source_shape_sigma_y",
    "Gamma_wash_over_H",
    "incident_flux_scale",
    "deplete_DM_from_source",
    "T_max_over_Tp",
    "T_min_over_Tp",
    "Y_chi_init",
    "n_chi_at_Tp_GeV3",
    "m_B_GeV",
    "ode_reference_step_cap",
)


def config_identity_dict(cfg: Config) -> Dict[str, Any]:
    """The config as a resume-identity payload.

    Reference keys always; result-affecting extension knobs always at
    their RESOLVED values (see ``RESULT_AFFECTING_EXTENSIONS``); the
    remaining extension keys only when they differ from their defaults.
    Used by the sweep-manifest hash and the MCMC checkpoint identity.
    The omit-at-default filtering keeps checkpoints forward-compatible
    — adding a new extension field must not invalidate every
    pre-existing sweep/chain directory — while the resolved-value
    pinning makes sure changing a default that alters results does.
    """
    defaults = default_config()
    out: Dict[str, Any] = {k: getattr(cfg, k) for k in REFERENCE_KEYS}
    for k in defaults:
        if (
            k in REFERENCE_KEYS
            or k in ROBUSTNESS_CONFIG_FIELDS
            or k in SERVE_CONFIG_FIELDS
            or k in CACHE_CONFIG_FIELDS
            or k in EMULATOR_CONFIG_FIELDS
            or k in SCENARIO_CONFIG_FIELDS
            or k in SAMPLER_CONFIG_FIELDS
        ):
            continue
        if k in RESULT_AFFECTING_EXTENSIONS or getattr(cfg, k) != defaults[k]:
            out[k] = getattr(cfg, k)
    return out


def needs_ode_path(cfg: Config) -> bool:
    """True when the fast quadrature is invalid and the ODE path runs.

    THE single definition of the reference's ``can_quad`` guard (:372),
    negated — ``cli.can_use_quadrature``, ``validate``'s regime admission,
    and the sweep engine's ESDIRK routing must all agree on this predicate
    or an admitted ``regime:"auto"`` config could route to the quadrature
    path, which has no unknown-regime fallback.
    """
    return (
        cfg.deplete_DM_from_source
        or cfg.sigma_v_chi_GeV_m2 != 0.0
        or cfg.Gamma_wash_over_H != 0.0
    )


def validate(cfg: Config, backend: Optional[str] = None) -> Config:
    """Check field values that the reference either trusts or crashes on.

    ``regime`` handling follows the reference exactly where the reference
    *works*, and rejects-at-validation where it *crashes* (SURVEY §2.1
    quirks list):

    * the reference's quadrature path dies with ``UnboundLocalError`` on
      any regime that is neither thermal nor nonthermal (:376-384 has no
      else branch) — we reject those configs up-front instead of crashing
      mid-run;
    * its ODE path has an else branch that silently falls back to the
      thermal initial condition (:399-400) — on the reference (numpy)
      backend with the ODE path active, an unknown regime such as
      ``"auto"`` is therefore *accepted* and reproduces that thermal
      default (see ``cli.run_point``);
    * the TPU backend is strict on every path: unknown regimes are
      rejected, documented here.

    ``backend`` is the *effective* backend (CLI override included);
    defaults to the config's own key.
    """
    from bdlz_tpu.backend import is_jax_backend

    r = cfg.regime.lower()
    if not (r.startswith("therm") or r.startswith("non")):
        backend = cfg.backend if backend is None else backend
        if is_jax_backend(backend) or not needs_ode_path(cfg):
            raise ConfigError(
                f"regime={cfg.regime!r} is not supported here: use 'thermal' "
                "or 'nonthermal'. (The reference's quadrature path crashes on "
                "it — rejected up-front; its ODE path treats it as the "
                "thermal default, which this framework reproduces only on "
                "the reference backend.)"
            )
    # chi_stats follows the reference convention deliberately: any string
    # not starting with "ferm" is treated as a boson (reference :96).
    if cfg.n_y < 2:
        raise ConfigError("n_y must be >= 2")
    if cfg.ode_method not in VALID_ODE_METHODS:
        raise ConfigError(
            f"ode_method={cfg.ode_method!r} is not one of {VALID_ODE_METHODS}"
        )
    if not (cfg.ode_rtol > 0.0 and cfg.ode_atol > 0.0):
        raise ConfigError("ode_rtol and ode_atol must be positive")
    for k in ("ode_auto_h0", "ode_pi_controller", "ode_tabulated_av",
              "quad_panel_gl", "fault_injection", "retry_enabled",
              "cache_enabled", "seam_split", "health_enabled",
              "self_improve"):
        v = getattr(cfg, k)
        if v is not None and not isinstance(v, bool):
            raise ConfigError(f"{k} must be true, false, or null, got {v!r}")
    egt = cfg.error_gate_tol
    if egt is not None:
        if egt is True:
            raise ConfigError(
                "error_gate_tol=true is ambiguous: use null for the "
                "artifact's recorded rtol_target, false to disable the "
                "gate, or a positive tolerance"
            )
        if egt is not False and not (
            isinstance(egt, (int, float)) and float(egt) > 0.0
        ):
            raise ConfigError(
                f"error_gate_tol must be null, false, or a positive "
                f"relative tolerance, got {egt!r}"
            )
    if cfg.posterior_weight is not None and (
        cfg.posterior_weight not in VALID_POSTERIOR_WEIGHTS
    ):
        raise ConfigError(
            f"posterior_weight={cfg.posterior_weight!r} is not one of "
            f"{VALID_POSTERIOR_WEIGHTS} (or null)"
        )
    if cfg.refine_signal is not None and (
        cfg.refine_signal not in VALID_REFINE_SIGNALS
    ):
        raise ConfigError(
            f"refine_signal={cfg.refine_signal!r} is not one of "
            f"{VALID_REFINE_SIGNALS} (or null = curvature)"
        )
    if cfg.sampler not in VALID_SAMPLERS:
        raise ConfigError(
            f"sampler={cfg.sampler!r} is not one of {VALID_SAMPLERS}"
        )
    if cfg.mass_matrix not in VALID_MASS_MATRICES:
        raise ConfigError(
            f"mass_matrix={cfg.mass_matrix!r} is not one of "
            f"{VALID_MASS_MATRICES}"
        )
    if not (0.0 < float(cfg.target_accept) < 1.0):
        raise ConfigError(
            f"target_accept must be a fraction in (0, 1), got "
            f"{cfg.target_accept!r}"
        )
    if cfg.retry_max_attempts < 1:
        raise ConfigError("retry_max_attempts must be >= 1")
    if cfg.retry_backoff_s < 0.0:
        raise ConfigError("retry_backoff_s must be >= 0")
    if cfg.fault_plan is not None and not isinstance(cfg.fault_plan, str):
        raise ConfigError(
            f"fault_plan must be JSON text or a file path, got "
            f"{cfg.fault_plan!r}"
        )
    if cfg.n_replicas is not None and cfg.n_replicas < 1:
        raise ConfigError("n_replicas must be >= 1 (or null = all devices)")
    if cfg.queue_bound is not None and cfg.queue_bound < 1:
        raise ConfigError("queue_bound must be >= 1 (or null = unbounded)")
    if cfg.breaker_window < 1:
        raise ConfigError("breaker_window must be >= 1")
    if not (0.0 < cfg.breaker_threshold <= 1.0):
        raise ConfigError(
            f"breaker_threshold must be a fraction in (0, 1], got "
            f"{cfg.breaker_threshold!r}"
        )
    if not cfg.breaker_cooldown_s > 0.0:
        raise ConfigError("breaker_cooldown_s must be > 0")
    if cfg.breaker_latency_slo_s is not None and (
        not float(cfg.breaker_latency_slo_s) > 0.0
    ):
        raise ConfigError(
            "breaker_latency_slo_s must be > 0 (or null = latency not "
            "scored)"
        )
    if not (0.0 < cfg.rollback_budget <= 1.0):
        raise ConfigError(
            f"rollback_budget must be a fraction in (0, 1], got "
            f"{cfg.rollback_budget!r}"
        )
    if not (0.0 < cfg.drift_gated_rate <= 1.0):
        raise ConfigError(
            f"drift_gated_rate must be a fraction in (0, 1], got "
            f"{cfg.drift_gated_rate!r}"
        )
    if not (isinstance(cfg.rebuild_budget, int) and cfg.rebuild_budget >= 1):
        raise ConfigError(
            f"rebuild_budget must be an integer >= 1 (autonomous "
            f"rebuild+rollout cycles), got {cfg.rebuild_budget!r}"
        )
    if cfg.tenant_routing is not None and (
        cfg.tenant_routing not in VALID_TENANT_ROUTING
    ):
        raise ConfigError(
            f"tenant_routing={cfg.tenant_routing!r} is not one of "
            f"{VALID_TENANT_ROUTING} (or null = engine decides)"
        )
    if cfg.memory_budget_bytes is not None and cfg.memory_budget_bytes < 1:
        raise ConfigError(
            "memory_budget_bytes must be >= 1 (or null = unbounded)"
        )
    if not cfg.autoscale_interval_s > 0.0:
        raise ConfigError("autoscale_interval_s must be > 0")
    if cfg.pool_min_replicas < 1:
        raise ConfigError("pool_min_replicas must be >= 1")
    if cfg.cache_root is not None and not isinstance(cfg.cache_root, str):
        raise ConfigError(
            f"cache_root must be a directory path or null, got "
            f"{cfg.cache_root!r}"
        )
    # LZ scenario plane (docs/scenarios.md): the same "a knob the mode
    # would silently ignore is a caller error" rule as gamma_phi.
    if cfg.lz_mode not in VALID_LZ_MODES:
        raise ConfigError(
            f"lz_mode={cfg.lz_mode!r} is not one of {VALID_LZ_MODES}"
        )
    if not (isinstance(cfg.lz_n_levels, int) and cfg.lz_n_levels >= 2):
        raise ConfigError(
            f"lz_n_levels must be an integer >= 2, got {cfg.lz_n_levels!r}"
        )
    if cfg.lz_n_levels != 2 and cfg.lz_mode != "chain":
        raise ConfigError(
            f"lz_n_levels={cfg.lz_n_levels} has no effect with "
            f"lz_mode={cfg.lz_mode!r} (it parameterizes the N-level chain)"
        )
    if cfg.lz_bath_eta < 0.0 or cfg.lz_bath_omega_c < 0.0:
        raise ConfigError(
            "lz_bath_eta and lz_bath_omega_c must be >= 0 (Ohmic bath "
            "coupling and cutoff)"
        )
    if (cfg.lz_bath_eta or cfg.lz_bath_omega_c) and cfg.lz_mode != "thermal":
        raise ConfigError(
            f"lz_bath_eta/lz_bath_omega_c have no effect with "
            f"lz_mode={cfg.lz_mode!r} (they parameterize the thermal bath)"
        )
    if cfg.lz_mode == "thermal" and cfg.lz_bath_eta > 0.0 and (
        not cfg.lz_bath_omega_c > 0.0
    ):
        raise ConfigError(
            "lz_mode='thermal' with lz_bath_eta > 0 needs a positive "
            "lz_bath_omega_c cutoff (Gamma_phi = 2 eta T (1 - e^(-omega_c/T)) "
            "is identically 0 without one — set lz_bath_eta: 0 for the "
            "coherent limit instead)"
        )
    return cfg


class PointParams(NamedTuple):
    """Dynamic (sweepable, traceable) per-point parameters.

    Everything in here may be a scalar or a batched array under ``vmap``;
    categorical/structural choices (``chi_stats``, ``regime``, grid sizes)
    stay static and live outside, in :class:`StaticChoices`.
    """

    m_chi_GeV: Any
    g_chi: Any
    T_p_GeV: Any
    beta_over_H: Any
    v_w: Any
    I_p: Any
    g_star: Any
    g_star_s: Any
    P: Any
    sigma_y: Any
    flux_scale: Any
    Y_chi_init: Any
    m_B_kg: Any
    T_max_over_Tp: Any
    T_min_over_Tp: Any
    sigma_v: Any
    Gamma_wash_over_H: Any


class StaticChoices(NamedTuple):
    """Trace-static structural choices of a run."""

    chi_stats: str = "fermion"
    regime: str = "nonthermal"
    deplete_DM_from_source: bool = False
    n_y: int = 8000
    ode_method: str = "sdirk4"
    ode_rtol: float = 1e-8
    ode_atol: float = 1e-17
    # None = per-engine default (see Config): lockstep/per-point paths
    # resolve None -> False (bit-pinned), the repacked batch engine
    # resolves None -> True.
    ode_auto_h0: Optional[bool] = None
    ode_pi_controller: Optional[bool] = None
    ode_tabulated_av: Optional[bool] = None
    # None = per-engine default: the audited sweep/emulator paths resolve
    # it (see Config.quad_panel_gl); bit-pinned paths resolve None -> off.
    quad_panel_gl: Optional[bool] = None
    # Robustness knobs (see Config): orchestration-only — they change
    # failure handling, never numerics, so they are EXCLUDED from every
    # result identity (ROBUSTNESS_STATIC_FIELDS).
    retry_enabled: Optional[bool] = None
    fault_injection: Optional[bool] = None
    # LZ scenario plane (see Config.lz_mode): trace-static — the mode
    # selects which propagation kernel derives P, n_levels fixes array
    # shapes.  Excluded from the positional static identity payload
    # (SCENARIO_STATIC_FIELDS): the resolved scenario's single identity
    # home is the omit-at-default "lz_scenario" key (docs/scenarios.md),
    # which keeps every pre-existing two-channel hash byte-stable.
    lz_mode: str = "two_channel"
    lz_n_levels: int = 2
    lz_bath_eta: float = 0.0
    lz_bath_omega_c: float = 0.0


#: StaticChoices fields that must NOT enter result identities (emulator
#: artifact hashes, refcache keys): retry/fault handling is host-side
#: orchestration — with faults disabled it cannot change a single output
#: bit, and folding it in would gratuitously invalidate every
#: pre-existing artifact.
ROBUSTNESS_STATIC_FIELDS = ("retry_enabled", "fault_injection")

#: StaticChoices twins of SCENARIO_CONFIG_FIELDS, excluded from the
#: positional static payload for the same single-home reason (the
#: scenario key carries them; appending their values to the positional
#: list would churn every legacy refcache/artifact/chunk hash).
SCENARIO_STATIC_FIELDS = (
    "lz_mode", "lz_n_levels", "lz_bath_eta", "lz_bath_omega_c",
)


def resolve_Y_chi_init(cfg: Config) -> float:
    """Nonthermal initial-yield policy (reference :378-384 / :392-398).

    Y_chi_init if set, else n_chi(T_p)/s(T_p), else 1e-12. For the thermal
    regime the value is unused (the pipeline computes n_eq(T_hi)/s(T_hi)).
    """
    if cfg.Y_chi_init is not None:
        return float(cfg.Y_chi_init)
    if cfg.n_chi_at_Tp_GeV3 is not None:
        from bdlz_tpu.physics.thermo import entropy_density
        import numpy as np  # host-side helper (bdlz-lint R1 audit)

        s_p = entropy_density(cfg.T_p_GeV, cfg.g_star_s, np)
        return float(cfg.n_chi_at_Tp_GeV3) / max(s_p, 1e-300)
    return 1.0e-12


def point_params_from_config(cfg: Config, P: float) -> PointParams:
    """Bind a Config + resolved LZ probability into the dynamic parameter tuple."""
    m_B_kg = M_PROTON_KG if cfg.m_B_GeV is None else float(cfg.m_B_GeV) * GEV_TO_KG
    return PointParams(
        m_chi_GeV=float(cfg.m_chi_GeV),
        g_chi=float(cfg.g_chi),
        T_p_GeV=float(cfg.T_p_GeV),
        beta_over_H=float(cfg.beta_over_H),
        v_w=float(cfg.v_w),
        I_p=float(cfg.I_p),
        g_star=float(cfg.g_star),
        g_star_s=float(cfg.g_star_s),
        P=float(P),
        sigma_y=float(cfg.source_shape_sigma_y),
        flux_scale=float(cfg.incident_flux_scale),
        Y_chi_init=resolve_Y_chi_init(cfg),
        m_B_kg=m_B_kg,
        T_max_over_Tp=float(cfg.T_max_over_Tp),
        T_min_over_Tp=float(cfg.T_min_over_Tp),
        sigma_v=float(cfg.sigma_v_chi_GeV_m2),
        Gamma_wash_over_H=float(cfg.Gamma_wash_over_H),
    )


def static_choices_from_config(cfg: Config) -> StaticChoices:
    return StaticChoices(
        chi_stats=cfg.chi_stats,
        regime=cfg.regime,
        deplete_DM_from_source=bool(cfg.deplete_DM_from_source),
        n_y=int(cfg.n_y),
        ode_method=cfg.ode_method,
        ode_rtol=float(cfg.ode_rtol),
        ode_atol=float(cfg.ode_atol),
        ode_auto_h0=cfg.ode_auto_h0,
        ode_pi_controller=cfg.ode_pi_controller,
        ode_tabulated_av=cfg.ode_tabulated_av,
        quad_panel_gl=cfg.quad_panel_gl,
        retry_enabled=cfg.retry_enabled,
        fault_injection=cfg.fault_injection,
        lz_mode=cfg.lz_mode,
        lz_n_levels=int(cfg.lz_n_levels),
        lz_bath_eta=float(cfg.lz_bath_eta),
        lz_bath_omega_c=float(cfg.lz_bath_omega_c),
    )
