"""bdlz-lint — JAX-aware static analysis for the repo's contracts.

The package must stay bit-reproducible on the NumPy backend while being
jit/pjit-safe on the TPU path, and the regressions that break that are
silent: host ``np.`` calls leaking into jitted code, Python branches on
tracers, host syncs in hot paths, magic-number drift in the physics
layer, stray global JAX config writes, and jitted entry points missing
their static/donate declarations — the per-file rules R1–R7.  On top of
those, the KNOB CONTRACT that keeps result identities honest is policed
whole-program (cross-file symbol table, :mod:`bdlz_tpu.lint.contracts`):
identity-home coverage for every ``Config`` field (R8, the PR-7
``quad_panel_gl`` silent-resume drift class), validation coverage (R9),
tri-state resolver conformance (R10), CLI↔config parity (R11), and the
jit-in-a-loop retrace hazard (R12).  Everything is stdlib ``ast`` — no
third-party dependencies — with per-line suppression
(``# bdlz-lint: disable=R4``), stale-suppression detection, a JSON mode
and a SARIF 2.1.0 mode for tooling, and a content-hash-keyed run cache
through the provenance store:

    python -m bdlz_tpu.lint bdlz_tpu/ --format json
    python -m bdlz_tpu.lint --changed-only          # pre-commit path
    python -m bdlz_tpu.lint --format sarif > lint.sarif

Tier-1 pins ``bdlz_tpu/`` at zero unsuppressed findings and zero stale
suppressions (``tests/test_lint.py``); the runtime counterpart of this
static pass is the ``--sanitize`` flag on the CLIs
(:mod:`bdlz_tpu.sanitize`).  Rule table: docs/static_analysis.md.
"""
from bdlz_tpu.lint.analyzer import (  # noqa: F401
    LintReport,
    StaleSuppression,
    lint_paths,
    lint_source,
)
from bdlz_tpu.lint.rules import RULES, Finding, Rule  # noqa: F401
