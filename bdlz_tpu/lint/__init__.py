"""bdlz-lint — JAX-aware static analysis for the dual-backend contract.

The package must stay bit-reproducible on the NumPy backend while being
jit/pjit-safe on the TPU path, and the regressions that break that are
silent: host ``np.`` calls leaking into jitted code, Python branches on
tracers, host syncs in hot paths, magic-number drift in the physics
layer, stray global JAX config writes, and jitted entry points missing
their static/donate declarations. This package turns each class into a
lintable rule (R1–R6, see :mod:`bdlz_tpu.lint.rules`) over stdlib
``ast`` — no third-party dependencies — with per-line suppression
(``# bdlz-lint: disable=R4``) and a JSON mode for tooling:

    python -m bdlz_tpu.lint bdlz_tpu/ --format json

Tier-1 pins ``bdlz_tpu/`` at zero unsuppressed findings
(``tests/test_lint.py``); the runtime counterpart of this static pass is
the ``--sanitize`` flag on the CLIs (:mod:`bdlz_tpu.sanitize`).
"""
from bdlz_tpu.lint.analyzer import LintReport, lint_paths, lint_source  # noqa: F401
from bdlz_tpu.lint.rules import RULES, Finding, Rule  # noqa: F401
