"""SARIF 2.1.0 rendering of a bdlz-lint report.

One run, one driver (``bdlz-lint``), one result per finding.  Suppressed
findings are carried as SARIF in-source suppressions (CI viewers show
them greyed out instead of dropping them), and stale suppression
comments surface as ``stale-suppression`` warnings so the satellite
contract — a disable comment must suppress something — is visible in
the same upload.  Columns are converted from the analyzer's 0-based
``col_offset`` to SARIF's 1-based ``startColumn``.
"""
from __future__ import annotations

from typing import Any, Dict

from bdlz_tpu.lint.analyzer import LintReport
from bdlz_tpu.lint.rules import RULES

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

#: Synthetic rule id for stale ``# bdlz-lint: disable=`` comments.
STALE_RULE_ID = "stale-suppression"


def _location(path: str, line: int, col: int) -> Dict[str, Any]:
    return {
        "physicalLocation": {
            "artifactLocation": {"uri": path},
            "region": {"startLine": line, "startColumn": col + 1},
        }
    }


def to_sarif(report: LintReport) -> Dict[str, Any]:
    """The report as a SARIF 2.1.0 log object (``json.dumps``-ready)."""
    driver_rules = [
        {
            "id": rid,
            "shortDescription": {"text": rule.title},
            "help": {"text": rule.hint},
            "defaultConfiguration": {"level": "error"},
        }
        for rid, rule in RULES.items()
    ]
    driver_rules.append({
        "id": STALE_RULE_ID,
        "shortDescription": {
            "text": "bdlz-lint disable comment that suppresses nothing"
        },
        "help": {"text": "delete the stale comment"},
        "defaultConfiguration": {"level": "warning"},
    })
    rule_index = {r["id"]: i for i, r in enumerate(driver_rules)}

    results = []
    for f in report.findings:
        result: Dict[str, Any] = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": "error",
            "message": {"text": f"{f.message} — {f.hint}"},
            "locations": [_location(f.path, f.line, f.col)],
        }
        if f.suppressed:
            result["suppressions"] = [{"kind": "inSource"}]
        results.append(result)
    for s in report.stale_suppressions:
        results.append({
            "ruleId": STALE_RULE_ID,
            "ruleIndex": rule_index[STALE_RULE_ID],
            "level": "warning",
            "message": {
                "text": (
                    f"`bdlz-lint: disable={s.rule}` suppresses no "
                    f"{s.rule} finding on this line; delete the comment"
                )
            },
            "locations": [_location(s.path, s.line, 0)],
        })

    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "bdlz-lint",
                        "informationUri": (
                            "https://example.invalid/bdlz_tpu/"
                            "docs/static_analysis.md"
                        ),
                        "rules": driver_rules,
                    }
                },
                "results": results,
            }
        ],
    }
