"""CLI for bdlz-lint.

    python -m bdlz_tpu.lint [paths ...] [--format text|json|sarif]
        [--rules R1,R2] [--changed-only] [--cache auto|on|off]
        [--cache-root DIR]

Exit status: 0 when every finding is suppressed (or none exist) and no
suppression comment is stale, 1 when unsuppressed findings or stale
suppressions remain, 2 on usage errors.  The JSON mode emits the full
report (findings, suppressions, stale suppressions, per-rule counts)
for tooling; ``--format sarif`` emits a SARIF 2.1.0 log for CI code
scanning; ``scripts/lint.sh`` chains it with ruff as the repo's one
lint command.

``--changed-only`` restricts *reporting* to files touched in the git
working tree (staged, unstaged, untracked) — the analysis itself always
runs whole-program, because the contract rules (R8–R11) are cross-file:
an edit to ``config.py`` can surface a finding in an unchanged CLI
module, and that finding still reports (a changed file is always
reported at full strength; only findings in files you did not touch
are elided).

``--cache`` keys a whole-run result on the analyzer source + every
linted file's content hash through the provenance store (the
``resolve_store`` tri-state: ``auto`` caches exactly when a root is
configured via ``--cache-root``/``BDLZ_CACHE_ROOT``).
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
from typing import List, Optional

from bdlz_tpu.lint.rules import RULES


def _git_changed_files() -> Optional[List[str]]:
    """Python files touched in the working tree (staged + unstaged +
    untracked), repo-root-relative; None when git is unavailable."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        out: List[str] = []
        for cmd in (
            ["git", "diff", "--name-only", "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard"],
        ):
            res = subprocess.run(
                cmd, capture_output=True, text=True, check=True, cwd=top,
            )
            out.extend(line for line in res.stdout.splitlines() if line)
        import os

        return sorted({
            os.path.join(top, p) for p in out if p.endswith(".py")
        })
    except (OSError, subprocess.CalledProcessError):
        return None


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bdlz_tpu.lint",
        description="JAX-aware static analysis for the bdlz_tpu "
        "dual-backend and knob-contract conventions (rules R1-R12)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="Files or directories to lint (default: bdlz_tpu/)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--rules", default=None,
                    help="Comma-separated subset of rule ids (default: all)")
    ap.add_argument("--changed-only", action="store_true",
                    help="Report findings only in git-changed files "
                         "(analysis still runs whole-program)")
    ap.add_argument("--cache", choices=("auto", "on", "off"),
                    default="auto",
                    help="Whole-run result cache through the provenance "
                         "store: auto = on iff a root is configured "
                         "(--cache-root/BDLZ_CACHE_ROOT)")
    ap.add_argument("--cache-root", default=None,
                    help="Store root for --cache (default: BDLZ_CACHE_ROOT)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="Also print suppressed findings in text mode "
                         "(JSON/SARIF modes always carry them)")
    ap.add_argument("--list-rules", action="store_true",
                    help="Print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in RULES.items():
            print(f"{rid}  {rule.title}\n    fix: {rule.hint}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or ["bdlz_tpu"]

    store = None
    if args.cache != "off":
        from bdlz_tpu.provenance.store import resolve_store

        # "on" with no root falls back to the default user cache root
        # via a cache_enabled=True surrogate; "auto" is the bare
        # tri-state (cache iff a root is configured)
        class _Gate:
            cache_enabled = True if args.cache == "on" else None
            cache_root = None

        store = resolve_store(args.cache_root, _Gate(), label="lint-cache")

    from bdlz_tpu.lint.cache import cached_lint_paths

    report, cache_hit = cached_lint_paths(paths, rules=rules, store=store)

    if args.changed_only:
        changed = _git_changed_files()
        if changed is None:
            print("bdlz-lint: --changed-only needs git; reporting all "
                  "files", file=sys.stderr)
        else:
            report = report.restrict_to(changed)

    failed = bool(report.active or report.stale_suppressions)
    if args.format == "json":
        payload = report.to_dict()
        payload["cache_hit"] = cache_hit
        print(json.dumps(payload, indent=2))
    elif args.format == "sarif":
        from bdlz_tpu.lint.sarif import to_sarif

        print(json.dumps(to_sarif(report), indent=2))
    else:
        shown = report.findings if args.show_suppressed else report.active
        for f in shown:
            print(f.render())
        for s in report.stale_suppressions:
            print(s.render())
        cached = " [cached]" if cache_hit else ""
        print(
            f"bdlz-lint: {len(report.active)} finding(s), "
            f"{len(report.suppressed)} suppressed, "
            f"{len(report.stale_suppressions)} stale suppression(s), "
            f"{report.files_scanned} file(s) scanned{cached}"
        )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
