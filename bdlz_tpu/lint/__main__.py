"""CLI for bdlz-lint.

    python -m bdlz_tpu.lint [paths ...] [--format text|json] [--rules R1,R2]

Exit status: 0 when every finding is suppressed (or none exist), 1 when
unsuppressed findings remain, 2 on usage errors. The JSON mode emits the
full report (findings, suppressions, per-rule counts) for tooling;
`scripts/lint.sh` chains it with ruff as the repo's one lint command.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from bdlz_tpu.lint.analyzer import lint_paths
from bdlz_tpu.lint.rules import RULES


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bdlz_tpu.lint",
        description="JAX-aware static analysis for the bdlz_tpu "
        "dual-backend contract (rules R1-R6)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="Files or directories to lint (default: bdlz_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", default=None,
                    help="Comma-separated subset of rule ids (default: all)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="Also print suppressed findings in text mode "
                         "(JSON mode always carries them)")
    ap.add_argument("--list-rules", action="store_true",
                    help="Print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in RULES.items():
            print(f"{rid}  {rule.title}\n    fix: {rule.hint}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or ["bdlz_tpu"]
    report = lint_paths(paths, rules=rules)

    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2))
    else:
        shown = report.findings if args.show_suppressed else report.active
        for f in shown:
            print(f.render())
        print(
            f"bdlz-lint: {len(report.active)} finding(s), "
            f"{len(report.suppressed)} suppressed, "
            f"{report.files_scanned} file(s) scanned"
        )
    return 1 if report.active else 0


if __name__ == "__main__":
    sys.exit(main())
